"""ZeRO-3/FSDP sharded data parallelism: numerical equivalence vs the
replicated-DP baseline, shard-size accounting, and multi-step stability.

The FSDP step (all-gather params → backward → reduce-scatter grads →
local shard update) must produce the same updates as replicated DP with
mean reduction (part3/DDP semantics) — same math, different placement.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.models.vgg import VGGTest
from distributed_machine_learning_tpu.parallel.fsdp import (
    fsdp_memory_footprint,
    gather_fsdp_params,
    make_fsdp_train_step,
    shard_fsdp_state,
)
from distributed_machine_learning_tpu.parallel.strategies import get_strategy
from distributed_machine_learning_tpu.train.sgd import SGDConfig
from distributed_machine_learning_tpu.train.state import TrainState
from distributed_machine_learning_tpu.train.step import make_train_step, shard_batch

GLOBAL_BATCH = 16


def _fresh_state(model):
    variables = model.init(jax.random.PRNGKey(69143), jnp.zeros((1, 32, 32, 3)))
    params = jax.tree_util.tree_map(
        lambda x: jnp.array(x, copy=True), variables["params"]
    )
    return TrainState.create(
        params=params,
        batch_stats=variables.get("batch_stats"),
        rng=jax.random.PRNGKey(7),
        config=SGDConfig(),
    )


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (GLOBAL_BATCH, 32, 32, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, (GLOBAL_BATCH,)).astype(np.int32)
    return images, labels


def test_fsdp_shards_are_one_nth(mesh8):
    state = _fresh_state(VGGTest())
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(state.params))
    fsdp_state, _, n_elems = shard_fsdp_state(state, mesh8)
    assert n_elems == n_params
    padded = fsdp_state.param_shards.shape[0]
    assert padded % 8 == 0 and padded >= n_elems
    # Each device materializes exactly 1/8 of the padded flat vector.
    for shard in fsdp_state.param_shards.addressable_shards:
        assert shard.data.shape == (padded // 8,)
    for shard in fsdp_state.momentum_shards.addressable_shards:
        assert shard.data.shape == (padded // 8,)


@pytest.mark.parametrize(
    "use_bn", [False, pytest.param(True, marks=pytest.mark.slow)]
)
def test_fsdp_matches_replicated_dp(batch, mesh8, use_bn):
    images, labels = batch
    model = VGGTest(use_bn=use_bn)

    # Replicated DP, mean semantics (part3): the baseline.
    rep_state = _fresh_state(model)
    rep_step = make_train_step(
        model, get_strategy("all_reduce", mean=True), mesh=mesh8, augment=False
    )
    x, y = shard_batch(mesh8, images, labels)
    rep_state, rep_loss = rep_step(rep_state, x, y)
    rep_state, rep_loss2 = rep_step(rep_state, x, y)

    # FSDP on the same data.
    fsdp_state, unravel, n_elems = shard_fsdp_state(_fresh_state(model), mesh8)
    step = make_fsdp_train_step(model, mesh8, unravel, n_elems, augment=False)
    fsdp_state, loss = step(fsdp_state, x, y)
    fsdp_state, loss2 = step(fsdp_state, x, y)

    np.testing.assert_allclose(float(loss), float(rep_loss), rtol=1e-5)
    np.testing.assert_allclose(float(loss2), float(rep_loss2), rtol=1e-4)
    got = gather_fsdp_params(fsdp_state, unravel, n_elems)
    for la, lb in zip(
        jax.tree_util.tree_leaves(got),
        jax.tree_util.tree_leaves(rep_state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(la), np.asarray(lb), rtol=1e-4, atol=1e-5
        )
    # BN running stats follow the same axis-synced update in both steps.
    for la, lb in zip(
        jax.tree_util.tree_leaves(fsdp_state.batch_stats),
        jax.tree_util.tree_leaves(rep_state.batch_stats),
    ):
        np.testing.assert_allclose(
            np.asarray(la), np.asarray(lb), rtol=1e-4, atol=1e-5
        )


def test_fsdp_state_roundtrip(mesh8):
    state = _fresh_state(VGGTest())
    fsdp_state, unravel, n_elems = shard_fsdp_state(state, mesh8)
    got = gather_fsdp_params(fsdp_state, unravel, n_elems)
    for la, lb in zip(
        jax.tree_util.tree_leaves(got), jax.tree_util.tree_leaves(state.params)
    ):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_fsdp_memory_footprint():
    fp = fsdp_memory_footprint(9_231_114, 8)
    assert fp["fsdp"] * 7 < fp["replicated"]  # ~8x smaller (padding slack)
    fp1 = fsdp_memory_footprint(100, 1)
    assert fp1["fsdp"] == fp1["replicated"]
