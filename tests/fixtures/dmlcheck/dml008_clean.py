# dmlcheck-virtual-path: tests/test_fixture.py
"""DML008 clean case: every run() is bounded; Popen is exempt (its
bound lives on communicate(timeout=...))."""
import subprocess
import sys


def test_tool_runs(tmp_path):
    res = subprocess.run(
        [sys.executable, "tools/ckpt_verify.py", str(tmp_path)],
        capture_output=True, text=True, timeout=60,
    )
    assert res.returncode in (0, 2)


def test_worker_pipes(cmd):
    p = subprocess.Popen(cmd, stdout=subprocess.PIPE)
    out, _ = p.communicate(timeout=180)
    assert out is not None
