"""Run summary banners — parameter table + totals, and the resilience
event table.

The reference prints a torchsummary table for part1 (``part1/main.py:118``)
whose ~9.2M-parameter total the report leans on (group25.pdf p.2).
``model_summary`` is the pytree-native equivalent: per-module parameter
counts from the params tree itself, plus the totals line.
``resilience_summary`` is the same treatment for the self-healing
runtime: every skip/retry/stall/restart counter from a supervised run,
because a recovery nobody can see is indistinguishable from a fault
that never fired.
"""

from __future__ import annotations

import numpy as np


def _count(tree) -> int:
    import jax

    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(tree))


def model_summary(params, title: str = "Model") -> str:
    """A torchsummary-style table: one row per top-level module with its
    parameter shapes and count, then total params and fp32 size in MB."""
    import jax

    rows = []
    width = 24
    for name in sorted(params):
        sub = params[name]
        shapes = " ".join(
            "x".join(str(d) for d in leaf.shape) or "scalar"
            for leaf in jax.tree_util.tree_leaves(sub)
        )
        rows.append(f"  {name:<{width}} {_count(sub):>12,}  [{shapes}]")
    total = _count(params)
    lines = [
        f"{title} summary",
        "-" * 64,
        *rows,
        "-" * 64,
        f"  {'Total params':<{width}} {total:>12,}",
        f"  {'Size (fp32)':<{width}} {total * 4 / 2**20:>10.2f} MB",
        "-" * 64,
    ]
    return "\n".join(lines)


_EVENT_LABELS = {
    "skipped_steps": "updates skipped (non-finite grads)",
    "scaler_backoffs": "loss-scale halvings (overflow)",
    "scaler_growths": "loss-scale doublings",
    "loader_retries": "data-loader retries",
    "skipped_batches": "bad batches skipped",
    "stalls": "watchdog stalls declared",
    "restarts": "supervisor restarts",
    "preemptions": "preemption stops",
    "ckpt_kills": "injected mid-checkpoint kills",
    "rank_kills": "injected rank deaths",
    "rank_losses": "injected permanent rank losses",
    "rank_recoveries": "injected rank recoveries",
    "rank_stalls": "injected rank stalls",
    "ckpt_corruptions": "injected checkpoint corruptions",
    "peer_failures": "gang peers declared dead/stalled",
    "stragglers": "straggler advisories (slow ranks)",
    "gang_restarts": "gang coordinated restarts",
    "gang_shrinks": "gang shrinks to survivors",
    "gang_grows": "gang grows (joins/promotions admitted)",
    "spare_promotions": "warm spares promoted to live ranks",
    "spare_demotions": "live ranks demoted to spares",
    "reshard_restores": "restores resharded across world sizes",
    "ckpt_verify_failures": "checkpoints failing verification",
    "ckpt_fallbacks": "restores fell back past bad checkpoints",
    "transport_retries": "gang-transport ops retried (backoff)",
    "transport_timeouts": "gang-transport ops timed out/dropped",
    "replica_evictions": "serving replicas evicted (dead/slow)",
    "drains": "serving replicas drained gracefully",
    "request_rejects": "serving requests rejected (overload)",
    "weight_swaps": "replica weight hot-swaps committed",
    "canary_promotions": "deploys promoted after clean canary",
    "canary_rollbacks": "deploys rolled back (regression/burn)",
}


def resilience_summary(events, title: str = "Resilience") -> str:
    """The robustness counters table (``runtime/faults.FaultEvents``) in
    the same banner style as ``model_summary`` — printed at the end of a
    supervised/fault-injected run so recoveries are observable, not
    silent.  All-zero counters render as a one-line clean bill."""
    counts = events.as_dict()
    width = 36
    rows = [
        f"  {_EVENT_LABELS.get(name, name):<{width}} {count:>8,}"
        for name, count in counts.items()
        if count
    ]
    if not rows:
        return f"{title}: no fault events (clean run)"
    lines = [
        f"{title} summary",
        "-" * 64,
        *rows,
        "-" * 64,
        f"  {'Total events':<{width}} {sum(counts.values()):>8,}",
        "-" * 64,
    ]
    return "\n".join(lines)
