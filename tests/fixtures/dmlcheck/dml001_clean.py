# dmlcheck-virtual-path: distributed_machine_learning_tpu/runtime/fixture.py
"""DML001 clean case: monotonic durations, change-signature staleness,
wall timestamps only ever RECORDED into payloads."""
import os
import time

last_seen = time.monotonic()
_peer_sig = {}


def progress_age():
    return time.monotonic() - last_seen


def peer_changed(peer, path):
    st = os.stat(path)
    sig = (st.st_mtime_ns, st.st_size)   # equality only: sanctioned
    changed = _peer_sig.get(peer) != sig
    _peer_sig[peer] = sig
    return changed


def beat_payload(step):
    return {"step": step, "time": time.time()}  # recorded, no arithmetic
