"""Serving-tier A/B bench (bench/serving_tier.py, ISSUE 19): the
virtual-clock simulation of continuous batching vs the batch-static
dispatch loop.

The committed ``BENCH_r19_serving.json`` carries the r19 acceptance
verdicts (engine beats batch-static on useful tokens/sec at the
highest offered load AND on p95 e2e at the lowest); the fast tests
here pin the file's shape and verdicts, the slow test re-runs a small
sweep end to end under a wall-clock cap.
"""

import json
import os
import time

import pytest

from distributed_machine_learning_tpu.bench.serving_tier import (
    _quantiles,
    acceptance,
    make_workload,
)

HERE = os.path.dirname(os.path.abspath(__file__))
BENCH_JSON = os.path.join(os.path.dirname(HERE),
                          "BENCH_r19_serving.json")

SWEEP_BUDGET_S = 300.0


def test_workload_is_seeded_and_sorted():
    a = make_workload(40, 16.0, seed=3)
    b = make_workload(40, 16.0, seed=3)
    assert a == b
    assert a != make_workload(40, 16.0, seed=4)
    times = [r["t_arr"] for r in a]
    assert times == sorted(times) and times[0] > 0.0
    # Same seed, different rate: identical request MIX (prompts and
    # budgets), only the arrival spacing moves — what makes the
    # per-rate rows comparable.
    c = make_workload(40, 64.0, seed=3)
    assert [(r["prompt"], r["max_new"]) for r in c] \
        == [(r["prompt"], r["max_new"]) for r in a]
    assert sum(r["t_arr"] for r in c) < sum(times)


def test_quantiles_are_order_statistics():
    q = _quantiles([0.1 * i for i in range(1, 101)])
    assert q["p50_e2e_s"] == pytest.approx(5.0, abs=0.2)
    assert q["p95_e2e_s"] == pytest.approx(9.5, abs=0.2)
    assert q["max_e2e_s"] == pytest.approx(10.0)
    assert _quantiles([])["p99_e2e_s"] == 0.0


def test_committed_bench_rows_carry_the_r19_acceptance():
    # The checked-in sweep must contain BOTH head-to-head rows the
    # acceptance gate reads, and both verdicts must hold.
    with open(BENCH_JSON) as f:
        rows = json.load(f)
    data = [r for r in rows if r["bench"] == "serving_tier"]
    rates = sorted({r["rate_rps"] for r in data})
    assert len(rates) >= 3, "offered-load sweep needs 3+ rates"
    for rate in rates:
        systems = {r["system"] for r in data if r["rate_rps"] == rate}
        assert systems == {"batch_static", "engine"}
    hi = [r for r in data if r["rate_rps"] == rates[-1]
          and r["system"] == "engine"][0]
    lo = [r for r in data if r["rate_rps"] == rates[0]
          and r["system"] == "engine"][0]
    assert hi["engine_wins_tokens_per_sec"] is True
    assert lo["engine_wins_p95_e2e"] is True
    verdict = [r for r in rows
               if r["bench"] == "serving_tier_acceptance"][0]
    assert verdict["engine_beats_tokens_per_sec_at_highest_load"]
    assert verdict["engine_beats_p95_e2e_at_lowest_load"]
    assert acceptance(data) == {
        k: v for k, v in verdict.items()}


@pytest.mark.slow
def test_sweep_runs_end_to_end_and_engine_wins(tmp_path):
    """A reduced sweep, real compute: the engine must win useful
    tokens/sec at the saturating rate and p95 e2e at the light rate,
    within the wall-clock budget."""
    from distributed_machine_learning_tpu.bench.serving_tier import (
        make_model,
        run_sweep,
    )

    t0 = time.monotonic()
    model, params = make_model(d_model=192, n_layers=4)
    rows = run_sweep([6.0, 48.0], 40, seed=0, width=4,
                     model=model, params=params)
    elapsed = time.monotonic() - t0
    assert elapsed < SWEEP_BUDGET_S, f"sweep took {elapsed:.0f}s"
    assert len(rows) == 4
    verdict = acceptance(rows)
    assert verdict["engine_beats_tokens_per_sec_at_highest_load"], rows
    assert verdict["engine_beats_p95_e2e_at_lowest_load"], rows
    # The virtual clock conserves work: both systems served the same
    # useful tokens, and every row's percentiles are ordered.
    assert len({r["useful_tokens"] for r in rows}) == 1
    for r in rows:
        assert (r["p50_e2e_s"] <= r["p95_e2e_s"]
                <= r["p99_e2e_s"] <= r["max_e2e_s"])
