from distributed_machine_learning_tpu.data.cifar10 import (
    CIFAR10_MEAN,
    CIFAR10_STD,
    load_cifar10,
)
from distributed_machine_learning_tpu.data.sharding import shard_indices
from distributed_machine_learning_tpu.data.loader import BatchLoader
from distributed_machine_learning_tpu.data.augment import augment_batch, normalize

__all__ = [
    "CIFAR10_MEAN",
    "CIFAR10_STD",
    "load_cifar10",
    "shard_indices",
    "BatchLoader",
    "augment_batch",
    "normalize",
]
