"""Composed 3-D (data × pipeline × tensor) parallelism correctness.

The invariant is the same one every other strategy test asserts: the
distributed step must take exactly the step the single-device dense
baseline takes — here with all three parallelism dimensions active at
once on a (2, 2, 2) mesh of the 8 virtual CPU devices."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.models.transformer import TransformerLM
from distributed_machine_learning_tpu.parallel.parallel3d import (
    make_3d_lm_train_step,
    make_3d_mesh,
    microbatch,
    init_pipeline_state,
    p3_param_spec,
    shard_3d_batch,
    shard_3d_state,
)
from distributed_machine_learning_tpu.parallel.pipeline import stack_lm_params
from distributed_machine_learning_tpu.train.lm_step import (
    init_lm_state,
    make_lm_train_step,
)

MODEL = TransformerLM(vocab_size=64, d_model=32, n_layers=4, n_heads=4)


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(7)
    toks = rng.integers(0, 64, (4, 17))
    return toks[:, :-1].astype(np.int32), toks[:, 1:].astype(np.int32)


@pytest.fixture(scope="module")
def dense_step_result(batch):
    x, y = batch
    state = init_lm_state(MODEL)
    step = make_lm_train_step(MODEL)
    state, loss = step(state, jnp.asarray(x), jnp.asarray(y))
    return state, float(loss)


@pytest.mark.parametrize(
    "shape",
    [(2, 2, 2),
     pytest.param((1, 4, 2), marks=pytest.mark.slow),
     pytest.param((2, 4, 1), marks=pytest.mark.slow),
     pytest.param((1, 2, 4), marks=pytest.mark.slow)],
)
def test_3d_matches_dense_baseline(batch, dense_step_result, shape):
    dp, pp, tp = shape
    x, y = batch
    mesh = make_3d_mesh(dp, pp, tp)
    state = shard_3d_state(init_pipeline_state(MODEL), mesh)
    step = make_3d_lm_train_step(MODEL, mesh, num_microbatches=2)
    mx, my = shard_3d_batch(mesh, *microbatch(x, y, 2))
    state, loss = step(state, mx, my)

    dstate, dloss = dense_step_result
    np.testing.assert_allclose(float(loss), dloss, rtol=1e-5)
    ref = stack_lm_params(dstate.params, MODEL.n_layers)
    for a, b in zip(
        jax.tree_util.tree_leaves(ref), jax.tree_util.tree_leaves(state.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5
        )


def test_3d_two_steps_stay_in_sync(batch):
    """Error doesn't accumulate: two consecutive 3-D steps track the dense
    trajectory."""
    x, y = batch
    mesh = make_3d_mesh(2, 2, 2)
    state = shard_3d_state(init_pipeline_state(MODEL), mesh)
    step = make_3d_lm_train_step(MODEL, mesh, num_microbatches=2)
    mx, my = shard_3d_batch(mesh, *microbatch(x, y, 2))

    dstate = init_lm_state(MODEL)
    dstep = make_lm_train_step(MODEL)

    for _ in range(2):
        state, loss = step(state, mx, my)
        dstate, dloss = dstep(dstate, jnp.asarray(x), jnp.asarray(y))
    np.testing.assert_allclose(float(loss), float(dloss), rtol=1e-4)


def test_3d_param_specs():
    """Spot-check the layout rules: pipe on the stacked dim, Megatron
    splits inside blocks, embed fully replicated."""
    from jax.sharding import PartitionSpec as P

    assert p3_param_spec(("blocks", "attn", "qkv", "kernel"), 5) == P(
        "pipe", None, None, "model", None
    )
    assert p3_param_spec(("blocks", "fc_in", "kernel"), 3) == P(
        "pipe", None, "model"
    )
    assert p3_param_spec(("blocks", "ln1", "scale"), 2) == P("pipe", None)
    assert p3_param_spec(("embed", "embedding"), 2) == P(None, None)
    assert p3_param_spec(("lm_head", "kernel"), 2) == P(None, "model")


def test_3d_validations():
    mesh = make_3d_mesh(2, 2, 2)
    with pytest.raises(ValueError, match="pipeline stages"):
        make_3d_lm_train_step(MODEL.clone(n_layers=3), mesh, 2)
    with pytest.raises(ValueError, match="model-axis"):
        make_3d_lm_train_step(MODEL.clone(n_heads=3), mesh, 2)
    with pytest.raises(ValueError, match="attn_impl"):
        make_3d_lm_train_step(MODEL.clone(attn_impl="ring"), mesh, 2)


def test_3d_flash_matches_3d_dense():
    """Flash inside the 3-D step: the model's wrap manualizes the
    remaining (batch, model) axes from within the pipe-manual region —
    a nested partial-manual shard_map whose union covers the mesh.
    Must match the dense 3-D step within kernel tolerance."""
    import numpy as np

    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )
    from distributed_machine_learning_tpu.parallel.parallel3d import (
        make_3d_lm_train_step,
        make_3d_mesh,
        shard_3d_batch,
        shard_3d_state,
    )
    from distributed_machine_learning_tpu.parallel.pipeline import (
        init_pipeline_state,
        microbatch,
    )

    mesh = make_3d_mesh(2, 2, 2)
    rng = np.random.default_rng(31)
    toks = rng.integers(0, 64, (8, 13)).astype(np.int32)
    results = {}
    for attn in ("dense", "flash"):
        model = TransformerLM(vocab_size=64, d_model=32, n_layers=4,
                              n_heads=4, attn_impl=attn)
        step = make_3d_lm_train_step(model, mesh, num_microbatches=2)
        state = shard_3d_state(init_pipeline_state(model), mesh)
        mx, my = microbatch(toks[:, :-1], toks[:, 1:], 2)
        sx, sy = shard_3d_batch(mesh, mx, my)
        state, loss = step(state, sx, sy)
        results[attn] = (float(loss), state.params)
    d_loss, d_params = results["dense"]
    f_loss, f_params = results["flash"]
    np.testing.assert_allclose(f_loss, d_loss, rtol=1e-4)
    import jax

    for a, b in zip(jax.tree_util.tree_leaves(f_params),
                    jax.tree_util.tree_leaves(d_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-6)


def test_3d_zero1_dp_update_equivalence(batch):
    """ZeRO-1 over the dp axis of 3-D (VERDICT r4 item 8): dp-sharded
    optimizer moments take EXACTLY the plain-3-D step — elementwise
    update on shards + GSPMD's all-gather cannot change the math."""
    from distributed_machine_learning_tpu.train.adamw import AdamWConfig

    x, y = batch
    mesh = make_3d_mesh(2, 2, 2)
    mx, my = shard_3d_batch(mesh, *microbatch(x, y, 2))

    def run(zero1_dp):
        state = shard_3d_state(
            init_pipeline_state(MODEL, config=AdamWConfig()), mesh,
            zero1_dp=zero1_dp,
        )
        step = make_3d_lm_train_step(MODEL, mesh, num_microbatches=2,
                                     zero1_dp=zero1_dp)
        losses = []
        for _ in range(3):
            state, loss = step(state, mx, my)
            losses.append(float(loss))
        return state, losses

    plain_state, plain_losses = run(False)
    z1_state, z1_losses = run(True)
    np.testing.assert_allclose(z1_losses, plain_losses, rtol=1e-6)
    # fp tolerance, not bitwise: the dp-sharded update re-partitions the
    # grad reduction/all-gather, so reduction order shifts by ulps and
    # AdamW's rsqrt amplifies them (measured: 1/16384 elements at
    # |Δ|≈5e-6 after 3 steps).  A real layout slip (wrong slice, shard
    # misalignment) would blow past this on MANY elements.
    for a, b in zip(
        jax.tree_util.tree_leaves(plain_state.params),
        jax.tree_util.tree_leaves(z1_state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=2e-5)
    # The moments really live dp-sharded: every shardable leaf's spec
    # carries the data axis (the memory claim, checked structurally).
    def dp_sharded(arr):
        return any(
            ax == "batch" or (isinstance(ax, tuple) and "batch" in ax)
            for ax in tuple(arr.sharding.spec)
        )

    # Every WEIGHT-MATRIX moment (>= 2 free-dim leaves; the memory) must
    # be dp-sharded; small leaves with no free divisible dim (e.g. the
    # column-parallel fc_in bias, already fully TP-sharded) may
    # replicate — the documented O(d) minority.
    sharded = [
        dp_sharded(m)
        for m in jax.tree_util.tree_leaves(z1_state.momentum)
        if m.ndim >= 3  # stacked [L, ...] weight matrices
    ]
    assert sharded and all(sharded), "weight-moment leaves not dp-sharded"
    assert not any(
        dp_sharded(p)
        for p in jax.tree_util.tree_leaves(z1_state.params)
    ), "params must stay dp-replicated"


def test_3d_zero1_moment_spec_rules():
    from distributed_machine_learning_tpu.parallel.parallel3d import (
        p3_zero1_moment_spec,
    )

    # Stacked block leaf: pipe on dim 0, model on the TP dim, dp lands
    # on the largest FREE dp-divisible dim.
    spec = p3_zero1_moment_spec(
        ("blocks", "attn", "qkv", "kernel"), (2, 32, 3, 4, 8), dp=2
    )
    assert spec[0] == "pipe" and "batch" in tuple(spec)
    # No free divisible dim -> dp replicated (spec unchanged).
    spec2 = p3_zero1_moment_spec(("blocks", "attn", "qkv", "bias"),
                                 (2, 3), dp=4)
    assert "batch" not in tuple(spec2)


def test_3d_zero1_grad_spec_carries_dp_and_drops_pipe():
    """The backward→update annotation (the barrier replacement, ISSUE 9
    satellite): grads must be annotated with their MOMENT's dp-sharded
    layout — the data axis present on every shardable weight leaf, so
    the dp-sharded update propagates end to end — with the pipe axis
    dropped (manual inside the region).  The old PARAM-spec barrier
    carried no dp axis at all, which is exactly the layout-propagation
    block this replaces."""
    from distributed_machine_learning_tpu.parallel.parallel3d import (
        p3_zero1_grad_spec,
    )

    spec = p3_zero1_grad_spec(
        ("blocks", "attn", "qkv", "kernel"), (2, 32, 3, 4, 8), dp=2
    )
    axes = tuple(spec)
    assert "pipe" not in axes, "pipe is manual inside the region"
    assert "batch" in axes, (
        "the dp axis must reach the grads — a dp-free annotation is "
        "the old barrier behavior"
    )
    assert "model" in axes, "TP layout preserved"
    # Embed stays excluded (the documented gather-scatter CHECK class).
    embed = tuple(p3_zero1_grad_spec(("embed", "embedding"), (64, 32),
                                     dp=2))
    assert "batch" not in embed and "pipe" not in embed


def test_3d_zero1_dp_batch8_compiles_and_runs():
    """Regression: at microbatch rows > 1 per dp shard the partitioner
    used to hit an SPMD CHECK (the dp-sharded moment layout propagated
    into the stacked-layer backward scatter).  The two-stage
    sharding-annotated dependency in make_3d_lm_train_step (param-spec
    pin on the backward side + moment-spec annotation the update
    propagates through — the ISSUE-9 replacement for the old barrier)
    must keep this shape compiling AND leave the moments dp-sharded,
    with no barrier-induced dp-replicated grad pin between backward
    and update (the moment annotation is now the last word on the grad
    layout)."""
    from distributed_machine_learning_tpu.train.adamw import AdamWConfig

    rng = np.random.default_rng(7)
    toks = rng.integers(0, 64, (8, 17))
    mesh = make_3d_mesh(2, 2, 2)
    mx, my = shard_3d_batch(
        mesh, *microbatch(toks[:, :-1].astype(np.int32),
                          toks[:, 1:].astype(np.int32), 2)
    )
    state = shard_3d_state(
        init_pipeline_state(MODEL, config=AdamWConfig()), mesh,
        zero1_dp=True,
    )
    step = make_3d_lm_train_step(MODEL, mesh, num_microbatches=2,
                                 zero1_dp=True)
    state, loss = step(state, mx, my)
    assert np.isfinite(float(loss))
    # The memory claim survives the constraint rework: weight moments
    # really live dp-sharded after a step.
    def dp_sharded(arr):
        return any(
            ax == "batch" or (isinstance(ax, tuple) and "batch" in ax)
            for ax in tuple(arr.sharding.spec)
        )

    sharded = [
        dp_sharded(m)
        for m in jax.tree_util.tree_leaves(state.momentum)
        if m.ndim >= 3
    ]
    assert sharded and all(sharded)
