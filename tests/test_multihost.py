"""Real two-process multi-host run (VERDICT r01 weak #6): the rendezvous
(runtime/distributed.py::initialize_from_flags), cross-process gloo
collectives, and agree_stop's process_allgather branch
(runtime/resilience.py:224-244) exercised as two actual OS processes —
the reference bar is the 4-node cluster bring-up at
/root/reference/part2/2b/main.py:163-176."""

import os
import signal
import socket
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_rendezvous_identical_params_and_agree_stop():
    port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # The workers get ONE cpu device each (no 8-way virtual split) so the
    # 2-device mesh really spans the process boundary.
    env.pop("XLA_FLAGS", None)
    # A TPU-tunnel sitecustomize (if this host has one on PYTHONPATH)
    # pre-initializes jax.distributed for its own single-process session,
    # which would swallow the workers' 2-process rendezvous — keep only
    # non-sitecustomize entries and drop its trigger env vars.
    env.pop("PALLAS_AXON_POOL_IPS", None)
    keep = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and not os.path.exists(os.path.join(p, "sitecustomize.py"))]
    env["PYTHONPATH"] = os.pathsep.join([REPO] + keep)
    cmd = [sys.executable, os.path.join(HERE, "mh_worker.py"),
           "--port", str(port)]
    p0 = subprocess.Popen(cmd + ["--rank", "0"], stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, env=env, text=True,
                          cwd=REPO)
    p1 = subprocess.Popen(cmd + ["--rank", "1"], stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, env=env, text=True,
                          cwd=REPO)
    try:
        # Let rank 0 make progress, then preempt it mid-run: rank 1 must
        # stop at the SAME step via the cross-host agreement.
        lines0 = []
        deadline = time.time() + 240
        while time.time() < deadline:
            line = p0.stdout.readline()
            if not line:
                break
            lines0.append(line)
            if line.startswith("step 3"):
                p0.send_signal(signal.SIGTERM)
                break
        rest0, _ = p0.communicate(timeout=180)
        out1, _ = p1.communicate(timeout=180)
        out0 = "".join(lines0) + rest0
    finally:
        for p in (p0, p1):
            if p.poll() is None:
                p.kill()

    assert p0.returncode == 0, f"rank0 failed:\n{out0}"
    assert p1.returncode == 0, f"rank1 failed:\n{out1}"

    def field(out, key):
        vals = [l.split(None, 1)[1] for l in out.splitlines()
                if l.startswith(key)]
        assert vals, f"no {key!r} line in:\n{out}"
        return vals[-1]

    # SIGTERM landed on rank 0 only; BOTH ranks agreed to stop at the
    # same step boundary (a rank leaving early would hang the other in
    # the next collective — the exact failure agree_stop prevents).
    s0, s1 = field(out0, "stopped_at"), field(out1, "stopped_at")
    assert s0 == s1 and int(s0) >= 3, (s0, s1)
    # And the replicated params are bit-identical across processes.
    assert field(out0, "final") == field(out1, "final")
    # Per-host strided loader slices, scattered cross-process and
    # psum-reduced, equal the host-side global sum on both ranks.
    for out in (out0, out1):
        got, want = field(out, "data_sum").split()
        assert float(got) == float(want), (got, want)
    # Cross-process GSPMD (per-layer FSDP leaves sharded over the two
    # processes): both ranks agree on the loss and bit-for-bit on the
    # all-gathered updated params.
    assert field(out0, "gspmd_loss") == field(out1, "gspmd_loss")
    assert field(out0, "gspmd_params") == field(out1, "gspmd_params")


import pytest


@pytest.mark.slow
def test_two_process_lm_eval_runs():
    """The LM eval path on a REAL two-process run (VERDICT r02 item 8):
    params are all-gathered across processes to host numpy and every
    rank runs the plain-jit eval independently — the run must finish
    rc=0 on both ranks WITH an Eval line (the r02 code skipped eval on
    multi-process runs with a warning; before that it crashed mixing
    multi-host-committed params with host-local eval batches)."""
    port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    keep = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and not os.path.exists(os.path.join(p, "sitecustomize.py"))]
    env["PYTHONPATH"] = os.pathsep.join([REPO] + keep)
    cmd = [sys.executable, "-m", "distributed_machine_learning_tpu.cli.lm",
           "--master-ip", f"127.0.0.1:{port}", "--num-nodes", "2",
           "--parallel", "dp", "--d-model", "16", "--n-layers", "1",
           "--n-heads", "2", "--vocab", "64", "--seq-len", "16",
           "--batch-size", "2", "--max-iters", "2", "--eval-batches", "1"]
    p0 = subprocess.Popen(cmd + ["--rank", "0"], stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, env=env, text=True,
                          cwd=REPO)
    p1 = subprocess.Popen(cmd + ["--rank", "1"], stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, env=env, text=True,
                          cwd=REPO)
    try:
        out0, _ = p0.communicate(timeout=240)
        out1, _ = p1.communicate(timeout=240)
    finally:
        for p in (p0, p1):
            if p.poll() is None:
                p.kill()
    assert p0.returncode == 0, f"rank0 failed:\n{out0}"
    assert p1.returncode == 0, f"rank1 failed:\n{out1}"
    # rank0_print gates output to rank 0; the Eval line proves the
    # eval step ran (both ranks executed it — a dispatch error on
    # either would have failed that rank's exit code).
    assert "Eval: nll/token" in out0, out0
    assert "skipping eval" not in out0 + out1
