"""Fused int8 ring-hop codec as Pallas TPU kernels — the round-13 raw-speed lever.

The compressed ring (``ops/ring.py::Int8Scheme``) spells each hop's
dequantize–add–requantize as separate XLA ops: the encode materializes a
dequantized copy of the partial to compute the error-feedback residual
(``v − decode(encode(v))``), and the receive side materializes the
dequantized payload before adding it into the accumulator chunk.  On
the round-7/round-11 measurements those intermediates are the codec's
whole cost (≤6% p50 for int8 on the flat ring — docs/PERF.md rounds 7
and 11).  This module is the fused spelling: three kernels covering
every local piece of the hop, each one pass over the chunk with the
intermediates held in-register, so **HBM never sees a dequantized
partial**:

- :func:`encode_int8` — quantize a chunk: amax → per-chunk scale →
  ``q = clip(round(v/scale))``, optionally emitting the EF residual
  ``v − q·scale`` as a second output in the same pass (the XLA path
  pays a full decode round-trip for it);
- :func:`decode_add_int8` — one reduce-scatter arrival:
  ``acc + q·scale`` decoded and accumulated in f32 in-register (the
  requantize of the updated partial is the next hop's
  :func:`encode_int8` — encode→accumulate→decode with no dense
  intermediate between them);
- :func:`decode_int8` — the all-gather relay's plain decode.

The arithmetic is OP-FOR-OP the ``Int8Scheme`` XLA path (same amax, same
scale select, same round/clip, same f32 multiply-add), so the fused
codec is held to BITWISE parity with the XLA build — values, wire
payload, and EF residual — in ``tests/test_pallas_fusion.py``; the
wire payload shape/dtype is identical, so the static byte accounting
(``ring_wire_bytes``) and the DML103 HLO audit hold unchanged.

Chunks are flat [L] f32 vectors of arbitrary length: each kernel views
them as [rows, 128] lanes zero-padded to the int8 tile quantum (zero
pads are exact: they never raise the amax, quantize to 0, decode to 0,
and contribute 0 residual — sliced off before anything reaches the
wire).  The encode needs the global amax before any block can quantize,
so its grid is (2, blocks): a max pass, then a quantize pass over the
same tiles, the running amax carried in SMEM scratch.  Decode kernels
are single-pass with parallel grids, and the accumulator/decode output
aliases its input buffer (``input_output_aliases``) so the in-place add
stays in place.

Dispatch: ``Int8Scheme(impl="pallas")`` — the ``--ring-codec-impl``
knob resolved by ``ops.ring.get_wire_scheme(codec_impl=...)``; flat,
hierarchical inner/outer, and all-gather relay paths all route through
the scheme's ``encode``/``encode_with_residual``/``decode_add``/
``decode`` methods, so one knob moves every hop.  On non-TPU backends
the kernels run under the Pallas interpreter (``ops/pallas/common.py``)
— tier-1 exercises the identical code path the TPU compiles.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from distributed_machine_learning_tpu.ops.pallas.common import (
    LANES as _LANES,
    _interpret,
    lane_tiles,
    padded_lane_rows,
    pick_block,
    pltpu,
    tile_compiler_params,
)

# Low-8-mantissa-bit mask: truncating the scale to 16 significand bits
# makes every decode product EXACT in f32 (|q| ≤ 127 is 7 significant
# bits; 7 + 16 ≤ 24), which is what makes the fused/XLA parity contract
# BITWISE *by construction* — see ``truncate_scale``.  A numpy scalar,
# not a jnp array: inside a kernel trace it stays a literal instead of
# a captured constant (which pallas_call rejects).
_SCALE_MASK = np.uint32(0xFFFFFF00)


def truncate_scale(scale: jax.Array) -> jax.Array:
    """Truncate a positive f32 scale to 16 significand bits (zero the
    low 8 mantissa bits).

    Why: with a full-precision scale, ``q·scale`` rounds — and whether
    a downstream ``v − q·scale`` / ``acc + q·scale`` consumes the
    rounded product or an FMA-contracted exact one is a FUSION-CONTEXT
    decision XLA makes differently for the kernel build and the XLA
    build (``optimization_barrier``, identity ``reduce_precision`` and
    runtime-select fences are all deleted or distributed away by the
    CPU pipeline — measured).  Truncating the scale makes the product
    exact (7-bit ``|q|`` × 16-bit scale ≤ 24 significand bits), so
    contraction cannot change any bit and the two builds agree
    bitwise on every backend, as an arithmetic fact.  The cost is
    ≤ 2⁻¹⁶ relative on the scale — three orders of magnitude below the
    int8 quantization noise it scales.  Integer bit ops only, so the
    truncation itself is fusion-proof.
    """
    bits = jax.lax.bitcast_convert_type(scale, jnp.uint32)
    return jax.lax.bitcast_convert_type(bits & _SCALE_MASK, jnp.float32)


def chunk_scale(amax: jax.Array) -> jax.Array:
    """The ring codec's per-chunk scale from the chunk's ``max|v|``:
    symmetric ``amax/127`` (the serving weight quantizer's recipe —
    ``quantize_int8`` in ``ops/pallas/quant_matmul.py`` — per chunk),
    1.0 for an all-zero chunk (avoids 0/0), mantissa-truncated for the
    exact-product property (:func:`truncate_scale`)."""
    return truncate_scale(
        jnp.where(amax > 0, amax / jnp.float32(127.0), jnp.float32(1.0))
    )


def quantize_chunk_int8(v: jax.Array) -> tuple[jax.Array, jax.Array]:
    """XLA reference implementation of the ring-chunk quantizer:
    ``(q int8 [L], scale f32 [1])`` with ``v ≈ q·scale``.  ONE
    definition of the recipe shared with the fused kernels below (same
    amax, same truncated scale, same round/clip), so the two
    implementations cannot drift — the bitwise parity gate in
    ``tests/test_pallas_fusion.py`` holds them together."""
    v = v.astype(jnp.float32)
    scale = chunk_scale(jnp.max(jnp.abs(v)))
    q = jnp.clip(jnp.round(v / scale), -127, 127).astype(jnp.int8)
    return q, scale.reshape(1)


# int8 VMEM tiles need (32, 128) alignment; padding every chunk to the
# full 32×128 quantum keeps one layout for all three kernels (f32's
# (8, 128) and bf16's (16, 128) divide it).
_ROW_QUANTUM = 32
# Stationary-block target: 512 rows × 128 lanes = 64K elems — 256 KB
# f32 in + 64 KB int8 + 256 KB residual per block stays well under the
# ~2 MB/buffer double-buffered VMEM budget at any chunk size.
_BLOCK_ROWS = 512


def _padded_rows(length: int) -> int:
    return padded_lane_rows(length, _ROW_QUANTUM)


def _as_tiles(v: jax.Array, rows: int) -> jax.Array:
    return lane_tiles(v, rows)


def _block_rows(rows: int) -> int:
    # rows is a multiple of _ROW_QUANTUM, so a quantum-aligned divisor
    # always exists and pick_block cannot return None here.
    return pick_block(rows, _BLOCK_ROWS, _ROW_QUANTUM) or rows


# ---------------------------------------------------------------------------
# Encode: amax pass + quantize pass over the same tiles, one pallas_call.
# ---------------------------------------------------------------------------


def _encode_kernel(v_ref, q_ref, s_ref, *out_refs, with_residual):
    """Grid (2, blocks): phase 0 folds each tile's |max| into the SMEM
    running amax; phase 1 quantizes every tile against the final scale
    (and, with_residual, emits ``v − q·scale`` from the registers —
    the decode the XLA path materializes to HBM for the EF residual)."""
    if with_residual:
        err_ref, amax_ref = out_refs
    else:
        (amax_ref,) = out_refs
    phase = pl.program_id(0)
    blk = pl.program_id(1)

    @pl.when((phase == 0) & (blk == 0))
    def _init():
        amax_ref[0] = 0.0

    @pl.when(phase == 0)
    def _max_pass():
        amax_ref[0] = jnp.maximum(amax_ref[0], jnp.max(jnp.abs(v_ref[...])))

    @pl.when(phase == 1)
    def _quantize_pass():
        scale = chunk_scale(amax_ref[0])
        v = v_ref[...]
        q = jnp.clip(jnp.round(v / scale), -127, 127).astype(jnp.int8)
        q_ref[...] = q
        s_ref[0, 0] = scale
        if with_residual:
            # q·scale is EXACT (truncate_scale), so this subtraction is
            # FMA-contraction-immune and lands bit-identically to the
            # XLA build's ``v − decode(encode(v))``.
            err_ref[...] = v - q.astype(jnp.float32) * scale


def _encode_call(v: jax.Array, with_residual: bool):
    length = v.shape[0]
    rows = _padded_rows(length)
    tiles = _as_tiles(v.astype(jnp.float32), rows)
    br = _block_rows(rows)
    blocks = rows // br
    tile_spec = pl.BlockSpec((br, _LANES), lambda p, b: (b, 0))
    out_shapes = [
        jax.ShapeDtypeStruct((rows, _LANES), jnp.int8),
        jax.ShapeDtypeStruct((1, 1), jnp.float32),
    ]
    out_specs = [tile_spec, pl.BlockSpec((1, 1), lambda p, b: (0, 0))]
    if with_residual:
        out_shapes.append(jax.ShapeDtypeStruct((rows, _LANES), jnp.float32))
        out_specs.append(tile_spec)
    outs = pl.pallas_call(
        functools.partial(_encode_kernel, with_residual=with_residual),
        grid=(2, blocks),
        in_specs=[tile_spec],
        out_specs=tuple(out_specs),
        out_shape=tuple(out_shapes),
        scratch_shapes=[pltpu.SMEM((1,), jnp.float32)],
        interpret=_interpret(),
        # Both axes sequential: phase 1 must see phase 0's amax, and the
        # amax fold itself carries across blocks.
        **tile_compiler_params(("arbitrary", "arbitrary")),
    )(tiles)
    q = outs[0].reshape(-1)[:length]
    scale = outs[1].reshape(1)
    if not with_residual:
        return q, scale
    return q, scale, outs[2].reshape(-1)[:length]


def encode_int8(v: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Fused quantize of a flat f32 chunk → ``(q int8 [L], scale f32
    [1])`` — the exact ``Int8Scheme`` wire payload, computed in one
    kernel."""
    return _encode_call(v, with_residual=False)


def encode_int8_residual(
    v: jax.Array,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Fused quantize + error-feedback residual: ``(q, scale, err)``
    with ``err = v − q·scale`` emitted from the same registers that
    produced ``q`` — the dequantized copy the XLA path writes to HBM
    just to subtract it never exists here."""
    return _encode_call(v, with_residual=True)


# ---------------------------------------------------------------------------
# Decode / decode-accumulate: single pass, parallel grid, aliased output.
# ---------------------------------------------------------------------------


def _decode_add_kernel(s_ref, q_ref, acc_ref, o_ref):
    # q·scale exact (truncated scale) → the add cannot be perturbed by
    # FMA contraction; bitwise-stable across fusion contexts.
    o_ref[...] = acc_ref[...] + q_ref[...].astype(jnp.float32) * s_ref[0]


def _decode_kernel(s_ref, q_ref, o_ref):
    o_ref[...] = q_ref[...].astype(jnp.float32) * s_ref[0]


def decode_add_int8(
    q: jax.Array, scale: jax.Array, acc: jax.Array
) -> jax.Array:
    """One reduce-scatter arrival, fused: ``acc + q·scale`` with the
    dequantized payload living only in registers.  ``acc`` is aliased
    into the output, so the accumulate is genuinely in place."""
    length = acc.shape[0]
    rows = _padded_rows(length)
    q_t = _as_tiles(q, rows)
    acc_t = _as_tiles(acc.astype(jnp.float32), rows)
    br = _block_rows(rows)
    tile_spec = pl.BlockSpec((br, _LANES), lambda b: (b, 0))
    out = pl.pallas_call(
        _decode_add_kernel,
        grid=(rows // br,),
        in_specs=[
            pl.BlockSpec((1,), lambda b: (0,), memory_space=pltpu.SMEM),
            tile_spec,
            tile_spec,
        ],
        out_specs=tile_spec,
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
        input_output_aliases={2: 0},
        interpret=_interpret(),
        **tile_compiler_params(("parallel",)),
    )(scale, q_t, acc_t)
    return out.reshape(-1)[:length]


def decode_int8(q: jax.Array, scale: jax.Array, length: int) -> jax.Array:
    """All-gather relay decode: dense f32 chunk from ``(q, scale)``,
    one pass."""
    rows = _padded_rows(length)
    q_t = _as_tiles(q, rows)
    br = _block_rows(rows)
    tile_spec = pl.BlockSpec((br, _LANES), lambda b: (b, 0))
    out = pl.pallas_call(
        _decode_kernel,
        grid=(rows // br,),
        in_specs=[
            pl.BlockSpec((1,), lambda b: (0,), memory_space=pltpu.SMEM),
            tile_spec,
        ],
        out_specs=tile_spec,
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
        interpret=_interpret(),
        **tile_compiler_params(("parallel",)),
    )(scale, q_t)
    return out.reshape(-1)[:length]
