"""Auto-resume supervision — compose detection, checkpoints, and retry
into a run that survives.

The pieces existed but nothing composed them (ISSUE: the reference
deadlocks on the first fault; SURVEY.md §5): ``runtime/resilience.py``
detects stalls and preemptions, ``train/checkpoint.py`` writes
crash-consistent saves and ``latest_checkpoint`` skips incomplete ones,
``train/loop.py`` stops at step boundaries.  This module is the ladder
that joins them, the policy every flash-scale data-parallel run
(PAPERS.md: arxiv 1811.05233, 1711.04325) ends up with:

1. **skip** — a non-finite gradient skips one update (the guard inside
   the jitted step, ``train/step.py``/``train/lm_step.py``);
2. **retry** — a data-path exception recreates the iterator with
   backoff (``data/retry.py``);
3. **restart** — anything worse (stall, crash, death mid-checkpoint)
   restores the newest *complete* checkpoint and continues, up to
   ``max_restarts``.

Exactness contract: checkpoints record the data *cursor* (batches
consumed) alongside the step counter, and batch factories are
cursor-keyed, so a restarted run replays exactly the stream the dead run
would have seen — a supervised run with faults lands on the same final
step count, and bit-identical params, as a fault-free run of the same
seed minus the guard-skipped batches (``tests/test_resilience.py``
asserts this end to end).

Stall escalation is two-phase because a hung collective cannot be
un-hung from inside: the watchdog *declares* the stall from its daemon
thread (and can ``os._exit`` for external supervisors — the production
policy); in-process, :class:`RaisingWatchdog` turns the next completed
step boundary into a :class:`StallError` so a *transient* stall (slow
storage, injected sleep) is healed by restart rather than silently
absorbed into one long step.

Everything above heals one process.  :func:`gang_supervise` is the
multi-host rung: a gang of worker processes coordinated through
``runtime/coordinator.py`` (heartbeats, peer-failure detection,
coordinated abort) is restarted *as a group* from the restore point
every rank agrees on — the failure mode where one dead rank would
otherwise leave the others blocked in a collective forever.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Callable

import jax

from distributed_machine_learning_tpu.runtime.faults import (
    FaultEvents,
    FaultInjector,
)
from distributed_machine_learning_tpu.runtime.resilience import Watchdog
from distributed_machine_learning_tpu.utils.logging import rank0_print


class StallError(RuntimeError):
    """A watchdog-declared stall, surfaced at a step boundary so the
    supervisor can restart from the latest checkpoint."""


class RaisingWatchdog(Watchdog):
    """A Watchdog whose ``beat`` raises :class:`StallError` once a stall
    episode has been declared.

    The base class can only report (its thread cannot interrupt a stuck
    step); raising from ``beat`` moves the escalation into the training
    thread at the first step boundary *after* the stall — state is
    consistent there, so the supervisor can restore and retry.  A truly
    infinite hang never reaches a beat; that case is the base class's
    ``exit_code`` fail-fast + external supervisor territory.
    """

    def __init__(self, timeout_s: float, events: FaultEvents | None = None,
                 poll_s: float | None = None):
        def _on_stall(elapsed: float) -> None:
            if events is not None:
                events.stalls += 1
            rank0_print(
                f"[supervisor] stall: no step completed in {elapsed:.1f}s "
                f"(timeout {timeout_s}s); will restart from the latest "
                "checkpoint at the next step boundary"
            )

        super().__init__(timeout_s, on_stall=_on_stall, poll_s=poll_s)

    def beat(self) -> None:
        if self.stalled:
            raise StallError(
                f"step stalled past {self.timeout_s}s; restarting from "
                "the latest checkpoint"
            )
        super().beat()


def run_attempts(attempt: Callable[[int], object], *, max_restarts: int = 3,
                 events: FaultEvents | None = None):
    """Run ``attempt(restart_index)`` until it returns, restarting on any
    Exception up to ``max_restarts`` times.

    The generic retry primitive behind both :func:`supervised_train` and
    the CLI's ``--resume auto``: ``attempt`` owns its own
    restore-from-latest-checkpoint logic (it knows the model/template);
    this owns the policy — count, log, give up loudly.
    KeyboardInterrupt/SystemExit always propagate.
    """
    from distributed_machine_learning_tpu.telemetry import get_telemetry

    if max_restarts < 0:
        raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
    restarts = 0
    while True:
        # Each attempt is one `restart_attempt` span in the trace and
        # one attempt tag on every metrics row it produces — the chaos
        # timeline's backbone: fault → failed span → next attempt's rows
        # appended (never truncating the dead attempt's history).
        tel = get_telemetry()
        if tel is not None:
            tel.set_attempt(tel.attempt if restarts == 0 else
                            tel.attempt + 1)
        try:
            # Tag with the TELEMETRY attempt (disk-resumed offset
            # included), not the in-process restart index — spans and
            # metrics rows must carry the same number or the timeline
            # can't be correlated after a re-exec.
            with (tel.span("restart_attempt", attempt=tel.attempt)
                  if tel is not None else contextlib.nullcontext()):
                return attempt(restarts)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as exc:
            if restarts >= max_restarts:
                rank0_print(
                    f"[supervisor] giving up after {restarts} restart(s): "
                    f"{type(exc).__name__}: {exc}"
                )
                raise
            restarts += 1
            if events is not None:
                events.restarts += 1
            rank0_print(
                f"[supervisor] attempt failed ({type(exc).__name__}: "
                f"{exc}); restart {restarts}/{max_restarts} from the "
                "latest complete checkpoint"
            )


class GangFailure(RuntimeError):
    """The gang kept failing after exhausting its restarts."""

    def __init__(self, message: str, returncodes: list[int | None]):
        super().__init__(message)
        self.returncodes = returncodes


# ``by_rank`` of an abort latch the SUPERVISOR wrote to stop the gang at
# a PLANNED boundary (grow-on-join, straggler replacement) — no worker
# holds a negative rank, so post-mortems can tell a planned stop from a
# failure, and the attribution pass knows there is no victim to charge.
SUPERVISOR_BOUNDARY_RANK = -1


def _seed_checkpoint(dst_dir, step: int | None, src_dirs) -> bool:
    """Make ``dst_dir`` hold a valid copy of checkpoint ``step``, copying
    from the first of ``src_dirs`` whose copy validates — the admission
    half of a grow: a recovered host may have missed saves while it was
    gone, and a warm spare's prefetch may trail the elected restore
    point; either way the joiner must resume from the SAME step as the
    survivors or the gang diverges at the first barrier.  Valid for the
    replicated-dp layout the gang harness runs (every rank's checkpoint
    holds the full state); per-host SHARD layouts reshard offline
    instead (``tools/ckpt_reshard.py``).  Returns True when ``dst_dir``
    ends up holding a valid ``step_<step>`` (already had one, or the
    copy landed); False when no source could provide it."""
    import shutil

    from distributed_machine_learning_tpu.train.checkpoint import (
        validate_checkpoint,
    )

    if step is None:
        return False
    dst_dir = os.fspath(dst_dir)
    dst = os.path.join(dst_dir, f"step_{step}")
    if os.path.isdir(dst) and validate_checkpoint(dst) == []:
        return True
    for src_dir in src_dirs:
        src = os.path.join(os.fspath(src_dir), f"step_{step}")
        if not os.path.isdir(src) or validate_checkpoint(src) != []:
            continue
        # Copy to a temp name, validate the COPY, then rename into
        # place: a torn copy must never look like a complete
        # checkpoint to the joiner's fallback chain.
        tmp = dst + f".seed{os.getpid()}"
        shutil.rmtree(tmp, ignore_errors=True)
        try:
            shutil.copytree(src, tmp)
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)
            continue
        if validate_checkpoint(tmp) != []:
            shutil.rmtree(tmp, ignore_errors=True)
            continue
        shutil.rmtree(dst, ignore_errors=True)
        os.replace(tmp, dst)
        return True
    return False


def _gang_health_check(tx, sampler, detector, active, events, tel,
                       attempt: int, state: dict) -> None:
    """One advisory health pass over the gang's heartbeat snapshots —
    the straggler half of the observability plane (ISSUE 6).

    Feeds the per-rank effective step times (``HeartbeatSampler``:
    rolling mean, inflated by in-flight time only for the ranks the
    lock-step barrier is actually waiting on) into the shared
    ``StragglerDetector``.  Observations are throttled to at most one
    per gang-median step time (never faster than the poll), so
    ``consecutive`` keeps its offline meaning — K consecutive *steps*,
    not K poll ticks — on gangs whose steps outpace the poll.
    Detection only, this PR: verdicts become
    ``gang_straggler{rank=...}`` counters, the ``gang_skew_ratio``
    gauge, ``FaultEvents.stragglers``, a ``gang_health.jsonl`` ledger
    entry, and a supervisor log line — never an abort (the peer-timeout
    machinery owns life-and-death; this names the slow rank *before*
    that machinery has to).  Rank ids in verdicts/counters use the
    ORIGINAL numbering (``active[cur_rank]``), the identity that
    survives shrinks.  Beats arrive through the gang transport's
    batched snapshot (``tx.read_beat_payloads()`` — one read per poll
    regardless of world size), never by globbing beat files.
    """
    from distributed_machine_learning_tpu.telemetry.aggregator import (
        median,
    )

    samples = sampler.sample(None, beats=tx.read_beat_payloads())
    stimes = [s.step_time_s for s in samples.values()
              if s.step_time_s is not None]
    now = time.monotonic()
    if stimes and now - state.get("last_feed", 0.0) < median(stimes):
        return
    state["last_feed"] = now
    feed = {r: s.eff_step_time_s for r, s in samples.items()
            if not s.done and not s.suspended and r < len(active)}
    verdicts = detector.update(feed)
    # Per-ORIGINAL-rank flag streaks (consecutive health feeds the
    # detector holds the rank flagged) — the hysteresis input of the
    # backup-worker replacement policy: a verdict alone (one episode)
    # never flips the gang; the rank must STAY flagged across feeds.
    streaks = state.setdefault("flag_streak", {})
    flagged_orig = {active[r] for r in detector.flagged
                    if 0 <= r < len(active)}
    for orig in list(streaks):
        if orig not in flagged_orig:
            streaks[orig] = 0
    for orig in flagged_orig:
        streaks[orig] = streaks.get(orig, 0) + 1
    if tel is not None and detector.skew_ratio:
        tel.registry.gauge("gang_skew_ratio").set(detector.skew_ratio)
    for v in verdicts:
        orig = active[v.rank] if 0 <= v.rank < len(active) else v.rank
        if events is not None:
            events.stragglers += 1
        if tel is not None:
            tel.registry.counter("gang_straggler", rank=str(orig)).inc()
            tel.tracer.instant("gang_straggler", rank=orig,
                               ratio=round(v.ratio, 2))
            tel.flush()
        step = samples[v.rank].step if v.rank in samples else None
        tx.append_health_event(
            "straggler", rank=orig, cur_rank=v.rank,
            attempt=attempt, step=step, ratio=round(v.ratio, 3),
            value_s=v.value_s, median_s=v.median_s,
        )
        rank0_print(
            f"[gang] straggler advisory: rank {orig} step time "
            f"{v.value_s:.3f}s is {v.ratio:.1f}x the gang median "
            f"{v.median_s:.3f}s ({v.streak} consecutive observations; "
            "detection only — peer-timeout policy unchanged)"
        )


def _drain_gang(procs, grace_s: float,
                join_s: float = 2.0) -> list[int | None]:
    """Terminate (then kill) every still-running worker; returns the
    final returncodes.

    Before terminating, waits up to ``join_s`` for the survivors to
    exit on their own: when one rank dies, the others' monitors join
    the coordinated abort within a heartbeat poll — and that self-exit
    path FLUSHES their telemetry (the abort handler's ``tel.flush()``),
    while a SIGTERM racing it would drop every buffered row and span
    of the attempt being diagnosed.  Workers that are genuinely hung
    still get terminated (then killed) on the old schedule.
    """
    deadline = time.monotonic() + join_s
    while (time.monotonic() < deadline
           and any(p.poll() is None for p in procs)):
        time.sleep(0.05)
    for p in procs:
        if p.poll() is None:
            with contextlib.suppress(OSError):
                p.terminate()
    deadline = time.monotonic() + grace_s
    for p in procs:
        if p.poll() is None:
            timeout = max(deadline - time.monotonic(), 0.1)
            try:
                p.wait(timeout=timeout)
            except Exception:
                with contextlib.suppress(OSError):
                    p.kill()
                with contextlib.suppress(Exception):
                    p.wait(timeout=5)
    return [p.poll() for p in procs]


class _ThreadWorker:
    """A Popen-shaped handle on an IN-PROC gang member (ISSUE 12): a
    daemon thread running a callable that takes a stop event and
    returns an exit code.

    The supervisor's process machinery (poll/terminate/kill/wait) maps
    onto thread semantics: ``terminate``/``kill`` set the stop event —
    cooperative, because a thread cannot be SIGKILLed; the in-proc
    worker checks it at every barrier poll and in every injected-stall
    sleep, and a truly wedged thread is abandoned as a daemon (the
    hub's epoch guard keeps its late writes out of the next attempt's
    state).  Exit-code conventions match the subprocess harness:
    return value, or ``runtime/inproc_worker.py::WorkerExit``'s code
    (the coordinated-abort / injected-fault paths), or 1 on an
    unexpected exception."""

    def __init__(self, fn, name: str = "gang-inproc-worker"):
        import threading

        self.stop_event = threading.Event()
        self._code: int | None = None
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._run, args=(fn,),
                                        name=name, daemon=True)
        self._thread.start()

    def _run(self, fn) -> None:
        from distributed_machine_learning_tpu.runtime.inproc_worker import (
            WorkerExit,
        )

        try:
            code = fn(self.stop_event)
            code = 0 if code is None else int(code)
        except WorkerExit as exc:
            code = exc.code
        except BaseException as exc:  # surfaced as the exit code
            import traceback

            traceback.print_exc()
            rank0_print(
                f"[gang] in-proc worker {self._thread.name} died: "
                f"{type(exc).__name__}: {exc}"
            )
            code = 1
        with self._lock:
            self._code = code

    def poll(self) -> int | None:
        with self._lock:
            return self._code

    def terminate(self) -> None:
        self.stop_event.set()

    kill = terminate

    def wait(self, timeout: float | None = None) -> int | None:
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError(
                f"in-proc worker {self._thread.name} still running")
        return self.poll()


def _spawn_worker(spec, out, env):
    """One gang member from a ``worker_cmd`` result: an argv list
    spawns a subprocess (the historical path); a callable runs as an
    in-proc :class:`_ThreadWorker` (``spec(stop_event) -> exit code``,
    no log redirection — in-proc members share the supervisor's
    stdio)."""
    import subprocess

    if callable(spec):
        return _ThreadWorker(spec, name=getattr(spec, "__name__",
                                                "gang-inproc-worker"))
    return subprocess.Popen(
        spec, stdout=out,
        stderr=subprocess.STDOUT if out is not None else None, env=env,
    )


def _worker_cmd_arity(worker_cmd) -> int:
    """How many of ``(rank, attempt, world, orig_rank)`` the caller's
    ``worker_cmd`` accepts (2-4; ``*args`` takes all four).  Keeps the
    legacy two-argument closures working while elastic launchers opt in
    to the world-size/original-rank parameters a shrink needs."""
    import inspect

    try:
        params = inspect.signature(worker_cmd).parameters
    except (TypeError, ValueError):
        return 2
    if any(p.kind == p.VAR_POSITIONAL for p in params.values()):
        return 4
    # Count only positionally-fillable parameters: keyword-only and
    # **kwargs must not inflate the arity (a legacy closure with
    # trailing keyword-only options is still a two-argument worker_cmd).
    positional = sum(
        1 for p in params.values()
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
    )
    return min(max(positional, 2), 4)


def gang_supervise(worker_cmd, world: int, gang_dir,
                   *, ckpt_dirs=None, max_restarts: int = 3,
                   rank_restart_budget: int | None = None,
                   min_world: int | None = None,
                   max_world: int | None = None,
                   spares: int = 0, spare_cmd=None,
                   straggler_policy: str = "advise",
                   replace_after: int = 2,
                   events: FaultEvents | None = None,
                   poll_s: float | None = None, grace_s: float = 10.0,
                   env=None, log_dir=None,
                   straggler_multiple: float = 4.0,
                   straggler_consecutive: int = 3,
                   transport=None) -> list[int]:
    """Run a gang of ``world`` worker processes to completion, restarting
    ALL of them together on any failure — the multi-host analogue of
    :func:`run_attempts` — and, when allowed, SHRINKING past ranks that
    are gone for good.

    ``worker_cmd(rank, attempt[, world[, orig_rank]])`` returns the argv
    for one worker (the ``attempt`` parameter lets the caller pick a
    fresh coordination-service port per relaunch; ``world`` is the
    CURRENT gang size, which a shrink reduces; ``orig_rank`` is the
    rank's identity in the original numbering — its checkpoint
    directory follows it across renumberings).  Two-argument closures
    keep working; elastic launchers accept all four.  Workers
    coordinate through ``gang_dir`` via ``runtime/coordinator.py``:
    heartbeat files, the abort latch, and restore-point records.

    The restart protocol, in order:

    1. any worker exiting nonzero (a died rank, or survivors taking the
       coordinated abort exit) fails the attempt; the rest are
       terminated so no orphan keeps the next rendezvous port busy;
    2. the failure is ATTRIBUTED: ranks that exited on their own with a
       non-abort code, plus the peer named by the abort latch, each
       count one failure against their per-rank budget
       (``rank_restart_budget``; None = unlimited).  A rank whose
       budget is spent — or whose ``lose_rank`` fault is recorded in
       the fired-fault ledger (the dead-host marker) — is declared
       UNRECOVERABLE;
    3. with no unrecoverable ranks: the restore-point election
       (``elect_restore_step``) picks the highest checkpoint step EVERY
       rank verified — checkpoints newer than it are quarantined
       (``enforce_restore_point``) so each relaunched worker's fallback
       chain resolves to the same restore point — and the whole gang is
       relaunched at the same size (``gang_restarts`` counter, one
       ``gang_attempt`` span per try), up to ``max_restarts`` times;
    4. with unrecoverable ranks and ``min_world`` set: the gang
       SHRINKS to the survivors — the election runs over the survivors'
       records only, newer checkpoints are quarantined in the
       survivors' directories, the old numbering's restore records are
       dropped (the ledger is KEPT: renumbered survivors must not
       re-fire latched faults), and the gang relaunches at world size
       M < N with survivors renumbered ``0..M-1`` in original-rank
       order (``gang_shrinks`` counter + ``gang_shrink`` trace
       instant).  Shrinking below ``min_world`` — or any unrecoverable
       rank when ``min_world`` is None — raises :class:`GangFailure`.

    ``ckpt_dirs``: one shared checkpoint directory or one per ORIGINAL
    rank (per-host shard layouts); after a shrink, each survivor keeps
    its own directory.  Returns the final returncodes (all zero, one
    per surviving rank) on success; raises :class:`GangFailure` after
    the restart budget is spent.

    ``log_dir``: when given, each worker's stdout+stderr streams to
    ``rank<r>.attempt<k>.log`` there (current-numbering rank) — the
    gang post-mortem surface.

    Advisory health (ISSUE 6): every poll also runs the straggler
    detector over the heartbeat metric snapshots — a rank whose
    effective step time exceeds ``straggler_multiple`` x the gang
    median for ``straggler_consecutive`` observations is flagged
    (``gang_straggler{rank}`` counter, ``gang_skew_ratio`` gauge,
    ``FaultEvents.stragglers``, a ``gang_health.jsonl`` entry, and a
    log line) WITHOUT any change to restart policy under the default
    ``straggler_policy="advise"``.

    Elastic GROW (ISSUE 10) — the other direction of the shrink
    machinery, enabled by ``max_world``:

    5. at EVERY coordinated boundary the supervisor reads the join
       channel (``coordinator.announce_join`` / ``join_rank<r>.json``):
       announced non-spare ranks not currently active — a recovered
       host (the ``recover_rank@r:k`` fault is the deterministic test
       form), or a newly provisioned one — are ADMITTED up to
       ``max_world``; a ``recover_rank`` ledger entry also clears the
       rank's ``lose_rank`` marker and resets its failure budget.
       While the gang is healthy, a pending join triggers a PLANNED
       boundary: the supervisor itself latches the abort
       (``by_rank=-1``) so the workers stop at a checkpoint-consistent
       point; planned boundaries charge nobody's budget and do not
       consume ``max_restarts``;
    6. ``spares`` warm-spare processes (argv from
       ``spare_cmd(orig_rank, attempt)``; original ids ``world..
       world+spares-1``) run beside every attempt: they heartbeat on
       the join channel and prefetch the newest verified checkpoint
       into their own rank directory, but never train.  Spares are
       PROMOTED only at planned boundaries — filling the world at a
       grow admission, or replacing a demoted straggler — never
       silently at a failure restart, so a shrink's reduced world
       stays observable;
    7. admission is checkpoint-seeded: the election runs among the
       CARRIED-OVER members, and every joiner's directory is made to
       hold a valid copy of the elected step (``_seed_checkpoint``;
       newer strays quarantined) before the relaunch, so the grown
       gang resumes from one consistent restore point.  The world
       renumbers ``0..M-1`` in original-rank order exactly like a
       shrink, and ``reshard_restore`` absorbs the M→N change on
       every rank;
    8. ``straggler_policy="replace"`` (requires ``spares >= 1``) turns
       the advisory verdicts into backup-worker semantics
       (arxiv 1811.05233): a rank the detector holds flagged for
       ``replace_after`` consecutive health feeds — hysteresis: one
       flag never flips the gang — is DEMOTED to the spare pool and
       the best-prefetched live spare is promoted in its place at a
       planned replacement boundary (world size unchanged,
       ``spare_promotions``/``spare_demotions`` counters + health
       ledger entries tell the story).

    Observable surface of a grow: ``gang_grows`` counter +
    ``gang_world_size`` gauge + one ``gang_grow`` trace instant, and
    ``grow``/``promote``/``demote`` events in ``gang_health.jsonl`` —
    exact telemetry parity with the shrink path.

    Pluggable control plane (ISSUE 12): every channel above travels
    through a ``runtime/transport.py::GangTransport`` — ``transport``
    defaults to the historical file backend over ``gang_dir``.  A
    ``worker_cmd``/``spare_cmd`` may return a CALLABLE instead of an
    argv list: the member then runs as an in-proc daemon thread
    (:class:`_ThreadWorker`; ``runtime/inproc_worker.py`` builds such
    callables), which is what makes 64-128-rank chaos campaigns run in
    tier-1 time.  ``poll_s=None`` defers the supervision cadence to
    the transport (the cadence-is-a-transport-property bugfix); the
    run ends by appending a ``transport`` health-ledger record (ops /
    retries / timeouts) that ``tools/gang_status.py`` renders.
    """
    from distributed_machine_learning_tpu.runtime.coordinator import (
        GANG_ABORT_EXIT,
        elect_restore_step,
        enforce_restore_point,
    )
    from distributed_machine_learning_tpu.runtime.faults import (
        recovered_ranks_from_entries,
        unrecovered_lost_from_entries,
    )
    from distributed_machine_learning_tpu.runtime.transport import (
        FileTransport,
    )
    from distributed_machine_learning_tpu.telemetry import get_telemetry
    from distributed_machine_learning_tpu.telemetry.aggregator import (
        HeartbeatSampler,
        StragglerDetector,
    )

    tx = transport if transport is not None \
        else FileTransport(gang_dir, events=events)
    if getattr(tx, "events", None) is None:
        tx.events = events
    if poll_s is None:
        poll_s = tx.supervisor_poll_s(world)

    def _record_transport_stats() -> None:
        # The durable transport-health record (ops/retries/timeouts by
        # backend) the status tool renders post-mortem — written on
        # every terminal path, best-effort (a stats line must never
        # mask the run's real outcome).
        try:
            tx.append_health_event("transport", **tx.stats())
        except Exception as exc:
            rank0_print(f"[gang] transport stats not recorded: "
                        f"{type(exc).__name__}: {exc}")

    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    if max_restarts < 0:
        raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
    if min_world is not None and not 1 <= min_world <= world:
        raise ValueError(
            f"min_world must be in [1, {world}], got {min_world}"
        )
    if rank_restart_budget is not None and rank_restart_budget < 0:
        raise ValueError(
            f"rank_restart_budget must be >= 0, got {rank_restart_budget}"
        )
    if max_world is not None and max_world < world:
        raise ValueError(
            f"max_world must be >= the launch world {world}, got "
            f"{max_world}"
        )
    if spares < 0:
        raise ValueError(f"spares must be >= 0, got {spares}")
    if spares > 0 and spare_cmd is None:
        raise ValueError("spares > 0 requires spare_cmd(orig_rank, "
                         "attempt) to build the spare worker argv")
    if straggler_policy not in ("advise", "replace"):
        raise ValueError(
            f"straggler_policy must be 'advise' or 'replace', got "
            f"{straggler_policy!r}"
        )
    if straggler_policy == "replace" and spares < 1:
        raise ValueError(
            "straggler_policy='replace' needs at least one warm spare "
            "to promote (spares >= 1); without one the policy could "
            "only ever demote — use 'advise' instead"
        )
    if replace_after < 1:
        raise ValueError(f"replace_after must be >= 1, got {replace_after}")
    cmd_arity = _worker_cmd_arity(worker_cmd)
    if min_world is not None and cmd_arity < 3:
        raise ValueError(
            "shrinking (min_world) requires a worker_cmd that accepts "
            "the current world size — use worker_cmd(rank, attempt, "
            "world[, orig_rank]); a legacy two-argument closure would "
            "relaunch workers that still assume the original world"
        )
    if (max_world is not None or spares > 0) and cmd_arity < 4:
        raise ValueError(
            "growing (max_world/spares) requires the full elastic "
            "worker_cmd(rank, attempt, world, orig_rank): admissions "
            "and promotions renumber the gang, and a joiner's identity "
            "only travels via orig_rank"
        )
    # A fresh supervision run: stale beats/aborts AND restore records
    # from any earlier run in the same gang state would poison
    # detection and the election.
    tx.clear_gang_state(restore_records=True)
    if log_dir is not None:
        os.makedirs(log_dir, exist_ok=True)
    shared_ckpt = ckpt_dirs is None or isinstance(ckpt_dirs,
                                                  (str, os.PathLike))

    def dirs_for(origs):
        if ckpt_dirs is None:
            return None
        if shared_ckpt:
            return ckpt_dirs
        return [ckpt_dirs[o] for o in origs]

    # position = current rank, value = original rank: the identity map
    # a shrink compacts and a grow re-expands.  Failure counts and
    # checkpoint directories key on the ORIGINAL rank, which survives
    # renumbering.  Spares hold the original ids just past the launch
    # world; a promotion moves the id into `active`, a demotion moves
    # it back into `spare_pool`.
    active = list(range(world))
    spare_pool = list(range(world, world + spares))
    if not shared_ckpt and ckpt_dirs is not None:
        need = world + spares
        if len(ckpt_dirs) < need:
            raise ValueError(
                f"per-rank ckpt_dirs must cover every launch member "
                f"including spares ({need} dirs), got {len(ckpt_dirs)}"
            )
    fail_counts = {r: 0 for r in range(world + spares)}
    # Joiners whose checkpoint seeding failed at a boundary: their
    # announcements are KEPT (a recover_rank join is announced exactly
    # once — consuming it would strand the host forever) but the grow
    # TRIGGER skips them, so they can't re-declare budget-free planned
    # boundaries in a loop; any later boundary retries their admission.
    deferred_joins: set[int] = set()
    restarts = 0  # FAILURE restarts — the max_restarts budget
    attempt = 0   # every relaunch, planned boundaries included: the
    #               log/telemetry/consumption attempt tag
    while True:
        cur_world = len(active)
        tel = get_telemetry()
        if tel is not None:
            tel.registry.gauge("gang_world_size").set(cur_world)
        span = (tel.span("gang_attempt", attempt=attempt,
                         world=cur_world)
                if tel is not None else contextlib.nullcontext())
        # Fresh per attempt: the beat files were just cleared, and a
        # straggler episode must not carry a streak across a relaunch.
        sampler = HeartbeatSampler()
        detector = StragglerDetector(multiple=straggler_multiple,
                                     consecutive=straggler_consecutive)
        health_state: dict = {}
        procs, logs = [], []
        spare_procs: dict[int, object] = {}  # Popen or _ThreadWorker
        planned: dict | None = None

        def ready_spares() -> list[int]:
            """Spare ids promotable RIGHT NOW: process alive and its
            join-channel announcement present — best-prefetched first,
            so a promotion costs the smallest possible seed copy."""
            joins = tx.read_joins()
            alive = [o for o in spare_pool
                     if o in spare_procs
                     and spare_procs[o].poll() is None
                     and o in joins and joins[o].get("spare")]
            def prefetch_key(o):
                # None-check, not truthiness: a prefetched step_0 is a
                # real prefetch and must outrank "nothing prefetched".
                step = joins[o].get("prefetched_step")
                return (-step if step is not None else 1, o)

            return sorted(alive, key=prefetch_key)

        try:
            with span:
                for rank in range(cur_world):
                    spec = worker_cmd(*(rank, attempt, cur_world,
                                        active[rank])[:cmd_arity])
                    out = None
                    if log_dir is not None and not callable(spec):
                        out = open(
                            os.path.join(
                                log_dir,
                                f"rank{rank}.attempt{attempt}.log",
                            ),
                            "ab",
                        )
                    logs.append(out)
                    procs.append(_spawn_worker(spec, out, env))
                for orig in spare_pool:
                    spec = spare_cmd(orig, attempt)
                    out = None
                    if log_dir is not None and not callable(spec):
                        out = open(
                            os.path.join(
                                log_dir,
                                f"spare{orig}.attempt{attempt}.log",
                            ),
                            "ab",
                        )
                    logs.append(out)
                    spare_procs[orig] = _spawn_worker(spec, out, env)
                failed = None
                while failed is None:
                    codes = [p.poll() for p in procs]
                    bad = [(r, c) for r, c in enumerate(codes)
                           if c not in (None, 0)]
                    if bad:
                        failed = bad
                        break
                    if all(c == 0 for c in codes):
                        _record_transport_stats()
                        return list(codes)  # the gang finished cleanly
                    time.sleep(poll_s)
                    if not health_state.get("broken"):
                        try:
                            _gang_health_check(tx, sampler,
                                               detector, active, events,
                                               tel, attempt, health_state)
                        except Exception as exc:
                            # Advisory means advisory: a broken health
                            # pass (disk-full health ledger, torn dir)
                            # must not take down the gang it observes.
                            health_state["broken"] = True
                            rank0_print(
                                "[gang] health advisory disabled for "
                                f"this attempt: "
                                f"{type(exc).__name__}: {exc}"
                            )
                    # -- planned boundaries (elastic grow) -------------
                    # The supervisor itself latches the abort so the
                    # gang stops at a coordinated point; the snapshot of
                    # promotable spares is taken NOW, before the drain
                    # below kills their processes.
                    if (planned is None and max_world is not None
                            and len(active) < max_world):
                        # Same eligibility as the admission filter
                        # below — a join the admission step would skip
                        # (no ckpt dir provisioned for that rank) must
                        # not declare a boundary, or it re-triggers a
                        # budget-free restart every attempt forever.
                        # Seed-failure-deferred joins likewise wait for
                        # a boundary something else causes.
                        pending = sorted(
                            r for r, p in tx.read_joins().items()
                            if not p.get("spare") and r not in active
                            and r not in deferred_joins
                            and (shared_ckpt or r < len(ckpt_dirs or ()))
                        )
                        if pending and tx.declare_abort(
                                f"planned grow boundary: rank(s) "
                                f"{pending} announced join",
                                SUPERVISOR_BOUNDARY_RANK):
                            planned = {"kind": "grow",
                                       "ready": ready_spares()}
                            rank0_print(
                                f"[gang] rank(s) {pending} announced "
                                "join; stopping the gang at a planned "
                                "grow boundary"
                            )
                    if planned is None and straggler_policy == "replace":
                        streaks = health_state.get("flag_streak", {})
                        slow = sorted(
                            o for o, s in streaks.items()
                            if s >= replace_after and o in active
                        )
                        ready = ready_spares() if slow else []
                        if slow and ready and tx.declare_abort(
                                f"straggler replacement: demoting rank "
                                f"{slow[0]} (flagged {replace_after}+ "
                                "consecutive health feeds)",
                                SUPERVISOR_BOUNDARY_RANK):
                            planned = {"kind": "replace",
                                       "demote": slow[0],
                                       "ready": ready}
                            rank0_print(
                                f"[gang] straggler policy: demoting "
                                f"rank {slow[0]} to spare, promoting "
                                f"spare {ready[0]} at a planned "
                                "replacement boundary"
                            )
        finally:
            final_codes = _drain_gang(procs, grace_s)
            # Spares are drained every boundary too (SIGTERM is a clean
            # exit for them); the next attempt relaunches the pool.
            _drain_gang(list(spare_procs.values()), grace_s)
            for out in logs:
                if out is not None:
                    out.close()
        abort = tx.read_abort()
        # A boundary the supervisor itself declared (grow admission /
        # straggler replacement): nobody failed, nobody's budget is
        # charged, and max_restarts is not consumed — the stop is
        # progress, not a fault.  If a real worker abort won the latch
        # race, `planned` stays un-honored and the failure path below
        # runs (pending joins are still admitted at that boundary).
        planned_stop = (
            planned is not None and abort is not None
            and abort.get("by_rank") == SUPERVISOR_BOUNDARY_RANK
        )
        unrecoverable: set[int] = set()
        # recover_rank firings clear their target's EARLIER lose_rank
        # markers — the host came back; holding the old dead-host entry
        # against it would make every loss permanent forever.  The
        # masking is order-aware (unrecovered_lost_from_entries): a
        # rank that dies again AFTER recovering counts as lost again.
        ledger = tx.read_fault_entries()
        recovered = recovered_ranks_from_entries(ledger)
        if planned_stop:
            why = str(abort.get("reason"))
        else:
            why = (f"rank {failed[0][0]} exited {failed[0][1]}"
                   + (f"; abort declared by rank {abort.get('by_rank')}: "
                      f"{abort.get('reason')}" if abort else ""))
            # -- failure attribution (original-rank identities) ---------
            # Only self-exits count — ranks the drain terminated, and
            # ranks that took the coordinated abort exit, are casualties
            # of the victim, not victims themselves.
            victims_cur = {r for r, c in failed if c != GANG_ABORT_EXIT}
            peer = abort.get("peer") if abort else None
            if isinstance(peer, int) and 0 <= peer < cur_world:
                victims_cur.add(peer)
            for r in victims_cur:
                fail_counts[active[r]] += 1
            # lose_rank firings mark their rank's budget exhausted
            # outright (the dead-host event).  The ledger records
            # ORIGINAL-rank ids (the gang worker keys its injector on
            # --orig-rank), so the entries stay valid across
            # renumberings — ranks already shrunk away just filter out
            # of the active set.
            unrecoverable = (unrecovered_lost_from_entries(ledger)
                             & set(active))
            if rank_restart_budget is not None:
                unrecoverable |= {o for o in active
                                  if fail_counts[o] > rank_restart_budget}
            if restarts >= max_restarts:
                rank0_print(
                    f"[gang] giving up after {restarts} restart(s): {why}"
                )
                _record_transport_stats()
                raise GangFailure(
                    f"gang failed after {restarts} restart(s): {why}",
                    final_codes,
                )
            restarts += 1
            if events is not None:
                events.gang_restarts += 1
            if tel is not None:
                tel.registry.counter("gang_restarts").inc()
                tel.flush()
        attempt += 1
        # The health ledger keeps the restart/shrink/grow history the
        # status tool renders (beat files and the abort latch are about
        # to be cleared; this line is what survives).
        tx.append_health_event(
            "boundary" if planned_stop else "restart",
            attempt=attempt, world=cur_world, why=why,
        )

        # -- membership for the next attempt ----------------------------
        # survivors: carried over (they hold election records under the
        # failed attempt's numbering).  joiners: admitted announcements
        # + promoted spares — seeded to the elected restore point below.
        survivors = [o for o in active if o not in unrecoverable]
        lost_s = sorted(unrecoverable)
        if unrecoverable and (min_world is None
                              or len(survivors) < min_world):
            _record_transport_stats()
            raise GangFailure(
                f"rank(s) {lost_s} unrecoverable (budget exhausted "
                f"or lose_rank fired) and the gang cannot shrink "
                f"to {len(survivors)} worker(s)"
                + ("" if min_world is None
                   else f" (min_world {min_world})"),
                final_codes,
            )
        demoted: list[int] = []
        if planned_stop and planned.get("kind") == "replace":
            victim = planned["demote"]
            if victim in survivors:
                survivors = [o for o in survivors if o != victim]
                demoted = [victim]
        joined: list[int] = []
        promoted: list[int] = []
        if max_world is not None:
            room = max_world - len(survivors)
            pending = sorted(
                r for r, p in tx.read_joins().items()
                if not p.get("spare") and r not in survivors
                and (shared_ckpt or r < len(ckpt_dirs or ()))
            )
            joined = pending[:max(room, 0)]
            room -= len(joined)
        if planned_stop:
            # Spares promote ONLY at planned boundaries: filling the
            # world after a grow admission, or replacing the demoted
            # straggler — never silently backfilling a failure shrink.
            quota = (len(demoted) if planned.get("kind") == "replace"
                     else max(max_world - len(survivors) - len(joined),
                              0) if max_world is not None else 0)
            promoted = [o for o in planned.get("ready", [])
                        if o not in survivors][:quota]
        new_active = sorted(set(survivors) | set(joined) | set(promoted))
        reshaped = new_active != active

        if not reshaped:
            # Same membership: clear the dead attempt's beats and abort
            # latch, but KEEP restore records — the election input.
            tx.clear_gang_state()
            if ckpt_dirs is not None:
                elected = elect_restore_step(gang_dir, cur_world,
                                             ckpt_dirs=dirs_for(active),
                                             transport=tx)
                quarantined = enforce_restore_point(dirs_for(active),
                                                    elected)
                rank0_print(
                    f"[gang] restore-point election: step "
                    f"{elected if elected is not None else '<none>'}"
                    + (f"; quarantined {len(quarantined)} newer "
                       f"checkpoint(s)" if quarantined else "")
                )
            rank0_print(
                f"[gang] {why}; coordinated restart "
                f"{restarts}/{max_restarts}"
            )
            continue

        # -- reshape: elect among survivors, seed joiners, renumber -----
        surv_cur = [active.index(o) for o in survivors]
        elected = elect_restore_step(
            gang_dir, cur_world, ckpt_dirs=dirs_for(survivors),
            ranks=surv_cur, transport=tx,
        )
        quarantined = enforce_restore_point(dirs_for(survivors), elected)
        admitted = joined + promoted
        seeded: list[int] = []
        if admitted and ckpt_dirs is not None:
            src_dirs = dirs_for(survivors)
            src_dirs = [src_dirs] if shared_ckpt else src_dirs
            for o in admitted:
                dst = ckpt_dirs if shared_ckpt else ckpt_dirs[o]
                if _seed_checkpoint(dst, elected, src_dirs):
                    seeded.append(o)
                # Either way the joiner's directory must not hold strays
                # NEWER than the restore point (a pre-loss save, a
                # spare prefetch that outran the election).
                enforce_restore_point([dst], elected)
        if ckpt_dirs is not None and elected is not None:
            unseeded = sorted(set(admitted) - set(seeded))
            if unseeded:
                # Admitting a joiner that does NOT hold the elected
                # step would let it resume behind the gang and diverge
                # (re-consumed examples, non-identical params).  Defer
                # its admission instead — announcement kept, trigger
                # suppressed, retried at the next boundary; elected
                # None means no checkpoint exists anywhere and
                # everyone starts from scratch together, so nothing
                # to seed.
                rank0_print(
                    f"[gang] could not seed restore step {elected} "
                    f"for joiner(s) {unseeded}; deferring their "
                    "admission"
                )
                deferred_joins |= set(unseeded)
                joined = [o for o in joined if o in seeded]
                promoted = [o for o in promoted if o in seeded]
                if (planned_stop and planned.get("kind") == "replace"
                        and demoted and not promoted):
                    # The replacement failed to seed: keep the slow
                    # rank rather than shrink the world — a demotion
                    # without a promotion would break the "world size
                    # unchanged" replacement contract (and could dip
                    # below min_world, which only guards loss shrinks).
                    rank0_print(
                        f"[gang] replacement spare unseeded; keeping "
                        f"rank {demoted[0]} live"
                    )
                    # Its dir sat out the survivor election/enforcement;
                    # re-align it to the elected step (normally a no-op
                    # — it saved that step while it was live).
                    dst = (ckpt_dirs if shared_ckpt
                           else ckpt_dirs[demoted[0]])
                    _seed_checkpoint(dst, elected, src_dirs)
                    enforce_restore_point([dst], elected)
                    survivors = sorted(set(survivors) | set(demoted))
                    demoted = []
                admitted = joined + promoted
                new_active = sorted(
                    set(survivors) | set(joined) | set(promoted)
                )
        # Only actually-admitted announcements are consumed; a deferred
        # join's file is the retry ticket.
        for o in admitted:
            tx.consume_join(o)
            fail_counts.setdefault(o, 0)
            deferred_joins.discard(o)
        for o in joined:
            if o in recovered:
                fail_counts[o] = 0  # the budget recovered with the host
        spare_pool = sorted(
            (set(spare_pool) - set(promoted)) | set(demoted)
        )
        # Renumbering invalidates rank-keyed restore records; the
        # fired-fault ledger is KEPT — the member inheriting a fired
        # rank number must stay latched.
        tx.clear_gang_state(restore_records=True, fault_ledger=False)
        grown = len(new_active) > cur_world
        shrunk = bool(lost_s)
        if events is not None:
            events.gang_shrinks += 1 if shrunk else 0
            events.gang_grows += 1 if grown else 0
            events.spare_promotions += len(promoted)
            events.spare_demotions += len(demoted)
        if tel is not None:
            if shrunk:
                tel.registry.counter("gang_shrinks").inc()
                tel.tracer.instant(
                    "gang_shrink", from_world=cur_world,
                    to_world=len(survivors), lost=lost_s,
                )
            if grown:
                tel.registry.counter("gang_grows").inc()
                tel.tracer.instant(
                    "gang_grow", from_world=cur_world,
                    to_world=len(new_active), joined=joined,
                    promoted=promoted,
                )
            if promoted:
                tel.registry.counter("spare_promotions").inc(
                    len(promoted)
                )
            if demoted:
                tel.registry.counter("spare_demotions").inc(len(demoted))
            tel.registry.gauge("gang_world_size").set(len(new_active))
            tel.flush()
        if shrunk:
            tx.append_health_event(
                "shrink", attempt=attempt,
                from_world=cur_world, to_world=len(survivors),
                lost=lost_s, restore_step=elected,
            )
        if grown or promoted or demoted:
            tx.append_health_event(
                "grow" if grown else "replace",
                attempt=attempt, from_world=cur_world,
                to_world=len(new_active), joined=joined,
                promoted=promoted, demoted=demoted,
                restore_step=elected, seeded=seeded,
            )
        for o in promoted:
            tx.append_health_event("promote", attempt=attempt,
                                   rank=o, restore_step=elected)
        for o in demoted:
            tx.append_health_event("demote", attempt=attempt,
                                   rank=o, why="straggler replacement")
        rank0_print(
            f"[gang] {why}; world {cur_world} -> {len(new_active)}"
            + (f": rank(s) {lost_s} unrecoverable — shrinking to "
               f"{len(survivors)} survivor(s)" if lost_s else "")
            + (f" (joined {joined})" if joined else "")
            + (f" (promoted spare(s) {promoted})" if promoted else "")
            + (f" (demoted {demoted})" if demoted else "")
            + f"; restore point "
            f"{elected if elected is not None else '<none>'}"
            + (f", quarantined {len(quarantined)} newer checkpoint(s)"
               if quarantined else "")
            + (f"; restart {restarts}/{max_restarts}" if not planned_stop
               else " (planned boundary)")
        )
        active = new_active


def auto_resume(ckpt_dir, init_state, abstract_state=None, events=None):
    """(state, cursor, resumed_path) — the newest *valid* checkpoint
    under ``ckpt_dir`` restored against ``abstract_state`` (default: the
    fresh ``init_state``), or ``(init_state, 0, None)`` when none exists.
    Incomplete saves (crash/kill mid-write) and corrupt ones (manifest
    digest mismatch — quarantined with ``.invalid``) are skipped by
    ``latest_checkpoint``'s fallback chain — that chain IS the resume
    guarantee.  ``events``: optional FaultEvents; verification failures
    and fallbacks are counted there as well as in telemetry."""
    from distributed_machine_learning_tpu.train.checkpoint import (
        checkpoint_cursor,
        latest_checkpoint,
        restore_checkpoint,
    )

    latest = latest_checkpoint(ckpt_dir, events=events)
    if latest is None:
        return init_state, 0, None
    state = restore_checkpoint(
        latest, abstract_state=abstract_state or init_state,
        files_verified=True,  # the chain above just ran the file sweep
    )
    cursor = checkpoint_cursor(latest)
    if cursor is None:
        cursor = int(jax.device_get(state.step))
    return state, cursor, latest


def supervised_train(
    train_step,
    init_state,
    make_batches: Callable[[int], object],
    *,
    target_steps: int,
    ckpt_dir,
    save_every: int = 100,
    max_restarts: int = 3,
    events: FaultEvents | None = None,
    watchdog_timeout: float = 0.0,
    injector: FaultInjector | None = None,
    retry=None,
    place_batch=None,
    keep_last_n: int | None = None,
    abstract_state=None,
    stop=None,
    loss_print_every: int = 10**9,
):
    """Run ``train_step`` to ``target_steps`` applied updates, surviving
    faults: the full skip/retry/restart ladder in one call.

    ``make_batches(cursor)`` must yield the batch stream from absolute
    batch index ``cursor`` (deterministically — that seekability is what
    makes restart replay exact).  Checkpoints land every ``save_every``
    applied steps (cursor recorded), and the final state is saved at
    ``target_steps``.  ``target_steps`` counts APPLIED updates: a
    guard-skipped batch is consumed but retried with further data, so a
    faulted run finishes at the same step count as a clean one.

    ``retry``: a ``data/retry.RetryPolicy`` (None disables the retry
    layer); ``injector``: a ``runtime/faults.FaultInjector`` for chaos
    runs; ``stop``: zero-arg predicate (e.g. a ``PreemptionHandler``) —
    True checkpoints and returns early, cleanly.

    Returns the final state (a ``DynamicScaleState`` stays wrapped; its
    inner TrainState is what checkpoints hold, and the loss scale resets
    to its initial value after a restart — scale is ephemeral tuning
    state, not training progress).
    """
    from distributed_machine_learning_tpu.data.retry import retry_batches
    from distributed_machine_learning_tpu.train.checkpoint import (
        save_checkpoint,
    )
    from distributed_machine_learning_tpu.train.lm_step import (
        DynamicScaleState,
        unwrap_dynamic_scale,
        with_dynamic_scale,
    )
    from distributed_machine_learning_tpu.train.loop import train_epoch

    if target_steps < 1:
        raise ValueError(f"target_steps must be >= 1, got {target_steps}")
    if save_every < 1:
        raise ValueError(f"save_every must be >= 1, got {save_every}")
    events = events if events is not None else FaultEvents()
    mid_save = injector.mid_save_hook(events) if injector is not None else None
    post_save = (injector.post_save_hook(events) if injector is not None
                 else None)
    scaled = isinstance(init_state, DynamicScaleState)
    # Read the scaler's init values ONCE: the compiled step donates its
    # input state, so after attempt 0 these arrays may be dead buffers.
    init_scale = float(init_state.loss_scale) if scaled else None
    growth_interval = init_state.growth_interval if scaled else None

    def _rewrap(inner):
        if not scaled:
            return inner
        return with_dynamic_scale(
            inner, init_scale=init_scale, growth_interval=growth_interval
        )

    def _copy_state(tree):
        """Fresh buffers for every leaf — an attempt must never train on
        the caller's ``init_state`` directly: the jitted step donates its
        input, and a later restart that falls back to the fresh state
        (no complete checkpoint yet) would otherwise hand the step
        already-donated buffers."""
        from distributed_machine_learning_tpu.train.checkpoint import (
            fresh_buffers,
        )

        return fresh_buffers(tree)

    def _step_of(state) -> int:
        return int(jax.device_get(state.step))

    def attempt(restart_idx: int):
        inner, cursor, resumed = auto_resume(
            ckpt_dir,
            unwrap_dynamic_scale(init_state),
            abstract_state=unwrap_dynamic_scale(
                abstract_state if abstract_state is not None else init_state
            ),
            events=events,
        )
        if resumed is None:
            inner = _copy_state(inner)
        state = _rewrap(inner)
        if resumed:
            rank0_print(
                f"[supervisor] resumed from {resumed} "
                f"(step {_step_of(state)}, cursor {cursor})"
            )
        watchdog = (
            RaisingWatchdog(watchdog_timeout, events).start()
            if watchdog_timeout
            else None
        )
        cursor_box = {"v": cursor}

        def source(pos: int):
            base = make_batches(pos)

            def counted():
                for j, batch in enumerate(base):
                    cursor_box["v"] = pos + j + 1
                    yield batch

            it = counted()
            if injector is not None:
                it = injector.wrap_batches(it, events, start=pos)
            return it

        try:
            while _step_of(state) < target_steps:
                chunk_start = _step_of(state)
                cursor_start = cursor_box["v"]
                chunk_target = min(chunk_start + save_every, target_steps)
                if retry is not None:
                    batches = retry_batches(
                        source, retry, events, start=cursor_box["v"]
                    )
                else:
                    batches = source(cursor_box["v"])
                state, _ = train_epoch(
                    train_step,
                    state,
                    batches,
                    place_batch=place_batch,
                    max_iters=10**9,
                    loss_print_every=loss_print_every,
                    watchdog=watchdog,
                    events=events,
                    until_step=chunk_target,
                    stop=stop,
                )
                # Saves are not steps: suspend the watchdog so a slow
                # (but healthy) serialize can't be declared a stall.
                with (watchdog.suspend() if watchdog is not None
                      else contextlib.nullcontext()):
                    save_checkpoint(
                        ckpt_dir,
                        unwrap_dynamic_scale(state),
                        cursor=cursor_box["v"],
                        mid_save_hook=mid_save,
                        keep_last_n=keep_last_n,
                        post_save_hook=post_save,
                    )
                if stop is not None and stop():
                    events.preemptions += 1
                    rank0_print(
                        "[supervisor] stop requested; checkpointed at "
                        f"step {_step_of(state)} and exiting cleanly"
                    )
                    return state
                if (_step_of(state) == chunk_start
                        and cursor_box["v"] == cursor_start):
                    raise RuntimeError(
                        f"data stream exhausted at cursor "
                        f"{cursor_box['v']} with step {chunk_start} < "
                        f"target {target_steps}: make_batches must cover "
                        "the run (skipped batches consume extra data)"
                    )
            return state
        finally:
            if watchdog is not None:
                watchdog.stop()

    return run_attempts(attempt, max_restarts=max_restarts, events=events)
