"""Crash-safe streaming sinks — telemetry that survives the failures the
runtime recovers from.

The PR-1 self-healing runtime restarts through crashes, kills and
stalls; a metrics buffer held in memory (the old
``utils/profiling.MetricsLogger`` behavior) loses its entire history on
exactly those events.  The sink layer inverts that:

- **append-mode** JSONL, so a supervisor restart (same path, next
  attempt) appends to the survivor rows instead of truncating them;
- **flush + fsync every N rows**, so at most the last flush window is
  lost to a hard kill;
- **rank-0 gated**, the same multi-host discipline as every print in
  ``utils/logging.py``;
- a tolerant reader (:func:`read_jsonl`) that drops a torn final line —
  a process killed mid-``write(2)`` leaves exactly one partial row, and
  analysis must not die on the artifact of the crash it is analyzing.
"""

from __future__ import annotations

import json
import os
import threading


def _rank() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


class JsonlSink:
    """Append-mode JSONL writer, flushed (+fsynced) every ``flush_every``
    rows.  ``enabled=None`` gates on process 0 (the rank-0 contract);
    pass an explicit bool to override (tests, per-rank diagnostics)."""

    def __init__(self, path: str | os.PathLike, flush_every: int = 20,
                 fsync: bool = True, enabled: bool | None = None,
                 append: bool = True):
        if flush_every < 1:
            raise ValueError(f"flush_every must be >= 1, got {flush_every}")
        self.path = os.fspath(path)
        self.flush_every = flush_every
        self.fsync = fsync
        # append=False truncates at first open: for callers whose run is
        # NOT a continuation (a fresh --metrics-file run with no
        # --resume), where appending would silently mix unrelated runs.
        self.append = append
        # None = rank-0 gate, resolved LAZILY at the first write: sinks
        # are constructed before jax.distributed.initialize on multi-host
        # runs, where an eager process_index() would read 0 on every host
        # and every rank would write.
        self._enabled = enabled
        self._file = None
        self._pending = 0
        self.rows_written = 0
        # Writes/flushes can race (the fault mirror flushes from the
        # watchdog thread while the loop writes rows).
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        if self._enabled is None:
            self._enabled = _rank() == 0
        return self._enabled

    def _open(self):
        if self._file is None:
            parent = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(parent, exist_ok=True)
            if self.append:
                _truncate_torn_final_line(self.path)
                self._file = open(self.path, "a")
            else:
                self._file = open(self.path, "w")
        return self._file

    def touch(self) -> None:
        """Ensure the file exists (a reported path must exist even when
        zero rows were written — the MetricsLogger contract)."""
        if self.enabled:
            with self._lock:
                self._open()

    def write(self, row: dict) -> None:
        if not self.enabled:
            return
        line = json.dumps(row) + "\n"
        with self._lock:
            f = self._open()
            f.write(line)
            self.rows_written += 1
            self._pending += 1
            if self._pending >= self.flush_every:
                self._flush_locked()

    def _flush_locked(self) -> None:
        if self._file is None:
            return
        self._file.flush()
        if self.fsync:
            os.fsync(self._file.fileno())
        self._pending = 0

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._flush_locked()
                self._file.close()
                self._file = None

    def __enter__(self) -> "JsonlSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _truncate_torn_final_line(path: str) -> None:
    """Drop a partial (newline-less) final line before appending.

    A kill mid-``write(2)`` leaves one torn row at the tail.  Appending
    straight after it would weld the new attempt's first row onto the
    torn bytes — corrupting BOTH and moving the damage mid-file, where
    :func:`read_jsonl` rightly refuses to tolerate it.  Truncating back
    to the last newline sacrifices only the row the crash already
    destroyed.
    """
    try:
        with open(path, "rb+") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            if size == 0:
                return
            back = min(size, 1 << 20)
            f.seek(size - back)
            tail = f.read(back)
            if tail.endswith(b"\n"):
                return
            nl = tail.rfind(b"\n")
            f.truncate(size - back + nl + 1 if nl >= 0 else 0)
    except FileNotFoundError:
        return


def read_jsonl(path: str | os.PathLike, tolerate_truncation: bool = True
               ) -> list[dict]:
    """Parse a JSONL file back to rows.

    With ``tolerate_truncation`` (the default), an unparseable FINAL line
    is dropped — that is the signature of a kill mid-write, and the rows
    before it are exactly the crash-safe payload.  An unparseable line
    anywhere else is real corruption and raises.
    """
    rows = []
    with open(os.fspath(path)) as f:
        lines = f.read().splitlines()
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            rows.append(json.loads(line))
        except json.JSONDecodeError:
            if tolerate_truncation and i == len(lines) - 1:
                break
            raise
    return rows


def write_prometheus(path: str | os.PathLike, registry) -> None:
    """Atomic-rename write of ``registry.to_prometheus()`` — the
    node-exporter textfile-collector contract (a scraper must never see
    a half-written file)."""
    path = os.fspath(path)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(registry.to_prometheus())
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
