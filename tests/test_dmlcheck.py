"""dmlcheck (ISSUE 8): Layer-1 AST rules, the baseline workflow, the
CLI, and the Layer-2 program audits.

The tier-1 keystones here are ``test_package_is_clean`` (the whole repo
passes Layer 1 with zero non-baselined findings — the checker IS the
regression gate for every invariant it encodes) and
``test_layer1_is_fast_and_jax_free`` (the gate stays cheap enough to
run on every change: < 10 s, no jax import).  Compile-heavy Layer-2
sweeps over the real train steps live behind ``slow``; the SEEDED
violation programs (a donation XLA cannot alias, a forced sync
all-gather feeding the step output, a host callback in a step body) are
tiny compiles and stay in the default run — they are the acceptance
proof that each pass actually catches its bug class.
"""

import json
import os
import subprocess
import sys

import pytest

from distributed_machine_learning_tpu.analysis.ast_rules import (
    RULES,
    iter_source_files,
    run_layer1,
    run_source,
)
from distributed_machine_learning_tpu.analysis.findings import (
    BaselineError,
    Finding,
    apply_baseline,
    load_baseline,
)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
FIXTURES = os.path.join(HERE, "fixtures", "dmlcheck")
DMLCHECK = os.path.join(REPO, "tools", "dmlcheck.py")


# ---------------------------------------------------------------------------
# Per-rule fixtures: every rule has one firing and one clean case
# ---------------------------------------------------------------------------

def _fixture(name):
    with open(os.path.join(FIXTURES, name), encoding="utf-8") as f:
        return f.read()


@pytest.mark.parametrize("rule_id", sorted(RULES))
def test_rule_fires_on_its_fixture(rule_id):
    src = _fixture(f"{rule_id.lower()}_fires.py")
    hits = [f for f in run_source(src, "unused.py") if f.rule == rule_id]
    assert hits, f"{rule_id} did not fire on its firing fixture"
    assert all(f.line > 0 and f.snippet for f in hits)


@pytest.mark.parametrize("rule_id", sorted(RULES))
def test_rule_stays_quiet_on_clean_fixture(rule_id):
    src = _fixture(f"{rule_id.lower()}_clean.py")
    hits = [f for f in run_source(src, "unused.py") if f.rule == rule_id]
    assert not hits, (
        f"{rule_id} false-positived on its clean fixture: "
        + "; ".join(f"{f.line}: {f.snippet}" for f in hits)
    )


def test_fixture_set_is_complete():
    names = set(os.listdir(FIXTURES))
    for rule_id in RULES:
        assert f"{rule_id.lower()}_fires.py" in names
        assert f"{rule_id.lower()}_clean.py" in names


# ---------------------------------------------------------------------------
# The package itself is clean (the tier-1 gate)
# ---------------------------------------------------------------------------

def test_package_is_clean():
    """Zero non-baselined Layer-1 findings over the whole repo, zero
    stale baseline entries, every entry justified (load_baseline
    enforces the justification contract)."""
    findings = run_layer1(REPO)
    baseline = load_baseline(os.path.join(REPO, "dmlcheck_baseline.json"))
    assert baseline, "expected checked-in justified suppressions"
    new, suppressed, unused = apply_baseline(findings, baseline)
    assert not new, "non-baselined findings:\n" + "\n".join(
        f"  {f.rule} {f.location()}: {f.snippet or f.message}"
        for f in new)
    assert not unused, f"stale baseline entries (fixed? drop them): {unused}"
    assert suppressed, "baseline matched nothing — matching is broken"


def test_lock_discipline_rules_find_nothing_in_runtime():
    """ISSUE 15: DML013/DML014 (lock-ownership + check-then-act over
    the gang control plane) must find ZERO issues in the real
    ``runtime/transport.py`` / ``runtime/coordinator.py`` — genuine
    findings get fixed in-PR (the epoch fence moved inside the lock),
    never baselined."""
    findings = run_layer1(REPO, rules={"DML013", "DML014"})
    assert findings == [], [
        f"{f.rule} {f.location()}: {f.snippet or f.message}"
        for f in findings]


def test_scan_covers_the_tree_but_not_fixtures():
    files = list(iter_source_files(REPO))
    assert any(f.startswith("distributed_machine_learning_tpu/runtime/")
               for f in files)
    assert any(f.startswith("tools/") for f in files)
    assert any(f.startswith("tests/") for f in files)
    assert not any("fixtures" in f for f in files), (
        "fixtures are deliberate violations and must not be scanned")
    assert len(files) > 100


def test_layer1_is_fast_and_jax_free():
    """The whole Layer-1 scan completes in < 10 s in a fresh
    interpreter with NO jax import — ``-S`` skips this environment's
    sitecustomize (which pre-imports jax), so the assertion checks the
    analyzer itself, not the site config."""
    code = (
        "import sys, time; sys.path.insert(0, %r)\n"
        "t0 = time.monotonic()\n"
        "from distributed_machine_learning_tpu.analysis.ast_rules "
        "import run_layer1\n"
        "n = len(run_layer1(%r))\n"
        "print('%%.2f %%d %%s' %% (time.monotonic() - t0, n, "
        "'jax' in sys.modules))\n" % (REPO, REPO)
    )
    res = subprocess.run(
        [sys.executable, "-S", "-E", "-c", code],
        capture_output=True, text=True, timeout=120,
    )
    assert res.returncode == 0, res.stderr
    elapsed, n_findings, jax_loaded = res.stdout.split()
    assert jax_loaded == "False", "Layer 1 imported jax"
    assert float(elapsed) < 10.0, f"Layer 1 took {elapsed}s (budget 10s)"
    assert int(n_findings) >= 3  # the baselined deliberate sites


# ---------------------------------------------------------------------------
# Baseline machinery
# ---------------------------------------------------------------------------

def test_baseline_requires_justification(tmp_path):
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps({"suppressions": [
        {"rule": "DML001", "file": "x.py", "match": "y"},
    ]}))
    with pytest.raises(BaselineError, match="justification"):
        load_baseline(p)
    p.write_text(json.dumps({"suppressions": [
        {"rule": "DML001", "file": "x.py", "match": "y",
         "justification": "short"},
    ]}))
    with pytest.raises(BaselineError, match="justification"):
        load_baseline(p)
    p.write_text("{not json")
    with pytest.raises(BaselineError, match="JSON"):
        load_baseline(p)
    assert load_baseline(tmp_path / "absent.json") == []


def test_baseline_matching_is_line_number_free():
    f1 = Finding(rule="DML001", file="a.py", line=10,
                 message="m", snippet="time.time() - t0")
    f2 = Finding(rule="DML001", file="a.py", line=99,
                 message="m", snippet="time.time() - t0  # moved")
    entry = {"rule": "DML001", "file": "a.py",
             "match": "time.time() - t0",
             "justification": "x" * 20}
    new, suppressed, unused = apply_baseline([f1, f2], [entry])
    assert not new and len(suppressed) == 2 and not unused
    stale = {"rule": "DML002", "file": "b.py", "match": "nothing",
             "justification": "x" * 20}
    new, _, unused = apply_baseline([f1], [entry, stale])
    assert not new and unused == [stale]


# ---------------------------------------------------------------------------
# tools/dmlcheck.py CLI
# ---------------------------------------------------------------------------

def _run_tool(*args):
    return subprocess.run(
        [sys.executable, "-S", "-E", DMLCHECK, *args],
        capture_output=True, text=True, timeout=120,
    )


def test_tool_clean_run_and_json():
    res = _run_tool("--json")
    assert res.returncode == 0, res.stdout + res.stderr
    verdict = json.loads(res.stdout)
    assert verdict["clean"] is True
    assert verdict["errors"] == 0
    assert verdict["new"] == 0
    assert len(verdict["suppressed"]) >= 3
    assert verdict["baseline_unused"] == []
    assert "DML001" in verdict["rules_run"]
    # Per-layer / per-rule timing (ISSUE 15): budget regressions must
    # be visible in CI output.  Layers 2/3 did not run here → 0.
    timing = verdict["timing"]
    assert {"layer1_s", "layer2_s", "layer3_s", "rules"} <= set(timing)
    assert 0 < timing["layer1_s"] < 10.0
    assert timing["layer2_s"] == 0 and timing["layer3_s"] == 0
    for rule_id in ("DML001", "DML012", "DML013", "DML014"):
        assert rule_id in timing["rules"]
    res = _run_tool("--list-rules")
    assert res.returncode == 0
    for rule_id in RULES:
        assert rule_id in res.stdout


def _mini_repo(tmp_path, src):
    pkg = tmp_path / "distributed_machine_learning_tpu" / "runtime"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(src)
    return tmp_path


def test_tool_baseline_workflow(tmp_path):
    """finding → rc 1; unjustified suppression → rc 2; justified →
    rc 0; stale entry after the fix → rc 1 (baseline only shrinks)."""
    root = _mini_repo(tmp_path, _fixture("dml002_fires.py"))
    res = _run_tool(str(root))
    assert res.returncode == 1 and "DML002" in res.stdout

    baseline = root / "dmlcheck_baseline.json"
    entry = {"rule": "DML002",
             "file": "distributed_machine_learning_tpu/runtime/bad.py",
             "match": 'with open(ledger_path, "a") as f:',
             "justification": ""}
    entry2 = dict(entry, match='with open(gang_dir + "/gang_health.jsonl'
                               '", "a") as f:')
    baseline.write_text(json.dumps({"suppressions": [entry, entry2]}))
    res = _run_tool(str(root))
    assert res.returncode == 2 and "justification" in res.stderr

    for e in (entry, entry2):
        e["justification"] = ("fixture: deliberately unsynced ledger "
                              "writes for the workflow test")
    baseline.write_text(json.dumps({"suppressions": [entry, entry2]}))
    res = _run_tool(str(root), "--json")
    assert res.returncode == 0, res.stdout + res.stderr
    assert json.loads(res.stdout)["clean"] is True

    # "Fix" the violations: the now-stale suppressions must fail loud.
    (root / "distributed_machine_learning_tpu" / "runtime"
     / "bad.py").write_text(_fixture("dml002_clean.py"))
    res = _run_tool(str(root))
    assert res.returncode == 1 and "STALE" in res.stdout


def test_tool_write_baseline_skeleton(tmp_path):
    root = _mini_repo(tmp_path, _fixture("dml011_fires.py"))
    # dml011's virtual-path header does not apply to real files: the
    # file sits under runtime/, where DML011 is out of scope — use a
    # rule that applies everywhere in the package instead.
    (root / "distributed_machine_learning_tpu" / "runtime"
     / "bad.py").write_text(_fixture("dml009_fires.py"))
    res = _run_tool(str(root), "--write-baseline")
    assert res.returncode == 0
    skeleton = json.loads(res.stdout)["suppressions"]
    assert skeleton and all(e["justification"] == "" for e in skeleton)
    assert {e["rule"] for e in skeleton} == {"DML009"}


# ---------------------------------------------------------------------------
# Layer 2: seeded violations (the acceptance proof per pass)
# ---------------------------------------------------------------------------

def test_audit_donation_catches_unaliasable_donation():
    """Donate an f32 input to a program whose only output is bf16:
    XLA cannot alias (dtype width differs), the alias map stays empty,
    and the pass must flag the silent copy.  The well-formed twin
    (same-shape update) must alias and pass."""
    import warnings

    import jax
    import jax.numpy as jnp

    from distributed_machine_learning_tpu.analysis.program_audit import (
        audit_donation,
    )

    bad = jax.jit(lambda x: x.astype(jnp.bfloat16) * 2, donate_argnums=(0,))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # jax warns on unused donation
        hlo_bad = bad.lower(
            jax.ShapeDtypeStruct((128,), jnp.float32)).compile().as_text()
    findings = audit_donation(hlo_bad, [0], label="seeded")
    assert len(findings) == 1
    assert findings[0].rule == "DML101"
    assert "not aliased" in findings[0].message

    good = jax.jit(lambda x: x * 2 + 1, donate_argnums=(0,))
    hlo_good = good.lower(
        jax.ShapeDtypeStruct((128,), jnp.float32)).compile().as_text()
    assert audit_donation(hlo_good, [0], label="seeded") == []


def test_audit_flags_forced_critical_path_allgather(mesh8):
    """A sync all-gather whose result IS the step output — the exact
    2004.13336 anti-pattern — must be flagged, with the feeds-root
    attribution; a permute-only ring program must stay clean."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from distributed_machine_learning_tpu.analysis.program_audit import (
        audit_critical_path_collectives,
    )
    from distributed_machine_learning_tpu.bench.overlap_audit import (
        compile_ring_hlo,
    )
    from distributed_machine_learning_tpu.runtime.mesh import (
        shard_map_no_check,
    )

    def update(w_shard):
        new_shard = w_shard * 0.9
        return jax.lax.all_gather(new_shard, "batch", tiled=True)

    fn = jax.jit(shard_map_no_check(
        update, mesh=mesh8, in_specs=P("batch"), out_specs=P(None)))
    hlo = fn.lower(
        jax.ShapeDtypeStruct((8, 64), jnp.float32)).compile().as_text()
    findings = audit_critical_path_collectives(
        hlo, kinds=("all-gather",), label="seeded", severity="error")
    assert findings, "forced sync all-gather not flagged"
    assert any("feeds the step output directly" in f.message
               for f in findings)
    assert all(f.rule == "DML102" for f in findings)

    ring_hlo = compile_ring_hlo(mesh8, 512, bucket_bytes=8192)
    assert audit_critical_path_collectives(
        ring_hlo, kinds=("all-gather",), label="ring") == []


def test_audit_jaxpr_flags_host_callback():
    """jax.debug.print inside a step body is a per-step device→host
    round-trip; the jaxpr pass must see it through the jit wrapper."""
    import jax
    import jax.numpy as jnp

    from distributed_machine_learning_tpu.analysis.program_audit import (
        audit_step_host_callbacks,
    )

    @jax.jit
    def chatty_step(x):
        jax.debug.print("loss {}", x.sum())
        return x * 2

    x = jax.ShapeDtypeStruct((8,), jnp.float32)
    findings = audit_step_host_callbacks(chatty_step, x, label="seeded")
    assert findings and all(f.rule == "DML104" for f in findings)

    quiet = jax.jit(lambda x: x * 2)
    assert audit_step_host_callbacks(quiet, x, label="seeded") == []


# ---------------------------------------------------------------------------
# Layer 2: the real train steps (compile-heavy → slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_layer2_real_steps_have_no_errors(mesh8):
    """The full --layer2 sweep over the real programs: the ring step's
    donation is fully taken (every state leaf aliased) with no
    all-gather anywhere; the zero1 audit now gates the OVERLAP-AWARE
    build at ERROR severity (ISSUE 9 landed the 2004.13336 overlap
    item: the update program contains no all-gather at all and the
    consume program is a permute-only bucketed ring) and must be
    entirely clean — the pre-overlap advisory phase is over.  The
    per-layer FSDP audit (use-site gathers, none feeding ROOT) must be
    clean too."""
    from distributed_machine_learning_tpu.analysis.program_audit import (
        audit_fsdp_perlayer_step,
        audit_ring_step,
        audit_zero1_step,
    )

    ring = audit_ring_step(mesh8)
    assert ring == [], [f.message for f in ring]
    zero1 = audit_zero1_step(mesh8)
    assert zero1 == [], [f.message for f in zero1]
    pl = audit_fsdp_perlayer_step(mesh8)
    assert pl == [], [f.message for f in pl]
    # Round 11: the topology-aware hierarchical build holds the same
    # invariants — donation taken on state AND the EF residual,
    # permute-only, no host callbacks.
    from distributed_machine_learning_tpu.analysis.program_audit import (
        audit_hier_ring_step,
    )

    hier = audit_hier_ring_step(mesh8)
    assert hier == [], [f.message for f in hier]


def test_zero1_sync_baseline_still_flagged(mesh8):
    """The legacy sync zero1 build (overlap=False — kept for parity
    tests and the bench baseline) must STILL trip DML102 at error
    severity: the gate's teeth are demonstrated against the known-bad
    program, so a future change can't silently neuter the pass while
    the overlap build stays green."""
    import jax
    import jax.numpy as jnp

    from distributed_machine_learning_tpu.analysis.program_audit import (
        _vggtest_setup,
        audit_critical_path_collectives,
    )
    from distributed_machine_learning_tpu.parallel.zero1 import (
        make_zero1_train_step,
        shard_zero1_state,
    )

    model, init_state, _ = _vggtest_setup()
    z1, unravel, n_elems = shard_zero1_state(init_state(), mesh8)
    step = make_zero1_train_step(model, mesh8, unravel, n_elems,
                                 augment=False, overlap=False)
    zshape = jax.eval_shape(lambda: z1)
    x = jax.ShapeDtypeStruct((16, 32, 32, 3), jnp.float32)
    y = jax.ShapeDtypeStruct((16,), jnp.int32)
    hlo = step.lower(zshape, x, y).compile().as_text()
    findings = audit_critical_path_collectives(
        hlo, kinds=("all-gather",), label="zero1_sync")
    assert findings, "sync zero1 build no longer trips DML102"
    assert all(f.severity == "error" for f in findings), (
        "DML102 must default to error severity now that the overlap "
        "item landed")
    assert any("feeds the step output directly" in f.message
               for f in findings)


@pytest.mark.slow
def test_layer2_wire_accounting_all_schemes(mesh8):
    """Compiled collective-permute bytes == static ring_wire_bytes for
    every scheme the backend can carry; the bf16 widening on XLA:CPU is
    reported as an advisory, never an error (backend property)."""
    from distributed_machine_learning_tpu.analysis.program_audit import (
        audit_ring_wire_accounting,
    )

    findings, table = audit_ring_wire_accounting(
        mesh8, 4096, schemes=("none", "bf16", "int8", "topk"),
        bucket_bytes=8192)
    assert not [f for f in findings if f.severity == "error"], (
        [f.message for f in findings])
    for scheme in ("none", "int8", "topk"):
        assert table[scheme]["hlo_bytes"] == table[scheme]["static_bytes"]
    # int8 actually compresses in the artifact that runs.
    assert table["int8"]["hlo_bytes"] * 3 <= table["none"]["hlo_bytes"]
    # Round 11: the PER-AXIS accounting over the hierarchical build —
    # compiled inner/outer bytes equal the static split for every
    # scheme the backend carries, the bf16 widening stays a per-axis
    # advisory, and the exact build's inter-node bytes clear the
    # (1/inner + 5%) DynamiQ bound (asserted inside the audit).
    hfindings, htable = audit_ring_wire_accounting(
        mesh8, 4096, schemes=("none", "bf16", "int8", "topk"),
        bucket_bytes=8192, topology="2x4")
    assert not [f for f in hfindings if f.severity == "error"], (
        [f.message for f in hfindings])
    for scheme in ("none", "int8", "topk"):
        assert htable[scheme]["hlo_by_axis"] \
            == htable[scheme]["static_by_axis"]
    assert htable["int8"]["hlo_by_axis"]["outer"] * 3 \
        <= htable["none"]["hlo_by_axis"]["outer"]
