"""MoE transformer + expert parallelism: routing invariants and EP-sharded
step parity with the single-device step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.models.moe import MoEMLP, MoETransformerLM
from distributed_machine_learning_tpu.parallel.expert_parallel import (
    ep_spec_for,
    init_moe_state,
    make_ep_train_step,
    shard_ep_state,
)
from distributed_machine_learning_tpu.parallel.tensor_parallel import shard_tp_batch
from distributed_machine_learning_tpu.runtime.mesh import make_mesh

VOCAB, B, L = 64, 4, 16


def tiny_moe(**kw):
    kw.setdefault("n_experts", 4)
    return MoETransformerLM(
        vocab_size=VOCAB, d_model=32, n_layers=2, n_heads=4, **kw
    )


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(31)
    toks = rng.integers(0, VOCAB, (B, L + 1))
    return toks[:, :-1].astype(np.int32), toks[:, 1:].astype(np.int32)


def test_moe_mlp_capacity_and_shapes():
    mlp = MoEMLP(n_experts=2, d_ff=16, capacity_factor=1.0)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 8, 8)), jnp.float32)
    variables = mlp.init(jax.random.PRNGKey(0), x)
    y, mutated = mlp.apply(variables, x, mutable=["losses"])
    assert y.shape == x.shape
    aux = jax.tree_util.tree_leaves(mutated["losses"])[0]
    # Switch aux loss is >= 1 (perfect balance) for any routing.
    assert float(np.asarray(aux).squeeze()) >= 1.0 - 1e-5
    assert variables["params"]["w_in"].shape == (2, 8, 16)


def test_moe_overflow_tokens_pass_through_residual():
    """capacity_factor → tiny forces drops; dropped tokens' MLP output is 0."""
    mlp = MoEMLP(n_experts=2, d_ff=16, capacity_factor=0.01)  # capacity 1
    x = jnp.asarray(np.random.default_rng(1).standard_normal((1, 8, 8)), jnp.float32)
    variables = mlp.init(jax.random.PRNGKey(0), x)
    y, _ = mlp.apply(variables, x, mutable=["losses"])
    # At most 2 tokens (1 per expert) produce non-zero output.
    nonzero_rows = np.abs(np.asarray(y).reshape(8, 8)).sum(axis=-1) > 1e-7
    assert nonzero_rows.sum() <= 2


def test_ep_step_equals_single_device(batch):
    tokens, targets = batch
    model = tiny_moe()

    ref_state = init_moe_state(model)
    ref_step = make_ep_train_step(model, mesh=None)
    ref_state, ref_loss = ref_step(
        ref_state, jnp.asarray(tokens), jnp.asarray(targets)
    )

    mesh = make_mesh(8, axis_names=("batch", "expert"), axis_shape=(2, 4))
    state = shard_ep_state(init_moe_state(model), mesh)
    w_in = state.params["block_0"]["moe"]["w_in"]
    assert "expert" in tuple(w_in.sharding.spec)
    step = make_ep_train_step(model, mesh)
    x, y = shard_tp_batch(mesh, tokens, targets)
    state, loss = step(state, x, y)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(state.params),
        jax.tree_util.tree_leaves(ref_state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-5)


def test_moe_loss_decreases(batch):
    tokens, targets = batch
    model = tiny_moe()
    state = init_moe_state(model)
    step = make_ep_train_step(model, mesh=None)
    x, y = jnp.asarray(tokens), jnp.asarray(targets)
    state, first = step(state, x, y)
    for _ in range(5):
        state, loss = step(state, x, y)
    assert float(loss) < float(first)


def test_ep_guards():
    model = tiny_moe(n_experts=3)
    mesh = make_mesh(4, axis_names=("batch", "expert"), axis_shape=(2, 2))
    with pytest.raises(ValueError, match="divisible"):
        make_ep_train_step(model, mesh)


def test_ep_spec_rules():
    assert ep_spec_for(("block_0", "moe", "w_in"), 3)[0] == "expert"
    assert ep_spec_for(("block_0", "moe", "b_out"), 2)[0] == "expert"
    assert ep_spec_for(("block_0", "moe", "router", "kernel"), 2) == (None, None)
    assert ep_spec_for(("block_0", "attn", "qkv", "kernel"), 4)[0] is None


def test_grouped_impl_matches_einsum_when_nothing_drops():
    """With capacity ample enough that the einsum path drops nothing, the
    dropless grouped (ragged_dot) path computes the same mixture."""
    x = jnp.asarray(
        np.random.default_rng(2).standard_normal((2, 8, 16)), jnp.float32
    )
    ein = MoEMLP(n_experts=4, d_ff=32, capacity_factor=8.0)
    grp = MoEMLP(n_experts=4, d_ff=32, capacity_factor=8.0, moe_impl="grouped")
    variables = ein.init(jax.random.PRNGKey(0), x)
    ye, _ = ein.apply(variables, x, mutable=["losses"])
    yg, _ = grp.apply(variables, x, mutable=["losses"])
    np.testing.assert_allclose(
        np.asarray(yg), np.asarray(ye), rtol=2e-3, atol=2e-3
    )

    # Gradients agree too (routing is non-differentiable on both paths;
    # token/weight grads flow through ragged_dot's VJP).
    def loss(params, mod):
        y, _ = mod.apply({"params": params}, x, mutable=["losses"])
        return jnp.sum(y * y)

    ge = jax.grad(loss)(variables["params"], ein)
    gg = jax.grad(loss)(variables["params"], grp)
    for a, b in zip(
        jax.tree_util.tree_leaves(ge), jax.tree_util.tree_leaves(gg)
    ):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=2e-2, atol=2e-2
        )


def test_grouped_impl_is_dropless():
    """Starved capacity drops tokens on the einsum path; the grouped path
    processes every token regardless of capacity_factor."""
    x = jnp.asarray(
        np.random.default_rng(3).standard_normal((1, 16, 8)), jnp.float32
    )
    ein = MoEMLP(n_experts=2, d_ff=16, capacity_factor=0.01)
    grp = MoEMLP(n_experts=2, d_ff=16, capacity_factor=0.01, moe_impl="grouped")
    variables = ein.init(jax.random.PRNGKey(0), x)
    ye, _ = ein.apply(variables, x, mutable=["losses"])
    yg, _ = grp.apply(variables, x, mutable=["losses"])
    ein_rows = np.abs(np.asarray(ye).reshape(16, 8)).sum(-1) > 1e-7
    grp_rows = np.abs(np.asarray(yg).reshape(16, 8)).sum(-1) > 1e-7
    assert ein_rows.sum() <= 2  # capacity 1 per expert: nearly all dropped
    assert grp_rows.all()  # dropless: every token reaches its expert

    # And capacity_factor is a no-op for the grouped path.
    grp_hi = MoEMLP(n_experts=2, d_ff=16, capacity_factor=4.0, moe_impl="grouped")
    yh, _ = grp_hi.apply(variables, x, mutable=["losses"])
    np.testing.assert_array_equal(np.asarray(yg), np.asarray(yh))


def test_grouped_lm_trains_and_ep_mesh_rejects_it(batch):
    tokens, targets = batch
    model = tiny_moe(moe_impl="grouped")
    state = init_moe_state(model)
    step = make_ep_train_step(model, mesh=None)
    x, y = jnp.asarray(tokens), jnp.asarray(targets)
    state, first = step(state, x, y)
    for _ in range(5):
        state, loss = step(state, x, y)
    assert float(loss) < float(first)

    mesh = make_mesh(8, axis_names=("batch", "expert"), axis_shape=(2, 4))
    with pytest.raises(ValueError, match="einsum"):
        make_ep_train_step(model, mesh)


def test_moe_flash_attention_matches_dense(batch):
    """attn_impl='flash' in the MoE blocks (sequence-local kernel, so it
    composes with expert parallelism) == the dense MoE forward."""
    x, _ = batch
    dense = tiny_moe()
    flash = tiny_moe(attn_impl="flash")
    params = dense.init(jax.random.PRNGKey(0), jnp.asarray(x))["params"]
    ref = dense.apply({"params": params}, jnp.asarray(x))
    out = flash.apply({"params": params}, jnp.asarray(x))
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
    )
    with pytest.raises(NotImplementedError, match="sequence-local"):
        tiny_moe(attn_impl="ring").apply({"params": params}, jnp.asarray(x))


def test_ep_step_flash_matches_dense(batch):
    """flash attention composes with the jit-sharded EP step: one step on
    the (batch × expert) mesh matches the dense-attention EP step."""
    from distributed_machine_learning_tpu.parallel.expert_parallel import (
        init_moe_state,
        make_ep_train_step,
        shard_ep_state,
    )
    from distributed_machine_learning_tpu.parallel.tensor_parallel import (
        shard_tp_batch,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh

    mesh = make_mesh(8, ("batch", "expert"), (2, 4))
    x, y = batch

    def run(attn):
        model = tiny_moe(attn_impl=attn)
        state = shard_ep_state(init_moe_state(model), mesh)
        sx, sy = shard_tp_batch(mesh, x, y)
        state, loss = make_ep_train_step(model, mesh)(state, sx, sy)
        return float(loss), state

    loss_f, state_f = run("flash")
    loss_d, state_d = run("dense")
    np.testing.assert_allclose(loss_f, loss_d, rtol=2e-5)
    for a, b in zip(jax.tree_util.tree_leaves(state_f.params),
                    jax.tree_util.tree_leaves(state_d.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_ep_grouped_step_equals_single_device(batch):
    """The manual shard_map EP step (explicit all_to_all + local
    ragged_dot) takes the same update as the single-device dropless
    grouped step."""
    from distributed_machine_learning_tpu.parallel.expert_parallel import (
        make_ep_grouped_train_step,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    tokens, targets = batch
    model = tiny_moe(moe_impl="grouped")

    ref_state = init_moe_state(model)
    ref_step = make_ep_train_step(model, mesh=None)
    ref_state, ref_loss = ref_step(
        ref_state, jnp.asarray(tokens), jnp.asarray(targets)
    )

    mesh = make_mesh(4, axis_names=("batch", "expert"), axis_shape=(2, 2))
    state = shard_ep_state(init_moe_state(model), mesh)
    step = make_ep_grouped_train_step(model, mesh)
    sharding = NamedSharding(mesh, P(("batch", "expert"), None))
    x = jax.device_put(jnp.asarray(tokens), sharding)
    y = jax.device_put(jnp.asarray(targets), sharding)
    state, loss = step(state, x, y)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(state.params),
        jax.tree_util.tree_leaves(ref_state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-5
        )


def test_ep_grouped_step_equals_einsum_ep_at_ample_capacity(batch):
    """At a capacity factor large enough that the einsum path drops
    nothing, the dropless grouped-EP step and the GSPMD einsum-EP step
    take the same update from the same state (VERDICT r03 item 2)."""
    from distributed_machine_learning_tpu.parallel.expert_parallel import (
        make_ep_grouped_train_step,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    tokens, targets = batch
    mesh = make_mesh(4, axis_names=("batch", "expert"), axis_shape=(2, 2))

    ein = tiny_moe(capacity_factor=8.0)
    ein_state = shard_ep_state(init_moe_state(ein), mesh)
    sx, sy = shard_tp_batch(mesh, tokens, targets)
    ein_state, ein_loss = make_ep_train_step(ein, mesh)(ein_state, sx, sy)

    grp = tiny_moe(capacity_factor=8.0, moe_impl="grouped")
    grp_state = shard_ep_state(init_moe_state(grp), mesh)
    sharding = NamedSharding(mesh, P(("batch", "expert"), None))
    gx = jax.device_put(jnp.asarray(tokens), sharding)
    gy = jax.device_put(jnp.asarray(targets), sharding)
    grp_state, grp_loss = make_ep_grouped_train_step(grp, mesh)(
        grp_state, gx, gy
    )

    np.testing.assert_allclose(float(grp_loss), float(ein_loss), rtol=2e-4)
    for a, b in zip(
        jax.tree_util.tree_leaves(grp_state.params),
        jax.tree_util.tree_leaves(ein_state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4
        )


def test_ep_grouped_step_guards():
    from distributed_machine_learning_tpu.parallel.expert_parallel import (
        make_ep_grouped_train_step,
    )

    mesh = make_mesh(4, axis_names=("batch", "expert"), axis_shape=(2, 2))
    with pytest.raises(ValueError, match="grouped"):
        make_ep_grouped_train_step(tiny_moe(), mesh)  # einsum model
    with pytest.raises(ValueError, match="divisible"):
        make_ep_grouped_train_step(
            tiny_moe(n_experts=3, moe_impl="grouped"), mesh
        )


@pytest.mark.parametrize("attn", ["ring", "ulysses"])
def test_moe_context_parallel_step_equals_single_device(batch, attn):
    """MoE × context parallelism (VERDICT r03 item 3): experts on one
    mesh axis, sequence on another — the ring/ulysses-sharded MoE step
    equals the single-device dropless step."""
    from distributed_machine_learning_tpu.parallel.expert_parallel import (
        make_ep_grouped_train_step,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    tokens, targets = batch
    ref_model = tiny_moe(moe_impl="grouped")
    ref_state = init_moe_state(ref_model)
    ref_state, ref_loss = make_ep_train_step(ref_model, mesh=None)(
        ref_state, jnp.asarray(tokens), jnp.asarray(targets)
    )

    model = tiny_moe(moe_impl="grouped", attn_impl=attn)
    mesh = make_mesh(
        8, axis_names=("batch", "expert", "seq"), axis_shape=(2, 2, 2)
    )
    state = shard_ep_state(init_moe_state(model), mesh)
    step = make_ep_grouped_train_step(model, mesh, seq_axis="seq")
    sharding = NamedSharding(mesh, P(("batch", "expert"), "seq"))
    x = jax.device_put(jnp.asarray(tokens), sharding)
    y = jax.device_put(jnp.asarray(targets), sharding)
    state, loss = step(state, x, y)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(state.params),
        jax.tree_util.tree_leaves(ref_state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5
        )


def test_moe_cp_guards():
    from distributed_machine_learning_tpu.parallel.expert_parallel import (
        make_ep_grouped_train_step,
    )

    mesh = make_mesh(
        8, axis_names=("batch", "expert", "seq"), axis_shape=(2, 2, 2)
    )
    # ring without seq_axis → must name the CP layout.
    with pytest.raises(ValueError, match="seq_axis"):
        make_ep_grouped_train_step(
            tiny_moe(moe_impl="grouped", attn_impl="ring"), mesh
        )
    # dense attention cannot shard the sequence.
    with pytest.raises(ValueError, match="cannot shard"):
        make_ep_grouped_train_step(
            tiny_moe(moe_impl="grouped"), mesh, seq_axis="seq"
        )


def test_moe_remat_matches_no_remat(batch):
    """MoE selective remat (LN2 + expert MLP checkpointed) is a pure
    memory trade: same loss, same grads."""
    tokens, targets = batch
    x = jnp.asarray(tokens)
    base = tiny_moe(moe_impl="grouped")
    rem = tiny_moe(moe_impl="grouped", remat=True)
    params = base.init(jax.random.PRNGKey(2), x)["params"]

    def loss_fn(model):
        def f(p):
            logits, _ = model.apply(
                {"params": p}, x, train=True, mutable=["losses"]
            )
            return jnp.sum(logits * logits) * 1e-4

        return jax.jit(jax.value_and_grad(f))

    l0, g0 = loss_fn(base)(params)
    l1, g1 = loss_fn(rem)(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(g0), jax.tree_util.tree_leaves(g1)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6)


def test_moe_gqa_ep_step_runs(batch):
    """GQA (n_kv_heads < n_heads) wires through the MoE blocks and the
    EP-grouped step."""
    from distributed_machine_learning_tpu.parallel.expert_parallel import (
        make_ep_grouped_train_step,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    tokens, targets = batch
    model = tiny_moe(moe_impl="grouped", n_kv_heads=2, remat=True)
    mesh = make_mesh(4, axis_names=("batch", "expert"), axis_shape=(2, 2))
    state = shard_ep_state(init_moe_state(model), mesh)
    step = make_ep_grouped_train_step(model, mesh)
    sharding = NamedSharding(mesh, P(("batch", "expert"), None))
    x = jax.device_put(jnp.asarray(tokens), sharding)
    y = jax.device_put(jnp.asarray(targets), sharding)
    state, loss = step(state, x, y)
    assert np.isfinite(float(loss))
    # GQA param structure: separate q and fused kv with 2 heads.
    kv = state.params["block_0"]["attn"]["kv"]["kernel"]
    assert kv.shape[2] == 2


@pytest.mark.parametrize("impl", ["einsum", "grouped"])
@pytest.mark.parametrize("seed", [1, 4, 17])
def test_moe_cached_decode_matches_teacher_forced(batch, impl, seed):
    """MoE serving: KV-cached greedy generation equals the argmax of the
    teacher-forced forward at every step — the cache, RoPE offsets,
    position counter, and per-token routing all line up (the dense LM's
    strongest cache invariant, MoE flavor).

    Serving routes DROPLESS (MoEMLP.dropless — a decode step's N is
    B·1, so Switch capacity would drop on any expert collision), so the
    teacher-forced reference must be dropless too: ample
    capacity_factor makes the einsum forward drop-free.  Multiple seeds
    guard against expert-collision luck (the bug a single lucky seed
    hid in review)."""
    from distributed_machine_learning_tpu.inference.generate import generate

    model = tiny_moe(moe_impl=impl, capacity_factor=8.0)
    params = model.init(
        jax.random.PRNGKey(4), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    rng = np.random.default_rng(seed)
    prompt = jnp.asarray(rng.integers(0, VOCAB, (2, 5)), jnp.int32)
    out = generate(model, params, prompt, max_new_tokens=6)
    assert out.shape == (2, 11)
    full_logits = model.apply({"params": params}, out)
    want = np.argmax(np.asarray(full_logits[:, 4:-1]), axis=-1)
    np.testing.assert_array_equal(np.asarray(out[:, 5:]), want)


def test_moe_quant_requires_decode():
    model = tiny_moe(weight_quant="int8")
    with pytest.raises(ValueError, match="decode"):
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))


def _dequant_moe_tree(params, qparams):
    """Quantized MoE tree → full-precision tree (the serving reference):
    dense modules un-flatten w_q·scale back into ``kernel``; expert
    modules rebuild [E, D_in, D_out] ``w_in``/``w_out`` from the
    per-expert scales; the router passed through untouched."""

    def walk(ref, node):
        if isinstance(ref, dict):
            if "w_q" in node:
                w = node["w_q"].astype(jnp.float32) * node["scale"][None, :]
                out = {"kernel": w.reshape(ref["kernel"].shape)}
                if "bias" in node:
                    out["bias"] = node["bias"]
                return out
            if "w_in_q" in node:
                return {
                    "router": node["router"],
                    "w_in": node["w_in_q"].astype(jnp.float32)
                    * node["w_in_scale"][:, None, :],
                    "w_out": node["w_out_q"].astype(jnp.float32)
                    * node["w_out_scale"][:, None, :],
                    "b_in": node["b_in"], "b_out": node["b_out"],
                }
            return {k: walk(ref[k], node[k]) for k in ref}
        return node

    return walk(params, qparams)


@pytest.mark.parametrize("seed", [1, 4])
def test_moe_quantized_generate_token_exact_vs_dequant(seed):
    """int8 MoE serving (VERDICT r4 item 2): expert weights quantized
    per-expert per-channel and read through the scale-folded ragged_dot,
    attention/lm_head through QuantDenseGeneral — the served stream must
    equal the unquantized model serving the DEQUANTIZED weights (same
    numbers, two read paths)."""
    from distributed_machine_learning_tpu.inference.generate import (
        generate,
        make_generate_fn,
    )
    from distributed_machine_learning_tpu.ops.quant import quantize_lm_params

    model = tiny_moe()
    params = model.init(
        jax.random.PRNGKey(4), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    qparams = quantize_lm_params(params)
    moe = qparams["block_0"]["moe"]
    assert moe["w_in_q"].dtype == jnp.int8
    assert moe["w_in_scale"].shape == (4, 128)  # [E, d_ff]
    assert moe["router"]["kernel"].dtype == jnp.float32  # router stays f32

    rng = np.random.default_rng(seed)
    prompt = jnp.asarray(rng.integers(0, VOCAB, (2, 5)), jnp.int32)
    ref = generate(model, _dequant_moe_tree(params, qparams), prompt, 8)
    fn = make_generate_fn(model, 8, quantize="int8")
    out = fn(qparams, prompt, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


@pytest.mark.parametrize("batch_rows", [1, 3])
def test_moe_speculative_greedy_token_exact(batch_rows):
    """Speculative decoding with an MoE TARGET and a dense draft
    (VERDICT r4 item 2): the served stream equals vanilla MoE greedy —
    including batched rows on per-row frontiers."""
    from distributed_machine_learning_tpu.inference.generate import (
        make_generate_fn,
    )
    from distributed_machine_learning_tpu.inference.speculative import (
        make_speculative_generate_fn,
    )
    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )
    from distributed_machine_learning_tpu.train.lm_step import init_lm_state

    target = tiny_moe()
    tparams = target.init(
        jax.random.PRNGKey(4), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    draft = TransformerLM(vocab_size=VOCAB, d_model=16, n_layers=1,
                          n_heads=2)
    dparams = init_lm_state(draft, seed=7).params
    rng = np.random.default_rng(3)
    prompt = jnp.asarray(rng.integers(0, VOCAB, (batch_rows, 5)), jnp.int32)
    ref = make_generate_fn(target, 8)(tparams, prompt, jax.random.PRNGKey(0))
    fn = make_speculative_generate_fn(target, draft, 8, gamma=3)
    out = fn(tparams, dparams, prompt, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_moe_speculative_with_int8_target():
    """--moe --quant --spec-gamma all at once: the int8 MoE target's
    speculative stream equals its own vanilla int8 stream."""
    from distributed_machine_learning_tpu.inference.generate import (
        make_generate_fn,
    )
    from distributed_machine_learning_tpu.inference.speculative import (
        make_speculative_generate_fn,
    )
    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )
    from distributed_machine_learning_tpu.ops.quant import quantize_lm_params
    from distributed_machine_learning_tpu.train.lm_step import init_lm_state

    target = tiny_moe()
    tparams = target.init(
        jax.random.PRNGKey(4), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    qt = quantize_lm_params(tparams)
    draft = TransformerLM(vocab_size=VOCAB, d_model=16, n_layers=1,
                          n_heads=2)
    dparams = init_lm_state(draft, seed=7).params
    rng = np.random.default_rng(9)
    prompt = jnp.asarray(rng.integers(0, VOCAB, (1, 6)), jnp.int32)
    ref = make_generate_fn(target, 8, quantize="int8")(
        qt, prompt, jax.random.PRNGKey(0)
    )
    fn = make_speculative_generate_fn(target, draft, 8, gamma=3,
                                      quantize="int8")
    out = fn(qt, dparams, prompt, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_ep_bounded_slots_matches_dropless_when_ample(batch):
    """slots_per_owner = N_local (ample) must take EXACTLY the dropless
    default's step: the trash-slot machinery is inert when nothing
    overflows (ADVICE r4 — capacity-bounded EP dispatch)."""
    from distributed_machine_learning_tpu.parallel.expert_parallel import (
        make_ep_grouped_train_step,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    tokens, targets = batch
    mesh = make_mesh(4, axis_names=("batch", "expert"), axis_shape=(2, 2))
    sharding = NamedSharding(mesh, P(("batch", "expert"), None))
    x = jax.device_put(jnp.asarray(tokens), sharding)
    y = jax.device_put(jnp.asarray(targets), sharding)
    n_local = tokens.shape[0] * tokens.shape[1] // 4

    model = tiny_moe(moe_impl="grouped")
    losses = {}
    for slots in (None, n_local):
        state = shard_ep_state(init_moe_state(model), mesh)
        step = make_ep_grouped_train_step(model, mesh,
                                          slots_per_owner=slots)
        state, loss = step(state, x, y)
        losses[slots] = (float(loss), state.params)
    np.testing.assert_allclose(losses[None][0], losses[n_local][0],
                               rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(losses[None][1]),
                    jax.tree_util.tree_leaves(losses[n_local][1])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_ep_bounded_slots_overflow_drops_with_zero_grads():
    """Adversarial routing into a tiny slot bound: overflowing rows get
    ZERO expert output (residual pass-through) and ZERO gradients;
    surviving rows are exact vs the dropless path.  Exercised at the op
    level under shard_map so the trash-slot scatter/gather VJPs are the
    thing being tested."""
    from distributed_machine_learning_tpu.ops.grouped import (
        grouped_expert_mlp_ep,
    )
    from distributed_machine_learning_tpu.runtime.mesh import (
        make_mesh as _mk,
        shard_map_no_check,
    )
    from jax.sharding import PartitionSpec as P

    ep, n_local, d, dff, e_global = 2, 8, 4, 8, 4
    mesh = _mk(2, axis_names=("expert",))
    rng = np.random.default_rng(5)
    tokens = jnp.asarray(rng.standard_normal((ep * n_local, d)), jnp.float32)
    # ALL tokens route to expert 0 (owner device 0) — with S=2 slots,
    # each sender keeps 2 rows and drops the rest.
    eidx = jnp.zeros((ep * n_local,), jnp.int32)
    w_in = jnp.asarray(rng.standard_normal((e_global // ep, d, dff)),
                       jnp.float32)
    b_in = jnp.zeros((e_global // ep, dff))
    w_out = jnp.asarray(rng.standard_normal((e_global // ep, dff, d)),
                        jnp.float32)
    b_out = jnp.zeros((e_global // ep, d))

    def run(slots):
        def f(t, ei, wi, bi, wo, bo):
            out = grouped_expert_mlp_ep(
                t, ei, wi, bi, wo, bo, expert_axis="expert",
                n_experts_global=e_global, slots_per_owner=slots,
                return_dropped=slots is not None,
            )
            if slots is None:
                return out
            y, nd = out
            return y, nd[None]  # rank >= 1 for the out_specs

        spec = (P("expert"),) * 6
        out_spec = (P("expert"), P("expert")) if slots is not None \
            else P("expert")
        return jax.jit(shard_map_no_check(
            f, mesh=mesh, in_specs=spec, out_specs=out_spec,
        ))(tokens, eidx, jnp.concatenate([w_in, w_in]),
           jnp.concatenate([b_in, b_in]),
           jnp.concatenate([w_out, w_out]),
           jnp.concatenate([b_out, b_out]))

    y_full = run(None)
    y_bounded, dropped = run(2)
    dropped = np.asarray(dropped)
    # Each of the 2 senders dropped all but 2 of its 8 rows.
    assert dropped.sum() == 2 * (n_local - 2), dropped
    yb = np.asarray(y_bounded)
    yf = np.asarray(y_full)
    # Surviving rows (within-owner rank < 2 per sender): exact; dropped
    # rows: exactly zero.
    for s in range(ep):
        lo = s * n_local
        np.testing.assert_allclose(yb[lo:lo + 2], yf[lo:lo + 2],
                                   rtol=1e-6)
        np.testing.assert_array_equal(yb[lo + 2:lo + n_local], 0.0)

    # Gradients: dropped rows' token grads are exactly zero; surviving
    # rows' match the dropless path.
    def loss(slots):
        def f(t, ei, wi, bi, wo, bo):
            out = grouped_expert_mlp_ep(
                t, ei, wi, bi, wo, bo, expert_axis="expert",
                n_experts_global=e_global, slots_per_owner=slots,
            )
            return jnp.sum(out * out)

        spec = (P("expert"),) * 6
        fn = shard_map_no_check(
            lambda *a: jax.lax.psum(f(*a), "expert"), mesh=mesh,
            in_specs=spec, out_specs=P(),
        )
        return jax.jit(jax.grad(fn))(
            tokens, eidx, jnp.concatenate([w_in, w_in]),
            jnp.concatenate([b_in, b_in]),
            jnp.concatenate([w_out, w_out]),
            jnp.concatenate([b_out, b_out]))

    g_full = np.asarray(loss(None))
    g_bounded = np.asarray(loss(2))
    for s in range(ep):
        lo = s * n_local
        np.testing.assert_allclose(g_bounded[lo:lo + 2],
                                   g_full[lo:lo + 2], rtol=1e-5)
        np.testing.assert_array_equal(g_bounded[lo + 2:lo + n_local], 0.0)


def test_ep_bounded_slots_guards():
    from distributed_machine_learning_tpu.ops.grouped import (
        grouped_expert_mlp_ep,
    )
    from distributed_machine_learning_tpu.runtime.mesh import (
        make_mesh as _mk,
        shard_map_no_check,
    )
    from jax.sharding import PartitionSpec as P

    mesh = _mk(2, axis_names=("expert",))

    def f(t, ei, wi, bi, wo, bo):
        return grouped_expert_mlp_ep(
            t, ei, wi, bi, wo, bo, expert_axis="expert",
            n_experts_global=4, slots_per_owner=99,
        )

    with pytest.raises(ValueError, match="slots_per_owner"):
        jax.jit(shard_map_no_check(
            f, mesh=mesh, in_specs=(P("expert"),) * 6,
            out_specs=P("expert"),
        ))(jnp.zeros((8, 4)), jnp.zeros((8,), jnp.int32),
           jnp.zeros((4, 4, 8)), jnp.zeros((4, 8)),
           jnp.zeros((4, 8, 4)), jnp.zeros((4, 4)))


@pytest.mark.parametrize("quant", [None, "int8"], ids=["bf", "int8"])
def test_moe_tp_decode_token_exact(quant):
    """MoE x TP decode: every expert's d_ff column/row-splits over the
    model axis inside the Megatron decode shard_map (router replicated,
    b_out pre-divided, per-expert psum) — token-exact vs single-device
    MoE decode, bf16 and int8 expert weights."""
    from distributed_machine_learning_tpu.inference.generate import (
        make_generate_fn,
        make_tp_generate_fn,
    )
    from distributed_machine_learning_tpu.ops.quant import quantize_lm_params
    from distributed_machine_learning_tpu.parallel.tensor_parallel import (
        tp_decode_params,
    )

    mesh = make_mesh(2, axis_names=("model",))
    model = tiny_moe(n_kv_heads=2)
    params = model.init(
        jax.random.PRNGKey(4), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    if quant == "int8":
        params = quantize_lm_params(params)
    rng = np.random.default_rng(3)
    prompt = jnp.asarray(rng.integers(0, VOCAB, (2, 5)), jnp.int32)
    ref = make_generate_fn(model, 8, quantize=quant)(
        params, prompt, jax.random.PRNGKey(0)
    )
    fn = make_tp_generate_fn(model, 8, mesh, quantize=quant)
    out = fn(tp_decode_params(params, 2), prompt, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_moe_tp_speculative_token_exact():
    """The full stack: MoE target x TP x batched speculation — the
    sharded speculative stream equals single-device MoE speculation."""
    from distributed_machine_learning_tpu.inference.speculative import (
        make_speculative_generate_fn,
        make_tp_speculative_generate_fn,
    )
    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )
    from distributed_machine_learning_tpu.parallel.tensor_parallel import (
        tp_decode_params,
    )
    from distributed_machine_learning_tpu.train.lm_step import init_lm_state

    mesh = make_mesh(2, axis_names=("model",))
    target = tiny_moe()
    tparams = target.init(
        jax.random.PRNGKey(4), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    draft = TransformerLM(vocab_size=VOCAB, d_model=16, n_layers=1,
                          n_heads=2)
    dparams = init_lm_state(draft, seed=7).params
    rng = np.random.default_rng(9)
    prompt = jnp.asarray(rng.integers(0, VOCAB, (2, 5)), jnp.int32)
    ref = make_speculative_generate_fn(target, draft, 8, gamma=3)(
        tparams, dparams, prompt, jax.random.PRNGKey(0)
    )
    fn = make_tp_speculative_generate_fn(target, draft, 8, mesh, gamma=3)
    out = fn(tp_decode_params(tparams, 2), dparams, prompt,
             jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
