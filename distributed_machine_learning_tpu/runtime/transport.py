"""Pluggable gang transport — the control-plane seam (ISSUE 12).

Every coordination channel the gang stack uses — heartbeats, the
first-writer-wins abort latch, join announcements, restore-point
records, the health/fault/consumption ledgers, and the between-attempt
state clear — historically lived as files in a shared ``gang_dir``
(``runtime/coordinator.py``).  Correct, but it hard-capped gang tests
at worlds ≤ 5 (one OS process per rank), assumed a shared filesystem,
and left the resilience stack unproven at the worlds the papers it
reproduces run at (arxiv 1811.05233's hundreds of replicas).

:class:`GangTransport` extracts that channel set as an interface with
three backends:

- :class:`FileTransport` — today's behavior, delegated verbatim to the
  ``runtime/coordinator.py`` file functions: the on-disk format is
  byte-compatible with every earlier PR, the fsync discipline on the
  ledgers is preserved (dmlcheck DML002), and a coordinator built
  without an explicit transport gets exactly this.
- :class:`InProcTransport` over an :class:`InProcHub` — threads +
  in-memory channels: no shared filesystem, no subprocess spawn.  This
  is what makes 64-128-rank supervised gangs run in seconds and
  unlocks the chaos *campaigns* (``runtime/inproc_worker.py``,
  ``tests/test_chaos_campaign.py``).  Durable ledgers (health, faults,
  consumption) can MIRROR to a ``mirror_dir`` so post-mortem tools
  (``tools/gang_status.py``) read a dead campaign exactly like a file
  gang.
- :class:`TcpTransport` against a :class:`TcpGangServer` — the first
  transport with a LOSSY medium, so it carries the robustness layer
  the others never needed: a per-operation timeout on every socket
  call, bounded retry with exponential backoff + jitter, idempotent
  message semantics (every mutating request carries an ``op_id`` the
  server deduplicates, so a duplicated or retried delivery can never
  double-fire an abort, double-append a ledger line, or re-admit a
  consumed join), and persistent connection loss surfaced as
  :class:`TransportError` — which ``GangCoordinator`` feeds into the
  existing peer-death detector (a rank that cannot reach the gang for
  ``peer_timeout_s`` treats ITSELF as partitioned off and exits).

Poll cadence is a TRANSPORT property (the ISSUE 12 bugfix): the old
``min(heartbeat_interval_s, peer_timeout_s / 4)`` monitor cadence and
the supervisor's fixed 0.2 s poll were tuned for file-stat costs.  The
in-proc backend polls tightly (reads are dict lookups — tight polls
are what make the campaigns fast), while the TCP backend scales its
cadence with the world size so 128 monitors polling N-1 peers each
cannot self-DoS the rank-0 host (reads are also BATCHED:
``read_beats`` is one round trip for the whole gang, never N).

Telemetry: every operation counts into ``gang_transport_ops{op,
backend}``; retries and timeouts count into ``gang_transport_retries``
/ ``gang_transport_timeouts`` and mirror into
``FaultEvents.transport_retries``/``transport_timeouts`` (the
``resilience_summary`` rows).  ``stats()`` returns the same totals for
the supervisor's end-of-run health-ledger record, which
``tools/gang_status.py`` renders as the transport-health line.

This module is deliberately stdlib-only (no jax, no numpy) so the
``tools/`` layer can import it against a dead run's directory — the
same contract as ``telemetry/aggregator.py``.
"""

from __future__ import annotations

import contextlib
import json
import os
import random
import socket
import socketserver
import threading
import time
import uuid
from collections import OrderedDict

try:  # posix only; the file backend falls back to post-then-reverify
    import fcntl
except ImportError:  # pragma: no cover
    fcntl = None

from distributed_machine_learning_tpu.runtime import coordinator as _coord

TRANSPORT_BACKENDS = ("file", "inproc", "tcp")

# One line of the TCP wire protocol (request or response) may not
# exceed this — a 128-rank beat snapshot with metrics is ~100 KiB.
_MAX_LINE = 8 * 1024 * 1024

# Ops that mutate server/hub state: each request carries an op_id the
# server deduplicates, so retries and duplicated deliveries are exactly
# -once.  Reads are naturally idempotent and retry without ids.
_MUTATING_OPS = frozenset({
    "publish_beat", "declare_abort", "announce_join", "consume_join",
    "write_restore", "append_health", "append_fault", "append_consumed",
    "clear",
    # Serving-plane channels (ISSUE 16).  ``take_requests`` and
    # ``take_results`` are destructive pops, so the dedup matters MOST
    # there: a tcp retry after a lost response must return the batch
    # the original pop claimed, never pop a second one — that is the
    # request-level exactly-once the serving router builds on.
    "push_request", "take_requests", "post_result", "take_results",
    "set_drain", "set_role", "retire_replica",
    # Continuous-deployment weight channel (ISSUE 18): staging and
    # committing a weights version both mutate the per-replica weight
    # record the post fence reads, so a tcp retry must be a result
    # fetch, never a second stage/commit.
    "set_weights", "commit_weights",
})


# Deterministic-scheduler seam (dmlcheck layer 3): the hooks live in
# runtime/coordinator.py — the bottom of the runtime import chain,
# which this module already imports — and are aliased here so every
# schedule point on the transport hot paths is one plain call.  With
# no scheduler installed (every production run) a point is a global
# read + None test; ``_sched_block`` returns False and the caller
# falls back to its real blocking wait.
_sched_point = _coord._sched_point
_sched_block = _coord._sched_block


class TransportError(RuntimeError):
    """A gang-transport operation failed for good (retries exhausted,
    or the channel is severed).  The coordinator treats a persistent
    TransportError streak as evidence this rank is partitioned off the
    gang — peer death, seen from the inside."""


# ---------------------------------------------------------------------------
# Request-scoped stage events (ISSUE 17)
# ---------------------------------------------------------------------------
# A serving request that carries an ``events`` list gets one stage
# record appended at every hop of its journey: router-side (admitted,
# queued, dispatched, requeued, dropped, completed) and worker-side
# (taken, bound, computed, posted, fenced).  Replicas running the
# continuous-batching engine (ISSUE 19) replace the single batch-wide
# ``computed`` stamp with the per-request pair ``prefill`` (bound →
# first token sampled) and ``decode`` (prefill → retirement), so the
# stage histograms decompose a request's compute into its two
# regimes instead of hiding both under one micro-batch interval.  The record is
# ``{"stage", "by", "dt"}`` where ``dt`` is the seconds since the SAME
# actor's previous stamp on this request, measured on that actor's own
# monotonic clock — or None when the previous stamp came from another
# process.  The clock anchor rides the payload as the private
# ``_mono_last``/``_mono_by`` pair and is STRIPPED at every wire
# crossing (``push_request``/``post_result``), so no timestamp is ever
# compared across hosts (the DML001 discipline): only rank-local
# deltas travel.  Stamping for ``taken``/``posted`` lives in the
# GangTransport base wrappers below, which run exactly once per
# LOGICAL operation on every backend — tcp retries happen underneath,
# inside ``_call``, and the op-id dedup returns the original effect,
# so stage events inherit the same exactly-once guarantee as the ops
# that carry them.

SERVING_STAGES = ("admitted", "queued", "dispatched", "taken", "bound",
                  "prefill", "decode", "computed", "posted", "completed",
                  "requeued", "fenced", "dropped")

# Terminal stages: after one of these, the actor that stamped it holds
# no further obligation for the request (dmlcheck DML015 keys on this
# split — an open stage without a terminal stamp on some exit path is
# an abandoned record that silently skews the stage histograms).
SERVING_TERMINAL_STAGES = frozenset(
    {"posted", "completed", "requeued", "fenced", "dropped"})

_STAGE_CLOCK_KEYS = ("_mono_last", "_mono_by")


def stamp_stage(payload: dict, stage: str, by: str, **extra) -> dict:
    """Append one stage event to ``payload["events"]`` and advance the
    payload's per-actor monotonic anchor.  ``dt`` is filled only when
    the previous stamp was made by the same ``by`` (same process) —
    never a cross-host clock comparison."""
    now = time.monotonic()
    dt = None
    if payload.get("_mono_by") == by:
        last = payload.get("_mono_last")
        if isinstance(last, (int, float)):
            dt = now - float(last)
    payload["_mono_last"] = now
    payload["_mono_by"] = by
    ev = {"stage": str(stage), "by": str(by), "dt": dt}
    if "dispatch" in payload:
        ev["disp"] = payload["dispatch"]
    ev.update(extra)
    payload.setdefault("events", []).append(ev)
    return ev


def strip_stage_clock(payload: dict) -> dict:
    """Remove the private monotonic anchor before a payload crosses the
    wire: monotonic values are meaningless in another process, and
    leaving them attached would invite exactly the cross-host
    comparison the event schema exists to avoid."""
    for k in _STAGE_CLOCK_KEYS:
        payload.pop(k, None)
    return payload


def carry_stage_context(src: dict, dst: dict) -> dict:
    """Move the trace context (events + dispatch tag + clock anchor)
    from a taken request onto the result being posted for it, so the
    worker-side stamps reach the router.  No-op for requests submitted
    without tracing."""
    if isinstance(src.get("events"), list):
        dst["events"] = src["events"]
        for k in ("dispatch", *_STAGE_CLOCK_KEYS):
            if k in src:
                dst[k] = src[k]
    return dst


def append_jsonl_fsync(path: str | os.PathLike, entry: dict) -> None:
    """Append one JSON line to a ledger file, flushed AND fsynced
    before returning (dmlcheck DML002): ledger consumers include
    relaunched processes whose writer may ``os._exit`` on its very
    next statement."""
    with open(os.fspath(path), "a") as f:
        f.write(json.dumps(entry) + "\n")
        f.flush()
        os.fsync(f.fileno())


def _read_jsonl_dicts(path: str) -> list[dict]:
    """Tolerant JSONL reader: absent file → empty, torn final line (a
    kill mid-append) skipped — the shared reading rule of every gang
    ledger."""
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return []
    out = []
    for line in lines:
        try:
            entry = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(entry, dict):
            out.append(entry)
    return out


# ---------------------------------------------------------------------------
# Interface
# ---------------------------------------------------------------------------


class GangTransport:
    """The channel set ``GangCoordinator``/``gang_supervise`` coordinate
    through.  Subclasses implement the ``_do_*`` operations; this base
    owns operation accounting (the telemetry satellite) and the poll
    cadence defaults (the file backend's historical numbers).

    Beat reads return ``(signature, payload)`` pairs: ``signature`` is
    an opaque token that changes whenever the rank re-publishes (file:
    ``(st_mtime_ns, st_size)``; hub: a version counter) — the
    change-signature staleness basis the peer detector judges on, never
    a cross-host clock.  ``payload`` may be None for a torn/unreadable
    beat whose signature still advanced (alive, content unreadable this
    instant).
    """

    backend = "?"

    def __init__(self, events=None):
        self.events = events
        self.op_counts: dict[str, int] = {}
        self.retries = 0
        self.timeouts = 0
        self._stats_lock = threading.Lock()
        self._tel_counters: dict[str, object] = {}

    # -- accounting ------------------------------------------------------
    def _telemetry(self):
        from distributed_machine_learning_tpu.telemetry import get_telemetry

        return get_telemetry()

    def _count(self, op: str) -> None:
        with self._stats_lock:
            self.op_counts[op] = self.op_counts.get(op, 0) + 1
            counter = self._tel_counters.get(op)
        if counter is None:
            tel = self._telemetry()
            if tel is None:
                return
            counter = tel.registry.counter(
                "gang_transport_ops", op=op, backend=self.backend
            )
            with self._stats_lock:
                self._tel_counters[op] = counter
        counter.inc()

    def _count_retry(self) -> None:
        with self._stats_lock:
            self.retries += 1
        if self.events is not None:
            self.events.transport_retries += 1
        tel = self._telemetry()
        if tel is not None:
            tel.registry.counter("gang_transport_retries",
                                 backend=self.backend).inc()

    def _count_timeout(self) -> None:
        with self._stats_lock:
            self.timeouts += 1
        if self.events is not None:
            self.events.transport_timeouts += 1
        tel = self._telemetry()
        if tel is not None:
            tel.registry.counter("gang_transport_timeouts",
                                 backend=self.backend).inc()

    def stats(self) -> dict:
        """Op/retry/timeout totals — the transport-health record the
        supervisor appends to the health ledger at the end of a run."""
        with self._stats_lock:
            ops = dict(sorted(self.op_counts.items()))
            return {
                "backend": self.backend,
                "ops": ops,
                "ops_total": sum(ops.values()),
                "retries": self.retries,
                "timeouts": self.timeouts,
            }

    # -- poll cadence (transport property — the ISSUE 12 bugfix) ---------
    def monitor_poll_s(self, heartbeat_interval_s: float,
                       peer_timeout_s: float, world: int) -> float:
        """How often one rank's monitor thread should poll the gang."""
        return min(heartbeat_interval_s, peer_timeout_s / 4)

    def supervisor_poll_s(self, world: int) -> float:
        """How often the gang supervisor should poll worker liveness,
        joins, and the health snapshot."""
        return 0.2

    def barrier_poll_s(self) -> float:
        """How often ``wait_for_peers`` re-reads the gang's steps."""
        return 0.05

    # -- channel operations (subclass hooks wrapped with accounting) -----
    def publish_beat(self, rank: int, payload: dict) -> None:
        self._count("publish_beat")
        self._do_publish_beat(rank, payload)

    def read_beat(self, rank: int):
        """(signature, payload|None) or None when the rank never
        published."""
        self._count("read_beat")
        return self._do_read_beat(rank)

    def read_beats(self) -> dict[int, tuple]:
        """rank -> (signature, payload|None) for every published beat —
        ONE operation regardless of world size (the batched read the
        TCP cadence depends on)."""
        self._count("read_beats")
        return self._do_read_beats()

    def read_beat_payloads(self) -> dict[int, dict]:
        """rank -> payload for every beat readable right now."""
        return {r: p for r, (_, p) in self.read_beats().items()
                if isinstance(p, dict)}

    def declare_abort(self, reason: str, by_rank: int,
                      peer: int | None = None) -> bool:
        self._count("declare_abort")
        return self._do_declare_abort(reason, by_rank, peer)

    def read_abort(self) -> dict | None:
        self._count("read_abort")
        return self._do_read_abort()

    def announce_join(self, rank: int, payload: dict) -> None:
        """Publish (or refresh — idempotent overwrite) a join
        announcement.  ``payload`` must carry at least ``rank`` and
        ``spare``; callers add ``prefetched_step``/``kind``/... ."""
        self._count("announce_join")
        self._do_announce_join(int(rank), payload)

    def read_joins(self) -> dict[int, dict]:
        self._count("read_joins")
        return self._do_read_joins()

    def consume_join(self, rank: int) -> None:
        self._count("consume_join")
        self._do_consume_join(int(rank))

    def write_restore_record(self, rank: int, steps) -> None:
        self._count("write_restore")
        self._do_write_restore(int(rank), sorted(int(s) for s in steps))

    def read_restore_record(self, rank: int) -> set[int] | None:
        self._count("read_restore")
        return self._do_read_restore(int(rank))

    def append_health_event(self, kind: str, **fields) -> None:
        self._count("append_health")
        self._do_append_health({"kind": kind, "time": time.time(),
                                **fields})

    def read_health_events(self) -> list[dict]:
        self._count("read_health")
        return self._do_read_health()

    def append_fault_entry(self, entry: dict) -> None:
        self._count("append_fault")
        self._do_append_fault(dict(entry))

    def read_fault_entries(self) -> list[dict]:
        self._count("read_faults")
        return self._do_read_faults()

    def append_consumed(self, orig_rank: int, payload: dict) -> None:
        self._count("append_consumed")
        self._do_append_consumed(int(orig_rank), dict(payload))

    def read_consumed(self, orig_rank: int | None = None) -> list[dict]:
        """Consumption rows for one original rank, or (None) for every
        rank — the exactly-once audit input."""
        self._count("read_consumed")
        return self._do_read_consumed(
            None if orig_rank is None else int(orig_rank))

    # -- serving-plane channels (ISSUE 16) -------------------------------
    # The replicated-inference tier reuses the gang control plane and
    # adds four channels: a per-replica inbound request queue, a shared
    # completed-result queue, a per-replica drain latch, and the
    # role/epoch record that fences a retired replica's late writes.
    def push_request(self, replica: int, payload: dict) -> None:
        """Enqueue one request onto ``replica``'s inbound queue.  The
        router stamps each payload with ``rid`` and the replica's
        serving epoch; the transport treats it as opaque — except the
        trace context: a payload carrying an ``events`` list has its
        private monotonic anchor stripped here (monotonic values never
        cross the wire, DML001) and its events copied so the caller's
        record cannot alias the queued one."""
        self._count("push_request")
        payload = dict(payload)
        if isinstance(payload.get("events"), list):
            payload["events"] = [dict(e) for e in payload["events"]]
            strip_stage_clock(payload)
        self._do_push_request(int(replica), payload)

    def take_requests(self, replica: int, max_n: int = 1) -> list[dict]:
        """Destructively pop up to ``max_n`` pending requests from
        ``replica``'s queue, FIFO.  On tcp the op_id dedup makes a
        retried take return the ORIGINAL batch — a request can be
        claimed by at most one take.  Traced requests are stamped
        ``taken`` here: the wrapper runs in the worker's process on
        every backend, exactly once per logical take (retries collapse
        below it), so the stamp is both on the right clock and
        exactly-once."""
        self._count("take_requests")
        reqs = self._do_take_requests(int(replica), int(max_n))
        by = f"replica{int(replica)}"
        for r in reqs:
            if isinstance(r.get("events"), list):
                stamp_stage(r, "taken", by)
        return reqs

    def post_result(self, replica: int, epoch: int,
                    payload: dict, version: int | None = None) -> bool:
        """Append one completed result — ACCEPTED only when ``epoch``
        matches the replica's current serving epoch (checked atomically
        with the append).  Returns False for a fenced (stale-epoch)
        post: a drained/evicted replica's late result is discarded at
        the hub, never double-delivered.  ``version`` (ISSUE 18): the
        weights version the compute was bound to; when given it is
        checked — inside the SAME atomic section — against the
        replica's committed weights version, so a late post from an
        old-version compute can never complete a request after the
        hot-swap committed.  A traced result is stamped ``posted`` on a
        COPY of its event record (a fenced post's stamp is discarded
        with the post — the caller's record never shows a delivery
        that did not happen), clock anchor stripped before the wire."""
        self._count("post_result")
        payload = dict(payload)
        if isinstance(payload.get("events"), list):
            payload["events"] = [dict(e) for e in payload["events"]]
            stamp_stage(payload, "posted", f"replica{int(replica)}")
            strip_stage_clock(payload)
        return bool(self._do_post_result(
            int(replica), int(epoch), payload,
            None if version is None else int(version)))

    def take_results(self, max_n: int = 16) -> list[dict]:
        """Destructively pop up to ``max_n`` completed results (the
        router's collection read)."""
        self._count("take_results")
        return self._do_take_results(int(max_n))

    def set_drain(self, replica: int, draining: bool = True) -> None:
        """Set/clear ``replica``'s drain latch: a draining replica
        finishes its in-flight work but the router stops dispatching
        to it."""
        self._count("set_drain")
        self._do_set_drain(int(replica), bool(draining))

    def set_serving_role(self, replica: int, role: str) -> None:
        """Record ``replica``'s role (``"live"`` or ``"spare"``) — the
        promotion edge of the replica state machine."""
        self._count("set_role")
        self._do_set_role(int(replica), str(role))

    def set_weights(self, replica: int, version: int,
                    meta: dict | None = None) -> None:
        """Stage a new weights version for ``replica`` (ISSUE 18): the
        deployment controller's announce edge.  ``meta`` (checkpoint
        step, digest, path…) rides the record so the worker's swap
        callback knows what to load.  Staging does NOT move the fence:
        the replica keeps posting under its committed version until it
        drains its in-flight micro-batch and calls
        :meth:`commit_weights` — that is the zero-dropped-requests
        half of the swap protocol."""
        self._count("set_weights")
        self._do_set_weights(int(replica), int(version),
                             dict(meta or {}))

    def commit_weights(self, replica: int, version: int) -> bool:
        """Commit ``replica``'s weights version — the swap's fence
        move, atomic at the hub with the :meth:`post_result` version
        check: from this op on, a post carrying the OLD version is
        fenced (returns False), so an old-version compute can never
        complete a new-version request.  Called by the worker after it
        drained in-flight work and loaded the staged weights."""
        self._count("commit_weights")
        return bool(self._do_commit_weights(int(replica), int(version)))

    def retire_replica(self, replica: int) -> list[dict]:
        """Demote ``replica`` in ONE atomic step: bump its serving
        epoch (fencing any in-flight ``post_result`` from the old
        epoch), flip its role back to ``spare``, clear its drain
        latch, and return whatever requests were still queued for it —
        the router re-dispatches those to survivors."""
        self._count("retire_replica")
        return self._do_retire_replica(int(replica))

    def read_serving(self, replica: int | None = None) -> dict:
        """One replica's ``{role, epoch, drain, queued, weights}``
        record, or (``None``) the whole serving plane: ``{replicas:
        {rank: record}, results: depth}`` — the status-tool read.
        ``weights`` is ``{version, pending, …meta}``: the committed
        weights version fencing this replica's posts, plus any staged
        (not yet committed) version and its deploy metadata."""
        self._count("read_serving")
        return self._do_read_serving(
            None if replica is None else int(replica))

    def clear_gang_state(self, restore_records: bool = False,
                         fault_ledger: bool | None = None) -> None:
        """Same contract as ``coordinator.clear_gang_state``: beats and
        the abort latch always; restore records on request; the
        fault/health/consumed ledgers and pending joins only at
        fresh-run init (``fault_ledger`` defaults to
        ``restore_records``)."""
        self._count("clear")
        self._do_clear(restore_records,
                       restore_records if fault_ledger is None
                       else fault_ledger)

    def snapshot(self) -> dict:
        """Everything a status tool needs in one read: beats, the abort
        latch, pending joins, health events, fired faults — the API
        ``tools/gang_status.py`` reads instead of globbing
        ``beat_rank*.json``."""
        return {
            "backend": self.backend,
            "beats": self.read_beat_payloads(),
            "abort": self.read_abort(),
            "joins": self.read_joins(),
            "health": self.read_health_events(),
            "faults_fired": self.read_fault_entries(),
            "serving": self.read_serving(),
        }

    def close(self) -> None:
        """Release any live resources (sockets).  Idempotent."""


# ---------------------------------------------------------------------------
# File backend — the PR 3/5/10 behavior, extracted verbatim
# ---------------------------------------------------------------------------


class FileTransport(GangTransport):
    """The shared-directory backend: every operation delegates to the
    ``runtime/coordinator.py`` file functions (or reproduces their
    exact format for the ledgers), so the on-disk layout is
    byte-compatible with every pre-transport release and mixed
    deployments (old reader, new writer) keep working."""

    backend = "file"

    def __init__(self, gang_dir: str | os.PathLike, events=None):
        super().__init__(events=events)
        self.gang_dir = os.fspath(gang_dir)
        # The directory is created on the first WRITE, never at
        # construction: read-only consumers (tools/gang_status.py
        # pointed at a post-mortem mount, or a typo'd path) must not
        # mutate the filesystem.
        self._dir_ready = False
        # Orphaned-claim GC state: claim path -> (stat signature,
        # monotonic time this handle first saw that signature).
        self._claim_seen: dict[str, tuple] = {}

    def _ensure_dir(self) -> None:
        if not self._dir_ready:
            os.makedirs(self.gang_dir, exist_ok=True)
            self._dir_ready = True

    # beats
    def _do_publish_beat(self, rank: int, payload: dict) -> None:
        self._ensure_dir()
        _coord._write_atomic(_coord._beat_path(self.gang_dir, rank),
                             payload)

    def _beat_entry(self, path: str):
        try:
            st = os.stat(path)
        except OSError:
            return None
        sig = (st.st_mtime_ns, st.st_size)
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, json.JSONDecodeError):
            payload = None  # torn read mid-replace: alive by signature
        return (sig, payload if isinstance(payload, dict) else None)

    def _do_read_beat(self, rank: int):
        return self._beat_entry(_coord._beat_path(self.gang_dir, rank))

    def _do_read_beats(self) -> dict[int, tuple]:
        out: dict[int, tuple] = {}
        prefix = _coord._BEAT_PREFIX
        try:
            names = os.listdir(self.gang_dir)
        except OSError:
            return out
        for name in names:
            if not (name.startswith(prefix) and name.endswith(".json")):
                continue
            rank_s = name[len(prefix):-len(".json")]
            if not rank_s.isdigit():
                continue
            entry = self._beat_entry(os.path.join(self.gang_dir, name))
            if entry is not None:
                out[int(rank_s)] = entry
        return out

    # abort latch
    def _do_declare_abort(self, reason, by_rank, peer) -> bool:
        self._ensure_dir()
        return _coord.declare_abort(self.gang_dir, reason, by_rank,
                                    peer=peer)

    def _do_read_abort(self):
        return _coord.read_abort(self.gang_dir)

    # joins
    def _do_announce_join(self, rank: int, payload: dict) -> None:
        self._ensure_dir()
        _coord._write_atomic(_coord._join_path(self.gang_dir, rank),
                             payload)

    def _do_read_joins(self):
        return _coord.read_joins(self.gang_dir)

    def _do_consume_join(self, rank: int) -> None:
        _coord.consume_join(self.gang_dir, rank)

    # restore records
    def _do_write_restore(self, rank: int, steps: list[int]) -> None:
        self._ensure_dir()
        _coord._write_atomic(
            _coord._restore_path(self.gang_dir, rank),
            {"rank": rank, "steps": steps, "time": time.time()},
        )

    def _do_read_restore(self, rank: int):
        return _coord.read_restore_record(self.gang_dir, rank)

    # ledgers (append paths carry the DML002 flush+fsync discipline)
    def _do_append_health(self, payload: dict) -> None:
        self._ensure_dir()
        append_jsonl_fsync(
            os.path.join(self.gang_dir, _coord.GANG_HEALTH_FILE), payload)

    def _do_read_health(self) -> list[dict]:
        return _read_jsonl_dicts(
            os.path.join(self.gang_dir, _coord.GANG_HEALTH_FILE))

    def fault_ledger_path(self) -> str:
        # Import-free name: runtime/faults.py pulls in numpy, which the
        # stdlib-only tools layer must never load.
        return os.path.join(self.gang_dir, "faults_fired.jsonl")

    def _do_append_fault(self, entry: dict) -> None:
        self._ensure_dir()
        append_jsonl_fsync(self.fault_ledger_path(), entry)

    def _do_read_faults(self) -> list[dict]:
        return _read_jsonl_dicts(self.fault_ledger_path())

    def _consumed_path(self, orig_rank: int) -> str:
        return os.path.join(
            self.gang_dir, f"{_coord.CONSUMED_PREFIX}{orig_rank}.jsonl")

    def _do_append_consumed(self, orig_rank: int, payload: dict) -> None:
        self._ensure_dir()
        append_jsonl_fsync(self._consumed_path(orig_rank), payload)

    def _do_read_consumed(self, orig_rank: int | None) -> list[dict]:
        if orig_rank is not None:
            return _read_jsonl_dicts(self._consumed_path(orig_rank))
        out: list[dict] = []
        try:
            names = sorted(os.listdir(self.gang_dir))
        except OSError:
            return out
        for name in names:
            if name.startswith(_coord.CONSUMED_PREFIX) \
                    and name.endswith(".jsonl"):
                out.extend(_read_jsonl_dicts(
                    os.path.join(self.gang_dir, name)))
        return out

    def _do_clear(self, restore_records: bool, fault_ledger: bool) -> None:
        _coord.clear_gang_state(self.gang_dir,
                                restore_records=restore_records,
                                fault_ledger=fault_ledger)
        if fault_ledger:
            self._clear_serving()

    # -- serving channels: spool directories under gang_dir/serving ------
    # Queues are one-file-per-request spools; a pop CLAIMS a file with
    # an atomic os.rename before reading it, so two competing takers can
    # never both consume the same request.  File names carry a
    # per-handle counter (FIFO per writer) plus a uuid suffix so
    # concurrent writers never collide.  A claim orphaned by a crashed
    # taker (renamed but never read+removed) is garbage-collected: a
    # claim a scanner observes with an UNCHANGED stat signature for
    # ``_TAKE_ORPHAN_S`` of its own monotonic clock (change-signatures,
    # never cross-host wall time — DML001) is renamed back to its spool
    # name, restoring it to takes, retire reclaim, and the queued count.
    _SERVING_DIR = "serving"
    _TAKE_ORPHAN_S = 30.0

    def _serving_path(self, *parts) -> str:
        return os.path.join(self.gang_dir, self._SERVING_DIR, *parts)

    def _serving_seq_name(self) -> str:
        with self._stats_lock:
            seq = getattr(self, "_serving_seq", 0) + 1
            self._serving_seq = seq
        return f"{seq:010d}_{uuid.uuid4().hex[:8]}.json"

    @staticmethod
    def _read_json(path: str):
        try:
            with open(path) as f:
                entry = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None
        return entry if isinstance(entry, dict) else None

    def _spool_push(self, subdir: str, payload: dict) -> str:
        self._ensure_dir()
        d = self._serving_path(subdir)
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, self._serving_seq_name())
        _coord._write_atomic(path, payload)
        return path

    def _spool_take(self, subdir: str, max_n: int) -> list[dict]:
        d = self._serving_path(subdir)
        try:
            names = sorted(os.listdir(d))
        except OSError:
            return []
        out: list[dict] = []
        claims: set[str] = set()
        for name in names:
            if ".take" in name:
                # GC an orphaned claim: the taker crashed between its
                # rename and the read+remove.  Staleness is this
                # handle's monotonic clock over an unchanged stat
                # signature; once stale, the claim is renamed back to
                # its spool name and is claimable on the next scan.
                path = os.path.join(d, name)
                claims.add(path)
                try:
                    st = os.stat(path)
                except OSError:
                    self._claim_seen.pop(path, None)
                    continue
                sig = (st.st_mtime_ns, st.st_size)
                seen = self._claim_seen.get(path)
                now = time.monotonic()
                if seen is None or seen[0] != sig:
                    self._claim_seen[path] = (sig, now)
                elif now - seen[1] > self._TAKE_ORPHAN_S:
                    with contextlib.suppress(OSError):
                        os.rename(path, os.path.join(
                            d, name.split(".take", 1)[0]))
                    self._claim_seen.pop(path, None)
                continue
            if len(out) >= max_n or not name.endswith(".json"):
                continue
            path = os.path.join(d, name)
            claimed = f"{path}.take{os.getpid()}.{threading.get_ident()}"
            try:
                os.rename(path, claimed)  # atomic claim: one winner
            except OSError:
                continue  # another taker won this file
            entry = self._read_json(claimed)
            with contextlib.suppress(OSError):
                os.remove(claimed)
            if entry is not None:
                out.append(entry)
        # Forget claims that vanished (their takers finished normally).
        prefix = d + os.sep
        for p in [p for p in self._claim_seen
                  if p.startswith(prefix) and p not in claims]:
            self._claim_seen.pop(p, None)
        return out

    def _do_push_request(self, replica: int, payload: dict) -> None:
        self._spool_push(f"requests_r{replica}", payload)

    def _do_take_requests(self, replica: int, max_n: int) -> list[dict]:
        return self._spool_take(f"requests_r{replica}", max_n)

    @contextlib.contextmanager
    def _replica_fence(self, replica: int):
        """Cross-process mutual exclusion between a result post's
        epoch check + spool push and ``retire_replica``'s epoch bump —
        the file-backend equivalent of the hub lock the in-proc fence
        holds, so the 'checked atomically with the append' contract is
        real, not check-then-act.  No-op without fcntl (the post path
        re-verifies after the push instead)."""
        if fcntl is None:
            yield
            return
        self._ensure_dir()
        os.makedirs(self._serving_path(), exist_ok=True)
        fd = os.open(self._serving_path(f"fence_r{replica}.lock"),
                     os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            with contextlib.suppress(OSError):
                fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    def _weights_path(self, replica: int) -> str:
        return self._serving_path(f"weights_r{replica}.json")

    def _weights_ok(self, replica: int, version: int | None) -> bool:
        if version is None:
            return True
        cur = self._read_json(self._weights_path(replica)) or {}
        return int(version) == int(cur.get("version", 0))

    def _do_post_result(self, replica: int, epoch: int,
                        payload: dict, version: int | None) -> bool:
        epoch_path = self._serving_path(f"epoch_r{replica}.json")
        with self._replica_fence(replica):
            cur = self._read_json(epoch_path) or {}
            if int(epoch) != int(cur.get("epoch", 0)):
                return False
            # The weight-swap fence (ISSUE 18), under the SAME flock
            # commit_weights takes: a post from an old-version compute
            # after the swap committed is discarded here, atomic with
            # the append.
            if not self._weights_ok(replica, version):
                return False
            if version is not None:
                payload = dict(payload, version=int(version))
            posted = self._spool_push(
                "results",
                dict(payload, replica=replica, epoch=int(epoch)))
        if fcntl is not None:
            return True
        # Lock-free fallback: a retire_replica (or commit_weights) may
        # have moved a fence between the read and the push.  Re-verify
        # and reclaim the stale file; if the router consumed it first,
        # it was delivered (the router's ledger dedups regardless).
        cur = self._read_json(epoch_path) or {}
        if (int(epoch) == int(cur.get("epoch", 0))
                and self._weights_ok(replica, version)):
            return True
        claimed = f"{posted}.take{os.getpid()}.{threading.get_ident()}"
        try:
            os.rename(posted, claimed)
        except OSError:
            return True
        with contextlib.suppress(OSError):
            os.remove(claimed)
        return False

    def _do_take_results(self, max_n: int) -> list[dict]:
        return self._spool_take("results", max_n)

    def _do_set_drain(self, replica: int, draining: bool) -> None:
        self._ensure_dir()
        os.makedirs(self._serving_path(), exist_ok=True)
        _coord._write_atomic(self._serving_path(f"drain_r{replica}.json"),
                             {"drain": bool(draining)})

    def _do_set_role(self, replica: int, role: str) -> None:
        self._ensure_dir()
        os.makedirs(self._serving_path(), exist_ok=True)
        _coord._write_atomic(self._serving_path(f"role_r{replica}.json"),
                             {"role": role})

    def _do_set_weights(self, replica: int, version: int,
                        meta: dict) -> None:
        self._ensure_dir()
        os.makedirs(self._serving_path(), exist_ok=True)
        with self._replica_fence(replica):
            cur = self._read_json(self._weights_path(replica)) or {}
            committed = int(cur.get("version", 0))
            rec = dict(meta)
            rec["version"] = committed
            rec["pending"] = int(version)
            _coord._write_atomic(self._weights_path(replica), rec)

    def _do_commit_weights(self, replica: int, version: int) -> bool:
        self._ensure_dir()
        os.makedirs(self._serving_path(), exist_ok=True)
        with self._replica_fence(replica):
            cur = self._read_json(self._weights_path(replica)) or {}
            cur["version"] = int(version)
            if cur.get("pending") == int(version):
                cur["pending"] = None
            _coord._write_atomic(self._weights_path(replica), cur)
        return True

    def _do_retire_replica(self, replica: int) -> list[dict]:
        self._ensure_dir()
        os.makedirs(self._serving_path(), exist_ok=True)
        with self._replica_fence(replica):
            cur = self._read_json(
                self._serving_path(f"epoch_r{replica}.json")) or {}
            _coord._write_atomic(
                self._serving_path(f"epoch_r{replica}.json"),
                {"epoch": int(cur.get("epoch", 0)) + 1})
        self._do_set_role(replica, "spare")
        with contextlib.suppress(OSError):
            os.remove(self._serving_path(f"drain_r{replica}.json"))
        return self._spool_take(f"requests_r{replica}", 1 << 30)

    def _replica_record(self, replica: int) -> dict:
        role = self._read_json(
            self._serving_path(f"role_r{replica}.json")) or {}
        epoch = self._read_json(
            self._serving_path(f"epoch_r{replica}.json")) or {}
        drain = self._read_json(
            self._serving_path(f"drain_r{replica}.json")) or {}
        try:
            queued = sum(
                n.endswith(".json") for n in os.listdir(
                    self._serving_path(f"requests_r{replica}")))
        except OSError:
            queued = 0
        weights = self._read_json(self._weights_path(replica)) or {}
        wrec = dict(weights)
        wrec["version"] = int(weights.get("version", 0))
        wrec.setdefault("pending", None)
        return {"role": role.get("role", "spare"),
                "epoch": int(epoch.get("epoch", 0)),
                "drain": bool(drain.get("drain", False)),
                "queued": queued,
                "weights": wrec}

    def _do_read_serving(self, replica: int | None) -> dict:
        if replica is not None:
            return self._replica_record(replica)
        replicas: dict[int, dict] = {}
        try:
            names = os.listdir(self._serving_path())
        except OSError:
            names = []
        for name in names:
            for prefix in ("role_r", "epoch_r", "drain_r", "weights_r"):
                if name.startswith(prefix) and name.endswith(".json"):
                    rank_s = name[len(prefix):-len(".json")]
                    if rank_s.isdigit():
                        replicas.setdefault(int(rank_s), {})
        for rank in list(replicas):
            replicas[rank] = self._replica_record(rank)
        try:
            results = sum(
                n.endswith(".json")
                for n in os.listdir(self._serving_path("results")))
        except OSError:
            results = 0
        return {"replicas": replicas, "results": results}

    def _clear_serving(self) -> None:
        root = self._serving_path()
        try:
            names = os.listdir(root)
        except OSError:
            return
        for name in names:
            path = os.path.join(root, name)
            if os.path.isdir(path):
                for inner in os.listdir(path):
                    with contextlib.suppress(OSError):
                        os.remove(os.path.join(path, inner))
                with contextlib.suppress(OSError):
                    os.rmdir(path)
            else:
                with contextlib.suppress(OSError):
                    os.remove(path)


# ---------------------------------------------------------------------------
# In-proc backend — threads + in-memory channels
# ---------------------------------------------------------------------------


class InProcHub:
    """The shared in-memory gang state N thread-ranks coordinate
    through — one hub per gang, one :class:`InProcTransport` handle per
    member.  All mutation is under one lock (operations are dict
    updates; contention is negligible against even the in-proc poll
    cadence).

    ``mirror_dir``: when given, the DURABLE ledgers (health, faults,
    consumption) are also appended to files in that directory in the
    exact file-backend format — volatile channels (beats, abort latch,
    joins) stay memory-only.  This is what lets ``tools/gang_status.py``
    and the exactly-once audits read a finished 64-128-rank campaign
    exactly like a file-backed gang.

    ``epoch`` advances on every :meth:`clear`: member transports bind
    the epoch they were created under, so a zombie worker thread from a
    drained attempt (threads cannot be SIGKILLed) gets
    :class:`TransportError` on its next write instead of polluting the
    relaunched attempt's state.
    """

    def __init__(self, mirror_dir: str | os.PathLike | None = None):
        self.lock = threading.RLock()
        self.mirror_dir = (os.fspath(mirror_dir)
                           if mirror_dir is not None else None)
        if self.mirror_dir is not None:
            os.makedirs(self.mirror_dir, exist_ok=True)
        self.epoch = 0
        self.beats: dict[int, tuple[int, dict]] = {}
        self.abort: dict | None = None
        self.joins: dict[int, dict] = {}
        self.restore: dict[int, list[int]] = {}
        self.health: list[dict] = []
        self.faults: list[dict] = []
        self.consumed: dict[int, list[dict]] = {}
        self.box: dict = {}
        # Serving-plane state (ISSUE 16): per-replica request queues,
        # the shared result queue, the drain latches, and the
        # role/epoch records that fence retired replicas.
        self.serving_requests: dict[int, list[dict]] = {}
        self.serving_results: list[dict] = []
        self.serving_drain: dict[int, bool] = {}
        self.serving_epoch: dict[int, int] = {}
        self.serving_role: dict[int, str] = {}
        # Per-replica weight records (ISSUE 18): {"version": committed,
        # "pending": staged-or-None, ...deploy meta} — the version
        # fence post_result checks atomically with its append.
        self.serving_weights: dict[int, dict] = {}
        self._version = 0
        # Digital-twin seam (ISSUE 20): when a campaign attaches a
        # ``runtime.netmodel.NetModel`` here, in-proc workers report
        # MODELED step times through ``observe_step`` and the fault
        # injector's gray link kinds mutate it.  Hub-scoped on purpose:
        # ``clear`` resets beats and aborts between attempts, but a
        # degraded physical link stays degraded across a relaunch —
        # the fault ledger (not the model) is what stops the
        # *injection* from re-firing.
        self.netmodel = None

    # -- the broadcast box (in-proc worker extension) --------------------
    # A tiny rank-0-broadcast channel the in-proc worker harness uses to
    # share the restored state and save commits (on a real pod this is a
    # host-side broadcast collective; in-proc it is a dict).
    def box_put(self, key, value) -> None:
        _sched_point("hub:box:w")
        with self.lock:
            self.box[key] = value

    def box_get(self, key, default=None):
        _sched_point("hub:box:r")
        with self.lock:
            return self.box.get(key, default)

    def clear(self, restore_records: bool, fault_ledger: bool) -> None:
        _sched_point("hub:clear:w")
        with self.lock:
            self.epoch += 1
            self.beats.clear()
            self.abort = None
            self.box.clear()
            if restore_records:
                self.restore.clear()
            if fault_ledger:
                self.health.clear()
                self.faults.clear()
                self.consumed.clear()
                self.joins.clear()
                self.serving_requests.clear()
                self.serving_results.clear()
                self.serving_drain.clear()
                self.serving_epoch.clear()
                self.serving_role.clear()
                self.serving_weights.clear()
        if self.mirror_dir is not None:
            _coord.clear_gang_state(self.mirror_dir,
                                    restore_records=restore_records,
                                    fault_ledger=fault_ledger)


class InProcTransport(GangTransport):
    """One gang member's handle on an :class:`InProcHub`.

    ``bind_epoch=True`` (the worker-thread default via
    :func:`make_transport`) pins the handle to the hub epoch at
    creation: after the supervisor clears between attempts, writes from
    a leftover thread of the drained attempt raise
    :class:`TransportError` — the in-proc analogue of a killed process
    staying dead.  The supervisor's own handle binds no epoch (it is
    the one doing the clearing)."""

    backend = "inproc"

    def __init__(self, hub: InProcHub, events=None,
                 bind_epoch: bool = False):
        super().__init__(events=events)
        self.hub = hub
        self._epoch = hub.epoch if bind_epoch else None

    @contextlib.contextmanager
    def _locked(self, label: str):
        """Enter the hub's critical section for one operation: schedule
        point (layer-3 seam), lock, THEN the epoch fence — checked
        INSIDE the lock, atomic with the caller's read/mutate.  The
        fence used to run before the acquire: a drained zombie thread
        could pass the check, lose the CPU to the supervisor's
        ``clear`` (which advances the epoch), and then write into the
        NEXT attempt's state — the check-then-act race layer 3's
        epoch-fence scenario explores (and whose broken form survives
        as ``analysis/interleave.py``'s ``epoch-unlocked`` mutation)."""
        _sched_point(label)
        hub = self.hub
        with hub.lock:
            if self._epoch is not None and self._epoch != hub.epoch:
                raise TransportError(
                    f"stale transport handle (epoch {self._epoch}, hub "
                    f"at {hub.epoch}): this member was drained and the "
                    "gang state cleared"
                )
            yield hub

    def _do_publish_beat(self, rank: int, payload: dict) -> None:
        with self._locked("hub:beats:w") as hub:
            hub._version += 1
            hub.beats[rank] = (hub._version, dict(payload))

    def _do_read_beat(self, rank: int):
        with self._locked("hub:beats:r") as hub:
            entry = hub.beats.get(rank)
            # Payloads are replaced wholesale on publish and treated
            # read-only by every consumer, so reads hand out the stored
            # reference: N ranks re-reading N beats every barrier poll
            # must stay O(world) dict lookups, not O(world) deep
            # copies.
            return (entry[0], entry[1]) if entry else None

    def _do_read_beats(self) -> dict[int, tuple]:
        with self._locked("hub:beats:r") as hub:
            return dict(hub.beats)

    def barrier_ready(self, step: int, rank: int, world: int) -> bool:
        """Copy-free lock-step barrier probe (the coordinator's
        ``wait_for_peers`` fast path).  Semantically identical to
        snapshotting the beat table and scanning — every peer must
        have published ``step`` (or ``done``) — but one lock entry and
        zero dict copies, which is the difference between a 512-rank
        barrier costing ~2µs per poll and ~150µs: at pod scale the
        generic path alone would saturate the single CI core."""
        with self._locked("hub:barrier:r") as hub:
            beats = hub.beats
            for peer in range(world):
                if peer == rank:
                    continue
                entry = beats.get(peer)
                if entry is None or not isinstance(entry[1], dict):
                    return False
                payload = entry[1]
                if (not payload.get("done")
                        and int(payload.get("step", -1)) < step):
                    return False
            return True

    def _do_declare_abort(self, reason, by_rank, peer) -> bool:
        payload = {"reason": reason, "by_rank": by_rank,
                   "time": time.time()}
        if peer is not None:
            payload["peer"] = peer
        with self._locked("hub:abort:w") as hub:
            if hub.abort is not None:
                return False
            hub.abort = payload
            return True

    def _do_read_abort(self):
        with self._locked("hub:abort:r") as hub:
            return dict(hub.abort) if hub.abort else None

    def _do_announce_join(self, rank: int, payload: dict) -> None:
        with self._locked("hub:joins:w") as hub:
            hub.joins[rank] = dict(payload)

    def _do_read_joins(self):
        with self._locked("hub:joins:r") as hub:
            return {r: dict(p) for r, p in hub.joins.items()}

    def _do_consume_join(self, rank: int) -> None:
        with self._locked("hub:joins:w") as hub:
            hub.joins.pop(rank, None)

    def _do_write_restore(self, rank: int, steps: list[int]) -> None:
        with self._locked("hub:restore:w") as hub:
            hub.restore[rank] = list(steps)

    def _do_read_restore(self, rank: int):
        with self._locked("hub:restore:r") as hub:
            steps = hub.restore.get(rank)
            return set(steps) if steps is not None else None

    def _do_append_health(self, payload: dict) -> None:
        # Mirror writes happen INSIDE the hub lock: the on-disk ledger
        # order must match the authoritative in-memory order (the
        # fault ledger's loss/recovery masking is explicitly
        # order-aware), and hub.lock is an RLock so the ledger paths
        # stay one critical section.
        with self._locked("hub:health:w") as hub:
            hub.health.append(dict(payload))
            if hub.mirror_dir is not None:
                append_jsonl_fsync(
                    os.path.join(hub.mirror_dir,
                                 _coord.GANG_HEALTH_FILE), payload)

    def _do_read_health(self) -> list[dict]:
        with self._locked("hub:health:r") as hub:
            return [dict(e) for e in hub.health]

    def _do_append_fault(self, entry: dict) -> None:
        with self._locked("hub:faults:w") as hub:
            hub.faults.append(dict(entry))
            if hub.mirror_dir is not None:
                append_jsonl_fsync(
                    os.path.join(hub.mirror_dir,
                                 "faults_fired.jsonl"), entry)

    def _do_read_faults(self) -> list[dict]:
        with self._locked("hub:faults:r") as hub:
            return [dict(e) for e in hub.faults]

    def _do_append_consumed(self, orig_rank: int, payload: dict) -> None:
        with self._locked("hub:consumed:w") as hub:
            hub.consumed.setdefault(orig_rank, []).append(
                dict(payload))
            if hub.mirror_dir is not None:
                append_jsonl_fsync(
                    os.path.join(
                        hub.mirror_dir,
                        f"{_coord.CONSUMED_PREFIX}{orig_rank}.jsonl"),
                    payload)

    def _do_read_consumed(self, orig_rank: int | None) -> list[dict]:
        with self._locked("hub:consumed:r") as hub:
            if orig_rank is not None:
                return [dict(e)
                        for e in hub.consumed.get(orig_rank, ())]
            return [dict(e) for r in sorted(hub.consumed)
                    for e in hub.consumed[r]]

    # -- serving channels ------------------------------------------------
    # Schedule-point labels: the queue channels get structured
    # ``hub:<channel>:w`` labels (independent channels prune against
    # each other in the layer-3 POR), while ``retire_replica`` and the
    # cross-channel snapshot read get deliberately NON-structured
    # labels so the explorer treats them as conflicting with every
    # serving op — they touch several channels in one critical section.
    def _do_push_request(self, replica: int, payload: dict) -> None:
        with self._locked("hub:srequests:w") as hub:
            hub.serving_requests.setdefault(replica, []).append(
                dict(payload))

    def _do_take_requests(self, replica: int, max_n: int) -> list[dict]:
        with self._locked("hub:srequests:w") as hub:
            q = hub.serving_requests.get(replica)
            if not q:
                return []
            out = q[:max_n]
            del q[:max_n]
            return [dict(e) for e in out]

    def _do_post_result(self, replica: int, epoch: int,
                        payload: dict, version: int | None) -> bool:
        # The drain/promote fence: the epoch is compared INSIDE the
        # lock, atomic with the append.  A retired replica's late post
        # (its epoch was bumped by ``retire_replica``) returns False
        # and touches nothing — the check-then-act race the layer-3
        # ``drain_promote`` scenario explores, whose broken form
        # survives as ``analysis/interleave.py``'s ``result-unfenced``
        # mutation.  The weights version (ISSUE 18) is fenced in the
        # SAME critical section — its hoisted-check form is the
        # ``swap-unfenced`` mutation the ``weight_swap`` scenario
        # rediscovers.
        with self._locked("hub:sresults:w") as hub:
            if int(epoch) != hub.serving_epoch.get(replica, 0):
                return False
            if version is not None:
                wrec = hub.serving_weights.get(replica) or {}
                if int(version) != int(wrec.get("version", 0)):
                    return False
                payload = dict(payload, version=int(version))
            hub.serving_results.append(
                dict(payload, replica=replica, epoch=int(epoch)))
            return True

    def _do_take_results(self, max_n: int) -> list[dict]:
        with self._locked("hub:sresults:w") as hub:
            out = hub.serving_results[:max_n]
            del hub.serving_results[:max_n]
            return [dict(e) for e in out]

    def _do_set_drain(self, replica: int, draining: bool) -> None:
        with self._locked("hub:sdrain:w") as hub:
            hub.serving_drain[replica] = bool(draining)

    def _do_set_role(self, replica: int, role: str) -> None:
        with self._locked("hub:srole:w") as hub:
            hub.serving_role[replica] = role

    def _do_set_weights(self, replica: int, version: int,
                        meta: dict) -> None:
        # Non-structured label: the weight record is read by the post
        # fence (hub:sresults:w) and the snapshot — staging must
        # conflict with both in the layer-3 POR, not prune against
        # them as an independent channel.
        with self._locked("hub:serving:setw") as hub:
            cur = hub.serving_weights.get(replica) or {}
            rec = dict(meta)
            rec["version"] = int(cur.get("version", 0))
            rec["pending"] = int(version)
            hub.serving_weights[replica] = rec

    def _do_commit_weights(self, replica: int, version: int) -> bool:
        # The swap's fence move: committed version flips under the hub
        # lock, atomic with every concurrent post's version check.
        with self._locked("hub:serving:commitw") as hub:
            rec = dict(hub.serving_weights.get(replica) or {})
            rec["version"] = int(version)
            if rec.get("pending") == int(version):
                rec["pending"] = None
            hub.serving_weights[replica] = rec
            return True

    def _do_retire_replica(self, replica: int) -> list[dict]:
        with self._locked("hub:serving:retire") as hub:
            hub.serving_epoch[replica] = \
                hub.serving_epoch.get(replica, 0) + 1
            undelivered = hub.serving_requests.pop(replica, [])
            hub.serving_role[replica] = "spare"
            hub.serving_drain.pop(replica, None)
            return [dict(e) for e in undelivered]

    def _replica_record_locked(self, hub: InProcHub,
                               replica: int) -> dict:
        wrec = dict(hub.serving_weights.get(replica) or {})
        wrec["version"] = int(wrec.get("version", 0))
        wrec.setdefault("pending", None)
        return {"role": hub.serving_role.get(replica, "spare"),
                "epoch": hub.serving_epoch.get(replica, 0),
                "drain": bool(hub.serving_drain.get(replica, False)),
                "queued": len(hub.serving_requests.get(replica, ())),
                "weights": wrec}

    def _do_read_serving(self, replica: int | None) -> dict:
        with self._locked("hub:serving:snapshot") as hub:
            if replica is not None:
                return self._replica_record_locked(hub, replica)
            ranks = (set(hub.serving_role) | set(hub.serving_epoch)
                     | set(hub.serving_drain)
                     | set(hub.serving_requests)
                     | set(hub.serving_weights))
            return {"replicas": {r: self._replica_record_locked(hub, r)
                                 for r in sorted(ranks)},
                    "results": len(hub.serving_results)}

    def _do_clear(self, restore_records: bool, fault_ledger: bool) -> None:
        self.hub.clear(restore_records, fault_ledger)

    # cadence: reads are dict lookups — poll tightly so barriers and
    # boundary detection turn around in milliseconds, which is the
    # whole point of the backend (64-128-rank campaigns in seconds).
    # Above ~128 ranks the tight cadence itself becomes the bottleneck
    # (512 threads × 2 ms polls is ~256k acquisitions/s on ONE hub
    # lock), so the poll intervals stretch with world size — pod-scale
    # twins trade per-op latency for lock headroom.
    def monitor_poll_s(self, heartbeat_interval_s, peer_timeout_s,
                       world) -> float:
        base = max(min(heartbeat_interval_s, peer_timeout_s / 4, 0.05),
                   0.005)
        return base * max(1.0, world / 128)

    def supervisor_poll_s(self, world: int) -> float:
        return 0.02 * max(1.0, world / 256)

    def barrier_poll_s(self) -> float:
        # No world argument on this hook, but the beat table holds one
        # entry per live member — stretch by it so a 512-rank barrier
        # (each poll copies the whole table) doesn't burn the single
        # CI core on 256k lock acquisitions per second.  Unlocked
        # len() is safe (GIL) and only tunes a poll interval.
        return 0.002 * max(1.0, len(self.hub.beats) / 128)


# ---------------------------------------------------------------------------
# TCP backend — the lossy medium, with the robustness layer
# ---------------------------------------------------------------------------


class _InFlight:
    """Reservation slot for a mutating op being applied: duplicates
    arriving while the original is in flight wait on it instead of
    re-applying."""

    def __init__(self):
        self._done = threading.Event()
        self._claimed = False
        self._lock = threading.Lock()
        self.result = None
        self.error: BaseException | None = None

    def claim(self) -> bool:
        with self._lock:
            was = self._claimed
            self._claimed = True
            return not was

    def finish(self, result) -> None:
        self.result = result
        self._done.set()

    def fail(self, exc: BaseException) -> None:
        self.error = exc
        self._done.set()

    def wait(self, timeout_s: float):
        if not self._done.wait(timeout_s):
            raise TimeoutError("duplicate op still in flight")
        if self.error is not None:
            raise RuntimeError(
                f"original delivery failed: {self.error}")
        return self.result


class _TcpHandler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        # Per-connection timeout: a wedged client must not pin a
        # handler thread forever (dmlcheck DML012).
        self.request.settimeout(self.server.io_timeout_s)
        try:
            line = self.rfile.readline(_MAX_LINE)
        except OSError:
            return
        if not line:
            return
        try:
            req = json.loads(line)
            result = self.server.dispatch(req)
            resp = {"ok": True, "result": result}
        except Exception as exc:  # surfaced to the client as an error
            resp = {"ok": False, "error": f"{type(exc).__name__}: {exc}"}
        with contextlib.suppress(OSError):
            self.wfile.write((json.dumps(resp) + "\n").encode("utf-8"))


class _TcpServerCore(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class TcpGangServer:
    """The rank-0-side gang state server: a threaded stdlib TCP server
    speaking newline-delimited JSON, holding its state in an
    :class:`InProcHub` (optionally ledger-mirrored to ``mirror_dir``).

    On a real pod this runs on rank 0 / the controller host; in the
    local launcher (``cli/gang.py --gang-transport tcp``) the
    supervisor process hosts it and hands workers the address.

    Idempotency: every mutating request carries an ``op_id``; the
    server remembers the last :data:`_DEDUP_CAP` ids with their
    results, so a client retry after a lost RESPONSE (the request
    actually landed) — or a network-duplicated delivery — returns the
    original result instead of double-firing.  The abort latch, join
    overwrite, and consume are idempotent by construction; the dedup
    store is what extends exactly-once to the ledger appends and makes
    ``declare_abort``'s first-writer verdict stable under retry.
    """

    _DEDUP_CAP = 65536

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 mirror_dir=None, io_timeout_s: float = 10.0,
                 listen: bool = True):
        self.hub = InProcHub(mirror_dir=mirror_dir)
        self._state = InProcTransport(self.hub)
        self._seen: OrderedDict[str, object] = OrderedDict()
        self._seen_lock = threading.Lock()
        self.io_timeout_s = float(io_timeout_s)
        # ``listen=False`` builds the dispatch/dedup state machine with
        # no socket at all — the layer-3 explorer drives ``dispatch()``
        # directly, so exploring a schedule never binds a port.
        self._server = None
        if listen:
            self._server = _TcpServerCore((host, port), _TcpHandler)
            self._server.dispatch = self.dispatch
            self._server.io_timeout_s = self.io_timeout_s
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> str:
        if self._server is None:
            raise RuntimeError("server built with listen=False has no "
                               "address")
        host, port = self._server.server_address[:2]
        return f"{host}:{port}"

    def start(self) -> "TcpGangServer":
        if self._server is None:
            raise RuntimeError("server built with listen=False cannot "
                               "serve")
        if self._thread is not None:
            raise RuntimeError("server already started")
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.05},
            name="gang-tcp-server", daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is None:
            return
        if self._thread is not None:
            self._server.shutdown()
            self._thread.join(timeout=5)
            self._thread = None
        self._server.server_close()

    def local_transport(self, events=None) -> InProcTransport:
        """A direct (no-socket) handle on the server's hub for the
        process hosting it — the supervisor must never compete with
        128 workers for its own socket.  Labeled ``tcp`` (it is the
        server side of the tcp control plane); its stats count the
        supervisor's ops, while each worker's retry/timeout counts land
        in that worker's own telemetry registry."""
        handle = InProcTransport(self.hub, events=events)
        handle.backend = "tcp"
        return handle

    # -- request dispatch ------------------------------------------------
    def dispatch(self, req: dict):
        op = req.get("op")
        op_id = req.get("op_id")
        if op_id is None:
            return self._apply(op, req)
        # The op_id is RESERVED under the lock before the apply runs: a
        # duplicate racing a still-in-flight original (client timeout
        # shorter than a slow mirror fsync) must wait for the original's
        # result, never re-apply — check-then-apply outside the lock
        # would double-append and break exactly-once.
        _sched_point("tcp:seen:w:reserve")
        with self._seen_lock:
            if op_id in self._seen:  # membership: a result may be None
                entry = self._seen[op_id]
            else:
                entry = _InFlight()
                self._seen[op_id] = entry
        if isinstance(entry, _InFlight):
            if entry.claim():  # this thread owns the apply
                _sched_point("tcp:apply")
                try:
                    result = self._apply(op, req)
                except BaseException as exc:
                    # A failed apply must not poison the id: drop the
                    # reservation so the client's retry re-applies.
                    with self._seen_lock:
                        self._seen.pop(op_id, None)
                    entry.fail(exc)
                    raise
                entry.finish(result)
                _sched_point("tcp:seen:w:store")
                with self._seen_lock:
                    self._seen[op_id] = result
                    self._evict_seen_locked()
                return result
            if _sched_block("tcp:inflight:wait", entry._done.is_set):
                # The scheduler descheduled this thread until the
                # original settled; the zero-timeout wait just fetches
                # the result (or re-raises the original's failure).
                return entry.wait(0)
            return entry.wait(self.io_timeout_s)
        return entry  # already-completed result, cached

    def _evict_seen_locked(self) -> None:
        """Trim the dedup store to ``_DEDUP_CAP``, oldest first — but
        NEVER a still-``_InFlight`` reservation (caller holds
        ``_seen_lock``).  Evicting one would forget that its op is
        being applied right now, so the op's retry would miss the
        dedup store, re-apply, and break exactly-once; in-flight
        entries rotate to the young end instead (the store runs over
        cap until they settle).  The pre-fix form — a plain
        ``popitem(last=False)`` loop — survives as the layer-3
        mutation-test seed (``analysis/interleave.py``,
        ``MUTATIONS['dedup-evict']``): the explorer must rediscover
        this bug whenever it is re-introduced."""
        excess = len(self._seen) - self._DEDUP_CAP
        for _ in range(len(self._seen)):
            if excess <= 0:
                break
            op_id, entry = self._seen.popitem(last=False)
            if isinstance(entry, _InFlight):
                self._seen[op_id] = entry
            else:
                excess -= 1

    def _apply(self, op: str, req: dict):
        s = self._state
        if op == "ping":
            return "pong"
        if op == "publish_beat":
            rank, payload = int(req["rank"]), req["payload"]
            # A duplicated/reordered beat delivery must not make a dead
            # rank look freshly alive: the version (the reader-side
            # change signature) only advances when the CONTENT changes.
            with self.hub.lock:
                cur = self.hub.beats.get(rank)
                if cur is not None and cur[1] == payload:
                    return None
            s._do_publish_beat(rank, payload)
            return None
        if op == "read_beats":
            return {str(r): [v, p]
                    for r, (v, p) in s._do_read_beats().items()}
        if op == "read_beat":
            entry = s._do_read_beat(int(req["rank"]))
            return None if entry is None else [entry[0], entry[1]]
        if op == "declare_abort":
            return s._do_declare_abort(req["reason"], req["by_rank"],
                                       req.get("peer"))
        if op == "read_abort":
            return s._do_read_abort()
        if op == "announce_join":
            s._do_announce_join(int(req["rank"]), req["payload"])
            return None
        if op == "read_joins":
            return {str(r): p for r, p in s._do_read_joins().items()}
        if op == "consume_join":
            s._do_consume_join(int(req["rank"]))
            return None
        if op == "write_restore":
            s._do_write_restore(int(req["rank"]), req["steps"])
            return None
        if op == "read_restore":
            steps = s._do_read_restore(int(req["rank"]))
            return None if steps is None else sorted(steps)
        if op == "append_health":
            s._do_append_health(req["payload"])
            return None
        if op == "read_health":
            return s._do_read_health()
        if op == "append_fault":
            s._do_append_fault(req["payload"])
            return None
        if op == "read_faults":
            return s._do_read_faults()
        if op == "append_consumed":
            s._do_append_consumed(int(req["rank"]), req["payload"])
            return None
        if op == "read_consumed":
            rank = req.get("rank")
            return s._do_read_consumed(
                None if rank is None else int(rank))
        if op == "clear":
            self.hub.clear(bool(req["restore_records"]),
                           bool(req["fault_ledger"]))
            return None
        if op == "push_request":
            s._do_push_request(int(req["rank"]), req["payload"])
            return None
        if op == "take_requests":
            return s._do_take_requests(int(req["rank"]),
                                       int(req["max_n"]))
        if op == "post_result":
            version = req.get("version")
            return s._do_post_result(
                int(req["rank"]), int(req["epoch"]), req["payload"],
                None if version is None else int(version))
        if op == "take_results":
            return s._do_take_results(int(req["max_n"]))
        if op == "set_drain":
            s._do_set_drain(int(req["rank"]), bool(req["draining"]))
            return None
        if op == "set_role":
            s._do_set_role(int(req["rank"]), req["role"])
            return None
        if op == "set_weights":
            s._do_set_weights(int(req["rank"]), int(req["version"]),
                              req.get("meta") or {})
            return None
        if op == "commit_weights":
            return s._do_commit_weights(int(req["rank"]),
                                        int(req["version"]))
        if op == "retire_replica":
            return s._do_retire_replica(int(req["rank"]))
        if op == "read_serving":
            rank = req.get("rank")
            state = s._do_read_serving(
                None if rank is None else int(rank))
            if rank is None:
                state = dict(state,
                             replicas={str(r): rec for r, rec
                                       in state["replicas"].items()})
            return state
        raise ValueError(f"unknown transport op {op!r}")


class TcpTransport(GangTransport):
    """A gang member's client on a :class:`TcpGangServer` — the lossy
    medium, so every call carries the robustness layer:

    - **per-op timeout**: every socket op (connect, send, read) is
      bounded by ``timeout_s`` — no call can hang a monitor thread;
    - **bounded retry, backoff + jitter**: up to ``max_tries`` attempts
      with exponential backoff (``backoff_s * 2**k``) times a random
      0.5-1.5 jitter factor, so 128 clients recovering from one server
      hiccup do not re-arrive in lockstep;
    - **idempotent delivery**: mutating requests carry an ``op_id``
      (unique per logical operation, REUSED across its retries) the
      server deduplicates — a retry after a lost response or a
      fault-injected duplicate can never double-append or re-admit;
    - **connection loss as peer-death evidence**: retries exhausted →
      :class:`TransportError`, which ``GangCoordinator`` escalates to a
      self-abort once the outage outlives ``peer_timeout_s`` (a rank
      partitioned off the gang IS a dead peer, seen from inside).

    ``chaos``: an optional ``runtime/faults.py::TransportChaos`` plan
    injecting drop/delay/duplicate/partition at the send boundary —
    how the retry/idempotency claims are tested rather than asserted.
    """

    backend = "tcp"

    def __init__(self, address: str, events=None, *,
                 timeout_s: float = 2.0, max_tries: int = 4,
                 backoff_s: float = 0.05, chaos=None,
                 client_id: str | None = None):
        super().__init__(events=events)
        host, _, port_s = address.rpartition(":")
        if not host or not port_s.isdigit():
            raise ValueError(
                f"bad gang transport address {address!r} "
                "(expected host:port)")
        self.address = (host, int(port_s))
        self.timeout_s = float(timeout_s)
        self.max_tries = int(max_tries)
        self.backoff_s = float(backoff_s)
        self.chaos = chaos
        # Unique per INSTANCE, not per process: several clients in one
        # process (worker + monitor + tools) must never collide in the
        # server's op_id dedup space.
        self._id = client_id or (
            f"{socket.gethostname()}.{os.getpid()}."
            f"{uuid.uuid4().hex[:12]}")
        self._seq = 0
        self._seq_lock = threading.Lock()
        # Jitter only — never used for anything that must reproduce.
        self._rng = random.Random()

    # -- wire ------------------------------------------------------------
    def _next_op_id(self) -> str:
        with self._seq_lock:
            self._seq += 1
            return f"{self._id}.{self._seq}"

    def _roundtrip(self, req: dict):
        data = (json.dumps(req) + "\n").encode("utf-8")
        with socket.create_connection(self.address,
                                      timeout=self.timeout_s) as sock:
            sock.settimeout(self.timeout_s)
            sock.sendall(data)
            f = sock.makefile("rb")
            line = f.readline(_MAX_LINE)
        if not line:
            raise TransportError("gang server closed the connection")
        resp = json.loads(line)
        if not resp.get("ok"):
            raise TransportError(
                f"gang server rejected {req.get('op')}: "
                f"{resp.get('error')}")
        return resp.get("result")

    def _call(self, op: str, **fields):
        req = {"op": op, **fields}
        if op in _MUTATING_OPS:
            # ONE op_id per logical operation, reused by every retry:
            # the server-side dedup is what turns at-least-once
            # delivery into exactly-once application.
            req["op_id"] = self._next_op_id()
        last: Exception | None = None
        for attempt in range(self.max_tries):
            if attempt:
                self._count_retry()
                sleep_s = (self.backoff_s * (2 ** (attempt - 1))
                           * (0.5 + self._rng.random()))
                time.sleep(sleep_s)
            act = self.chaos.plan(op) if self.chaos is not None else None
            if act is not None:
                if act.partitioned:
                    raise TransportError(
                        f"{op}: channel severed (injected partition)")
                if act.delay_s:
                    time.sleep(act.delay_s)
                if act.drop:
                    # The medium ate the request: to the client this is
                    # indistinguishable from a timeout.
                    self._count_timeout()
                    last = TransportError(
                        f"{op}: request dropped (injected)")
                    continue
            try:
                if act is not None and act.duplicate:
                    # The medium delivered it twice: same op_id, so the
                    # server must apply it once.
                    self._roundtrip(dict(req))
                return self._roundtrip(req)
            except socket.timeout as exc:
                self._count_timeout()
                last = exc
            except (TransportError, OSError, ValueError) as exc:
                # TransportError here covers a response lost to a clean
                # connection close and transient server-side errors
                # (e.g. a duplicate that outwaited its in-flight
                # original) — all retry-safe BECAUSE the op_id rides
                # every retry: the dedup layer turns the re-send into a
                # result fetch, never a re-apply.  A deterministic
                # rejection just burns the bounded retry budget before
                # surfacing.
                last = exc
        raise TransportError(
            f"{op} failed after {self.max_tries} tries against "
            f"{self.address[0]}:{self.address[1]}: {last}")

    # -- operations ------------------------------------------------------
    def _do_publish_beat(self, rank, payload):
        self._call("publish_beat", rank=rank, payload=payload)

    def _do_read_beat(self, rank):
        entry = self._call("read_beat", rank=rank)
        return None if entry is None else (entry[0], entry[1])

    def _do_read_beats(self):
        raw = self._call("read_beats")
        return {int(r): (v_p[0], v_p[1]) for r, v_p in raw.items()}

    def _do_declare_abort(self, reason, by_rank, peer):
        return bool(self._call("declare_abort", reason=reason,
                               by_rank=by_rank, peer=peer))

    def _do_read_abort(self):
        return self._call("read_abort")

    def _do_announce_join(self, rank, payload):
        self._call("announce_join", rank=rank, payload=payload)

    def _do_read_joins(self):
        return {int(r): p
                for r, p in self._call("read_joins").items()}

    def _do_consume_join(self, rank):
        self._call("consume_join", rank=rank)

    def _do_write_restore(self, rank, steps):
        self._call("write_restore", rank=rank, steps=steps)

    def _do_read_restore(self, rank):
        steps = self._call("read_restore", rank=rank)
        return None if steps is None else {int(s) for s in steps}

    def _do_append_health(self, payload):
        self._call("append_health", payload=payload)

    def _do_read_health(self):
        return self._call("read_health")

    def _do_append_fault(self, entry):
        self._call("append_fault", payload=entry)

    def _do_read_faults(self):
        return self._call("read_faults")

    def _do_append_consumed(self, orig_rank, payload):
        self._call("append_consumed", rank=orig_rank, payload=payload)

    def _do_read_consumed(self, orig_rank):
        return self._call("read_consumed", rank=orig_rank)

    def _do_clear(self, restore_records, fault_ledger):
        self._call("clear", restore_records=restore_records,
                   fault_ledger=fault_ledger)

    # serving channels — all mutating ops ride the op_id dedup, so a
    # retried take/post is a result fetch, never a second pop/append.
    def _do_push_request(self, replica, payload):
        self._call("push_request", rank=replica, payload=payload)

    def _do_take_requests(self, replica, max_n):
        return self._call("take_requests", rank=replica, max_n=max_n)

    def _do_post_result(self, replica, epoch, payload, version):
        return bool(self._call("post_result", rank=replica,
                               epoch=epoch, payload=payload,
                               version=version))

    def _do_take_results(self, max_n):
        return self._call("take_results", max_n=max_n)

    def _do_set_drain(self, replica, draining):
        self._call("set_drain", rank=replica, draining=draining)

    def _do_set_role(self, replica, role):
        self._call("set_role", rank=replica, role=role)

    def _do_set_weights(self, replica, version, meta):
        self._call("set_weights", rank=replica, version=version,
                   meta=meta)

    def _do_commit_weights(self, replica, version):
        return bool(self._call("commit_weights", rank=replica,
                               version=version))

    def _do_retire_replica(self, replica):
        return self._call("retire_replica", rank=replica)

    def _do_read_serving(self, replica):
        state = self._call("read_serving", rank=replica)
        if replica is None:
            state = dict(state,
                         replicas={int(r): rec for r, rec
                                   in state["replicas"].items()})
        return state

    # cadence: each monitor poll is ONE batched read_beats round trip,
    # and the interval grows with the world so the whole gang's request
    # rate on the rank-0 host stays bounded (~world/poll ≈ 500/s at any
    # size) instead of quadratic — the self-DoS fix of ISSUE 12.
    _PER_RANK_BUDGET_S = 0.002

    def monitor_poll_s(self, heartbeat_interval_s, peer_timeout_s,
                       world) -> float:
        base = min(heartbeat_interval_s, peer_timeout_s / 4)
        return min(max(base, self._PER_RANK_BUDGET_S * world),
                   peer_timeout_s / 4)

    def supervisor_poll_s(self, world: int) -> float:
        return max(0.2, self._PER_RANK_BUDGET_S * world)

    def barrier_poll_s(self) -> float:
        return max(0.05, self._PER_RANK_BUDGET_S * 8)


# ---------------------------------------------------------------------------
# Factory
# ---------------------------------------------------------------------------


def make_transport(backend: str, *, gang_dir=None, address=None,
                   hub: InProcHub | None = None, events=None,
                   bind_epoch: bool = True, chaos=None,
                   timeout_s: float = 2.0) -> GangTransport:
    """Build a transport from launcher-level selection flags:

    - ``file``: requires ``gang_dir`` (the historical default);
    - ``inproc``: requires ``hub`` (one per gang, shared by every
      member thread; ``bind_epoch`` pins worker handles to the current
      attempt — see :class:`InProcTransport`);
    - ``tcp``: requires ``address`` (``host:port`` of the gang server).
    """
    if backend == "file":
        if gang_dir is None:
            raise ValueError("file transport requires gang_dir")
        return FileTransport(gang_dir, events=events)
    if backend == "inproc":
        if hub is None:
            raise ValueError("inproc transport requires a shared hub")
        return InProcTransport(hub, events=events, bind_epoch=bind_epoch)
    if backend == "tcp":
        if address is None:
            raise ValueError("tcp transport requires address host:port")
        return TcpTransport(address, events=events, chaos=chaos,
                            timeout_s=timeout_s)
    raise ValueError(
        f"unknown gang transport {backend!r}; choose from "
        f"{TRANSPORT_BACKENDS}")
