"""VGG family for 32×32 CIFAR-10, cfg-driven, TPU-native (Flax linen).

Capability parity with the reference's ``part1/model.py`` (cloned into
part2/2a, part2/2b, part3):

- cfg table exposing VGG11/13/16/19 (``part1/model.py:3-8``; the reference
  only wires up ``VGG11()`` at ``:49-50`` — we expose all four).
- 3×3 stride-1 pad-1 convs with bias, ReLU, 2×2 max-pools
  (``part1/model.py:11-27``).
- optional BatchNorm: commented out in part1/2a/2b (``part1/model.py:24``;
  the report removed it because unsynced running stats caused cross-node
  accuracy drift), **enabled** in part3 (``part3/model.py:24``).  Here it is
  a constructor flag — `use_bn=True` reproduces part3's model; the
  running stats live in the `batch_stats` collection and are axis-synced
  by the distributed train step (the reference's per-node unsynced stats
  were a quirk its report flagged as causing accuracy drift).
- single Linear(512→10) head on the flattened 1×1×512 feature map
  (``part1/model.py:38-46``).

TPU-first notes: NHWC layout (XLA:TPU's native conv layout), optional
bfloat16 compute (params stay fp32; casts fuse into the convs so the MXU
runs bf16 while the optimizer sees fp32), no Python control flow dependent
on data — the whole forward traces to one fusable XLA graph.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from distributed_machine_learning_tpu.models.initializers import (
    make_torch_bias_init,
    torch_kernel_init,
)

# Reference cfg table (part1/model.py:3-8): ints are conv output channels,
# 'M' is a 2×2 max-pool.
_cfg: dict[str, Sequence] = {
    # Narrow VGG-shaped net for the test suite: same depth-of-structure
    # (conv/BN/relu blocks, 5 pools, flatten+fc) at ~1/1000 the params,
    # so strategy-math tests (whose invariants are model-independent)
    # compile in seconds on the 1-core test host instead of minutes.
    # Not part of the reference cfg table (part1/model.py:3-8).
    "VGGTEST": [8, "M", 16, "M", 16, "M", 16, "M", 16, "M"],
    "VGG11": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "VGG13": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "VGG16": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
              512, 512, 512, "M"],
    "VGG19": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
              512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(nn.Module):
    """VGG for NHWC 3-channel 32×32 input, `num_classes` logits.

    Attributes:
      name_cfg: one of VGG11/VGG13/VGG16/VGG19.
      use_bn: part3 parity flag (BatchNorm2d after each conv,
        ``part3/model.py:24``); off reproduces part1/2a/2b.
      num_classes: classifier width (reference: 10).
      compute_dtype: activations/matmul dtype; bfloat16 targets the MXU,
        float32 reproduces the reference numerics.
    """

    name_cfg: str = "VGG11"
    use_bn: bool = False
    num_classes: int = 10
    compute_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.astype(self.compute_dtype)
        in_channels = 3
        for layer_cfg in _cfg[self.name_cfg]:
            if layer_cfg == "M":
                x = nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))
            else:
                x = nn.Conv(
                    features=layer_cfg,
                    kernel_size=(3, 3),
                    strides=(1, 1),
                    padding=1,
                    use_bias=True,
                    kernel_init=torch_kernel_init,
                    bias_init=make_torch_bias_init(9 * in_channels),
                    dtype=self.compute_dtype,
                )(x)
                if self.use_bn:
                    # part3/model.py:24 — torch BatchNorm2d defaults:
                    # eps=1e-5, momentum=0.1 (torch's "momentum" is the
                    # update fraction for running stats; flax's `momentum`
                    # is the retain fraction, hence 0.9).
                    x = nn.BatchNorm(
                        use_running_average=not train,
                        momentum=0.9,
                        epsilon=1e-5,
                        dtype=self.compute_dtype,
                    )(x)
                x = nn.relu(x)
                in_channels = layer_cfg
        # part1/model.py:43-45: flatten (1×1×512 after five pools) + fc1.
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(
            features=self.num_classes,
            kernel_init=torch_kernel_init,
            bias_init=make_torch_bias_init(512),
            dtype=self.compute_dtype,
            name="fc1",
        )(x)
        # Logits in fp32: the loss's logsumexp wants full precision even
        # when the trunk ran in bf16.
        return x.astype(jnp.float32)


def VGG11(**kw) -> VGG:
    """Factory matching the reference's only exposed model (part1/model.py:49-50)."""
    return VGG(name_cfg="VGG11", **kw)


def VGGTest(**kw) -> VGG:
    """Narrow VGG-shaped net for fast-compiling tests (see _cfg note)."""
    return VGG(name_cfg="VGGTEST", **kw)


def VGG13(**kw) -> VGG:
    return VGG(name_cfg="VGG13", **kw)


def VGG16(**kw) -> VGG:
    return VGG(name_cfg="VGG16", **kw)


def VGG19(**kw) -> VGG:
    return VGG(name_cfg="VGG19", **kw)
