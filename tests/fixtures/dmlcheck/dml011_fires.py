# dmlcheck-virtual-path: distributed_machine_learning_tpu/train/fixture.py
"""DML011 firing case: a hard exit outside runtime/ — skips atexit,
buffered IO, and telemetry flush."""
import os


def give_up(msg):
    print(msg)
    os._exit(1)
