"""LM checkpoint/resume through cli.lm and serving through cli.generate
— the train → save → resume → generate loop a user of the framework
actually runs (the LM-side analogue of the CNN parts' --ckpt-dir
coverage in test_checkpoint.py)."""

import os

import pytest


def _corpus(tmp_path):
    d = tmp_path / "corpus"
    d.mkdir()
    (d / "a.txt").write_text("hello tpu world. " * 200)
    return str(d)


def test_lm_train_save_resume_generate(tmp_path, capsys):
    from distributed_machine_learning_tpu.cli import generate, lm

    ck = str(tmp_path / "ck")
    common = ["--parallel", "dp", "--d-model", "32", "--n-layers", "1",
              "--n-heads", "2", "--seq-len", "32", "--batch-size", "8",
              "--max-iters", "2", "--data-dir", _corpus(tmp_path),
              "--ckpt-dir", ck]
    lm.main(common)
    out = capsys.readouterr().out
    assert "Saved checkpoint to" in out
    assert os.path.isdir(ck)

    lm.main(common + ["--resume"])
    out = capsys.readouterr().out
    assert "Resumed from" in out and "step 2" in out

    generate.main([
        "--ckpt-dir", ck, "--prompt", "hel", "--max-new-tokens", "8",
        "--temperature", "0", "--d-model", "32", "--n-layers", "1",
        "--n-heads", "2", "--compute-dtype", "float32",
    ])
    out = capsys.readouterr().out
    assert "restored" in out
    # The untrained-ish model may emit line-break bytes; assert the
    # prompt-prefixed output line exists rather than parsing the tail.
    assert any(line.startswith("hel") for line in out.splitlines())


def test_lm_resume_optimizer_mismatch_raises(tmp_path):
    from distributed_machine_learning_tpu.cli import lm

    ck = str(tmp_path / "ck")
    base = ["--parallel", "dp", "--d-model", "32", "--n-layers", "1",
            "--n-heads", "2", "--seq-len", "16", "--batch-size", "8",
            "--max-iters", "2", "--ckpt-dir", ck]
    lm.main(base + ["--optimizer", "adamw"])
    with pytest.raises(ValueError, match="matching optimizer"):
        lm.main(base + ["--optimizer", "sgd", "--resume"])


def test_lm_flat_fsdp_ckpt_refused(tmp_path):
    from distributed_machine_learning_tpu.cli import lm

    with pytest.raises(ValueError, match="fsdp_pl"):
        lm.main(["--parallel", "fsdp", "--d-model", "32", "--n-layers", "1",
                 "--n-heads", "2", "--seq-len", "16", "--batch-size", "8",
                 "--max-iters", "2", "--ckpt-dir", str(tmp_path / "ck")])


def test_lm_resume_with_adjusted_lr(tmp_path):
    """Resuming with a different learning rate (same optimizer) is a
    routine operation — the static config must not poison the
    restored-state tree_map."""
    from distributed_machine_learning_tpu.cli import lm

    ck = str(tmp_path / "ck")
    base = ["--parallel", "dp", "--d-model", "32", "--n-layers", "1",
            "--n-heads", "2", "--seq-len", "16", "--batch-size", "8",
            "--max-iters", "2", "--ckpt-dir", ck]
    lm.main(base)
    lm.main(base + ["--resume", "--lr", "0.05"])


def test_lm_pp_layout_mismatch_refused(tmp_path):
    """A pipeline checkpoint's block stacking is schedule-dependent but
    structurally identical — resuming under a different layout must be
    refused, not silently load permuted layers."""
    from distributed_machine_learning_tpu.cli import lm

    ck = str(tmp_path / "ck")
    base = ["--parallel", "pp", "--d-model", "32", "--n-layers", "16",
            "--n-heads", "2", "--seq-len", "16", "--batch-size", "8",
            "--microbatches", "2", "--max-iters", "2", "--ckpt-dir", ck]
    lm.main(base + ["--pp-schedule", "interleaved"])
    with pytest.raises(ValueError, match="layout"):
        lm.main(base + ["--pp-schedule", "1f1b", "--resume"])
    # Same layout resumes fine.
    lm.main(base + ["--pp-schedule", "interleaved", "--resume"])


def test_pp_chunks_guarded(tmp_path):
    from distributed_machine_learning_tpu.cli import lm

    with pytest.raises(ValueError, match="pp-chunks"):
        lm.main(["--parallel", "pp", "--pp-schedule", "1f1b",
                 "--pp-chunks", "4", "--d-model", "32", "--n-layers", "8",
                 "--n-heads", "2", "--seq-len", "16", "--batch-size", "8",
                 "--max-iters", "2"])
