"""Language-model training entrypoint — every parallelism scheme behind
one flag.

The reference's CLI surface only trains its CNN (SURVEY.md §1); this
entrypoint gives the transformer stack the same driveable surface, with
``--parallel`` selecting how the step distributes over the mesh:

  dp       data parallelism (replicated params, pmean grads)
  fsdp     ZeRO-3 sharded data parallelism, flat-vector layout — params
           + optimizer state 1/N per device, one whole-model all-gather
           up front (parallel/fsdp.py); pair with adamw, whose fp32
           moments are the memory ZeRO shards
  fsdp_pl  ZeRO-3, per-layer GSPMD layout — each leaf sharded over the
           data axis; XLA gathers weights at their use site and
           overlaps layer i+1's gather with layer i's compute
           (parallel/fsdp_perlayer.py)
  ring     context parallelism — ppermute ring attention over the
           sequence axis (ops/ring_attention.py)
  ulysses  context parallelism — all-to-all head re-sharding
           (ops/ulysses.py)
  tp       tensor parallelism — Megatron layout via GSPMD
           (parallel/tensor_parallel.py)
  pp       pipeline parallelism — ppermute pipeline; --pp-schedule
           picks 1f1b (default: one backward per forward, O(P)
           activation memory, parallel/pipeline_1f1b.py), interleaved
           (--pp-chunks virtual stages per device, bubble
           (P-1)/(v*M+P-1), parallel/pipeline_interleaved.py), or
           gpipe (all-forward-then-all-backward, parallel/pipeline.py)
  3d       data × pipeline × tensor composed
           (parallel/parallel3d.py)
  ep       expert parallelism — Switch-routed MoE transformer, experts
           sharded over an expert axis, batch over the rest
           (parallel/expert_parallel.py, models/moe.py)

Data is a deterministic synthetic byte stream (seeded from the shared
69143) — the reference's CIFAR runs are likewise about the training
machinery, not the dataset.  The measurement protocol is the reference's:
``--max-iters`` capped, iteration 0 excluded from timing, loss printed
every 20 iterations, total/average summary at the end
(``part1/main.py:32-58``).
"""

from __future__ import annotations

import numpy as np
import jax

from distributed_machine_learning_tpu.cli.common import SEED
from distributed_machine_learning_tpu.models.transformer import TransformerLM
from distributed_machine_learning_tpu.runtime.distributed import (
    initialize_from_flags,
)
from distributed_machine_learning_tpu.runtime.mesh import make_mesh
from distributed_machine_learning_tpu.train.loop import train_epoch
from distributed_machine_learning_tpu.utils.logging import rank0_print


def make_parser():
    import argparse

    from distributed_machine_learning_tpu.cli.common import (
        add_node_flags,
        add_telemetry_flags,
    )

    p = argparse.ArgumentParser(description=__doc__)
    add_node_flags(p)
    add_telemetry_flags(p)
    p.add_argument("--parallel", default="dp",
                   choices=["dp", "ring", "ulysses", "fsdp", "fsdp_pl",
                            "tp", "pp", "3d", "ep"])
    p.add_argument("--n-experts", dest="n_experts", default=8, type=int,
                   help="MoE experts (--parallel ep only)")
    p.add_argument("--capacity-factor", dest="capacity_factor", default=1.25,
                   type=float, help="MoE expert capacity factor (ep only)")
    p.add_argument("--ep", default=None, type=int,
                   help="expert-axis size for --parallel ep (default: the "
                        "greatest common divisor of the device count and "
                        "--n-experts); the remaining devices/ep factor "
                        "becomes the data axis")
    p.add_argument("--moe-impl", dest="moe_impl", default="einsum",
                   choices=["einsum", "grouped"],
                   help="MoE expert compute (--parallel ep only): 'einsum' "
                        "= Switch capacity + drops, GSPMD-sharded over the "
                        "expert axis; 'grouped' = dropless ragged-matmul "
                        "path (ops/grouped.py) — single-device fast path "
                        "(measured 1.33x on the MoE portion on-chip) AND, "
                        "multi-device, the manual shard_map EP step with "
                        "an explicit token all_to_all to expert owners "
                        "(batch shards over data x expert; no attention "
                        "duplication)")
    p.add_argument("--ep-slots", dest="ep_slots", default=None, type=int,
                   help="grouped-EP send slots per owner device (default "
                        "N_local = provably dropless; lower bounds the "
                        "dispatch all-to-all bytes at Switch-style "
                        "per-owner overflow drops -- ops/grouped.py)")
    p.add_argument("--ep-seq", dest="ep_seq", default=1, type=int,
                   help="sequence-axis size for MoE x context parallelism "
                        "(--parallel ep --moe-impl grouped only): shards "
                        "the sequence over a third mesh axis and runs "
                        "ring attention over it while the MoE dispatch "
                        "all_to_alls over the expert axis")
    p.add_argument("--d-model", dest="d_model", default=256, type=int)
    p.add_argument("--n-layers", dest="n_layers", default=4, type=int)
    p.add_argument("--n-heads", dest="n_heads", default=8, type=int)
    p.add_argument("--n-kv-heads", dest="n_kv_heads", default=None, type=int,
                   help="grouped-query attention: K/V heads shared by "
                        "query-head groups (1 = MQA; shrinks the decode "
                        "KV cache by n_heads/n_kv_heads); default = MHA")
    p.add_argument("--vocab", default=256, type=int,
                   help="byte-level vocabulary by default")
    p.add_argument("--seq-len", dest="seq_len", default=256, type=int)
    p.add_argument("--batch-size", dest="batch_size", default=8, type=int,
                   help="global batch (sequences per step)")
    p.add_argument("--max-iters", dest="max_iters", default=40, type=int)
    p.add_argument("--microbatches", default=2, type=int,
                   help="pipeline microbatches (pp/3d)")
    p.add_argument("--ckpt-dir", default=None,
                   help="save the trained state here (orbax, sharded "
                        "global arrays as-is); restores with --resume. "
                        "All schemes except the flat-vector fsdp (whose "
                        "FSDPState is not a TrainState; use fsdp_pl for "
                        "checkpointable ZeRO-3)")
    p.add_argument("--resume", nargs="?", const="latest", default=None,
                   choices=["latest", "auto"],
                   help="restore the latest checkpoint in --ckpt-dir "
                        "before training (same scheme + optimizer as "
                        "the save).  '--resume auto' supervises the run: "
                        "a crash restores the newest complete checkpoint "
                        "and retrains, up to --max-restarts times "
                        "(runtime/supervisor.py; coarse-grained here — "
                        "the LM path checkpoints once, at the end)")
    p.add_argument("--max-restarts", dest="max_restarts", default=3, type=int,
                   help="with --resume auto: restore-and-retry this many "
                        "times before giving up")
    p.add_argument("--guard-nonfinite", dest="guard_nonfinite",
                   action="store_true",
                   help="compile a non-finite-gradient guard into the "
                        "train step: a NaN/Inf gradient skips that update "
                        "(state unchanged, step not counted); "
                        "dp/ring/ulysses schemes")
    p.add_argument("--loss-scale", dest="loss_scale", default="none",
                   choices=["none", "dynamic"],
                   help="'dynamic' enables dynamic loss scaling for the "
                        "bf16 path (train/lm_step.py): loss multiplied by "
                        "an adaptive scale before backward, gradients "
                        "unscaled after; overflow skips the update and "
                        "halves the scale, 200 consecutive good steps "
                        "double it; dp/ring/ulysses schemes")
    p.add_argument("--pp-schedule", dest="pp_schedule", default="1f1b",
                   choices=["1f1b", "gpipe", "interleaved"],
                   help="pipeline schedule (pp only): 1f1b interleaves "
                        "one backward with one forward per tick — O(P) "
                        "activation memory instead of GPipe's O(M) "
                        "(parallel/pipeline_1f1b.py); gpipe is "
                        "all-forward-then-all-backward; interleaved "
                        "gives each device --pp-chunks virtual stages, "
                        "cutting the bubble to (P-1)/(v*M+P-1) "
                        "(parallel/pipeline_interleaved.py)")
    p.add_argument("--pp-chunks", dest="pp_chunks", default=None, type=int,
                   help="virtual stages per device for "
                        "--pp-schedule interleaved (v, default 2); "
                        "n_layers must divide by devices x v")
    p.add_argument("--dp", default=None, type=int,
                   help="data-axis size for --parallel 3d "
                        "(default: devices // (pp*tp))")
    p.add_argument("--pp", default=2, type=int,
                   help="pipe-axis size for --parallel 3d")
    p.add_argument("--tp", default=2, type=int,
                   help="model-axis size for --parallel 3d")
    p.add_argument("--zero1-dp", dest="zero1_dp", action="store_true",
                   help="with --parallel 3d: shard the optimizer moments "
                        "1/dp over the data axis (ZeRO-1 x 3-D, the 4th "
                        "composed axis — parallel/parallel3d.py::"
                        "p3_zero1_moment_spec); update-equivalent to "
                        "plain 3d")
    p.add_argument("--overlap-update", dest="overlap_update",
                   action="store_true",
                   help="overlap-aware sharded weight update (arxiv "
                        "2004.13336): with --parallel fsdp, take the "
                        "parameter gather off the critical path (the "
                        "prefetch protocol of parallel/overlap.py — "
                        "bit-identical trajectory); with --parallel pp "
                        "--pp-schedule gpipe, shard the boundary-module "
                        "update over the pipe axis and ring-gather the "
                        "slices back")
    p.add_argument("--compute-dtype", default="float32",
                   choices=["float32", "bfloat16"])
    from distributed_machine_learning_tpu.train.optimizers import (
        optimizer_names,
    )

    p.add_argument("--optimizer", default="adamw", choices=optimizer_names(),
                   help="LM default is adamw (train/adamw.py); sgd gives "
                        "the reference's torch-semantics update")
    p.add_argument("--lr", default=None, type=float,
                   help="override the optimizer config's learning rate")
    p.add_argument("--fused-update", dest="fused_update",
                   action="store_true",
                   help="run the AdamW update as the fused one-pass Pallas "
                        "kernel (ops/pallas/fused_adamw.py) — moment "
                        "update, bias correction, decay, parameter update "
                        "and the bf16 cast in-register; adamw only")
    p.add_argument("--momentum-dtype", dest="momentum_dtype", default=None,
                   help="SGD momentum-buffer storage dtype (e.g. "
                        "bfloat16): halves optimizer-state memory, the "
                        "term that bounds model depth on one chip; "
                        "update math stays f32 (train/sgd.py; sgd only)")
    p.add_argument("--data-dir", dest="data_dir", default=None, type=str,
                   help="train on real text: every text file under this "
                        "directory becomes a byte-level corpus "
                        "(data/text.py; vocab auto-set to 257 = bytes+BOS); "
                        "default trains on the deterministic synthetic "
                        "stream")
    p.add_argument("--eval-batches", dest="eval_batches", default=0, type=int,
                   help="after training, evaluate perplexity on this many "
                        "windows from a held-out corpus slice (the final "
                        "10%% of tokens is reserved from training when "
                        "--data-dir is set); dp/ring/ulysses/fsdp, "
                        "single-process only; 0 skips)")
    p.add_argument("--fused-ce-chunks", dest="fused_ce_chunks", default=None,
                   type=int,
                   help="compute the loss fused with the lm_head in this "
                        "many vocab chunks (ops/fused_ce.py) — the "
                        "[B,L,vocab] logits are never materialized; "
                        "dp/ring/ulysses modes only")
    p.add_argument("--attn", default="auto",
                   choices=["auto", "dense", "flash"],
                   help="attention kernel: for dp/fsdp, 'auto' picks the "
                        "Pallas flash kernel from 512 context up (the "
                        "measured crossover, docs/PERF.md) and 'dense' "
                        "the XLA fused path; for --parallel ring, "
                        "'auto'/'flash' upgrade the per-chunk math to "
                        "the flash-kernel ring when the per-device chunk "
                        "is big enough, 'dense' pins the einsum ring; "
                        "tp/fsdp_pl/ep honor 'auto'/'flash' via the "
                        "shard_map-wrapped kernel, pp takes explicit "
                        "'flash', 3d and flat fsdp resolve 'auto' to "
                        "dense, ulysses owns its attention")
    p.add_argument("--remat", action="store_true",
                   help="jax.checkpoint each transformer block: activation "
                        "memory drops ~n_layers-fold for ~33%% more FLOPs "
                        "— the long-context enabler (models/transformer.py)")
    p.add_argument("--remat-policy", dest="remat_policy", default="mlp",
                   choices=["mlp", "block"],
                   help="with --remat: 'mlp' checkpoints only the LN2+MLP "
                        "sub-layer (attention residuals incl. flash "
                        "out+lse stay saved — backward never re-runs the "
                        "O(L^2) attention forward); 'block' is whole-block "
                        "remat, the maximal-memory-savings fallback")
    return p


def synthetic_tokens(rng: np.random.Generator, batch: int, seq_len: int,
                     vocab: int):
    """[B, L+1] int32 token block; [:, :-1] feeds, [:, 1:] targets."""
    return rng.integers(0, vocab, (batch, seq_len + 1)).astype(np.int32)


def build(args):
    """(step, state, place, model, params_fn) for the chosen parallelism
    scheme; ``params_fn(state)`` yields the replicated params pytree for
    eval (a gather for fsdp)."""
    import jax.numpy as jnp

    n = jax.device_count()
    dtype = jnp.bfloat16 if args.compute_dtype == "bfloat16" else jnp.float32
    attn = getattr(args, "attn", "auto")
    if args.parallel in ("pp", "fsdp") and attn == "auto":
        # These steps resolve "auto" to the dense path they default to
        # (pp accepts an EXPLICIT --attn flash — its pipe-axis shard_map
        # is fully manual; flat-fsdp's step is dense-only and keeps a
        # loud guard for explicit flash).  tp/fsdp_pl/ep/3d honor auto
        # themselves via the model's flash_mesh shard_map wrap.
        attn = "dense"
    common = dict(
        vocab_size=args.vocab, d_model=args.d_model, n_layers=args.n_layers,
        n_heads=args.n_heads, compute_dtype=dtype, remat=args.remat,
        remat_policy=getattr(args, "remat_policy", "mlp"),
        n_kv_heads=args.n_kv_heads,
        # ring/ulysses overwrite this below; all other modes honor it.
        attn_impl=attn,
    )
    from distributed_machine_learning_tpu.train.optimizers import get_optimizer

    cfg_cls = get_optimizer(args.optimizer)[0]
    if args.pp_chunks is not None and not (
        args.parallel == "pp" and args.pp_schedule == "interleaved"
    ):
        # Checked before the scheme dispatch so the flag cannot be
        # silently ignored under any --parallel value.
        raise ValueError(
            "--pp-chunks applies to --parallel pp with --pp-schedule "
            f"interleaved only (got --parallel {args.parallel}, "
            f"--pp-schedule {args.pp_schedule})"
        )
    if getattr(args, "ep_seq", 1) != 1 and args.parallel != "ep":
        # Same pre-dispatch discipline as --pp-chunks: a flag that only
        # one scheme reads must not be silently ignored by the others.
        raise ValueError(
            "--ep-seq (MoE x context parallelism) applies to --parallel "
            f"ep only (got --parallel {args.parallel})"
        )
    if getattr(args, "ep_slots", None) is not None and not (
        args.parallel == "ep" and args.moe_impl == "grouped"
    ):
        raise ValueError(
            "--ep-slots applies to --parallel ep --moe-impl grouped only "
            f"(got --parallel {args.parallel}, --moe-impl {args.moe_impl})"
        )
    if getattr(args, "zero1_dp", False) and args.parallel != "3d":
        raise ValueError(
            "--zero1-dp (ZeRO-1 x 3-D moment sharding) applies to "
            f"--parallel 3d only (got --parallel {args.parallel}); the "
            "standalone ZeRO-1 scheme is parallel/zero1.py"
        )
    if getattr(args, "overlap_update", False):
        if args.parallel not in ("fsdp", "pp") or (
            args.parallel == "pp" and args.pp_schedule != "gpipe"
        ):
            raise ValueError(
                "--overlap-update applies to --parallel fsdp (prefetch "
                "protocol) or --parallel pp --pp-schedule gpipe "
                "(pipe-sharded boundary update); got --parallel "
                f"{args.parallel}"
                + (f" --pp-schedule {args.pp_schedule}"
                   if args.parallel == "pp" else "")
            )
    cfg_kwargs = {}
    if args.lr is not None:
        cfg_kwargs["learning_rate"] = args.lr
    if args.momentum_dtype is not None:
        if args.optimizer != "sgd":
            raise ValueError(
                "--momentum-dtype applies to --optimizer sgd only "
                "(AdamW keeps fp32 moments; LARS accumulates in the "
                "buffer dtype and refuses narrowing)"
            )
        cfg_kwargs["momentum_dtype"] = args.momentum_dtype
    if getattr(args, "fused_update", False):
        if args.optimizer != "adamw":
            raise ValueError(
                "--fused-update applies to --optimizer adamw only (the "
                "fused kernel is the AdamW rule; got "
                f"--optimizer {args.optimizer})"
            )
        cfg_kwargs["fused"] = True
    opt_config = cfg_cls(**cfg_kwargs)
    if args.fused_ce_chunks and args.parallel not in (
        "dp", "ring", "ulysses", "fsdp", "fsdp_pl"
    ):
        raise ValueError(
            "--fused-ce-chunks applies to the dp/ring/ulysses/fsdp/"
            "fsdp_pl steps only (tp shards the lm_head, pp computes the "
            "loss on the last stage)"
        )
    guard = bool(getattr(args, "guard_nonfinite", False))
    dynamic_scale = getattr(args, "loss_scale", "none") == "dynamic"
    if (guard or dynamic_scale) and args.parallel not in (
        "dp", "ring", "ulysses"
    ):
        # Same pre-dispatch discipline as --pp-chunks: a robustness flag
        # the chosen step doesn't implement must fail loudly, not
        # silently train unguarded.
        raise ValueError(
            "--guard-nonfinite/--loss-scale apply to the replicated "
            f"dp/ring/ulysses steps only (got --parallel {args.parallel})"
        )

    if args.parallel in ("dp", "ring", "ulysses"):
        from distributed_machine_learning_tpu.train.lm_step import (
            init_lm_state,
            make_lm_train_step,
            shard_lm_batch,
        )

        if args.parallel == "dp":
            if args.batch_size % n:
                raise ValueError(
                    f"--batch-size {args.batch_size} must be divisible by "
                    f"the {n}-device data axis"
                )
            mesh = make_mesh(n, ("batch", "seq"), (n, 1))
            model = TransformerLM(**common)
        else:
            if args.seq_len % n:
                raise ValueError(
                    f"--seq-len {args.seq_len} must be divisible by the "
                    f"{n}-device sequence axis ({args.parallel} shards the "
                    "sequence)"
                )
            mesh = make_mesh(n, ("batch", "seq"), (1, n))
            impl = args.parallel
            if args.parallel == "ring" and args.attn in ("auto", "flash"):
                from distributed_machine_learning_tpu.models.transformer import (
                    _ring_flash_wins,
                )
                from distributed_machine_learning_tpu.ops.pallas.flash_attention import (  # noqa: E501
                    _needs_pad,
                )

                # Explicit --attn flash still requires a natively
                # tileable chunk: the ring kernels have no pad/slice
                # wrapper, so an untileable chunk (largest power-of-two
                # divisor < 128) stays on the einsum ring rather than
                # handing Mosaic a block it must reject.
                chunk = args.seq_len // n
                if (args.attn == "flash" and not _needs_pad(chunk)) or (
                    args.attn == "auto" and _ring_flash_wins(chunk)
                ):
                    impl = "ring_flash"
                elif args.attn == "flash":
                    rank0_print(
                        f"WARNING: --attn flash with --parallel ring: "
                        f"per-device chunk {chunk} is not natively "
                        "tileable (largest power-of-two divisor < 128) "
                        "and the ring kernels have no pad path — "
                        "falling back to the einsum ring"
                    )
            model = TransformerLM(**{**common, "attn_impl": impl})
        state = init_lm_state(model, seed=SEED, config=opt_config)
        step = make_lm_train_step(model, mesh=mesh,
                                  fused_ce_chunks=args.fused_ce_chunks,
                                  guard_nonfinite=guard,
                                  dynamic_scale=dynamic_scale)
        place = lambda x, y: shard_lm_batch(mesh, x, y)
        return step, state, place, model, lambda st: st.params

    if args.parallel == "fsdp":
        from jax.sharding import NamedSharding, PartitionSpec as P

        from distributed_machine_learning_tpu.parallel.fsdp import (
            gather_fsdp_params,
            make_fsdp_lm_train_step,
            shard_fsdp_state,
        )
        from distributed_machine_learning_tpu.train.lm_step import init_lm_state

        if args.batch_size % n:
            raise ValueError(
                f"--batch-size {args.batch_size} must be divisible by "
                f"the {n}-device data axis"
            )
        mesh = make_mesh(n)
        model = TransformerLM(**common)
        fstate, unravel, n_elems = shard_fsdp_state(
            init_lm_state(model, seed=SEED, config=opt_config), mesh
        )
        step = make_fsdp_lm_train_step(
            model, mesh, unravel, n_elems,
            fused_ce_chunks=args.fused_ce_chunks,
            overlap=getattr(args, "overlap_update", False),
        )
        sharding = NamedSharding(mesh, P("batch"))
        place = lambda x, y: (
            jax.device_put(x, sharding), jax.device_put(y, sharding)
        )
        params_fn = lambda st: gather_fsdp_params(st, unravel, n_elems)
        return step, fstate, place, model, params_fn

    if args.parallel == "ep":
        from distributed_machine_learning_tpu.models.moe import (
            MoETransformerLM,
        )
        from distributed_machine_learning_tpu.parallel.expert_parallel import (
            init_moe_state,
            make_ep_train_step,
            shard_ep_state,
        )
        from distributed_machine_learning_tpu.parallel.tensor_parallel import (
            shard_tp_batch,
        )

        if args.n_kv_heads is not None and (
            args.n_kv_heads < 1 or args.n_heads % args.n_kv_heads
        ):
            raise ValueError(
                f"--n-kv-heads {args.n_kv_heads} must be a positive "
                f"divisor of --n-heads {args.n_heads}"
            )
        if args.remat and getattr(args, "remat_policy", "mlp") != "mlp":
            # MoETransformerLM implements the selective policy only (the
            # Block-level remat_mlp wrap); dropping 'block' silently
            # would surprise anyone counting on its memory profile.
            raise ValueError(
                "--parallel ep supports --remat-policy mlp only (the "
                "selective LN2+expert-MLP checkpoint); whole-block "
                "remat is not wired through the MoE blocks"
            )
        if args.n_experts < 1:
            raise ValueError(f"--n-experts must be >= 1, got "
                             f"{args.n_experts}")
        if args.ep is None:
            # Largest axis size dividing BOTH the device count and the
            # expert count — the biggest valid default on any host.
            import math

            ep = math.gcd(n, args.n_experts)
        else:
            ep = args.ep
        if ep < 1 or n % ep:
            raise ValueError(
                f"--ep {ep} must be a positive divisor of the device "
                f"count {n}"
            )
        if args.n_experts % ep:
            raise ValueError(
                f"--n-experts {args.n_experts} must be divisible by "
                f"--ep {ep}"
            )
        sp = args.ep_seq
        if sp < 1:
            raise ValueError(f"--ep-seq must be >= 1, got {sp}")
        if sp > 1 and args.moe_impl != "grouped":
            raise ValueError(
                "--ep-seq (MoE x context parallelism) requires "
                "--moe-impl grouped (the manual shard_map step; the "
                "GSPMD einsum step has no sequence axis)"
            )
        if n % (ep * sp):
            raise ValueError(
                f"--ep {ep} x --ep-seq {sp} must divide the device "
                f"count {n}"
            )
        dp = n // (ep * sp)
        if args.batch_size % dp:
            raise ValueError(
                f"--batch-size {args.batch_size} must be divisible by "
                f"the {dp}-device data axis (devices/(ep*ep_seq))"
            )
        model = MoETransformerLM(
            vocab_size=args.vocab, d_model=args.d_model,
            n_layers=args.n_layers, n_heads=args.n_heads,
            n_kv_heads=args.n_kv_heads, remat=args.remat,
            n_experts=args.n_experts, capacity_factor=args.capacity_factor,
            compute_dtype=dtype, attn_impl=attn, moe_impl=args.moe_impl,
        )
        if args.moe_impl == "grouped":
            if n == 1 and sp == 1:
                # Single device: the plain-jit dropless path.
                step = make_ep_train_step(model, mesh=None)
                state = init_moe_state(model, seed=SEED, config=opt_config)
                place = lambda x, y: (jnp.asarray(x), jnp.asarray(y))
                return step, state, place, model, lambda st: st.params
            # Multi-device: the manual shard_map EP step — explicit token
            # all_to_all to expert owners + local ragged_dot (dropless).
            # The batch shards over data × expert (the einsum step
            # replicates activations over the expert axis; this one does
            # not).  With --ep-seq > 1 the sequence shards over a third
            # mesh axis (MoE × context parallelism): attention becomes
            # the ppermute ring — upgraded to the flash-kernel ring
            # exactly like --parallel ring when the per-device chunk
            # tiles natively and the user asked for flash/auto.
            from jax.sharding import NamedSharding, PartitionSpec as P

            from distributed_machine_learning_tpu.parallel.expert_parallel import (  # noqa: E501
                make_ep_grouped_train_step,
            )

            if args.batch_size % (dp * ep):
                raise ValueError(
                    f"--batch-size {args.batch_size} must be divisible "
                    f"by data x expert = {dp * ep} (the EP-grouped step "
                    "shards the batch over both)"
                )
            if sp > 1:
                from distributed_machine_learning_tpu.models.transformer import (  # noqa: E501
                    _ring_flash_wins,
                )

                if args.seq_len % sp:
                    raise ValueError(
                        f"--seq-len {args.seq_len} must be divisible by "
                        f"--ep-seq {sp}"
                    )
                chunk = args.seq_len // sp
                if attn in ("auto", "flash") and _ring_flash_wins(chunk):
                    ring_impl = "ring_flash"
                else:
                    if attn == "flash":
                        rank0_print(
                            f"WARNING: per-device chunk {chunk} does not "
                            "qualify for the flash ring kernels — "
                            "falling back to the einsum ring"
                        )
                    ring_impl = "ring"
                model = model.clone(attn_impl=ring_impl)
                mesh = make_mesh(
                    n, ("batch", "expert", "seq"), (dp, ep, sp)
                )
                step = make_ep_grouped_train_step(
                    model, mesh, seq_axis="seq",
                    slots_per_owner=args.ep_slots,
                )
                batch_spec = P(("batch", "expert"), "seq")
            else:
                mesh = make_mesh(n, ("batch", "expert"), (dp, ep))
                step = make_ep_grouped_train_step(
                    model, mesh, slots_per_owner=args.ep_slots
                )
                batch_spec = P(("batch", "expert"), None)
            state = shard_ep_state(
                init_moe_state(model, seed=SEED, config=opt_config), mesh
            )
            batch_sharding = NamedSharding(mesh, batch_spec)
            place = lambda x, y: (
                jax.device_put(jnp.asarray(x), batch_sharding),
                jax.device_put(jnp.asarray(y), batch_sharding),
            )
            return step, state, place, model, lambda st: st.params
        mesh = make_mesh(n, ("batch", "expert"), (dp, ep))
        step = make_ep_train_step(model, mesh)
        state = shard_ep_state(
            init_moe_state(model, seed=SEED, config=opt_config), mesh
        )
        place = lambda x, y: shard_tp_batch(mesh, x, y)
        return step, state, place, model, lambda st: st.params

    if args.parallel == "fsdp_pl":
        from distributed_machine_learning_tpu.parallel.fsdp_perlayer import (
            make_fsdp_pl_lm_train_step,
            shard_fsdp_pl_state,
        )
        from distributed_machine_learning_tpu.parallel.tensor_parallel import (
            shard_tp_batch,
        )
        from distributed_machine_learning_tpu.train.lm_step import init_lm_state

        if args.batch_size % n:
            raise ValueError(
                f"--batch-size {args.batch_size} must be divisible by "
                f"the {n}-device data axis"
            )
        mesh = make_mesh(n)
        model = TransformerLM(**common)
        step = make_fsdp_pl_lm_train_step(
            model, mesh, fused_ce_chunks=args.fused_ce_chunks
        )
        state = shard_fsdp_pl_state(
            init_lm_state(model, seed=SEED, config=opt_config), mesh
        )
        place = lambda x, y: shard_tp_batch(mesh, x, y)
        return step, state, place, model, lambda st: st.params

    if args.parallel == "tp":
        from distributed_machine_learning_tpu.parallel.tensor_parallel import (
            make_tp_lm_train_step,
            shard_tp_batch,
            shard_tp_state,
        )
        from distributed_machine_learning_tpu.train.lm_step import init_lm_state

        mesh = make_mesh(n, ("batch", "model"), (1, n))
        model = TransformerLM(**common)
        # Build the step first: its validation (n_heads % model-axis size)
        # gives a clear error before any state is placed.
        step = make_tp_lm_train_step(model, mesh)
        state = shard_tp_state(init_lm_state(model, seed=SEED, config=opt_config), mesh)
        place = lambda x, y: shard_tp_batch(mesh, x, y)
        return step, state, place, model, lambda st: st.params

    if args.parallel == "pp":
        from distributed_machine_learning_tpu.parallel.pipeline import (
            init_pipeline_state,
            make_pp_lm_train_step,
            microbatch,
            shard_pp_state,
        )

        mesh = make_mesh(n, ("pipe",))
        model = TransformerLM(**common)
        # Each schedule picks its step builder and (for interleaved, whose
        # block stacking is permuted) its state init; the placement and
        # return tail are shared.
        if args.pp_schedule == "1f1b":
            from distributed_machine_learning_tpu.parallel.pipeline_1f1b import (  # noqa: E501
                make_pp_1f1b_lm_train_step,
            )

            step = make_pp_1f1b_lm_train_step(model, mesh, args.microbatches)
            raw_state = init_pipeline_state(model, seed=SEED,
                                            config=opt_config)
        elif args.pp_schedule == "interleaved":
            from distributed_machine_learning_tpu.parallel.pipeline_interleaved import (  # noqa: E501
                init_interleaved_state,
                make_pp_interleaved_lm_train_step,
            )

            v = args.pp_chunks or 2
            step = make_pp_interleaved_lm_train_step(
                model, mesh, args.microbatches, v
            )
            raw_state = init_interleaved_state(model, n, v, seed=SEED,
                                               config=opt_config)
        else:
            step = make_pp_lm_train_step(
                model, mesh, args.microbatches,
                overlap_update=getattr(args, "overlap_update", False),
            )
            raw_state = init_pipeline_state(model, seed=SEED,
                                            config=opt_config)
        state = shard_pp_state(raw_state, mesh)
        place = lambda x, y: microbatch(x, y, args.microbatches)
        return step, state, place, model, lambda st: st.params

    # 3d
    from distributed_machine_learning_tpu.parallel.parallel3d import (
        init_pipeline_state,
        make_3d_lm_train_step,
        make_3d_mesh,
        microbatch,
        shard_3d_batch,
        shard_3d_state,
    )

    if args.pp < 1 or args.tp < 1:
        raise ValueError(
            f"--pp and --tp must be >= 1, got pp={args.pp} tp={args.tp}"
        )
    if args.dp is not None and args.dp < 1:
        raise ValueError(f"--dp must be >= 1, got {args.dp}")
    dp = args.dp if args.dp is not None else max(n // (args.pp * args.tp), 1)
    if dp * args.pp * args.tp != n:
        raise ValueError(
            f"3-D mesh dp×pp×tp = {dp}×{args.pp}×{args.tp} = "
            f"{dp * args.pp * args.tp} must equal the device count {n} "
            "(a prefix-subset mesh would silently idle the rest)"
        )
    mesh = make_3d_mesh(dp, args.pp, args.tp)
    model = TransformerLM(**common)
    step = make_3d_lm_train_step(model, mesh, args.microbatches,
                                 zero1_dp=args.zero1_dp)
    state = shard_3d_state(
        init_pipeline_state(model, seed=SEED, config=opt_config), mesh,
        zero1_dp=args.zero1_dp,
    )
    place = lambda x, y: shard_3d_batch(mesh, *microbatch(x, y, args.microbatches))
    return step, state, place, model, lambda st: st.params


def main(argv=None) -> None:
    parser = make_parser()
    args = parser.parse_args(argv)
    if args.telemetry_flush_every < 1:
        # Same parse-time validation the CNN parts get from parse_flags.
        parser.error(
            f"--telemetry-flush-every must be >= 1, got "
            f"{args.telemetry_flush_every}"
        )
    from distributed_machine_learning_tpu.telemetry import (
        set_telemetry,
        telemetry_from_flags,
    )

    telemetry = telemetry_from_flags(args)
    prev_telemetry = None
    if telemetry is not None:
        prev_telemetry = set_telemetry(telemetry)
    ctx = initialize_from_flags(args.master_ip, args.rank, args.num_nodes)
    try:
        rank0_print(
            f"lm parallel={args.parallel} devices={jax.device_count()} "
            f"d_model={args.d_model} layers={args.n_layers} "
            f"seq_len={args.seq_len} batch={args.batch_size}"
        )
        # Eval runs for EVERY scheme and process count: params are
        # materialized to host numpy first (a cross-process all-gather
        # on multi-host runs), then every process runs the plain-jit
        # eval step over the identical held-out stream independently —
        # the reference's every-rank eval semantics
        # (``part1/main.py:62-77``).
        will_eval = bool(args.eval_batches)
        corpus = None
        eval_corpus = None
        if args.data_dir is not None:
            from distributed_machine_learning_tpu.data.text import (
                VOCAB_SIZE,
                load_corpus,
            )

            corpus = load_corpus(args.data_dir)
            if args.vocab < VOCAB_SIZE:
                rank0_print(
                    f"--data-dir is byte-level: vocab {args.vocab} -> "
                    f"{VOCAB_SIZE} (256 bytes + BOS)"
                )
                args.vocab = VOCAB_SIZE
            if will_eval:
                from distributed_machine_learning_tpu.data.text import (
                    split_corpus,
                )

                corpus, eval_corpus = split_corpus(
                    corpus, eval_frac=0.1,
                    min_eval_tokens=args.seq_len + 1,
                )
                if len(eval_corpus) == len(corpus):
                    # split_corpus's documented degrade path: don't let
                    # training-set perplexity masquerade as held-out.
                    rank0_print(
                        "WARNING: corpus too small to hold out an eval "
                        "slice — eval will run on in-distribution "
                        "training windows"
                    )
                    rank0_print(
                        f"corpus: {len(corpus)} tokens from {args.data_dir}"
                    )
                else:
                    rank0_print(
                        f"corpus: {len(corpus)} train tokens from "
                        f"{args.data_dir}, {len(eval_corpus)} held-out "
                        "eval tokens"
                    )
            else:
                rank0_print(
                    f"corpus: {len(corpus)} tokens from {args.data_dir}"
                )
        step, state, place, model, params_fn = build(args)
        if telemetry is not None:
            # MFU cost model: ~6·P/token + attention term
            # (utils/flops.py).  Parameter count from the state when it
            # exposes a params tree (every scheme but flat-fsdp, whose
            # state is one sharded vector — throughput-only there).
            params_tree = getattr(state, "params", None)
            if params_tree is not None:
                from distributed_machine_learning_tpu.utils.flops import (
                    transformer_train_flops_per_token,
                )

                n_params = sum(
                    int(np.prod(leaf.shape))
                    for leaf in jax.tree_util.tree_leaves(params_tree)
                    if hasattr(leaf, "shape")
                )
                telemetry.flops_per_token = (
                    transformer_train_flops_per_token(
                        n_params, args.n_layers, args.d_model,
                        args.seq_len,
                    )
                )
        rng = np.random.default_rng(SEED)

        if corpus is not None:
            from distributed_machine_learning_tpu.data.text import (
                TextWindowLoader,
            )

            # Same convention as the synthetic path: every process
            # draws the identical FULL global batch (seeded), and
            # place() shards it over the mesh — so the global data
            # stream is process-count-invariant.  (TextWindowLoader's
            # rank/world striding is the per-host-slice alternative for
            # pipelines that assemble global arrays from local shards.)
            batches = lambda: iter(TextWindowLoader(
                corpus, args.batch_size, args.seq_len, seed=SEED,
            ))
        else:
            def batches():
                for _ in range(args.max_iters):
                    block = synthetic_tokens(
                        rng, args.batch_size, args.seq_len, args.vocab
                    )
                    yield block[:, :-1], block[:, 1:]

        if args.ckpt_dir and args.parallel == "fsdp":
            raise ValueError(
                "--ckpt-dir does not support the flat-vector fsdp state "
                "(FSDPState is not a TrainState); use --parallel fsdp_pl "
                "for checkpointable ZeRO-3"
            )
        # The pipeline schedules permute the stacked block layout but
        # share one tree structure — a resume under the wrong layout
        # would silently load permuted layers, so the layout is tagged
        # into the checkpoint and checked here.
        if args.parallel == "pp" and args.pp_schedule == "interleaved":
            from distributed_machine_learning_tpu.parallel.pipeline_interleaved import (  # noqa: E501
                interleaved_layout_tag,
            )

            run_layout = interleaved_layout_tag(jax.device_count(),
                                                args.pp_chunks or 2)
        elif args.parallel in ("pp", "3d"):
            run_layout = "pp-contiguous"
        else:
            run_layout = None
        def _resume(state):
            """State from the newest complete checkpoint (or unchanged
            when none exists) — re-runnable, so --resume auto can
            restore after every supervised restart."""
            from distributed_machine_learning_tpu.train.checkpoint import (
                checkpoint_config,
                checkpoint_layout,
                latest_checkpoint,
                restore_checkpoint,
            )

            if not args.ckpt_dir:
                raise ValueError("--resume requires --ckpt-dir")
            latest = latest_checkpoint(args.ckpt_dir)
            if latest is None:
                rank0_print(f"No checkpoint under {args.ckpt_dir}; "
                            "starting from scratch.")
            else:
                saved_layout = checkpoint_layout(latest)
                # Pre-tag checkpoints (saved before the layout field
                # existed) are all contiguous stackings — interleaved
                # postdates the tag — so None is compatible with the
                # contiguous layouts (including plain, non-pipeline
                # ones, whose run_layout is None too).
                compatible = saved_layout == run_layout or (
                    saved_layout is None and run_layout in
                    (None, "pp-contiguous")
                )
                if not compatible:
                    raise ValueError(
                        f"checkpoint parameter layout {saved_layout!r} "
                        f"does not match this run's {run_layout!r} "
                        "(same tree structure, permuted layers — "
                        "resume with the schedule/chunks/device-count "
                        "it was saved under)"
                    )
                saved_cfg = checkpoint_config(latest)
                if type(saved_cfg) is not type(state.config):
                    raise ValueError(
                        f"checkpoint was trained with "
                        f"{type(saved_cfg).__name__} but this run uses "
                        f"--optimizer {args.optimizer}; the LM resume "
                        "path requires a matching optimizer (the CNN "
                        "parts' cross-optimizer reset lives in "
                        "cli/common.py)"
                    )
                # The placed state doubles as the abstract template, so
                # sharded leaves (fsdp_pl/tp/pp) restore straight into
                # their shardings.  Leaves the scheme keeps UNCOMMITTED
                # (dp/ring's replicated state under shard_map) must stay
                # uncommitted — a restore pins them to one device, which
                # then conflicts with the mesh-sharded batch at dispatch
                # — so those take a host round-trip back to plain
                # relocatable arrays.
                import jax.numpy as _jnp

                restored = restore_checkpoint(latest, abstract_state=state,
                                              files_verified=True)
                # This run's hyperparameters win (same semantics as the
                # CNN path): carrying the current config also keeps the
                # static config leaves identical for the tree_map below,
                # which would otherwise reject two TrainStates whose
                # configs differ in any field (e.g. a routine --lr
                # adjustment on resume).
                restored = restored.replace(config=state.config)

                from distributed_machine_learning_tpu.train.checkpoint import (  # noqa: E501
                    fresh_buffers,
                )

                def _match_commitment(orig, new):
                    if getattr(orig, "committed", True):
                        return new
                    # fresh_buffers is load-bearing: donating the bare
                    # asarray corrupts the heap when the host buffer
                    # happens to be 64-byte aligned (zero-copied, then
                    # freed with XLA's allocator) — see its docstring.
                    return fresh_buffers(_jnp.asarray(jax.device_get(new)))

                state = jax.tree_util.tree_map(
                    _match_commitment, state, restored
                )
                rank0_print(
                    f"Resumed from {latest} (step "
                    f"{int(jax.device_get(state.step))})"
                )
            return state

        if args.resume:
            state = _resume(state)

        def run_once(s):
            """Train + final save; the unit a supervised restart retries.
            The shared driver owns the measurement protocol (iter-0-
            excluded timing, loss cadence, summary) — one copy for CNN
            and LM."""
            if getattr(args, "loss_scale", "none") == "dynamic":
                from distributed_machine_learning_tpu.train.lm_step import (
                    with_dynamic_scale,
                )

                s = with_dynamic_scale(s)
            s, _ = train_epoch(
                step, s, batches(), place_batch=place,
                max_iters=args.max_iters,
            )
            from distributed_machine_learning_tpu.train.lm_step import (
                unwrap_dynamic_scale,
            )

            s = unwrap_dynamic_scale(s)
            if args.ckpt_dir:
                from distributed_machine_learning_tpu.train.checkpoint import (
                    save_checkpoint,
                )

                path = save_checkpoint(args.ckpt_dir, s, layout=run_layout)
                rank0_print(f"Saved checkpoint to {path}")
            return s

        if args.resume == "auto":
            # Coarse-grained supervision: on any crash, restore the
            # newest complete checkpoint (possibly none — fresh start)
            # and retrain, up to --max-restarts times.  The fine-grained
            # cursor-exact machinery is runtime/supervisor.py::
            # supervised_train; the CNN parts wire it per-epoch.
            from distributed_machine_learning_tpu.runtime.supervisor import (
                run_attempts,
            )

            def attempt(restart_idx):
                s = state
                if restart_idx > 0:
                    _, fresh, *_ = build(args)
                    s = _resume(fresh)
                return run_once(s)

            state = run_attempts(attempt, max_restarts=args.max_restarts)
        else:
            state = run_once(state)
        if args.eval_batches:
            from distributed_machine_learning_tpu.data.text import (
                eval_windows,
            )
            from distributed_machine_learning_tpu.train.lm_step import (
                make_lm_eval_step,
            )
            from distributed_machine_learning_tpu.train.loop import (
                evaluate_lm,
            )

            if corpus is not None:
                ev = eval_windows(eval_corpus, args.batch_size,
                                  args.seq_len, args.eval_batches)
            else:
                ev_rng = np.random.default_rng(SEED + 1)
                ev = (
                    (b[:, :-1], b[:, 1:])
                    for b in (
                        synthetic_tokens(ev_rng, args.batch_size,
                                         args.seq_len, args.vocab)
                        for _ in range(args.eval_batches)
                    )
                )
            params = params_fn(state)
            if args.parallel in ("pp", "3d"):
                # Pipeline layouts stack the blocks along a leading
                # layer dim; restore the per-layer tree the plain model
                # apply expects.  The interleaved schedule stacks in its
                # chunk-major device order, so it has its own inverse.
                if (args.parallel == "pp"
                        and args.pp_schedule == "interleaved"):
                    from distributed_machine_learning_tpu.parallel.pipeline_interleaved import (  # noqa: E501
                        unstack_interleaved,
                    )

                    params = unstack_interleaved(
                        params, args.n_layers, jax.device_count(),
                        args.pp_chunks or 2,
                    )
                else:
                    from distributed_machine_learning_tpu.parallel.pipeline import (  # noqa: E501
                        unstack_lm_params,
                    )

                    params = unstack_lm_params(params, args.n_layers)
            # Materialize params on the host so the eval jit owns its
            # own placement: sharded leaves (fsdp_pl/tp) assemble, and
            # on multi-host runs the cross-process all-gather replaces
            # the old single-process gate — every process then runs the
            # identical eval stream independently, per the reference's
            # every-rank eval loop (``part1/main.py:62-77``).
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils

                # tiled=True is the required mode for global (non-fully-
                # addressable) arrays: it re-jits each leaf to a fully
                # replicated sharding and returns the whole value as
                # host numpy on every process.
                params = multihost_utils.process_allgather(params,
                                                           tiled=True)
            else:
                params = jax.device_get(params)
            import contextlib

            with (telemetry.span("eval") if telemetry is not None
                  else contextlib.nullcontext()):
                evaluate_lm(make_lm_eval_step(model), params, ev)
    finally:
        if telemetry is not None:
            set_telemetry(prev_telemetry)
            telemetry.close()
            rank0_print(f"Telemetry written to {args.telemetry_dir}")
        ctx.shutdown()


if __name__ == "__main__":
    main()
