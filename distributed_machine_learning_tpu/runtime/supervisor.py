"""Auto-resume supervision — compose detection, checkpoints, and retry
into a run that survives.

The pieces existed but nothing composed them (ISSUE: the reference
deadlocks on the first fault; SURVEY.md §5): ``runtime/resilience.py``
detects stalls and preemptions, ``train/checkpoint.py`` writes
crash-consistent saves and ``latest_checkpoint`` skips incomplete ones,
``train/loop.py`` stops at step boundaries.  This module is the ladder
that joins them, the policy every flash-scale data-parallel run
(PAPERS.md: arxiv 1811.05233, 1711.04325) ends up with:

1. **skip** — a non-finite gradient skips one update (the guard inside
   the jitted step, ``train/step.py``/``train/lm_step.py``);
2. **retry** — a data-path exception recreates the iterator with
   backoff (``data/retry.py``);
3. **restart** — anything worse (stall, crash, death mid-checkpoint)
   restores the newest *complete* checkpoint and continues, up to
   ``max_restarts``.

Exactness contract: checkpoints record the data *cursor* (batches
consumed) alongside the step counter, and batch factories are
cursor-keyed, so a restarted run replays exactly the stream the dead run
would have seen — a supervised run with faults lands on the same final
step count, and bit-identical params, as a fault-free run of the same
seed minus the guard-skipped batches (``tests/test_resilience.py``
asserts this end to end).

Stall escalation is two-phase because a hung collective cannot be
un-hung from inside: the watchdog *declares* the stall from its daemon
thread (and can ``os._exit`` for external supervisors — the production
policy); in-process, :class:`RaisingWatchdog` turns the next completed
step boundary into a :class:`StallError` so a *transient* stall (slow
storage, injected sleep) is healed by restart rather than silently
absorbed into one long step.

Everything above heals one process.  :func:`gang_supervise` is the
multi-host rung: a gang of worker processes coordinated through
``runtime/coordinator.py`` (heartbeats, peer-failure detection,
coordinated abort) is restarted *as a group* from the restore point
every rank agrees on — the failure mode where one dead rank would
otherwise leave the others blocked in a collective forever.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Callable

import jax

from distributed_machine_learning_tpu.runtime.faults import (
    FaultEvents,
    FaultInjector,
)
from distributed_machine_learning_tpu.runtime.resilience import Watchdog
from distributed_machine_learning_tpu.utils.logging import rank0_print


class StallError(RuntimeError):
    """A watchdog-declared stall, surfaced at a step boundary so the
    supervisor can restart from the latest checkpoint."""


class RaisingWatchdog(Watchdog):
    """A Watchdog whose ``beat`` raises :class:`StallError` once a stall
    episode has been declared.

    The base class can only report (its thread cannot interrupt a stuck
    step); raising from ``beat`` moves the escalation into the training
    thread at the first step boundary *after* the stall — state is
    consistent there, so the supervisor can restore and retry.  A truly
    infinite hang never reaches a beat; that case is the base class's
    ``exit_code`` fail-fast + external supervisor territory.
    """

    def __init__(self, timeout_s: float, events: FaultEvents | None = None,
                 poll_s: float | None = None):
        def _on_stall(elapsed: float) -> None:
            if events is not None:
                events.stalls += 1
            rank0_print(
                f"[supervisor] stall: no step completed in {elapsed:.1f}s "
                f"(timeout {timeout_s}s); will restart from the latest "
                "checkpoint at the next step boundary"
            )

        super().__init__(timeout_s, on_stall=_on_stall, poll_s=poll_s)

    def beat(self) -> None:
        if self.stalled:
            raise StallError(
                f"step stalled past {self.timeout_s}s; restarting from "
                "the latest checkpoint"
            )
        super().beat()


def run_attempts(attempt: Callable[[int], object], *, max_restarts: int = 3,
                 events: FaultEvents | None = None):
    """Run ``attempt(restart_index)`` until it returns, restarting on any
    Exception up to ``max_restarts`` times.

    The generic retry primitive behind both :func:`supervised_train` and
    the CLI's ``--resume auto``: ``attempt`` owns its own
    restore-from-latest-checkpoint logic (it knows the model/template);
    this owns the policy — count, log, give up loudly.
    KeyboardInterrupt/SystemExit always propagate.
    """
    from distributed_machine_learning_tpu.telemetry import get_telemetry

    if max_restarts < 0:
        raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
    restarts = 0
    while True:
        # Each attempt is one `restart_attempt` span in the trace and
        # one attempt tag on every metrics row it produces — the chaos
        # timeline's backbone: fault → failed span → next attempt's rows
        # appended (never truncating the dead attempt's history).
        tel = get_telemetry()
        if tel is not None:
            tel.set_attempt(tel.attempt if restarts == 0 else
                            tel.attempt + 1)
        try:
            # Tag with the TELEMETRY attempt (disk-resumed offset
            # included), not the in-process restart index — spans and
            # metrics rows must carry the same number or the timeline
            # can't be correlated after a re-exec.
            with (tel.span("restart_attempt", attempt=tel.attempt)
                  if tel is not None else contextlib.nullcontext()):
                return attempt(restarts)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as exc:
            if restarts >= max_restarts:
                rank0_print(
                    f"[supervisor] giving up after {restarts} restart(s): "
                    f"{type(exc).__name__}: {exc}"
                )
                raise
            restarts += 1
            if events is not None:
                events.restarts += 1
            rank0_print(
                f"[supervisor] attempt failed ({type(exc).__name__}: "
                f"{exc}); restart {restarts}/{max_restarts} from the "
                "latest complete checkpoint"
            )


class GangFailure(RuntimeError):
    """The gang kept failing after exhausting its restarts."""

    def __init__(self, message: str, returncodes: list[int | None]):
        super().__init__(message)
        self.returncodes = returncodes


def _drain_gang(procs, grace_s: float) -> list[int | None]:
    """Terminate (then kill) every still-running worker; returns the
    final returncodes."""
    for p in procs:
        if p.poll() is None:
            with contextlib.suppress(OSError):
                p.terminate()
    deadline = time.monotonic() + grace_s
    for p in procs:
        if p.poll() is None:
            timeout = max(deadline - time.monotonic(), 0.1)
            try:
                p.wait(timeout=timeout)
            except Exception:
                with contextlib.suppress(OSError):
                    p.kill()
                with contextlib.suppress(Exception):
                    p.wait(timeout=5)
    return [p.poll() for p in procs]


def gang_supervise(worker_cmd, world: int, gang_dir,
                   *, ckpt_dirs=None, max_restarts: int = 3,
                   events: FaultEvents | None = None,
                   poll_s: float = 0.2, grace_s: float = 10.0,
                   env=None, log_dir=None) -> list[int]:
    """Run a gang of ``world`` worker processes to completion, restarting
    ALL of them together on any failure — the multi-host analogue of
    :func:`run_attempts`.

    ``worker_cmd(rank, attempt)`` returns the argv for one worker (the
    ``attempt`` parameter lets the caller pick a fresh coordination-
    service port per relaunch — the dead attempt's port may linger in
    TIME_WAIT).  Workers coordinate through ``gang_dir`` via
    ``runtime/coordinator.py``: heartbeat files, the abort latch, and
    restore-point records.

    The restart protocol, in order:

    1. any worker exiting nonzero (a died rank, or survivors taking the
       coordinated abort exit) fails the attempt; the rest are
       terminated so no orphan keeps the next rendezvous port busy;
    2. the restore-point election (``elect_restore_step``) picks the
       highest checkpoint step EVERY rank verified — and checkpoints
       newer than it are quarantined (``enforce_restore_point``) so
       each relaunched worker's fallback chain resolves to the same
       restore point.  ``ckpt_dirs``: one shared checkpoint directory
       or one per rank (per-host shard layouts);
    3. the whole gang is relaunched (``gang_restarts`` counter, one
       ``gang_attempt`` span per try), up to ``max_restarts`` times.

    Returns the final returncodes (all zero) on success; raises
    :class:`GangFailure` after the restart budget is spent.

    ``log_dir``: when given, each worker's stdout+stderr streams to
    ``rank<r>.attempt<k>.log`` there — the gang post-mortem surface.
    """
    import subprocess

    from distributed_machine_learning_tpu.runtime.coordinator import (
        clear_gang_state,
        elect_restore_step,
        enforce_restore_point,
        read_abort,
    )
    from distributed_machine_learning_tpu.telemetry import get_telemetry

    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    if max_restarts < 0:
        raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
    # A fresh supervision run: stale beats/aborts AND restore records
    # from any earlier run in the same gang_dir would poison detection
    # and the election.
    clear_gang_state(gang_dir, restore_records=True)
    if log_dir is not None:
        os.makedirs(log_dir, exist_ok=True)
    restarts = 0
    while True:
        # Between attempts: clear the dead attempt's beats and abort
        # latch, but KEEP restore records — they are the election input.
        clear_gang_state(gang_dir)
        if restarts > 0 and ckpt_dirs is not None:
            elected = elect_restore_step(gang_dir, world,
                                         ckpt_dirs=ckpt_dirs)
            quarantined = enforce_restore_point(ckpt_dirs, elected)
            rank0_print(
                f"[gang] restore-point election: step "
                f"{elected if elected is not None else '<none>'}"
                + (f"; quarantined {len(quarantined)} newer "
                   f"checkpoint(s)" if quarantined else "")
            )
        tel = get_telemetry()
        span = (tel.span("gang_attempt", attempt=restarts, world=world)
                if tel is not None else contextlib.nullcontext())
        procs, logs = [], []
        try:
            with span:
                for rank in range(world):
                    out = None
                    if log_dir is not None:
                        out = open(
                            os.path.join(
                                log_dir,
                                f"rank{rank}.attempt{restarts}.log",
                            ),
                            "ab",
                        )
                    logs.append(out)
                    procs.append(subprocess.Popen(
                        worker_cmd(rank, restarts),
                        stdout=out,
                        stderr=subprocess.STDOUT if out is not None
                        else None,
                        env=env,
                    ))
                failed = None
                while failed is None:
                    codes = [p.poll() for p in procs]
                    bad = [(r, c) for r, c in enumerate(codes)
                           if c not in (None, 0)]
                    if bad:
                        failed = bad
                        break
                    if all(c == 0 for c in codes):
                        return list(codes)  # the gang finished cleanly
                    time.sleep(poll_s)
        finally:
            final_codes = _drain_gang(procs, grace_s)
            for out in logs:
                if out is not None:
                    out.close()
        abort = read_abort(gang_dir)
        why = (f"rank {failed[0][0]} exited {failed[0][1]}"
               + (f"; abort declared by rank {abort.get('by_rank')}: "
                  f"{abort.get('reason')}" if abort else ""))
        if restarts >= max_restarts:
            rank0_print(
                f"[gang] giving up after {restarts} restart(s): {why}"
            )
            raise GangFailure(
                f"gang failed after {restarts} restart(s): {why}",
                final_codes,
            )
        restarts += 1
        if events is not None:
            events.gang_restarts += 1
        if tel is not None:
            tel.registry.counter("gang_restarts").inc()
            tel.flush()
        rank0_print(
            f"[gang] {why}; coordinated restart {restarts}/{max_restarts}"
        )


def auto_resume(ckpt_dir, init_state, abstract_state=None, events=None):
    """(state, cursor, resumed_path) — the newest *valid* checkpoint
    under ``ckpt_dir`` restored against ``abstract_state`` (default: the
    fresh ``init_state``), or ``(init_state, 0, None)`` when none exists.
    Incomplete saves (crash/kill mid-write) and corrupt ones (manifest
    digest mismatch — quarantined with ``.invalid``) are skipped by
    ``latest_checkpoint``'s fallback chain — that chain IS the resume
    guarantee.  ``events``: optional FaultEvents; verification failures
    and fallbacks are counted there as well as in telemetry."""
    from distributed_machine_learning_tpu.train.checkpoint import (
        checkpoint_cursor,
        latest_checkpoint,
        restore_checkpoint,
    )

    latest = latest_checkpoint(ckpt_dir, events=events)
    if latest is None:
        return init_state, 0, None
    state = restore_checkpoint(
        latest, abstract_state=abstract_state or init_state,
        files_verified=True,  # the chain above just ran the file sweep
    )
    cursor = checkpoint_cursor(latest)
    if cursor is None:
        cursor = int(jax.device_get(state.step))
    return state, cursor, latest


def supervised_train(
    train_step,
    init_state,
    make_batches: Callable[[int], object],
    *,
    target_steps: int,
    ckpt_dir,
    save_every: int = 100,
    max_restarts: int = 3,
    events: FaultEvents | None = None,
    watchdog_timeout: float = 0.0,
    injector: FaultInjector | None = None,
    retry=None,
    place_batch=None,
    keep_last_n: int | None = None,
    abstract_state=None,
    stop=None,
    loss_print_every: int = 10**9,
):
    """Run ``train_step`` to ``target_steps`` applied updates, surviving
    faults: the full skip/retry/restart ladder in one call.

    ``make_batches(cursor)`` must yield the batch stream from absolute
    batch index ``cursor`` (deterministically — that seekability is what
    makes restart replay exact).  Checkpoints land every ``save_every``
    applied steps (cursor recorded), and the final state is saved at
    ``target_steps``.  ``target_steps`` counts APPLIED updates: a
    guard-skipped batch is consumed but retried with further data, so a
    faulted run finishes at the same step count as a clean one.

    ``retry``: a ``data/retry.RetryPolicy`` (None disables the retry
    layer); ``injector``: a ``runtime/faults.FaultInjector`` for chaos
    runs; ``stop``: zero-arg predicate (e.g. a ``PreemptionHandler``) —
    True checkpoints and returns early, cleanly.

    Returns the final state (a ``DynamicScaleState`` stays wrapped; its
    inner TrainState is what checkpoints hold, and the loss scale resets
    to its initial value after a restart — scale is ephemeral tuning
    state, not training progress).
    """
    from distributed_machine_learning_tpu.data.retry import retry_batches
    from distributed_machine_learning_tpu.train.checkpoint import (
        save_checkpoint,
    )
    from distributed_machine_learning_tpu.train.lm_step import (
        DynamicScaleState,
        unwrap_dynamic_scale,
        with_dynamic_scale,
    )
    from distributed_machine_learning_tpu.train.loop import train_epoch

    if target_steps < 1:
        raise ValueError(f"target_steps must be >= 1, got {target_steps}")
    if save_every < 1:
        raise ValueError(f"save_every must be >= 1, got {save_every}")
    events = events if events is not None else FaultEvents()
    mid_save = injector.mid_save_hook(events) if injector is not None else None
    post_save = (injector.post_save_hook(events) if injector is not None
                 else None)
    scaled = isinstance(init_state, DynamicScaleState)
    # Read the scaler's init values ONCE: the compiled step donates its
    # input state, so after attempt 0 these arrays may be dead buffers.
    init_scale = float(init_state.loss_scale) if scaled else None
    growth_interval = init_state.growth_interval if scaled else None

    def _rewrap(inner):
        if not scaled:
            return inner
        return with_dynamic_scale(
            inner, init_scale=init_scale, growth_interval=growth_interval
        )

    def _copy_state(tree):
        """Fresh buffers for every leaf — an attempt must never train on
        the caller's ``init_state`` directly: the jitted step donates its
        input, and a later restart that falls back to the fresh state
        (no complete checkpoint yet) would otherwise hand the step
        already-donated buffers."""
        from distributed_machine_learning_tpu.train.checkpoint import (
            fresh_buffers,
        )

        return fresh_buffers(tree)

    def _step_of(state) -> int:
        return int(jax.device_get(state.step))

    def attempt(restart_idx: int):
        inner, cursor, resumed = auto_resume(
            ckpt_dir,
            unwrap_dynamic_scale(init_state),
            abstract_state=unwrap_dynamic_scale(
                abstract_state if abstract_state is not None else init_state
            ),
            events=events,
        )
        if resumed is None:
            inner = _copy_state(inner)
        state = _rewrap(inner)
        if resumed:
            rank0_print(
                f"[supervisor] resumed from {resumed} "
                f"(step {_step_of(state)}, cursor {cursor})"
            )
        watchdog = (
            RaisingWatchdog(watchdog_timeout, events).start()
            if watchdog_timeout
            else None
        )
        cursor_box = {"v": cursor}

        def source(pos: int):
            base = make_batches(pos)

            def counted():
                for j, batch in enumerate(base):
                    cursor_box["v"] = pos + j + 1
                    yield batch

            it = counted()
            if injector is not None:
                it = injector.wrap_batches(it, events, start=pos)
            return it

        try:
            while _step_of(state) < target_steps:
                chunk_start = _step_of(state)
                cursor_start = cursor_box["v"]
                chunk_target = min(chunk_start + save_every, target_steps)
                if retry is not None:
                    batches = retry_batches(
                        source, retry, events, start=cursor_box["v"]
                    )
                else:
                    batches = source(cursor_box["v"])
                state, _ = train_epoch(
                    train_step,
                    state,
                    batches,
                    place_batch=place_batch,
                    max_iters=10**9,
                    loss_print_every=loss_print_every,
                    watchdog=watchdog,
                    events=events,
                    until_step=chunk_target,
                    stop=stop,
                )
                # Saves are not steps: suspend the watchdog so a slow
                # (but healthy) serialize can't be declared a stall.
                with (watchdog.suspend() if watchdog is not None
                      else contextlib.nullcontext()):
                    save_checkpoint(
                        ckpt_dir,
                        unwrap_dynamic_scale(state),
                        cursor=cursor_box["v"],
                        mid_save_hook=mid_save,
                        keep_last_n=keep_last_n,
                        post_save_hook=post_save,
                    )
                if stop is not None and stop():
                    events.preemptions += 1
                    rank0_print(
                        "[supervisor] stop requested; checkpointed at "
                        f"step {_step_of(state)} and exiting cleanly"
                    )
                    return state
                if (_step_of(state) == chunk_start
                        and cursor_box["v"] == cursor_start):
                    raise RuntimeError(
                        f"data stream exhausted at cursor "
                        f"{cursor_box['v']} with step {chunk_start} < "
                        f"target {target_steps}: make_batches must cover "
                        "the run (skipped batches consume extra data)"
                    )
            return state
        finally:
            if watchdog is not None:
                watchdog.stop()

    return run_attempts(attempt, max_restarts=max_restarts, events=events)
