"""Speculative decoding (inference/speculative.py): the draft must
change SPEED, never the distribution — greedy output is pinned bitwise
to the target-only stream."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.inference.generate import (
    make_generate_fn,
)
from distributed_machine_learning_tpu.inference.speculative import (
    make_speculative_generate_fn,
)
from distributed_machine_learning_tpu.models.transformer import TransformerLM
from distributed_machine_learning_tpu.train.lm_step import init_lm_state

VOCAB = 48


def _models():
    target = TransformerLM(vocab_size=VOCAB, d_model=32, n_layers=3,
                           n_heads=4)
    draft = TransformerLM(vocab_size=VOCAB, d_model=16, n_layers=1,
                          n_heads=2)
    return (target, init_lm_state(target).params,
            draft, init_lm_state(draft, seed=7).params)


@pytest.mark.parametrize("gamma", [1, 3, 5])
def test_greedy_speculative_bitwise_equals_vanilla(rng, gamma):
    """Any draft — here an unrelated random model with terrible
    acceptance — must produce EXACTLY the target's greedy stream."""
    target, tparams, draft, dparams = _models()
    prompt = jnp.asarray(rng.integers(0, VOCAB, (1, 6)), jnp.int32)
    ref = make_generate_fn(target, 12)(
        tparams, prompt, jax.random.PRNGKey(0)
    )
    fn = make_speculative_generate_fn(target, draft, 12, gamma=gamma)
    out = fn(tparams, dparams, prompt, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_greedy_speculative_with_target_as_draft(rng):
    """draft == target: every proposal accepted, output still the exact
    greedy stream (the all-accept + bonus path)."""
    target, tparams, _, _ = _models()
    prompt = jnp.asarray(rng.integers(0, VOCAB, (1, 5)), jnp.int32)
    ref = make_generate_fn(target, 10)(
        tparams, prompt, jax.random.PRNGKey(0)
    )
    fn = make_speculative_generate_fn(target, target, 10, gamma=4)
    out = fn(tparams, tparams, prompt, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_sampled_speculative_runs_and_stays_in_vocab(rng):
    target, tparams, draft, dparams = _models()
    prompt = jnp.asarray(rng.integers(0, VOCAB, (1, 5)), jnp.int32)
    fn = make_speculative_generate_fn(
        target, draft, 10, gamma=3, temperature=0.8, top_p=0.9
    )
    out = fn(tparams, dparams, prompt, jax.random.PRNGKey(3))
    assert out.shape == (1, 15)
    o = np.asarray(out)
    assert (o >= 0).all() and (o < VOCAB).all()
    np.testing.assert_array_equal(o[:, :5], np.asarray(prompt))


def test_speculative_guards():
    target = TransformerLM(vocab_size=VOCAB, d_model=32, n_layers=3,
                           n_heads=4)
    draft = TransformerLM(vocab_size=VOCAB, d_model=16, n_layers=1,
                          n_heads=2)
    with pytest.raises(ValueError, match="gamma"):
        make_speculative_generate_fn(target, draft, 8, gamma=0)
    with pytest.raises(ValueError, match="vocabulary"):
        make_speculative_generate_fn(
            target,
            TransformerLM(vocab_size=VOCAB + 1, d_model=16, n_layers=1,
                          n_heads=2),
            8,
        )


@pytest.mark.parametrize("gamma", [2, 4])
def test_batched_greedy_speculative_token_exact(rng, gamma):
    """Batch 8, rows with DIFFERENT prompts: every row's speculative
    stream must equal vanilla batched greedy — per-row frontiers commit
    different counts each round (the draft is random, so acceptance
    varies wildly by row) yet the output is token-exact per row."""
    target, tparams, draft, dparams = _models()
    prompt = jnp.asarray(rng.integers(0, VOCAB, (8, 6)), jnp.int32)
    ref = make_generate_fn(target, 12)(
        tparams, prompt, jax.random.PRNGKey(0)
    )
    fn = make_speculative_generate_fn(target, draft, 12, gamma=gamma)
    out = fn(tparams, dparams, prompt, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_batched_equals_rowwise_single(rng):
    """The batched program must serve each row exactly as the batch-1
    program serves it alone — freezing finished rows cannot leak into
    live rows' streams."""
    target, tparams, draft, dparams = _models()
    prompts = jnp.asarray(rng.integers(0, VOCAB, (4, 5)), jnp.int32)
    fn = make_speculative_generate_fn(target, draft, 9, gamma=3)
    batched = np.asarray(
        fn(tparams, dparams, prompts, jax.random.PRNGKey(1))
    )
    for b in range(4):
        solo = np.asarray(
            fn(tparams, dparams, prompts[b:b + 1], jax.random.PRNGKey(1))
        )
        np.testing.assert_array_equal(batched[b:b + 1], solo)


def test_batched_sampled_speculative_in_vocab(rng):
    target, tparams, draft, dparams = _models()
    prompt = jnp.asarray(rng.integers(0, VOCAB, (4, 5)), jnp.int32)
    fn = make_speculative_generate_fn(
        target, draft, 8, gamma=3, temperature=0.9, top_k=20
    )
    out = np.asarray(fn(tparams, dparams, prompt, jax.random.PRNGKey(5)))
    assert out.shape == (4, 13)
    assert (out >= 0).all() and (out < VOCAB).all()
    np.testing.assert_array_equal(out[:, :5], np.asarray(prompt))


def _oracle_acceptance(d, q, p, u):
    """NumPy oracle of the Leviathan rule, written as the paper states
    it (sequential scan per row): accept d_i while u_i < p_i(d_i)/q_i(d_i);
    at the first rejection the correction samples norm(max(p_i − q_i, 0));
    on full acceptance the bonus samples p_γ."""
    B, gamma = d.shape
    n_accs, resids = [], []
    for b in range(B):
        n = 0
        while n < gamma and u[b, n] * q[b, n, d[b, n]] < p[b, n, d[b, n]]:
            n += 1
        r = (np.maximum(p[b, n] - q[b, n], 0.0) if n < gamma
             else p[b, gamma].copy())
        n_accs.append(n)
        resids.append(r / max(r.sum(), 1e-30))
    return np.asarray(n_accs), np.stack(resids)


def test_sampled_acceptance_matches_numpy_oracle(rng):
    """The vectorized accept/reject-residual math
    (inference/speculative.py::sampled_acceptance) is pinned against a
    sequential NumPy transcription of the rule — including the
    all-accepted bonus branch and ties forced through q == p rows."""
    from distributed_machine_learning_tpu.inference.speculative import (
        sampled_acceptance,
    )

    B, gamma, V = 64, 4, 12
    q = rng.random((B, gamma, V)).astype(np.float32)
    q /= q.sum(-1, keepdims=True)
    p = rng.random((B, gamma + 1, V)).astype(np.float32)
    p /= p.sum(-1, keepdims=True)
    # Force some all-accept rows (draft == target ⇒ p/q = 1 > u a.s.).
    p[:8, :gamma] = q[:8]
    d = rng.integers(0, V, (B, gamma)).astype(np.int32)
    u = rng.random((B, gamma)).astype(np.float32)
    n_acc, resid = jax.jit(sampled_acceptance)(
        jnp.asarray(d), jnp.asarray(q), jnp.asarray(p), jnp.asarray(u)
    )
    n_ref, r_ref = _oracle_acceptance(d, q, p, u)
    np.testing.assert_array_equal(np.asarray(n_acc), n_ref)
    np.testing.assert_allclose(np.asarray(resid), r_ref, rtol=1e-5,
                               atol=1e-6)
    assert (np.asarray(n_acc)[:8] == gamma).all()  # bonus branch hit
    # The rule's point (Leviathan Thm 1), checked as arithmetic at
    # position 0: accept-mass + reject-mass·residual == p exactly.
    p0, q0 = p[8:, 0], q[8:, 0]
    accept = np.minimum(p0, q0)  # q·min(1, p/q)
    r0 = np.maximum(p0 - q0, 0.0)
    r0 /= r0.sum(-1, keepdims=True)
    emitted = accept + (1.0 - accept.sum(-1, keepdims=True)) * r0
    np.testing.assert_allclose(emitted, p0, rtol=1e-5, atol=1e-6)


def _tv(hist_a, hist_b):
    return 0.5 * float(np.abs(hist_a - hist_b).sum())


def test_sampled_speculative_preserves_distribution(rng):
    """End-to-end distributional pin (VERDICT r4 item 3): thousands of
    iid speculative streams (identical prompts on per-row frontiers —
    every jax.random draw is row-independent) vs plain sampled decode
    at matched warps.  The first generated token's empirical law is
    compared against the EXACT warped target distribution (computable
    from the logits), and later positions against plain decoding's
    empirical law.  n=8192, effective support ≲ 12 ⇒ E[TV] ≈ 0.03;
    thresholds sit ~2× above that."""
    from distributed_machine_learning_tpu.inference.generate import (
        warp_logits,
    )

    V = 16
    temperature, top_k, top_p = 0.9, 12, 0.9
    target = TransformerLM(vocab_size=V, d_model=16, n_layers=1, n_heads=2)
    draft = TransformerLM(vocab_size=V, d_model=8, n_layers=1, n_heads=2)
    tparams = init_lm_state(target).params
    dparams = init_lm_state(draft, seed=7).params
    n = 8192
    prompt1 = jnp.asarray([[3, 7, 1]], jnp.int32)
    prompt = jnp.tile(prompt1, (n, 1))
    new = 4
    spec = make_speculative_generate_fn(
        target, draft, new, gamma=3, temperature=temperature,
        top_k=top_k, top_p=top_p,
    )
    out_s = np.asarray(
        spec(tparams, dparams, prompt, jax.random.PRNGKey(0))
    )[:, 3:]
    plain = make_generate_fn(target, new, temperature=temperature,
                             top_k=top_k, top_p=top_p)
    out_p = np.asarray(
        plain(tparams, prompt, jax.random.PRNGKey(1))
    )[:, 3:]

    # Position 0 vs the EXACT warped target law.
    logits = target.apply({"params": tparams}, prompt1)[0, -1]
    p0 = np.asarray(
        jax.nn.softmax(warp_logits(logits, temperature, top_k, top_p))
    )
    hist_s = np.bincount(out_s[:, 0], minlength=V) / n
    assert _tv(hist_s, p0) < 0.06, (hist_s, p0)
    # Zero-probability (warped-out) tokens must never be emitted.
    assert hist_s[p0 <= 0].sum() == 0.0
    # Later positions: speculative vs plain empirical marginals.
    for j in range(1, new):
        hj_s = np.bincount(out_s[:, j], minlength=V) / n
        hj_p = np.bincount(out_p[:, j], minlength=V) / n
        assert _tv(hj_s, hj_p) < 0.09, j


def test_batched_greedy_speculative_int8_kv_cache(rng):
    """Per-row frontiers compose with the int8 KV cache: the vmapped
    per-row scale writes and the scale-folding einsum must keep the
    batched stream equal to the vanilla int8-cache stream."""
    target = TransformerLM(vocab_size=VOCAB, d_model=32, n_layers=2,
                           n_heads=4, kv_cache_dtype=jnp.int8)
    draft = TransformerLM(vocab_size=VOCAB, d_model=16, n_layers=1,
                          n_heads=2, kv_cache_dtype=jnp.int8)
    tparams = init_lm_state(target).params
    dparams = init_lm_state(draft, seed=7).params
    prompt = jnp.asarray(rng.integers(0, VOCAB, (4, 6)), jnp.int32)
    ref = make_generate_fn(target, 10)(
        tparams, prompt, jax.random.PRNGKey(0)
    )
    fn = make_speculative_generate_fn(target, draft, 10, gamma=3)
    out = fn(tparams, dparams, prompt, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_greedy_speculative_with_int8_target(rng):
    """Speculative composes with int8 serving: an int8-quantized target
    (and/or draft) still produces its own exact greedy stream — the
    reference is vanilla int8 decode, so quantization error and the
    speculative machinery are isolated from each other."""
    from distributed_machine_learning_tpu.ops.quant import quantize_lm_params

    target, tparams, draft, dparams = _models()
    qt = quantize_lm_params(tparams)
    qd = quantize_lm_params(dparams)
    prompt = jnp.asarray(rng.integers(0, VOCAB, (1, 5)), jnp.int32)
    ref = make_generate_fn(target, 10, quantize="int8")(
        qt, prompt, jax.random.PRNGKey(0)
    )
    fn = make_speculative_generate_fn(
        target, draft, 10, gamma=3, quantize="int8", draft_quantize="int8"
    )
    out = fn(qt, qd, prompt, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


@pytest.mark.parametrize("quant", [None, "int8"], ids=["bf", "int8"])
def test_tp_speculative_token_exact(rng, quant):
    """Speculative x TP (VERDICT r4 item 4): the tp=2 sharded target's
    speculative stream (replicated draft, local-width verify passes)
    equals single-device speculative decoding token-for-token — with
    and without the int8 target weights."""
    from distributed_machine_learning_tpu.inference.speculative import (
        make_tp_speculative_generate_fn,
    )
    from distributed_machine_learning_tpu.ops.quant import quantize_lm_params
    from distributed_machine_learning_tpu.parallel.tensor_parallel import (
        tp_decode_params,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh

    mesh = make_mesh(2, axis_names=("model",))
    target, tparams, draft, dparams = _models()
    if quant == "int8":
        tparams = quantize_lm_params(tparams)
    prompt = jnp.asarray(rng.integers(0, VOCAB, (1, 6)), jnp.int32)
    ref_fn = make_speculative_generate_fn(target, draft, 10, gamma=3,
                                          quantize=quant)
    ref = ref_fn(tparams, dparams, prompt, jax.random.PRNGKey(0))
    fn = make_tp_speculative_generate_fn(target, draft, 10, mesh, gamma=3,
                                         quantize=quant)
    out = fn(tp_decode_params(tparams, 2), dparams, prompt,
             jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_tp_speculative_batched_and_sampled(rng):
    """TP x batched speculation (per-row frontiers inside the shard_map)
    stays token-exact vs single-device; the sampled path runs and stays
    in-vocab."""
    from distributed_machine_learning_tpu.inference.speculative import (
        make_tp_speculative_generate_fn,
    )
    from distributed_machine_learning_tpu.parallel.tensor_parallel import (
        tp_decode_params,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh

    mesh = make_mesh(2, axis_names=("model",))
    target, tparams, draft, dparams = _models()
    tp_params = tp_decode_params(tparams, 2)
    prompt = jnp.asarray(rng.integers(0, VOCAB, (3, 5)), jnp.int32)
    ref = make_speculative_generate_fn(target, draft, 8, gamma=2)(
        tparams, dparams, prompt, jax.random.PRNGKey(0)
    )
    fn = make_tp_speculative_generate_fn(target, draft, 8, mesh, gamma=2)
    out = fn(tp_params, dparams, prompt, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    sfn = make_tp_speculative_generate_fn(
        target, draft, 8, mesh, gamma=2, temperature=0.8, top_k=16
    )
    s = np.asarray(sfn(tp_params, dparams, prompt, jax.random.PRNGKey(2)))
    assert s.shape == (3, 13)
    assert (s >= 0).all() and (s < VOCAB).all()
