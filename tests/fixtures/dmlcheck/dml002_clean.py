# dmlcheck-virtual-path: distributed_machine_learning_tpu/runtime/fixture.py
"""DML002 clean case: ledger appends flushed AND fsynced (the very
next statement may be os._exit), plus a non-ledger append that the
rule correctly ignores."""
import json
import os


def mark_fired(ledger_path, entry):
    with open(ledger_path, "a") as f:
        f.write(json.dumps(entry) + "\n")
        f.flush()
        os.fsync(f.fileno())


def append_note(path, text):
    with open(path, "a") as f:             # no ledger token: out of scope
        f.write(text)
