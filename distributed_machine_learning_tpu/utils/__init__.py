from distributed_machine_learning_tpu.utils.timing import IterationTimer
from distributed_machine_learning_tpu.utils.logging import rank0_print, get_logger

__all__ = ["IterationTimer", "rank0_print", "get_logger"]
