"""Gang-coordinated fault tolerance + end-to-end verified checkpoints
(ISSUE 3): the coordinator's heartbeat/peer-failure/abort protocol, the
restore-point election, the checkpoint manifest + fallback chain +
quarantine, the new multi-process fault kinds, the stdlib verifier
tool, and the full chaos proof — a 4-worker local gang surviving
``kill_rank`` with bit-identical final params.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.runtime.coordinator import (
    GangCoordinator,
    clear_gang_state,
    declare_abort,
    elect_restore_step,
    enforce_restore_point,
    read_abort,
)
from distributed_machine_learning_tpu.runtime.faults import (
    FAULT_LEDGER_FILE,
    FaultEvents,
    FaultInjector,
    corrupt_checkpoint_data,
)
from distributed_machine_learning_tpu.train.checkpoint import (
    CheckpointVerifyError,
    checkpoint_config,
    checkpoint_cursor,
    checkpoint_manifest,
    gc_checkpoints,
    latest_checkpoint,
    quarantine_checkpoint,
    quarantine_reason,
    restore_checkpoint,
    save_checkpoint,
    validate_checkpoint,
)
from distributed_machine_learning_tpu.train.state import TrainState

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

# Tight-but-safe chaos timing for the in-process coordinator tests: the
# 1-core CI box schedules threads with real jitter, so detection waits
# use generous deadlines and assert only ordering, never exact latency.
HB = 0.1
TIMEOUT = 0.5


def _wait_until(pred, deadline_s=8.0, poll_s=0.02):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll_s)
    return False


def _tiny_state(step: int = 0) -> TrainState:
    state = TrainState.create(params={"w": jnp.zeros((8,), jnp.float32)})
    if step:
        state = state.replace(step=state.step + step)
    return state


# ---------------------------------------------------------------------------
# GangCoordinator: heartbeat, peer-failure detection, coordinated abort
# ---------------------------------------------------------------------------


def test_coordinator_validates_configuration(tmp_path):
    with pytest.raises(ValueError):
        GangCoordinator(tmp_path, rank=0, world=0)
    with pytest.raises(ValueError):
        GangCoordinator(tmp_path, rank=2, world=2)
    with pytest.raises(ValueError):
        GangCoordinator(tmp_path, rank=0, world=2,
                        heartbeat_interval_s=0.0)
    with pytest.raises(ValueError):
        # timeout must exceed two heartbeat intervals
        GangCoordinator(tmp_path, rank=0, world=2,
                        heartbeat_interval_s=1.0, peer_timeout_s=1.5)


def test_detects_dead_peer_and_declares_abort(tmp_path):
    """Rank 1 beats once and dies (its coordinator stops); rank 0 must
    declare it dead once the beat file goes stale, write the abort
    latch, and count a peer failure."""
    aborts = []
    events = FaultEvents()
    c1 = GangCoordinator(tmp_path, rank=1, world=2,
                         heartbeat_interval_s=HB, peer_timeout_s=TIMEOUT,
                         on_abort=lambda r: None).start()
    c1.stop()  # beat file exists but will never refresh: a dead process
    c0 = GangCoordinator(tmp_path, rank=0, world=2,
                         heartbeat_interval_s=HB, peer_timeout_s=TIMEOUT,
                         events=events, check_self=False,
                         on_abort=aborts.append).start()
    try:
        c0.beat()
        assert _wait_until(lambda: aborts), "dead peer never declared"
        assert "rank 1" in aborts[0] and "dead" in aborts[0]
        assert events.peer_failures == 1
        abort = read_abort(tmp_path)
        assert abort is not None and abort["by_rank"] == 0
    finally:
        c0.stop()


def test_detects_stalled_peer(tmp_path):
    """Rank 1 is alive (heartbeat file keeps refreshing) but makes no
    step progress — declared stalled at 1.5x the peer timeout."""
    aborts = []
    c1 = GangCoordinator(tmp_path, rank=1, world=2,
                         heartbeat_interval_s=HB, peer_timeout_s=TIMEOUT,
                         check_self=False, on_abort=lambda r: None).start()
    c0 = GangCoordinator(tmp_path, rank=0, world=2,
                         heartbeat_interval_s=HB, peer_timeout_s=TIMEOUT,
                         check_self=False, on_abort=aborts.append).start()
    try:
        # Keep rank 0 progressing so only rank 1 reads as stalled.
        assert _wait_until(lambda: (c0.beat() or aborts)), \
            "stalled peer never declared"
        assert "rank 1" in aborts[0] and "stalled" in aborts[0]
    finally:
        c0.stop()
        c1.stop()


def test_suspension_exempts_progress_judgement(tmp_path):
    """A suspended peer (checkpoint save, compile) is never declared
    stalled, no matter how stale its progress."""
    aborts = []
    c1 = GangCoordinator(tmp_path, rank=1, world=2,
                         heartbeat_interval_s=HB, peer_timeout_s=TIMEOUT,
                         check_self=False, on_abort=lambda r: None).start()
    c0 = GangCoordinator(tmp_path, rank=0, world=2,
                         heartbeat_interval_s=HB, peer_timeout_s=TIMEOUT,
                         check_self=False, on_abort=aborts.append).start()
    try:
        with c1.suspend():
            deadline = time.monotonic() + 4 * TIMEOUT
            while time.monotonic() < deadline:
                c0.beat()
                time.sleep(HB / 2)
        assert not aborts, aborts
    finally:
        c0.stop()
        c1.stop()


def test_abort_latch_is_joined_by_every_rank(tmp_path):
    aborts = []
    c0 = GangCoordinator(tmp_path, rank=0, world=2,
                         heartbeat_interval_s=HB, peer_timeout_s=TIMEOUT,
                         check_self=False, on_abort=aborts.append).start()
    c1 = GangCoordinator(tmp_path, rank=1, world=2,
                         heartbeat_interval_s=HB, peer_timeout_s=TIMEOUT,
                         check_self=False, on_abort=aborts.append).start()
    try:
        declare_abort(tmp_path, "test abort", by_rank=9)
        assert _wait_until(lambda: len(aborts) >= 2)
        assert all("rank 9" in r for r in aborts)
        # First writer wins: a second declaration does not overwrite.
        assert not declare_abort(tmp_path, "late", by_rank=1)
        assert read_abort(tmp_path)["by_rank"] == 9
    finally:
        c0.stop()
        c1.stop()


def test_finished_peer_reads_healthy_forever(tmp_path):
    """finish() publishes done=True; the frozen beat file must never be
    declared a death afterwards."""
    aborts = []
    c1 = GangCoordinator(tmp_path, rank=1, world=2,
                         heartbeat_interval_s=HB, peer_timeout_s=TIMEOUT,
                         on_abort=lambda r: None).start()
    c1.finish()
    c0 = GangCoordinator(tmp_path, rank=0, world=2,
                         heartbeat_interval_s=HB, peer_timeout_s=TIMEOUT,
                         check_self=False, on_abort=aborts.append).start()
    try:
        deadline = time.monotonic() + 4 * TIMEOUT
        while time.monotonic() < deadline:
            c0.beat()
            time.sleep(HB / 2)
        assert not aborts, aborts
    finally:
        c0.stop()


def test_wait_for_peers_barrier(tmp_path):
    """The lock-step barrier: blocks until the peer publishes the step,
    and a done peer satisfies any step."""
    c0 = GangCoordinator(tmp_path, rank=0, world=2,
                         heartbeat_interval_s=HB,
                         peer_timeout_s=10 * TIMEOUT, check_self=False,
                         on_abort=lambda r: None).start()
    c1 = GangCoordinator(tmp_path, rank=1, world=2,
                         heartbeat_interval_s=HB,
                         peer_timeout_s=10 * TIMEOUT, check_self=False,
                         on_abort=lambda r: None).start()
    try:
        c1.beat(step=3)
        assert c0.wait_for_peers(3) is True  # published after <= one beat
        c1.finish()
        assert c0.wait_for_peers(10 ** 6) is True  # done satisfies all
    finally:
        c0.stop()
        c1.stop()


def test_wait_for_peers_returns_false_after_abort(tmp_path):
    c0 = GangCoordinator(tmp_path, rank=0, world=2,
                         heartbeat_interval_s=HB, peer_timeout_s=TIMEOUT,
                         check_self=False, on_abort=lambda r: None).start()
    try:
        # No peer beat ever arrives; the never-wrote-a-heartbeat grace
        # expires and the monitor aborts (test mode: flag, not exit).
        assert c0.wait_for_peers(1) is False
        assert c0.aborted is not None
    finally:
        c0.stop()


# ---------------------------------------------------------------------------
# Restore-point election + gang state lifecycle
# ---------------------------------------------------------------------------


def test_election_is_intersection_highest(tmp_path):
    c0 = GangCoordinator(tmp_path, rank=0, world=2,
                         heartbeat_interval_s=HB, peer_timeout_s=TIMEOUT)
    c1 = GangCoordinator(tmp_path, rank=1, world=2,
                         heartbeat_interval_s=HB, peer_timeout_s=TIMEOUT)
    # No records at all: no agreement.
    assert elect_restore_step(tmp_path, 2) is None
    c0.record_valid_step(5)
    # One rank silent: still no agreement.
    assert elect_restore_step(tmp_path, 2) is None
    c1.record_valid_step(5)
    c0.record_valid_step(10)
    # 10 is rank 0's alone; 5 is common.
    assert elect_restore_step(tmp_path, 2) == 5
    c1.record_valid_step(10)
    assert elect_restore_step(tmp_path, 2) == 10


def test_election_filters_on_disk_validity(tmp_path):
    gang = tmp_path / "gang"
    ckpt = tmp_path / "ckpt"
    c0 = GangCoordinator(gang, rank=0, world=1,
                         heartbeat_interval_s=HB, peer_timeout_s=TIMEOUT)
    save_checkpoint(ckpt, _tiny_state(0))
    save_checkpoint(ckpt, _tiny_state(5))
    c0.record_valid_step(0)
    c0.record_valid_step(5)
    assert elect_restore_step(gang, 1, ckpt_dirs=ckpt) == 5
    corrupt_checkpoint_data(ckpt / "step_5")
    # The agreed-but-corrupt step must not be elected.
    assert elect_restore_step(gang, 1, ckpt_dirs=ckpt) == 0


def test_enforce_restore_point_quarantines_newer(tmp_path):
    for s in (3, 7, 9):
        d = tmp_path / f"step_{s}"
        (d / "state").mkdir(parents=True)
        (d / "sgd_config.json").write_text("{}")
    quarantined = enforce_restore_point(tmp_path, 3)
    assert sorted(os.path.basename(p) for p in quarantined) == [
        "step_7", "step_9"
    ]
    assert quarantine_reason(tmp_path / "step_3") is None
    assert quarantine_reason(tmp_path / "step_7") is not None
    # None = no agreement = nothing to enforce.
    assert enforce_restore_point(tmp_path, None) == []


def test_clear_gang_state_keeps_election_inputs_between_attempts(tmp_path):
    c0 = GangCoordinator(tmp_path, rank=0, world=1,
                         heartbeat_interval_s=HB, peer_timeout_s=TIMEOUT)
    c0.start()
    c0.record_valid_step(5)
    c0.stop()
    declare_abort(tmp_path, "x", by_rank=0)
    (tmp_path / FAULT_LEDGER_FILE).write_text("{}\n")
    clear_gang_state(tmp_path)  # between attempts
    assert read_abort(tmp_path) is None
    assert not list(tmp_path.glob("beat_rank*"))
    assert list(tmp_path.glob("restore_rank*"))  # election input kept
    assert (tmp_path / FAULT_LEDGER_FILE).exists()  # fired-latch kept
    clear_gang_state(tmp_path, restore_records=True)  # fresh run
    assert not list(tmp_path.glob("restore_rank*"))
    assert not (tmp_path / FAULT_LEDGER_FILE).exists()


# ---------------------------------------------------------------------------
# New fault kinds: grammar, rank targeting, ledger
# ---------------------------------------------------------------------------


def test_rank_fault_grammar_parses():
    inj = FaultInjector.parse(
        "kill_rank@1:7,stall_rank@0:3:0.5,corrupt_ckpt@2:params", rank=3
    )
    assert inj.pending() == [
        "kill_rank@1:7", "stall_rank@0:3:0.5", "corrupt_ckpt@2:params"
    ]


@pytest.mark.parametrize("spec", [
    "kill_rank@7",            # missing rank
    "kill_rank@1:7:extra",    # too many fields
    "stall_rank@1:7",         # missing seconds
    "stall_rank@1:7:abc",     # non-float seconds
    "kill_rank@-1:7",         # bad rank
    "corrupt_ckpt@0",         # save ordinals are 1-based
])
def test_rank_fault_grammar_rejects(spec):
    with pytest.raises(ValueError):
        FaultInjector.parse(spec)


def test_rank_faults_only_fire_on_their_rank():
    events = FaultEvents()
    inj = FaultInjector.parse("kill_rank@1:3,stall_rank@1:4:0.01", rank=0)
    out = list(inj.wrap_batches(range(6), events))
    assert out == list(range(6))  # non-target rank: latched, no action
    assert events.rank_kills == 0 and events.rank_stalls == 0
    assert inj.pending() == []


def test_stall_rank_fires_on_target_rank():
    events = FaultEvents()
    inj = FaultInjector.parse("stall_rank@1:2:0.01", rank=1)
    t0 = time.monotonic()
    out = list(inj.wrap_batches(range(4), events))
    assert out == list(range(4)) and time.monotonic() - t0 >= 0.01
    assert events.rank_stalls == 1


def test_fault_ledger_survives_relaunch(tmp_path):
    """The cross-process exactly-once latch: a fired fault recorded in
    the ledger stays fired for a fresh injector parsing the same spec —
    the property that lets a gang relaunch ever finish."""
    ledger = tmp_path / FAULT_LEDGER_FILE
    inj = FaultInjector.parse("raise@3", rank=0).attach_ledger(ledger)
    with pytest.raises(Exception):
        list(inj.wrap_batches(range(6), FaultEvents()))
    assert ledger.exists()
    fresh = FaultInjector.parse("raise@3", rank=0).attach_ledger(ledger)
    assert fresh.pending() == []  # already fired, per the ledger
    assert list(fresh.wrap_batches(range(6), FaultEvents())) == list(
        range(6)
    )
    # A different rank's injector is NOT latched by rank 0's firing.
    other = FaultInjector.parse("raise@3", rank=1).attach_ledger(ledger)
    assert other.pending() == ["raise@3"]


# ---------------------------------------------------------------------------
# Verified checkpoints: manifest, fallback chain, quarantine, GC
# ---------------------------------------------------------------------------


def test_manifest_written_and_validates(tmp_path):
    path = save_checkpoint(tmp_path, _tiny_state(0), cursor=3)
    manifest = checkpoint_manifest(path)
    assert manifest is not None and manifest["files"]
    leaves = manifest["leaves"]
    assert {"params/w", "momentum/w", "step", "rng"} <= set(leaves)
    entry = leaves["params/w"]
    assert entry["bytes"] == 32 and entry["dtype"] == "float32"
    assert {"sha256", "crc32", "shape"} <= set(entry)
    assert validate_checkpoint(path) == []


def test_corrupt_checkpoint_falls_back_and_quarantines(tmp_path):
    events = FaultEvents()
    p0 = save_checkpoint(tmp_path, _tiny_state(0))
    p1 = save_checkpoint(tmp_path, _tiny_state(5))
    corrupt_checkpoint_data(p1)
    assert validate_checkpoint(p1)  # digests no longer match
    assert latest_checkpoint(tmp_path, events=events) == p0
    assert quarantine_reason(p1) is not None  # marked, not re-probed
    assert events.ckpt_verify_failures == 1
    assert events.ckpt_fallbacks == 1
    # Second call: the quarantined dir is skipped without recounting.
    assert latest_checkpoint(tmp_path, events=events) == p0
    assert events.ckpt_verify_failures == 1


def test_restore_refuses_corrupt_and_quarantined(tmp_path):
    path = save_checkpoint(tmp_path, _tiny_state(0))
    corrupt_checkpoint_data(path)
    with pytest.raises(CheckpointVerifyError):
        restore_checkpoint(path, abstract_state=_tiny_state(0))
    # Now quarantined: refused without re-reading the data.
    assert quarantine_reason(path) is not None
    with pytest.raises(CheckpointVerifyError):
        restore_checkpoint(path, abstract_state=_tiny_state(0))


def test_quarantined_readers_tolerate(tmp_path):
    path = save_checkpoint(tmp_path, _tiny_state(0), cursor=7)
    assert checkpoint_cursor(path) == 7
    quarantine_checkpoint(path, "test verdict")
    assert checkpoint_cursor(path) is None  # never touches known-bad data
    with pytest.raises(CheckpointVerifyError):
        checkpoint_config(path)
    # A re-save over the quarantined dir is a fresh checkpoint: the old
    # verdict must not outlive the data it judged.
    save_checkpoint(tmp_path, _tiny_state(0), cursor=9)
    assert quarantine_reason(path) is None
    assert checkpoint_cursor(path) == 9


def test_gc_never_deletes_newest_valid(tmp_path):
    """The satellite fix: a corrupt NEWEST checkpoint must not trick GC
    into deleting the newest intact one."""
    p0 = save_checkpoint(tmp_path, _tiny_state(0))
    p1 = save_checkpoint(tmp_path, _tiny_state(5))
    p2 = save_checkpoint(tmp_path, _tiny_state(9))
    corrupt_checkpoint_data(p2)
    removed = gc_checkpoints(tmp_path, keep_last_n=1)
    assert os.path.isdir(p1), "newest VALID checkpoint was deleted"
    assert p0 in removed
    # The corrupt newest is retained (nothing newer-and-valid exists to
    # prove it superseded) but the fallback chain ignores it.
    assert latest_checkpoint(tmp_path) == p1
    # Once a newer valid save lands, the quarantined dir is collectable.
    p3 = save_checkpoint(tmp_path, _tiny_state(12))
    removed = gc_checkpoints(tmp_path, keep_last_n=1)
    assert p2 in removed and os.path.isdir(p3)


def test_async_writer_writes_manifest(tmp_path):
    from distributed_machine_learning_tpu.train.checkpoint import (
        AsyncCheckpointWriter,
    )

    with AsyncCheckpointWriter() as writer:
        path = writer.save(tmp_path, _tiny_state(0), cursor=2)
        writer.wait()
    assert validate_checkpoint(path) == []
    manifest = checkpoint_manifest(path)
    assert manifest["leaves"]["params/w"]["bytes"] == 32


# ---------------------------------------------------------------------------
# tools/ckpt_verify.py (stdlib CLI)
# ---------------------------------------------------------------------------


def _run_ckpt_verify(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ckpt_verify.py"),
         *args],
        capture_output=True, text=True, timeout=60,
    )


def test_ckpt_verify_tool_passes_good_and_fails_corrupt(tmp_path):
    save_checkpoint(tmp_path, _tiny_state(0))
    p1 = save_checkpoint(tmp_path, _tiny_state(5))
    res = _run_ckpt_verify(str(tmp_path))
    assert res.returncode == 0, res.stdout + res.stderr
    assert "2 checkpoint(s), 0 invalid" in res.stdout
    assert "params/w" in res.stdout  # per-leaf status table
    corrupt_checkpoint_data(p1)
    res = _run_ckpt_verify(str(tmp_path))
    assert res.returncode == 1, res.stdout + res.stderr
    assert "CORRUPT" in res.stdout and "1 invalid" in res.stdout
    res = _run_ckpt_verify(str(tmp_path / "step_0"), "--quiet")
    assert res.returncode == 0 and "OK" in res.stdout


def test_ckpt_verify_tool_flags_incomplete_and_quarantined(tmp_path):
    d = tmp_path / "step_3"
    (d / "state").mkdir(parents=True)  # config missing: torn save
    res = _run_ckpt_verify(str(tmp_path))
    assert res.returncode == 1 and "INCOMPLETE" in res.stdout
    (d / "sgd_config.json").write_text("{}")
    quarantine_checkpoint(d, "test verdict")
    res = _run_ckpt_verify(str(tmp_path))
    assert res.returncode == 1 and "QUARANTINED" in res.stdout


# ---------------------------------------------------------------------------
# Chaos: corruption fallback in a supervised run (single process)
# ---------------------------------------------------------------------------


def _vec_batch(i):
    r = np.random.default_rng(2000 + i)
    return (r.standard_normal((4, 8)).astype(np.float32),
            np.zeros((4,), np.int32))


@jax.jit
def _vec_step(state, x, y):
    del y
    g = x.mean(0)
    w = state.params["w"] - 0.1 * (g + 0.01 * state.params["w"])
    return state.replace(params={"w": w}, step=state.step + 1), x.sum()


def _vec_batches(cursor):
    def gen():
        i = cursor
        while i < 64:
            yield _vec_batch(i)
            i += 1
    return gen()


@pytest.mark.faultinject
def test_corrupt_ckpt_falls_back_in_supervised_run(tmp_path):
    """corrupt_ckpt flips bytes in the 2nd save (step 10); a loader
    fault then forces a restart, whose resume must fall back to the
    previous valid checkpoint (step 5) — no crash, no silent garbage —
    and finish bit-identical to the fault-free run, with the fallback
    visible in the counters."""
    from distributed_machine_learning_tpu.runtime.supervisor import (
        supervised_train,
    )
    from distributed_machine_learning_tpu.train.loop import train_epoch

    events = FaultEvents()
    injector = FaultInjector.parse("corrupt_ckpt@2,raise@11", rank=0)
    final = supervised_train(
        _vec_step, _tiny_state(0), _vec_batches, target_steps=12,
        ckpt_dir=tmp_path, save_every=5, max_restarts=2, events=events,
        injector=injector,
    )
    assert int(jax.device_get(final.step)) == 12
    assert events.ckpt_corruptions == 1
    assert events.ckpt_verify_failures >= 1
    assert events.ckpt_fallbacks >= 1
    assert events.restarts == 1

    clean, _ = train_epoch(
        _vec_step, _tiny_state(0), [_vec_batch(i) for i in range(12)],
        max_iters=10 ** 9, loss_print_every=10 ** 9,
    )
    assert np.array_equal(np.asarray(final.params["w"]),
                          np.asarray(clean.params["w"]))
    # The re-saved step_10 healed the quarantine; the verifier agrees.
    res = _run_ckpt_verify(str(tmp_path), "--quiet")
    assert res.returncode == 0, res.stdout + res.stderr


# ---------------------------------------------------------------------------
# Chaos: the 4-worker gang surviving kill_rank (multi-process)
# ---------------------------------------------------------------------------


def _run_gang(root, *, faults=None, workers=4, steps=12, save_every=5,
              peer_timeout=6.0, telemetry=False, timeout=280):
    from distributed_machine_learning_tpu.cli.gang import (
        scrubbed_worker_env,
    )

    cmd = [
        sys.executable, "-m", "distributed_machine_learning_tpu.cli.gang",
        "--workers", str(workers), "--steps", str(steps),
        "--save-every", str(save_every),
        "--ckpt-dir", os.path.join(root, "ckpt"),
        "--gang-dir", os.path.join(root, "gang"),
        "--peer-timeout", str(peer_timeout),
    ]
    if faults:
        cmd += ["--faults", faults]
    if telemetry:
        cmd += ["--telemetry-dir", os.path.join(root, "telemetry")]
    return subprocess.run(
        cmd, capture_output=True, text=True, timeout=timeout,
        env=scrubbed_worker_env(REPO), cwd=REPO,
    )


def _final_digests(root):
    """rank -> final params digest, from the LAST attempt log of each
    rank (the attempt that completed)."""
    logs = os.path.join(root, "gang", "logs")
    out = {}
    for name in os.listdir(logs):
        rank = int(name.split(".")[0][4:])
        with open(os.path.join(logs, name)) as f:
            for line in f:
                if line.startswith("final "):
                    out[rank] = line.split()[1]
    return out


@pytest.mark.slow
@pytest.mark.faultinject
def test_gang_survives_kill_rank_bit_identical(tmp_path):
    """ISSUE 3's acceptance bar: with kill_rank@1:7 on a 4-worker gang,
    rank 1 dies hard at step 7, the survivors' peer detectors abort the
    gang, gang_supervise relaunches everyone from the elected restore
    point, the run completes, and the final params are bit-identical to
    a fault-free run — on every rank, with the restart and the peer
    failure visible in the telemetry counters."""
    chaos_root = str(tmp_path / "chaos")
    clean_root = str(tmp_path / "clean")

    res = _run_gang(chaos_root, faults="kill_rank@1:7", telemetry=True)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "coordinated restart" in res.stdout

    clean = _run_gang(clean_root, peer_timeout=20.0)
    assert clean.returncode == 0, clean.stdout + clean.stderr
    assert "0 coordinated restart(s)" in clean.stdout

    chaos_digests = _final_digests(chaos_root)
    clean_digests = _final_digests(clean_root)
    assert set(chaos_digests) == set(clean_digests) == {0, 1, 2, 3}
    # Bit-identical across ranks AND across chaos/fault-free runs.
    assert len(set(chaos_digests.values())) == 1, chaos_digests
    assert chaos_digests == clean_digests

    # The kill really happened (rank 1, attempt 0) and was detected.
    rank1_log = os.path.join(chaos_root, "gang", "logs",
                             "rank1.attempt0.log")
    with open(rank1_log) as f:
        assert "exiting hard" in f.read()

    # Telemetry: the restart is a counter, not just a log line (ISSUE
    # acceptance: visible in telemetry).
    with open(os.path.join(chaos_root, "telemetry",
                           "registry.json")) as f:
        counters = {c["name"]: c["value"] for c in json.load(f)["counters"]}
    assert counters["gang_restarts"] >= 1

    # Every rank's checkpoint chain verifies end to end.
    res = _run_ckpt_verify(os.path.join(chaos_root, "ckpt"), "--quiet")
    assert res.returncode == 0, res.stdout + res.stderr
