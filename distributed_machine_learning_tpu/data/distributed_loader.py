"""Global-batch loader with per-rank DistributedSampler layout.

In the reference, each of the W gloo workers runs its own
``DataLoader(DistributedSampler(rank, W, shuffle=False))``
(``part2/2a/main.py:158-167``): rank r's step-i batch is dataset rows
``{r + W·(i·b + j) : j < b}``.  The union over ranks is the contiguous
block ``[W·i·b, W·(i+1)·b)`` — by design the same global batch part1
consumes with batch 256 = 4×64 ("we want to test on the same data for
all the tasks", ``part1/main.py:99``).

Under SPMD one host feeds the whole mesh, so this loader emits the
*global* batch laid out rank-major: shard r of the array (rows
``[r·b, (r+1)·b)`` under a ``P("batch")`` sharding) is exactly rank r's
DistributedSampler batch.  That keeps every strategy's numerics alignable
with the reference worker-for-worker.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from distributed_machine_learning_tpu.data.cifar10 import Dataset
from distributed_machine_learning_tpu.data.sharding import shard_indices


class DistributedBatchLoader:
    """Yields rank-major global batches of size ``per_rank_batch × num_ranks``.

    The layout is *derived from* ``shard_indices`` — the torch
    DistributedSampler-validated source of truth (tests/test_data.py) —
    rather than re-encoding the pad/stride contract: step i's global batch
    is the concatenation over ranks of each rank's sampler slice
    ``shard_indices(...)[i·b:(i+1)·b]``.
    """

    def __init__(
        self,
        dataset: Dataset,
        per_rank_batch: int,
        num_ranks: int,
        drop_last: bool = True,
    ):
        if per_rank_batch <= 0 or num_ranks <= 0:
            raise ValueError(
                f"per_rank_batch and num_ranks must be positive, got "
                f"{per_rank_batch}, {num_ranks}"
            )
        self.dataset = dataset
        self.per_rank_batch = per_rank_batch
        self.num_ranks = num_ranks
        self.global_batch = per_rank_batch * num_ranks
        self.drop_last = drop_last
        # (num_ranks, per_rank_count) index matrix, sampler semantics.
        self._rank_indices = np.stack(
            [shard_indices(len(dataset), r, num_ranks) for r in range(num_ranks)]
        )

    def __len__(self) -> int:
        per_rank_count = self._rank_indices.shape[1]
        if self.drop_last:
            return per_rank_count // self.per_rank_batch
        return -(-per_rank_count // self.per_rank_batch)

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        imgs, labels = self.dataset.images, self.dataset.labels
        b = self.per_rank_batch
        for step in range(len(self)):
            sl = self._rank_indices[:, step * b : (step + 1) * b]
            # Rank-major flatten: shard r of the global array == rank r's
            # sampler batch (short final slice only when drop_last=False).
            idx = sl.reshape(-1)
            yield imgs[idx], labels[idx]
