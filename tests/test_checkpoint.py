"""Checkpoint/resume: round-trip fidelity and training continuity.

The reference has no checkpointing (SURVEY.md §5); this subsystem is an
extension.  The key invariants: a restored state is bit-identical to the
saved one, and training resumed from a checkpoint produces the same
trajectory as uninterrupted training (pure-function step + saved PRNG
key make this exact, not approximate).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.cli.common import init_model_and_state
from distributed_machine_learning_tpu.models.vgg import VGGTest
from distributed_machine_learning_tpu.train.checkpoint import (
    latest_checkpoint,
    restore_checkpoint,
    save_checkpoint,
)
from distributed_machine_learning_tpu.train.sgd import SGDConfig
from distributed_machine_learning_tpu.train.step import make_train_step


def _tiny_model():
    return VGGTest(use_bn=True)


def _batch(rng, n=4):
    images = rng.integers(0, 256, (n, 32, 32, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, n).astype(np.int32)
    return jnp.asarray(images), jnp.asarray(labels)


def test_roundtrip_bit_identical(tmp_path, rng):
    state = init_model_and_state(_tiny_model(),
                                 config=SGDConfig(learning_rate=0.05))
    path = save_checkpoint(tmp_path, state)
    restored = restore_checkpoint(path)
    for a, b in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree_util.tree_leaves(state.batch_stats),
                    jax.tree_util.tree_leaves(restored.batch_stats)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(state.rng), np.asarray(restored.rng))
    assert int(restored.step) == int(state.step)
    assert restored.config == SGDConfig(learning_rate=0.05)


def test_latest_checkpoint_picks_highest_step(tmp_path):
    state = init_model_and_state(_tiny_model())
    assert latest_checkpoint(tmp_path) is None
    save_checkpoint(tmp_path, state)
    later = state.replace(step=jnp.asarray(7, jnp.int32))
    save_checkpoint(tmp_path, later)
    latest = latest_checkpoint(tmp_path)
    assert latest is not None and latest.endswith("step_7")
    assert latest_checkpoint(tmp_path / "nonexistent") is None


def test_incomplete_checkpoint_skipped_and_resave_overwrites(tmp_path):
    state = init_model_and_state(_tiny_model())
    complete = save_checkpoint(tmp_path, state)
    # Simulate a crash mid-save at a later step: directory exists but the
    # config file (written last) is missing.
    broken = tmp_path / "step_9" / "state"
    broken.mkdir(parents=True)
    latest = latest_checkpoint(tmp_path)
    assert latest == complete  # falls back past the incomplete step_9
    # Re-saving the same step must overwrite, not raise.
    save_checkpoint(tmp_path, state)


def test_resume_matches_uninterrupted_trajectory(tmp_path, rng):
    model = _tiny_model()
    step = make_train_step(model, augment=True)
    batches = [_batch(rng) for _ in range(4)]

    # Uninterrupted: 4 steps.
    s = init_model_and_state(model)
    for x, y in batches:
        s, loss_straight = step(s, x, y)

    # Interrupted: 2 steps, save, restore (with template), 2 more steps.
    s2 = init_model_and_state(model)
    for x, y in batches[:2]:
        s2, _ = step(s2, x, y)
    path = save_checkpoint(tmp_path, s2)
    s3 = restore_checkpoint(path, abstract_state=init_model_and_state(model))
    assert int(s3.step) == 2
    for x, y in batches[2:]:
        s3, loss_resumed = step(s3, x, y)

    assert float(loss_straight) == pytest.approx(float(loss_resumed), abs=0)
    for a, b in zip(jax.tree_util.tree_leaves(s.params),
                    jax.tree_util.tree_leaves(s3.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree_util.tree_leaves(s.momentum),
                    jax.tree_util.tree_leaves(s3.momentum)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_async_checkpoint_roundtrip(tmp_path, rng):
    # Async save must land the same complete layout as the sync writer,
    # be invisible to latest_checkpoint until finished, and restore
    # bit-identically.
    import numpy as np

    from distributed_machine_learning_tpu.cli.common import init_model_and_state
    from distributed_machine_learning_tpu.models.vgg import VGGTest
    from distributed_machine_learning_tpu.train.checkpoint import (
        AsyncCheckpointWriter,
        latest_checkpoint,
        restore_checkpoint,
    )

    state = init_model_and_state(VGGTest(use_bn=False))
    with AsyncCheckpointWriter() as writer:
        path = writer.save(tmp_path, state)
        writer.wait()
    assert latest_checkpoint(tmp_path) == path
    restored = restore_checkpoint(path, abstract_state=state)
    import jax

    for a, b in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert type(restored.config) is type(state.config)


@pytest.mark.slow
def test_resume_plain_checkpoint_into_unsync_bn_quirk(tmp_path):
    """Cross-layout resume: a checkpoint saved with plain synced-BN [C]
    stats restores into --unsync-bn quirk mode (stacked [world, C]) via
    the metadata-inspected template pick in cli/common.py — no blanket
    except, and a corrupt checkpoint would surface its real error."""
    from distributed_machine_learning_tpu.cli import part3
    from distributed_machine_learning_tpu.train.checkpoint import (
        checkpoint_array_shapes,
        latest_checkpoint,
    )

    common = ["--batch-size", "4", "--max-iters", "2", "--model", "vggtest",
              "--eval-batches", "0", "--eval-batch-size", "16",
              "--data-root", str(tmp_path), "--ckpt-dir", str(tmp_path / "ck")]
    part3.main(common)  # plain synced-BN run writes the checkpoint
    latest = latest_checkpoint(tmp_path / "ck")
    assert latest is not None
    stats_shapes = checkpoint_array_shapes(latest)["batch_stats"]
    first = jax.tree_util.tree_leaves(
        stats_shapes, is_leaf=lambda x: isinstance(x, tuple)
    )[0]
    assert len(first) == 1  # plain [C] layout on disk
    # Resume the same run in quirk mode: restore must go through the
    # plain template then stack per-device stats rows.
    part3.main(common + ["--resume", "--unsync-bn"])


def test_gc_checkpoints_keeps_newest_complete(tmp_path):
    from distributed_machine_learning_tpu.train.checkpoint import (
        gc_checkpoints,
    )

    state = init_model_and_state(_tiny_model())
    for s in (1, 2, 3):
        save_checkpoint(tmp_path, state.replace(step=jnp.asarray(s,
                                                                 jnp.int32)))
    # An old incomplete dir (crash leftover) and a newer-than-newest one
    # (possibly an in-flight async save).
    (tmp_path / "step_0" / "state").mkdir(parents=True)
    (tmp_path / "step_9" / "state").mkdir(parents=True)
    removed = gc_checkpoints(tmp_path, keep_last_n=2)
    names = {p.name for p in tmp_path.iterdir()}
    assert {"step_2", "step_3"} <= names  # newest 2 complete kept
    assert "step_1" not in names  # old complete beyond keep_last_n: gone
    assert "step_0" not in names  # old crash leftover: gone
    assert "step_9" in names  # newer incomplete: possibly in-flight, kept
    assert len(removed) == 2
    with pytest.raises(ValueError):
        gc_checkpoints(tmp_path, keep_last_n=0)


def test_save_checkpoint_keep_last_n_gc_inline(tmp_path):
    state = init_model_and_state(_tiny_model())
    for s in (1, 2, 3):
        save_checkpoint(tmp_path, state.replace(step=jnp.asarray(s,
                                                                 jnp.int32)),
                        keep_last_n=1)
    names = {p.name for p in tmp_path.iterdir()}
    assert names == {"step_3"}  # each save GCs its predecessors


def test_checkpoint_cursor_roundtrip(tmp_path):
    from distributed_machine_learning_tpu.train.checkpoint import (
        checkpoint_config,
        checkpoint_cursor,
    )

    state = init_model_and_state(_tiny_model(),
                                 config=SGDConfig(learning_rate=0.05))
    with_cursor = save_checkpoint(tmp_path / "a", state, cursor=17)
    assert checkpoint_cursor(with_cursor) == 17
    # The cursor tag must not leak into the optimizer config.
    assert checkpoint_config(with_cursor) == SGDConfig(learning_rate=0.05)
    without = save_checkpoint(tmp_path / "b", state)
    assert checkpoint_cursor(without) is None


def test_mid_save_crash_leaves_checkpoint_invisible(tmp_path):
    # The kill-mid-checkpoint window: state dir written, config not.
    # latest_checkpoint must fall back to the previous complete save.
    state = init_model_and_state(_tiny_model())
    complete = save_checkpoint(tmp_path, state)

    def die():
        raise RuntimeError("killed mid-save")

    later = state.replace(step=jnp.asarray(5, jnp.int32))
    with pytest.raises(RuntimeError):
        save_checkpoint(tmp_path, later, mid_save_hook=die)
    assert (tmp_path / "step_5" / "state").exists()  # torn save on disk
    assert latest_checkpoint(tmp_path) == complete  # ...and invisible
    # Re-saving the same step after the crash heals the torn directory.
    healed = save_checkpoint(tmp_path, later)
    assert latest_checkpoint(tmp_path) == healed


def test_async_config_written_only_after_state_commit(tmp_path):
    # The written-order invariant behind _is_complete, async path: the
    # config file (completeness marker) must not exist until the orbax
    # state write has committed — wait()/the next save flushes it.
    import os

    from distributed_machine_learning_tpu.train.checkpoint import (
        AsyncCheckpointWriter,
        checkpoint_cursor,
    )

    state = init_model_and_state(_tiny_model())
    with AsyncCheckpointWriter() as writer:
        path = writer.save(tmp_path, state, cursor=4)
        # Before the sync point the marker is ABSENT no matter how fast
        # orbax finished: save() never writes it eagerly.
        assert not os.path.exists(os.path.join(path, "sgd_config.json"))
        assert latest_checkpoint(tmp_path) is None
        writer.wait()
        assert latest_checkpoint(tmp_path) == path
        assert checkpoint_cursor(path) == 4
    restored = restore_checkpoint(path, abstract_state=state)
    assert int(restored.step) == int(state.step)
