"""RegimeScheduler (runtime/scheduler.py): dead-band + dwell
hysteresis, telemetry wiring, config validation (ISSUE 19)."""

import pytest

from distributed_machine_learning_tpu.runtime.scheduler import (
    LATENCY,
    THROUGHPUT,
    RegimeConfig,
    RegimeScheduler,
)
from distributed_machine_learning_tpu.telemetry.registry import (
    MetricsRegistry,
)


def test_config_validation():
    with pytest.raises(ValueError, match="dead band"):
        RegimeConfig(thin_width=4, wide_width=4)
    with pytest.raises(ValueError, match="dwell"):
        RegimeConfig(dwell_steps=0)
    with pytest.raises(ValueError, match="thin_width"):
        RegimeConfig(thin_width=-1)


def test_flip_to_throughput_needs_dwell():
    s = RegimeScheduler(RegimeConfig(thin_width=2, wide_width=6,
                                     dwell_steps=3))
    assert s.lever == LATENCY
    # Two wide observations: below dwell, no flip.
    assert s.observe(4, 3) == LATENCY
    assert s.observe(4, 3) == LATENCY
    # A single dip resets the streak.
    assert s.observe(0, 1) == LATENCY
    assert s.observe(4, 3) == LATENCY
    assert s.observe(4, 3) == LATENCY
    # Third consecutive wide observation commits the flip.
    assert s.observe(4, 3) == THROUGHPUT
    assert s.flips == 1


def test_dead_band_blocks_boundary_thrash():
    """Pressure oscillating strictly inside (thin, wide) never flips —
    in either direction."""
    s = RegimeScheduler(RegimeConfig(thin_width=2, wide_width=6,
                                     dwell_steps=1))
    for q, w in [(1, 2), (2, 3), (1, 3), (3, 2), (0, 3)] * 10:
        assert s.observe(q, w) == LATENCY
    assert s.flips == 0
    # Enter throughput, then oscillate in the band again: stays there.
    s.observe(6, 2)
    assert s.lever == THROUGHPUT
    for q, w in [(1, 2), (2, 3), (1, 3)] * 10:
        assert s.observe(q, w) == THROUGHPUT
    assert s.flips == 1
    # Only a true thin reading flips back.
    assert s.observe(0, 2) == LATENCY
    assert s.flips == 2


def test_round_trip_with_dwell():
    s = RegimeScheduler(RegimeConfig(thin_width=1, wide_width=4,
                                     dwell_steps=2))
    s.observe(3, 2)
    s.observe(3, 2)
    assert s.lever == THROUGHPUT
    s.observe(0, 1)
    assert s.lever == THROUGHPUT          # dwell not met yet
    s.observe(0, 0)
    assert s.lever == LATENCY
    assert s.flips == 2
    snap = s.snapshot()
    assert snap["lever"] == LATENCY and snap["flips"] == 2


def test_telemetry_gauges_and_flip_counter():
    reg = MetricsRegistry()
    s = RegimeScheduler(RegimeConfig(thin_width=1, wide_width=3,
                                     dwell_steps=1), registry=reg)
    s.observe(2, 2)                        # pressure 4 -> throughput
    snap = reg.snapshot()
    gauges = {g["name"]: g["value"] for g in snap["gauges"]}
    counters = {c["name"]: c["value"] for c in snap["counters"]}
    assert gauges["serving_regime"] == 1.0
    assert gauges["serving_pressure"] == 4.0
    assert counters["serving_regime_flips"] == 1


def test_router_stamps_fleet_regime_onto_engine_completions(tmp_path):
    """Fleet wiring (ISSUE 19): a RegimeScheduler handed to the router
    observes fleet-wide load (queue depth + total in-flight) once per
    pump and stamps the chosen lever onto every dispatched request;
    the replica's engine honors the hint, and each completion's stage
    events record which lever served it.  A burst of 8 requests
    against a 2-lane engine must push the fleet into the throughput
    regime."""
    import threading
    import time

    import jax
    import jax.numpy as jnp

    from distributed_machine_learning_tpu.inference.continuous import (
        ContinuousEngine,
        EngineConfig,
    )
    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )
    from distributed_machine_learning_tpu.runtime.serving import (
        ServingConfig,
        ServingRouter,
    )
    from distributed_machine_learning_tpu.runtime.serving_worker import (
        ServingWorkerConfig,
        start_worker_thread,
    )
    from distributed_machine_learning_tpu.runtime.transport import (
        InProcHub,
        InProcTransport,
    )

    model = TransformerLM(vocab_size=32, d_model=16, n_layers=2,
                          n_heads=4, n_kv_heads=2)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    engine = ContinuousEngine(model, params, EngineConfig(
        max_lanes=2, block_size=4, num_blocks=32, max_len=16,
        max_new=6, levers=(LATENCY, THROUGHPUT)))
    engine.warmup(prompt_lens=(3,))

    sched = RegimeScheduler(RegimeConfig(thin_width=0, wide_width=2,
                                         dwell_steps=1))
    hub = InProcHub(mirror_dir=str(tmp_path / "gang"))
    make_tx = lambda: InProcTransport(hub)  # noqa: E731
    router = ServingRouter(
        make_tx(), ServingConfig(replicas=1, micro_batch=4,
                                 poll_s=0.002), scheduler=sched)
    stop = threading.Event()
    t, _ = start_worker_thread(
        make_tx(), 0, None, stop,
        ServingWorkerConfig(heartbeat_interval=0.02, micro_batch=4),
        engine=engine)
    stop_router = threading.Event()
    rt = threading.Thread(target=router.run, args=(stop_router,),
                          name="regime-router", daemon=True)
    rt.start()
    try:
        deadline = time.monotonic() + 60.0
        while True:
            with router._lock:
                if router._replicas:
                    break
            assert time.monotonic() < deadline, "replica never joined"
            time.sleep(0.01)
        rids = [router.submit([1 + i % 11, 2, 3]) for i in range(8)]
        assert router.wait_idle(60.0), router.audit()
        levers = set()
        for rid in rids:
            entry = router.result(rid)
            assert entry["state"] == "done"
            evs = [ev for ev in entry["events"]
                   if ev.get("stage") == "decode"]
            assert evs, f"{rid} never stamped its decode stage"
            levers.add(evs[-1]["lever"])
        # The backlog (8 deep against 2 lanes) drove the fleet into
        # the wide regime; the hint reached the engine's completions.
        assert THROUGHPUT in levers, levers
        assert levers <= {LATENCY, THROUGHPUT}
        assert sched.flips >= 1
        assert sched.lever in (LATENCY, THROUGHPUT)
    finally:
        verdict = router.close()
        stop_router.set()
        stop.set()
        t.join(10.0)
        rt.join(10.0)
    assert verdict["exactly_once"], verdict
