"""Hand-rolled bucketed ring all-reduce on ``lax.ppermute``.

The north-star (BASELINE.json): reimplement part3's bucketed ring
all-reduce — which the reference delegates to PyTorch DDP's C++ reducer
with ``bucket_cap_mb=25`` (``part3/main.py:137``) — as an *explicit*
``lax.ppermute`` ring over the device axis.

Algorithm (classic two-phase ring, 2·(N−1) steps total):

  1. The flattened gradient vector is padded and viewed as N chunks.
  2. **reduce-scatter** (N−1 steps): at step s, device r sends its running
     partial sum of chunk ``(r − s) mod N`` to its right neighbor
     ``(r+1) mod N`` and adds the chunk it receives from the left into its
     local copy.  After N−1 steps device r holds the *complete* sum of
     chunk ``(r+1) mod N``.
  3. **all-gather** (N−1 steps): the completed chunks circulate around the
     same ring until every device holds the full reduced vector.

Each device moves 2·(N−1)/N of the gradient bytes — the bandwidth-optimal
schedule DDP's ring uses, here riding ICI links via ``ppermute``.

Bucketing: gradients are flattened once (``ravel_pytree``) and split into
``bucket_bytes`` buckets (default 25 MB — the reference's
``bucket_cap_mb=25``).  Buckets are independent rings, so XLA's async
collective scheduler overlaps bucket k's ppermutes with bucket k+1's
adds — the same comm/compute overlap DDP's autograd hooks implement in
C++ (``part3/main.py:59``, group25.pdf p.6), obtained from the compiler
instead of hand-written callbacks.  **Verified, not assumed** (round 4,
``bench/overlap_audit.py``): AOT-compiling the full part3 step for a
real v5e 2×4 target shows 28 async ``collective-permute-start/done``
pairs (= 2 buckets × 2·(N−1) steps), 21 of which have the *other*
bucket's ``slice_add``/``slice_reduce`` fusions scheduled inside their
in-flight window, with up to 2 ppermutes concurrently in flight and the
two buckets' rings interleaved step-for-step — docs/PERF.md "Ring
overlap audit" for the numbers and protocol.

The ring steps use *static* chunk indices (the loop over steps is unrolled;
N is a compile-time mesh constant), so every slice is a static-shape
``lax.slice`` the TPU backend can lay out without dynamic-update overhead.

**Wire compression** (round 7): every hop's payload can be compressed
through a :class:`WireScheme` — the quantized/sparsified multi-hop
all-reduce of the retrieved literature (DynamiQ, arxiv 2602.08923;
"Efficient Training of Convolutional Neural Nets on Large Distributed
Systems", arxiv 1711.00705).  Three codecs behind one interface:

- ``bf16`` — plain dtype cast on the wire (2 bytes/elem, no metadata);
  this is CAST-ONLY lossy compression, not the error-compensated scheme
  of the literature — residual correction lives a layer up, in
  ``parallel/strategies.py::RingAllReduce(error_feedback=True)``.
- ``int8`` — per-chunk symmetric int8 with one fp32 scale per chunk
  (~4x fewer wire bytes).  Each reduce-scatter hop dequantizes, adds
  in fp32, and requantizes — the dequantize–add–requantize fusion of
  the compressed multi-hop all-reduce.  Two implementations behind
  ``codec_impl`` (round 13): ``"xla"`` spells the codec as separate
  XLA ops (quantization arithmetic shared with the serving weight
  quantizer's recipe — ``quantize_int8`` in
  ``ops/pallas/quant_matmul.py`` — applied per chunk), ``"pallas"``
  runs the fused in-register kernels of the shared codec module
  ``ops/pallas/ring_codec.py`` (bitwise-identical payload, residual,
  and output; no dequantized partial ever reaches HBM).
- ``topk`` — magnitude top-k sparsification: (values, indices) on the
  wire, ``k = topk_frac × chunk``; the receiver scatter-adds.

The all-gather phase relays each completed chunk's *encoded payload*
bit-exactly around the ring and decodes it on every rank (including the
owner), so all ranks end the all-reduce with IDENTICAL synced gradients
and replicated params cannot drift — the same invariant the bf16 path
establishes by quantizing the owner's copy once.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.flatten_util import ravel_pytree

DEFAULT_BUCKET_BYTES = 25 * 2**20  # part3/main.py:137 (bucket_cap_mb=25)


def _right_shift_perm(n: int) -> list[tuple[int, int]]:
    """Ring permutation: every device sends to its right neighbor."""
    return [(i, (i + 1) % n) for i in range(n)]


# ---------------------------------------------------------------------------
# Wire schemes — per-chunk codecs for the ring hops.
# ---------------------------------------------------------------------------


class WireScheme:
    """Codec for one ring hop's payload over a flat fp32 chunk.

    ``encode(v) -> tuple[jax.Array, ...]`` produces the arrays that go
    over the wire (each leaf is ppermuted independently);
    ``decode(payload, length) -> jax.Array`` reconstructs a dense fp32
    chunk of ``length`` elements; ``payload_bytes(length)`` is the
    static byte accounting the telemetry counters and the HLO wire-byte
    audit (``bench/overlap_audit.py --wire-bytes``) check against.

    The base class is the exact (identity) scheme.
    """

    name = "none"

    def encode(self, v: jax.Array) -> tuple[jax.Array, ...]:
        return (v,)

    def decode(self, payload: tuple[jax.Array, ...], length: int) -> jax.Array:
        return payload[0]

    def payload_bytes(self, length: int, itemsize: int = 4) -> int:
        return length * itemsize

    # -- fusion seams (round 13) ---------------------------------------
    # The ring loops route every hop through these two methods instead
    # of spelling encode/decode/add/residual inline, so a codec that
    # owns fused kernels (Int8Scheme(impl="pallas")) can collapse each
    # piece to one in-register pass.  The defaults reproduce the
    # historical op-for-op XLA arithmetic exactly.

    def encode_with_residual(self, v: jax.Array):
        """``(payload, err)`` where ``err = v − decode(encode(v))`` is
        the error-feedback send error this encode drops."""
        enc = self.encode(v)
        return enc, v - self.decode(enc, v.shape[0]).astype(v.dtype)

    def decode_add(
        self, payload: tuple[jax.Array, ...], acc: jax.Array, length: int
    ) -> jax.Array:
        """One arrival: decode ``payload`` and accumulate into ``acc``
        (the reduce-scatter hop's dequantize–add)."""
        return acc + self.decode(payload, length).astype(acc.dtype)


class CastScheme(WireScheme):
    """Dtype cast on the wire (``bf16``): halves fp32 bytes, no metadata.

    Cast-only — per-hop rounding error is NOT tracked here; pairing it
    with the strategy layer's error-feedback residual is possible but
    historically this ran bare (the deprecated ``wire_dtype`` knob).
    """

    name = "bf16"

    def __init__(self, dtype=jnp.bfloat16):
        self.dtype = jnp.dtype(dtype)

    def encode(self, v):
        return (v.astype(self.dtype),)

    def decode(self, payload, length):
        return payload[0].astype(jnp.float32)

    def payload_bytes(self, length, itemsize=4):
        return length * self.dtype.itemsize


class Int8Scheme(WireScheme):
    """Per-chunk symmetric int8 + one fp32 scale (~itemsize/1 ≈ 4x fewer
    bytes for fp32 gradients).  Both implementations share ONE recipe,
    defined in the codec module ``ops/pallas/ring_codec.py``
    (:func:`~distributed_machine_learning_tpu.ops.pallas.ring_codec.quantize_chunk_int8`):
    the serving weight quantizer's symmetric ``scale = max|v|/127``
    applied per chunk, with the scale's mantissa truncated to 16 bits
    so every decode product ``q·scale`` is EXACT in f32 — the property
    that makes the fused/XLA parity bitwise by construction (FMA
    contraction cannot perturb an exact product) instead of at the
    mercy of backend fusion decisions.

    ``impl`` (round 13, the ``--ring-codec-impl`` knob): ``"xla"``
    spells encode/decode/residual as separate XLA ops (the historical
    build); ``"pallas"`` dispatches to the fused in-register kernels of
    the same codec module — identical wire payload (bitwise),
    identical residual, no dequantized partial in HBM.  The kernels
    engage on f32 chunks (the dtype every ring path carries — flat
    gradients ravel to f32); a non-f32 chunk falls back to the XLA
    seams, because the kernels accumulate/subtract in f32 and round
    once where the XLA seams compute in the chunk dtype — on f32 the
    two coincide bit for bit, on narrower dtypes they would not, and
    the bitwise contract must hold wherever the kernels run."""

    name = "int8"

    def __init__(self, impl: str = "xla"):
        if impl not in CODEC_IMPLS:
            raise ValueError(
                f"unknown int8 codec impl {impl!r}; choose from "
                f"{CODEC_IMPLS} (the fused kernels live in "
                "ops/pallas/ring_codec.py)"
            )
        self.impl = impl

    def encode(self, v):
        if self.impl == "pallas":
            from distributed_machine_learning_tpu.ops.pallas.ring_codec import (
                encode_int8,
            )

            return encode_int8(v)
        from distributed_machine_learning_tpu.ops.pallas.ring_codec import (
            quantize_chunk_int8,
        )

        return quantize_chunk_int8(v)

    def encode_with_residual(self, v):
        # f32-only kernel engagement (see class docstring): the kernel
        # subtracts in f32 and rounds the residual once, the XLA seam
        # subtracts in the chunk dtype — identical bits on f32 only.
        if self.impl != "pallas" or v.dtype != jnp.float32:
            return super().encode_with_residual(v)
        from distributed_machine_learning_tpu.ops.pallas.ring_codec import (
            encode_int8_residual,
        )

        q, scale, err = encode_int8_residual(v)
        return (q, scale), err

    def decode(self, payload, length):
        q, scale = payload
        if self.impl == "pallas":
            from distributed_machine_learning_tpu.ops.pallas.ring_codec import (
                decode_int8,
            )

            return decode_int8(q, scale, length)
        # Exact product (the truncated scale of ring_codec.chunk_scale
        # bounds q·scale to 24 significand bits), so downstream
        # adds/subtracts cannot be perturbed by FMA contraction —
        # bitwise-identical to the fused kernel in any fusion context.
        return q.astype(jnp.float32) * scale  # scale is [1]; broadcasts

    def decode_add(self, payload, acc, length):
        # f32-only kernel engagement (see class docstring): the kernel
        # accumulates in f32 and rounds the sum once, the XLA seam
        # casts the decode then adds in the accumulator dtype.
        if self.impl != "pallas" or acc.dtype != jnp.float32:
            return super().decode_add(payload, acc, length)
        from distributed_machine_learning_tpu.ops.pallas.ring_codec import (
            decode_add_int8,
        )

        q, scale = payload
        return decode_add_int8(q, scale, acc)

    def payload_bytes(self, length, itemsize=4):
        return length + 4  # int8 chunk + one fp32 scale


class TopKScheme(WireScheme):
    """Magnitude top-k sparsification: ``k = max(1, round(frac·L))``
    (values fp32, indices int32 — 8 bytes per kept element, so the wire
    ratio vs fp32 is ``2·frac``; the default frac 1/8 is 4x fewer
    bytes).  Indices from ``lax.top_k`` are unique, so decode is a
    scatter-``set`` into zeros."""

    name = "topk"

    def __init__(self, frac: float = 0.125):
        if not 0.0 < frac <= 1.0:
            raise ValueError(f"topk frac must be in (0, 1], got {frac}")
        self.frac = float(frac)

    def k_for(self, length: int) -> int:
        return min(length, max(1, int(round(self.frac * length))))

    def encode(self, v):
        k = self.k_for(v.shape[0])
        _, idx = lax.top_k(jnp.abs(v), k)
        return (jnp.take(v, idx), idx.astype(jnp.int32))

    def decode(self, payload, length):
        vals, idx = payload
        return jnp.zeros((length,), jnp.float32).at[idx].set(
            vals.astype(jnp.float32)
        )

    def payload_bytes(self, length, itemsize=4):
        return self.k_for(length) * (itemsize + 4)


WIRE_SCHEMES = ("none", "bf16", "int8", "topk")
CODEC_IMPLS = ("xla", "pallas")


def get_wire_scheme(
    name: str, topk_frac: float = 0.125, codec_impl: str = "xla"
) -> WireScheme:
    """Resolve a ``--ring-compress`` name to a codec instance.

    ``codec_impl`` (``--ring-codec-impl``): ``"pallas"`` routes the
    int8 codec through the fused in-register kernels of
    ``ops/pallas/ring_codec.py`` (bitwise-identical to the XLA build).
    Only int8 has a kernel: ``none``/``bf16`` have nothing to fuse and
    ``topk``'s top-k/scatter stays on the XLA path by design, so the
    knob is a no-op for them.
    """
    if codec_impl not in CODEC_IMPLS:
        raise ValueError(
            f"unknown codec impl {codec_impl!r}; choose from "
            f"{CODEC_IMPLS} (the fused int8 codec kernels live in "
            "ops/pallas/ring_codec.py)"
        )
    if name == "none":
        return WireScheme()
    if name == "bf16":
        return CastScheme(jnp.bfloat16)
    if name == "int8":
        return Int8Scheme(impl=codec_impl)
    if name == "topk":
        return TopKScheme(topk_frac)
    raise ValueError(
        f"unknown wire scheme {name!r}; choose from {WIRE_SCHEMES} "
        "(codecs live in ops/ring.py, the fused int8 kernels in "
        "ops/pallas/ring_codec.py)"
    )


def _resolve_scheme(scheme, wire_dtype) -> WireScheme | None:
    """Back-compat shim: the legacy ``wire_dtype`` kwarg maps onto the
    cast scheme; an explicit ``scheme`` wins.  None = exact (identity
    fast path: the uncompressed program is bit-identical to the
    pre-compression implementation)."""
    if scheme is not None:
        return None if scheme.name == "none" else scheme
    if wire_dtype is not None:
        return CastScheme(wire_dtype)
    return None


def ring_all_reduce_flat(
    x: jax.Array,
    axis_name: str,
    axis_size: int,
    mean: bool = False,
    wire_dtype=None,
    scheme: WireScheme | None = None,
    return_residual: bool = False,
    perm: list[tuple[int, int]] | None = None,
    ring_rank=None,
):
    """All-reduce a flat vector via an explicit ppermute ring.

    Must be called inside ``shard_map`` (or any context where ``axis_name``
    is bound).  ``axis_size`` is the static ring size (mesh axis length).

    ``perm``/``ring_rank`` (round 11): run the ring over a LOGICAL
    sub-axis of the bound mesh axis — ``perm`` is the full permutation
    table (one entry per physical rank; disjoint sub-rings run
    concurrently in each ppermute) and ``ring_rank`` this rank's traced
    position within its sub-ring of size ``axis_size``.  Defaults
    reproduce the flat whole-axis ring.  This is how the hierarchical
    all-reduce (``ops/topology.py``) reuses the codec + error-feedback
    machinery verbatim on the slow outer axis.

    ``scheme`` (a :class:`WireScheme`): compress every hop's payload —
    reduce-scatter hops dequantize–add–requantize, all-gather hops relay
    the encoded payload bit-exactly so every rank decodes the identical
    chunk.  ``wire_dtype`` (e.g. ``jnp.bfloat16``) is the legacy
    cast-only spelling of ``scheme=CastScheme(...)``; None/None = exact.

    ``return_residual``: also return this rank's error-feedback residual
    — COMPLETE local error accounting, zero extra collectives.  Every
    lossy encode in the ring is observed by exactly one rank:

    - *send error*: each reduce-scatter hop's sender sees
      ``partial − decode(encode(partial))`` — the mass that hop drops
      from the downstream accumulation.  Upstream ranks' errors were
      already theirs (the received value is the decode), so summing
      per-send errors over ranks counts every phase-1 drop exactly once;
    - *owner correction*: the rank that completed a chunk is the only
      one that sees both the true reduced chunk and its lossy broadcast
      encode; it re-injects that gap (× N under mean semantics, so the
      next step's mean moves by exactly the gap) — without this term
      the all-gather's loss is invisible to EF.

    Summed over ranks, the residuals equal the all-reduce's total
    compression error — the next step's reduction of ``grad + residual``
    recovers everything the wire dropped this step (EF-SGD with exact
    bookkeeping; arxiv 1711.00705's error compensation, DynamiQ's
    residual accumulation).
    """
    n = axis_size
    if n == 1:
        if return_residual:
            return x, jnp.zeros_like(x)
        return x
    scheme = _resolve_scheme(scheme, wire_dtype)

    orig_len = x.shape[0]
    chunk = -(-orig_len // n)  # ceil division
    padded = jnp.pad(x, (0, n * chunk - orig_len))
    chunks = padded.reshape(n, chunk)
    if perm is None:
        perm = _right_shift_perm(n)
    rank = lax.axis_index(axis_name) if ring_rank is None else ring_rank

    def hop(payload):
        return tuple(lax.ppermute(p, axis_name, perm) for p in payload)

    # Phase 1 — reduce-scatter.  The chunk index each rank touches at step s
    # is rank-dependent (r−s mod n), but ppermute needs every rank to execute
    # the same program; we roll the chunk axis by the (traced) rank once so
    # that the per-step indices become static: after rolling by −r, rank r's
    # "send chunk (r−s)" is row (−s mod n) for every rank.
    chunks = jnp.roll(chunks, -rank, axis=0)  # row i ≡ global chunk (i + r) mod n
    account = scheme is not None and return_residual
    res_rows = jnp.zeros_like(chunks) if account else None
    for s in range(n - 1):
        send_row = (-s) % n
        recv_row = (-s - 1) % n
        v = chunks[send_row]
        if scheme is None:
            recvd = lax.ppermute(v, axis_name, perm)
            chunks = chunks.at[recv_row].add(recvd)
        else:
            # One hop of dequantize–add–requantize: encode the partial,
            # permute the payload, decode-accumulate on arrival; the
            # requantize is the next hop's encode of the updated
            # partial.  Both pieces go through the scheme's fusion
            # seams, so the fused codec (Int8Scheme(impl="pallas"))
            # runs each as one in-register kernel.
            if account:
                # Send error: the mass THIS encode drops from the
                # downstream accumulation (decode(enc) is what the
                # receiver actually adds) — observed by the sender,
                # once per hop across the whole ring.
                enc, err = scheme.encode_with_residual(v)
                res_rows = res_rows.at[send_row].add(err)
            else:
                enc = scheme.encode(v)
            chunks = chunks.at[recv_row].set(
                scheme.decode_add(hop(enc), chunks[recv_row], chunk)
            )
    # Rank r now owns the full sum of global chunk (r+1) mod n == row 1.
    own = chunks[1 % n]
    if mean:
        own = own / n

    # Phase 2 — all-gather the completed chunks around the same ring.
    out = jnp.zeros_like(chunks)
    own_dec = own
    if scheme is None:
        out = out.at[1 % n].set(own)
        cur = own
        for s in range(n - 1):
            cur = lax.ppermute(cur, axis_name, perm)
            # After s+1 hops, the chunk arriving at rank r was completed by
            # rank (r − s − 1), i.e. global chunk (r − s) mod n == local row
            # (−s) mod n.
            out = out.at[(-s) % n].set(cur)
    else:
        # Encode the completed chunk ONCE, store its DECODE (the owner
        # must see exactly what receivers will see, or ranks end the
        # all-reduce with slightly different "synced" gradients and
        # replicated params silently drift apart), then relay the
        # encoded payload bit-exactly — every rank decodes identical
        # bits, so the replication invariant holds for lossy codecs too.
        payload = scheme.encode(own)
        own_dec = scheme.decode(payload, chunk).astype(x.dtype)
        out = out.at[1 % n].set(own_dec)
        for s in range(n - 1):
            payload = hop(payload)
            out = out.at[(-s) % n].set(
                scheme.decode(payload, chunk).astype(x.dtype)
            )
    # Undo the roll to restore global chunk order.
    out = jnp.roll(out, rank, axis=0)
    result = out.reshape(-1)[:orig_len]
    if not return_residual:
        return result
    if scheme is None:
        return result, jnp.zeros_like(x)
    # Owner correction on the owned row (row 1, the only row this rank
    # never sent): phase-1 send errors accumulated above are in SUM
    # units; the broadcast gap is in output units, so × N under mean
    # semantics makes the next step's mean move by exactly the gap.
    factor = float(n) if mean else 1.0
    res_rows = res_rows.at[1 % n].add(factor * (own - own_dec))
    res = jnp.roll(res_rows, rank, axis=0).reshape(-1)[:orig_len]
    return result, res


def _ring_gather_one(shard: jax.Array, axis_name: str, n: int) -> jax.Array:
    """One ring all-gather: local chunk → ``[n, L]`` in global rank
    order, via N−1 ppermute hops.

    Unlike the reduce ring (whose per-step SLICES need static indices,
    hence its roll-by-rank trick), the gather only WRITES — one
    dynamic-update-slice per hop at a traced row index is a single
    static-shape store, so the chunks land directly in global rank
    order and no roll/unroll repacking pass is ever materialized (a
    pair of whole-array permutes that measurably dominated the gather
    on the memcpy-bound CPU host)."""
    L = shard.shape[0]
    perm = _right_shift_perm(n)
    rank = lax.axis_index(axis_name)
    out = jnp.zeros((n, L), shard.dtype)
    # Own chunk is global row ``rank``; the chunk arriving after hop
    # s+1 was sent by rank (r − s − 1), whose chunk is that global row.
    out = lax.dynamic_update_slice(out, shard[None], (rank, 0))
    cur = shard
    for s in range(n - 1):
        cur = lax.ppermute(cur, axis_name, perm)
        out = lax.dynamic_update_slice(
            out, cur[None], ((rank - s - 1) % n, 0)
        )
    return out


def ring_all_gather_flat(
    shard: jax.Array,
    axis_name: str,
    axis_size: int,
    n_buckets: int = 1,
):
    """All-gather a flat shard via the ring's phase-2 structure.

    Rank r holds global chunk r (``shard``); after N−1 ppermute hops
    every rank holds the full ``[N·L]`` vector.  Pure data movement —
    bit-identical to ``lax.all_gather(shard, axis, tiled=True)`` — but
    spelled as a chunked ppermute chain so each hop's DMA gets its own
    async window, reused for the overlap-aware sharded weight update
    (arxiv 2004.13336), where the updated-parameter gather must stop
    feeding ROOT as one monolithic sync collective.

    ``n_buckets > 1`` splits the shard into that many independent rings
    whose hops interleave — the same bucket-pipelining that earns the
    reduce ring its comm/compute overlap (bucket k's DMA in flight
    while bucket k±1's assembly runs; schedule-verified on the v5e AOT
    target: 4 buckets → 4 DMAs concurrently in flight with assembly
    fusions inside the windows).  A single bucket is one serial hop
    chain: async, but with nothing of its own to hide under the DMAs.
    """
    n = axis_size
    if n == 1:
        return shard
    L = shard.shape[0]
    k = max(1, min(n_buckets, L))
    if k == 1:
        return _ring_gather_one(shard, axis_name, n).reshape(-1)
    bounds = [(i * L // k, (i + 1) * L // k) for i in range(k)]
    parts = [
        _ring_gather_one(shard[a:b], axis_name, n)
        for a, b in bounds
    ]
    # Reassemble [n, L] from the per-bucket [n, Lb] blocks, then
    # flatten: global layout is rank-major, bucket-minor.
    return jnp.concatenate(parts, axis=1).reshape(-1)


def _bucket_bounds(n_elems: int, bucket_bytes: int, itemsize: int):
    """(start, stop) element ranges of the ring buckets — ONE definition
    shared by the all-reduce/residual accounting and the static byte
    accounting, so the two can never chunk differently."""
    bucket_elems = max(1, int(bucket_bytes) // itemsize)
    return [
        (i, min(i + bucket_elems, n_elems))
        for i in range(0, n_elems, bucket_elems)
    ]


def ring_all_reduce(
    grads,
    axis_name: str,
    axis_size: int,
    mean: bool = True,
    bucket_bytes: int = DEFAULT_BUCKET_BYTES,
    wire_dtype=None,
    scheme: WireScheme | None = None,
    return_residual: bool = False,
    topology=None,
) -> object:
    """Bucketed ring all-reduce over a gradient pytree.

    ``mean=True`` reproduces DDP's averaging (part3 semantics — SURVEY.md
    §2.4); ``mean=False`` gives the SUM semantics of parts 2a/2b.
    ``scheme``/``wire_dtype``: optional on-the-wire compression;
    ``return_residual``: also return the per-rank error-feedback
    residual pytree (see :func:`ring_all_reduce_flat`).

    ``topology`` (round 11): an ``ops.topology.Topology`` descriptor —
    every bucket is dispatched through ``topology.select(bucket_bytes)``
    to the flat ring, the hierarchical (inner reduce-scatter →
    compressed outer ring → inner all-gather) path, or the
    recursive-halving-doubling latency path.  The descriptor carries the
    per-axis wire schemes, so ``scheme`` is ignored when it is given.
    ``topology=None`` compiles the exact historical flat-ring program.
    """
    flat, unravel = ravel_pytree(grads)
    if axis_size == 1 or flat.shape[0] == 0:
        if return_residual:
            return grads, jax.tree_util.tree_map(jnp.zeros_like, grads)
        return grads
    if topology is not None:
        from distributed_machine_learning_tpu.ops.topology import (
            topology_all_reduce_flat,
        )

        outs = [
            topology_all_reduce_flat(
                flat[start:stop],
                axis_name,
                topology,
                mean=mean,
                return_residual=return_residual,
            )
            for start, stop in _bucket_bounds(
                flat.shape[0], bucket_bytes, flat.dtype.itemsize
            )
        ]
    else:
        outs = [
            ring_all_reduce_flat(
                flat[start:stop],
                axis_name,
                axis_size,
                mean=mean,
                wire_dtype=wire_dtype,
                scheme=scheme,
                return_residual=return_residual,
            )
            for start, stop in _bucket_bounds(
                flat.shape[0], bucket_bytes, flat.dtype.itemsize
            )
        ]
    if return_residual:
        reduced = [o for o, _ in outs]
        residuals = [r for _, r in outs]
        return (
            unravel(reduced[0] if len(reduced) == 1
                    else jnp.concatenate(reduced)),
            unravel(residuals[0] if len(residuals) == 1
                    else jnp.concatenate(residuals)),
        )
    reduced = outs
    return unravel(reduced[0] if len(reduced) == 1 else jnp.concatenate(reduced))


def ring_wire_bytes(
    n_elems: int,
    axis_size: int,
    bucket_bytes: int = DEFAULT_BUCKET_BYTES,
    scheme: WireScheme | None = None,
    itemsize: int = 4,
    topology=None,
) -> int:
    """Static per-device wire bytes of ONE bucketed ring all-reduce:
    ``sum over buckets of 2·(N−1) hops × payload_bytes(chunk)``.

    Pure host arithmetic — the number the ``ring_wire_bytes`` telemetry
    counter accumulates per step, and the number the HLO audit
    (``bench/overlap_audit.py --wire-bytes``) verifies against the
    compiled program's actual collective-permute operand shapes.

    ``topology``: total over both axes of the hierarchical plan (see
    :func:`ring_wire_bytes_by_axis` for the per-axis split).
    """
    if topology is not None:
        return sum(
            ring_wire_bytes_by_axis(
                n_elems, axis_size, bucket_bytes=bucket_bytes,
                scheme=scheme, itemsize=itemsize, topology=topology,
            ).values()
        )
    if axis_size <= 1 or n_elems <= 0:
        return 0
    scheme = scheme or WireScheme()
    total = 0
    for start, stop in _bucket_bounds(n_elems, bucket_bytes, itemsize):
        chunk = -(-(stop - start) // axis_size)
        total += 2 * (axis_size - 1) * scheme.payload_bytes(chunk, itemsize)
    return total


def ring_wire_bytes_by_axis(
    n_elems: int,
    axis_size: int,
    bucket_bytes: int = DEFAULT_BUCKET_BYTES,
    scheme: WireScheme | None = None,
    itemsize: int = 4,
    topology=None,
) -> dict[str, int]:
    """Per-AXIS static wire bytes — the split the round-11 telemetry
    counter labels (``ring_wire_bytes{axis=inner|outer|flat}``) carry
    and the per-axis HLO audit checks against the compiled program.

    Without a topology the flat ring's bytes all ride one undeclared
    link class: ``{"flat": total}``.  With one, each bucket's plan
    (``topology.select``) is accounted hop-by-hop and every hop's bytes
    are attributed by the SAME pair classifier the HLO walker uses
    (``ops.topology.classify_permute_pairs``): a hop whose
    permutation crosses an inner block is inter-node (outer-axis)
    traffic — which for the flat ring on a 2-D topology means ALL of
    its bytes, exactly the bottleneck the hierarchical plan divides by
    ``inner``.
    """
    if topology is None:
        return {"flat": ring_wire_bytes(
            n_elems, axis_size, bucket_bytes=bucket_bytes, scheme=scheme,
            itemsize=itemsize,
        )}
    from distributed_machine_learning_tpu.ops.topology import (
        topology_wire_bytes,
    )

    return topology_wire_bytes(
        n_elems, topology, bucket_bytes=bucket_bytes, itemsize=itemsize,
    )
