"""Ring attention: exact causal self-attention over a sequence-sharded mesh.

Long-context support the reference (a 32×32-image CNN stack, zero attention
— SURVEY.md §5 "long-context: none") never had, built the TPU way: the
sequence is sharded over a mesh axis, every device keeps its Q block
resident, and the K/V blocks rotate around the ring via ``lax.ppermute``
(the same ICI ring the bucketed gradient all-reduce in ``ops/ring.py``
rides).  Softmax is computed *online* — running max / normalizer /
accumulator updated per block (the flash-attention recurrence) — so the
full L×L score matrix never materializes and per-device attention memory
is O(L·L/n): context length scales linearly with the number of chips.

The block loop is unrolled over the static ring size, so XLA sees n-1
independent ppermutes it can overlap with each block's einsums — comm
hides behind compute exactly like the gradient ring.

All score/normalizer arithmetic runs in fp32 regardless of the trunk dtype
(bf16 QKV is fine into the MXU; the logsumexp recurrence is not).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30  # large-negative instead of -inf: keeps exp()/where() NaN-free


def _block_scores(q, k, scale):
    """[B, Lq, H, D] × [B, Lk, H, D] → fp32 scores [B, H, Lq, Lk]."""
    return (
        jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
        * scale
    )


def _online_update(carry, q, k, v, q_pos, k_pos, scale):
    """One flash-attention block update of the (m, l, o) running triple."""
    m, l, o = carry
    s = _block_scores(q, k, scale)  # [B, H, Lq, Lk] fp32
    causal = q_pos[:, None] >= k_pos[None, :]  # [Lq, Lk]
    s = jnp.where(causal[None, None], s, NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))  # [B, H, Lq]
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])  # [B, H, Lq, Lk]
    # Masked entries must contribute 0 even in a fully-masked row (there
    # s == m_new == NEG_INF and the exp above would give 1, not 0).
    p = jnp.where(s > 0.5 * NEG_INF, p, 0.0)
    l_new = l * alpha + p.sum(axis=-1)
    pv = jnp.einsum(
        "bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def ring_self_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    axis_size: int,
) -> jax.Array:
    """Exact causal attention over sequence chunks sharded on ``axis_name``.

    Must run inside ``shard_map``.  ``q``/``k``/``v`` are the local chunks,
    shape [B, L/n, H, D] with global sequence order following the mesh axis
    order; grouped-query K/V may be NARROW ([B, L/n, Hkv, D], Hkv | H) —
    the narrow chunks are what rotates around the ring (ICI bytes ÷ the
    group factor, same saving as the flash ring), widened only at the
    local block math where XLA fuses the broadcast into the einsums.
    Returns the local output chunk, same shape/dtype as ``q``.
    """
    n = axis_size
    B, Lc, H, D = q.shape
    Hkv = k.shape[2]
    if H % Hkv:
        raise ValueError(
            f"query heads ({H}) must be a multiple of K/V heads ({Hkv})"
        )
    rep = H // Hkv
    scale = 1.0 / (D**0.5)
    rank = lax.axis_index(axis_name)
    q_pos = rank * Lc + jnp.arange(Lc)

    m = jnp.full((B, H, Lc), NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, Lc), jnp.float32)
    o = jnp.zeros((B, Lc, H, D), jnp.float32)
    carry = (m, l, o)

    perm = [(i, (i + 1) % n) for i in range(n)]
    kv = (k, v)

    def widen(t):
        return jnp.repeat(t, rep, axis=2) if rep > 1 else t

    for s in range(n):
        # After s right-shifts this device holds the K/V chunk that
        # originated on rank − s.
        kv_rank = (rank - s) % n
        k_pos = kv_rank * Lc + jnp.arange(Lc)
        carry = _online_update(
            carry, q, widen(kv[0]), widen(kv[1]), q_pos, k_pos, scale
        )
        if s < n - 1:
            kv = lax.ppermute(kv, axis_name, perm)

    m, l, o = carry
    # Fully-masked rows (none, under causal: every q sees at least itself)
    # would have l == 0; guard anyway so the op is safe for future masks.
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def dense_self_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    positions: jax.Array | None = None,
) -> jax.Array:
    """Single-device exact causal attention — the ring op's reference
    semantics (and the attention used when the model runs unsharded).

    [B, L, H, D] in, [B, L, H, D] out.
    """
    B, L, H, D = q.shape
    if positions is None:
        positions = jnp.arange(L)
    s = _block_scores(q, k, 1.0 / (D**0.5))
    causal = positions[:, None] >= positions[None, :]
    s = jnp.where(causal[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)
