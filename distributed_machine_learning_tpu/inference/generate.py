"""Autoregressive generation with a KV cache.

The reference has no inference path at all (its ``test_model`` is
classification eval — ``part1/main.py:62-77``); this module is the LM
serving half this framework adds: prefill the prompt once, then decode
one token per step against per-layer K/V caches
(``models/transformer.py`` ``decode=True``), the whole loop a single
jitted program (`lax.scan`) — no per-token Python dispatch, which on a
remote/tunneled TPU would cost more than the step itself (same argument
as bench.py's scanned epoch).

TPU notes: the decode step is memory-bound (matvec against the cache),
so the cache stays in the model's compute dtype (bf16 halves HBM
traffic); sampling math is fp32.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def warp_logits(logits, temperature: float, top_k: int | None,
                top_p: float | None):
    """The sampling warper chain, HF-warper order: temperature FIRST,
    then ``top_k``, then ``top_p`` (nucleus sampling, Holtzman et al.:
    the smallest token set whose TEMPERED probability mass ≥ p) over
    the survivors.  Returns f32 logits with masked entries at -inf.
    The ONE warper shared by ``_sample`` and the speculative decoder
    (``inference/speculative.py``) — guards and semantics cannot drift.
    ``temperature`` must be > 0 (greedy has its own exact path)."""
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None:
        if top_k > logits.shape[-1]:
            raise ValueError(
                f"top_k={top_k} exceeds the vocabulary size "
                f"{logits.shape[-1]}"
            )
        # lax.top_k is O(V·k) vs a full O(V log V) sort — this runs once
        # per decoded token inside the scan, so it matters at real vocabs.
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None:
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        # Nucleus: sort descending, keep the prefix whose cumulative
        # probability is < p PLUS the first token crossing it (so the
        # kept mass is >= p and at least one token always survives).
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep_sorted = cum - probs < top_p  # prefix + the crossing token
        # Threshold logit = smallest kept logit per row; mask below it.
        thresh = jnp.min(
            jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1,
            keepdims=True,
        )
        logits = jnp.where(logits < thresh, -jnp.inf, logits)
    return logits


def _sample(logits, rng, temperature: float, top_k: int | None,
            top_p: float | None = None):
    """One sampling decision per batch row.  [B, V] fp32 → [B] int32.
    Greedy (``temperature=0``) returns before any masking — argmax is
    invariant to it, and the nucleus sort is O(V log V) per decoded
    token inside the scan."""
    if temperature == 0.0:  # greedy (static: part of the compiled program)
        return jnp.argmax(
            logits.astype(jnp.float32), axis=-1
        ).astype(jnp.int32)
    return jax.random.categorical(
        rng, warp_logits(logits, temperature, top_k, top_p), axis=-1
    ).astype(jnp.int32)


def make_generate_fn(
    model,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    quantize: str | None = None,
    top_p: float | None = None,
    eos_id: int | None = None,
):
    """Build a jitted ``fn(params, prompt, rng) -> tokens``.

    ``prompt``: [B, Lp] int32; returns [B, Lp + max_new_tokens] with the
    prompt preserved as a prefix.  ``temperature=0`` is greedy decoding
    (``rng`` unused); ``top_k`` restricts sampling to the k highest
    logits.  The model is cloned to dense cached attention — parameters
    from any training-time ``attn_impl`` (ring/ulysses/flash share the
    exact same parameter structure) drop in unchanged.

    ``quantize="int8"`` serves weight-only int8: pass params already
    converted by ``ops.quant.quantize_lm_params`` (the ``generate``
    wrapper converts for you) — decode is weight-bandwidth-bound, so
    halving the weight bytes is ~the step-time divisor (docs/PERF.md).

    ``eos_id`` (ISSUE 19): with an EOS token set, decode runs as a
    ``lax.while_loop`` that exits as soon as EVERY row has emitted
    ``eos_id`` — a short batch stops paying ``max_new_tokens`` steps.
    Rows that finish early emit ``eos_id`` for their remaining slots
    (the output shape stays static), and their pre-EOS tokens are
    token-for-token identical to the ``eos_id=None`` run — asserted in
    ``tests/test_serving.py``.  ``eos_id=None`` keeps the original
    fixed-length ``lax.scan`` program bit-for-bit.
    """
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if quantize not in (None, "int8"):
        raise ValueError(f"quantize must be None or 'int8', got {quantize!r}")
    dm = model.clone(attn_impl="dense", decode=True, weight_quant=quantize)
    sample = partial(_sample, temperature=temperature, top_k=top_k,
                     top_p=top_p)
    return jax.jit(partial(_generate_body, dm, sample, max_new_tokens,
                           eos_id))


def _generate_body(dm, sample, max_new_tokens, eos_id, params, prompt, rng):
    """The traced generate program (prefill + decode scan) — shared by
    the single-device jit (:func:`make_generate_fn`) and the manual-TP
    shard_map wrap (:func:`make_tp_generate_fn`), so the two paths can
    never drift."""
    B, Lp = prompt.shape
    max_len = Lp + max_new_tokens
    # Cache layout via eval_shape (no FLOPs): init in decode mode
    # with a [B, cache_len] input sizing every layer's K/V cache.
    # The allocation rounds up to a 512 multiple so the cache tiles
    # into the flash-decode kernel's S blocks
    # (ops/pallas/decode_attention.py) — the frontier-clamped DMA
    # never reads the pad slots, so the only cost is their
    # allocation.
    cache_len = -(-max_len // 512) * 512
    shapes = jax.eval_shape(
        lambda: dm.init(
            jax.random.PRNGKey(0),
            jnp.zeros((B, cache_len), jnp.int32),
            train=False,
        )
    )["cache"]
    cache = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes
    )

    # Prefill: one pass over the whole prompt fills slots [0, Lp).
    logits, vars_ = dm.apply(
        {"params": params, "cache": cache}, prompt, train=False,
        mutable=["cache"],
    )
    rng, r = jax.random.split(rng)
    tok = sample(logits[:, -1], r)  # first generated token

    if eos_id is None:
        def body(carry, _):
            cache, tok, rng = carry
            logits, vars_ = dm.apply(
                {"params": params, "cache": cache}, tok[:, None],
                train=False, mutable=["cache"],
            )
            rng, r = jax.random.split(rng)
            nxt = sample(logits[:, -1], r)
            return (vars_["cache"], nxt, rng), tok

        (_, last, _), toks = lax.scan(
            body, (vars_["cache"], tok, rng), None,
            length=max_new_tokens - 1,
        )
        # toks: [max_new-1, B] tokens 1..max_new-1; `last` is the final.
        gen = jnp.concatenate([toks, last[None]], axis=0).swapaxes(0, 1)
        return jnp.concatenate([prompt, gen], axis=1)

    # EOS early-exit (ISSUE 19): a while_loop that stops the moment
    # every row has finished.  Finished rows keep riding the batch
    # (the program stays batch-static; their cache writes are masked
    # into irrelevance by forcing their tokens to eos), but once ALL
    # rows are done the remaining decode steps are never issued —
    # that is the "finished sequences stop consuming decode steps"
    # fix for the batch-static serving path.
    eos = jnp.int32(eos_id)
    done = tok == eos
    buf = jnp.full((B, max_new_tokens), eos, jnp.int32)
    buf = buf.at[:, 0].set(tok)

    def cond(carry):
        _, _, _, _, done, i = carry
        return jnp.logical_and(i < max_new_tokens,
                               jnp.logical_not(jnp.all(done)))

    def body(carry):
        cache, tok, rng, buf, done, i = carry
        logits, vars_ = dm.apply(
            {"params": params, "cache": cache}, tok[:, None],
            train=False, mutable=["cache"],
        )
        rng, r = jax.random.split(rng)
        nxt = sample(logits[:, -1], r)
        nxt = jnp.where(done, eos, nxt)
        done = jnp.logical_or(done, nxt == eos)
        buf = buf.at[:, i].set(nxt)
        return (vars_["cache"], nxt, rng, buf, done, i + 1)

    _, _, _, buf, _, _ = lax.while_loop(
        cond, body,
        (vars_["cache"], tok, rng, buf, done, jnp.int32(1)),
    )
    return jnp.concatenate([prompt, buf], axis=1)


def tp_local_decode_clone(model, mesh, model_axis: str,
                          quantize: str | None):
    """Validate the Megatron decode layout's divisibility rules and
    clone ``model`` at its LOCAL width (heads, KV heads, d_ff ÷ tp;
    head_dim pinned global; ``tp_axis`` set so the model's psums
    complete each row-parallel projection).  The ONE place those rules
    live — shared by :func:`make_tp_generate_fn` and the speculative TP
    wrapper (``inference/speculative.py``), so the two cannot drift."""
    if quantize not in (None, "int8"):
        raise ValueError(f"quantize must be None or 'int8', got {quantize!r}")
    if model_axis not in mesh.axis_names:
        raise ValueError(
            f"mesh is missing axis {model_axis!r}: {mesh.axis_names}"
        )
    tp = mesh.shape[model_axis]
    if model.n_heads % tp:
        raise ValueError(
            f"n_heads={model.n_heads} must be divisible by tp={tp}"
        )
    n_kv = model.n_kv_heads
    if n_kv is not None and n_kv % tp:
        raise ValueError(
            f"n_kv_heads={n_kv} must be divisible by tp={tp}"
        )
    d_ff = model.d_ff or 4 * model.d_model
    if d_ff % tp:
        raise ValueError(f"d_ff={d_ff} must be divisible by tp={tp}")
    return model.clone(
        n_heads=model.n_heads // tp,
        n_kv_heads=None if n_kv is None else n_kv // tp,
        d_ff=d_ff // tp,
        # Global per-head width (honoring an explicit override).
        head_dim=model.head_dim or model.d_model // model.n_heads,
        attn_impl="dense", decode=True, weight_quant=quantize,
        tp_axis=model_axis,
    )


def tp_param_specs(params, model_axis: str):
    """The TP decode in_specs tree for params arranged by
    ``tp_decode_params`` — one leaf-path → PartitionSpec mapping for
    every TP decode factory."""
    from distributed_machine_learning_tpu.parallel.tensor_parallel import (
        tp_decode_spec_for,
    )

    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: tp_decode_spec_for(
            tuple(k.key if hasattr(k, "key") else str(k) for k in path),
            leaf.ndim if hasattr(leaf, "ndim") else 0,
            model_axis,
        ),
        params,
    )


def make_tp_generate_fn(
    model,
    max_new_tokens: int,
    mesh,
    temperature: float = 0.0,
    top_k: int | None = None,
    quantize: str | None = None,
    model_axis: str = "model",
    top_p: float | None = None,
    eos_id: int | None = None,
):
    """Tensor-parallel generation: ``fn(params, prompt, rng) -> tokens``.

    The Megatron decode layout, written as a fully-manual shard_map over
    ``model_axis``: each device runs the SAME generate program as the
    single-device path (:func:`_generate_body`) on a model clone at its
    LOCAL width (``n_heads=H/tp``, ``n_kv_heads=Hkv/tp``,
    ``d_ff=F/tp``), with the model's ``tp_axis`` psums completing the
    row-parallel out-projection and fc_out (``models/transformer.py``).
    Every Pallas kernel on the path — flash prefill, the decode cache
    kernel, the int8 weight-reading matmul — sees purely local shapes
    and never meets the GSPMD partitioner: this is how ``--quant int8``
    composes with TP.  The KV cache is born head-sharded (each device's
    cache holds its Hkv/tp heads — the cache memory ÷ tp).

    ``params`` must be pre-arranged by
    ``parallel.tensor_parallel.tp_decode_params`` (row-parallel biases
    ÷ tp; fused quantized column blocks re-ordered head-contiguous);
    pass them global — the shard_map in_specs slice each device's
    shard.  Sampling runs replicated (same rng, same logits on every
    device), so the returned tokens are identical across devices.
    """
    from jax.sharding import PartitionSpec as P

    from distributed_machine_learning_tpu.runtime.mesh import (
        shard_map_no_check,
    )

    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    local = tp_local_decode_clone(model, mesh, model_axis, quantize)
    sample = partial(_sample, temperature=temperature, top_k=top_k,
                     top_p=top_p)
    body = partial(_generate_body, local, sample, max_new_tokens, eos_id)

    jitted: dict = {}

    def run(params, prompt, rng):
        key = jax.tree_util.tree_structure(params)
        fn = jitted.get(key)
        if fn is None:
            fn = jitted[key] = jax.jit(shard_map_no_check(
                body,
                mesh=mesh,
                in_specs=(tp_param_specs(params, model_axis), P(), P()),
                out_specs=P(),
            ))
        return fn(params, prompt, rng)

    return run


def generate(
    model,
    params,
    prompt,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    rng=None,
    quantize: str | None = None,
    top_p: float | None = None,
    eos_id: int | None = None,
):
    """One-shot convenience wrapper around :func:`make_generate_fn`.

    For repeated generation at fixed shapes, build the fn once instead —
    this wrapper retraces on every call.  ``quantize="int8"`` converts
    the (full-precision) params with ``quantize_lm_params`` here.
    """
    fn = make_generate_fn(model, max_new_tokens, temperature, top_k,
                          quantize=quantize, top_p=top_p, eos_id=eos_id)
    if quantize == "int8":
        from distributed_machine_learning_tpu.ops.quant import (
            quantize_lm_params,
        )

        params = quantize_lm_params(params)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    return fn(params, jnp.asarray(prompt, jnp.int32), rng)


def make_serving_step(
    model,
    params,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int | None = None,
    quantize: str | None = None,
    top_p: float | None = None,
    rng=None,
    eos_id: int | None = None,
):
    """The step-callable seam for the serving fleet (ISSUE 16): wrap
    the batch-static decode program as ``step(prompts) -> outputs``
    over plain python token lists — the signature
    ``runtime/serving_worker.py`` drives, so the worker serves requests
    without forking this module.

    Ragged micro-batches are grouped by prompt length and each group
    runs as one batched call (the program stays batch-static; expect
    one jit cache entry per distinct ``(batch, length)`` shape — a
    router with a fixed ``micro_batch`` converges on a handful).  The
    RNG threads through calls so repeated sampling steps never reuse a
    key.

    ``eos_id`` fixes the semantics drift this path had vs
    ``generate``: without it every group decodes ``max_new_tokens``
    unconditionally; with it a group's while_loop exits once all its
    rows emit EOS and finished rows pad with ``eos_id`` (see
    :func:`make_generate_fn`).  The group-level exit is the
    batch-static ceiling — per-sequence retirement is what the
    continuous engine (``inference/continuous.py``) adds.
    """
    fn = make_generate_fn(model, max_new_tokens, temperature, top_k,
                          quantize=quantize, top_p=top_p, eos_id=eos_id)
    if quantize == "int8":
        from distributed_machine_learning_tpu.ops.quant import (
            quantize_lm_params,
        )

        params = quantize_lm_params(params)
    state = {"rng": rng if rng is not None else jax.random.PRNGKey(0)}

    def step(prompts):
        if any(len(p) == 0 for p in prompts):
            raise ValueError("serving step got an empty prompt")
        outs: list = [None] * len(prompts)
        by_len: dict[int, list[int]] = {}
        for i, p in enumerate(prompts):
            by_len.setdefault(len(p), []).append(i)
        for length in sorted(by_len):
            idxs = by_len[length]
            batch = jnp.asarray([list(map(int, prompts[i]))
                                 for i in idxs], jnp.int32)
            state["rng"], call_rng = jax.random.split(state["rng"])
            tokens = jax.device_get(fn(params, batch, call_rng))
            for row, i in zip(tokens.tolist(), idxs):
                outs[i] = [int(t) for t in row]
        return outs

    return step
