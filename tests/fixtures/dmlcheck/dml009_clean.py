# dmlcheck-virtual-path: distributed_machine_learning_tpu/runtime/fixture.py
"""DML009 clean case: flush-then-die re-raise, and the producer-thread
channel pattern (the exception is handed off, not eaten)."""


def worker_loop(step_once, telemetry):
    try:
        while True:
            step_once()
    except SystemExit:
        telemetry.flush()        # flush-then-die
        raise


def producer(source, put, failure):
    try:
        for batch in source():
            put(batch)
    except BaseException as exc:
        failure.append(exc)      # reaches the consumer thread
