"""Interleaved pipeline schedule — virtual stages cut the bubble by v.

GPipe and 1F1B give each device ONE contiguous span of layers, so a
microbatch crosses the machine in P hops and the pipeline idles for
(P−1)/(M+P−1) of the time.  The interleaved schedule (Megatron's
"virtual pipeline stages") gives each device v NON-contiguous chunks —
chunk c on device s holds global span c·P+s — so the layer order
visits every device v times.  Work per hop shrinks v-fold while the
number of in-flight hops stays P−1, and the bubble drops to

    (P−1) / (v·M + P−1)

at the cost of v× as many (v-fold smaller) ppermute hops.

The schedule reduces to startlingly little code because of a clean
arithmetic fact.  Process microbatches in groups of P and let device s
at tick t decode its work from u = t − s:

    i = u mod P          (microbatch within the group)
    c = (u div P) mod v  (which of this device's chunks)
    g = u div (v·P)      (group index)  →  microbatch m = g·P + i

The decomposition is unique, every device does exactly one span-step
per tick, and the single activation ppermuted along the ring each tick
is EXACTLY the one the next device's own (u = t − s) decomposition
expects — including the wrap from device P−1 back to device 0 at chunk
boundaries, which needs no special case at all.  Injection happens on
device 0 when c == 0; the loss peels on device P−1 when c == v−1.
Total ticks: v·M + P − 1 (M padded up to a multiple of P by masking).

The backward needs no hand-written schedule: like GPipe, ``jax.grad``
of the tick scan IS the reverse interleaved pipeline (the transpose of
``ppermute`` is the reverse ring).  Activation memory is O(v·M) per
device like GPipe — the memory-lean interleaved-1F1B hybrid is the
known next rung; this module contributes the BUBBLE lever, 1F1B
(``parallel/pipeline_1f1b.py``) the memory lever.

Parameter layout: blocks are stacked so the ``pipe``-sharded leading
axis hands device s its v chunks contiguously (chunk-major within the
device) — ``stack_interleaved`` / ``unstack_interleaved`` convert from
and to the plain per-layer tree.  Inside the step the local stack
``[v·Lc, ...]`` is sliced per tick at chunk c (``dynamic_slice``, Lc
layers) and applied with the same ``_apply_local_span`` scan the other
schedules use.

Update-equivalence to GPipe (same loss, same grads, any M, P, v) is
property-tested in ``tests/test_pipeline_interleaved.py``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from distributed_machine_learning_tpu.models.transformer import TransformerLM
from distributed_machine_learning_tpu.parallel.pipeline import (
    PIPE_AXIS,
    _apply_local_span,
    _block_module,
    _whole_layer_remat,
    make_pipeline_step,
)
from distributed_machine_learning_tpu.train.losses import lm_cross_entropy
from distributed_machine_learning_tpu.train.state import TrainState


def interleaved_layout_tag(num_stages: int, v: int) -> str:
    """Checkpoint layout tag for this stacking (see
    ``train/checkpoint.py::save_checkpoint``) — the ONE encoder
    ``parse_interleaved_layout`` inverts."""
    return f"pp-interleaved-P{num_stages}-v{v}"


def parse_interleaved_layout(tag: str) -> tuple[int, int] | None:
    """(num_stages, v) from an interleaved layout tag; None if the tag
    names a different layout.

    A tag that *claims* to be interleaved (``pp-interleaved-`` prefix)
    but does not parse raises instead of returning None: falling through
    to a contiguous-unstack would silently load permuted layer weights.
    """
    import re

    tag = tag or ""
    m = re.fullmatch(r"pp-interleaved-P(\d+)-v(\d+)", tag)
    if m:
        return int(m.group(1)), int(m.group(2))
    if tag.startswith("pp-interleaved-"):
        raise ValueError(
            f"unrecognized interleaved pipeline layout tag {tag!r} "
            "(expected 'pp-interleaved-P<stages>-v<chunks>'); refusing "
            "to fall back to a contiguous unstack, which would permute "
            "layer weights"
        )
    return None


def _interleaved_order(n_layers: int, num_stages: int, v: int) -> list[int]:
    """Global layer indices in the interleaved stacking order: for each
    device s, its v chunks (span c·P+s) in chunk order — the ONE
    definition ``stack_interleaved``/``unstack_interleaved`` must agree
    on to stay mutually inverse."""
    lc = n_layers // (num_stages * v)
    return [
        layer
        for s in range(num_stages)
        for c in range(v)
        for layer in range((c * num_stages + s) * lc,
                           (c * num_stages + s + 1) * lc)
    ]


def stack_interleaved(params: dict, n_layers: int, num_stages: int,
                      v: int) -> dict:
    """Plain per-layer params → interleaved pipeline layout: a ``P(pipe)``
    sharding of the stacked axis hands every device exactly its chunks."""
    order = _interleaved_order(n_layers, num_stages, v)
    blocks = [params[f"block_{i}"] for i in order]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *blocks)
    return {
        "embed": params["embed"],
        "blocks": stacked,
        "ln_f": params["ln_f"],
        "lm_head": params["lm_head"],
    }


def unstack_interleaved(pipeline_params: dict, n_layers: int,
                        num_stages: int, v: int) -> dict:
    """Inverse of ``stack_interleaved`` (checkpoint interop/eval)."""
    order = _interleaved_order(n_layers, num_stages, v)
    out = {
        "embed": pipeline_params["embed"],
        "ln_f": pipeline_params["ln_f"],
        "lm_head": pipeline_params["lm_head"],
    }
    for pos, layer in enumerate(order):
        out[f"block_{layer}"] = jax.tree_util.tree_map(
            lambda x, pos=pos: x[pos], pipeline_params["blocks"]
        )
    return out


def init_interleaved_state(model: TransformerLM, num_stages: int, v: int,
                           seed: int = 69143, config=None) -> TrainState:
    """Initialize TransformerLM params (dense path) and restack them in
    the interleaved order for a P-stage, v-chunk pipeline."""
    from distributed_machine_learning_tpu.train.lm_step import init_lm_state

    if model.n_layers % (num_stages * v):
        raise ValueError(
            f"n_layers={model.n_layers} must divide evenly into "
            f"{num_stages} stages x {v} chunks"
        )
    state = init_lm_state(model, seed=seed, config=config)
    return TrainState.create(
        params=stack_interleaved(state.params, model.n_layers, num_stages, v),
        rng=state.rng,
        config=state.config,
    )


def _interleaved_forward_loss(
    model: TransformerLM,
    params: dict,
    tokens_mb,  # [M, mb, L] int32 (replicated)
    targets_mb,  # [M, mb, L] int32
    *,
    pipe_axis: str,
    num_stages: int,
    v: int,
):
    import flax.linen as nn

    block = _block_module(model)
    M, mb, L = tokens_mb.shape
    E = model.d_model
    P_ = num_stages
    lc = model.n_layers // (P_ * v)
    rank = lax.axis_index(pipe_axis)
    positions = jnp.arange(L)
    is_first = rank == 0
    is_last = rank == P_ - 1
    perm = [(i, (i + 1) % P_) for i in range(P_)]
    groups = -(-M // P_)  # groups of P microbatches, padded by masking
    T = v * groups * P_ + P_ - 1

    embed_mod = nn.Embed(model.vocab_size, E, dtype=model.compute_dtype)
    ln_f_mod = nn.LayerNorm(dtype=model.compute_dtype)
    head_mod = nn.Dense(model.vocab_size, dtype=model.compute_dtype)

    def embed(tok):
        return embed_mod.apply({"params": params["embed"]}, tok)

    def head_loss(x, tgt):
        h = ln_f_mod.apply({"params": params["ln_f"]}, x)
        logits = head_mod.apply({"params": params["lm_head"]}, h)
        return lm_cross_entropy(logits.astype(jnp.float32), tgt)

    def chunk_params(c):
        """This device's chunk c: Lc layers dynamically sliced from the
        local [v·Lc, ...] stack."""
        return jax.tree_util.tree_map(
            lambda x: lax.dynamic_slice_in_dim(x, c * lc, lc, axis=0),
            params["blocks"],
        )

    def tick_core(act, loss_acc, t):
        u = t - rank
        i = jnp.where(u >= 0, u, 0)
        mb_i = i % P_
        c = (i // P_) % v
        g = i // (v * P_)
        m = g * P_ + mb_i
        valid = (u >= 0) & (u < v * groups * P_) & (m < M)

        inject = embed(
            lax.dynamic_index_in_dim(tokens_mb, jnp.clip(m, 0, M - 1),
                                     keepdims=False)
        )
        x = jnp.where(is_first & (c == 0) & valid, inject, act)
        y = _apply_local_span(block, chunk_params(c), x, positions,
                              remat=_whole_layer_remat(model))
        tgt = lax.dynamic_index_in_dim(
            targets_mb, jnp.clip(m, 0, M - 1), keepdims=False
        )
        peel = (is_last & (c == v - 1) & valid).astype(jnp.float32)
        return y, loss_acc + peel * head_loss(y, tgt)

    def tick(carry, t):
        act, loss_acc = carry
        y, loss_acc = tick_core(act, loss_acc, t)
        return (lax.ppermute(y, pipe_axis, perm), loss_acc), None

    act = jnp.zeros((mb, L, E), model.compute_dtype)
    loss_acc = jnp.zeros((), jnp.float32)
    (act, loss_acc), _ = lax.scan(tick, (act, loss_acc), jnp.arange(T - 1))
    _, loss_acc = tick_core(act, loss_acc, jnp.asarray(T - 1))
    return loss_acc / M


def _ppi_step_impl(
    model, state: TrainState, tokens_mb, targets_mb, *, pipe_axis,
    num_stages, v,
):
    from distributed_machine_learning_tpu.parallel.pipeline import (
        pp_grads_and_update,
    )

    loss_fn = partial(
        _interleaved_forward_loss,
        model,
        tokens_mb=tokens_mb,
        targets_mb=targets_mb,
        pipe_axis=pipe_axis,
        num_stages=num_stages,
        v=v,
    )
    return pp_grads_and_update(state, loss_fn, pipe_axis)


def make_pp_interleaved_lm_train_step(
    model: TransformerLM,
    mesh: Mesh,
    num_microbatches: int,
    v: int,
    pipe_axis: str = PIPE_AXIS,
):
    """Build the interleaved ``step(state, tokens_mb, targets_mb)`` —
    state from ``init_interleaved_state(model, P, v)`` + the shared
    ``shard_pp_state``.  ``v`` is the virtual-stage (chunk) count per
    device; ``v == 1`` degenerates to GPipe's schedule exactly.
    Requires ``n_layers % (P·v) == 0``.
    """
    num_stages = mesh.shape[pipe_axis]
    if v < 1:
        raise ValueError(f"v (virtual stages per device) must be >= 1, "
                         f"got {v}")
    if model.n_layers % (num_stages * v):
        raise ValueError(
            f"n_layers={model.n_layers} must divide evenly into "
            f"{num_stages} stages x {v} chunks"
        )

    def step_impl(m, state, x, y, *, pipe_axis, num_stages):
        return _ppi_step_impl(m, state, x, y, pipe_axis=pipe_axis,
                              num_stages=num_stages, v=v)

    return make_pipeline_step(step_impl, model, mesh, num_microbatches,
                              pipe_axis)
