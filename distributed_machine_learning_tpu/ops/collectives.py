"""Gradient-sync collectives over a named mesh axis.

TPU-native replacements for the reference's gloo primitives (SURVEY.md
§2.2): these run inside ``shard_map`` over a ``jax.sharding.Mesh`` axis,
so XLA lowers them to ICI collectives (intra-slice) or DCN (cross-slice)
— there is no hand-written transport layer to maintain, unlike gloo/TCP.
"""

from __future__ import annotations

import jax
from jax import lax


def all_reduce_sum(grads, axis_name: str):
    """``dist.all_reduce(SUM)`` per parameter (part2/2b/main.py:101-106).

    The reference deliberately SUMs and never divides by world size
    (SURVEY.md §2.4) — ``lax.psum`` reproduces that exactly.  One psum per
    leaf, like the reference's one all_reduce per parameter tensor; XLA
    fuses/schedules these (and can overlap them with surrounding compute),
    which is the job DDP's bucketing C++ does by hand.
    """
    return jax.tree_util.tree_map(lambda g: lax.psum(g, axis_name), grads)


def all_reduce_mean(grads, axis_name: str):
    """DDP averaging semantics (part3: grads arrive averaged — SURVEY.md §2.4)."""
    return jax.tree_util.tree_map(lambda g: lax.pmean(g, axis_name), grads)


def gather_scatter_sum(grads, axis_name: str):
    """The part2a centralized pattern, SPMD-honestly (part2/2a/main.py:89-116).

    The reference gathers every rank's gradient to rank 0, sums there in
    rank order, and scatters the sum back — a centralized pattern alien to
    SPMD (SURVEY.md §7.3).  The honest TPU equivalent: ``all_gather`` every
    rank's contribution to every rank, then let each rank perform the same
    rank-0-ordered summation locally.  Every rank ends with bit-identical
    results — the same postcondition as gather+scatter, with the fp32
    reduction happening in the same rank order (0,1,...,N-1) the
    reference's in-place loop at ``part2/2a/main.py:104-107`` uses.  The
    rank-0 traffic concentration (report: ~3× — group25.pdf p.4) is a gloo
    artifact with no ICI analogue.
    """

    def _sync(g):
        gathered = lax.all_gather(g, axis_name)  # leading axis = rank order
        # jnp.sum over a leading axis reduces in index order, matching the
        # reference's sequential rank-0 accumulation.
        return gathered.sum(axis=0)

    return jax.tree_util.tree_map(_sync, grads)
