"""Minimal batched loader.

Replaces the reference's ``DataLoader(batch_size, shuffle=False,
pin_memory=True)`` (``part2/2a/main.py:162-167``).  Because augmentation
and normalization moved on-device (``augment.py``), the host side reduces
to contiguous uint8 slicing — there is nothing left for worker processes
to do, so no multiprocessing machinery is needed (pin_memory has no TPU
equivalent; transfers stage through the runtime).  A background-thread
prefetcher overlaps the (tiny) host slicing + H2D with device compute.
A C++ fast path for parsing/slicing lives in ``native/`` (see
``native_loader.py``).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator

import numpy as np

from distributed_machine_learning_tpu.data.cifar10 import Dataset


class BatchLoader:
    """Iterates (images_u8, labels) batches over given indices.

    drop_last=False like the reference's DataLoader: the final short batch
    is yielded as-is (the reference's 40-iteration cap makes this moot for
    training, but eval consumes the full test set — part1/main.py:67).
    """

    def __init__(
        self,
        dataset: Dataset,
        batch_size: int,
        indices: np.ndarray | None = None,
        prefetch: int = 2,
    ):
        if batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {batch_size}")
        self.dataset = dataset
        self.batch_size = batch_size
        self.indices = (
            np.arange(len(dataset)) if indices is None else np.asarray(indices)
        )
        self.prefetch = prefetch

    def __len__(self) -> int:
        return (len(self.indices) + self.batch_size - 1) // self.batch_size

    def _batches(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        imgs, labels = self.dataset.images, self.dataset.labels
        for start in range(0, len(self.indices), self.batch_size):
            idx = self.indices[start : start + self.batch_size]
            yield imgs[idx], labels[idx]

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        if self.prefetch <= 0:
            yield from self._batches()
            return
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()
        sentinel = object()

        def producer():
            for batch in self._batches():
                # Bounded put that aborts if the consumer goes away (the
                # training loop breaks at its 40-iteration cap mid-epoch —
                # part1/main.py:32-33 — so early abandonment is the norm).
                while not stop.is_set():
                    try:
                        q.put(batch, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
            while not stop.is_set():
                try:
                    q.put(sentinel, timeout=0.1)
                    return
                except queue.Full:
                    continue

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    break
                yield item
        finally:
            stop.set()
            t.join()
