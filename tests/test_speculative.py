"""Speculative decoding (inference/speculative.py): the draft must
change SPEED, never the distribution — greedy output is pinned bitwise
to the target-only stream."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.inference.generate import (
    make_generate_fn,
)
from distributed_machine_learning_tpu.inference.speculative import (
    make_speculative_generate_fn,
)
from distributed_machine_learning_tpu.models.transformer import TransformerLM
from distributed_machine_learning_tpu.train.lm_step import init_lm_state

VOCAB = 48


def _models():
    target = TransformerLM(vocab_size=VOCAB, d_model=32, n_layers=3,
                           n_heads=4)
    draft = TransformerLM(vocab_size=VOCAB, d_model=16, n_layers=1,
                          n_heads=2)
    return (target, init_lm_state(target).params,
            draft, init_lm_state(draft, seed=7).params)


@pytest.mark.parametrize("gamma", [1, 3, 5])
def test_greedy_speculative_bitwise_equals_vanilla(rng, gamma):
    """Any draft — here an unrelated random model with terrible
    acceptance — must produce EXACTLY the target's greedy stream."""
    target, tparams, draft, dparams = _models()
    prompt = jnp.asarray(rng.integers(0, VOCAB, (1, 6)), jnp.int32)
    ref = make_generate_fn(target, 12)(
        tparams, prompt, jax.random.PRNGKey(0)
    )
    fn = make_speculative_generate_fn(target, draft, 12, gamma=gamma)
    out = fn(tparams, dparams, prompt, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_greedy_speculative_with_target_as_draft(rng):
    """draft == target: every proposal accepted, output still the exact
    greedy stream (the all-accept + bonus path)."""
    target, tparams, _, _ = _models()
    prompt = jnp.asarray(rng.integers(0, VOCAB, (1, 5)), jnp.int32)
    ref = make_generate_fn(target, 10)(
        tparams, prompt, jax.random.PRNGKey(0)
    )
    fn = make_speculative_generate_fn(target, target, 10, gamma=4)
    out = fn(tparams, tparams, prompt, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_sampled_speculative_runs_and_stays_in_vocab(rng):
    target, tparams, draft, dparams = _models()
    prompt = jnp.asarray(rng.integers(0, VOCAB, (1, 5)), jnp.int32)
    fn = make_speculative_generate_fn(
        target, draft, 10, gamma=3, temperature=0.8, top_p=0.9
    )
    out = fn(tparams, dparams, prompt, jax.random.PRNGKey(3))
    assert out.shape == (1, 15)
    o = np.asarray(out)
    assert (o >= 0).all() and (o < VOCAB).all()
    np.testing.assert_array_equal(o[:, :5], np.asarray(prompt))


def test_speculative_guards():
    target = TransformerLM(vocab_size=VOCAB, d_model=32, n_layers=3,
                           n_heads=4)
    draft = TransformerLM(vocab_size=VOCAB, d_model=16, n_layers=1,
                          n_heads=2)
    with pytest.raises(ValueError, match="gamma"):
        make_speculative_generate_fn(target, draft, 8, gamma=0)
    with pytest.raises(ValueError, match="vocabulary"):
        make_speculative_generate_fn(
            target,
            TransformerLM(vocab_size=VOCAB + 1, d_model=16, n_layers=1,
                          n_heads=2),
            8,
        )


@pytest.mark.parametrize("gamma", [2, 4])
def test_batched_greedy_speculative_token_exact(rng, gamma):
    """Batch 8, rows with DIFFERENT prompts: every row's speculative
    stream must equal vanilla batched greedy — per-row frontiers commit
    different counts each round (the draft is random, so acceptance
    varies wildly by row) yet the output is token-exact per row."""
    target, tparams, draft, dparams = _models()
    prompt = jnp.asarray(rng.integers(0, VOCAB, (8, 6)), jnp.int32)
    ref = make_generate_fn(target, 12)(
        tparams, prompt, jax.random.PRNGKey(0)
    )
    fn = make_speculative_generate_fn(target, draft, 12, gamma=gamma)
    out = fn(tparams, dparams, prompt, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_batched_equals_rowwise_single(rng):
    """The batched program must serve each row exactly as the batch-1
    program serves it alone — freezing finished rows cannot leak into
    live rows' streams."""
    target, tparams, draft, dparams = _models()
    prompts = jnp.asarray(rng.integers(0, VOCAB, (4, 5)), jnp.int32)
    fn = make_speculative_generate_fn(target, draft, 9, gamma=3)
    batched = np.asarray(
        fn(tparams, dparams, prompts, jax.random.PRNGKey(1))
    )
    for b in range(4):
        solo = np.asarray(
            fn(tparams, dparams, prompts[b:b + 1], jax.random.PRNGKey(1))
        )
        np.testing.assert_array_equal(batched[b:b + 1], solo)


def test_batched_sampled_speculative_in_vocab(rng):
    target, tparams, draft, dparams = _models()
    prompt = jnp.asarray(rng.integers(0, VOCAB, (4, 5)), jnp.int32)
    fn = make_speculative_generate_fn(
        target, draft, 8, gamma=3, temperature=0.9, top_k=20
    )
    out = np.asarray(fn(tparams, dparams, prompt, jax.random.PRNGKey(5)))
    assert out.shape == (4, 13)
    assert (out >= 0).all() and (out < VOCAB).all()
    np.testing.assert_array_equal(out[:, :5], np.asarray(prompt))


def test_batched_greedy_speculative_int8_kv_cache(rng):
    """Per-row frontiers compose with the int8 KV cache: the vmapped
    per-row scale writes and the scale-folding einsum must keep the
    batched stream equal to the vanilla int8-cache stream."""
    target = TransformerLM(vocab_size=VOCAB, d_model=32, n_layers=2,
                           n_heads=4, kv_cache_dtype=jnp.int8)
    draft = TransformerLM(vocab_size=VOCAB, d_model=16, n_layers=1,
                          n_heads=2, kv_cache_dtype=jnp.int8)
    tparams = init_lm_state(target).params
    dparams = init_lm_state(draft, seed=7).params
    prompt = jnp.asarray(rng.integers(0, VOCAB, (4, 6)), jnp.int32)
    ref = make_generate_fn(target, 10)(
        tparams, prompt, jax.random.PRNGKey(0)
    )
    fn = make_speculative_generate_fn(target, draft, 10, gamma=3)
    out = fn(tparams, dparams, prompt, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_greedy_speculative_with_int8_target(rng):
    """Speculative composes with int8 serving: an int8-quantized target
    (and/or draft) still produces its own exact greedy stream — the
    reference is vanilla int8 decode, so quantization error and the
    speculative machinery are isolated from each other."""
    from distributed_machine_learning_tpu.ops.quant import quantize_lm_params

    target, tparams, draft, dparams = _models()
    qt = quantize_lm_params(tparams)
    qd = quantize_lm_params(dparams)
    prompt = jnp.asarray(rng.integers(0, VOCAB, (1, 5)), jnp.int32)
    ref = make_generate_fn(target, 10, quantize="int8")(
        qt, prompt, jax.random.PRNGKey(0)
    )
    fn = make_speculative_generate_fn(
        target, draft, 10, gamma=3, quantize="int8", draft_quantize="int8"
    )
    out = fn(qt, qd, prompt, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
