"""Weak-scaling sweep harness (bench/sweep.py) on the virtual CPU mesh."""

import jax
import numpy as np
import pytest

from distributed_machine_learning_tpu.bench.sweep import (
    run_point,
    weak_scaling_sweep,
)
from distributed_machine_learning_tpu.models.vgg import VGGTest


def test_weak_scaling_sweep_structure():
    model = VGGTest()
    points = weak_scaling_sweep(
        model, "ring", device_counts=[1, 2], per_device_batch=4, timed_iters=2
    )
    assert [p.num_devices for p in points] == [1, 2]
    assert points[0].strategy == "none"  # baseline: part1 path, no mesh
    assert points[1].strategy == "ring"
    for p in points:
        assert p.imgs_per_sec > 0
        assert np.isclose(
            p.imgs_per_sec_per_device, p.imgs_per_sec / p.num_devices, rtol=1e-2
        )
    assert points[0].efficiency == 1.0
    assert points[1].efficiency is not None and points[1].efficiency > 0


def test_run_point_does_not_consume_shared_state():
    """run_point must deep-copy a provided init state (steps donate it)."""
    from distributed_machine_learning_tpu.cli.common import init_model_and_state

    model = VGGTest()
    state = init_model_and_state(model)
    run_point(model, "all_reduce", 2, per_device_batch=4, timed_iters=1,
              init_state=state)
    # Re-usable: a second point from the same state object still works.
    p = run_point(model, "all_reduce", 2, per_device_batch=4, timed_iters=1,
                  init_state=state)
    assert p.imgs_per_sec > 0


@pytest.mark.parametrize("scheme", ["fsdp_pl", "tp", "pp", "ep", "ring"])
def test_lm_sweep_point_runs_and_reports(scheme):
    """Each LM scheme's sweep point builds its sharded program, runs the
    chained-timing protocol, and reports sane fields (bench/lm_sweep.py;
    VERDICT r03 item 6; ep/ring — VERDICT r04 item 5)."""
    from distributed_machine_learning_tpu.bench.lm_sweep import lm_run_point

    p = lm_run_point(
        scheme, 2, d_model=32, n_heads=4, n_layers=2, layers_per_stage=1,
        experts_per_device=1, seq_len=32, per_device_batch=2, timed_iters=2,
    )
    assert p.num_devices == 2 and p.scheme == scheme
    assert p.tokens_per_sec > 0
    assert p.tokens_per_sec_per_device == p.tokens_per_sec / 2
    if scheme == "pp":
        assert p.mode == "weak-depth" and p.n_layers == 2  # 1 x 2 stages
    elif scheme == "tp":
        assert p.mode == "strong"
    elif scheme == "ep":
        # experts and the global batch grow with the mesh.
        assert p.mode == "weak-expert" and p.global_batch == 4
    elif scheme == "ring":
        # the global SEQUENCE grows with the mesh at fixed batch.
        assert p.mode == "weak-seq" and p.seq_len == 64
        assert p.flops_per_token and p.flops_per_token > 0
    else:
        assert p.mode == "weak-batch" and p.global_batch == 4


def test_lm_sweep_ring_efficiency_uses_flops_norm():
    """The weak-seq efficiency multiplies by modeled FLOPs/token — a
    longer-sequence point with the same token rate must show HIGHER
    efficiency than raw token-rate normalization would."""
    from distributed_machine_learning_tpu.bench.lm_sweep import (
        lm_scaling_sweep,
    )

    pts = lm_scaling_sweep(
        "ring", device_counts=[1, 2], d_model=32, n_heads=4, n_layers=2,
        seq_len=32, per_device_batch=2, timed_iters=2,
    )
    assert pts[0].efficiency == 1.0
    raw = (pts[1].tokens_per_sec_per_device
           / pts[0].tokens_per_sec_per_device)
    assert pts[1].efficiency > raw  # fpt(64) > fpt(32)


def test_lm_sweep_guards():
    from distributed_machine_learning_tpu.bench.lm_sweep import (
        lm_run_point,
        lm_scaling_sweep,
    )

    with pytest.raises(ValueError, match="scheme"):
        lm_run_point("zz", 2)
    with pytest.raises(ValueError, match="n_heads"):
        lm_run_point("tp", 3, n_heads=4)
    with pytest.raises(ValueError, match="empty"):
        lm_scaling_sweep("tp", device_counts=[])
