"""AdamW: numerics vs optax, bias correction, decoupled decay, and
dispatch through the train step (SURVEY.md §4 test strategy — numerical
equivalence checks the reference only eyeballed)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_machine_learning_tpu.train.adamw import (
    AdamWConfig,
    adamw_init,
    adamw_update,
)


def _tree(rng):
    return {
        "w": jnp.asarray(rng.standard_normal((4, 3)), jnp.float32),
        "b": jnp.asarray(rng.standard_normal((3,)), jnp.float32),
    }


def test_matches_optax_adamw(rng):
    cfg = AdamWConfig(learning_rate=1e-2, beta1=0.9, beta2=0.95,
                      eps=1e-8, weight_decay=0.1)
    params = _tree(rng)
    ref_params = params
    tx = optax.adamw(cfg.learning_rate, b1=cfg.beta1, b2=cfg.beta2,
                     eps=cfg.eps, weight_decay=cfg.weight_decay)
    opt_state = tx.init(ref_params)
    moments = adamw_init(params)
    for step in range(5):
        grads = jax.tree_util.tree_map(
            lambda p: jnp.asarray(
                np.random.default_rng(step).standard_normal(p.shape),
                jnp.float32,
            ),
            params,
        )
        params, moments = adamw_update(params, moments, grads, cfg,
                                       step=jnp.asarray(step))
        updates, opt_state = tx.update(grads, opt_state, ref_params)
        ref_params = optax.apply_updates(ref_params, updates)
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_bias_correction_first_step(rng):
    # At t=1 with zero moments, m̂ = g and n̂ = g², so the Adam term is
    # g/(|g|+eps) ≈ sign(g): the first step is ±lr regardless of the
    # gradient's magnitude.
    cfg = AdamWConfig(learning_rate=1e-3, weight_decay=0.0)
    p = {"w": jnp.zeros((5,), jnp.float32)}
    g = {"w": jnp.asarray(rng.standard_normal(5) * 100, jnp.float32)}
    new_p, _ = adamw_update(p, adamw_init(p), g, cfg, step=jnp.asarray(0))
    np.testing.assert_allclose(
        np.asarray(new_p["w"]),
        -cfg.learning_rate * np.sign(np.asarray(g["w"])),
        rtol=1e-4,
    )


def test_decay_is_decoupled():
    # Zero gradient: AdamW still shrinks weights by lr·wd (decoupled
    # decay acts on the parameter, not through the gradient — the
    # Loshchilov-Hutter distinction vs Adam+L2).
    cfg = AdamWConfig(learning_rate=0.1, weight_decay=0.5)
    p = {"w": jnp.ones((3,), jnp.float32)}
    g = {"w": jnp.zeros((3,), jnp.float32)}
    new_p, _ = adamw_update(p, adamw_init(p), g, cfg, step=jnp.asarray(0))
    np.testing.assert_allclose(np.asarray(new_p["w"]),
                               (1 - 0.1 * 0.5) * np.ones(3), rtol=1e-6)


def test_moments_stay_fp32_for_bf16_params():
    cfg = AdamWConfig()
    p = {"w": jnp.ones((3,), jnp.bfloat16)}
    moments = adamw_init(p)
    assert moments["mu"]["w"].dtype == jnp.float32
    g = {"w": jnp.ones((3,), jnp.bfloat16)}
    new_p, new_m = adamw_update(p, moments, g, cfg, step=jnp.asarray(0))
    assert new_p["w"].dtype == jnp.bfloat16
    assert new_m["nu"]["w"].dtype == jnp.float32


def test_config_type_guard():
    from distributed_machine_learning_tpu.train.sgd import SGDConfig

    p = {"w": jnp.ones((2,), jnp.float32)}
    with pytest.raises(TypeError, match="AdamWConfig"):
        adamw_update(p, adamw_init(p), p, SGDConfig(), step=jnp.asarray(0))
    with pytest.raises(ValueError, match="step"):
        adamw_update(p, adamw_init(p), p, AdamWConfig())


def test_train_step_dispatches_on_config(mesh4, rng):
    # A VGG train step built with optimizer=None honors AdamWConfig on
    # the state — including under shard_map with gradient sync.
    from distributed_machine_learning_tpu.cli.common import init_model_and_state
    from distributed_machine_learning_tpu.models.vgg import VGGTest
    from distributed_machine_learning_tpu.parallel.strategies import get_strategy
    from distributed_machine_learning_tpu.train.step import (
        make_train_step,
        shard_batch,
    )

    model = VGGTest(use_bn=False)
    state = init_model_and_state(model, config=AdamWConfig(learning_rate=1e-3))
    assert set(state.momentum) == {"mu", "nu"}
    step = make_train_step(model, get_strategy("all_reduce"), mesh=mesh4,
                           augment=False)
    images = rng.integers(0, 256, (8, 32, 32, 3)).astype(np.uint8)
    labels = rng.integers(0, 10, 8).astype(np.int32)
    x, y = shard_batch(mesh4, images, labels)
    state2, loss = step(state, x, y)
    assert np.isfinite(float(loss))
    assert int(state2.step) == 1
    # The update actually moved the params.
    before = jax.tree_util.tree_leaves(
        init_model_and_state(model, config=AdamWConfig()).params
    )
    after = jax.tree_util.tree_leaves(state2.params)
    assert any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(before, after)
    )


@pytest.mark.slow
def test_adamw_under_tensor_parallel_and_pipeline(rng):
    # The {"mu","nu"} moment layout must flow through the GSPMD sharding
    # derivation (parallel/gspmd.py) and the pipeline's manual spec
    # builder (parallel/pipeline.py::_moment_layout).
    from distributed_machine_learning_tpu.models.transformer import TransformerLM
    from distributed_machine_learning_tpu.parallel.pipeline import (
        init_pipeline_state,
        make_pp_lm_train_step,
        microbatch,
        shard_pp_state,
    )
    from distributed_machine_learning_tpu.parallel.tensor_parallel import (
        make_tp_lm_train_step,
        shard_tp_batch,
        shard_tp_state,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh
    from distributed_machine_learning_tpu.train.lm_step import init_lm_state

    model = TransformerLM(vocab_size=32, d_model=16, n_layers=2, n_heads=2)
    cfg = AdamWConfig(learning_rate=1e-3)
    toks = rng.integers(0, 32, (4, 9)).astype(np.int32)

    tp_mesh = make_mesh(4, ("batch", "model"), (2, 2))
    tp_state = shard_tp_state(init_lm_state(model, config=cfg), tp_mesh)
    tp_step = make_tp_lm_train_step(model, tp_mesh)
    x, y = shard_tp_batch(tp_mesh, toks[:, :-1], toks[:, 1:])
    tp_state, tp_loss = tp_step(tp_state, x, y)
    assert np.isfinite(float(tp_loss))

    pp_mesh = make_mesh(2, ("pipe",))
    pp_state = shard_pp_state(
        init_pipeline_state(model, config=cfg), pp_mesh
    )
    pp_step = make_pp_lm_train_step(model, pp_mesh, num_microbatches=2)
    px, py = microbatch(toks[:, :-1], toks[:, 1:], 2)
    pp_state, pp_loss = pp_step(pp_state, px, py)
    assert np.isfinite(float(pp_loss))


def test_zero_sharding_rejects_lars(mesh4):
    # Elementwise AdamW shards exactly; LARS (per-layer norms) cannot.
    from distributed_machine_learning_tpu.cli.common import init_model_and_state
    from distributed_machine_learning_tpu.models.vgg import VGGTest
    from distributed_machine_learning_tpu.parallel.fsdp import shard_fsdp_state
    from distributed_machine_learning_tpu.parallel.zero1 import shard_zero1_state
    from distributed_machine_learning_tpu.train.lars import LARSConfig

    state = init_model_and_state(VGGTest(use_bn=False), config=LARSConfig())
    with pytest.raises(ValueError, match="LARS"):
        shard_zero1_state(state, mesh4)
    with pytest.raises(ValueError, match="LARS"):
        shard_fsdp_state(state, mesh4)


def test_zero_sharding_with_adamw_matches_replicated(mesh4, rng):
    # The flat-sharded AdamW update (ZeRO-1 and ZeRO-3) must reproduce
    # the replicated data-parallel AdamW step: same loss, same params
    # after the step — elementwise updates are exact on any slice.
    from distributed_machine_learning_tpu.cli.common import init_model_and_state
    from distributed_machine_learning_tpu.models.vgg import VGGTest
    from distributed_machine_learning_tpu.parallel.fsdp import (
        gather_fsdp_params,
        make_fsdp_train_step,
        shard_fsdp_state,
    )
    from distributed_machine_learning_tpu.parallel.strategies import get_strategy
    from distributed_machine_learning_tpu.parallel.zero1 import (
        make_zero1_train_step,
        shard_zero1_state,
        zero1_params,
    )
    from distributed_machine_learning_tpu.train.step import (
        make_train_step,
        shard_batch,
    )

    model = VGGTest(use_bn=False)
    cfg = AdamWConfig(learning_rate=1e-3)
    images = rng.integers(0, 256, (8, 32, 32, 3)).astype(np.uint8)
    labels = rng.integers(0, 10, 8).astype(np.int32)
    x, y = shard_batch(mesh4, images, labels)

    ref_state = init_model_and_state(model, config=cfg)
    # MEAN semantics to match the ZeRO schemes: ring with mean.
    ref_step = make_train_step(model, get_strategy("ring"), mesh=mesh4,
                               augment=False)
    ref_state, ref_loss = ref_step(ref_state, x, y)

    f0, unravel, n_elems = shard_fsdp_state(
        init_model_and_state(model, config=cfg), mesh4
    )
    assert set(f0.momentum_shards) == {"mu", "nu"}
    fsdp_step = make_fsdp_train_step(model, mesh4, unravel, n_elems,
                                     augment=False)
    f1, f_loss = fsdp_step(f0, x, y)
    np.testing.assert_allclose(float(f_loss), float(ref_loss), rtol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(gather_fsdp_params(f1, unravel, n_elems)),
        jax.tree_util.tree_leaves(ref_state.params),
    ):
        # ring-mean vs psum_scatter reduction orders differ; Adam's
        # 1/sqrt(v) amplifies the last-ulp difference slightly.
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)

    z0, z_unravel, z_n = shard_zero1_state(
        init_model_and_state(model, config=cfg), mesh4
    )
    z_step = make_zero1_train_step(model, mesh4, z_unravel, z_n,
                                   augment=False)
    z1, z_loss = z_step(z0, x, y)
    np.testing.assert_allclose(float(z_loss), float(ref_loss), rtol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(zero1_params(z1, z_unravel, z_n)),
        jax.tree_util.tree_leaves(ref_state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_fsdp_lm_step_matches_dp(mesh4, rng):
    # ZeRO-3 LM step vs replicated dp LM step: identical loss and
    # post-step params (AdamW, fused CE on to cover that path too).
    from distributed_machine_learning_tpu.models.transformer import TransformerLM
    from distributed_machine_learning_tpu.parallel.fsdp import (
        gather_fsdp_params,
        make_fsdp_lm_train_step,
        shard_fsdp_state,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh
    from distributed_machine_learning_tpu.train.lm_step import (
        init_lm_state,
        make_lm_train_step,
        shard_lm_batch,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    model = TransformerLM(vocab_size=32, d_model=16, n_layers=2, n_heads=2)
    cfg = AdamWConfig(learning_rate=1e-3)
    toks = rng.integers(0, 32, (4, 9)).astype(np.int32)

    dp_mesh = make_mesh(4, ("batch", "seq"), (4, 1))
    dp_state = init_lm_state(model, config=cfg)
    dp_step = make_lm_train_step(model, mesh=dp_mesh)
    dx, dy = shard_lm_batch(dp_mesh, toks[:, :-1], toks[:, 1:])
    dp_state, dp_loss = dp_step(dp_state, dx, dy)

    f0, unravel, n_elems = shard_fsdp_state(
        init_lm_state(model, config=cfg), mesh4
    )
    step = make_fsdp_lm_train_step(model, mesh4, unravel, n_elems,
                                   fused_ce_chunks=3)
    sharding = NamedSharding(mesh4, P("batch"))
    fx = jax.device_put(toks[:, :-1], sharding)
    fy = jax.device_put(toks[:, 1:], sharding)
    f1, f_loss = step(f0, fx, fy)
    np.testing.assert_allclose(float(f_loss), float(dp_loss), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(gather_fsdp_params(f1, unravel, n_elems)),
        jax.tree_util.tree_leaves(dp_state.params),
    ):
        # First-step Adam on near-zero grads is g/(|g|+eps): reduction-
        # order noise there moves the step by ~1e-5 abs (vs lr=1e-3).
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_moe_state_accepts_config():
    from distributed_machine_learning_tpu.models.moe import MoETransformerLM
    from distributed_machine_learning_tpu.parallel.expert_parallel import (
        init_moe_state,
    )

    moe = MoETransformerLM(vocab_size=32, d_model=16, n_layers=2,
                           n_heads=2, n_experts=2)
    state = init_moe_state(moe, config=AdamWConfig())
    assert set(state.momentum) == {"mu", "nu"}
