"""Runtime package exports — lazy on purpose (ISSUE 12).

``runtime.transport`` and ``runtime.coordinator`` are stdlib-only by
contract (the ``tools/`` layer imports them against a dead run's
directory on hosts without jax); an eager ``from .mesh import ...``
here would drag jax into every such import.  PEP 562 module
``__getattr__`` keeps the public ``from ...runtime import make_mesh``
surface identical while deferring the jax-heavy submodules until a
name is actually touched.
"""

import importlib

_EXPORTS = {
    "make_mesh": ".mesh",
    "BATCH_AXIS": ".mesh",
    "initialize_from_flags": ".distributed",
    "DistributedContext": ".distributed",
    "GangCoordinator": ".coordinator",
    "GANG_ABORT_EXIT": ".coordinator",
    "elect_restore_step": ".coordinator",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    try:
        module = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    return getattr(importlib.import_module(module, __name__), name)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
