"""Device-side normalization and augmentation.

The reference augments on the host per-sample through torchvision
transforms: RandomCrop(32, padding=4) + RandomHorizontalFlip, then
normalizes with fixed CIFAR statistics (``part1/main.py:82-89``).

TPU-first redesign: the batch crosses host→device as uint8 NHWC and both
normalization and augmentation run **inside the jitted train step** —
they're elementwise/gather ops XLA fuses into the first conv's input, so
augmentation is effectively free and the host pipeline has nothing to do
but slice contiguous uint8.  Randomness is stateless `jax.random` keyed
from the train-state PRNG (seed 69143 — ``part1/main.py:17``), which keeps
every rank's augmentation stream deterministic and reproducible, the
property the reference gets from per-rank torch seeding.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distributed_machine_learning_tpu.data.cifar10 import CIFAR10_MEAN, CIFAR10_STD


def normalize(images_u8: jax.Array) -> jax.Array:
    """uint8 NHWC → normalized fp32 (ToTensor + Normalize, part1/main.py:82-83)."""
    x = images_u8.astype(jnp.float32) / 255.0
    mean = jnp.asarray(CIFAR10_MEAN)
    std = jnp.asarray(CIFAR10_STD)
    return (x - mean) / std


def _random_crop_one(key: jax.Array, img: jax.Array, padding: int = 4) -> jax.Array:
    """RandomCrop(32, padding=4): zero-pad to 40×40, take a random 32×32 window."""
    h, w, _ = img.shape
    padded = jnp.pad(img, ((padding, padding), (padding, padding), (0, 0)))
    kx, ky = jax.random.split(key)
    top = jax.random.randint(kx, (), 0, 2 * padding + 1)
    left = jax.random.randint(ky, (), 0, 2 * padding + 1)
    return jax.lax.dynamic_slice(padded, (top, left, 0), (h, w, img.shape[2]))


def augment_batch(key: jax.Array, images_u8: jax.Array) -> jax.Array:
    """RandomCrop(32, pad=4) + RandomHorizontalFlip + normalize, whole batch.

    vmapped per-image so each sample draws its own crop offset / flip coin,
    like torchvision's per-sample transforms; everything stays static-shaped
    so XLA tiles it without host round-trips.
    """
    n = images_u8.shape[0]
    crop_keys, flip_key = (
        jax.random.split(jax.random.fold_in(key, 0), n),
        jax.random.fold_in(key, 1),
    )
    cropped = jax.vmap(_random_crop_one)(crop_keys, images_u8)
    flip = jax.random.bernoulli(flip_key, 0.5, (n,))
    flipped = jnp.where(flip[:, None, None, None], cropped[:, :, ::-1, :], cropped)
    return normalize(flipped)
