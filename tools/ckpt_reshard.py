#!/usr/bin/env python3
"""Offline checkpoint resharder — rewrite a checkpoint from world-size N
to M, re-emitting a valid manifest.

Usage::

    python tools/ckpt_reshard.py SRC DST --world M

``SRC`` is a single ``step_<n>`` checkpoint directory, or a directory
containing them (the newest *valid* one is picked, same fallback chain
as resume).  The resharded checkpoint lands under ``DST/step_<n>`` with
its manifest, config payload (cursor/layout preserved), and
``ShardSpec`` re-aimed at world ``M`` — ``tools/ckpt_verify.py`` (and
every restore path) accepts it like any native save.

This is the operator's tool for the planned half of elasticity: a job
about to move from an N-host to an M-host reservation reshards its
checkpoint ONCE, offline, instead of paying the reshard on the critical
restart path of every rank.  The unplanned half (a shrink mid-run) uses
the same machinery in-process (``train/checkpoint.py::reshard_restore``).

Needs jax + the package (flat zero1/fsdp vectors are re-laid-out
host-side and re-saved through orbax); for a verify-only pass that runs
where training isn't installed, use the stdlib-only
``tools/ckpt_verify.py``.
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="reshard a checkpoint to a different world size"
    )
    ap.add_argument("src", help="a step_<n> checkpoint dir, or a dir "
                               "containing them (newest valid wins)")
    ap.add_argument("dst", help="output checkpoint ROOT (the resharded "
                               "checkpoint lands at DST/step_<n>)")
    ap.add_argument("--world", type=int, required=True,
                    help="target world size")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the per-leaf progress line")
    args = ap.parse_args(argv)
    if args.world < 1:
        print(f"ckpt_reshard: --world must be >= 1, got {args.world}",
              file=sys.stderr)
        return 2
    if not os.path.isdir(args.src):
        print(f"ckpt_reshard: no such directory: {args.src}",
              file=sys.stderr)
        return 2

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # Runnable straight from a checkout (python tools/ckpt_reshard.py):
    # the package root is the parent of this script's directory.
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    from distributed_machine_learning_tpu.train.checkpoint import (
        NoRestorableCheckpointError,
        checkpoint_cursor,
        checkpoint_layout,
        checkpoint_shard_spec,
        require_latest_checkpoint,
        reshard_restore,
        save_checkpoint,
        validate_checkpoint,
    )

    name = os.path.basename(os.path.abspath(args.src))
    if name.startswith("step_") and name[5:].isdigit():
        src = os.path.abspath(args.src)
        problems = validate_checkpoint(src)
        if problems:
            print(f"ckpt_reshard: {src} is not restorable: "
                  + "; ".join(problems), file=sys.stderr)
            return 1
    else:
        try:
            src = require_latest_checkpoint(args.src)
        except NoRestorableCheckpointError as e:
            print(f"ckpt_reshard: {e}", file=sys.stderr)
            return 1

    saved_spec = checkpoint_shard_spec(src)
    state, spec = reshard_restore(src, world=args.world,
                                  files_verified=True)
    if not args.quiet:
        frm = (f"{saved_spec.layout} world {saved_spec.world}"
               if saved_spec is not None else "spec-less (dp)")
        print(f"resharding {src} [{frm}] -> world {args.world}")
    path = save_checkpoint(
        args.dst, state,
        layout=checkpoint_layout(src),
        cursor=checkpoint_cursor(src),
        shard_spec=spec,
    )
    problems = validate_checkpoint(path)
    if problems:
        print(f"ckpt_reshard: re-emitted checkpoint failed its own "
              f"manifest: {'; '.join(problems)}", file=sys.stderr)
        return 1
    print(f"wrote {path} ({spec.layout}, world {spec.world})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
