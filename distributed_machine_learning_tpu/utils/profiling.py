"""Tracing / profiling + structured per-step metrics.

The reference's observability is a hand-rolled wall-clock harness
(``part1/main.py:36,53-58``) plus out-of-band dstat plots in its report
(group25.pdf p.4,7) — SURVEY.md §5.  TPU-native equivalents:

- :func:`trace` — context manager around ``jax.profiler`` producing an
  XPlane/Perfetto trace directory (the principled replacement for the
  report's external CPU/network plots: the trace shows MXU occupancy,
  HBM traffic, and ICI collective time per step).
- :class:`MetricsLogger` — per-step structured metrics (step, loss,
  wall-clock) accumulated in memory and flushed to CSV and/or JSONL,
  rank-0 gated; feeds the scaling-sweep harness.
- :func:`annotate` — ``jax.profiler.TraceAnnotation`` wrapper so driver
  phases (train/eval/checkpoint) show up as named spans in the trace.
"""

from __future__ import annotations

import contextlib
import csv
import json
import os
import time
from dataclasses import dataclass, field

import jax


@contextlib.contextmanager
def trace(log_dir: str | os.PathLike | None):
    """Profile the enclosed block with ``jax.profiler`` into `log_dir`.

    No-op when `log_dir` is falsy, so call sites can thread a CLI flag
    straight through.  View the result with TensorBoard's profile plugin
    or Perfetto (the trace directory contains ``*.xplane.pb``).
    """
    if not log_dir:
        yield
        return
    log_dir = os.fspath(log_dir)
    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named span in the profiler timeline (host side)."""
    return jax.profiler.TraceAnnotation(name)


@dataclass
class MetricsLogger:
    """Accumulate per-step metric rows; flush to CSV / JSONL, rank-0 gated.

    Rows are plain dicts; the column set is the union over rows (missing
    keys serialize empty in CSV, absent in JSONL).
    """

    rows: list[dict] = field(default_factory=list)

    def log(self, step: int, **metrics) -> None:
        self.rows.append({"step": step, "time": time.time(), **metrics})

    def save(self, path: str | os.PathLike) -> None:
        """Write rows to `path`, format chosen by extension: ``.csv`` for
        CSV, anything else JSONL.  The single dispatch point for every
        caller (CLI, bench, sweep)."""
        if os.fspath(path).endswith(".csv"):
            self.to_csv(path)
        else:
            self.to_jsonl(path)

    def to_csv(self, path: str | os.PathLike) -> None:
        if jax.process_index() != 0:
            return
        columns: list[str] = []
        for row in self.rows:
            for key in row:
                if key not in columns:
                    columns.append(key)
        os.makedirs(os.path.dirname(os.path.abspath(os.fspath(path))),
                    exist_ok=True)
        # Zero rows still writes the (possibly header-only) file, so a
        # reported path always exists.
        with open(path, "w", newline="") as f:
            writer = csv.DictWriter(f, fieldnames=columns)
            if columns:
                writer.writeheader()
            writer.writerows(self.rows)

    def to_jsonl(self, path: str | os.PathLike) -> None:
        if jax.process_index() != 0:
            return
        os.makedirs(os.path.dirname(os.path.abspath(os.fspath(path))),
                    exist_ok=True)
        with open(path, "w") as f:
            for row in self.rows:
                f.write(json.dumps(row) + "\n")
