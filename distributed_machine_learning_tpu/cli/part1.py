"""part1 — single-device baseline (reference ``part1/main.py``).

No flags in the reference (``part1/main.py:129-130``); batch 256
(``part1/main.py:18``), VGG-11 without BatchNorm, plain jitted train step,
no collectives.  Run: ``python -m distributed_machine_learning_tpu.cli.part1``.
"""

from __future__ import annotations

from distributed_machine_learning_tpu.cli.common import make_flag_parser, parse_flags, run_part

BATCH_SIZE = 256  # part1/main.py:18


def main(argv=None) -> None:
    args = parse_flags(make_flag_parser(__doc__), argv)
    run_part("none", per_rank_batch=BATCH_SIZE, use_bn=False, args=args)


if __name__ == "__main__":
    main()
