"""The pluggable gradient-synchronization layer.

This is the reference's one *varying* layer (SURVEY.md §1): its four parts
are copy-pasted clones differing only in what happens between
``loss.backward()`` and ``optimizer.step()``.  Here that seam is an
explicit interface — a strategy is a pure function on the gradient pytree,
executed inside the shard_mapped train step over the mesh's data axis:

  =============  ======================================  =================
  strategy       reference                               reduction
  =============  ======================================  =================
  none           part1 (single process, no sync)         —
  gather_scatter part2/2a ``gatherAndScatter``            SUM (§2.4)
                 (``part2/2a/main.py:89-116``)
  all_reduce     part2/2b ``allReduce``                   SUM (§2.4)
                 (``part2/2b/main.py:101-106``)
  ring           part3 DDP bucketed ring                  MEAN (DDP avgs)
                 (``part3/main.py:137``), rebuilt as an
                 explicit lax.ppermute ring (north-star)
  =============  ======================================  =================

SUM-vs-MEAN is a real semantic difference the reference's report glossed
over (SURVEY.md §2.4): 2a/2b sum gradients and never divide by world size
(an effective world_size× learning-rate), part3's DDP averages.  Each
strategy reproduces its part's exact semantics; the ``mean`` flag lets a
user override.

**Stateful strategies** (round 7): a strategy that carries per-device
state across steps — the error-feedback residual of the compressed ring
— sets ``stateful = True`` and implements the three-method protocol:

- ``init_state(grads)`` → the per-device state pytree (zeros at start);
- ``apply(grads, state, axis_name, axis_size)`` → ``(synced, new_state)``;
- ``__call__`` keeps working as the stateless form (no residual).

``train/step.py::make_train_step`` threads the state through the
compiled step (state in, state out, donated, sharded P(batch) so each
device keeps its OWN residual — error feedback is rank-local by
construction).  Stateless strategies pay nothing: the compiled program
without state is unchanged.
"""

from __future__ import annotations

import warnings
from dataclasses import dataclass

from distributed_machine_learning_tpu.ops.collectives import (
    all_reduce_mean,
    all_reduce_sum,
    gather_scatter_sum,
)
from distributed_machine_learning_tpu.ops.ring import (
    DEFAULT_BUCKET_BYTES,
    WIRE_SCHEMES,
    get_wire_scheme,
    ring_all_reduce,
    ring_wire_bytes,
)


@dataclass(frozen=True)
class SyncStrategy:
    """Base: a pure transform grads → synced grads over `axis_name`."""

    name = "base"
    #: True when the strategy carries per-device state across steps
    #: (``apply``/``init_state`` protocol); the train-step factory then
    #: threads a donated state pytree through the compiled step.
    stateful = False

    def __call__(self, grads, axis_name: str, axis_size: int):
        raise NotImplementedError

    def init_state(self, grads):
        """Fresh per-device strategy state, congruent to ``grads``
        (None for stateless strategies)."""
        return None

    def apply(self, grads, state, axis_name: str, axis_size: int):
        """Stateful form: ``(synced grads, new state)``.  Default
        delegates to the stateless ``__call__`` with the state passed
        through untouched."""
        return self(grads, axis_name, axis_size), state


@dataclass(frozen=True)
class NoSync(SyncStrategy):
    """part1: single-process, no gradient exchange."""

    name = "none"

    def __call__(self, grads, axis_name: str, axis_size: int):
        return grads


@dataclass(frozen=True)
class AllReduce(SyncStrategy):
    """part2b: one all-reduce per parameter; SUM by default (§2.4)."""

    name = "all_reduce"
    mean: bool = False

    def __call__(self, grads, axis_name: str, axis_size: int):
        if self.mean:
            return all_reduce_mean(grads, axis_name)
        return all_reduce_sum(grads, axis_name)


@dataclass(frozen=True)
class GatherScatter(SyncStrategy):
    """part2a: centralized gather→sum→scatter, as all-gather + rank-order sum."""

    name = "gather_scatter"

    def __call__(self, grads, axis_name: str, axis_size: int):
        return gather_scatter_sum(grads, axis_name)


@dataclass(frozen=True)
class RingAllReduce(SyncStrategy):
    """part3 north-star: bucketed explicit ppermute ring, DDP mean semantics.

    ``compress`` picks the per-hop wire codec (``ops/ring.py``):

    - ``"none"`` — exact fp32 hops (default; reference parity);
    - ``"bf16"`` — cast-only wire compression (half the bytes).  NOTE:
      this is a plain dtype cast with NO residual correction — it is
      *not* the error-compensated compressed all-reduce of the
      retrieved literature; ``int8``/``topk`` + ``error_feedback`` are;
    - ``"int8"`` — per-chunk symmetric int8 + fp32 scale, fused
      dequantize–add–requantize per hop (~4x fewer wire bytes);
    - ``"topk"`` — top-``topk_frac`` magnitude sparsification
      (values+indices on the wire; 2·frac of the fp32 bytes).

    ``error_feedback`` (int8/topk only): accumulate each step's local
    compression error and add it back into the next step's gradient —
    EF-SGD residual correction (arxiv 1711.00705; DynamiQ).  Makes the
    strategy STATEFUL: the train step threads a per-device residual
    pytree through the compiled program (see ``make_train_step``).

    ``topology`` ("INNERxOUTER", round 11 — ``--ring-topology``): run
    the topology-aware hierarchical plan (``ops/topology.py``) instead
    of the flat ring: reduce-scatter on the fast inner axis, the
    ``compress`` codec's ring on the slow OUTER axis over 1/inner of
    the data (inter-node traffic drops to ~1/inner of the flat
    ring's), all-gather back down — with recursive halving-doubling
    for latency-bound small buckets, per ``Topology.select``.  The
    factorization must equal the mesh's data-axis world (validated at
    ``topology_for``); a 1-sized axis degenerates to exactly the flat
    ring.  Error feedback becomes per-axis but the residuals still sum
    to the total dropped mass — the stateful protocol is unchanged.

    ``codec_impl`` ("xla"/"pallas", round 13 — ``--ring-codec-impl``):
    the int8 codec's implementation.  ``"pallas"`` dispatches every
    hop's dequantize–add–requantize (and the EF residual) through the
    fused in-register kernels of ``ops/pallas/ring_codec.py`` —
    bitwise-identical wire payload, output, and residual, with no
    dequantized partial ever materialized in HBM.  Flat, hierarchical
    inner/outer, and all-gather relay paths all follow the knob; only
    int8 has kernels (``topk``/``bf16`` keep the XLA path).

    ``wire_dtype="bfloat16"`` is the deprecated spelling of
    ``compress="bf16"``.
    """

    name = "ring"
    mean: bool = True
    bucket_bytes: int = DEFAULT_BUCKET_BYTES
    wire_dtype: str | None = None
    compress: str = "none"
    topk_frac: float = 0.125
    error_feedback: bool = True
    topology: str | None = None
    codec_impl: str = "xla"

    def __post_init__(self):
        if self.compress not in WIRE_SCHEMES:
            raise ValueError(
                f"unknown ring compress scheme {self.compress!r}; choose "
                f"from {WIRE_SCHEMES}"
            )
        from distributed_machine_learning_tpu.ops.ring import CODEC_IMPLS

        if self.codec_impl not in CODEC_IMPLS:
            raise ValueError(
                f"unknown ring codec impl {self.codec_impl!r}; choose "
                f"from {CODEC_IMPLS} (the fused int8 kernels live in "
                "ops/pallas/ring_codec.py)"
            )
        if not 0.0 < self.topk_frac <= 1.0:
            raise ValueError(
                f"topk_frac must be in (0, 1], got {self.topk_frac}"
            )
        if self.topology is not None:
            from distributed_machine_learning_tpu.ops.topology import (
                parse_topology,
            )

            parse_topology(self.topology)  # format fails fast, pre-mesh
        if self.wire_dtype is not None:
            warnings.warn(
                "RingAllReduce(wire_dtype=...) is deprecated: use "
                "compress='bf16' (--ring-compress bf16); wire_dtype is "
                "cast-only compression with no error feedback",
                DeprecationWarning,
                stacklevel=3,
            )

    def scheme(self):
        """The resolved :class:`~...ops.ring.WireScheme` (exact scheme
        for ``compress='none'`` without a legacy ``wire_dtype``)."""
        if self.compress != "none":
            return get_wire_scheme(self.compress, topk_frac=self.topk_frac,
                                   codec_impl=self.codec_impl)
        if self.wire_dtype is not None:
            from distributed_machine_learning_tpu.ops.ring import CastScheme

            import jax.numpy as jnp

            return CastScheme(jnp.dtype(self.wire_dtype))
        return get_wire_scheme("none")

    @property
    def stateful(self):  # type: ignore[override]
        # bf16 stays stateless (cast-only, historical semantics); the
        # lossier codecs carry the EF residual unless explicitly off.
        return self.error_feedback and self.compress in ("int8", "topk")

    def _wire_scheme_or_none(self):
        s = self.scheme()
        return None if s.name == "none" else s

    def topology_for(self, axis_size: int):
        """The resolved ``ops.topology.Topology`` for this mesh world
        (None when the strategy is flat).  Raises ValueError when the
        declared inner×outer does not factor the world — the
        world-equality half of ``--ring-topology`` validation, run by
        the CLI before any training starts."""
        if self.topology is None:
            return None
        from distributed_machine_learning_tpu.ops.topology import (
            Topology,
            parse_topology,
        )

        inner, outer = parse_topology(self.topology)
        if inner * outer != axis_size:
            examples = (
                [f"2x{axis_size // 2}"] if axis_size % 2 == 0
                and axis_size > 2 else []
            ) + [f"{axis_size}x1"]
            raise ValueError(
                f"--ring-topology {self.topology}: inner×outer = "
                f"{inner * outer} must equal the mesh's data-axis world "
                f"{axis_size} (e.g. {axis_size} devices factor as "
                + " or ".join(examples) + ")"
            )
        # --ring-compress is the OUTER (inter-node) codec: compress
        # where the wire is expensive; the intra-node axis stays exact.
        # EXCEPT outer==1 (one node): the inner axis is then the whole
        # wire, and the degenerate flat ring must still carry the
        # user's codec — parking it on the dead outer axis would
        # silently decompress an Nx1 run.
        scheme_axis = ("inner_scheme" if outer == 1 and inner > 1
                       else "outer_scheme")
        return Topology(
            inner, outer,
            topk_frac=self.topk_frac,
            codec_impl=self.codec_impl,
            **{scheme_axis: self.scheme().name},
        )

    def __call__(self, grads, axis_name: str, axis_size: int):
        return ring_all_reduce(
            grads,
            axis_name,
            axis_size,
            mean=self.mean,
            bucket_bytes=self.bucket_bytes,
            scheme=self._wire_scheme_or_none(),
            topology=self.topology_for(axis_size),
        )

    def init_state(self, grads):
        if not self.stateful:
            return None
        import jax
        import jax.numpy as jnp

        return jax.tree_util.tree_map(jnp.zeros_like, grads)

    def apply(self, grads, state, axis_name: str, axis_size: int):
        if not self.stateful:
            return self(grads, axis_name, axis_size), state
        import jax

        # EF-SGD: compress (gradient + carried residual); the new
        # residual is the compression error the ring itself observed —
        # this rank's dropped contribution mass plus, for the chunk it
        # reduced, the all-gather encode's loss (ring_all_reduce_flat's
        # return_residual docstring) — zero extra collectives.
        g_eff = jax.tree_util.tree_map(lambda g, r: g + r, grads, state)
        synced, new_state = ring_all_reduce(
            g_eff,
            axis_name,
            axis_size,
            mean=self.mean,
            bucket_bytes=self.bucket_bytes,
            scheme=self._wire_scheme_or_none(),
            return_residual=True,
            topology=self.topology_for(axis_size),
        )
        return synced, new_state

    # -- static wire accounting (telemetry + audit) ---------------------

    def wire_bytes_per_step(self, n_elems: int, axis_size: int) -> int:
        """Per-device wire bytes of one synchronized step (the
        ``ring_wire_bytes`` telemetry counter's increment)."""
        return ring_wire_bytes(
            n_elems, axis_size, bucket_bytes=self.bucket_bytes,
            scheme=self.scheme(), topology=self.topology_for(axis_size),
        )

    def wire_bytes_by_axis(self, n_elems: int, axis_size: int) -> dict:
        """Per-AXIS wire bytes of one step — ``{"flat": total}`` for
        the flat ring, ``{"inner": ..., "outer": ...}`` under a
        topology: the increments behind the
        ``ring_wire_bytes{axis=...}`` telemetry labels."""
        from distributed_machine_learning_tpu.ops.ring import (
            ring_wire_bytes_by_axis,
        )

        return ring_wire_bytes_by_axis(
            n_elems, axis_size, bucket_bytes=self.bucket_bytes,
            scheme=self.scheme(), topology=self.topology_for(axis_size),
        )

    def compression_ratio(self, n_elems: int, axis_size: int) -> float:
        """Exact FLAT-ring bytes / this build's wire bytes (1.0 =
        exact flat; under a topology the denominator is the whole
        hierarchical plan's per-device bytes)."""
        exact = ring_wire_bytes(
            n_elems, axis_size, bucket_bytes=self.bucket_bytes
        )
        mine = self.wire_bytes_per_step(n_elems, axis_size)
        return exact / mine if mine else 1.0


STRATEGIES = {
    "none": NoSync,
    "gather_scatter": GatherScatter,
    "all_reduce": AllReduce,
    "ring": RingAllReduce,
}


def get_strategy(name: str, **kwargs) -> SyncStrategy:
    try:
        return STRATEGIES[name](**kwargs)
    except KeyError:
        raise ValueError(
            f"unknown sync strategy {name!r}; choose from {sorted(STRATEGIES)}"
        ) from None
