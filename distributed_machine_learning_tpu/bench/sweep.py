"""Weak-scaling sweep harness (SURVEY.md §7.2 step 5).

The reference's scalability story is a hand-run sweep over 1→4 CPU nodes
whose result — "~2.8× speedup given 4× computational power", i.e. ~70%
weak-scaling efficiency — lives only in its report (group25.pdf p.10,
SURVEY.md §6).  Here the sweep is a first-class harness: fixed per-device
batch (weak scaling), growing device count, measuring imgs/sec/device and
efficiency relative to the single-device baseline.  Target ≥85%
(BASELINE.json north-star).

Runs anywhere a mesh runs: real TPU chips, or a virtual CPU mesh via
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` (the test path —
efficiency numbers on virtual devices are not meaningful, but the harness
logic and the sharded programs are identical).
"""

from __future__ import annotations

import argparse
import json
from dataclasses import asdict, dataclass

import jax
import numpy as np

from distributed_machine_learning_tpu.parallel.strategies import get_strategy
from distributed_machine_learning_tpu.runtime.mesh import make_mesh
from distributed_machine_learning_tpu.train.step import make_train_step


@dataclass
class ScalePoint:
    """One measured point of the sweep."""

    num_devices: int
    strategy: str
    per_device_batch: int
    timed_iters: int
    imgs_per_sec: float
    imgs_per_sec_per_device: float
    efficiency: float | None = None  # filled in by the sweep vs its baseline


def _synthetic_batch(rng: np.random.Generator, global_batch: int):
    """CIFAR-shaped uint8 batch; data content is irrelevant to step timing."""
    images = rng.integers(0, 256, (global_batch, 32, 32, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, global_batch).astype(np.int32)
    return images, labels


def run_point(
    model,
    strategy_name: str,
    num_devices: int,
    per_device_batch: int = 64,
    timed_iters: int = 10,
    seed: int = 0,
    init_state=None,
    devices=None,
) -> ScalePoint:
    """Measure one (strategy, device-count) point.

    ``num_devices == 1`` runs the part1 path (plain jit, no mesh) so the
    baseline carries zero collective overhead — the honest denominator for
    weak-scaling efficiency.  ``model`` is a flax module instance;
    ``init_state`` (optional) is a pre-built TrainState to reuse across
    points so each point times the step, not initialization.  ``devices``
    (optional) pins the point to an explicit device list (e.g. virtual
    CPU devices under a TPU-default backend, the dryrun path).
    """
    from distributed_machine_learning_tpu.cli.common import init_model_and_state

    if num_devices < 1:
        raise ValueError(f"num_devices must be >= 1, got {num_devices}")
    if timed_iters < 1:
        raise ValueError(f"timed_iters must be >= 1, got {timed_iters}")
    # Nothing in the scan-epoch path donates buffers (the step is built
    # with jit=False and the harness jit has no donate_argnums), so one
    # shared init can seed every point as-is.
    state = init_state if init_state is not None else init_model_and_state(model)
    rng = np.random.default_rng(seed)
    global_batch = per_device_batch * num_devices

    if num_devices == 1:
        mesh = None
        step = make_train_step(model, mesh=None, jit=False)
    elif strategy_name == "fsdp":
        # ZeRO-3 is a different step builder, not a grad-strategy: the
        # state becomes flat 1/N shards and the step gathers/scatters
        # around the forward/backward (parallel/fsdp.py).
        from distributed_machine_learning_tpu.parallel.fsdp import (
            make_fsdp_train_step,
            shard_fsdp_state,
        )

        mesh = make_mesh(num_devices, devices=devices)
        state, unravel, n_elems = shard_fsdp_state(state, mesh)
        step = make_fsdp_train_step(model, mesh, unravel, n_elems,
                                    jit=False)
    else:
        mesh = make_mesh(num_devices, devices=devices)
        step = make_train_step(
            model, get_strategy(strategy_name), mesh=mesh, jit=False
        )

    # Shared scan-epoch methodology (bench/harness.py): one compiled scan,
    # timing bracketed by a value fetch, compile run excluded.
    from distributed_machine_learning_tpu.bench.harness import timed_scan_epoch

    batches = [_synthetic_batch(rng, global_batch) for _ in range(timed_iters)]
    imgs = np.stack([b[0] for b in batches])
    lbls = np.stack([b[1] for b in batches])
    if mesh is None:
        dx, dy = jax.numpy.asarray(imgs), jax.numpy.asarray(lbls)
        if devices is not None:
            # Commit inputs to the pinned device so jit runs there, not on
            # the ambient default backend.
            dx = jax.device_put(dx, devices[0])
            dy = jax.device_put(dy, devices[0])
    else:
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(mesh, P(None, "batch"))
        dx = jax.device_put(jax.numpy.asarray(imgs), sharding)
        dy = jax.device_put(jax.numpy.asarray(lbls), sharding)

    elapsed, _, state = timed_scan_epoch(step, state, dx, dy, reps=1)

    imgs_per_sec = global_batch * timed_iters / elapsed
    return ScalePoint(
        num_devices=num_devices,
        strategy=strategy_name if num_devices > 1 else "none",
        per_device_batch=per_device_batch,
        timed_iters=timed_iters,
        imgs_per_sec=imgs_per_sec,
        imgs_per_sec_per_device=imgs_per_sec / num_devices,
    )


def weak_scaling_sweep(
    model,
    strategy_name: str = "ring",
    device_counts: list[int] | None = None,
    per_device_batch: int = 64,
    timed_iters: int = 10,
    devices=None,
) -> list[ScalePoint]:
    """Sweep device counts at fixed per-device batch; annotate efficiency
    relative to the smallest point's per-device throughput."""
    if device_counts is None:
        n = len(devices) if devices is not None else jax.device_count()
        device_counts = [d for d in (1, 2, 4, 8, 16, 32) if d <= n]
    device_counts = sorted(set(device_counts))
    if not device_counts:
        raise ValueError("device_counts is empty: nothing to sweep")
    from distributed_machine_learning_tpu.cli.common import init_model_and_state

    state = init_model_and_state(model)
    points = [
        run_point(
            model,
            strategy_name,
            d,
            per_device_batch=per_device_batch,
            timed_iters=timed_iters,
            init_state=state,
            devices=devices,
        )
        for d in device_counts
    ]
    base = points[0].imgs_per_sec_per_device
    for p in points:
        p.efficiency = round(p.imgs_per_sec_per_device / base, 4) if base else None
    return points


def main() -> None:
    from distributed_machine_learning_tpu.models.registry import list_models

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--model", default="vgg11", choices=list_models())
    parser.add_argument("--strategy", default="ring",
                        choices=["gather_scatter", "all_reduce", "ring",
                                 "fsdp"])
    parser.add_argument("--devices", default=None, type=str,
                        help="comma-separated device counts, e.g. 1,2,4,8 "
                             "(default: powers of two up to the device count)")
    parser.add_argument("--batch-per-device", default=64, type=int)
    parser.add_argument("--iters", default=10, type=int)
    parser.add_argument("--compute-dtype", default="bfloat16",
                        choices=["float32", "bfloat16"])
    args = parser.parse_args()

    import jax.numpy as jnp

    from distributed_machine_learning_tpu.models.registry import get_model

    model = get_model(args.model, compute_dtype=getattr(jnp, args.compute_dtype))
    counts = (
        [int(d) for d in args.devices.split(",")] if args.devices else None
    )
    points = weak_scaling_sweep(
        model,
        args.strategy,
        device_counts=counts,
        per_device_batch=args.batch_per_device,
        timed_iters=args.iters,
    )
    for p in points:
        row = asdict(p)
        row["imgs_per_sec"] = round(row["imgs_per_sec"], 2)
        row["imgs_per_sec_per_device"] = round(row["imgs_per_sec_per_device"], 2)
        print(json.dumps(row))
    if len(points) > 1:
        print(
            json.dumps(
                {
                    "metric": "weak_scaling_efficiency",
                    "value": points[-1].efficiency,
                    "unit": f"x{points[-1].num_devices}_vs_x{points[0].num_devices}",
                    # Reference figure: ~70% at 4 nodes, VGG-11 only
                    # (group25.pdf p.10) — any other model is not comparable.
                    "vs_baseline": (
                        round(points[-1].efficiency / 0.70, 2)
                        if points[-1].efficiency and args.model == "vgg11"
                        else None
                    ),
                }
            )
        )


if __name__ == "__main__":
    main()
