"""Numerical sanitizers (utils/debug.py) + transformer remat: checkify
catches the first NaN with provenance, the pytree scanner localizes bad
leaves, and remat changes memory behavior but not a single gradient bit."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.utils.debug import (
    all_devices_identical,
    assert_all_finite,
    checked,
    find_nonfinite,
)


def test_checked_raises_on_nan():
    def f(x):
        return jnp.log(x)  # log(-1) -> nan

    g = checked(f)
    np.testing.assert_allclose(g(jnp.asarray(1.0)), 0.0)
    with pytest.raises(Exception, match="nan"):
        g(jnp.asarray(-1.0))


def test_find_nonfinite_localizes():
    tree = {
        "ok": jnp.ones((3,)),
        "bad": {"w": jnp.asarray([1.0, np.nan, np.inf])},
        "ints": jnp.arange(3),  # non-float leaves are skipped
    }
    report = find_nonfinite(tree)
    assert list(report) == ["bad/w"]
    assert "nan" in report["bad/w"] and "x2" in report["bad/w"]
    with pytest.raises(ValueError, match="bad/w"):
        assert_all_finite(tree, "grads")
    assert find_nonfinite({"a": jnp.zeros(2)}) == {}


def test_all_devices_identical(mesh8):
    from jax.sharding import NamedSharding, PartitionSpec as P

    x = jax.device_put(jnp.ones((8, 4)), NamedSharding(mesh8, P()))
    assert all_devices_identical(x)


def test_remat_grads_bit_identical(rng):
    # Whole-block jax.checkpoint recomputes the same ops in the same
    # order — the gradient must be bitwise identical, only peak memory
    # differs.  (The selective "mlp" policy moves fusion boundaries, so
    # it is equivalence-tested to tolerance instead —
    # tests/test_transformer.py::test_remat_policies_match_no_remat.)
    from distributed_machine_learning_tpu.models.transformer import TransformerLM
    from distributed_machine_learning_tpu.train.losses import lm_cross_entropy
    from distributed_machine_learning_tpu.train.lm_step import init_lm_state

    base = TransformerLM(vocab_size=32, d_model=16, n_layers=2, n_heads=2)
    state = init_lm_state(base)
    toks = jnp.asarray(rng.integers(0, 32, (2, 9)), jnp.int32)

    def grads_for(model):
        def loss(p):
            return lm_cross_entropy(
                model.apply({"params": p}, toks[:, :-1], train=True),
                toks[:, 1:],
            )

        return jax.jit(jax.grad(loss))(state.params)

    g0 = grads_for(base)
    g1 = grads_for(base.clone(remat=True, remat_policy="block"))
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_remat_pipeline_matches_no_remat(rng):
    from distributed_machine_learning_tpu.models.transformer import TransformerLM
    from distributed_machine_learning_tpu.parallel.pipeline import (
        init_pipeline_state,
        make_pp_lm_train_step,
        microbatch,
        shard_pp_state,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh

    mesh = make_mesh(2, ("pipe",))
    toks = rng.integers(0, 32, (4, 9)).astype(np.int32)
    px, py = microbatch(toks[:, :-1], toks[:, 1:], 2)
    losses = []
    for remat in (False, True):
        model = TransformerLM(vocab_size=32, d_model=16, n_layers=2,
                              n_heads=2, remat=remat)
        st = shard_pp_state(init_pipeline_state(model), mesh)
        step = make_pp_lm_train_step(model, mesh, num_microbatches=2)
        st, loss = step(st, px, py)
        losses.append(float(loss))
    assert losses[0] == losses[1]
