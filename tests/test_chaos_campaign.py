"""100-rank chaos campaigns over the in-proc gang transport (ISSUE 12).

The resilience stack — coordinated abort, shrink-to-survivors,
grow/spares/replacement, world-size-aware scaling — had only ever run
at worlds ≤ 5, because the control plane was one OS process per rank
over shared files.  The in-proc transport (threads + in-memory
channels, ``runtime/inproc_worker.py``) runs the SAME ``gang_supervise``
policy at 64-128 ranks in seconds, so tier-1 can finally storm the
gang at the worlds the papers it reproduces assume (arxiv 1811.05233's
hundreds of replicas; the arxiv 1711.04325 scaling rules in
``train/scaling.py`` are *specified* for those worlds).

Tier-1 campaigns (``faultinject`` — fast by construction, each under
an in-test wall-clock cap so a future regression cannot silently eat
the 870s suite budget):

- a 64-rank FAULT STORM: concurrent ``kill_rank``/``stall_rank``/
  ``lose_rank`` firings across the gang, finishing shrunk with
  exactly-once consumption chained across every attempt;
- the 64→48→96 WORLD TRAJECTORY: a 16-host rack loss, a 16-host
  recovery plus 32 warm-spare promotions, under the ``linear`` and
  ``lars`` scaling rules — loss-continuous across both transitions,
  exactly-once throughout, final checkpoint reshard-restorable at
  arbitrary worlds, and ``gang_status`` rendering the whole story from
  the mirrored ledgers.

Slow campaigns (``slow`` + ``faultinject``):

- ROLLING STRAGGLERS under ``--straggler-policy=replace``: repeated
  ``stall_rank`` waves each demote the slow rank to the spare pool and
  promote a warm spare in its place, world size unchanged throughout;
- the END-TO-END TCP PARTITION proof: a real subprocess gang over the
  tcp backend with one rank's channel severed (``--tx-chaos``) — the
  partitioned rank is declared dead within ``peer_timeout_s``, the
  gang restarts coordinated, and finishes clean once the link heals.
"""

from __future__ import annotations

import collections
import importlib.util
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from distributed_machine_learning_tpu.runtime.faults import FaultEvents
from distributed_machine_learning_tpu.runtime.inproc_worker import (
    InprocGangConfig,
    inproc_worker_cmds,
)
from distributed_machine_learning_tpu.runtime.supervisor import (
    gang_supervise,
)
from distributed_machine_learning_tpu.runtime.transport import (
    InProcHub,
    InProcTransport,
)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

# Generous CI wall-clock caps (measured: storm ~6s, trajectory ~9s on
# the 1-core host).  The point is the BUDGET guard: an in-proc 64-rank
# supervise that stops finishing in tier-1 time must fail loudly here,
# not eat the suite's 870s timeout.
STORM_BUDGET_S = 150.0
TRAJECTORY_BUDGET_S = 180.0


def _campaign(tmp_path, *, world, faults, steps=8, save_every=4,
              scaling_rule="pinned", spares=0, **supervise_kwargs):
    """One supervised in-proc campaign; returns (codes, events,
    supervisor transport, hub, elapsed seconds, config)."""
    hub = InProcHub(mirror_dir=os.path.join(tmp_path, "gang"))
    tx = InProcTransport(hub)
    cfg = InprocGangConfig(
        ckpt_dir=os.path.join(tmp_path, "ckpt"), steps=steps,
        save_every=save_every, global_batch=world,
        scaling_rule=scaling_rule, base_world=world, feature_dim=32,
        heartbeat_interval=0.05, peer_timeout=2.0, faults=faults,
    )
    os.makedirs(cfg.ckpt_dir, exist_ok=True)
    worker_cmd, spare_cmd = inproc_worker_cmds(cfg, hub)
    events = FaultEvents()
    start = time.monotonic()
    codes = gang_supervise(
        worker_cmd, world, None, ckpt_dirs=cfg.ckpt_dir, events=events,
        spares=spares, spare_cmd=spare_cmd if spares else None,
        grace_s=3.0, transport=tx, **supervise_kwargs,
    )
    return codes, events, tx, hub, time.monotonic() - start, cfg


def _assert_exactly_once_chained(rows, n_steps) -> dict[int, int]:
    """Judged in the attempt that finally carried the run past each
    step, the consumed example stream partitions into contiguous,
    non-overlapping global batches — the elastic exactly-once
    invariant, at campaign scale.

    Every final-attempt row must anchor at the chained example cursor
    and claim only ids inside its step's slot, with no id claimed
    twice anywhere.  A step whose final attempt has every rank's row
    must cover its slot EXACTLY.  Fewer rows than the world is legal
    only for a step some rank died inside (its shard was applied —
    the gradient is the global-batch mean every rank computes — but
    the dead rank's ledger row was never written; the subsequent
    restart resumed PAST the step from the committed checkpoint):
    those steps still assert non-overlap and cursor chaining, so
    nothing is ever lost or consumed twice.  Returns step -> world."""
    by_step: dict[int, list] = collections.defaultdict(list)
    for r in rows:
        by_step[r["step"]].append(r)
    assert sorted(by_step) == list(range(n_steps))
    cursor = 0
    worlds: dict[int, int] = {}
    for step in range(n_steps):
        final_attempt = max(r["attempt"] for r in by_step[step])
        final = [r for r in by_step[step]
                 if r["attempt"] == final_attempt]
        batches = {r["global_batch"] for r in final}
        ws = {r["world"] for r in final}
        assert len(ws) == 1 and len(batches) == 1, (
            f"step {step}: mixed worlds {ws} / batches {batches} in "
            "one final attempt"
        )
        worlds[step] = ws.pop()
        batch = batches.pop()
        assert all(r["example_cursor"] == cursor for r in final), (
            f"step {step}: example cursor does not chain at {cursor} — "
            "a window was lost or replayed"
        )
        ids = sorted(i for r in final for i in r["ids"])
        assert len(set(ids)) == len(ids), (
            f"step {step}: an example id was consumed twice")
        slot = range(cursor, cursor + batch)
        assert set(ids) <= set(slot), (
            f"step {step}: ids escaped the step's slot {slot}")
        if len(final) == worlds[step]:
            assert ids == list(slot), (
                f"step {step}: fully-logged step does not cover its "
                "slot exactly"
            )
        cursor += batch
    return worlds


def _final_losses(rows) -> dict[int, float]:
    """step -> loss from current-rank-0's rows, later attempts
    overriding replayed steps (the loss is computed from replicated
    params, identical on every rank)."""
    best: dict[int, tuple[int, float]] = {}
    for r in rows:
        if r["rank"] != 0:
            continue
        cur = best.get(r["step"])
        if cur is None or r["attempt"] >= cur[0]:
            best[r["step"]] = (r["attempt"], float(r["loss"]))
    return {s: v for s, (_, v) in best.items()}


def _gang_status_tool():
    spec = importlib.util.spec_from_file_location(
        "gang_status", os.path.join(REPO, "tools", "gang_status.py")
    )
    tool = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tool)
    return tool


# ---------------------------------------------------------------------------
# Tier-1 campaigns
# ---------------------------------------------------------------------------


@pytest.mark.faultinject
def test_storm_64_ranks_concurrent_faults(tmp_path):
    """The fault storm: five concurrent fault firings across a 64-rank
    gang — two hard kills, two stalls riding through the same attempts,
    one permanent loss — must end with the gang finished at 63, the
    consumption stream chained exactly-once through every restart, and
    the whole campaign inside the wall-clock budget."""
    codes, events, tx, hub, elapsed, cfg = _campaign(
        str(tmp_path), world=64,
        faults=("kill_rank@5:3,stall_rank@9:3:1.0,lose_rank@17:4,"
                "kill_rank@33:5,stall_rank@41:2:0.8"),
        max_restarts=6, min_world=56,
    )
    assert elapsed < STORM_BUDGET_S, (
        f"64-rank storm took {elapsed:.1f}s — the in-proc campaign "
        "stopped being fast and will eat the tier-1 budget"
    )
    assert codes == [0] * 63  # rank 17 is gone for good
    assert events.gang_shrinks == 1
    assert events.gang_restarts >= 2  # the kills each charged one
    rows = tx.read_consumed()
    worlds = _assert_exactly_once_chained(rows, cfg.steps)
    assert worlds[0] == 64 and worlds[cfg.steps - 1] == 63
    health = tx.read_health_events()
    kinds = [e["kind"] for e in health]
    assert "restart" in kinds and "shrink" in kinds
    # The supervisor's end-of-run transport record (the satellite the
    # status tool renders as the transport-health line).
    transport_events = [e for e in health if e["kind"] == "transport"]
    assert transport_events and transport_events[-1]["backend"] == "inproc"
    assert transport_events[-1]["ops_total"] > 0
    # Every fault fired exactly once, per the (mirrored) ledger.
    fired = collections.Counter(
        (e["kind"], e.get("target", e.get("rank")))
        for e in tx.read_fault_entries())
    assert fired[("lose_rank", 17)] == 1
    assert fired[("kill_rank", 5)] == 1 and fired[("kill_rank", 33)] == 1


@pytest.mark.faultinject
@pytest.mark.parametrize("rule", ["linear", "lars"])
def test_world_trajectory_64_48_96(tmp_path, rule):
    """The flagship trajectory at the worlds the scaling rules were
    written for: lose a 16-host rack (64→48), then readmit it at a
    planned boundary alongside 32 warm-spare promotions (48→96) —
    exactly-once consumption across both transitions, the loss curve
    continuous under the scaling rule, a final checkpoint that
    reshard-restores onto arbitrary worlds, and ``gang_status``
    narrating the whole trajectory from the mirrored ledgers."""
    lost = list(range(48, 64))
    faults = (",".join(f"lose_rank@{r}:4" for r in lost) + ","
              + ",".join(f"recover_rank@{r}:8" for r in lost))
    codes, events, tx, hub, elapsed, cfg = _campaign(
        str(tmp_path), world=64, faults=faults, steps=12, save_every=4,
        scaling_rule=rule, spares=32, max_restarts=6, min_world=48,
        max_world=96,
    )
    assert elapsed < TRAJECTORY_BUDGET_S, (
        f"64→48→96 campaign took {elapsed:.1f}s — over the tier-1 "
        "wall-clock budget"
    )
    assert codes == [0] * 96
    assert events.gang_shrinks == 1 and events.gang_grows == 1
    assert events.spare_promotions == 32

    rows = tx.read_consumed()
    worlds = _assert_exactly_once_chained(rows, cfg.steps)
    assert sorted(set(worlds.values())) == [48, 64, 96]
    assert worlds[0] == 64 and worlds[cfg.steps - 1] == 96

    # Loss continuity across both transitions: the scaling rule keeps
    # the stationary floor world-invariant, so neither boundary may
    # show a discontinuity beyond the noise band (dim 32: per-step
    # chi-square noise ~25%, windows of 3 average it down).
    losses = _final_losses(rows)
    assert sorted(losses) == list(range(cfg.steps))
    transitions = sorted({min(s for s, w in worlds.items() if w == wv)
                          for wv in (48, 96)})
    for boundary in transitions:
        pre = np.mean([losses[s]
                       for s in range(boundary - 3, boundary)])
        post = np.mean([losses[s]
                        for s in range(boundary, boundary + 3)])
        assert 1 / 3 < post / pre < 3, (
            f"{rule}: loss discontinuity at the world change near step "
            f"{boundary}: {pre:.5f} -> {post:.5f}"
        )

    # The final checkpoint is a first-class verified artifact: it
    # reshard-restores cleanly onto arbitrary worlds, bit-identically.
    from distributed_machine_learning_tpu.train.checkpoint import (
        latest_checkpoint,
        reshard_restore,
    )

    latest = latest_checkpoint(cfg.ckpt_dir)
    assert latest is not None and latest.endswith(f"step_{cfg.steps}")
    restored = {}
    for w in (1, 48, 96, 7):
        state, spec = reshard_restore(latest, world=w)
        assert spec.world == w
        restored[w] = np.asarray(state.params["w"]).tobytes()
    assert len(set(restored.values())) == 1

    # gang_status renders the full trajectory and the transport line
    # from the mirror directory — a dead campaign reads like any gang.
    tool = _gang_status_tool()
    status = tool.collect(os.path.join(str(tmp_path), "gang"),
                          os.path.join(str(tmp_path), "no-telemetry"))
    assert status["world_trajectory"] == [64, 48, 96]
    kinds = [e.get("kind") for e in status["health"]]
    assert "shrink" in kinds and "grow" in kinds and "promote" in kinds
    assert status["transport"]["backend"] == "inproc"
    rendered = tool.render(status)
    assert "world trajectory: 64 -> 48 -> 96" in rendered
    assert "transport: inproc" in rendered


# ---------------------------------------------------------------------------
# Slow campaigns
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.faultinject
def test_rolling_stragglers_replace_policy(tmp_path):
    """Rolling stragglers at world 16 under the backup-worker policy:
    three stall waves on different ranks each demote the flagged rank
    to the spare pool and promote a warm spare in its place — world
    size unchanged through every replacement, consumption exactly-once
    throughout, the health ledger narrating every swap."""
    codes, events, tx, hub, elapsed, cfg = _campaign(
        str(tmp_path), world=16, steps=14, save_every=5,
        faults=("stall_rank@3:4:3.0,stall_rank@6:7:3.0,"
                "stall_rank@9:10:3.0"),
        spares=4, max_restarts=8, straggler_policy="replace",
        replace_after=2, straggler_multiple=4.0,
        straggler_consecutive=3,
    )
    assert len(codes) == 16 and set(codes) == {0}  # world unchanged
    assert events.spare_demotions >= 2
    assert events.spare_promotions >= 2
    assert events.gang_grows == 0 and events.gang_shrinks == 0
    health = tx.read_health_events()
    kinds = [e["kind"] for e in health]
    assert kinds.count("replace") >= 2
    assert "demote" in kinds and "promote" in kinds
    demoted = {e["rank"] for e in health if e["kind"] == "demote"}
    assert demoted & {3, 6, 9}, (
        f"demotions {demoted} never touched a stalled rank")
    _assert_exactly_once_chained(tx.read_consumed(), cfg.steps)


@pytest.mark.slow
@pytest.mark.faultinject
def test_tcp_gang_survives_partition_end_to_end(tmp_path):
    """The full-stack TCP proof: a real subprocess gang over the tcp
    backend with rank 1's channel severed mid-run (--tx-chaos).  Its
    beats stop advancing, the peers declare it dead within
    ``peer_timeout_s``, the gang restarts coordinated, the relaunch
    heals the link, and the run finishes clean — with the partitioned
    rank's own log showing the self-abort and the transport-health
    line in gang_status."""
    from distributed_machine_learning_tpu.cli.gang import (
        scrubbed_worker_env,
    )

    root = str(tmp_path / "tcp")
    res = subprocess.run(
        [sys.executable, "-m",
         "distributed_machine_learning_tpu.cli.gang",
         "--workers", "3", "--steps", "8", "--save-every", "4",
         "--ckpt-dir", os.path.join(root, "ckpt"),
         "--gang-dir", os.path.join(root, "gang"),
         "--gang-transport", "tcp",
         "--tx-chaos", "partition@1:40",
         "--peer-timeout", "6", "--heartbeat-interval", "0.25"],
        capture_output=True, text=True, timeout=280,
        env=scrubbed_worker_env(REPO), cwd=REPO,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert "1 coordinated restart(s)" in res.stdout
    # The victim's own log names the partition (connection loss is
    # peer death, seen from the inside).
    with open(os.path.join(root, "gang", "logs",
                           "rank1.attempt0.log")) as f:
        assert "partitioned off the gang" in f.read()
    # Post-mortem: the status tool renders the tcp transport line and
    # the restart history from the server's mirrored ledgers.
    res_status = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "gang_status.py"),
         os.path.join(root, "gang"), "--json"],
        capture_output=True, text=True, timeout=60,
    )
    assert res_status.returncode == 0, res_status.stderr
    status = json.loads(res_status.stdout)
    assert status["transport"]["backend"] == "tcp"
    assert any(e.get("kind") == "restart" for e in status["health"])


# ---------------------------------------------------------------------------
# ISSUE 19: continuous-batching replicas under the serving chaos rules
# ---------------------------------------------------------------------------

SERVING_ENGINE_BUDGET_S = 150.0


@pytest.mark.faultinject
def test_serving_engine_replica_kill_requeues_exactly_once(tmp_path):
    """Kill a replica whose continuous-batching engine holds sequences
    mid-decode.  The router's beat-staleness eviction must requeue
    every rid the dead replica owned, the survivor plus the promoted
    warm spare must re-serve them token-for-token (greedy decode: the
    re-served answer is bit-identical to the reference), the audit
    must stay exactly-once, and the whole recovery must land inside
    the wall-clock cap.  The engine's prefill/decode stage split and
    the requeue scar must both show in the router's stage quantiles."""
    import threading

    import jax
    import jax.numpy as jnp

    from distributed_machine_learning_tpu.inference.continuous import (
        ContinuousEngine,
        EngineConfig,
    )
    from distributed_machine_learning_tpu.inference.generate import (
        generate,
    )
    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )
    from distributed_machine_learning_tpu.runtime.serving import (
        ServingConfig,
        ServingRouter,
    )
    from distributed_machine_learning_tpu.runtime.serving_worker import (
        ServingWorkerConfig,
        start_worker_thread,
    )

    MAX_NEW = 12
    model = TransformerLM(vocab_size=32, d_model=16, n_layers=2,
                          n_heads=4, n_kv_heads=2)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]

    def make_engine():
        eng = ContinuousEngine(model, params, EngineConfig(
            max_lanes=2, block_size=4, num_blocks=32, max_len=16,
            max_new=MAX_NEW, levers=("latency",)))
        # Compile before heartbeating: XLA tracing inside the first
        # live step reads as a stale beat at a 0.4s eviction timeout.
        eng.warmup(prompt_lens=(3,))
        return eng

    hub = InProcHub(mirror_dir=os.path.join(str(tmp_path), "gang"))
    make_tx = lambda: InProcTransport(hub)  # noqa: E731
    router = ServingRouter(make_tx(), ServingConfig(
        replicas=2, micro_batch=2, max_outstanding=6,
        replica_timeout_s=0.4, poll_s=0.002))
    wcfg = ServingWorkerConfig(heartbeat_interval=0.02, micro_batch=2)
    fleet = []
    for rank in range(3):           # 2 live + 1 warm spare
        stop = threading.Event()
        t, out = start_worker_thread(make_tx(), rank, None, stop, wcfg,
                                     engine=make_engine())
        fleet.append((rank, stop, t, out))
    stop_router = threading.Event()
    rt = threading.Thread(target=router.run, args=(stop_router,),
                          name="engine-chaos-router", daemon=True)
    rt.start()
    start = time.monotonic()
    try:
        deadline = time.monotonic() + 60.0
        while True:
            with router._lock:
                if len(router._replicas) >= 2:
                    break
            assert time.monotonic() < deadline, "fleet never warmed up"
            time.sleep(0.01)
        prompts = {}
        for i in range(16):
            p = [1 + i % 11, 2 + i % 7, 3]
            prompts[router.submit(list(p))] = p
        # Kill the first replica seen holding >= 2 in-flight rids —
        # its engine is mid-decode on real sequences at that moment.
        victim = None
        while victim is None:
            with router._lock:
                for rank, rep in router._replicas.items():
                    if len(rep.in_flight) >= 2:
                        victim = rank
                        break
            assert time.monotonic() < deadline, "no replica loaded up"
        fleet[victim][1].set()      # hard kill, mid-flight
        assert router.wait_idle(90.0), router.audit()
    finally:
        verdict = router.close()
        stop_router.set()
        for _, stop, t, _ in fleet:
            stop.set()
            t.join(10.0)
        rt.join(10.0)
    elapsed = time.monotonic() - start
    assert elapsed < SERVING_ENGINE_BUDGET_S, (
        f"engine kill campaign took {elapsed:.1f}s")
    assert verdict["exactly_once"], verdict
    assert verdict["evictions"] >= 1, verdict
    assert verdict["redispatches"] >= 1, verdict
    # Every answer is the model's true decode — re-served rids
    # included (requeue restarts from the prompt; greedy decode makes
    # the second serving bit-identical).
    for rid, p in prompts.items():
        entry = router.result(rid)
        assert entry is not None and entry["state"] == "done", rid
        want = np.asarray(generate(
            model, params, np.asarray([p], np.int32), MAX_NEW
        ))[0].tolist()
        assert entry["result"] == want, rid
    # The engine's stage split reached the stage histograms, and at
    # least one completion carries the requeue scar.
    stages = verdict["stage_latency"]
    assert "prefill" in stages and "decode" in stages, sorted(stages)
    assert "requeued" in stages, sorted(stages)
