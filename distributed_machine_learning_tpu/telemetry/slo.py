"""Live SLO engine for the serving fleet (ISSUE 17).

PR 16's serving audit answers "did every request complete exactly
once"; it cannot answer "is the fleet meeting its latency objective
RIGHT NOW" — the question a deploy gate or a canary rollback (the
ROADMAP items above this layer) actually asks.  This module evaluates
declared objectives over the live request outcome stream:

- :func:`parse_slo` — one objective from CLI text: ``p99<=250ms``
  (a latency quantile bound), ``error_ratio<=0.01`` or
  ``reject_ratio<=1%`` (outcome-fraction bounds).
- :class:`SLOEngine` — feed one :meth:`observe` per request outcome
  (completed with a latency, errored, or rejected at admission).  Every
  objective maps onto an **error budget** — the allowed bad-outcome
  fraction (``p99<=X`` allows 1% of requests over ``X``;
  ``reject_ratio<=Y`` allows ``Y``) — and the engine tracks the
  **burn rate**: observed bad fraction ÷ budget, over a short and a
  long sliding window.  An alert fires when BOTH windows burn above
  the threshold (the standard multi-window rule: the long window
  proves the problem is sustained, the short window proves it is
  still happening — a burst that already ended never pages).
- :meth:`SLOEngine.verdict` — the end-of-run judgement ``cli/serve.py``
  prints: an objective fails when its whole-run bad fraction exceeded
  the budget OR a burn-rate alert fired during the run (a sustained
  mid-run breach is a violation even if a quiet tail averages it away).

Clock discipline: the engine never reads wall time on its own — the
caller injects timestamps (``now=``) or a ``now_fn`` (defaulting to
``time.monotonic``, single-process only, per DML001).  Injected
timestamps are what make the burn-rate tests deterministic.

Deliberately stdlib-only and jax-free, like ``telemetry/aggregator.py``
— the ``tools/`` layer imports it against a dead run's ledgers.
"""

from __future__ import annotations

import dataclasses
import re
import time
from collections import deque

_LATENCY_RE = re.compile(r"^p(\d{1,2}(?:\.\d+)?)$")
_RATIO_KINDS = ("error_ratio", "reject_ratio")


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One declared objective, normalized to an error budget."""

    raw: str            # the CLI text, echoed in verdicts/alerts
    kind: str           # "latency" | "error_ratio" | "reject_ratio"
    threshold: float    # latency bound (seconds) or allowed fraction
    budget: float       # allowed bad-outcome fraction, in (0, 1)

    def is_relevant(self, outcome: "_Outcome") -> bool:
        if self.kind == "latency":
            return outcome.latency_s is not None
        if self.kind == "error_ratio":
            return not outcome.rejected
        return True  # reject_ratio judges every admission attempt

    def is_bad(self, outcome: "_Outcome") -> bool:
        if self.kind == "latency":
            return (outcome.latency_s is not None
                    and outcome.latency_s > self.threshold)
        if self.kind == "error_ratio":
            return outcome.error
        return outcome.rejected


@dataclasses.dataclass(frozen=True)
class _Outcome:
    t: float
    latency_s: float | None
    error: bool
    rejected: bool


def _parse_seconds(text: str) -> float:
    text = text.strip()
    for suffix, scale in (("ms", 1e-3), ("us", 1e-6), ("s", 1.0)):
        if text.endswith(suffix):
            return float(text[: -len(suffix)]) * scale
    return float(text)


def _parse_fraction(text: str) -> float:
    text = text.strip()
    if text.endswith("%"):
        return float(text[:-1]) / 100.0
    return float(text)


def parse_slo(spec: str) -> SLOSpec:
    """``p99<=250ms`` / ``p95<=0.1`` / ``error_ratio<=0.01`` /
    ``reject_ratio<=5%`` -> :class:`SLOSpec`.  Raises ``ValueError``
    with the offending text for anything else."""
    raw = spec.strip()
    if "<=" not in raw:
        raise ValueError(f"SLO spec needs '<=': {spec!r}")
    lhs, rhs = (part.strip() for part in raw.split("<=", 1))
    m = _LATENCY_RE.match(lhs)
    if m:
        q = float(m.group(1)) / 100.0
        if not 0.0 < q < 1.0:
            raise ValueError(f"SLO quantile out of range: {spec!r}")
        threshold = _parse_seconds(rhs)
        if threshold <= 0:
            raise ValueError(f"SLO latency bound must be > 0: {spec!r}")
        return SLOSpec(raw=raw, kind="latency", threshold=threshold,
                       budget=1.0 - q)
    if lhs in _RATIO_KINDS:
        frac = _parse_fraction(rhs)
        if not 0.0 < frac < 1.0:
            raise ValueError(f"SLO ratio must be in (0, 1): {spec!r}")
        return SLOSpec(raw=raw, kind=lhs, threshold=frac, budget=frac)
    raise ValueError(
        f"unknown SLO objective {lhs!r} (want pNN, error_ratio or "
        f"reject_ratio): {spec!r}")


class SLOEngine:
    """Sliding-window burn-rate evaluation over request outcomes."""

    def __init__(self, objectives, *, short_window_s: float = 5.0,
                 long_window_s: float = 60.0,
                 burn_threshold: float = 2.0, now_fn=None):
        if short_window_s <= 0 or long_window_s < short_window_s:
            raise ValueError(
                "windows must satisfy 0 < short <= long, got "
                f"{short_window_s}/{long_window_s}")
        if burn_threshold <= 0:
            raise ValueError(
                f"burn threshold must be > 0, got {burn_threshold}")
        self.objectives: list[SLOSpec] = [
            o if isinstance(o, SLOSpec) else parse_slo(o)
            for o in objectives]
        self.short_window_s = float(short_window_s)
        self.long_window_s = float(long_window_s)
        self.burn_threshold = float(burn_threshold)
        self._now = now_fn if now_fn is not None else time.monotonic
        self._window: deque[_Outcome] = deque()
        # Whole-run tallies per objective (never trimmed): the verdict
        # judges the run, the windows judge the moment.
        self._relevant = {o.raw: 0 for o in self.objectives}
        self._bad = {o.raw: 0 for o in self.objectives}
        self._alerting: set[str] = set()  # objectives in an alert episode
        self.alerts: list[dict] = []

    # -- feed ------------------------------------------------------------
    def observe(self, *, latency_s: float | None = None,
                error: bool = False, rejected: bool = False,
                now: float | None = None) -> list[dict]:
        """Record one request outcome; returns any alerts fired by it.
        ``now`` injects a deterministic timestamp (tests, replays)."""
        t = float(now) if now is not None else self._now()
        outcome = _Outcome(t=t, latency_s=latency_s, error=bool(error),
                           rejected=bool(rejected))
        self._window.append(outcome)
        while self._window and self._window[0].t < t - self.long_window_s:
            self._window.popleft()
        fired = []
        for obj in self.objectives:
            if obj.is_relevant(outcome):
                self._relevant[obj.raw] += 1
                if obj.is_bad(outcome):
                    self._bad[obj.raw] += 1
            short = self._burn(obj, t, self.short_window_s)
            long_ = self._burn(obj, t, self.long_window_s)
            if (short is not None and long_ is not None
                    and short > self.burn_threshold
                    and long_ > self.burn_threshold):
                if obj.raw not in self._alerting:
                    self._alerting.add(obj.raw)
                    alert = {"slo": obj.raw, "at": t,
                             "short_burn": short, "long_burn": long_}
                    self.alerts.append(alert)
                    fired.append(alert)
            elif short is not None and short <= self.burn_threshold:
                # Recovery re-arms the alert: a later sustained breach
                # is a new episode, not a continuation.
                self._alerting.discard(obj.raw)
        return fired

    def _burn(self, obj: SLOSpec, now: float,
              window_s: float) -> float | None:
        """Bad fraction ÷ budget over the trailing window, or None with
        no relevant outcome in it (no evidence is not a breach)."""
        relevant = bad = 0
        for o in self._window:
            if o.t < now - window_s or not obj.is_relevant(o):
                continue
            relevant += 1
            if obj.is_bad(o):
                bad += 1
        if relevant == 0:
            return None
        return (bad / relevant) / obj.budget

    # -- judgement -------------------------------------------------------
    def verdict(self) -> dict:
        """Whole-run pass/fail per objective, plus the alert history."""
        rows = []
        ok = True
        for obj in self.objectives:
            relevant = self._relevant[obj.raw]
            bad = self._bad[obj.raw]
            ratio = bad / relevant if relevant else 0.0
            alerts = sum(1 for a in self.alerts if a["slo"] == obj.raw)
            row_ok = ratio <= obj.budget and alerts == 0
            ok = ok and row_ok
            rows.append({
                "slo": obj.raw, "kind": obj.kind,
                "budget": obj.budget, "bad_ratio": ratio,
                "relevant": relevant, "bad": bad,
                "alerts": alerts, "ok": row_ok,
            })
        return {"ok": ok, "objectives": rows,
                "alerts": list(self.alerts)}


def format_verdict(verdict: dict) -> str:
    """One human line per objective + the overall verdict — what
    ``cli/serve.py`` prints at end of run."""
    lines = []
    for row in verdict["objectives"]:
        mark = "PASS" if row["ok"] else "FAIL"
        lines.append(
            f"  slo {row['slo']}: {mark} "
            f"(bad {row['bad']}/{row['relevant']} = "
            f"{row['bad_ratio']:.4f} vs budget {row['budget']:.4f}, "
            f"{row['alerts']} alert(s))")
    lines.append("slo verdict: "
                 + ("PASS" if verdict["ok"] else "FAIL")
                 + f" ({len(verdict['alerts'])} alert(s) fired)")
    return "\n".join(lines)
