"""Checkpoint/resume: round-trip fidelity and training continuity.

The reference has no checkpointing (SURVEY.md §5); this subsystem is an
extension.  The key invariants: a restored state is bit-identical to the
saved one, and training resumed from a checkpoint produces the same
trajectory as uninterrupted training (pure-function step + saved PRNG
key make this exact, not approximate).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.cli.common import init_model_and_state
from distributed_machine_learning_tpu.models.vgg import VGGTest
from distributed_machine_learning_tpu.train.checkpoint import (
    latest_checkpoint,
    restore_checkpoint,
    save_checkpoint,
)
from distributed_machine_learning_tpu.train.sgd import SGDConfig
from distributed_machine_learning_tpu.train.step import make_train_step


def _tiny_model():
    return VGGTest(use_bn=True)


def _batch(rng, n=4):
    images = rng.integers(0, 256, (n, 32, 32, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, n).astype(np.int32)
    return jnp.asarray(images), jnp.asarray(labels)


def test_roundtrip_bit_identical(tmp_path, rng):
    state = init_model_and_state(_tiny_model(),
                                 config=SGDConfig(learning_rate=0.05))
    path = save_checkpoint(tmp_path, state)
    restored = restore_checkpoint(path)
    for a, b in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree_util.tree_leaves(state.batch_stats),
                    jax.tree_util.tree_leaves(restored.batch_stats)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(state.rng), np.asarray(restored.rng))
    assert int(restored.step) == int(state.step)
    assert restored.config == SGDConfig(learning_rate=0.05)


def test_latest_checkpoint_picks_highest_step(tmp_path):
    state = init_model_and_state(_tiny_model())
    assert latest_checkpoint(tmp_path) is None
    save_checkpoint(tmp_path, state)
    later = state.replace(step=jnp.asarray(7, jnp.int32))
    save_checkpoint(tmp_path, later)
    latest = latest_checkpoint(tmp_path)
    assert latest is not None and latest.endswith("step_7")
    assert latest_checkpoint(tmp_path / "nonexistent") is None


def test_incomplete_checkpoint_skipped_and_resave_overwrites(tmp_path):
    state = init_model_and_state(_tiny_model())
    complete = save_checkpoint(tmp_path, state)
    # Simulate a crash mid-save at a later step: directory exists but the
    # config file (written last) is missing.
    broken = tmp_path / "step_9" / "state"
    broken.mkdir(parents=True)
    latest = latest_checkpoint(tmp_path)
    assert latest == complete  # falls back past the incomplete step_9
    # Re-saving the same step must overwrite, not raise.
    save_checkpoint(tmp_path, state)


def test_resume_matches_uninterrupted_trajectory(tmp_path, rng):
    model = _tiny_model()
    step = make_train_step(model, augment=True)
    batches = [_batch(rng) for _ in range(4)]

    # Uninterrupted: 4 steps.
    s = init_model_and_state(model)
    for x, y in batches:
        s, loss_straight = step(s, x, y)

    # Interrupted: 2 steps, save, restore (with template), 2 more steps.
    s2 = init_model_and_state(model)
    for x, y in batches[:2]:
        s2, _ = step(s2, x, y)
    path = save_checkpoint(tmp_path, s2)
    s3 = restore_checkpoint(path, abstract_state=init_model_and_state(model))
    assert int(s3.step) == 2
    for x, y in batches[2:]:
        s3, loss_resumed = step(s3, x, y)

    assert float(loss_straight) == pytest.approx(float(loss_resumed), abs=0)
    for a, b in zip(jax.tree_util.tree_leaves(s.params),
                    jax.tree_util.tree_leaves(s3.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree_util.tree_leaves(s.momentum),
                    jax.tree_util.tree_leaves(s3.momentum)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_async_checkpoint_roundtrip(tmp_path, rng):
    # Async save must land the same complete layout as the sync writer,
    # be invisible to latest_checkpoint until finished, and restore
    # bit-identically.
    import numpy as np

    from distributed_machine_learning_tpu.cli.common import init_model_and_state
    from distributed_machine_learning_tpu.models.vgg import VGGTest
    from distributed_machine_learning_tpu.train.checkpoint import (
        AsyncCheckpointWriter,
        latest_checkpoint,
        restore_checkpoint,
    )

    state = init_model_and_state(VGGTest(use_bn=False))
    with AsyncCheckpointWriter() as writer:
        path = writer.save(tmp_path, state)
        writer.wait()
    assert latest_checkpoint(tmp_path) == path
    restored = restore_checkpoint(path, abstract_state=state)
    import jax

    for a, b in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert type(restored.config) is type(state.config)


@pytest.mark.slow
def test_resume_plain_checkpoint_into_unsync_bn_quirk(tmp_path):
    """Cross-layout resume: a checkpoint saved with plain synced-BN [C]
    stats restores into --unsync-bn quirk mode (stacked [world, C]) via
    the metadata-inspected template pick in cli/common.py — no blanket
    except, and a corrupt checkpoint would surface its real error."""
    from distributed_machine_learning_tpu.cli import part3
    from distributed_machine_learning_tpu.train.checkpoint import (
        checkpoint_array_shapes,
        latest_checkpoint,
    )

    common = ["--batch-size", "4", "--max-iters", "2", "--model", "vggtest",
              "--eval-batches", "0", "--eval-batch-size", "16",
              "--data-root", str(tmp_path), "--ckpt-dir", str(tmp_path / "ck")]
    part3.main(common)  # plain synced-BN run writes the checkpoint
    latest = latest_checkpoint(tmp_path / "ck")
    assert latest is not None
    stats_shapes = checkpoint_array_shapes(latest)["batch_stats"]
    first = jax.tree_util.tree_leaves(
        stats_shapes, is_leaf=lambda x: isinstance(x, tuple)
    )[0]
    assert len(first) == 1  # plain [C] layout on disk
    # Resume the same run in quirk mode: restore must go through the
    # plain template then stack per-device stats rows.
    part3.main(common + ["--resume", "--unsync-bn"])
