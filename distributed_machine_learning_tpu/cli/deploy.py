"""Continuous deployment onto a live serving fleet (ISSUE 18).

Builds an elastic fleet (``cli/serve.py`` shape: router + replica
workers over the chosen ``--gang-transport``), fires sustained
synthetic load at it, and while the fleet is under load rolls
``--deploys`` checkpoints from a training-style step directory
through the full train-to-serve pipeline: verified-chain watch →
reshard+int8 requantize (digests re-verified post-requantize) →
per-replica fenced hot-swap → canary slice → auto-promote or
auto-rollback.  Zero requests drop across every swap — the exit
status is the exactly-once audit plus the expected deploy outcomes.

    python -m distributed_machine_learning_tpu.cli.deploy \
        --replicas 4 --spares 1 --requests 300 --deploys 2

    # inject a quality regression into deploy #2: the canary probe
    # fails, the controller rolls back, the run still audits clean:
    python -m distributed_machine_learning_tpu.cli.deploy \
        --replicas 4 --requests 300 --deploys 2 --inject regression@2

The checkpoints are real: a tiny ``TransformerLM`` ``TrainState`` is
saved per deploy (dp layout) and every deploy restores it through
``runtime/deploy.py::load_serving_weights`` — the manifest chain,
the serving quantizer, and the post-requantize digest all run.  The
replica *compute* stays synthetic (echo + checksum token, version-
tagged) so the fleet story is demonstrable without a decode model;
a production ``on_swap`` would call ``load_serving_weights`` +
``inference/generate.py::make_serving_step`` with the staged
checkpoint path instead.

``tools/serve_status.py <gang-dir>`` renders the resulting
deployment history (per-replica weight versions, canary state, the
promote/rollback ledger) after the run.
"""

from __future__ import annotations

import argparse
import sys
import threading
import time

from distributed_machine_learning_tpu.cli.serve import (
    _instance_telemetry,
    synthetic_step,
)


def checksum_token(prompt) -> int:
    """The synthetic step's answer contract (``cli/serve.py``): the
    deploy-time quality probe recomputes this to judge an output."""
    return (sum(prompt) + len(prompt)) % 97


def versioned_step(version: int, service_time_s: float = 0.0,
                   corrupt: bool = False):
    """A version-tagged synthetic decode step.  ``corrupt=True`` mis-
    computes the checksum token — the injected quality regression the
    canary probe must catch."""
    base = synthetic_step(service_time_s)

    def step(prompts):
        outs = base(prompts)
        if corrupt:
            outs = [o[:-1] + [(o[-1] + 1) % 97] for o in outs]
        return outs

    return step


def quality_probe(outcome: dict) -> bool:
    """True iff the served output honours the synthetic-step contract
    (echo + correct checksum token)."""
    prompt, out = outcome.get("prompt"), outcome.get("output")
    if not isinstance(out, list) or prompt is None:
        return False
    return out == list(prompt) + [checksum_token(prompt)]


def write_demo_checkpoint(directory: str, step: int):
    """Save a verified tiny-LM dp checkpoint at ``step`` — the
    training side of the demo.  Returns the step dir written."""
    import jax
    import jax.numpy as jnp

    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )
    from distributed_machine_learning_tpu.train.checkpoint import (
        save_checkpoint,
    )
    from distributed_machine_learning_tpu.train.optimizers import (
        AdamWConfig,
    )
    from distributed_machine_learning_tpu.train.state import TrainState

    model = TransformerLM(vocab_size=32, d_model=16, n_layers=1,
                          n_heads=2)
    params = model.init(jax.random.PRNGKey(step),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    state = TrainState.create(params=params,
                              rng=jax.random.PRNGKey(9),
                              config=AdamWConfig())
    state = state.replace(step=jnp.asarray(step, jnp.int32))
    return save_checkpoint(directory, state)


def _run(args) -> int:
    import tempfile

    from distributed_machine_learning_tpu.runtime.deploy import (
        DeployConfig,
        DeployController,
    )
    from distributed_machine_learning_tpu.runtime.faults import FaultEvents
    from distributed_machine_learning_tpu.runtime.serving import (
        Overloaded,
        ServingConfig,
        ServingRouter,
    )
    from distributed_machine_learning_tpu.runtime.serving_worker import (
        ServingWorkerConfig,
        start_worker_thread,
    )
    from distributed_machine_learning_tpu.runtime.transport import (
        FileTransport,
        InProcHub,
        InProcTransport,
        TcpGangServer,
        TcpTransport,
    )
    from distributed_machine_learning_tpu.utils.summary import (
        resilience_summary,
    )

    inject_at = 0
    if args.inject:
        kind, _, at = args.inject.partition("@")
        if kind != "regression" or not at.isdigit():
            print(f"bad --inject {args.inject!r} "
                  "(expected regression@DEPLOY_N)", file=sys.stderr)
            return 2
        inject_at = int(at)

    world = args.replicas + args.spares
    server = None
    if args.gang_transport == "inproc":
        hub = InProcHub(mirror_dir=args.gang_dir)
        make_tx = lambda: InProcTransport(hub)  # noqa: E731
    elif args.gang_transport == "file":
        if not args.gang_dir:
            print("--gang-transport file requires --gang-dir",
                  file=sys.stderr)
            return 2
        make_tx = lambda: FileTransport(args.gang_dir)  # noqa: E731
    else:  # tcp: host the gang server in-process
        server = TcpGangServer(mirror_dir=args.gang_dir).start()
        address = server.address
        make_tx = lambda: TcpTransport(address,  # noqa: E731
                                       backoff_s=0.01)

    ckpt_dir = args.checkpoint_dir or tempfile.mkdtemp(
        prefix="deploy_ckpts_")
    events = FaultEvents()
    router_tel = _instance_telemetry(args, "router")
    router = ServingRouter(
        make_tx(),
        ServingConfig(replicas=args.replicas,
                      max_queue=args.max_queue,
                      micro_batch=args.micro_batch,
                      replica_timeout_s=args.replica_timeout),
        events=events, telemetry=router_tel)

    # Each deploy version gets its own step; --inject corrupts one.
    def on_swap_for():
        def on_swap(version, rec):
            corrupt = inject_at and version == inject_at
            return versioned_step(version, args.service_time,
                                  corrupt=bool(corrupt))
        return on_swap

    stop = threading.Event()
    wcfg = ServingWorkerConfig(micro_batch=args.micro_batch)
    worker_tels = [_instance_telemetry(args, f"replica{rank}")
                   for rank in range(world)]
    workers = [start_worker_thread(
        make_tx(), rank, versioned_step(0, args.service_time), stop,
        wcfg, on_swap=on_swap_for(), telemetry=worker_tels[rank])
        for rank in range(world)]
    router_thread = threading.Thread(target=router.run, args=(stop,),
                                     name="deploy-router", daemon=True)
    router_thread.start()

    controller = DeployController(
        make_tx(), router,
        DeployConfig(checkpoint_dir=ckpt_dir,
                     canary_replicas=args.canary_replicas,
                     canary_every_n=args.canary_every,
                     canary_window=args.canary_window,
                     judge_timeout_s=args.timeout,
                     slo=tuple(args.slo)),
        events=events, telemetry=router_tel,
        quality_fn=quality_probe)

    # Sustained load from a client thread while deploys roll: traffic
    # keeps flowing until every deploy has been judged (canary windows
    # need completions) AND at least --requests were admitted.
    submitted = {"n": 0}
    deploys_done = threading.Event()
    rng_state = 12345

    def load():
        nonlocal rng_state
        while not stop.is_set():
            if deploys_done.is_set() and submitted["n"] >= args.requests:
                return
            rng_state = (1103515245 * rng_state + 12345) % (1 << 31)
            prompt = [1 + (rng_state >> s) % 13 for s in (3, 7, 11)][
                :1 + rng_state % 3]
            try:
                router.submit(prompt)
                submitted["n"] += 1
            except Overloaded:
                time.sleep(0.005)

    load_thread = threading.Thread(target=load, name="deploy-load",
                                   daemon=True)
    load_thread.start()

    outcomes = []
    try:
        for n in range(1, args.deploys + 1):
            write_demo_checkpoint(ckpt_dir, step=100 * n)
            out = controller.poll_once()
            outcomes.append(out)
            print(f"deploy {n}: {out['outcome']}"
                  + (f" ({out['reason']})"
                     if out["outcome"] == "rolled_back" else ""))
        deploys_done.set()
        load_thread.join(timeout=args.timeout)
        ok = router.wait_idle(args.timeout)
    finally:
        verdict = router.close()
        stop.set()
        for t, _ in workers:
            t.join(timeout=5)
        router_thread.join(timeout=5)
        load_thread.join(timeout=5)
        for tel in (router_tel, *worker_tels):
            if tel is not None:
                tel.close()
        if server is not None:
            server.stop()

    summary = controller.summary()
    print(f"fleet: {args.replicas} replicas + {args.spares} spares "
          f"over {args.gang_transport}")
    print(f"requests: {verdict['completed']}/{verdict['admitted']} "
          f"completed, {verdict['duplicates_discarded']} duplicates "
          "discarded")
    print(f"deploys: {len(outcomes)} "
          f"({events.canary_promotions} promoted, "
          f"{events.canary_rollbacks} rolled back, "
          f"{events.weight_swaps} replica swaps)")
    print(f"deployed version: v{summary['deployed_version']} "
          f"(state: {summary['state']})")
    print(resilience_summary(events))
    rc = 0
    for n, out in enumerate(outcomes, 1):
        want = "rolled_back" if inject_at == n else "promoted"
        if out is None or out["outcome"] != want:
            print(f"FAILED: deploy {n} expected {want}, got "
                  f"{out and out['outcome']}", file=sys.stderr)
            rc = 1
    if not ok or not verdict["exactly_once"]:
        print("FAILED: not every admitted request completed exactly "
              "once", file=sys.stderr)
        return 1
    if rc == 0:
        print("exactly-once audit: PASS")
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--replicas", type=int, default=4,
                    help="target live replicas")
    ap.add_argument("--spares", type=int, default=1,
                    help="warm spares kept ready for promotion")
    ap.add_argument("--requests", type=int, default=200,
                    help="synthetic requests fired across the run")
    ap.add_argument("--deploys", type=int, default=1,
                    help="checkpoints written and rolled onto the fleet")
    ap.add_argument("--inject", default=None, metavar="regression@N",
                    help="corrupt the Nth deploy's outputs so the "
                         "canary probe fails and the rollback path runs")
    ap.add_argument("--checkpoint-dir", dest="checkpoint_dir",
                    default=None,
                    help="step directory the controller watches "
                         "(default: a temp dir this run owns)")
    ap.add_argument("--canary-replicas", dest="canary_replicas",
                    type=int, default=1,
                    help="replicas swapped first as the canary")
    ap.add_argument("--canary-every", dest="canary_every", type=int,
                    default=3,
                    help="traffic slice: every Nth dispatch to canary")
    ap.add_argument("--canary-window", dest="canary_window", type=int,
                    default=12,
                    help="canary completions needed before judging")
    ap.add_argument("--max-queue", dest="max_queue", type=int,
                    default=64, help="admission bound")
    ap.add_argument("--micro-batch", dest="micro_batch", type=int,
                    default=4, help="requests per dispatch")
    ap.add_argument("--service-time", dest="service_time", type=float,
                    default=0.0,
                    help="simulated decode seconds per micro-batch")
    ap.add_argument("--replica-timeout", dest="replica_timeout",
                    type=float, default=2.0,
                    help="beat staleness that evicts a replica")
    ap.add_argument("--timeout", type=float, default=60.0,
                    help="per-phase deadline (canary fill, fleet idle)")
    ap.add_argument("--gang-transport", dest="gang_transport",
                    choices=("file", "inproc", "tcp"),
                    default="inproc", help="control-plane backend")
    ap.add_argument("--gang-dir", dest="gang_dir", default=None,
                    help="file backend directory / ledger mirror for "
                         "post-mortem serve_status")
    ap.add_argument("--telemetry-dir", dest="telemetry_dir",
                    default=None,
                    help="per-instance telemetry artifacts")
    ap.add_argument("--slo", action="append", default=[],
                    metavar="SPEC",
                    help="canary-scoped objective, e.g. p99<=250ms "
                         "(repeatable): a burn-rate alert during the "
                         "canary window rolls the deploy back")
    args = ap.parse_args(argv)
    if args.replicas < 1:
        ap.error(f"--replicas must be >= 1, got {args.replicas}")
    if args.deploys < 1:
        ap.error(f"--deploys must be >= 1, got {args.deploys}")
    return _run(args)


if __name__ == "__main__":
    sys.exit(main())
