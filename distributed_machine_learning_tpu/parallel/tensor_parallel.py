"""Tensor parallelism for the transformer — the GSPMD way.

Capability beyond the reference (TP absent — SURVEY.md §2.3), designed
TPU-first: instead of hand-writing Megatron's f/g collectives, we declare
*where parameters live* (column-split then row-split per block, the
Megatron layout) as ``PartitionSpec`` rules and ``jit`` the unmodified
train step with those in/out shardings.  XLA's SPMD partitioner then
derives every activation sharding and inserts the all-reduces — one psum
after attention-out and one after fc_out per block, riding ICI — which is
exactly Megatron's schedule, obtained from the compiler instead of
hand-rolled comm calls.

Composes with data parallelism on the same mesh: batch sharded over
``data_axis``, params over ``model_axis``; the compiler emits the gradient
all-reduce over ``data_axis`` and the activation all-reduces over
``model_axis`` in one program it can overlap freely.

Layout rules (flax param paths of ``models/transformer.py``):

  ====================  =====================  ========================
  param                 shape                  spec (model axis = "model")
  ====================  =====================  ========================
  attn qkv kernel       [E, 3, H, Dh]          heads sharded: (·,·,model,·)
  attn qkv bias         [3, H, Dh]             (·,model,·)
  attn out kernel       [H, Dh, E]             row-split: (model,·,·)
  fc_in kernel          [E, F]                 column-split: (·,model)
  fc_in bias            [F]                    (model,)
  fc_out kernel         [F, E]                 row-split: (model,·)
  embed embedding       [V, E]                 vocab-sharded: (model,·)
  lm_head kernel        [E, V]                 column-split: (·,model)
  lm_head bias          [V]                    (model,)
  everything else       —                      replicated
  ====================  =====================  ========================
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_machine_learning_tpu.parallel.gspmd import (
    make_cached_sharded_step,
    shard_state,
    state_shardings,
)
from distributed_machine_learning_tpu.train.lm_step import _lm_step_impl
from distributed_machine_learning_tpu.train.state import TrainState

MODEL_AXIS = "model"


def tp_spec_for(path: tuple[str, ...], ndim: int, model_axis: str = MODEL_AXIS) -> P:
    """PartitionSpec for one parameter, by its flax path."""
    path = tuple(path)
    leaf = path[-1]
    module = path[-2] if len(path) >= 2 else ""
    m = model_axis
    if module == "qkv":
        return P(None, None, m, None) if leaf == "kernel" else P(None, m, None)
    if module == "q":
        # GQA query projection: kernel [E, H, Dh], bias [H, Dh].
        return P(None, m, None) if leaf == "kernel" else P(m, None)
    if module == "kv":
        # GQA K/V projection: kernel [E, 2, Hkv, Dh], bias [2, Hkv, Dh].
        return P(None, None, m, None) if leaf == "kernel" else P(None, m, None)
    if module == "out" and leaf == "kernel":
        return P(m, None, None)
    if module == "fc_in":
        return P(None, m) if leaf == "kernel" else P(m)
    if module == "fc_out" and leaf == "kernel":
        return P(m, None)
    if module == "embed" and leaf == "embedding":
        return P(m, None)
    if module == "lm_head":
        return P(None, m) if leaf == "kernel" else P(m)
    return P(*(None,) * ndim)


def _spec_for(model_axis: str):
    # gspmd.SpecFor passes the leaf shape; the TP rules only need rank.
    return lambda path, shape: tp_spec_for(path, len(shape), model_axis)


def tp_state_shardings(
    state: TrainState, mesh: Mesh, model_axis: str = MODEL_AXIS
):
    """NamedSharding pytree for a TrainState: params + momentum follow the
    TP layout, scalar fields replicate."""
    return state_shardings(state, mesh, _spec_for(model_axis))


def shard_tp_state(
    state: TrainState, mesh: Mesh, model_axis: str = MODEL_AXIS
) -> TrainState:
    """Place a (host or replicated) TrainState into the TP layout."""
    return shard_state(state, mesh, _spec_for(model_axis))


def make_tp_lm_train_step(
    model,
    mesh: Mesh,
    data_axis: str = "batch",
    model_axis: str = MODEL_AXIS,
):
    """Build the TP(+DP) LM train step.

    ``model`` may use dense, flash, or auto attention (flash runs
    head-sharded inside the model's fully-manual shard_map wrap — see
    ``Attention.flash_head_axis``; sequence stays whole — combining TP
    with ring attention is the 3-D mesh step's job).
    The returned ``step(state, tokens, targets)`` expects ``state`` already
    placed via ``shard_tp_state`` and tokens/targets sharded over
    ``data_axis`` (see ``shard_tp_batch``).

    The sharding declarations are built from the first call's actual state
    (and cached per tree structure), so custom SGDConfig values — static
    pytree metadata on TrainState — never mismatch the jitted signature.
    """
    for a in (data_axis, model_axis):
        if a not in mesh.axis_names:
            raise ValueError(f"mesh is missing axis {a!r}: {mesh.axis_names}")
    if model.attn_impl in ("flash", "auto") and model.flash_mesh is None:
        # Flash composes with TP through the model's fully-manual
        # shard_map wrap with the HEAD dim sharded over the model axis:
        # heads are independent in flash, and each shard's local GQA
        # grouping stays aligned because H_local = groups · Hkv_local
        # (the divisibility checks below enforce both).  The Mosaic
        # custom call then sees local head counts and never meets the
        # partitioner.
        model = model.clone(
            flash_mesh=mesh,
            flash_batch_axis=data_axis,
            flash_head_axis=model_axis,
        )
    elif model.attn_impl not in ("dense", "flash", "auto"):
        raise ValueError(
            "tensor-parallel step supports dense/flash/auto attention; "
            "ring attention composes with TP via the 3-D mesh step"
        )
    n_model = mesh.shape[model_axis]
    if model.n_heads % n_model:
        raise ValueError(
            f"n_heads={model.n_heads} must be divisible by the model-axis "
            f"size {n_model} (heads are sharded over {model_axis!r})"
        )
    n_kv = getattr(model, "n_kv_heads", None)
    if n_kv is not None and n_kv % n_model:
        raise ValueError(
            f"n_kv_heads={n_kv} must be divisible by the model-axis size "
            f"{n_model} (K/V heads are sharded over {model_axis!r})"
        )
    batch_sharding = NamedSharding(mesh, P(data_axis, None))
    impl = partial(_lm_step_impl, model, axis_names=())
    return make_cached_sharded_step(impl, mesh, _spec_for(model_axis), batch_sharding)


def tp_decode_spec_for(
    path: tuple[str, ...], ndim: int, model_axis: str = MODEL_AXIS
) -> P:
    """PartitionSpec for one DECODE parameter (manual Megatron layout —
    ``inference.generate.make_tp_generate_fn``).

    Differences from the training rules (:func:`tp_spec_for`): embed and
    lm_head stay replicated (the embed gather reads only B rows per
    step; a sharded lm_head would shard the logits the sampler needs),
    row-parallel biases (``out``/``fc_out``) are replicated (pre-divided
    by tp in :func:`tp_decode_params` so the model's psum reassembles
    them), and the quantized leaves (``w_q`` [D_in, K_out] flat /
    ``scale`` [K_out]) shard the axis their module's parallelism splits
    — columns for the column-parallel projections (qkv/q/kv/fc_in),
    rows for the row-parallel ones (out/fc_out, scale replicated).
    """
    path = tuple(path)
    leaf = path[-1]
    module = path[-2] if len(path) >= 2 else ""
    m = model_axis
    col_parallel = module in ("qkv", "q", "kv", "fc_in")
    row_parallel = module in ("out", "fc_out")
    if "router" in path:
        # MoE router: tiny f32 [D, E] matmul whose argmax decides the
        # routing — replicated so every device routes identically.
        return P(*(None,) * ndim)
    if module == "moe":
        # Expert leaves shard their d_ff dim over the model axis — the
        # Megatron column/row split applied per expert (w_in/w_in_q
        # [E, D, F] column-parallel on F, w_out/w_out_q [E, F, D]
        # row-parallel on F); b_out is the row-parallel bias
        # (replicated, pre-divided by tp); w_out_scale is per-OUT-
        # channel [E, D], applied to each partial sum — commutes with
        # the psum, so replicated.
        return {
            "w_in": P(None, None, m), "w_in_q": P(None, None, m),
            "b_in": P(None, m), "w_in_scale": P(None, m),
            "w_out": P(None, m, None), "w_out_q": P(None, m, None),
            "b_out": P(*(None,) * ndim),
            "w_out_scale": P(*(None,) * ndim),
        }.get(leaf, P(*(None,) * ndim))
    if leaf == "w_q":
        if col_parallel:
            return P(None, m)
        if row_parallel:
            return P(m, None)
        return P(*(None,) * ndim)  # lm_head & others: replicated
    if leaf == "scale":
        return P(m) if col_parallel else P(*(None,) * ndim)
    if leaf == "bias" and row_parallel:
        return P(*(None,) * ndim)  # replicated, pre-divided by tp
    if module == "embed" or module == "lm_head":
        return P(*(None,) * ndim)
    if leaf in ("kernel", "bias"):
        return tp_spec_for(path, ndim, model_axis)
    return P(*(None,) * ndim)


# Fused projections whose FLAT quantized k_out mixes a leading part axis
# with the head axis: (n_parts, n_heads_axis_position). qkv = (3, H, Dh),
# kv = (2, Hkv, Dh); q = (H, Dh) is head-major already.
_FUSED_QUANT_LAYOUTS = {"qkv": 3, "kv": 2}


def tp_decode_params(params, tp: int, model_axis: str = MODEL_AXIS):
    """Arrange a decode param tree (full-precision or
    ``quantize_lm_params`` output) for :func:`tp_decode_spec_for`:

    - row-parallel biases (``out``/``fc_out``) divide by ``tp`` so the
      model's psum reassembles them exactly (tp is a power of two in
      practice, making the division bit-exact);
    - fused quantized projections (qkv/kv) re-order their flat ``w_q``
      columns and ``scale`` head-contiguously: [D, (3, H, Dh)flat] →
      [D, (tp, 3, H/tp, Dh)flat], so a plain ``P(None, model)`` hands
      each device exactly its heads' columns in the local flat layout
      its ``QuantDenseGeneral`` expects.

    Pure array transform — run once at serving setup, before
    ``jax.device_put`` with the decode shardings.
    """

    def permute_cols(w_q, scale, parts: int):
        d_in, k_out = w_q.shape
        hd = k_out // parts  # H·Dh
        # [D, parts, tp, H/tp, Dh] → [D, tp, parts, H/tp, Dh] → flat.
        def arrange(a, lead):
            # (H, Dh) is head-major in the flat layout, so tp blocks of
            # hd/tp columns ARE head blocks; hoisting the tp axis over
            # the parts axis makes each device's slice contiguous.
            shaped = a.reshape(*lead, parts, tp, hd // tp)
            return shaped.swapaxes(-3, -2).reshape(*lead, k_out)

        return arrange(w_q, (d_in,)), arrange(scale, ())

    def walk(name, node):
        if isinstance(node, dict) or hasattr(node, "items"):
            node = dict(node)
            if name in _FUSED_QUANT_LAYOUTS and "w_q" in node:
                parts = _FUSED_QUANT_LAYOUTS[name]
                w_q, scale = permute_cols(node["w_q"], node["scale"], parts)
                node = {**node, "w_q": w_q, "scale": scale}
            if name in ("out", "fc_out") and "bias" in node:
                node = {**node, "bias": node["bias"] / tp}
            if name == "moe" and "b_out" in node:
                # Expert row-parallel bias — the model's psum over the
                # tp partial sums reassembles it (same trick as fc_out).
                node = {**node, "b_out": node["b_out"] / tp}
            return {k: walk(k, v) for k, v in node.items()}
        return node

    return walk("", params)


def shard_tp_batch(mesh: Mesh, tokens, targets, data_axis: str = "batch"):
    """Tokens/targets sharded over the data axis, sequence whole."""
    from distributed_machine_learning_tpu.train.lm_step import shard_lm_batch

    return shard_lm_batch(mesh, tokens, targets, data_axis=data_axis, seq_axis=None)
