#!/usr/bin/env python3
"""Fuse per-rank Chrome traces into ONE Perfetto timeline — stdlib-only.

Each gang worker streams its own trace (``trace.rank<r>.json`` under
the shared telemetry dir, or ``rank<r>/trace.json``), and each records
its events under its own local ``pid`` — every rank believes it is
process 0, so dragging the files into Perfetto one by one can never
show the thing cross-rank traces exist for: barrier convoys, skewed
phases, and which rank's stall the others were waiting on ("Automatic
Cross-Replica Sharding", arxiv 2004.13336, motivates exactly this
per-phase overlap proof).

The merge rewrites every event's ``pid`` to the rank that produced it
(one Perfetto process track per rank, named and sorted), keeps ``tid``
(worker-side threads stay distinct within a track), and carries the
events through otherwise untouched — attempt tags
(``gang_worker_start`` instants, ``restart_attempt``/``gang_attempt``
spans) stay in ``args``, so one timeline spans every attempt of a
supervised chaos run.  Ranks are ORIGINAL-numbering identities: a
renumbered survivor keeps appending to its original stream, so its
track is continuous across shrinks.  Torn final events (a killed rank)
and unterminated arrays are tolerated by construction — the readers
drop exactly the record the crash destroyed.

Usage:  python tools/trace_merge.py <telemetry-dir> [-o OUT.json]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))
from distributed_machine_learning_tpu.telemetry.tracer import (  # noqa: E402,E501
    read_trace,
)

_TRACE_FILE_RE = re.compile(r"^trace\.rank(\d+)\.json$")
_RANK_DIR_RE = re.compile(r"^rank(\d+)$")


def discover_rank_traces(root: str) -> dict[int, str]:
    """rank -> trace path, over both layouts (rank-suffixed files win,
    mirroring ``telemetry/aggregator.py::discover_rank_streams``)."""
    out: dict[int, str] = {}
    if not os.path.isdir(root):
        return out
    names = sorted(os.listdir(root))
    for name in names:
        m = _TRACE_FILE_RE.match(name)
        if m:
            out.setdefault(int(m.group(1)), os.path.join(root, name))
    for name in names:
        m = _RANK_DIR_RE.match(name)
        if m:
            path = os.path.join(root, name, "trace.json")
            if os.path.isfile(path):
                out.setdefault(int(m.group(1)), path)
    return out


def merge_traces(root: str) -> tuple[dict, dict[int, int]]:
    """(merged trace object, rank -> event count).

    The result is the Chrome JSON Object Format (``{"traceEvents":
    [...]}``) — strictly-valid JSON whatever state the inputs were
    killed in, with one metadata-named process track per rank.
    """
    traces = discover_rank_traces(root)
    events: list[dict] = []
    counts: dict[int, int] = {}
    for rank, path in sorted(traces.items()):
        rank_events = [e for e in read_trace(path) if isinstance(e, dict)]
        for e in rank_events:
            e = dict(e)
            e["pid"] = rank  # every rank thinks it's pid 0: re-home it
            events.append(e)
        counts[rank] = len(rank_events)
        events.append({"name": "process_name", "ph": "M", "pid": rank,
                       "args": {"name": f"rank {rank}"}})
        events.append({"name": "process_sort_index", "ph": "M",
                       "pid": rank, "args": {"sort_index": rank}})
    # Chronological order is not required by the format but makes the
    # merged file diffable and stream-readable; metadata events carry
    # no ts and sort first.
    events.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0)))
    return {"traceEvents": events}, counts


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("telemetry_dir",
                        help="gang telemetry dir holding per-rank "
                             "traces (trace.rank<r>.json or "
                             "rank<r>/trace.json)")
    parser.add_argument("-o", "--out", default=None,
                        help="output path (default: "
                             "<telemetry-dir>/trace.merged.json)")
    args = parser.parse_args(argv)
    if not os.path.isdir(args.telemetry_dir):
        print(f"not a directory: {args.telemetry_dir}", file=sys.stderr)
        return 2
    merged, counts = merge_traces(args.telemetry_dir)
    if not counts:
        print(f"no per-rank traces under {args.telemetry_dir} "
              "(expected trace.rank<r>.json or rank<r>/trace.json)",
              file=sys.stderr)
        return 2
    out = args.out or os.path.join(args.telemetry_dir,
                                   "trace.merged.json")
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(merged, f)
    os.replace(tmp, out)
    spans = [e["ts"] for e in merged["traceEvents"] if "ts" in e]
    dur_s = (max(spans) - min(spans)) / 1e6 if spans else 0.0
    per_rank = "  ".join(f"rank{r}:{n}" for r, n in sorted(counts.items()))
    print(f"merged {sum(counts.values())} event(s) from "
          f"{len(counts)} rank(s) spanning {dur_s:.1f}s -> {out}")
    print(f"  {per_rank}")
    print("  open in ui.perfetto.dev (one process track per rank)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
