"""KV-cache generation: cached decode must agree exactly with the
teacher-forced dense forward (the strongest cache-correctness check),
plus sampling-mode invariants."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_machine_learning_tpu.inference.generate import (
    generate,
    make_generate_fn,
)
from distributed_machine_learning_tpu.models.transformer import TransformerLM
from distributed_machine_learning_tpu.train.lm_step import init_lm_state

VOCAB = 32


def _model_and_params(attn_impl="dense"):
    model = TransformerLM(
        vocab_size=VOCAB, d_model=16, n_layers=2, n_heads=2,
        attn_impl=attn_impl,
    )
    state = init_lm_state(model)
    return model, state.params


def test_greedy_matches_teacher_forced_argmax(rng):
    # Every generated token must equal the argmax of the full (uncached)
    # forward at the previous position — verifying the KV cache, the RoPE
    # offsets, and the position counter all line up.
    model, params = _model_and_params()
    prompt = jnp.asarray(rng.integers(0, VOCAB, (2, 5)), jnp.int32)
    out = generate(model, params, prompt, max_new_tokens=6)
    assert out.shape == (2, 11)
    np.testing.assert_array_equal(np.asarray(out[:, :5]), np.asarray(prompt))
    full_logits = model.apply({"params": params}, out, train=False)
    want = np.argmax(np.asarray(full_logits[:, 4:-1]), axis=-1)
    np.testing.assert_array_equal(np.asarray(out[:, 5:]), want)


def test_single_token_generation(rng):
    model, params = _model_and_params()
    prompt = jnp.asarray(rng.integers(0, VOCAB, (1, 3)), jnp.int32)
    out = generate(model, params, prompt, max_new_tokens=1)
    assert out.shape == (1, 4)
    logits = model.apply({"params": params}, prompt, train=False)
    assert int(out[0, 3]) == int(jnp.argmax(logits[0, -1]))


def test_params_from_ring_trained_model_drop_in(rng):
    # attn_impl is a runtime choice, not a parameter-structure choice:
    # generation clones to dense and must accept ring-model params as-is.
    model, params = _model_and_params(attn_impl="ring")
    prompt = jnp.asarray(rng.integers(0, VOCAB, (1, 4)), jnp.int32)
    out = generate(model, params, prompt, max_new_tokens=3)
    assert out.shape == (1, 7)


def test_sampling_deterministic_per_key_and_topk1_is_greedy(rng):
    model, params = _model_and_params()
    prompt = jnp.asarray(rng.integers(0, VOCAB, (2, 4)), jnp.int32)
    fn = make_generate_fn(model, 5, temperature=1.0)
    a = fn(params, prompt, jax.random.PRNGKey(7))
    b = fn(params, prompt, jax.random.PRNGKey(7))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = fn(params, prompt, jax.random.PRNGKey(8))
    assert not np.array_equal(np.asarray(a), np.asarray(c))  # overwhelmingly

    greedy = generate(model, params, prompt, max_new_tokens=5)
    top1 = generate(model, params, prompt, max_new_tokens=5,
                    temperature=1.0, top_k=1, rng=jax.random.PRNGKey(3))
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(top1))


def test_tokens_in_vocab_range(rng):
    model, params = _model_and_params()
    prompt = jnp.asarray(rng.integers(0, VOCAB, (3, 2)), jnp.int32)
    out = generate(model, params, prompt, max_new_tokens=8,
                   temperature=0.8, top_k=5, rng=jax.random.PRNGKey(1))
    arr = np.asarray(out)
    assert arr.min() >= 0 and arr.max() < VOCAB


def test_top_k_exceeding_vocab_is_a_clear_error(rng):
    import pytest

    model, params = _model_and_params()
    prompt = jnp.asarray(rng.integers(0, VOCAB, (1, 3)), jnp.int32)
    with pytest.raises(ValueError, match="top_k"):
        generate(model, params, prompt, max_new_tokens=2,
                 temperature=1.0, top_k=VOCAB + 1)


def test_tp_decode_token_exact_vs_single_device(rng):
    """Manual Megatron TP decode (VERDICT r03 item 5): the tp=4
    head-sharded generate produces the same greedy tokens as the
    single-device path, bf16-free f32 for exactness headroom."""
    from distributed_machine_learning_tpu.inference.generate import (
        make_tp_generate_fn,
    )
    from distributed_machine_learning_tpu.parallel.tensor_parallel import (
        tp_decode_params,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh

    model = TransformerLM(
        vocab_size=VOCAB, d_model=32, n_layers=2, n_heads=4, n_kv_heads=4,
    )
    params = init_lm_state(model).params
    prompt = jnp.asarray(rng.integers(0, VOCAB, (2, 5)), jnp.int32)
    ref = generate(model, params, prompt, max_new_tokens=8)

    mesh = make_mesh(4, axis_names=("model",))
    fn = make_tp_generate_fn(model, 8, mesh)
    out = fn(tp_decode_params(params, 4), prompt, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_tp_decode_fused_qkv_and_gqa_layouts(rng):
    """Both param layouts cross TP correctly: classic MHA (fused qkv
    kernel) and GQA (separate q / fused kv) at tp=2."""
    from distributed_machine_learning_tpu.inference.generate import (
        make_tp_generate_fn,
    )
    from distributed_machine_learning_tpu.parallel.tensor_parallel import (
        tp_decode_params,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh

    mesh = make_mesh(2, axis_names=("model",))
    prompt = jnp.asarray(rng.integers(0, VOCAB, (1, 4)), jnp.int32)
    for n_kv in (None, 2):  # None = fused qkv; 2 = GQA q+kv modules
        model = TransformerLM(
            vocab_size=VOCAB, d_model=32, n_layers=2, n_heads=4,
            n_kv_heads=n_kv,
        )
        params = init_lm_state(model).params
        ref = generate(model, params, prompt, max_new_tokens=6)
        fn = make_tp_generate_fn(model, 6, mesh)
        out = fn(tp_decode_params(params, 2), prompt, jax.random.PRNGKey(0))
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_tp_decode_guards(rng):
    from distributed_machine_learning_tpu.inference.generate import (
        make_tp_generate_fn,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh

    mesh = make_mesh(4, axis_names=("model",))
    import pytest

    with pytest.raises(ValueError, match="n_heads"):
        make_tp_generate_fn(
            TransformerLM(vocab_size=VOCAB, d_model=18, n_layers=1,
                          n_heads=6), 4, mesh,
        )
    with pytest.raises(ValueError, match="n_kv_heads"):
        make_tp_generate_fn(
            TransformerLM(vocab_size=VOCAB, d_model=32, n_layers=1,
                          n_heads=8, n_kv_heads=2), 4, mesh,
        )


def test_top_p_nucleus_sampling(rng):
    """Nucleus sampling invariants: top_p=1.0 keeps the full
    distribution; a tiny top_p degenerates to greedy (only the argmax
    survives the nucleus); sampled tokens always come from the kept
    set."""
    from distributed_machine_learning_tpu.inference.generate import _sample

    logits = jnp.asarray(rng.standard_normal((4, 32)) * 3, jnp.float32)
    key = jax.random.PRNGKey(0)
    # Tiny p: nucleus = {argmax} exactly.
    t = _sample(logits, key, temperature=1.0, top_k=None, top_p=1e-6)
    np.testing.assert_array_equal(
        np.asarray(t), np.argmax(np.asarray(logits), axis=-1)
    )
    # p=1.0 == unrestricted sampling (identical to top_p=None, same key).
    a = _sample(logits, key, temperature=1.0, top_k=None, top_p=1.0)
    b = _sample(logits, key, temperature=1.0, top_k=None, top_p=None)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Sampled tokens live inside the nucleus for moderate p.
    probs = np.asarray(jax.nn.softmax(logits, axis=-1))
    for trial in range(8):
        t = _sample(logits, jax.random.PRNGKey(trial), temperature=1.0,
                    top_k=None, top_p=0.5)
        for row, tok in enumerate(np.asarray(t)):
            order = np.argsort(-probs[row])
            cum = np.cumsum(probs[row][order])
            nucleus = set(order[: int(np.searchsorted(cum, 0.5)) + 1])
            assert int(tok) in nucleus
    # Guard.
    import pytest

    with pytest.raises(ValueError, match="top_p"):
        _sample(logits, key, temperature=1.0, top_k=None, top_p=1.5)


def test_top_p_through_generate(rng):
    """top_p threads through the jitted generate loop AND the TP shard
    map path — with the same rng and replicated sampling, the two must
    produce identical tokens."""
    from distributed_machine_learning_tpu.inference.generate import (
        make_generate_fn,
        make_tp_generate_fn,
    )
    from distributed_machine_learning_tpu.parallel.tensor_parallel import (
        tp_decode_params,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh

    model, params = _model_and_params()
    prompt = jnp.asarray(rng.integers(0, VOCAB, (2, 4)), jnp.int32)
    fn = make_generate_fn(model, 6, temperature=0.8, top_p=0.9)
    out = fn(params, prompt, jax.random.PRNGKey(1))
    assert out.shape == (2, 10)
    assert np.asarray(out).max() < VOCAB

    mesh = make_mesh(2, axis_names=("model",))
    tp_fn = make_tp_generate_fn(model, 6, mesh, temperature=0.8, top_p=0.9)
    tp_out = tp_fn(tp_decode_params(params, 2), prompt, jax.random.PRNGKey(1))
    np.testing.assert_array_equal(np.asarray(tp_out), np.asarray(out))
