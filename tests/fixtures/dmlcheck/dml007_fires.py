# dmlcheck-virtual-path: distributed_machine_learning_tpu/train/checkpoint.py
"""DML007 firing case: mutable default + wall clock in a manifest
builder (manifests are digest-compared across ranks)."""
import time


def gather_leaves(tree, out=[]):           # shared across calls
    out.append(tree)
    return out


def build_manifest(leaves):
    return {"leaves": leaves, "written_at": time.time()}  # nondeterministic
