# dmlcheck-virtual-path: distributed_machine_learning_tpu/train/loop.py
"""DML004 firing case: unguarded host syncs in the per-step loop."""
import jax


def train_epoch(train_step, state, batches):
    for images, labels in batches:
        state, loss = train_step(state, images, labels)
        step_now = int(jax.device_get(state.step))   # every step, no guard
        loss.block_until_ready()                     # ditto
        del step_now
    return state
