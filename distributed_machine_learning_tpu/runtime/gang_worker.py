"""One rank of a coordinated local gang — the end-to-end chaos harness.

Run as a subprocess by ``gang_supervise`` (``cli/gang.py`` launches it;
``tests/test_gang.py`` / ``tests/test_elastic.py`` assert on it): each
of N OS processes trains lock-step SGD steps with real verified
checkpoints (``train/checkpoint.py``) in a PER-RANK checkpoint
directory (``<ckpt-root>/rank<orig>`` — the per-host-shards layout of a
pod run, which is what makes the restore-point election load-bearing:
validity is each rank's own view), and wires the gang coordinator
(``runtime/coordinator.py``) around the loop: heartbeats per step,
suspensions around compile/saves, a restore-point record after every
verified save.

Lock-step is enforced by ``GangCoordinator.wait_for_peers`` — a barrier
over the beat directory — rather than a cross-process XLA collective:
the CI host's CPU backend cannot run multi-process XLA computations
(the same env drift that fails ``tests/test_multihost.py`` here), and
the barrier reproduces the exact failure semantics this subsystem
exists for: when a peer dies or stalls, the survivors BLOCK, and only
the peer-failure detector's coordinated abort frees them.  On real TPU
pods the blocking collective is the psum itself and the identical
coordinator sits around it (``cli/common.py``'s ``--gang-dir`` path).

Elastic semantics (ISSUE 5): the worker is WORLD-SIZE-AWARE.  Each
step's GLOBAL batch is a fixed ``--global-batch`` examples keyed on the
absolute step index alone, and a rank consumes only its shard of it —
``data/sharding.py::exact_shard_indices(B, rank, world)`` — logging the
consumed example ids to ``consumed_rank<orig>.jsonl`` in the gang dir.
When the supervisor shrinks the gang from N to M survivors, relaunched
workers re-evaluate their shards at world M: the per-host batch grows
from B/N to B/M (the global batch — and therefore the effective LR
schedule — is preserved), and every example is still consumed exactly
once per step.  The gradient each rank applies is the mean over the
global batch in canonical order — the value the psum over ANY
world-size partition of it produces — so params stay bit-identical
across ranks, across restarts, and across world sizes (the loss-curve
continuity the chaos test asserts).  Checkpoints are saved with a dp
``ShardSpec`` recording the world size and restored through
``reshard_restore``, which tolerates (and counts) a world-size change.

Observability (ISSUE 6): per-rank telemetry is ON by default — each
rank streams attempt-tagged step rows, phase spans
(``barrier_wait``/``compute``) and trace instants into the shared
``<gang-dir>/telemetry`` under collision-safe rank-suffixed filenames
(``metrics.rank<orig>.jsonl``, ...), and publishes a rolling
step-time snapshot on every heartbeat via
``GangCoordinator.observe_step`` — the inputs to
``telemetry/aggregator.py``'s cross-rank rollups, the supervisor's
straggler detector, and the ``gang_status``/``trace_merge`` tools.
Disable with ``--no-telemetry``.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import signal
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _global_batch_for_step(step: int, batch: int) -> "object":
    """The global batch for an absolute step index — deterministic in
    ``step`` alone, so every rank, every restart attempt, and every
    world size agrees on it.  Row ``j`` is global example id
    ``step * batch + j``."""
    import numpy as np

    rng = np.random.default_rng(10_000 + step)
    return rng.standard_normal((batch, 8)).astype(np.float32)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--world", type=int, required=True)
    ap.add_argument("--orig-rank", type=int, default=None,
                    help="rank identity in the ORIGINAL (pre-shrink) "
                         "numbering; owns the checkpoint dir and the "
                         "consumed-example ledger (default: --rank)")
    ap.add_argument("--attempt", type=int, default=0,
                    help="supervisor attempt number (tags consumption "
                         "records so post-mortems can tell replays apart)")
    ap.add_argument("--gang-dir", required=True)
    ap.add_argument("--ckpt-dir", required=True,
                    help="checkpoint ROOT; this rank writes under "
                         "<ckpt-dir>/rank<orig> (per-host shard layout)")
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--save-every", type=int, default=5)
    ap.add_argument("--global-batch", type=int, default=24,
                    help="examples per GLOBAL step batch; each rank "
                         "consumes its exact shard (B/world), so a "
                         "shrink rescales the per-host batch while the "
                         "global batch — and the LR schedule — is "
                         "preserved")
    ap.add_argument("--faults", default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--heartbeat-interval", type=float, default=0.25)
    ap.add_argument("--peer-timeout", type=float, default=15.0)
    ap.add_argument("--step-sleep", type=float, default=0.02)
    ap.add_argument("--telemetry-dir", default=None,
                    help="per-rank telemetry home (default: "
                         "<gang-dir>/telemetry — the gang plane "
                         "telemetry/aggregator.py reads)")
    ap.add_argument("--telemetry-instance", default=None,
                    help="artifact filename tag (default rank<orig>): "
                         "N ranks sharing one telemetry dir write "
                         "metrics.rank<r>.jsonl etc. so appends never "
                         "interleave")
    ap.add_argument("--no-telemetry", action="store_true",
                    help="disable the default-on per-rank telemetry")
    args = ap.parse_args(argv)
    orig_rank = args.rank if args.orig_rank is None else args.orig_rank

    # A drain/preemption SIGTERM becomes a SystemExit raised at the next
    # bytecode: the exception path below flushes telemetry before dying,
    # so the terminated attempt's rows and spans survive for the
    # post-mortem instead of dying in the sink buffer.
    def _on_term(sig, frame):
        raise SystemExit(128 + sig)

    signal.signal(signal.SIGTERM, _on_term)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_machine_learning_tpu.data.sharding import (
        exact_shard_indices,
    )
    from distributed_machine_learning_tpu.runtime.coordinator import (
        GangCoordinator,
    )
    from distributed_machine_learning_tpu.runtime.faults import (
        FaultEvents,
        FaultInjector,
    )
    from distributed_machine_learning_tpu.runtime.mesh import ShardSpec
    from distributed_machine_learning_tpu.train.checkpoint import (
        checkpoint_chain_report,
        checkpoint_cursor,
        latest_checkpoint,
        reshard_restore,
        save_checkpoint,
    )
    from distributed_machine_learning_tpu.train.state import TrainState
    from distributed_machine_learning_tpu.utils.summary import (
        resilience_summary,
    )

    # Telemetry is ON by default (ISSUE 6): every rank streams into the
    # shared <gang-dir>/telemetry with a rank-suffixed instance tag, so
    # the per-rank artifacts land collision-free in ONE directory the
    # aggregator / gang_status / trace_merge tools read as a gang plane.
    telemetry = None
    if not args.no_telemetry:
        from distributed_machine_learning_tpu.telemetry import (
            Telemetry,
            set_telemetry,
        )

        tel_dir = args.telemetry_dir or os.path.join(args.gang_dir,
                                                     "telemetry")
        instance = (args.telemetry_instance
                    if args.telemetry_instance is not None
                    else f"rank{orig_rank}")
        telemetry = Telemetry(tel_dir, instance=instance or None)
        set_telemetry(telemetry)
        # Attempt tags must match the supervisor's numbering so the
        # merged timeline lines up across ranks (set_attempt never
        # moves backwards — a resumed stream keeps its disk offset).
        telemetry.set_attempt(args.attempt)
        telemetry.tracer.instant(
            "gang_worker_start", rank=args.rank, orig_rank=orig_rank,
            world=args.world, attempt=args.attempt,
        )

    ckpt_dir = os.path.join(args.ckpt_dir, f"rank{orig_rank}")
    events = FaultEvents()
    # Fault targeting is keyed on the ORIGINAL rank identity: a spec
    # written against the launch-time numbering must keep aiming at the
    # same host after a shrink renumbers the survivors — and the ledger
    # then records stable ids the supervisor can read without mapping.
    injector = FaultInjector.from_flags(
        args.faults, seed=args.seed, horizon=max(args.steps, 2),
        rank=orig_rank,
    )
    if injector is not None:
        from distributed_machine_learning_tpu.runtime.faults import (
            FAULT_LEDGER_FILE,
        )

        os.makedirs(args.gang_dir, exist_ok=True)
        # The exactly-once latch must survive the relaunch this very
        # fault will cause — without the ledger every attempt re-fires
        # the same kill and the gang can never finish.
        injector.attach_ledger(
            os.path.join(args.gang_dir, FAULT_LEDGER_FILE)
        )
    coord = GangCoordinator(
        args.gang_dir, rank=args.rank, world=args.world,
        heartbeat_interval_s=args.heartbeat_interval,
        peer_timeout_s=args.peer_timeout, events=events,
    ).start()

    # This rank's share of every step's global batch under the CURRENT
    # world size — the shard a shrink rebalances.  exact partition: the
    # union over ranks is every example exactly once, padding-free.
    from distributed_machine_learning_tpu.runtime.coordinator import (
        CONSUMED_PREFIX,
    )

    local_ids = exact_shard_indices(args.global_batch, args.rank,
                                    args.world)
    consumed_path = os.path.join(
        args.gang_dir, f"{CONSUMED_PREFIX}{orig_rank}.jsonl"
    )

    def record_consumed(step: int) -> None:
        """One line per completed step: which global example ids THIS
        rank consumed, under which (attempt, world) — the exactly-once
        audit trail the elastic chaos test checks."""
        # flush+fsync (dmlcheck DML002): the coordinator's monitor
        # thread may os._exit this process at any poll, and a consumed
        # row lost from the ledger reads as a missed example in the
        # exactly-once audit.
        with open(consumed_path, "a") as f:
            f.write(json.dumps({
                "attempt": args.attempt, "world": args.world,
                "rank": args.rank, "orig_rank": orig_rank, "step": step,
                "ids": [int(step) * args.global_batch + int(j)
                        for j in local_ids],
            }) + "\n")
            f.flush()
            os.fsync(f.fileno())

    with coord.suspend():
        state = TrainState.create(
            params={"w": jnp.zeros((8,), jnp.float32)}
        )
        start = 0
        latest = latest_checkpoint(ckpt_dir, events=events)
        if latest is not None:
            # reshard_restore tolerates a checkpoint saved under a
            # DIFFERENT world size (the shrink case) — dp params carry
            # no padding, so this is a verified plain restore plus a
            # reshard_restores count when the worlds differ.
            state, _spec = reshard_restore(latest, world=args.world,
                                           events=events,
                                           files_verified=True)
            restored_step = int(jax.device_get(state.step))
            cursor = checkpoint_cursor(latest)
            start = cursor if cursor is not None else restored_step
            # The restore is this rank's proof the checkpoint is whole —
            # record it so the next election can agree on it even if no
            # further save ever lands.
            coord.record_valid_step(restored_step)
            print(f"resumed {latest} step {restored_step}", flush=True)
        else:
            report = checkpoint_chain_report(ckpt_dir)
            if report:
                # Candidates exist but none is restorable: say WHY per
                # candidate (the satellite fix for the bare "no
                # checkpoint found") before training from scratch —
                # the supervisor log is the post-mortem surface.
                print(f"no restorable checkpoint under {ckpt_dir}:",
                      flush=True)
                for p, verdict in report:
                    print(f"  {p}: {verdict}", flush=True)

        @jax.jit
        def step_fn(state, xs):
            # The mean gradient over the GLOBAL batch in canonical
            # order — the value a psum over the per-rank shards would
            # produce under ANY world size, so replicated params stay
            # bit-identical across ranks, restarts, and shrinks
            # (asserted by digest below).
            g = xs.mean(0)
            w = state.params["w"] - 0.1 * (g + 0.01 * state.params["w"])
            return state.replace(params={"w": w}, step=state.step + 1)

        # AOT-compile inside the suspension: the first step's compile
        # must not read as a stall under short chaos-test timeouts.
        compiled = step_fn.lower(
            state, _global_batch_for_step(start, args.global_batch)
        ).compile()
        # Publish the resumed position BEFORE the first barrier: peers
        # wait for our published step, and a gang resuming at step k
        # would otherwise deadlock at barrier k with everyone still
        # publishing step 0.
        coord.beat(step=start)

    print(f"ready rank={args.rank} orig={orig_rank} world={args.world} "
          f"start={start}", flush=True)
    post_save = injector.post_save_hook(events) if injector else None
    batches = range(start, args.steps)
    if injector is not None:
        batches = injector.wrap_batches(batches, events, start=start)

    try:
        for idx in batches:
            t_start = time.perf_counter()
            # The lock-step barrier: the stand-in for the synchronous
            # collective — blocks until every peer has published step
            # idx (a dead peer blocks us here until the detector aborts
            # the gang, exactly like a hung psum).
            if not coord.wait_for_peers(idx):
                break  # test mode only; production aborts the process
            t_barrier = time.perf_counter()
            state = compiled(
                state, _global_batch_for_step(idx, args.global_batch)
            )
            jax.block_until_ready(state.params["w"])
            t_end = time.perf_counter()
            record_consumed(idx)
            iter_s = t_end - t_start
            phases = {"barrier_wait_s": t_barrier - t_start,
                      "compute_s": t_end - t_barrier}
            # One call publishes progress AND the heartbeat metric
            # snapshot (rolling step time + phase breakdown) the
            # supervisor's straggler detector compares across ranks.
            coord.observe_step(idx + 1, iter_s, phases)
            if telemetry is not None:
                telemetry.tracer.complete("barrier_wait", t_start,
                                          t_barrier, step=idx)
                telemetry.tracer.complete("compute", t_barrier, t_end,
                                          step=idx)
                reg = telemetry.registry
                reg.counter("steps_total").inc()
                reg.histogram("step_seconds").observe(iter_s)
                eps = len(local_ids) / iter_s if iter_s > 0 else 0.0
                reg.gauge("examples_per_s").set(eps)
                telemetry.log_step(idx, iter_s=iter_s, **phases,
                                   examples_per_s=eps, rank=args.rank,
                                   orig_rank=orig_rank, world=args.world)
            if args.rank == 0:
                print(f"step {idx}", flush=True)
            if (idx + 1) % args.save_every == 0 or idx + 1 == args.steps:
                # Saves are liveness, not progress: suspend the stall
                # clock exactly as the watchdog path does.
                with coord.suspend():
                    save_checkpoint(
                        ckpt_dir, state, cursor=idx + 1,
                        post_save_hook=post_save,
                        shard_spec=ShardSpec("dp", world=args.world),
                    )
                coord.record_valid_step(int(jax.device_get(state.step)))
            if args.step_sleep:
                time.sleep(args.step_sleep)
    except SystemExit:
        # Drained/preempted (the SIGTERM handler above): flush the
        # attempt's telemetry so its rows and spans reach disk, but
        # never finish() — a terminated rank is not a finished rank.
        if telemetry is not None:
            telemetry.flush()
        raise

    digest = hashlib.sha256(
        np.ascontiguousarray(np.asarray(state.params["w"])).tobytes()
    ).hexdigest()[:16]
    print(f"final_step {int(jax.device_get(state.step))}", flush=True)
    print(f"final_world {args.world}", flush=True)
    print(f"final {digest}", flush=True)
    if events.total():
        print(resilience_summary(events), flush=True)
    coord.finish()
    if telemetry is not None:
        telemetry.tracer.instant(
            "gang_worker_finish", rank=args.rank, orig_rank=orig_rank,
            world=args.world, attempt=args.attempt,
            step=int(jax.device_get(state.step)),
        )
        telemetry.close()


if __name__ == "__main__":
    main()
