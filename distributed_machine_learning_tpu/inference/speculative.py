"""Speculative decoding — draft-and-verify autoregressive generation.

Decode is bound by HBM reads of the target model's weights per token
(docs/PERF.md); speculative decoding (Leviathan et al.) buys tokens per
weight-read: a cheap DRAFT model proposes ``gamma`` tokens
autoregressively, the TARGET verifies all of them in ONE forward pass
(γ+1 positions against its cache — compute-parallel, the same weight
bytes as a single decode step), and a rejection rule keeps the output
distribution EXACTLY the target's:

- greedy (``temperature=0``): accept the longest prefix where the
  draft's token equals the target argmax, then emit the target argmax
  at the first mismatch (or the bonus token when all γ survive) — the
  output is bitwise the target-only greedy stream under matched
  numerics (f32 compute, as the tests pin it).  bf16-serving caveat,
  measured not hypothesized: where the top-2 logits tie within one
  bf16 ulp, DIFFERENTLY-SHAPED programs break the tie differently —
  the Lq=γ+1 verify pass vs the Lq=1 decode step, but equally the
  Lq=1 decode step vs the teacher-forced full forward (at the first
  observed flip on a trained bf16 model, the teacher-forced argmax
  matched NEITHER stream; top-2 gap exactly one bf16 ulp).  Ties are
  equal-probability choices, so the served distribution is unchanged;
  this is a property of shape-dependent XLA numerics, not of
  speculation;
- sampled: accept ``d_i`` with probability ``min(1, p_i(d_i)/q_i(d_i))``
  (p = target, q = draft, both WARPED — temperature/top-k/top-p — so
  the preserved distribution is the one the plain sampler uses); on
  rejection sample from ``norm(max(p_i − q_i, 0))``; on full acceptance
  sample the bonus from ``p_γ``.

TPU-shaped implementation notes:

- **Cache rollback is free.**  The KV caches index slots by absolute
  position with a single ``idx`` frontier counter; slots past the
  frontier are causally masked (``slot <= pos``) and overwritten by the
  next write.  Rejecting draft tokens is therefore just rewinding the
  counter in the carried cache pytree — no K/V copy, no re-prefill.
- The draft phase runs γ+1 steps (it processes its own last proposal),
  keeping its cache exactly one token behind the committed stream at
  every round — the invariant that makes the loop shape-static.
- One ``lax.while_loop`` emits a variable 1..γ+1 tokens per round into
  a fixed output buffer at a moving pointer; every slot below the final
  pointer is committed before it can be read.
- Batch 1 only: acceptance length is data-dependent PER ROW, and the
  cache frontier is one scalar — the standard latency-serving shape.

The reference has no inference path at all (SURVEY.md §2); this extends
the serving surface of ``inference/generate.py``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from distributed_machine_learning_tpu.inference.generate import warp_logits


def make_speculative_generate_fn(
    target_model,
    draft_model,
    max_new_tokens: int,
    gamma: int = 4,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    quantize: str | None = None,
    draft_quantize: str | None = None,
):
    """Build ``fn(target_params, draft_params, prompt, rng) -> tokens``.

    ``prompt``: [1, Lp] int32 (batch 1 — see module docstring); returns
    [1, Lp + max_new_tokens].  ``gamma``: draft tokens per verify round.
    ``quantize``/``draft_quantize``: "int8" serves that model through
    the weight-only kernel (``ops/quant.py``) — pass params converted by
    ``quantize_lm_params``.

    Correctness contract: the emitted stream follows the TARGET's
    sampling distribution exactly (greedy: bitwise-identical to
    ``make_generate_fn`` with the same flags — tested); the draft only
    changes HOW FAST tokens appear, never WHICH distribution they come
    from.
    """
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    if target_model.vocab_size != draft_model.vocab_size:
        raise ValueError(
            f"target and draft must share a vocabulary (got "
            f"{target_model.vocab_size} vs {draft_model.vocab_size})"
        )
    for q in (quantize, draft_quantize):
        if q not in (None, "int8"):
            raise ValueError(f"quantize must be None or 'int8', got {q!r}")
    tm = target_model.clone(attn_impl="dense", decode=True,
                            weight_quant=quantize)
    dm = draft_model.clone(attn_impl="dense", decode=True,
                           weight_quant=draft_quantize)
    # The verify pass applies γ+1 tokens MID-STREAM: it must attend the
    # full cache, not take the start-0 prefill fast path — the
    # continuation clone routes multi-token decode through
    # _cached_attention (same params, same cache layout).
    tm_verify = tm.clone(decode_continuation=True)
    greedy = temperature == 0.0
    V = target_model.vocab_size

    def warp(logits):
        return warp_logits(logits, temperature, top_k, top_p)

    @jax.jit
    def run(tparams, dparams, prompt, rng):
        B, Lp = prompt.shape
        if B != 1:
            raise ValueError(
                f"speculative decoding is batch-1 (got B={B}): acceptance "
                "length is data-dependent per row but the KV-cache "
                "frontier is one scalar"
            )
        budget = max_new_tokens + gamma + 1  # output buffer slack
        cache_len = -(-(Lp + budget + 1) // 512) * 512

        def init_cache(model):
            shapes = jax.eval_shape(
                lambda: model.init(
                    jax.random.PRNGKey(0),
                    jnp.zeros((B, cache_len), jnp.int32),
                    train=False,
                )
            )["cache"]
            return jax.tree_util.tree_map(
                lambda s: jnp.zeros(s.shape, s.dtype), shapes
            )

        tcache, dcache = init_cache(tm), init_cache(dm)

        # Prefill both models on the prompt; the target's last logits
        # sample the first committed token.
        tlogits, tvars = tm.apply(
            {"params": tparams, "cache": tcache}, prompt, train=False,
            mutable=["cache"],
        )
        _, dvars = dm.apply(
            {"params": dparams, "cache": dcache}, prompt, train=False,
            mutable=["cache"],
        )
        tcache, dcache = tvars["cache"], dvars["cache"]
        rng, r0 = jax.random.split(rng)
        if greedy:
            cur = jnp.argmax(tlogits[:, -1], axis=-1).astype(jnp.int32)
        else:
            cur = jax.random.categorical(
                r0, warp(tlogits[:, -1]), axis=-1
            ).astype(jnp.int32)

        out = jnp.zeros((B, budget), jnp.int32)
        out = lax.dynamic_update_slice(out, cur[:, None], (0, 0))
        # ptr: tokens EMITTED so far (cur at slot 0 counts).
        state = (tcache, dcache, cur, out, jnp.asarray(1, jnp.int32), rng)

        def round_body(state):
            tcache, dcache, cur, out, ptr, rng = state

            # ---- draft phase: γ+1 steps (the last processes its own
            # final proposal, keeping the draft cache one token behind
            # the committed stream after any acceptance count).
            def dstep(carry, r):
                dcache, tok = carry
                logits, vars_ = dm.apply(
                    {"params": dparams, "cache": dcache}, tok[:, None],
                    train=False, mutable=["cache"],
                )
                lg = logits[:, -1]
                if greedy:
                    nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                    q = jnp.zeros((B, V), jnp.float32)  # unused
                else:
                    w = warp(lg)  # one warp per step: probs AND sample
                    q = jax.nn.softmax(w, axis=-1)
                    nxt = jax.random.categorical(r, w, axis=-1).astype(
                        jnp.int32
                    )
                return (vars_["cache"], nxt), (nxt, q)

            rng, *draft_keys = jax.random.split(rng, gamma + 2)
            (dcache2, _), (draft_toks, draft_q) = lax.scan(
                dstep, (dcache, cur), jnp.stack(draft_keys)
            )
            # draft_toks: [γ+1, B]; proposals are the first γ.
            d = draft_toks[:gamma, 0]  # [γ] int32 (B=1)
            q = draft_q[:gamma, 0]  # [γ, V]

            # ---- verify: one target pass over [cur, d_0..d_{γ-1}].
            verify_in = jnp.concatenate([cur, d], axis=0)[None]  # [1, γ+1]
            vlogits, tvars = tm_verify.apply(
                {"params": tparams, "cache": tcache}, verify_in,
                train=False, mutable=["cache"],
            )
            vlogits = vlogits[0]  # [γ+1, V]; row i predicts slot of d_i

            rng, r_acc, r_fix = jax.random.split(rng, 3)
            if greedy:
                tbest = jnp.argmax(vlogits, axis=-1).astype(jnp.int32)
                acc = d == tbest[:gamma]  # [γ]
                # n_acc = length of the all-accepted prefix.
                n_acc = jnp.sum(jnp.cumprod(acc.astype(jnp.int32)))
                # Correction/bonus token: target argmax at position n_acc.
                t_new = tbest[n_acc][None]
            else:
                p = jax.nn.softmax(warp(vlogits), axis=-1)  # [γ+1, V]
                p_d = jnp.take_along_axis(
                    p[:gamma], d[:, None], axis=1
                )[:, 0]
                q_d = jnp.take_along_axis(q, d[:, None], axis=1)[:, 0]
                u = jax.random.uniform(r_acc, (gamma,))
                acc = u * q_d < p_d  # accept iff u < p/q (q>0 where sampled)
                n_acc = jnp.sum(jnp.cumprod(acc.astype(jnp.int32)))
                # Residual at the first rejection; bonus row at γ.
                p_row = p[n_acc]
                q_row = jnp.where(
                    n_acc < gamma,
                    q[jnp.minimum(n_acc, gamma - 1)],
                    jnp.zeros((V,), jnp.float32),
                )
                resid = jnp.maximum(p_row - q_row, 0.0)
                resid = resid / jnp.maximum(resid.sum(), 1e-30)
                t_new = jax.random.categorical(
                    r_fix, jnp.log(jnp.maximum(resid, 1e-30))
                )[None].astype(jnp.int32)

            # ---- commit: window = [d_0..d_{n_acc-1}, t_new, junk...];
            # the junk beyond n_acc is overwritten by the next round's
            # window (or never read past the final pointer).
            window = jnp.where(
                jnp.arange(gamma + 1) == n_acc,
                t_new[0],
                jnp.concatenate([d, jnp.zeros((1,), jnp.int32)]),
            )
            out = lax.dynamic_update_slice(out, window[None], (0, ptr))

            # ---- cache rewinds (the free rollback): target holds the
            # committed stream MINUS t_new; draft holds one token less.
            tcache = dict(tvars["cache"])
            tcache["idx"] = tcache["idx"] - (gamma + 1) + (n_acc + 1)
            dcache2 = dict(dcache2)
            dcache2["idx"] = dcache2["idx"] - (gamma + 1) + (n_acc + 1)
            return (tcache, dcache2, t_new, out, ptr + n_acc + 1, rng)

        def cond(state):
            return state[4] < max_new_tokens

        _, _, _, out, _, _ = lax.while_loop(cond, round_body, state)
        return jnp.concatenate([prompt, out[:, :max_new_tokens]], axis=1)

    return run
