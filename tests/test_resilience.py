"""Failure detection + preemption (runtime/resilience.py): the watchdog
must catch a stalled step, the preemption handler must turn SIGTERM into
a clean stop-at-step-boundary, and the training loop must honor both."""

import os
import signal
import time

import numpy as np
import pytest

from distributed_machine_learning_tpu.runtime.resilience import (
    PreemptionHandler,
    Watchdog,
)


def test_watchdog_fires_on_stall():
    fired = []
    with Watchdog(timeout_s=0.2, on_stall=fired.append, poll_s=0.05) as wd:
        time.sleep(0.6)
    assert wd.stalled
    assert fired and fired[0] >= 0.2


def test_watchdog_beats_prevent_stall():
    fired = []
    with Watchdog(timeout_s=0.4, on_stall=fired.append, poll_s=0.05) as wd:
        for _ in range(6):
            time.sleep(0.1)
            wd.beat()
    assert not wd.stalled
    assert not fired


def test_watchdog_rejects_bad_timeout():
    with pytest.raises(ValueError):
        Watchdog(timeout_s=0)


def test_preemption_handler_catches_sigterm():
    with PreemptionHandler() as handler:
        assert not handler()
        os.kill(os.getpid(), signal.SIGTERM)
        # Signal delivery is synchronous-enough on the main thread: the
        # handler runs before the next bytecode boundary completes.
        time.sleep(0.05)
        assert handler()
    # Outside the context, the previous disposition is restored.
    assert signal.getsignal(signal.SIGTERM) not in (handler._handle,)


def test_preemption_restores_previous_handler():
    prev = signal.getsignal(signal.SIGTERM)
    h = PreemptionHandler().install()
    h.uninstall()
    assert signal.getsignal(signal.SIGTERM) is prev


def test_train_epoch_stops_at_boundary_and_beats_watchdog(rng):
    # A tiny real train loop: stop requested after the 3rd step must end
    # the epoch with exactly 3 updates applied and consistent state.
    from distributed_machine_learning_tpu.cli.common import init_model_and_state
    from distributed_machine_learning_tpu.models.vgg import VGGTest
    from distributed_machine_learning_tpu.train.loop import train_epoch
    from distributed_machine_learning_tpu.train.step import make_train_step

    model = VGGTest(use_bn=False)
    state = init_model_and_state(model)
    step = make_train_step(model, augment=False)

    def batches():
        while True:
            yield (rng.integers(0, 256, (2, 32, 32, 3)).astype(np.uint8),
                   rng.integers(0, 10, 2).astype(np.int32))

    calls = {"n": 0}

    def stop():
        return calls["n"] >= 3

    real_step = step

    def counting_step(s, x, y):
        calls["n"] += 1
        return real_step(s, x, y)

    wd = Watchdog(timeout_s=60).start()
    state, _ = train_epoch(
        counting_step, state, batches(), max_iters=10, stop=stop,
        watchdog=wd,
    )
    wd.stop()
    assert calls["n"] == 3
    assert int(state.step) == 3
    assert not wd.stalled


def test_agree_stop_single_process():
    from distributed_machine_learning_tpu.runtime.resilience import agree_stop

    assert agree_stop(True) is True
    assert agree_stop(False) is False


def test_periodic_agree_stop_single_process_is_immediate():
    from distributed_machine_learning_tpu.runtime.resilience import (
        periodic_agree_stop,
    )

    flag = {"v": False}
    stop = periodic_agree_stop(lambda: flag["v"], every=10)
    assert not stop()
    flag["v"] = True
    # Single-process forces every=1: honored on the very next poll,
    # and sticky afterwards.
    assert stop()
    flag["v"] = False
    assert stop()


def test_periodic_agree_stop_validates_every():
    import pytest

    from distributed_machine_learning_tpu.runtime.resilience import (
        periodic_agree_stop,
    )

    with pytest.raises(ValueError):
        periodic_agree_stop(lambda: False, every=0)
