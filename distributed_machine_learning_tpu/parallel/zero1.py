"""ZeRO-1: optimizer-state sharding under replicated parameters.

The middle point of the ZeRO family this framework offers (SURVEY.md
§2.3 records all of it as absent in the reference):

- replicated DP (``parallel/strategies.py``) — params + momentum on
  every device;
- **ZeRO-1 (this module)** — params replicated, momentum sharded 1/N;
- ZeRO-3/FSDP (``parallel/fsdp.py``) — params *and* momentum sharded.

The step:

  1. forward/backward on the replicated params (local gradients);
  2. ``lax.psum_scatter`` the flattened gradient — each device receives
     only the mean-reduced slice it owns (half the ring);
  3. SGD/momentum update on that slice against its momentum shard;
  4. ``lax.all_gather`` the updated parameter slices back to the full
     replicated vector (the other half of the ring).

Per-step traffic is exactly one all-reduce's worth (reduce-scatter +
all-gather), the same bytes replicated DP pays — ZeRO-1 costs no extra
bandwidth and saves (N−1)/N of the momentum memory, the reason it is
the default first rung of optimizer sharding.  Flat-vector layout and
padding follow ``parallel/fsdp.py``.

Step (4) has two builds (see :func:`make_zero1_train_step`): the sync
baseline keeps the gather inside the program (on the critical path,
feeding ROOT — the arxiv 2004.13336 anti-pattern, dmlcheck DML102),
and ``overlap=True`` moves it to a separately-dispatched bucketed
ppermute ring (``parallel/overlap.py``) that runs behind the next
step's data wait — bit-identical trajectory, gather off the critical
path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_machine_learning_tpu.data.augment import augment_batch, normalize
from distributed_machine_learning_tpu.parallel.fsdp import (
    _padded_len,
    flat_mean_grad_shard,
    flatten_padded,
    fsdp_memory_footprint,
)
from distributed_machine_learning_tpu.runtime.mesh import (
    BATCH_AXIS,
    shard_map_no_check as _shard_map,
)
from distributed_machine_learning_tpu.train.common import step_rng
from distributed_machine_learning_tpu.train.lars import LARSConfig
from distributed_machine_learning_tpu.train.optimizers import update_fn_for_config
from distributed_machine_learning_tpu.train.sgd import SGDConfig
from distributed_machine_learning_tpu.train.state import TrainState


@struct.dataclass
class Zero1State:
    """Replicated flat params + 1/N momentum shards per device."""

    param_flat: jax.Array  # [padded_len], replicated
    # [padded_len] global, sharded over the batch axis; a {"mu","nu"}
    # dict of such vectors for AdamW.
    momentum_shards: jax.Array | dict
    batch_stats: dict
    step: jax.Array
    rng: jax.Array
    config: SGDConfig = struct.field(pytree_node=False)


def shard_zero1_state(state: TrainState, mesh: Mesh, axis_name: str = BATCH_AXIS):
    """Flatten a replicated TrainState into the ZeRO-1 layout.

    Returns ``(zero1_state, unravel, n_elems)`` — ``unravel`` maps the
    unpadded flat vector back to the params pytree.
    """
    if isinstance(state.config, LARSConfig):
        # Elementwise updates (SGD, AdamW) are exact on any slice of the
        # flat vector; LARS's per-leaf norms are not.
        raise ValueError(
            "ZeRO-1 cannot shard LARS (per-layer norms are not "
            "sliceable); use sgd or adamw"
        )
    flat, mom_flat, unravel, n_elems = flatten_padded(
        state, mesh.shape[axis_name]
    )
    z1 = Zero1State(
        param_flat=jax.device_put(flat, NamedSharding(mesh, P())),
        momentum_shards=jax.tree_util.tree_map(
            lambda a: jax.device_put(a, NamedSharding(mesh, P(axis_name))),
            mom_flat,
        ),
        batch_stats=jax.device_put(
            state.batch_stats, NamedSharding(mesh, P())
        ),
        step=jax.device_put(state.step, NamedSharding(mesh, P())),
        rng=jax.device_put(state.rng, NamedSharding(mesh, P())),
        config=state.config,
    )
    return z1, unravel, n_elems


def zero1_params(state: Zero1State, unravel, n_elems: int):
    """The params pytree (for eval/checkpoint) — params are replicated,
    so this is just an unravel, no collective."""
    return unravel(jnp.asarray(state.param_flat)[:n_elems])


def make_zero1_train_step(
    model,
    mesh: Mesh,
    unravel,
    n_elems: int,
    axis_name: str = BATCH_AXIS,
    augment: bool = True,
    overlap: bool = False,
):
    """Build the jitted ZeRO-1 train step (MEAN gradient semantics).

    Returns ``step(zero1_state, images_u8, labels) -> (state, loss)``
    with the batch sharded along the data axis.

    ``overlap=False`` (the sync baseline): one program whose final op
    is the parameter all-gather — the gather feeds ROOT and nothing can
    be scheduled under it, exactly the critical-path anti-pattern
    "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
    Training" (arxiv 2004.13336) eliminates (dmlcheck DML102 flags this
    build as an error).

    ``overlap=True`` (the 2004.13336 recipe): the step is split into an
    **update phase** — forward/backward, gradient reduce-scatter, and
    the shard-local optimizer step, whose program ends at the updated
    SHARD (no gather anywhere; the host's loss block returns as soon as
    the update lands) — and a **consume phase**: the gather of the
    updated shards is dispatched as a separate, immediately-issued
    program (a chunked :func:`~distributed_machine_learning_tpu.ops.ring.ring_all_gather_flat`
    ppermute chain, each hop an async window the scheduler fills with
    the per-chunk assembly), so it executes behind the host's
    ``data_wait`` for the next batch and is consumed by the next step's
    forward.  Dispatch is async, so the returned state's ``param_flat``
    is simply the in-flight gather result — checkpoint/eval callers
    block on it transparently and see the identical replicated vector.
    The two builds are BIT-IDENTICAL in trajectory (the gather is pure
    data movement; the update math is shared) — tested.

    When telemetry is installed the wrapper records a ``param_gather``
    span from gather dispatch to observed readiness (closed at the next
    step's consume), and exposes ``step.pop_gather_seconds()`` so the
    train loop can add a ``param_gather_s`` column — the span that
    should overlap ``data_wait`` on the trace timeline while
    ``device_block`` shrinks.  ``step.update_for(cfg)`` /
    ``step.gather_inner`` expose the two jitted programs for AOT
    lowering and the HLO overlap audit (``bench/overlap_audit.py``).
    """
    n = mesh.shape[axis_name]

    def sharded_for(cfg: SGDConfig, gather: bool):
        def impl(param_flat, momentum_shard, batch_stats, step_ctr, rng,
                 images_u8, labels):
            shard_len = param_flat.shape[0] // n
            rank = lax.axis_index(axis_name)
            params = unravel(param_flat[:n_elems])

            r = step_rng(rng, step_ctr, axis_name)
            x = augment_batch(r, images_u8) if augment else normalize(images_u8)

            # (2) forward/backward + reduce-scatter of the MEAN gradient —
            # shared with ZeRO-3 (parallel/fsdp.py) so the schemes cannot
            # drift apart.
            loss, new_stats, grad_shard = flat_mean_grad_shard(
                model, params, batch_stats, x, labels, axis_name, n,
                param_flat.shape[0],
            )

            # (3) Update the owned param slice against the momentum shard.
            p_shard = lax.dynamic_slice(
                param_flat, (rank * shard_len,), (shard_len,)
            )
            new_p_shard, new_m_shard = update_fn_for_config(cfg)(
                p_shard, momentum_shard, grad_shard, cfg, step=step_ctr
            )

            if gather:
                # (4, sync build) All-gather the updated slices into the
                # full vector — ON the critical path, feeding ROOT.
                new_flat = lax.all_gather(new_p_shard, axis_name, tiled=True)
                return new_flat, new_m_shard, new_stats, loss
            # (4, overlap build) stop at the shard; the consume-phase
            # program gathers it behind the next step's data wait.
            return new_p_shard, new_m_shard, new_stats, loss

        shard = P(axis_name)
        return _shard_map(
            impl,
            mesh=mesh,
            in_specs=(P(), shard, P(), P(), P(), shard, shard),
            out_specs=((P() if gather else shard), shard, P(), P()),
        )

    if not overlap:
        def step(state: Zero1State, images_u8, labels):
            new_flat, new_mom, new_stats, loss = sharded_for(
                state.config, gather=True
            )(
                state.param_flat,
                state.momentum_shards,
                state.batch_stats,
                state.step,
                state.rng,
                images_u8,
                labels,
            )
            new_state = state.replace(
                param_flat=new_flat,
                momentum_shards=new_mom,
                batch_stats=new_stats,
                step=state.step + 1,
            )
            return new_state, loss

        return jax.jit(step, donate_argnums=(0,))

    from distributed_machine_learning_tpu.parallel.overlap import (
        GatherSpanClock,
        make_ring_gather,
    )

    # The consume-phase program: the freshly updated shards are donated
    # into the gather (nothing else reads them); the replicated full
    # vector is the survivor the next step reads.
    gather_inner = make_ring_gather(mesh, axis_name, n, donate=True)

    jitted: dict = {}

    def update_for(cfg):
        # Donate param_flat (arg 0 — it cannot alias the SHARDED
        # shard-output, but freeing it mid-program caps peak HBM at
        # the sync build's level, same reasoning as the fsdp prefetch
        # wrapper's full vector) plus the momentum and BN-stats
        # buffers (1, 2), which alias their updated twins.  NOT
        # donated: step (3) is read again by the wrapper's
        # ``state.step + 1`` and rng (4) is carried unchanged into the
        # next step — donating either would hand the wrapper a dead
        # buffer on backends that take donation.
        fn = jitted.get(cfg)
        if fn is None:
            fn = jitted[cfg] = jax.jit(
                sharded_for(cfg, gather=False), donate_argnums=(0, 1, 2)
            )
        return fn

    clock = GatherSpanClock()

    def step(state: Zero1State, images_u8, labels):
        clock.close()
        new_shard, new_mom, new_stats, loss = update_for(state.config)(
            state.param_flat,
            state.momentum_shards,
            state.batch_stats,
            state.step,
            state.rng,
            images_u8,
            labels,
        )
        new_flat = gather_inner(new_shard)
        clock.open(new_flat)
        new_state = state.replace(
            param_flat=new_flat,
            momentum_shards=new_mom,
            batch_stats=new_stats,
            step=state.step + 1,
        )
        return new_state, loss

    step.overlap = True
    step.update_for = update_for
    step.gather_inner = gather_inner
    step.pop_gather_seconds = clock.pop
    return step


def zero1_memory_footprint(n_params: int, n_dev: int, bytes_per_elem: int = 4):
    """Per-device param+momentum bytes: replicated vs ZeRO-1 vs ZeRO-3.

    ZeRO-1 counts the *padded* replicated vector — what
    :func:`shard_zero1_state` actually materializes per device — plus the
    1/N momentum shard (also padded, matching the momentum term of
    ``fsdp_memory_footprint``).
    """
    fp = fsdp_memory_footprint(n_params, n_dev, bytes_per_elem)
    padded = _padded_len(n_params, n_dev)
    fp["zero1"] = (padded + padded // n_dev) * bytes_per_elem
    return fp
