"""The pluggable gradient-synchronization layer.

This is the reference's one *varying* layer (SURVEY.md §1): its four parts
are copy-pasted clones differing only in what happens between
``loss.backward()`` and ``optimizer.step()``.  Here that seam is an
explicit interface — a strategy is a pure function on the gradient pytree,
executed inside the shard_mapped train step over the mesh's data axis:

  =============  ======================================  =================
  strategy       reference                               reduction
  =============  ======================================  =================
  none           part1 (single process, no sync)         —
  gather_scatter part2/2a ``gatherAndScatter``            SUM (§2.4)
                 (``part2/2a/main.py:89-116``)
  all_reduce     part2/2b ``allReduce``                   SUM (§2.4)
                 (``part2/2b/main.py:101-106``)
  ring           part3 DDP bucketed ring                  MEAN (DDP avgs)
                 (``part3/main.py:137``), rebuilt as an
                 explicit lax.ppermute ring (north-star)
  =============  ======================================  =================

SUM-vs-MEAN is a real semantic difference the reference's report glossed
over (SURVEY.md §2.4): 2a/2b sum gradients and never divide by world size
(an effective world_size× learning-rate), part3's DDP averages.  Each
strategy reproduces its part's exact semantics; the ``mean`` flag lets a
user override.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from distributed_machine_learning_tpu.ops.collectives import (
    all_reduce_mean,
    all_reduce_sum,
    gather_scatter_sum,
)
from distributed_machine_learning_tpu.ops.ring import (
    DEFAULT_BUCKET_BYTES,
    ring_all_reduce,
)


@dataclass(frozen=True)
class SyncStrategy:
    """Base: a pure transform grads → synced grads over `axis_name`."""

    name = "base"

    def __call__(self, grads, axis_name: str, axis_size: int):
        raise NotImplementedError


@dataclass(frozen=True)
class NoSync(SyncStrategy):
    """part1: single-process, no gradient exchange."""

    name = "none"

    def __call__(self, grads, axis_name: str, axis_size: int):
        return grads


@dataclass(frozen=True)
class AllReduce(SyncStrategy):
    """part2b: one all-reduce per parameter; SUM by default (§2.4)."""

    name = "all_reduce"
    mean: bool = False

    def __call__(self, grads, axis_name: str, axis_size: int):
        if self.mean:
            return all_reduce_mean(grads, axis_name)
        return all_reduce_sum(grads, axis_name)


@dataclass(frozen=True)
class GatherScatter(SyncStrategy):
    """part2a: centralized gather→sum→scatter, as all-gather + rank-order sum."""

    name = "gather_scatter"

    def __call__(self, grads, axis_name: str, axis_size: int):
        return gather_scatter_sum(grads, axis_name)


@dataclass(frozen=True)
class RingAllReduce(SyncStrategy):
    """part3 north-star: bucketed explicit ppermute ring, DDP mean semantics.

    ``wire_dtype="bfloat16"`` compresses each hop's payload on the wire
    (half the ring bytes for fp32 gradients — the compressed-all-reduce
    technique from the retrieved literature, PAPERS.md); default exact.
    """

    name = "ring"
    mean: bool = True
    bucket_bytes: int = DEFAULT_BUCKET_BYTES
    wire_dtype: str | None = None

    def __call__(self, grads, axis_name: str, axis_size: int):
        import jax.numpy as jnp

        return ring_all_reduce(
            grads,
            axis_name,
            axis_size,
            mean=self.mean,
            bucket_bytes=self.bucket_bytes,
            wire_dtype=None if self.wire_dtype is None
            else jnp.dtype(self.wire_dtype).type,
        )


STRATEGIES = {
    "none": NoSync,
    "gather_scatter": GatherScatter,
    "all_reduce": AllReduce,
    "ring": RingAllReduce,
}


def get_strategy(name: str, **kwargs) -> SyncStrategy:
    try:
        return STRATEGIES[name](**kwargs)
    except KeyError:
        raise ValueError(
            f"unknown sync strategy {name!r}; choose from {sorted(STRATEGIES)}"
        ) from None
