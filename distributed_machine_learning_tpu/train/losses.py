"""Loss and metric functions.

The reference uses ``torch.nn.CrossEntropyLoss()`` with default mean
reduction (``part1/main.py:115``) for both training and eval, and top-1
accuracy via argmax (``part1/main.py:71-72``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cross_entropy_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy over all leading axes (CrossEntropyLoss
    parity; handles [B, C] classification and [B, L, C] token logits).

    The target logit is selected by a one-hot contraction rather than
    ``take_along_axis``: on TPU a masked reduction vectorizes where a
    gather serializes, and when the class dim is tensor-parallel-sharded
    (column-split lm_head — ``parallel/parallel3d.py``) the reduction
    partitions cleanly while a class-dim gather trips XLA's SPMD gather
    partitioner.
    """
    logits32 = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits32, axis=-1)
    one_hot = jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
    target_logit = jnp.sum(logits32 * one_hot, axis=-1)
    return (lse - target_logit).mean()


def count_correct(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Top-1 correct-prediction count (part1/main.py:71-72)."""
    return (logits.argmax(axis=-1) == labels).sum()


def lm_cross_entropy(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean next-token cross-entropy over [B, L] targets.

    Caller supplies already-shifted targets (under sequence sharding the
    shift crosses chunk boundaries, so shifting belongs to the host data
    pipeline, not the sharded step).  Equal chunk sizes make the global
    mean equal the pmean of local means.
    """
    return cross_entropy_loss(logits, targets)
