"""Ulysses sequence parallelism: all-to-all head-sharded attention.

The second context-parallel scheme (DeepSpeed-Ulysses pattern), next to
the ppermute ring of ``ops/ring_attention.py``: instead of rotating K/V
blocks, one ``lax.all_to_all`` re-shards activations from
sequence-sharded [B, L/n, H, D] to head-sharded [B, L, H/n, D], every
device runs ordinary dense causal attention over the *full* sequence for
its slice of heads, and a second all-to-all restores sequence sharding.

Trade-offs vs the ring (why both exist): Ulysses does 2 all-to-alls of
activation size regardless of n (cheaper than the ring's n−1 rotations
when heads are plentiful and ICI all-to-all bandwidth is good), but
requires ``n_heads % n == 0`` and holds full-L scores per head slice;
the ring has no head constraint and O(L·L/n) score memory.  Both are
exact.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from distributed_machine_learning_tpu.ops.ring_attention import (
    dense_self_attention,
)


def ulysses_self_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    axis_size: int,
    local_attn: str = "auto",
) -> jax.Array:
    """Exact causal attention over sequence chunks sharded on ``axis_name``.

    Must run inside ``shard_map``.  ``q``/``k``/``v``: local chunks
    [B, L/n, H, D] in mesh-axis order; returns the local output chunk.

    ``local_attn``: the kernel for the per-device full-sequence attention
    after the head re-shard — "dense" (XLA), "flash" (the Pallas kernel,
    the big win here: Ulysses holds full-L scores per head slice, exactly
    the regime flash exists for), or "auto" (flash from the measured 512
    crossover up for natively-tileable lengths, always from 2048 via the
    kernel's pad-and-slice path — ``flash_wins``).
    """
    n = axis_size
    H, Hkv = q.shape[2], k.shape[2]
    if H % Hkv:
        raise ValueError(
            f"query heads ({H}) must be a multiple of K/V heads ({Hkv})"
        )
    rep = H // Hkv
    if n == 1:
        k = jnp.repeat(k, rep, axis=2) if rep > 1 else k
        v = jnp.repeat(v, rep, axis=2) if rep > 1 else v
        return dense_self_attention(q, k, v)
    if H % n:
        raise ValueError(
            f"Ulysses needs n_heads divisible by the sequence-axis size: "
            f"{H} heads over {n} devices (use the ring instead)"
        )
    L = q.shape[1] * n
    from distributed_machine_learning_tpu.ops.pallas.flash_attention import (
        flash_self_attention,
        flash_wins,
    )

    use_flash = local_attn == "flash" or (
        local_attn == "auto" and flash_wins(L)
    )
    if rep > 1 and Hkv % n == 0:
        # GQA narrow path: the all-to-all moves the NARROW K/V heads —
        # query head block r = [r·H/n, (r+1)·H/n) maps exactly onto kv
        # block r = [r·Hkv/n, (r+1)·Hkv/n) (h → h//rep is block-
        # preserving when n | Hkv), so the bytes drop from 3·H to
        # H + 2·Hkv per token — the same group-factor ICI saving the
        # flash ring gets by rotating narrow chunks.  One launch, like
        # the wide path: head order is (hkv, rep) under h//rep, so q
        # viewed [B, Lc, Hkv, rep, D] concatenates with k/v on the rep
        # axis and the single collective splits the SHARED Hkv axis —
        # alignment of q and kv blocks is then true by construction.
        qg = q.reshape(*q.shape[:2], Hkv, rep, q.shape[3])
        pack = jnp.concatenate(
            [qg, k[:, :, :, None], v[:, :, :, None]], axis=3
        )  # [B, Lc, Hkv, rep+2, D]
        pack = lax.all_to_all(
            pack, axis_name, split_axis=2, concat_axis=1, tiled=True
        )  # [B, L, Hkv/n, rep+2, D]
        B, L_, hkv_l = pack.shape[:3]
        q2 = pack[:, :, :, :rep].reshape(B, L_, hkv_l * rep, -1)
        k2, v2 = pack[:, :, :, rep], pack[:, :, :, rep + 1]
        if use_flash:
            # The kernel is GQA-native: the narrow K/V stream as-is.
            out = flash_self_attention(q2, k2, v2)
        else:
            out = dense_self_attention(
                q2, jnp.repeat(k2, rep, axis=2), jnp.repeat(v2, rep, axis=2)
            )
        return lax.all_to_all(
            out, axis_name, split_axis=1, concat_axis=2, tiled=True
        )
    if rep > 1:
        # Hkv not divisible by n: widen first (block alignment would
        # break), paying the classic wide all-to-all.
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    # seq-sharded → head-sharded: each device keeps heads [r·H/n,(r+1)·H/n)
    # for the FULL sequence (all_to_all concatenates chunks in axis order,
    # so global sequence order is preserved).  Q/K/V ride ONE stacked
    # collective — same bytes as three, one launch.
    qkv = jnp.stack([q, k, v], axis=2)  # [B, Lc, 3, H, D]
    qkv = lax.all_to_all(
        qkv, axis_name, split_axis=3, concat_axis=1, tiled=True
    )  # [B, L, 3, H/n, D]
    local = flash_self_attention if use_flash else dense_self_attention
    out = local(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2])
    # head-sharded → seq-sharded.
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)
