"""GangTransport conformance + the TCP robustness layer (ISSUE 12).

One parametrized contract suite runs the SAME assertions against all
three backends (file / in-proc / tcp), so a fourth backend is a
checklist, not an archaeology dig: beat freshness (signature advances
on publish), abort first-writer-wins under concurrent latching, join
announce/consume idempotency, restore-record round-trips, ledger
append-only semantics and their survival across
``clear_gang_state(fault_ledger=False)``, the snapshot API, and the
poll-cadence contract (cadence is a transport property — the ISSUE 12
bugfix).

The TCP half then proves the lossy-medium claims against injected
faults (``runtime/faults.py::TransportChaos``) instead of asserting
them: a dropped request is retried (with the retry/timeout counters
landing in the registry), a duplicated delivery is applied exactly
once (op_id dedup), a REPLAYED join announcement cannot re-admit a
consumed join, and a partitioned member both self-detects (its
coordinator treats the outage as its own death) and is detected by its
peers within ``peer_timeout_s``.
"""

from __future__ import annotations

import os
import threading
import time

import pytest

from distributed_machine_learning_tpu.runtime.coordinator import (
    GangCoordinator,
)
from distributed_machine_learning_tpu.runtime.faults import (
    FaultEvents,
    TransportChaos,
)
from distributed_machine_learning_tpu.runtime.transport import (
    FileTransport,
    InProcHub,
    InProcTransport,
    TcpGangServer,
    TcpTransport,
    TransportError,
    make_transport,
)

BACKENDS = ("file", "inproc", "tcp")


@pytest.fixture(params=BACKENDS)
def backend(request, tmp_path):
    """(name, make_handle): ``make_handle()`` returns a FRESH transport
    handle on the SAME underlying gang state — the multi-member view
    the contract is about."""
    name = request.param
    if name == "file":
        yield name, lambda: FileTransport(tmp_path / "gang")
        return
    if name == "inproc":
        hub = InProcHub()
        yield name, lambda: InProcTransport(hub)
        return
    server = TcpGangServer().start()
    try:
        yield name, lambda: TcpTransport(server.address, backoff_s=0.01)
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# Conformance: the same contract against every backend
# ---------------------------------------------------------------------------


def test_beat_publish_read_and_signature_freshness(backend):
    _, make = backend
    tx, peer = make(), make()
    assert peer.read_beat(0) is None
    assert peer.read_beats() == {}
    tx.publish_beat(0, {"rank": 0, "seq": 1, "step": 3, "done": False})
    sig1, payload = peer.read_beat(0)
    assert payload["step"] == 3
    # A re-publish with NEW content must advance the signature — the
    # change-signature staleness basis the peer detector judges on.
    time.sleep(0.02)  # file mtime granularity
    tx.publish_beat(0, {"rank": 0, "seq": 2, "step": 4, "done": False})
    sig2, payload2 = peer.read_beat(0)
    assert sig2 != sig1 and payload2["step"] == 4
    beats = peer.read_beats()
    assert set(beats) == {0} and beats[0][1]["step"] == 4
    assert peer.read_beat_payloads()[0]["seq"] == 2


def test_abort_latch_first_writer_wins_under_concurrency(backend):
    _, make = backend
    reader = make()
    assert reader.read_abort() is None
    wins: list[tuple[int, bool]] = []
    lock = threading.Lock()

    def latch(i):
        won = make().declare_abort(f"declared by {i}", i, peer=i)
        with lock:
            wins.append((i, won))

    threads = [threading.Thread(target=latch, args=(i,))
               for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    winners = [i for i, won in wins if won]
    assert len(winners) == 1, wins
    abort = reader.read_abort()
    assert abort["by_rank"] == winners[0]
    # The losers' reasons never overwrite the winner's.
    assert abort["reason"] == f"declared by {winners[0]}"
    assert reader.declare_abort("late", 99) is False


def test_join_announce_consume_idempotency(backend):
    _, make = backend
    tx, peer = make(), make()
    tx.announce_join(2, {"rank": 2, "spare": False, "time": time.time(),
                         "kind": "recover", "at_step": 5})
    tx.announce_join(4, {"rank": 4, "spare": True, "time": time.time(),
                         "prefetched_step": 10})
    joins = peer.read_joins()
    assert set(joins) == {2, 4}
    assert joins[2]["at_step"] == 5 and joins[4]["prefetched_step"] == 10
    # Re-announcing is an idempotent overwrite (the spare heartbeat).
    tx.announce_join(4, {"rank": 4, "spare": True, "time": time.time(),
                         "prefetched_step": 12})
    assert peer.read_joins()[4]["prefetched_step"] == 12
    tx.consume_join(2)
    assert set(peer.read_joins()) == {4}
    tx.consume_join(2)  # consuming twice is a no-op
    assert set(peer.read_joins()) == {4}


def test_restore_records_roundtrip(backend):
    _, make = backend
    tx, peer = make(), make()
    assert peer.read_restore_record(0) is None
    tx.write_restore_record(0, {5, 3})
    assert peer.read_restore_record(0) == {3, 5}
    tx.write_restore_record(0, {3, 5, 10})
    assert peer.read_restore_record(0) == {3, 5, 10}
    assert peer.read_restore_record(1) is None


def test_ledgers_append_only_and_clear_semantics(backend):
    _, make = backend
    tx, peer = make(), make()
    tx.append_health_event("restart", attempt=1, world=4)
    tx.append_health_event("shrink", attempt=2, from_world=4, to_world=3)
    tx.append_fault_entry({"index": 0, "kind": "lose_rank", "rank": 1,
                           "at": 7})
    tx.append_consumed(0, {"step": 0, "ids": [0, 1]})
    tx.append_consumed(2, {"step": 0, "ids": [2, 3]})
    tx.publish_beat(0, {"rank": 0, "seq": 1, "step": 1})
    tx.declare_abort("boom", 0)
    tx.write_restore_record(0, {4})
    tx.announce_join(3, {"rank": 3, "spare": False, "time": time.time()})

    kinds = [e["kind"] for e in peer.read_health_events()]
    assert kinds == ["restart", "shrink"]  # append order preserved
    assert [e["kind"] for e in peer.read_fault_entries()] == ["lose_rank"]
    assert [r["ids"] for r in peer.read_consumed(0)] == [[0, 1]]
    assert len(peer.read_consumed()) == 2  # all-ranks read

    # Between-attempt clear: beats + abort die, everything durable
    # survives — the ledger is what keeps fired faults latched and the
    # pending join is what the next boundary admits.
    tx.clear_gang_state(fault_ledger=False)
    assert peer.read_beats() == {} and peer.read_abort() is None
    assert peer.read_restore_record(0) == {4}
    assert [e["kind"] for e in peer.read_health_events()] == kinds
    assert len(peer.read_fault_entries()) == 1
    assert len(peer.read_consumed()) == 2
    assert 3 in peer.read_joins()

    # Renumbering clear: restore records go, ledgers stay.
    tx.clear_gang_state(restore_records=True, fault_ledger=False)
    assert peer.read_restore_record(0) is None
    assert len(peer.read_fault_entries()) == 1

    # Fresh-run clear: everything durable goes too.
    tx.clear_gang_state(restore_records=True)
    assert peer.read_health_events() == []
    assert peer.read_fault_entries() == []
    assert peer.read_consumed() == []
    assert peer.read_joins() == {}


def test_snapshot_api(backend):
    name, make = backend
    tx = make()
    tx.publish_beat(1, {"rank": 1, "seq": 1, "step": 2})
    tx.announce_join(5, {"rank": 5, "spare": True, "time": time.time()})
    tx.append_health_event("restart", attempt=1, world=2)
    tx.append_fault_entry({"index": 0, "kind": "kill_rank", "rank": 0,
                           "at": 3})
    snap = make().snapshot()
    assert snap["backend"] == name
    assert snap["beats"][1]["step"] == 2
    assert snap["abort"] is None
    assert set(snap["joins"]) == {5}
    assert [e["kind"] for e in snap["health"]] == ["restart"]
    assert [e["kind"] for e in snap["faults_fired"]] == ["kill_rank"]


def test_poll_cadence_is_a_transport_property(backend):
    """The ISSUE 12 bugfix contract: the file backend keeps the
    historical file-stat cadence; in-proc polls at least as tightly
    (dict reads); tcp never polls faster than its per-world request
    budget allows, and never slower than a quarter peer timeout — at
    world 128 the whole gang's read rate on the rank-0 host stays
    bounded instead of quadratic."""
    name, make = backend
    tx = make()
    file_like = min(0.25, 30.0 / 4)
    poll_small = tx.monitor_poll_s(0.25, 30.0, 2)
    poll_big = tx.monitor_poll_s(0.25, 30.0, 128)
    for poll in (poll_small, poll_big):
        assert 0 < poll <= 30.0 / 4
    if name == "file":
        assert poll_small == poll_big == file_like
    elif name == "inproc":
        assert poll_small <= file_like and poll_big <= file_like
    else:
        assert poll_big > poll_small  # cadence backs off with world
        assert poll_big >= 128 * TcpTransport._PER_RANK_BUDGET_S
    assert tx.supervisor_poll_s(2) > 0
    assert tx.barrier_poll_s() > 0
    if name == "tcp":
        assert tx.supervisor_poll_s(128) >= tx.supervisor_poll_s(2)


def test_op_accounting(backend):
    _, make = backend
    tx = make()
    tx.publish_beat(0, {"rank": 0, "seq": 1, "step": 0})
    tx.read_beats()
    tx.read_beats()
    stats = tx.stats()
    assert stats["ops"]["publish_beat"] == 1
    assert stats["ops"]["read_beats"] == 2
    assert stats["ops_total"] >= 3
    assert stats["retries"] == 0 and stats["timeouts"] == 0


def test_coordinator_detects_dead_peer_over_backend(backend):
    """The peer-death detector works unchanged over every transport:
    rank 1 publishes once and goes silent; rank 0's monitor declares it
    dead within the timeout and the abort latch names it."""
    _, make = backend
    aborts: list[str] = []
    c1 = GangCoordinator(None, rank=1, world=2, transport=make(),
                         heartbeat_interval_s=0.05, peer_timeout_s=0.6,
                         check_self=False, on_abort=lambda r: None)
    c1.start()
    c1.stop()  # one beat published, then silence — a dead process
    c0 = GangCoordinator(None, rank=0, world=2, transport=make(),
                         heartbeat_interval_s=0.05, peer_timeout_s=0.6,
                         check_self=False, on_abort=aborts.append)
    c0.start()
    try:
        deadline = time.monotonic() + 6.0
        while not aborts and time.monotonic() < deadline:
            c0.beat()
            time.sleep(0.05)
        assert aborts and "rank 1" in aborts[0]
        assert "rank 1" in str(make().read_abort()["reason"])
    finally:
        c0.stop()


# ---------------------------------------------------------------------------
# Serving channels (ISSUE 16): the router/replica contract
# ---------------------------------------------------------------------------


def test_serving_request_spool_is_fifo_and_destructive(backend):
    _, make = backend
    router, worker = make(), make()
    assert worker.take_requests(0, 8) == []
    for i in range(5):
        router.push_request(0, {"rid": f"r{i}", "i": i})
    # FIFO in dispatch order, destructive in micro-batch slices.
    assert [r["rid"] for r in worker.take_requests(0, 2)] == ["r0", "r1"]
    assert [r["rid"] for r in worker.take_requests(0, 8)] == ["r2", "r3",
                                                              "r4"]
    assert worker.take_requests(0, 8) == []
    # Queues are per-replica: rank 1's spool is invisible to rank 0.
    router.push_request(1, {"rid": "other"})
    assert worker.take_requests(0, 8) == []
    assert [r["rid"] for r in worker.take_requests(1, 8)] == ["other"]


def test_serving_result_fence_retire_and_roles(backend):
    _, make = backend
    router, worker = make(), make()
    # Every rank starts as a spare; promotion is an explicit write.
    assert router.read_serving(0)["role"] == "spare"
    router.set_serving_role(0, "live")
    state = router.read_serving(0)
    assert state["role"] == "live" and state["drain"] is False
    e0 = state["epoch"]
    # Posts under the bound epoch land; any other epoch is fenced.
    assert worker.post_result(0, e0, {"rid": "a", "out": [1]}) is True
    assert worker.post_result(0, e0 + 1, {"rid": "ghost"}) is False
    got = router.take_results(8)
    assert [r["rid"] for r in got] == ["a"]
    assert got[0]["replica"] == 0 and got[0]["epoch"] == e0
    assert router.take_results(8) == []  # destructive
    # Drain is a latch the worker observes via read_serving.
    router.set_drain(0, True)
    assert router.read_serving(0)["drain"] is True
    # Retire is the atomic handoff: epoch bump, queue reclaim, role
    # back to spare, drain cleared.
    router.push_request(0, {"rid": "undelivered"})
    undelivered = router.retire_replica(0)
    assert [r["rid"] for r in undelivered] == ["undelivered"]
    after = router.read_serving(0)
    assert after == {"role": "spare", "epoch": e0 + 1,
                     "drain": False, "queued": 0,
                     "weights": {"version": 0, "pending": None}}
    # The retired epoch's late post bounces off the fence...
    assert worker.post_result(0, e0, {"rid": "late"}) is False
    assert router.take_results(8) == []
    # ...while the re-promoted epoch serves normally.
    assert worker.post_result(0, e0 + 1, {"rid": "b"}) is True
    assert [r["rid"] for r in router.take_results(8)] == ["b"]


def test_serving_state_reaches_fleet_view_and_snapshot(backend):
    _, make = backend
    tx, peer = make(), make()
    tx.set_serving_role(1, "live")
    tx.push_request(1, {"rid": "q"})
    tx.set_drain(2, True)
    fleet = peer.read_serving()
    assert fleet["replicas"][1] == {"role": "live", "epoch": 0,
                                    "drain": False, "queued": 1,
                                    "weights": {"version": 0,
                                                "pending": None}}
    assert fleet["replicas"][2]["drain"] is True
    assert fleet["results"] == 0
    snap = peer.snapshot()
    assert snap["serving"]["replicas"][1]["queued"] == 1


def test_weight_swap_stage_commit_and_fence(backend):
    """ISSUE 18: the weights channel on every backend.  Staging a
    version does NOT move the fence (in-flight old-version work keeps
    completing — the zero-dropped-requests half); the commit flips it
    atomically, and from then on an old-version post is discarded."""
    _, make = backend
    deploy, worker = make(), make()
    deploy.set_serving_role(3, "live")
    e0 = deploy.read_serving(3)["epoch"]
    deploy.set_weights(3, 1, {"step": 100, "digest": "abc"})
    rec = deploy.read_serving(3)["weights"]
    assert rec["version"] == 0 and rec["pending"] == 1
    assert rec["step"] == 100 and rec["digest"] == "abc"
    assert worker.post_result(3, e0, {"rid": "pre"}, version=0) is True
    assert worker.commit_weights(3, 1) is True
    rec = deploy.read_serving(3)["weights"]
    assert rec["version"] == 1 and rec["pending"] is None
    assert worker.post_result(3, e0, {"rid": "old"}, version=0) is False
    assert worker.post_result(3, e0, {"rid": "new"}, version=1) is True
    got = {r["rid"]: r for r in deploy.take_results(8)}
    assert set(got) == {"pre", "new"}
    assert got["new"]["version"] == 1
    # Version-less posts (fleets with no deployment controller) are
    # never version-fenced — the pre-ISSUE-18 contract is unchanged.
    assert worker.post_result(3, e0, {"rid": "plain"}) is True
    # The weights record survives a retire (the spare still HOLDS the
    # weights it last served; re-promotion decides what to load).
    deploy.retire_replica(3)
    assert deploy.read_serving(3)["weights"]["version"] == 1


def test_serving_state_is_wiped_with_the_gang(backend):
    _, make = backend
    tx = make()
    tx.set_serving_role(0, "live")
    tx.push_request(0, {"rid": "x"})
    tx.post_result(0, 0, {"rid": "x"})
    tx.clear_gang_state(fault_ledger=True)
    fleet = make().read_serving()
    assert fleet["replicas"] == {} and fleet["results"] == 0


def test_file_spool_orphaned_take_claim_is_garbage_collected(tmp_path):
    """REVIEW fix: a taker that crashes between its rename-claim and
    the read+remove would orphan the request forever (invisible to
    takes, retire reclaim, and the queued count).  A claim older than
    the GC threshold is renamed back and becomes claimable again."""
    tx = FileTransport(tmp_path / "gang")
    tx._TAKE_ORPHAN_S = 0.05  # shrink the staleness window for the test
    tx.push_request(0, {"rid": "orphan"})
    d = os.path.join(tx.gang_dir, "serving", "requests_r0")
    (name,) = os.listdir(d)
    os.rename(os.path.join(d, name),
              os.path.join(d, f"{name}.take999.1"))
    # Invisible while claimed and fresh (the owner may still read it);
    # this first scan registers the claim's stat signature.
    assert tx.take_requests(0, 8) == []
    assert tx.read_serving(0)["queued"] == 0
    # Unchanged past the staleness window: the next scan restores it,
    # the one after claims it.
    time.sleep(0.1)
    assert tx.take_requests(0, 8) == []
    assert tx.read_serving(0)["queued"] == 1
    assert [r["rid"] for r in tx.take_requests(0, 8)] == ["orphan"]


def test_file_post_result_reverify_reclaims_on_raced_retire(
        tmp_path, monkeypatch):
    """REVIEW fix: the file backend's epoch fence must be atomic with
    the result push.  Without fcntl it falls back to push-then-
    reverify: a ``retire_replica`` landing between the epoch read and
    the push must not leave a stale-epoch result in the spool."""
    from distributed_machine_learning_tpu.runtime import (
        transport as transport_mod,
    )
    monkeypatch.setattr(transport_mod, "fcntl", None)
    tx = FileTransport(tmp_path / "gang")
    tx.set_serving_role(0, "live")
    real_push = tx._spool_push

    def racing_push(subdir, payload):
        path = real_push(subdir, payload)
        FileTransport(tx.gang_dir).retire_replica(0)  # the TOCTOU race
        return path

    monkeypatch.setattr(tx, "_spool_push", racing_push)
    assert tx.post_result(0, 0, {"rid": "stale"}) is False
    monkeypatch.setattr(tx, "_spool_push", real_push)
    assert tx.take_results(8) == []  # the stale file was reclaimed
    # The post-retire epoch serves normally.
    assert tx.post_result(0, 1, {"rid": "ok"}) is True
    assert [r["rid"] for r in tx.take_results(8)] == ["ok"]


# ---------------------------------------------------------------------------
# TCP robustness layer: the lossy-medium claims, tested not asserted
# ---------------------------------------------------------------------------


@pytest.fixture
def tcp_server():
    server = TcpGangServer().start()
    try:
        yield server
    finally:
        server.stop()


def test_tcp_drop_is_retried_and_counted(tcp_server):
    events = FaultEvents()
    chaos = TransportChaos(drop=[("append_health", 1)])
    tx = TcpTransport(tcp_server.address, events=events, chaos=chaos,
                      backoff_s=0.01)
    tx.append_health_event("x", n=1)
    # The drop looked like a timeout to the client; the retry landed
    # the op exactly once.
    reader = TcpTransport(tcp_server.address)
    assert len(reader.read_health_events()) == 1
    stats = tx.stats()
    assert stats["retries"] >= 1 and stats["timeouts"] >= 1
    assert events.transport_retries >= 1
    assert events.transport_timeouts >= 1
    assert ("drop", "append_health", 1) in chaos.fired


def test_tcp_retry_timeout_counters_reach_the_registry(tcp_server,
                                                       tmp_path):
    from distributed_machine_learning_tpu.telemetry import (
        Telemetry,
        set_telemetry,
    )

    tel = Telemetry(str(tmp_path / "tel"))
    set_telemetry(tel)
    try:
        chaos = TransportChaos(drop=[("publish_beat", 1)])
        tx = TcpTransport(tcp_server.address, chaos=chaos,
                          backoff_s=0.01)
        tx.publish_beat(0, {"rank": 0, "seq": 1, "step": 0})
        snap = tel.registry.snapshot()
        counters = {(c["name"], tuple(sorted((c.get("labels") or {})
                                             .items()))): c["value"]
                    for c in snap["counters"]}
        assert counters[("gang_transport_retries",
                         (("backend", "tcp"),))] >= 1
        assert counters[("gang_transport_timeouts",
                         (("backend", "tcp"),))] >= 1
        assert any(name == "gang_transport_ops"
                   and dict(labels).get("op") == "publish_beat"
                   for name, labels in counters)
    finally:
        set_telemetry(None)
        tel.close()


def test_tcp_duplicate_delivery_applies_exactly_once(tcp_server):
    """A network-duplicated delivery (same op_id, delivered twice)
    must not double-append a ledger line or double-fire an abort."""
    chaos = TransportChaos(duplicate=[("append_fault", 1),
                                      ("declare_abort", 1)])
    tx = TcpTransport(tcp_server.address, chaos=chaos, backoff_s=0.01)
    tx.append_fault_entry({"index": 0, "kind": "lose_rank", "rank": 1,
                           "at": 3})
    reader = TcpTransport(tcp_server.address)
    assert len(reader.read_fault_entries()) == 1
    # The duplicated declare still reports ONE first-writer win, and a
    # different member's later declare correctly loses.
    assert tx.declare_abort("first", 1) is True
    assert reader.read_abort()["by_rank"] == 1
    assert reader.declare_abort("late", 2) is False


def test_tcp_replayed_join_cannot_readmit_after_consume(tcp_server):
    """Reordered/duplicated delivery of an OLD announce arriving after
    the supervisor consumed the join must not resurrect it — the
    server's op_id dedup extends exactly-once across the reorder."""
    tx = TcpTransport(tcp_server.address)
    tx.announce_join(3, {"rank": 3, "spare": False,
                         "time": time.time()})
    replay = {"op": "announce_join", "rank": 3, "op_id": "replay-123",
              "payload": {"rank": 3, "spare": False, "time": 1.0}}
    tx._roundtrip(dict(replay))  # first delivery
    tx.consume_join(3)
    assert tx.read_joins() == {}
    tx._roundtrip(dict(replay))  # late duplicate of the SAME message
    assert tx.read_joins() == {}


def test_tcp_duplicate_racing_inflight_original_applies_once(tcp_server):
    """The nasty dedup window: a duplicate arrives while the ORIGINAL
    is still being applied (client timeout shorter than a slow apply).
    The op_id is reserved before the apply runs, so the racer waits for
    the original's result instead of re-applying."""
    real_apply = tcp_server._apply
    started = threading.Event()
    release = threading.Event()

    def slow_apply(op, req):
        if op == "append_fault":
            started.set()
            release.wait(5.0)
        return real_apply(op, req)

    tcp_server._apply = slow_apply
    req = {"op": "append_fault", "op_id": "race-1",
           "payload": {"index": 0, "kind": "kill_rank", "rank": 0,
                       "at": 1}}
    results = []
    t1 = threading.Thread(
        target=lambda: results.append(tcp_server.dispatch(dict(req))))
    t1.start()
    assert started.wait(5.0)
    t2 = threading.Thread(
        target=lambda: results.append(tcp_server.dispatch(dict(req))))
    t2.start()
    time.sleep(0.1)  # let the duplicate reach the reservation
    release.set()
    t1.join(5.0)
    t2.join(5.0)
    tcp_server._apply = real_apply
    assert len(results) == 2
    assert len(TcpTransport(tcp_server.address)
               .read_fault_entries()) == 1


def test_tcp_dropped_serving_push_applies_exactly_once(tcp_server):
    """The serving channels ride the same op_id dedup as the ledgers:
    a dropped ``push_request`` is retried and the request lands in the
    replica's spool exactly once — a retried request is re-dispatched
    without duplication."""
    events = FaultEvents()
    chaos = TransportChaos(drop=[("push_request", 1)])
    tx = TcpTransport(tcp_server.address, events=events, chaos=chaos,
                      backoff_s=0.01)
    tx.push_request(0, {"rid": "only"})
    assert tx.stats()["retries"] >= 1
    reader = TcpTransport(tcp_server.address)
    assert reader.read_serving(0)["queued"] == 1
    assert [r["rid"] for r in reader.take_requests(0, 8)] == ["only"]


def test_tcp_retried_take_returns_the_same_batch(tcp_server):
    """``take_requests`` is DESTRUCTIVE, so a response lost after the
    server applied is the nasty case: the batch is already popped.  The
    retry reuses the op_id, and the dedup layer answers with the SAME
    batch instead of an empty second pop — no request is stranded."""
    tx = TcpTransport(tcp_server.address)
    tx.push_request(3, {"rid": "precious"})
    req = {"op": "take_requests", "rank": 3, "max_n": 8,
           "op_id": "take-retry-1"}
    first = tx._roundtrip(dict(req))
    assert [r["rid"] for r in first] == ["precious"]
    # The retry after the lost response: a result fetch, not a re-pop.
    assert tx._roundtrip(dict(req)) == first
    assert tx.take_requests(3, 8) == []


def test_tcp_duplicated_post_result_lands_once(tcp_server):
    chaos = TransportChaos(duplicate=[("post_result", 1)])
    tx = TcpTransport(tcp_server.address, chaos=chaos, backoff_s=0.01)
    assert tx.post_result(5, 0, {"rid": "x", "out": [1]}) is True
    reader = TcpTransport(tcp_server.address)
    assert [r["rid"] for r in reader.take_results(8)] == ["x"]
    assert reader.take_results(8) == []


def test_tcp_delay_is_survived(tcp_server):
    """A delayed delivery (well under the op timeout) is just latency:
    the op lands once, no retry, no timeout."""
    chaos = TransportChaos(delay=[("append_health", 1)], delay_s=0.2)
    tx = TcpTransport(tcp_server.address, chaos=chaos, timeout_s=2.0)
    t0 = time.monotonic()
    tx.append_health_event("late", n=1)
    assert time.monotonic() - t0 >= 0.2
    assert len(TcpTransport(tcp_server.address)
               .read_health_events()) == 1
    stats = tx.stats()
    assert stats["retries"] == 0 and stats["timeouts"] == 0
    assert ("delay", "append_health", 1) in chaos.fired


def test_tcp_partition_raises_transport_error(tcp_server):
    chaos = TransportChaos(partition_after=2)
    tx = TcpTransport(tcp_server.address, chaos=chaos, backoff_s=0.01)
    tx.read_abort()
    tx.read_abort()
    with pytest.raises(TransportError):
        tx.read_abort()


def test_tcp_partitioned_rank_detected_as_dead_by_both_sides(tcp_server):
    """The connection-loss-is-peer-death contract, detector level: rank
    1's channel is severed; its peers declare it dead within
    ``peer_timeout_s`` (its beats stop advancing) and rank 1 itself
    escalates the outage to a self-abort naming the partition."""
    aborts0: list[str] = []
    aborts1: list[str] = []
    t0 = TcpTransport(tcp_server.address, backoff_s=0.01)
    chaos = TransportChaos(partition_after=20)
    t1 = TcpTransport(tcp_server.address, chaos=chaos, backoff_s=0.01,
                      max_tries=2)
    c0 = GangCoordinator(None, rank=0, world=2, transport=t0,
                         heartbeat_interval_s=0.05, peer_timeout_s=0.8,
                         check_self=False, on_abort=aborts0.append)
    c1 = GangCoordinator(None, rank=1, world=2, transport=t1,
                         heartbeat_interval_s=0.05, peer_timeout_s=0.8,
                         check_self=False, on_abort=aborts1.append)
    c0.start()
    c1.start()
    try:
        deadline = time.monotonic() + 8.0
        while (not aborts0 or not aborts1) \
                and time.monotonic() < deadline:
            c0.beat()
            c1.beat()
            time.sleep(0.05)
        assert aborts0 and "rank 1" in aborts0[0]
        assert aborts1 and "partitioned" in aborts1[0]
        abort = t0.read_abort()
        assert abort is not None and abort["by_rank"] == 0
    finally:
        c0.stop()
        c1.stop()


def test_make_transport_factory_validation(tmp_path):
    with pytest.raises(ValueError):
        make_transport("file")
    with pytest.raises(ValueError):
        make_transport("inproc")
    with pytest.raises(ValueError):
        make_transport("tcp")
    with pytest.raises(ValueError):
        make_transport("carrier-pigeon", gang_dir=tmp_path)
    with pytest.raises(ValueError):
        TcpTransport("no-port-here")
    hub = InProcHub()
    assert make_transport("inproc", hub=hub).backend == "inproc"
    assert make_transport("file", gang_dir=tmp_path).backend == "file"


def test_inproc_epoch_guard_fences_drained_members():
    """A zombie thread from a drained attempt (threads cannot be
    SIGKILLed) must not write into the next attempt's state: its
    epoch-bound handle raises once the supervisor clears."""
    hub = InProcHub()
    worker = InProcTransport(hub, bind_epoch=True)
    supervisor = InProcTransport(hub)  # the clearing side: unbound
    worker.publish_beat(0, {"rank": 0, "seq": 1, "step": 0})
    supervisor.clear_gang_state()
    with pytest.raises(TransportError):
        worker.publish_beat(0, {"rank": 0, "seq": 2, "step": 1})
    with pytest.raises(TransportError):
        worker.read_beats()
    # The next attempt's fresh handle works.
    fresh = InProcTransport(hub, bind_epoch=True)
    fresh.publish_beat(0, {"rank": 0, "seq": 1, "step": 0})
    assert supervisor.read_beat_payloads()[0]["seq"] == 1


def test_file_reads_never_create_the_directory(tmp_path):
    """A read-only consumer (gang_status on a typo'd or post-mortem
    path) must not mutate the filesystem: reads on a missing gang dir
    return empty, and the directory appears only on the first write."""
    gang = tmp_path / "never-written"
    tx = FileTransport(gang)
    assert tx.read_beats() == {}
    assert tx.read_abort() is None
    assert tx.read_health_events() == []
    assert tx.snapshot()["joins"] == {}
    assert not gang.exists()
    tx.publish_beat(0, {"rank": 0, "seq": 1, "step": 0})
    assert gang.exists()


def test_file_backend_layout_is_byte_compatible(tmp_path):
    """The transport writes the EXACT file layout the pre-transport
    readers (and PR 10 artifacts) use — same names, same payload
    shapes, ledgers fsynced as JSONL."""
    import json
    import os

    from distributed_machine_learning_tpu.runtime.coordinator import (
        read_abort,
        read_joins,
        read_restore_record,
    )
    from distributed_machine_learning_tpu.telemetry.aggregator import (
        read_beats,
        read_health_events,
    )

    gang = tmp_path / "gang"
    tx = FileTransport(gang)
    tx.publish_beat(2, {"rank": 2, "seq": 1, "step": 5, "beat_age": 0.0,
                        "suspended": False, "done": False,
                        "time": time.time()})
    tx.declare_abort("boom", 1, peer=2)
    tx.announce_join(3, {"rank": 3, "spare": False, "time": time.time()})
    tx.write_restore_record(2, {5})
    tx.append_health_event("restart", attempt=1, world=2)
    tx.append_fault_entry({"index": 0, "kind": "kill_rank", "rank": 0,
                           "at": 3})
    tx.append_consumed(2, {"step": 5, "ids": [1, 2]})
    names = set(os.listdir(gang))
    assert {"beat_rank2.json", "abort.json", "join_rank3.json",
            "restore_rank2.json", "gang_health.jsonl",
            "faults_fired.jsonl",
            "consumed_rank2.jsonl"} <= names
    # The legacy (pre-transport) readers parse every channel.
    assert read_beats(gang)[2]["step"] == 5
    assert read_abort(gang)["by_rank"] == 1
    assert read_joins(gang)[3]["spare"] is False
    assert read_restore_record(gang, 2) == {5}
    assert read_health_events(gang)[0]["kind"] == "restart"
    with open(gang / "consumed_rank2.jsonl") as f:
        assert json.loads(f.readline())["ids"] == [1, 2]


# ---------------------------------------------------------------------------
# Concurrent writers (ISSUE 15): the real-threads smoke complement to
# the layer-3 interleaving explorer
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", BACKENDS)
def test_concurrent_writers_exactly_once_and_mirror_order(name, tmp_path):
    """N real threads appending to ONE health ledger through each
    backend — tcp with every writer's first append dropped so the
    retry path is exercised — asserting every append applied exactly
    once, per-writer order preserved, and the durable mirror
    order-consistent with the authoritative ledger.  Layer 3 explores
    these interleavings deterministically; this is the uncontrolled
    real-scheduler smoke over the same invariants."""
    import json as _json

    from distributed_machine_learning_tpu.runtime.coordinator import (
        GANG_HEALTH_FILE,
    )

    n_writers, n_appends = 4, 5
    server = None
    if name == "file":
        gang = tmp_path / "gang"
        ledger = gang / GANG_HEALTH_FILE

        def make():
            return FileTransport(gang)
    elif name == "inproc":
        hub = InProcHub(mirror_dir=tmp_path / "mirror")
        ledger = tmp_path / "mirror" / GANG_HEALTH_FILE

        def make():
            return InProcTransport(hub)
    else:
        server = TcpGangServer(mirror_dir=tmp_path / "mirror").start()
        ledger = tmp_path / "mirror" / GANG_HEALTH_FILE

        def make():
            # Every writer's first append_health response is dropped:
            # the client retries with the SAME op_id and the server's
            # dedup store must absorb it.
            chaos = TransportChaos(drop=[("append_health", 1)])
            return TcpTransport(server.address, chaos=chaos,
                                backoff_s=0.01)
    try:
        errors: list[BaseException] = []

        def writer(i):
            try:
                tx = make()
                for j in range(n_appends):
                    tx.append_health_event("mark", w=i, n=j)
            except BaseException as e:
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(i,))
                   for i in range(n_writers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30.0)
        assert not errors, errors

        rows = make().read_health_events()
        keys = [(e["w"], e["n"]) for e in rows]
        want = [(i, j) for i in range(n_writers)
                for j in range(n_appends)]
        assert sorted(keys) == want, (
            "exactly-once broken: " + repr(sorted(keys)))
        for i in range(n_writers):
            mine = [n for (w, n) in keys if w == i]
            assert mine == sorted(mine), (
                f"writer {i}'s appends reordered: {mine}")
        with open(ledger) as f:
            mirror = [( _json.loads(line)["w"], _json.loads(line)["n"])
                      for line in f if line.strip()]
        assert mirror == keys, (
            "on-disk mirror order diverged from the ledger")
    finally:
        if server is not None:
            server.stop()


def test_tcp_stage_events_stamped_exactly_once_under_retries(tcp_server):
    """ISSUE 17: stage stamping lives in the base wrappers, ABOVE the
    op-id retry machinery — a dropped ``push_request``, a dropped
    ``take_requests`` (the destructive one), and a duplicated
    ``post_result`` each stamp their stage once per LOGICAL op, so a
    lossy wire can never double-stamp a journey.  The private monotonic
    anchor never crosses the wire with the payload."""
    from distributed_machine_learning_tpu.runtime.transport import (
        carry_stage_context,
        stamp_stage,
    )

    chaos = TransportChaos(drop=[("push_request", 1)])
    router = TcpTransport(tcp_server.address, chaos=chaos,
                          backoff_s=0.01)
    entry = {"rid": "j1", "prompt": [7], "epoch": 0, "dispatch": 1,
             "events": []}
    stamp_stage(entry, "admitted", "router")
    stamp_stage(entry, "queued", "router")
    stamp_stage(entry, "dispatched", "router")
    router.push_request(0, entry)   # dropped once -> retried
    assert router.stats()["retries"] >= 1
    assert ("drop", "push_request", 1) in chaos.fired
    # The caller's record keeps its own clock anchor (the router keeps
    # stamping on it later); the wire copy must not.
    assert "_mono_last" in entry

    wchaos = TransportChaos(drop=[("take_requests", 1)],
                            duplicate=[("post_result", 1)])
    worker = TcpTransport(tcp_server.address, chaos=wchaos,
                          backoff_s=0.01)
    (req,) = worker.take_requests(0, 8)
    assert worker.stats()["retries"] >= 1
    # One "taken" stamp despite the dropped-and-retried destructive op,
    # and dt None: the previous stamp was another process's clock.
    assert [e["stage"] for e in req["events"]] == [
        "admitted", "queued", "dispatched", "taken"]
    assert req["events"][-1] == {"stage": "taken", "by": "replica0",
                                 "dt": None, "disp": 1}
    assert all(e["dt"] is None or e["dt"] >= 0
               for e in req["events"])

    stamp_stage(req, "bound", "replica0", epoch=0)
    stamp_stage(req, "computed", "replica0")
    assert worker.post_result(0, 0, carry_stage_context(req, {
        "rid": "j1", "output": [7, 7]})) is True
    assert ("duplicate", "post_result", 1) in wchaos.fired

    reader = TcpTransport(tcp_server.address)
    (res,) = reader.take_results(8)
    assert reader.take_results(8) == []   # duplicated post landed once
    stages = [e["stage"] for e in res["events"]]
    assert stages == ["admitted", "queued", "dispatched", "taken",
                      "bound", "computed", "posted"]
    assert stages.count("posted") == 1
    assert res["events"][-1]["by"] == "replica0"
    assert res["events"][-1]["dt"] >= 0   # computed -> posted, one clock
    assert "_mono_last" not in res and "_mono_by" not in res
