"""Paged KV-cache allocator unit tests (ISSUE 19).

Every test calls ``check_invariants()`` after every mutating op — the
accounting identities (no double-booking, no leak, pledge consistency,
"sum of table entries == allocated blocks") are the allocator's whole
contract.
"""

import threading

import pytest

from distributed_machine_learning_tpu.inference.kv_blocks import (
    BlockAllocator,
    CacheExhausted,
    blocks_needed,
)


def _alloc(num_blocks=16, block_size=4):
    return BlockAllocator(num_blocks, block_size)


def test_blocks_needed_ceil():
    assert blocks_needed(1, 4) == 1
    assert blocks_needed(4, 4) == 1
    assert blocks_needed(5, 4) == 2
    assert blocks_needed(16, 4) == 4


def test_admit_append_free_lifecycle():
    a = _alloc(num_blocks=8, block_size=4)
    # Admit: prompt 6 tokens -> 2 prefill blocks; worst case 6+6=12
    # tokens -> 3 blocks pledged.
    table = a.admit("s", prompt_len=6, max_new=6)
    a.check_invariants()
    assert len(table) == 2
    assert a.length("s") == 6
    assert a.free_blocks() == 6          # 2 bound
    assert a.available_blocks() == 5     # 1 more pledged
    # Appends: slots 6, 7 stay in block 1; slot 8 binds block 2.
    assert a.append("s") == 6
    a.check_invariants()
    assert a.append("s") == 7
    a.check_invariants()
    assert len(a.table("s")) == 2
    assert a.append("s") == 8
    a.check_invariants()
    assert len(a.table("s")) == 3
    assert a.available_blocks() == 5     # pledge converted, not spent
    # Free: everything returns, pool back to pristine.
    freed = a.free("s")
    a.check_invariants()
    assert sorted(freed) == sorted(table + [a_id for a_id in freed
                                            if a_id not in table])
    assert a.free_blocks() == 8
    assert a.available_blocks() == 8
    assert a.sequences() == []


def test_append_beyond_reservation_refused():
    a = _alloc(num_blocks=8, block_size=4)
    a.admit("s", prompt_len=4, max_new=0)
    with pytest.raises(ValueError, match="reservation"):
        a.append("s")
    a.check_invariants()


def test_duplicate_and_unknown_sequences():
    a = _alloc()
    a.admit("s", 4, 4)
    with pytest.raises(ValueError, match="already admitted"):
        a.admit("s", 4, 4)
    with pytest.raises(KeyError):
        a.append("ghost")
    with pytest.raises(KeyError):
        a.free("ghost")
    a.check_invariants()


def test_block_reuse_after_retire():
    """Freed blocks are the first reused (LIFO free stack) — the
    warmest pages go to the next admitted sequence."""
    a = _alloc(num_blocks=8, block_size=4)
    t1 = a.admit("s1", prompt_len=8, max_new=0)   # binds 2 blocks
    a.admit("s2", prompt_len=8, max_new=0)
    a.check_invariants()
    freed = a.free("s1")
    a.check_invariants()
    assert freed == t1
    t3 = a.admit("s3", prompt_len=8, max_new=0)
    a.check_invariants()
    assert set(t3) == set(t1)  # exactly the retired sequence's blocks


def test_admission_rejection_at_exhaustion_and_recovery():
    a = _alloc(num_blocks=4, block_size=4)
    a.admit("s1", prompt_len=4, max_new=4)   # pledges 2
    a.admit("s2", prompt_len=4, max_new=4)   # pledges 2
    a.check_invariants()
    assert a.available_blocks() == 0
    with pytest.raises(CacheExhausted):
        a.admit("s3", prompt_len=1, max_new=0)
    a.check_invariants()
    # Rejection must leave no partial state behind.
    assert a.sequences() == ["s1", "s2"]
    a.free("s1")
    a.check_invariants()
    a.admit("s3", prompt_len=4, max_new=4)   # retry after free succeeds
    a.check_invariants()


def test_pledge_counts_against_admission_not_binding():
    """The worst case is pledged up front even though blocks bind
    lazily — an admitted sequence can never fail mid-decode."""
    a = _alloc(num_blocks=4, block_size=4)
    a.admit("s1", prompt_len=1, max_new=14)  # 1 bound, 4 pledged total
    a.check_invariants()
    assert a.free_blocks() == 3
    assert a.available_blocks() == 0
    with pytest.raises(CacheExhausted):
        a.admit("s2", prompt_len=1, max_new=0)
    # And the pledge is honored: 14 appends all succeed.
    for _ in range(14):
        a.append("s1")
        a.check_invariants()
    assert len(a.table("s1")) == 4


def test_fragmentation_bound():
    """Bound-but-unwritten slots are at most block_size-1 per live
    sequence — the paged layout's total waste is O(sequences), not
    O(batch x max_len)."""
    a = _alloc(num_blocks=32, block_size=8)
    for i, lp in enumerate([1, 3, 9, 17, 8, 15]):
        a.admit(f"s{i}", prompt_len=lp, max_new=0)
        a.check_invariants()
    st = a.stats()
    assert st["waste_slots"] <= (a.block_size - 1) * st["sequences"]
    # Exact check: waste is the sum of per-sequence tail gaps.
    expect = sum(
        blocks_needed(lp, 8) * 8 - lp for lp in [1, 3, 9, 17, 8, 15]
    )
    assert st["waste_slots"] == expect


def test_eos_early_exit_returns_unused_pledge():
    a = _alloc(num_blocks=8, block_size=4)
    a.admit("s", prompt_len=4, max_new=16)  # pledges 5 blocks
    assert a.available_blocks() == 3
    a.append("s")                           # binds block 2 of 5
    a.check_invariants()
    a.free("s")                             # EOS after 1 token
    a.check_invariants()
    assert a.available_blocks() == 8        # unused pledge released


def test_invariants_after_every_op_scripted_churn():
    """A deterministic churn of admits/appends/frees with the full
    invariant audit after every single operation."""
    a = _alloc(num_blocks=24, block_size=4)
    live = []
    ops = 0
    for round_ in range(6):
        for i in range(4):
            seq = f"r{round_}s{i}"
            lp = 1 + (3 * round_ + 5 * i) % 9
            mn = (7 * round_ + i) % 6
            try:
                a.admit(seq, prompt_len=lp, max_new=mn)
                live.append([seq, mn])
            except CacheExhausted:
                pass
            a.check_invariants()
            ops += 1
        for rec in live:
            for _ in range(min(rec[1], 2)):
                a.append(rec[0])
                rec[1] -= 1
                a.check_invariants()
                ops += 1
        # Retire half, oldest first.
        for seq, _ in live[: len(live) // 2]:
            a.free(seq)
            a.check_invariants()
            ops += 1
        live = live[len(live) // 2:]
    for seq, _ in live:
        a.free(seq)
        a.check_invariants()
    assert ops > 50
    assert a.free_blocks() == 24


def test_ragged_mix_beats_padded_capacity():
    """ISSUE 19 acceptance: the paged pool admits a ragged mix whose
    total token count exceeds what ``batch x max_len`` padding could
    hold in the same cache budget.

    Budget: 64 blocks x 16 slots = 1024 cache slots.  The mix: one
    256-token worst-case request plus 24 requests of 32 tokens each.
    A padded cache must size every slot at max_len=256, so the same
    budget holds floor(1024/256) = 4 sequences — at most 352 tokens of
    real sequence data (the 4 largest).  The paged pool admits all 25
    concurrently: 1024 tokens, zero waste."""
    budget_blocks, block_size = 64, 16
    a = BlockAllocator(budget_blocks, block_size)
    mix = [(32, 224)] + [(8, 24)] * 24        # (prompt, max_new)
    for i, (lp, mn) in enumerate(mix):
        a.admit(f"s{i}", prompt_len=lp, max_new=mn)
        a.check_invariants()
    assert len(a.sequences()) == len(mix)

    totals = sorted((lp + mn for lp, mn in mix), reverse=True)
    max_len = totals[0]
    budget_tokens = budget_blocks * block_size
    padded_capacity = budget_tokens // max_len      # sequences
    assert padded_capacity == 4
    assert len(mix) > padded_capacity
    # Total tokens of the admitted mix vs the most padding could host.
    mix_tokens = sum(totals)
    padding_best = sum(totals[:padded_capacity])
    assert mix_tokens == budget_tokens
    assert mix_tokens > padding_best
    # And the pledge is real: every sequence can decode to its cap.
    for i, (lp, mn) in enumerate(mix):
        for _ in range(mn):
            a.append(f"s{i}")
    a.check_invariants()
    assert a.free_blocks() == 0


def test_concurrent_admit_free_keeps_invariants():
    """Native-thread smoke (the exhaustive interleaving sweep is layer
    3's job): admitters and retirers hammer one pool."""
    a = BlockAllocator(32, 4)
    errs = []

    def churn(tid):
        try:
            for k in range(60):
                seq = (tid, k)
                try:
                    a.admit(seq, prompt_len=1 + (k % 7), max_new=k % 3)
                except CacheExhausted:
                    continue
                for _ in range(k % 3):
                    a.append(seq)
                a.free(seq)
        except Exception as e:  # pragma: no cover - failure path
            errs.append(e)

    threads = [threading.Thread(target=churn, args=(t,))
               for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    a.check_invariants()
    assert a.free_blocks() == 32
