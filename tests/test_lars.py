"""LARS optimizer and compressed-wire ring all-reduce."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from conftest import shard_map_compat as shard_map

from distributed_machine_learning_tpu.cli.common import init_model_and_state
from distributed_machine_learning_tpu.models.vgg import VGGTest
from distributed_machine_learning_tpu.runtime.mesh import make_mesh
from distributed_machine_learning_tpu.train.lars import LARSConfig, lars_update


def test_lars_trust_ratio_bounds_update():
    """The layer step norm is lr·trust·||w||/(1+wd) when gradients are
    huge — LARS' defining property: no layer can step further than a
    fixed fraction of its own weight norm."""
    cfg = LARSConfig(learning_rate=1.0, momentum=0.0, weight_decay=0.0)
    w = {"k": jnp.ones((10,)) * 2.0}  # ||w|| = 2*sqrt(10)
    m = {"k": jnp.zeros((10,))}
    huge = {"k": jnp.ones((10,)) * 1e6}
    new_w, _ = lars_update(w, m, huge, cfg)
    step_norm = float(jnp.linalg.norm(w["k"] - new_w["k"]))
    w_norm = float(jnp.linalg.norm(w["k"]))
    # step = lr·trust·(||w||/||g||)·g  →  ||step|| = lr·trust·||w||
    assert step_norm == pytest.approx(cfg.trust_coefficient * w_norm, rel=1e-4)


def test_lars_zero_norm_fallback_is_plain_lr():
    """Zero-norm leaves (zero grads here) take the PLAIN lr fallback —
    trust applies only to the adaptive ratio (apex/LARC convention), so
    zero-init biases are not ~1/trust-fold frozen versus SGD."""
    cfg = LARSConfig()
    w = {"k": jnp.ones((4,))}
    m = {"k": jnp.zeros((4,))}
    g = {"k": jnp.zeros((4,))}
    # fallback scale = 1: step = lr·wd·w
    new_w, _ = lars_update(w, m, g, cfg)
    np.testing.assert_allclose(
        np.asarray(new_w["k"]),
        1.0 - cfg.learning_rate * cfg.weight_decay,
        rtol=1e-5,
    )


def test_lars_train_step_runs():
    from distributed_machine_learning_tpu.train.step import make_train_step

    model = VGGTest()
    state = init_model_and_state(model, config=LARSConfig())
    step = make_train_step(model, augment=False, optimizer="lars")
    rng = np.random.default_rng(0)
    x = rng.integers(0, 256, (8, 32, 32, 3), dtype=np.uint8)
    y = rng.integers(0, 10, 8).astype(np.int32)
    state, loss = step(state, x, y)
    assert np.isfinite(float(loss))
    with pytest.raises(ValueError, match="unknown optimizer"):
        make_train_step(model, optimizer="adam")


def test_ring_wire_compression_close_to_exact():
    """bf16-wire ring all-reduce approximates the exact psum within bf16
    tolerance, and the strategy plumbing accepts wire_dtype."""
    from distributed_machine_learning_tpu.ops.ring import ring_all_reduce_flat
    from distributed_machine_learning_tpu.parallel.strategies import get_strategy

    n = 8
    mesh = make_mesh(n)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((n, 1000), dtype=np.float32))

    def reduce(wire):
        f = shard_map(
            lambda v: ring_all_reduce_flat(v[0], "batch", n, wire_dtype=wire),
            mesh=mesh,
            in_specs=P("batch"),
            out_specs=P(),
            check_vma=False,
        )
        return np.asarray(jax.jit(f)(x))

    exact = x.sum(axis=0)
    np.testing.assert_allclose(reduce(None), exact, rtol=1e-5, atol=1e-5)
    # bf16 wire: ~3 significant digits per hop; generous tolerance
    np.testing.assert_allclose(reduce(jnp.bfloat16), exact, rtol=0.05, atol=0.05)

    s = get_strategy("ring", wire_dtype="bfloat16")
    assert s.wire_dtype == "bfloat16"


def test_ring_wire_compression_is_rank_identical():
    """Every rank must end the compressed all-reduce with the SAME values
    (the owner quantizes its own chunk like receivers do) — otherwise
    replicated params drift apart across devices over training."""
    from distributed_machine_learning_tpu.ops.ring import ring_all_reduce_flat

    n = 8
    mesh = make_mesh(n)
    rng = np.random.default_rng(9)
    x = jnp.asarray(rng.standard_normal((n, 1000), dtype=np.float32))

    f = shard_map(
        lambda v: ring_all_reduce_flat(
            v[0], "batch", n, wire_dtype=jnp.bfloat16
        )[None],
        mesh=mesh,
        in_specs=P("batch"),
        out_specs=P("batch"),  # keep per-rank outputs for comparison
        check_vma=False,
    )
    per_rank = np.asarray(jax.jit(f)(x))  # [n, 1000]
    for r in range(1, n):
        np.testing.assert_array_equal(per_rank[0], per_rank[r])


def test_lars_checkpoint_roundtrip(tmp_path):
    """LARSConfig survives save/restore (the config class is recorded), and
    a cross-optimizer resume through the CLI path resets momentum instead
    of crashing or misapplying it."""
    from distributed_machine_learning_tpu.train.checkpoint import (
        restore_checkpoint,
        save_checkpoint,
    )

    model = VGGTest()
    state = init_model_and_state(model, config=LARSConfig(trust_coefficient=2e-3))
    path = save_checkpoint(tmp_path, state)
    restored = restore_checkpoint(path, abstract_state=state)
    assert isinstance(restored.config, LARSConfig)
    assert restored.config.trust_coefficient == pytest.approx(2e-3)

    # CLI cross-optimizer resume: sgd checkpoint + --optimizer lars runs
    # (momentum reset path) and prints the warning.
    from distributed_machine_learning_tpu.cli.common import (
        make_flag_parser,
        parse_flags,
        run_part,
    )

    sgd_state = init_model_and_state(model)
    save_checkpoint(tmp_path / "sgd_ckpt", sgd_state)
    parser = make_flag_parser("t")
    args = parse_flags(
        parser,
        ["--batch-size", "4", "--max-iters", "2", "--eval-batches", "1",
         "--model", "vggtest", "--eval-batch-size", "16",
         "--optimizer", "lars", "--resume", "--ckpt-dir",
         str(tmp_path / "sgd_ckpt")],
    )
    run_part("none", 4, use_bn=False, args=args)


@pytest.mark.slow
def test_distributed_resume_places_state_on_mesh(tmp_path, capsys):
    """Resuming a DISTRIBUTED run must re-place the restored (device-0
    committed) state onto the mesh; regression for the device-mismatch
    crash this produced."""
    from distributed_machine_learning_tpu.cli.common import (
        make_flag_parser,
        parse_flags,
        run_part,
    )

    base = ["--batch-size", "4", "--max-iters", "2", "--eval-batches", "1",
            "--model", "vggtest", "--eval-batch-size", "16",
            "--ckpt-dir", str(tmp_path)]
    parser = make_flag_parser("t")
    run_part("all_reduce", 4, use_bn=False, args=parse_flags(parser, base))
    run_part("all_reduce", 4, use_bn=False,
             args=parse_flags(parser, base + ["--resume"]))
    out = capsys.readouterr().out
    assert "Resumed from" in out
    assert out.count("Test set: Average loss:") == 2


def test_ring_empty_gradtree_is_noop():
    from distributed_machine_learning_tpu.ops.ring import ring_all_reduce

    out = ring_all_reduce({}, "batch", 8)
    assert out == {}


def test_lars_rejected_under_pipeline():
    # Stage-local leaf norms would silently change LARS's trust ratios
    # with the stage count (see parallel/pipeline.py guard).
    import numpy as np
    import pytest

    from distributed_machine_learning_tpu.models.transformer import TransformerLM
    from distributed_machine_learning_tpu.parallel.pipeline import (
        init_pipeline_state,
        make_pp_lm_train_step,
        microbatch,
        shard_pp_state,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh

    model = TransformerLM(vocab_size=32, d_model=16, n_layers=2, n_heads=2)
    mesh = make_mesh(2, ("pipe",))
    state = shard_pp_state(
        init_pipeline_state(model, config=LARSConfig()), mesh
    )
    step = make_pp_lm_train_step(model, mesh, num_microbatches=2)
    toks = np.zeros((4, 9), np.int32)
    px, py = microbatch(toks[:, :-1], toks[:, 1:], 2)
    with pytest.raises(ValueError, match="LARS"):
        step(state, px, py)
