"""Streaming telemetry subsystem (telemetry/): registry semantics,
crash-safe JSONL sinks, Chrome-trace spans, loop integration, and the
chaos-run acceptance — one attempt-tagged stream spanning a supervised
restart, with registry counters matching the run's FaultEvents exactly.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from distributed_machine_learning_tpu.telemetry import (
    JsonlSink,
    MetricsRegistry,
    SpanTracer,
    Telemetry,
    get_telemetry,
    read_jsonl,
    read_trace,
    set_telemetry,
)

TOOLS = os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")


# ---------------------------------------------------------------------------
# Registry (telemetry/registry.py)
# ---------------------------------------------------------------------------


def test_counter_gauge_semantics():
    reg = MetricsRegistry()
    c = reg.counter("steps_total")
    c.inc()
    c.inc(4)
    assert c.value == 5
    assert reg.counter("steps_total") is c  # get-or-create
    with pytest.raises(ValueError):
        c.inc(-1)  # counters are monotonic
    g = reg.gauge("queue_depth")
    g.set(3)
    g.set(1)
    assert g.value == 1


def test_labels_key_distinct_instruments():
    reg = MetricsRegistry()
    a = reg.counter("fault_events", kind="stalls")
    b = reg.counter("fault_events", kind="restarts")
    a.inc(2)
    b.inc(7)
    assert a is not b
    assert reg.counter("fault_events", kind="stalls").value == 2
    assert reg.counter("fault_events", kind="restarts").value == 7


def test_histogram_quantiles():
    reg = MetricsRegistry()
    h = reg.histogram("step_seconds", buckets=[0.1 * i for i in range(1, 11)])
    for v in np.linspace(0.05, 0.95, 100):
        h.observe(float(v))
    q = h.quantiles()
    assert h.count == 100
    assert abs(h.mean - 0.5) < 0.01
    # Fixed-bucket interpolation: right bucket, not exact rank.
    assert 0.4 <= q["p50"] <= 0.6
    assert 0.85 <= q["p95"] <= 1.0
    assert q["max"] == pytest.approx(0.95)
    # Observations past the last bound land in +inf; its quantile
    # reports the exact max rather than interpolating to infinity.
    h.observe(5.0)
    assert h.percentile(1.0) == 5.0


def test_histogram_empty_and_validation():
    reg = MetricsRegistry()
    h = reg.histogram("empty")
    assert h.quantiles() == {"p50": 0.0, "p95": 0.0, "p99": 0.0, "max": 0.0}
    with pytest.raises(ValueError):
        h.percentile(1.5)


def test_prometheus_export_format():
    reg = MetricsRegistry()
    reg.counter("fault_events", kind="stalls").inc(3)
    reg.gauge("examples_per_s").set(123.0)
    h = reg.histogram("step_seconds", buckets=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    text = reg.to_prometheus()
    assert '# TYPE fault_events counter' in text
    assert 'fault_events{kind="stalls"} 3' in text
    assert "examples_per_s 123.0" in text
    assert 'step_seconds_bucket{le="+Inf"} 2' in text
    assert "step_seconds_count 2" in text


def _parse_prometheus(text):
    """A minimal exposition-format parser for round-trip assertions:
    {(name, ((label, value), ...)): float}, plus {family: type}.

    Label values are matched with the escape-aware pattern
    ``(?:[^"\\\\]|\\\\.)*`` (a quote inside a value is always written
    escaped, so an unescaped quote really ends the value) and unescaped
    in a SINGLE pass — sequential str.replace would corrupt values like
    a literal backslash-n, and splitting on '",' would cut any value
    containing a quote-then-comma.
    """
    import re

    label_re = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

    def unescape(v):
        return re.sub(r"\\(.)",
                      lambda m: "\n" if m.group(1) == "n" else m.group(1),
                      v)

    series, types = {}, {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            _, _, fam, kind = line.split(" ")
            assert fam not in types, f"duplicate TYPE for {fam}"
            types[fam] = kind
            continue
        head, _, value = line.rpartition(" ")
        labels = ()
        if "{" in head:
            name, _, rest = head.partition("{")
            labels = tuple((k, unescape(v))
                           for k, v in label_re.findall(rest.rstrip("}")))
        else:
            name = head
        key = (name, labels)
        assert key not in series, f"duplicate series {key}"
        series[key] = float(value)
    return series, types


def test_prometheus_labeled_round_trip_with_escaping(tmp_path):
    """The textfile export must survive hostile label values (quotes,
    backslashes, newlines — an abort reason or fault spec carried as a
    label) and parse back to the exact instrument values."""
    from distributed_machine_learning_tpu.telemetry import (
        write_prometheus,
    )

    reg = MetricsRegistry()
    hostile = 'rank "1"\\fault\nspec'
    tricky = 'a",b\\n'  # quote-then-comma + literal backslash-n
    reg.counter("gang_straggler", rank="1").inc(2)
    reg.counter("fault_events", kind=hostile).inc(5)
    reg.counter("fault_events", kind=tricky).inc(1)
    reg.gauge("gang_skew_ratio", why='a "quoted" reason').set(7.5)
    text = reg.to_prometheus()
    for line in text.splitlines():
        assert "\n" not in line  # the raw newline must be escaped away
    assert r"\n" in text and r"\"" in text
    series, types = _parse_prometheus(text)
    assert types["gang_straggler"] == "counter"
    assert types["gang_skew_ratio"] == "gauge"
    assert series[("gang_straggler", (("rank", "1"),))] == 2
    assert series[("fault_events", (("kind", hostile),))] == 5
    assert series[("fault_events", (("kind", tricky),))] == 1
    assert series[("gang_skew_ratio",
                   (("why", 'a "quoted" reason'),))] == 7.5
    # And the atomic file writer emits the same parseable payload.
    write_prometheus(tmp_path / "m.prom", reg)
    assert (tmp_path / "m.prom").read_text() == text


def test_prometheus_histogram_bucket_round_trip():
    """Labeled histograms: bucket bounds strictly ascending with +Inf
    last, cumulative counts non-decreasing and ending at _count, _sum
    matching the observations — per label series, under one TYPE."""
    reg = MetricsRegistry()
    # Creation order descends on purpose: export must still ascend.
    for shard in ("a", "b"):
        h = reg.histogram("step_seconds", buckets=[1.0, 0.1, 0.5],
                          shard=shard)
        obs = [0.05, 0.3, 0.3, 0.7, 2.0] if shard == "a" else [0.2]
        for v in obs:
            h.observe(v)
    text = reg.to_prometheus()
    assert text.count("# TYPE step_seconds histogram") == 1
    series, _ = _parse_prometheus(text)
    for shard, total, summed in (("a", 5, 3.35), ("b", 1, 0.2)):
        sel = {
            dict(labels)["le"]: v
            for (name, labels), v in series.items()
            if name == "step_seconds_bucket"
            and dict(labels)["shard"] == shard
        }
        bounds = [b for b in sel if b != "+Inf"]
        assert [float(b) for b in bounds] == sorted(float(b)
                                                    for b in bounds)
        assert list(sel)[-1] == "+Inf"  # +Inf closes the series
        cum = [sel[b] for b in sel]
        assert cum == sorted(cum)  # cumulative counts never decrease
        assert cum[-1] == total
        assert series[("step_seconds_count",
                       (("shard", shard),))] == total
        assert series[("step_seconds_sum",
                       (("shard", shard),))] == pytest.approx(summed)


def test_registry_snapshot_shape():
    reg = MetricsRegistry()
    reg.counter("c").inc()
    reg.gauge("g").set(2.0)
    reg.histogram("h").observe(0.01)
    snap = reg.snapshot()
    assert snap["counters"][0] == {"name": "c", "labels": {}, "value": 1}
    assert snap["gauges"][0]["value"] == 2.0
    hist = snap["histograms"][0]
    assert hist["count"] == 1 and "p95" in hist and "max" in hist


# ---------------------------------------------------------------------------
# JSONL sink (telemetry/sink.py)
# ---------------------------------------------------------------------------


def test_sink_appends_and_flushes(tmp_path):
    p = tmp_path / "m.jsonl"
    with JsonlSink(p, flush_every=2, enabled=True) as sink:
        sink.write({"step": 0})
        sink.write({"step": 1})  # hits the flush threshold
        # Rows up to the flush boundary are durable BEFORE close.
        assert len(read_jsonl(p)) == 2
        sink.write({"step": 2})
    assert [r["step"] for r in read_jsonl(p)] == [0, 1, 2]


def test_sink_append_mode_survives_restart(tmp_path):
    # A second sink on the same path (the supervisor-restart case) must
    # APPEND to the survivor rows, never truncate them.
    p = tmp_path / "m.jsonl"
    with JsonlSink(p, flush_every=1, enabled=True) as s:
        s.write({"attempt": 0, "step": 0})
    with JsonlSink(p, flush_every=1, enabled=True) as s:
        s.write({"attempt": 1, "step": 0})
    assert [r["attempt"] for r in read_jsonl(p)] == [0, 1]


def test_sink_disabled_writes_nothing(tmp_path):
    p = tmp_path / "m.jsonl"
    with JsonlSink(p, enabled=False) as sink:
        sink.write({"step": 0})
    assert not p.exists()


def test_read_jsonl_tolerates_torn_final_line(tmp_path):
    # A kill mid-write leaves one partial trailing line — the reader
    # must return every complete row and drop the torn one.
    p = tmp_path / "m.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"step": 0}) + "\n")
        f.write(json.dumps({"step": 1}) + "\n")
        f.write('{"step": 2, "loss"')  # torn by the simulated kill
    rows = read_jsonl(p)
    assert [r["step"] for r in rows] == [0, 1]


def test_read_jsonl_raises_on_mid_file_corruption(tmp_path):
    p = tmp_path / "m.jsonl"
    with open(p, "w") as f:
        f.write('{"step": 0}\n')
        f.write("NOT JSON\n")
        f.write('{"step": 2}\n')
    with pytest.raises(json.JSONDecodeError):
        read_jsonl(p)


def test_sink_validates_flush_every(tmp_path):
    with pytest.raises(ValueError):
        JsonlSink(tmp_path / "x.jsonl", flush_every=0)


def test_sink_reopen_truncates_torn_final_line(tmp_path):
    # A restart must not weld its first row onto the dead run's torn
    # final line (that would corrupt BOTH and move the damage mid-file,
    # where read_jsonl rightly raises).
    p = tmp_path / "m.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"attempt": 0, "step": 0}) + "\n")
        f.write('{"attempt": 0, "step"')  # killed mid-write
    with JsonlSink(p, flush_every=1, enabled=True) as s:
        s.write({"attempt": 1, "step": 0})
    rows = read_jsonl(p, tolerate_truncation=False)  # strictly clean now
    assert [(r["attempt"], r["step"]) for r in rows] == [(0, 0), (1, 0)]


def test_prometheus_one_type_line_per_family():
    # The exposition format allows ONE `# TYPE` per metric family;
    # promtool rejects duplicates, so multi-kind fault counters (every
    # chaos run) must group under a single header.
    reg = MetricsRegistry()
    reg.counter("fault_events", kind="stalls").inc()
    reg.counter("fault_events", kind="restarts").inc(2)
    text = reg.to_prometheus()
    assert text.count("# TYPE fault_events counter") == 1
    assert 'fault_events{kind="stalls"} 1' in text
    assert 'fault_events{kind="restarts"} 2' in text


# ---------------------------------------------------------------------------
# Chrome-trace span tracer (telemetry/tracer.py)
# ---------------------------------------------------------------------------


def test_tracer_closed_file_is_valid_json_with_nested_spans(tmp_path):
    p = tmp_path / "trace.json"
    tr = SpanTracer(p, flush_every=1, enabled=True)
    with tr.span("outer", step=0):
        with tr.span("inner", step=0):
            pass
    tr.instant("fault_stalls")
    tr.close()
    events = json.loads(p.read_text())  # strict JSON after a clean close
    assert isinstance(events, list) and len(events) == 3
    by_name = {e["name"]: e for e in events}
    outer, inner = by_name["outer"], by_name["inner"]
    assert outer["ph"] == "X" and inner["ph"] == "X"
    # Proper nesting: the inner span's [ts, ts+dur] lies within the
    # outer's — that containment is what the viewer renders as a stack.
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-6
    assert by_name["fault_stalls"]["ph"] == "i"


def test_tracer_unterminated_trace_still_loads(tmp_path):
    # No close() — the crash case.  The JSON Array Format's trailing ]
    # is optional for viewers; read_trace applies the same tolerance.
    p = tmp_path / "trace.json"
    tr = SpanTracer(p, flush_every=1, enabled=True)
    with tr.span("step_dispatch", step=0):
        pass
    with tr.span("device_block", step=0):
        pass
    tr.flush()
    with pytest.raises(json.JSONDecodeError):
        json.loads(p.read_text())  # not yet strict JSON...
    names = [e["name"] for e in read_trace(p)]  # ...but fully readable
    assert names == ["step_dispatch", "device_block"]


def test_tracer_reopen_after_clean_close_stays_one_valid_array(tmp_path):
    # Run 1 closes the array; run 2 (same dir, the append/resume
    # contract) must strip the terminator before appending — events
    # after a ']' are rejected by viewers (unlike a missing ']').
    p = tmp_path / "trace.json"
    tr1 = SpanTracer(p, flush_every=1, enabled=True)
    with tr1.span("run1"):
        pass
    tr1.close()
    tr2 = SpanTracer(p, flush_every=1, enabled=True)
    with tr2.span("run2"):
        pass
    tr2.close()
    events = json.loads(p.read_text())  # strictly valid, ONE array
    assert [e["name"] for e in events] == ["run1", "run2"]
    # And chronological: run2's anchor is later wall-clock.
    assert events[0]["ts"] <= events[1]["ts"]


def test_tracer_reopen_after_torn_event_truncates_it(tmp_path):
    p = tmp_path / "trace.json"
    tr1 = SpanTracer(p, flush_every=1, enabled=True)
    with tr1.span("survivor"):
        pass
    tr1.flush()
    with open(p, "a") as f:
        f.write(',\n{"name": "torn_by_kil')  # killed mid-event
    tr2 = SpanTracer(p, flush_every=1, enabled=True)
    with tr2.span("after_restart"):
        pass
    tr2.close()
    events = json.loads(p.read_text())
    assert [e["name"] for e in events] == ["survivor", "after_restart"]


def test_tracer_span_records_error_and_max_events(tmp_path):
    p = tmp_path / "trace.json"
    tr = SpanTracer(p, flush_every=1, enabled=True, max_events=2)
    with pytest.raises(RuntimeError):
        with tr.span("restart_attempt", attempt=0):
            raise RuntimeError("injected")
    tr.instant("second")
    tr.instant("dropped-by-cap")
    tr.close()
    events = json.loads(p.read_text())
    assert len(events) == 2  # the cap held
    assert events[0]["args"]["error"] == "RuntimeError"


# ---------------------------------------------------------------------------
# Telemetry facade (attempt tagging, registry export)
# ---------------------------------------------------------------------------


def test_telemetry_log_step_tags_attempt_and_exports(tmp_path):
    with Telemetry(tmp_path, flush_every=1) as tel:
        tel.registry.counter("fault_events", kind="stalls").inc()
        tel.log_step(0, iter_s=0.1)
        tel.set_attempt(1)
        tel.log_step(0, iter_s=0.2)
    rows = read_jsonl(tmp_path / "metrics.jsonl")
    assert [r["attempt"] for r in rows] == [0, 1]
    snap = json.loads((tmp_path / "registry.json").read_text())
    assert snap["counters"][0]["value"] == 1
    assert 'fault_events{kind="stalls"} 1' in (
        (tmp_path / "metrics.prom").read_text()
    )


def test_telemetry_resumes_attempt_numbering_from_disk(tmp_path):
    # A re-executed process (external supervisor, os._exit restart) must
    # continue the attempt sequence already on disk, not restart at 0.
    with Telemetry(tmp_path, flush_every=1) as tel:
        tel.set_attempt(2)
        tel.log_step(5, iter_s=0.1)
    tel2 = Telemetry(tmp_path, flush_every=1)
    assert tel2.attempt == 3
    # set_attempt never moves backwards: the in-process supervisor's
    # attempt 0 keeps the resumed offset.
    tel2.set_attempt(0)
    assert tel2.attempt == 3
    tel2.close()


def test_telemetry_off_by_default():
    assert get_telemetry() is None


def test_telemetry_resume_rehydrates_counter_totals(tmp_path):
    # A re-exec'd process resuming into the same dir must extend the
    # exported counter totals, not clobber registry.json back to zero —
    # same append-not-truncate contract as the stream artifacts.
    with Telemetry(tmp_path, flush_every=1) as tel:
        tel.registry.counter("fault_events", kind="ckpt_kills").inc()
        tel.log_step(0, iter_s=0.1)
    with Telemetry(tmp_path, flush_every=1) as tel2:
        assert tel2.attempt == 1
        tel2.registry.counter("fault_events", kind="ckpt_kills").inc()
        tel2.log_step(0, iter_s=0.1)
    snap = json.loads((tmp_path / "registry.json").read_text())
    kills = [c["value"] for c in snap["counters"]
             if c["labels"].get("kind") == "ckpt_kills"]
    assert kills == [2]  # both processes' kills, one counter


# ---------------------------------------------------------------------------
# train_epoch integration (phase spans, throughput, zero-cost off)
# ---------------------------------------------------------------------------


class _S:
    def __init__(self, step=0):
        self.step = step


def _fake_step(s, x, y):
    return _S(s.step + 1), 0.0


def _img_batches(n=4, b=4):
    r = np.random.default_rng(0)
    return [(r.integers(0, 256, (b, 8, 8, 3)).astype(np.uint8),
             r.integers(0, 10, b).astype(np.int32)) for _ in range(n)]


def test_train_epoch_emits_phase_spans_and_rows(tmp_path):
    from distributed_machine_learning_tpu.train.loop import train_epoch

    with Telemetry(tmp_path, flush_every=1) as tel:
        tel.flops_per_example = 1e6
        state, _ = train_epoch(
            _fake_step, _S(), _img_batches(3),
            place_batch=lambda x, y: (x, y), max_iters=10,
            loss_print_every=10**9, telemetry=tel,
        )
    assert state.step == 3
    rows = read_jsonl(tmp_path / "metrics.jsonl")
    assert len(rows) == 3
    for r in rows:
        assert r["attempt"] == 0
        for k in ("iter_s", "data_wait_s", "place_s", "dispatch_s",
                  "block_s", "examples_per_s", "mfu"):
            assert k in r, f"missing {k}"
        assert "tokens_per_s" not in r  # image batches have no tokens
    # The first (timer-excluded, compile-bearing) iteration is tagged so
    # quantile consumers can keep it out of the tail.
    assert rows[0].get("warmup") is True
    assert all("warmup" not in r for r in rows[1:])
    names = {e["name"] for e in read_trace(tmp_path / "trace.json")}
    assert {"data_wait", "place_batch", "step_dispatch",
            "device_block"} <= names
    snap = json.loads((tmp_path / "registry.json").read_text())
    counters = {c["name"]: c["value"] for c in snap["counters"]}
    assert counters["steps_total"] == 3
    hists = {h["name"]: h for h in snap["histograms"]}
    # Histogram mirrors the timer's warm-up protocol: 3 steps, first
    # excluded — registry quantiles and summary() describe one sample.
    assert hists["step_seconds"]["count"] == 2


def test_train_epoch_applies_static_step_counters(tmp_path):
    """``Telemetry.step_counters``: static per-step increments the CLI
    registers (ring_wire_bytes) accumulate once per completed step and
    land in the registry snapshot next to the compression-ratio gauge —
    the surface trace_summary and gang benches read bytes-saved from."""
    from distributed_machine_learning_tpu.train.loop import train_epoch

    with Telemetry(tmp_path, flush_every=1) as tel:
        tel.step_counters["ring_wire_bytes"] = 1000
        tel.registry.gauge("ring_compression_ratio").set(4.0)
        train_epoch(
            _fake_step, _S(), _img_batches(3),
            place_batch=lambda x, y: (x, y), max_iters=10,
            loss_print_every=10**9, telemetry=tel,
        )
    snap = json.loads((tmp_path / "registry.json").read_text())
    counters = {c["name"]: c["value"] for c in snap["counters"]}
    assert counters["ring_wire_bytes"] == 3000
    gauges = {g["name"]: g["value"] for g in snap["gauges"]}
    assert gauges["ring_compression_ratio"] == 4.0
    # trace_summary's ring section renders from exactly this snapshot.
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "trace_summary.py"),
         str(tmp_path)],
        capture_output=True, text=True, check=True, timeout=60,
    ).stdout
    assert "Ring wire compression" in out
    assert "3,000" in out and "compression ratio        4.00x" in out


def test_train_epoch_applies_labeled_step_counters(tmp_path):
    """Round-11 satellite: a ``step_counters`` entry may be a list of
    ``(labels, value)`` sub-counters — the per-AXIS ring_wire_bytes
    split a ``--ring-topology`` run registers — and trace_summary
    renders the inner/outer breakdown under the ring section."""
    from distributed_machine_learning_tpu.train.loop import train_epoch

    with Telemetry(tmp_path, flush_every=1) as tel:
        tel.step_counters["ring_wire_bytes"] = [
            ({"axis": "inner"}, 800), ({"axis": "outer"}, 200),
        ]
        train_epoch(
            _fake_step, _S(), _img_batches(3),
            place_batch=lambda x, y: (x, y), max_iters=10,
            loss_print_every=10**9, telemetry=tel,
        )
    snap = json.loads((tmp_path / "registry.json").read_text())
    wire = {c["labels"]["axis"]: c["value"] for c in snap["counters"]
            if c["name"] == "ring_wire_bytes"}
    assert wire == {"inner": 2400, "outer": 600}
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "trace_summary.py"),
         str(tmp_path)],
        capture_output=True, text=True, check=True, timeout=60,
    ).stdout
    assert "Ring wire compression" in out
    assert "axis=inner" in out and "axis=outer" in out
    assert "(80%)" in out and "(20%)" in out


def test_train_epoch_token_batches_report_tokens_per_s(tmp_path):
    from distributed_machine_learning_tpu.train.loop import train_epoch

    r = np.random.default_rng(1)
    batches = [(r.integers(0, 32, (2, 16)).astype(np.int32),
                r.integers(0, 32, (2, 16)).astype(np.int32))
               for _ in range(2)]
    with Telemetry(tmp_path, flush_every=1) as tel:
        train_epoch(_fake_step, _S(), batches, max_iters=10,
                    loss_print_every=10**9, telemetry=tel)
    rows = read_jsonl(tmp_path / "metrics.jsonl")
    assert all(r["tokens_per_s"] > 0 for r in rows)


def test_train_epoch_telemetry_off_is_inert(tmp_path, monkeypatch):
    # Off (the default): no telemetry object is consulted at all — the
    # loop must never touch a Telemetry method, so patching every
    # instrument to a tripwire proves the no-op guard is a guard.
    from distributed_machine_learning_tpu.train.loop import train_epoch

    assert get_telemetry() is None
    monkeypatch.chdir(tmp_path)

    def boom(*a, **k):
        raise AssertionError("telemetry touched while off")

    monkeypatch.setattr(Telemetry, "log_step", boom)
    monkeypatch.setattr(Telemetry, "span", boom)
    state, _ = train_epoch(_fake_step, _S(), _img_batches(2),
                           max_iters=10, loss_print_every=10**9)
    assert state.step == 2
    assert os.listdir(tmp_path) == []  # and no files appeared


def test_async_checkpoint_save_records_telemetry(tmp_path):
    # --async-ckpt is the path built BECAUSE saves are slow; it must not
    # be the one path whose saves are invisible to the telemetry.
    from distributed_machine_learning_tpu.cli.common import (
        init_model_and_state,
    )
    from distributed_machine_learning_tpu.models.vgg import VGGTest
    from distributed_machine_learning_tpu.train.checkpoint import (
        AsyncCheckpointWriter,
    )

    state = init_model_and_state(VGGTest(use_bn=False))
    tel = Telemetry(tmp_path / "tel", flush_every=1)
    prev = set_telemetry(tel)
    try:
        with AsyncCheckpointWriter() as w:
            w.save(tmp_path / "ck", state)
    finally:
        set_telemetry(prev)
        tel.close()
    names = [e["name"] for e in read_trace(tmp_path / "tel" / "trace.json")]
    assert "checkpoint_save" in names
    snap = json.loads((tmp_path / "tel" / "registry.json").read_text())
    counters = {c["name"]: c["value"] for c in snap["counters"]}
    assert counters["checkpoint_saves_total"] == 1
    assert counters["checkpoint_save_bytes_total"] > 0


# ---------------------------------------------------------------------------
# MetricsLogger streaming shim (utils/profiling.py satellite)
# ---------------------------------------------------------------------------


def test_metrics_logger_streams_rows_as_they_land(tmp_path):
    from distributed_machine_learning_tpu.utils.profiling import (
        MetricsLogger,
    )

    p = tmp_path / "m.jsonl"
    m = MetricsLogger(path=p, flush_every=1)
    m.log(step=1, loss=2.5)
    # On disk BEFORE save() — the crash-loss fix.
    assert len(read_jsonl(p)) == 1
    m.log(step=2, loss=2.4)
    m.save(p)  # flush, not rewrite
    assert [r["step"] for r in read_jsonl(p)] == [1, 2]
    # Streaming mode: the DISK is the buffer — no unbounded in-memory
    # duplicate of a long run's history; `count` carries the tally.
    assert m.count == 2 and m.rows == []
    # And a save to some OTHER path has nothing buffered to write with:
    # it must refuse loudly, not silently produce an empty file.
    with pytest.raises(ValueError):
        m.save(tmp_path / "elsewhere.jsonl")


def test_metrics_logger_streaming_save_appends_not_truncates(tmp_path):
    from distributed_machine_learning_tpu.utils.profiling import (
        MetricsLogger,
    )

    p = tmp_path / "m.jsonl"
    m0 = MetricsLogger(path=p, flush_every=1)
    m0.log(step=1, attempt=0)
    m0.save(p)
    # The restarted (resumed) process's logger appends to the survivor
    # rows; append=True is what the CLI passes under --resume.
    m1 = MetricsLogger(path=p, flush_every=1, append=True)
    m1.log(step=1, attempt=1)
    m1.save(p)
    assert [r["attempt"] for r in read_jsonl(p)] == [0, 1]
    # A FRESH run (append=False, the default) truncates — two unrelated
    # runs must not silently interleave in one file.
    m2 = MetricsLogger(path=p, flush_every=1)
    m2.log(step=1, attempt=0)
    m2.save(p)
    assert len(read_jsonl(p)) == 1


def test_metrics_logger_csv_stays_buffered(tmp_path):
    from distributed_machine_learning_tpu.utils.profiling import (
        MetricsLogger,
    )

    p = tmp_path / "m.csv"
    m = MetricsLogger(path=p, flush_every=1)
    m.log(step=1, loss=1.0)
    assert not p.exists()  # CSV cannot stream (union-of-columns header)
    m.save(p)
    assert p.read_text().startswith("step,")


# ---------------------------------------------------------------------------
# get_logger satellite (utils/logging.py)
# ---------------------------------------------------------------------------


def test_get_logger_does_not_propagate_to_root(capsys):
    import logging

    from distributed_machine_learning_tpu.utils.logging import get_logger

    root_records = []
    handler = logging.Handler()
    handler.emit = lambda record: root_records.append(record)
    logging.getLogger().addHandler(handler)
    try:
        logger = get_logger("dml_tpu_prop_test")
        assert logger.propagate is False
        logger.info("hello once")
        assert root_records == []  # a configured root would double-print
    finally:
        logging.getLogger().removeHandler(handler)


def test_get_logger_is_idempotent():
    from distributed_machine_learning_tpu.utils.logging import get_logger

    a = get_logger("dml_tpu_idem")
    b = get_logger("dml_tpu_idem")
    assert a is b and len(a.handlers) == 1


# ---------------------------------------------------------------------------
# IterationTimer percentiles satellite (utils/timing.py, bench/harness.py)
# ---------------------------------------------------------------------------


def test_percentile_stats_exact():
    from distributed_machine_learning_tpu.utils.timing import (
        percentile,
        percentile_stats,
    )

    xs = [float(i) for i in range(1, 101)]
    assert percentile(xs, 0.5) == pytest.approx(50.5)
    s = percentile_stats(xs)
    assert s["p95"] == pytest.approx(95.05)
    assert s["max"] == 100.0
    assert percentile_stats([]) == {"p50": 0.0, "p95": 0.0, "p99": 0.0,
                                    "max": 0.0}
    with pytest.raises(ValueError):
        percentile(xs, 2.0)


def test_iteration_timer_summary_includes_tail():
    from distributed_machine_learning_tpu.utils.timing import IterationTimer

    t = IterationTimer(skip_first=0)
    t.times = [0.1, 0.2, 0.3, 1.0]
    p = t.percentiles()
    assert p["max"] == 1.0 and 0.1 <= p["p50"] <= 0.3
    text = t.summary()
    assert "Total execution time is" in text  # reference lines intact
    assert "p50/p95/p99/max" in text


def test_timed_scan_epoch_fills_stats(rng):
    import jax.numpy as jnp

    from distributed_machine_learning_tpu.bench.harness import (
        timed_scan_epoch,
    )

    def step(c, x, y):
        return c + jnp.sum(x) + jnp.sum(y), jnp.sum(x) * 0.0

    xs = jnp.asarray(rng.normal(size=(3, 2)).astype(np.float32))
    ys = jnp.asarray(rng.normal(size=(3, 2)).astype(np.float32))
    stats = {}
    best, _, _ = timed_scan_epoch(step, jnp.float32(0.0), xs, ys, reps=2,
                                  chain=2, stats=stats)
    # Longest-chain regime only: the 1-dispatch reps carry the full
    # dispatch round-trip the chained ones amortize — pooling them
    # would make "p95" measure RTT, not step stragglers.
    assert stats["samples"] == 2
    assert 0 < stats["p50_s"] <= stats["p95_s"] <= stats["max_s"]
    assert best > 0


# ---------------------------------------------------------------------------
# tools/trace_summary.py smoke (tier-1: the artifact format cannot drift)
# ---------------------------------------------------------------------------


def _make_telemetry_dir(tmp_path):
    with Telemetry(tmp_path, flush_every=1) as tel:
        tel.registry.counter("fault_events", kind="restarts").inc()
        for i in range(6):
            with tel.span("data_wait", step=i):
                pass
            with tel.span("step_dispatch", step=i):
                pass
            tel.log_step(
                i, batch=i, iter_s=0.01 * (i + 1), data_wait_s=0.001,
                place_s=0.0, dispatch_s=0.005, block_s=0.004,
                examples_per_s=100.0,
            )
    return tmp_path


def test_trace_summary_smoke(tmp_path):
    d = _make_telemetry_dir(tmp_path)
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "trace_summary.py"), str(d),
         "--top", "3"],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert "Phase time shares" in out.stdout
    assert "data_wait" in out.stdout and "step_dispatch" in out.stdout
    assert "slowest steps" in out.stdout
    assert "6 step rows" in out.stdout
    assert "restarts" in out.stdout  # fault counter section
    # The slowest step is the last one (iter_s grows with i).
    assert "step      5" in out.stdout


def test_trace_summary_tolerates_crashed_artifacts(tmp_path):
    d = _make_telemetry_dir(tmp_path)
    # Simulate a kill mid-write on BOTH artifacts.
    with open(d / "metrics.jsonl", "a") as f:
        f.write('{"step": 99, "iter_s"')
    trace = (d / "trace.json").read_text()
    (d / "trace.json").write_text(trace.rstrip().rstrip("]").rstrip()
                                  + ',\n{"name": "torn')
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "trace_summary.py"), str(d)],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert "6 step rows" in out.stdout  # torn row dropped, not fatal


# ---------------------------------------------------------------------------
# CLI flags + the chaos acceptance run
# ---------------------------------------------------------------------------


def test_cli_telemetry_flags_parse_and_validate():
    from distributed_machine_learning_tpu.cli.common import (
        make_flag_parser,
        parse_flags,
    )

    parser = make_flag_parser("test")
    args = parse_flags(parser, [])
    assert args.telemetry_dir is None  # off by default
    assert args.telemetry_flush_every == 20
    args = parse_flags(parser, ["--telemetry-dir", "/tmp/t",
                                "--telemetry-flush-every", "5"])
    assert args.telemetry_dir == "/tmp/t"
    assert args.telemetry_flush_every == 5
    with pytest.raises(SystemExit):
        parse_flags(parser, ["--telemetry-flush-every", "0"])


def test_lm_cli_has_telemetry_flags():
    from distributed_machine_learning_tpu.cli.lm import make_parser

    args = make_parser().parse_args([])
    assert args.telemetry_dir is None


@pytest.mark.faultinject
def test_part_cli_chaos_run_yields_one_attempt_tagged_timeline(tmp_path,
                                                               capsys):
    """The PR-2 acceptance keystone: a PR-1 chaos run with
    --telemetry-dir yields ONE metrics stream whose rows span both
    attempts (attempt-0 rows intact after the restart), a Chrome trace
    containing restart_attempt and per-step phase spans, and registry
    counters matching the run's FaultEvents totals exactly."""
    from distributed_machine_learning_tpu.cli import part1

    tel_dir = tmp_path / "tel"
    ck = tmp_path / "ck"
    part1.main([
        "--batch-size", "4", "--max-iters", "3", "--epochs", "2",
        "--model", "vggtest", "--eval-batches", "0",
        "--data-root", str(tmp_path), "--ckpt-dir", str(ck),
        "--resume", "auto", "--max-restarts", "2",
        "--guard-nonfinite", "--loader-retries", "2",
        "--faults", "kill_ckpt@1,nan@2,raise@4",
        "--telemetry-dir", str(tel_dir), "--telemetry-flush-every", "1",
    ])
    out = capsys.readouterr().out
    assert "Telemetry written to" in out
    assert get_telemetry() is None  # uninstalled after the run

    # One metrics stream spanning both attempts; the pre-restart
    # (attempt-0) rows were appended to, never truncated.
    rows = read_jsonl(tel_dir / "metrics.jsonl")
    by_attempt = {}
    for r in rows:
        by_attempt.setdefault(r["attempt"], []).append(r)
    assert set(by_attempt) == {0, 1}
    # Attempt 0: the 3 pre-kill batches; attempt 1: the replayed epoch 0
    # plus epoch 1 (the raise@4 retry consumes no extra row).
    assert len(by_attempt[0]) == 3
    assert len(by_attempt[1]) == 6

    # The trace shows the restart and the per-step phase structure.
    names = [e["name"] for e in read_trace(tel_dir / "trace.json")]
    assert names.count("restart_attempt") == 2  # failed + successful
    # No place_batch span: part1 is the single-device path (place=None);
    # the distributed parts add it (unit-covered in the loop test above).
    for phase in ("data_wait", "step_dispatch", "device_block",
                  "checkpoint_save", "eval"):
        assert phase in names, f"missing {phase} span"
    assert "fault_ckpt_kills" in names  # the fault instant marker

    # Registry counters match the run's FaultEvents totals exactly:
    # kill_ckpt@1 → 1 kill + 1 restart; nan@2 → 1 guard skip; raise@4 →
    # 1 loader retry; nothing else fired.
    snap = json.loads((tel_dir / "registry.json").read_text())
    faults = {
        c["labels"]["kind"]: c["value"]
        for c in snap["counters"] if c["name"] == "fault_events"
    }
    assert faults.get("ckpt_kills") == 1
    assert faults.get("skipped_steps") == 1
    assert faults.get("loader_retries") == 1
    assert faults.get("restarts") == 1
    assert faults.get("stalls") is None and faults.get("preemptions") is None
    counters = {
        (c["name"], c["labels"].get("kind")): c["value"]
        for c in snap["counters"]
    }
    # 3 applied + 1 skipped on attempt 0's view... the steps_total
    # counter counts loop iterations that completed: 3 + 6.
    assert counters[("steps_total", None)] == 9

    # And the stdlib summarizer digests the whole directory.
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "trace_summary.py"),
         str(tel_dir)],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert "attempt(s) 0,1" in out.stdout
