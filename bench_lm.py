"""LM throughput benchmark — tokens/sec through the transformer train
step on the attached accelerator, per attention implementation.

Secondary to ``bench.py`` (the driver's reference-protocol CNN bench):
this one characterizes the framework's beyond-parity surface — the
decoder-only LM with dense vs Pallas-flash attention — so kernel wins
are measured, not assumed.  Same honest-measurement design as bench.py:
the timed iterations run as ONE jitted ``lax.scan`` over device-resident
batches, timed around a host fetch (remote-TPU dispatch RTT would
otherwise swamp the step).

Usage::

    python bench_lm.py                         # default config
    python bench_lm.py --seq-len 2048 --attn flash
    python bench_lm.py --attn dense,flash      # comparison table
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from distributed_machine_learning_tpu.models.transformer import TransformerLM
from distributed_machine_learning_tpu.train.lm_step import (
    _lm_step_impl,
    init_lm_state,
)

TIMED_ITERS = 20


# Shared with bench/spec_trained.py via the package harness (one copy
# of the serving cast + chained-dispatch fit).
from distributed_machine_learning_tpu.bench.harness import (  # noqa: E402
    cast_serving_params as _cast_params,
    two_point_dispatch as _two_point_dispatch,
)


def bench_one(attn: str, args) -> tuple[float, int]:
    """(tokens/sec, n_params) for one attention implementation."""
    model = TransformerLM(
        vocab_size=args.vocab,
        d_model=args.d_model,
        n_layers=args.n_layers,
        n_heads=args.n_heads,
        n_kv_heads=args.n_kv_heads,
        attn_impl=attn,
        compute_dtype=jnp.bfloat16 if args.bf16 else jnp.float32,
        remat=args.remat,
        remat_policy=args.remat_policy,
    )
    from distributed_machine_learning_tpu.train.sgd import SGDConfig

    state = init_lm_state(
        model, config=SGDConfig(momentum_dtype=args.momentum_dtype)
    )
    rng = np.random.default_rng(0)
    toks = rng.integers(
        0, args.vocab, (TIMED_ITERS, args.batch, args.seq_len + 1)
    ).astype(np.int32)
    dx = jax.device_put(jnp.asarray(toks[:, :, :-1]))
    dy = jax.device_put(jnp.asarray(toks[:, :, 1:]))

    from functools import partial

    from jax import lax

    step = partial(
        _lm_step_impl, model, axis_names=(),
        fused_ce_chunks=args.fused_ce_chunks,
    )

    @jax.jit
    def epoch(state, xs, ys):
        def body(s, xy):
            s, loss = step(s, xy[0], xy[1])
            return s, loss

        state, losses = lax.scan(body, state, (xs, ys))
        return state, losses[-1]

    # Compile + warm-up (excluded, like the reference's iteration 0).
    _, loss = epoch(state, dx, dy)
    if not np.isfinite(float(loss)):
        raise RuntimeError("bench_lm diverged; refusing to report")

    def timed(n_dispatches):
        """Best-of-reps seconds: n async same-epoch dispatches + 1 fetch.
        Every dispatch starts from the same initial state, so numerics
        match the canonical epoch regardless of n."""
        best = float("inf")
        for _ in range(args.reps):
            t0 = time.perf_counter()
            for _ in range(n_dispatches):
                _, loss = epoch(state, dx, dy)
            float(loss)  # host fetch forces completion of the queue
            best = min(best, time.perf_counter() - t0)
        return best

    # Two-point fit cancels the constant tunnel round-trip (bench.py's
    # methodology — the r01 numbers under-read by the RTT share).
    from distributed_machine_learning_tpu.bench.harness import two_point_fit

    best = two_point_fit(timed, args.chain)
    tokens = TIMED_ITERS * args.batch * args.seq_len
    n_params = sum(
        int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(state.params)
    )
    # The input embedding is a gather, not a matmul — drop it from the
    # 6P matmul-FLOPs term (at 32k vocab × d2048 it would otherwise
    # inflate MFU ~12%).  The lm_head IS a matmul and stays counted.
    # Assumes the UNTIED embed + lm_head layout TransformerLM uses; if
    # weight tying is ever added, this subtraction must become
    # conditional or it would remove the (real) lm_head matmul instead.
    n_params -= args.vocab * args.d_model
    return tokens / best, n_params


def bench_decode(args) -> None:
    """Decode-path benchmark: prefill vs steady-state tokens/sec.

    Methodology: build two generate fns differing only in
    ``max_new_tokens`` (N_small, N_big); each is timed with the same
    two-point dispatch fit as the train benches (cancelling the tunnel
    RTT), and the per-token steady-state time is the slope
    ``(T_big − T_small) / (N_big − N_small)`` — prefill, sampling setup,
    and any constant overhead cancel in the subtraction.  Prefill time
    is then ``T_small − N_small·t_tok``.  Weights are cast to the
    compute dtype first (serving configuration: decode is bound by HBM
    reads of weights + KV cache, so fp32 master params would halve
    throughput).
    """
    from distributed_machine_learning_tpu.inference.generate import (
        make_generate_fn,
    )

    kv_dtype = (
        jnp.dtype(args.kv_cache_dtype) if args.kv_cache_dtype else None
    )
    if args.moe:
        from distributed_machine_learning_tpu.models.moe import (
            MoETransformerLM,
        )

        model = MoETransformerLM(
            vocab_size=args.vocab, d_model=args.d_model,
            n_layers=args.n_layers, n_heads=args.n_heads,
            n_kv_heads=args.n_kv_heads, n_experts=args.n_experts,
            compute_dtype=jnp.bfloat16 if args.bf16 else jnp.float32,
            kv_cache_dtype=kv_dtype,
        )
    else:
        model = TransformerLM(
            vocab_size=args.vocab,
            d_model=args.d_model,
            n_layers=args.n_layers,
            n_heads=args.n_heads,
            n_kv_heads=args.n_kv_heads,
            compute_dtype=jnp.bfloat16 if args.bf16 else jnp.float32,
            kv_cache_dtype=kv_dtype,
        )
    state = init_lm_state(model)
    dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    master = state.params
    # A serving bench must not hold training state: the f32 momentum
    # buffer alone is ~2 GB at this width, and keeping it (plus the f32
    # master params after the cast) resident is the difference between
    # the f32-cache 32k config fitting the 16 GB chip or OOMing.
    del state
    # Shared serving pipeline (bench/harness.py): int8 quantization from
    # the f32 master params, or the compute-dtype cast.
    from distributed_machine_learning_tpu.bench.harness import (
        prepare_serving_params,
    )

    params = prepare_serving_params(
        master, "int8" if args.quant else None, dtype
    )
    del master
    params = jax.block_until_ready(params)
    rng = np.random.default_rng(0)
    prompt = jax.device_put(jnp.asarray(
        rng.integers(0, args.vocab, (args.batch, args.prompt_len)),
        jnp.int32,
    ))
    key = jax.random.PRNGKey(0)

    n_small, n_big = 32, args.gen_tokens
    if n_big <= n_small:
        raise ValueError(f"--gen-tokens must exceed {n_small}")

    def timed_for(n_tokens):
        fn = make_generate_fn(model, n_tokens, temperature=0.0,
                              quantize="int8" if args.quant else None)
        jax.block_until_ready(fn(params, prompt, key))
        return _two_point_dispatch(
            lambda: fn(params, prompt, key),
            lambda out: np.asarray(out[0, -1]),  # fetch drains the queue
            args.reps, args.chain,
        )

    t_small = timed_for(n_small)
    t_big = timed_for(n_big)
    t_tok = (t_big - t_small) / (n_big - n_small)
    # An n-token generate runs n−1 scanned decode steps (token 0 comes
    # from the prefill logits), so prefill = T − (n−1)·t_tok.
    t_prefill = max(t_small - (n_small - 1) * t_tok, 0.0)
    print(json.dumps({
        "metric": "lm_decode_tokens_per_sec",
        "value": round(args.batch / t_tok, 1),
        "unit": "tokens/sec",
        "per_sequence_tokens_per_sec": round(1.0 / t_tok, 1),
        "prefill_tokens_per_sec": round(
            args.batch * args.prompt_len / t_prefill, 1
        ) if t_prefill > 0 else None,
        "prefill_ms": round(t_prefill * 1e3, 2),
        "ms_per_decode_step": round(t_tok * 1e3, 3),
        "config": {
            "d_model": args.d_model, "n_layers": args.n_layers,
            "n_heads": args.n_heads, "n_kv_heads": args.n_kv_heads,
            "vocab": args.vocab, "batch": args.batch,
            "prompt_len": args.prompt_len, "gen_tokens": args.gen_tokens,
            "bf16": args.bf16, "kv_cache_dtype": args.kv_cache_dtype,
            "quant": "int8" if args.quant else None,
            "moe": args.n_experts if args.moe else None,
        },
    }))

    if args.spec_gamma > 0:
        # Speculative-decoding FLOOR (random draft, acceptance ~ 0): the
        # reproducible command behind docs/PERF.md's envelope — a real
        # draft only raises tokens/round, never the per-round cost.
        # Any --batch: rows ride per-row frontiers (batched speculation);
        # the floor is per ROW, so total tok/s scales with the batch.
        from distributed_machine_learning_tpu.inference.speculative import (
            make_speculative_generate_fn,
        )

        draft = TransformerLM(
            vocab_size=args.vocab, d_model=args.spec_draft_d_model,
            n_layers=args.spec_draft_n_layers, n_heads=args.n_heads,
            n_kv_heads=args.n_kv_heads, compute_dtype=dtype,
            kv_cache_dtype=kv_dtype,
        )
        dparams = _cast_params(init_lm_state(draft, seed=11).params, dtype)

        def spec_timed_for(n_tokens):
            fn = make_speculative_generate_fn(
                model, draft, n_tokens, gamma=args.spec_gamma,
                quantize="int8" if args.quant else None,
            )
            jax.block_until_ready(fn(params, dparams, prompt, key))
            return _two_point_dispatch(
                lambda: fn(params, dparams, prompt, key),
                lambda out: np.asarray(out[0, -1]),
                args.reps, args.chain,
            )

        st_small = spec_timed_for(n_small)
        st_big = spec_timed_for(n_big)
        st_tok = (st_big - st_small) / (n_big - n_small)
        if st_tok <= 0:
            # Cross-fit jitter (two_point_fit guards within one fit,
            # not across the two): fail loudly like harness.py's own
            # slope guard rather than print a negative rate.
            raise RuntimeError(
                f"speculative slope non-positive ({st_tok:.2e}s): "
                "tunnel jitter swamped the measurement; raise "
                "--gen-tokens and/or --reps"
            )
        print(json.dumps({
            "metric": "lm_speculative_decode_floor_tokens_per_sec",
            "value": round(args.batch / st_tok, 1),
            "unit": "tokens/sec",
            "per_sequence_tokens_per_sec": round(1.0 / st_tok, 1),
            "ms_per_token": round(st_tok * 1e3, 3),
            "vs_vanilla": round(t_tok / st_tok, 3),
            "note": "random draft: acceptance~0 floor of the envelope",
            "config": {"gamma": args.spec_gamma, "batch": args.batch,
                       "draft_d_model": args.spec_draft_d_model,
                       "draft_n_layers": args.spec_draft_n_layers,
                       "kv_cache_dtype": args.kv_cache_dtype,
                       "quant": "int8" if args.quant else None},
        }))


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--attn", default="dense",
                   help="comma-separated: dense, flash")
    p.add_argument("--d-model", dest="d_model", default=512, type=int)
    p.add_argument("--n-layers", dest="n_layers", default=8, type=int)
    p.add_argument("--n-heads", dest="n_heads", default=8, type=int)
    p.add_argument("--n-kv-heads", dest="n_kv_heads", default=None, type=int)
    p.add_argument("--vocab", default=32000, type=int)
    p.add_argument("--seq-len", dest="seq_len", default=1024, type=int)
    p.add_argument("--batch", default=8, type=int)
    p.add_argument("--reps", default=3, type=int)
    p.add_argument("--chain", default=4, type=int,
                   help="chained epoch dispatches per measurement; per-"
                        "epoch time is the (chain vs 1) slope, cancelling "
                        "the constant tunnel round-trip")
    p.add_argument("--fused-ce-chunks", dest="fused_ce_chunks",
                   default=None, type=int)
    p.add_argument("--momentum-dtype", dest="momentum_dtype", default=None,
                   help="SGD momentum-buffer storage dtype (e.g. bfloat16) "
                        "— optimizer-state memory is what bounds depth at "
                        "realistic width on one chip (train/sgd.py)")
    p.add_argument("--remat", action="store_true",
                   help="rematerialization (selective 'mlp' policy by "
                        "default — attention residuals stay saved) — lets "
                        "realistic-width long-context configs fit the "
                        "chip; reported MFU counts model FLOPs only")
    p.add_argument("--remat-policy", dest="remat_policy", default="mlp",
                   choices=("mlp", "block"),
                   help="'mlp' (selective: save attention residuals, remat "
                        "only LN2+MLP — backward never re-runs the O(L^2) "
                        "attention forward) or 'block' (whole-block, "
                        "maximal memory savings)")
    p.add_argument("--fp32", dest="bf16", action="store_false",
                   help="run the trunk in fp32 (default bfloat16)")
    p.add_argument("--quant", action="store_true",
                   help="with --decode: weight-only int8 serving (the "
                        "Pallas int8 matmul kernel, ops/quant.py)")
    p.add_argument("--decode", action="store_true",
                   help="benchmark the KV-cached decode path instead of "
                        "the train step (prefill vs steady-state tok/s)")
    p.add_argument("--moe", action="store_true",
                   help="with --decode: serve a Switch-MoE model "
                        "(dropless grouped expert path; composes with "
                        "--quant int8 expert weights and --spec-gamma)")
    p.add_argument("--n-experts", dest="n_experts", default=8, type=int)
    p.add_argument("--spec-gamma", dest="spec_gamma", default=0, type=int,
                   help="with --decode: ALSO measure speculative decoding "
                        "at this gamma with a random draft (the "
                        "acceptance~0 FLOOR of the envelope -- "
                        "docs/PERF.md; any --batch via per-row frontiers)")
    p.add_argument("--spec-draft-d-model", dest="spec_draft_d_model",
                   default=512, type=int)
    p.add_argument("--spec-draft-n-layers", dest="spec_draft_n_layers",
                   default=2, type=int)
    p.add_argument("--prompt-len", dest="prompt_len", default=2048, type=int)
    p.add_argument("--gen-tokens", dest="gen_tokens", default=160, type=int)
    p.add_argument("--kv-cache-dtype", dest="kv_cache_dtype", default=None,
                   help="decode KV-cache storage dtype ablation "
                        "(e.g. float32; default = compute dtype)")
    args = p.parse_args()

    if args.spec_gamma > 0 and not args.decode:
        raise ValueError(
            "--spec-gamma is a decode-path option; pass --decode with it "
            "(any --batch: per-row frontiers, inference/speculative.py)"
        )
    if args.quant and not args.decode:
        raise ValueError(
            "--quant is a decode-path option (weight-only int8 serving); "
            "pass --decode with it — the train benches run full precision"
        )
    if args.moe and not args.decode:
        raise ValueError(
            "--moe here is a decode-path option (the MoE TRAIN benches "
            "are cli.lm --parallel ep and bench/lm_sweep --scheme ep)"
        )
    if args.decode:
        bench_decode(args)
        return

    from distributed_machine_learning_tpu.utils.flops import (
        mfu,
        transformer_train_flops_per_token,
    )

    for attn in args.attn.split(","):
        tps, n_params = bench_one(attn.strip(), args)
        # Two FLOPs conventions (utils/flops.py): "causal" counts the
        # attention term at the work a causal kernel performs (T/2);
        # "full" is the PaLM-style full-score-matrix convention most
        # published MFU tables use.  Report both — they differ by up to
        # 2× on the attention term at long context.
        fpt = transformer_train_flops_per_token(
            n_params, args.n_layers, args.d_model, args.seq_len, causal=True
        )
        fpt_full = transformer_train_flops_per_token(
            n_params, args.n_layers, args.d_model, args.seq_len, causal=False
        )
        print(json.dumps({
            "metric": f"lm_{attn.strip()}_train_tokens_per_sec",
            "value": round(tps, 1),
            "unit": "tokens/sec",
            # Keyed by convention (like mfu_*) — the r02 artifacts'
            # "tflops_per_sec" used the full convention WITH embedding
            # params, so neither new key is silently comparable to it.
            "tflops_causal": round(tps * fpt / 1e12, 1),
            "tflops_full": round(tps * fpt_full / 1e12, 1),
            "mfu_causal": round(mfu(tps * fpt), 3),
            "mfu_full": round(mfu(tps * fpt_full), 3),
            "config": {
                "d_model": args.d_model, "n_layers": args.n_layers,
                "seq_len": args.seq_len, "batch": args.batch,
                "vocab": args.vocab, "bf16": args.bf16,
                "n_kv_heads": args.n_kv_heads,
                "fused_ce_chunks": args.fused_ce_chunks,
            },
        }))


if __name__ == "__main__":
    main()
