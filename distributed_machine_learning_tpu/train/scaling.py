"""World-size-aware batch/LR scaling rules — the numeric contract of an
elastic gang that GROWS.

PR 5's elasticity pinned the global batch: a shrink rescales the
per-host batch from B/N to B/M so the global batch — and with it the
LR schedule — is world-size-invariant.  That is the right conservative
default, but it wastes the grow direction: five hosts each pushing
B/5 examples leave hardware idle that could be consuming a *larger*
global batch.  The large-batch literature this repo already cites says
exactly how to change the batch without breaking the trajectory:

- **linear scaling** ("Massively Distributed SGD", arxiv 1811.05233;
  Goyal et al.): grow the global batch proportionally to the world and
  the LR proportionally to the batch — the mean gradient's noise
  variance shrinks as 1/B, so a B-proportional step keeps the
  per-example learning signal (and the stationary loss floor) fixed;
- **sqrt/LARS scaling** ("Extremely Large Minibatch SGD", arxiv
  1711.04325): at batch sizes where linear scaling diverges, scale the
  LR with sqrt(B) and normalize each layer's step by its trust ratio
  (``train/lars.py``) so no layer's update outruns its weights.

A :class:`ScalingRule` is a pure, picklable description of that
contract: given the launch-time base point (lr, global batch, world),
:meth:`at_world` answers "at world W, what is the global batch, what is
the LR, and what does each rank consume?" — deterministically, so every
rank, the supervisor, and a post-mortem tool agree without
communicating.  The gang worker re-evaluates it at every relaunch (the
world size is an argv fact there), and ``exact_shard_indices``
(``data/sharding.py``) keeps the per-rank shares an exact partition, so
exactly-once consumption survives the transition.

Kinds:

- ``pinned``   — PR 5 semantics: global batch and LR fixed at the base
                 point regardless of world.  The default everywhere.
- ``linear``   — B(W) = round(B0 · W/W0), lr(W) = lr0 · B(W)/B0.
- ``lars``     — B(W) as linear, lr(W) = lr0 · sqrt(B(W)/B0); pair
                 with ``optimizer="lars"`` so the trust ratio bounds
                 per-layer steps (the 1711.04325 recipe).
- ``unscaled`` — B(W) as linear but the LR pinned at lr0.  This is the
                 deliberately-WRONG control: the batch changes and
                 nothing compensates, so the stationary loss floor
                 moves with 1/W.  It exists so the chaos proof can
                 demonstrate the rule is load-bearing, not decorative.

Everything here is stdlib+math on host scalars (no jax): the rule is
consulted at relaunch boundaries, never inside the compiled step —
inside the step the LR rides the normal ``schedule`` hook
(:func:`scaled_schedule` wraps any ``step -> lr`` schedule with the
rule's factor).
"""

from __future__ import annotations

import dataclasses
import math

SCALING_KINDS = ("pinned", "linear", "lars", "unscaled")


@dataclasses.dataclass(frozen=True)
class WorldScaling:
    """The resolved numbers at one world size — what a relaunched rank
    actually uses.  ``lr_factor`` is ``lr / base_lr`` (the multiplier
    :func:`scaled_schedule` applies to a schedule's output)."""

    world: int
    global_batch: int
    lr: float
    lr_factor: float

    def shard_size(self, rank: int) -> int:
        """Examples rank ``rank`` consumes per step — the exact-partition
        share (counts differ by at most one across ranks)."""
        if not 0 <= rank < self.world:
            raise ValueError(
                f"rank {rank} out of range for world {self.world}"
            )
        base, extra = divmod(self.global_batch, self.world)
        return base + (1 if rank < extra else 0)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class ScalingRule:
    """How (global batch, LR) respond to a world-size change, anchored
    at the launch-time base point.  Immutable and world-stateless: the
    same rule object answers for every W, so there is no order
    dependence between a 4→3 shrink and a 3→5 grow."""

    kind: str = "pinned"
    base_lr: float = 0.1
    base_global_batch: int = 24
    base_world: int = 1

    def __post_init__(self):
        if self.kind not in SCALING_KINDS:
            raise ValueError(
                f"unknown scaling kind {self.kind!r}; known: "
                f"{list(SCALING_KINDS)}"
            )
        if self.base_lr <= 0:
            raise ValueError(f"base_lr must be > 0, got {self.base_lr}")
        if self.base_global_batch < 1:
            raise ValueError(
                f"base_global_batch must be >= 1, got "
                f"{self.base_global_batch}"
            )
        if self.base_world < 1:
            raise ValueError(
                f"base_world must be >= 1, got {self.base_world}"
            )

    def at_world(self, world: int) -> WorldScaling:
        """The (global batch, LR) this rule prescribes at world size
        ``world``.  Batch rounding is shared by every scaling kind
        (round-half-up to at least 1), and the LR compensates for the
        ACTUAL batch ratio, rounding included — not the nominal world
        ratio — so a ragged world never under/over-scales the step."""
        if world < 1:
            raise ValueError(f"world must be >= 1, got {world}")
        if self.kind == "pinned":
            return WorldScaling(world=world,
                                global_batch=self.base_global_batch,
                                lr=self.base_lr, lr_factor=1.0)
        batch = max(
            1, int(self.base_global_batch * world / self.base_world + 0.5)
        )
        ratio = batch / self.base_global_batch
        if self.kind == "linear":
            factor = ratio
        elif self.kind == "lars":
            factor = math.sqrt(ratio)
        else:  # unscaled: the documented control — nothing compensates
            factor = 1.0
        return WorldScaling(world=world, global_batch=batch,
                            lr=self.base_lr * factor, lr_factor=factor)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, payload: dict) -> "ScalingRule":
        return cls(**{k: payload[k] for k in
                      ("kind", "base_lr", "base_global_batch",
                       "base_world") if k in payload})


def scaled_schedule(rule: ScalingRule, world: int, base_schedule):
    """Wrap a ``step -> lr`` schedule (``train/schedule.py``) with the
    rule's world factor — the hook a real training CLI uses: the base
    schedule keeps its shape (warmup/cosine/staircase) while the whole
    curve scales with the world's batch.  Identity for ``pinned`` (the
    wrapper is not even allocated)."""
    factor = rule.at_world(world).lr_factor
    if factor == 1.0:
        return base_schedule

    def schedule(step):
        return base_schedule(step) * factor

    return schedule
