from distributed_machine_learning_tpu.runtime.mesh import make_mesh, BATCH_AXIS
from distributed_machine_learning_tpu.runtime.distributed import (
    initialize_from_flags,
    DistributedContext,
)

__all__ = ["make_mesh", "BATCH_AXIS", "initialize_from_flags", "DistributedContext"]
