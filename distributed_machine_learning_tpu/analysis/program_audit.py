"""Layer 2: jaxpr/HLO audit passes over COMPILED train steps.

Layer 1 lints what the source says; these passes check what the
executable actually does — the invariants live in the compiled
artifact, and source-level truth can be compiled away (an unaliasable
donation silently becomes a copy; a "sharded" update can still gather
on the critical path).  Builds on ``bench/overlap_audit.py``'s HLO-text
walkers (the ppermute overlap audit and the wire-byte parser grew
there; this module generalizes them into reusable passes):

- :func:`audit_donation` — every donated operand must appear in the
  module's ``input_output_alias`` map; a donated-but-copied buffer
  doubles peak memory exactly where donation was supposed to save it
  (the ISSUE 1 restore-then-donate class, seen from the program side).
- :func:`audit_critical_path_collectives` — SYNC collectives (no
  ``-start``/``-done`` split) sit on the critical path by construction;
  for the zero1 weight update this is the all-gather that "Automatic
  Cross-Replica Sharding of Weight Update in Data-Parallel Training"
  (arxiv 2004.13336) eliminates.  The overlap-aware update (ISSUE 9:
  ``make_zero1_train_step(overlap=True)`` splits the step into an
  update program and a bucketed-ring consume program) landed, so this
  is now an ERROR for the zero1 step — the historical advisory phase
  is over and a re-serialized gather fails the run.
- :func:`audit_ring_wire_accounting` — the compiled program's
  collective-permute payload bytes must equal the static
  ``ops.ring.ring_wire_bytes`` accounting for every wire scheme (the
  generalization of ISSUE 7's single CI assertion): the telemetry
  counter and the executable can never drift apart silently.
- :func:`audit_step_host_callbacks` — a jaxpr pass: no host callback
  primitives (``pure_callback``/``io_callback``/debug prints) inside a
  compiled train step — the program-level twin of Layer 1's DML004.

jax is imported lazily INSIDE the passes that need it; importing this
module stays stdlib-cheap (the parsers are pure text).
"""

from __future__ import annotations

import re
from typing import Iterable, Sequence

from distributed_machine_learning_tpu.analysis.findings import Finding
from distributed_machine_learning_tpu.bench.overlap_audit import (
    audit_schedule,
    compile_ring_hlo,
    sync_collectives_from_hlo,
    wire_bytes_from_hlo,
)

# Layer-2 rule ids (DML1xx so a --rules filter can select layers).
RULE_DONATION = "DML101"
RULE_CRITICAL_PATH = "DML102"
RULE_WIRE_ACCOUNTING = "DML103"
RULE_HOST_CALLBACK = "DML104"

_ALIAS_ENTRY_RE = re.compile(
    r"\{([\d,\s]*)\}:\s*\((\d+),\s*\{([\d,\s]*)\}"
)
_ENTRY_LAYOUT_RE = re.compile(r"entry_computation_layout=\{\((.*?)\)->")
_SHAPE_RE = re.compile(r"[a-z]+\d*\[[^\]]*\](?:\{[^}]*\})?")


def parse_input_output_alias(hlo_text: str) -> list[dict]:
    """The module header's donation/alias map as
    ``[{"output_index", "param_number", "param_index"}]`` — empty when
    XLA took no donation at all."""
    # Brace-balanced extraction: the map nests braces per entry
    # (``{ {0}: (0, {}, may-alias), ... }``), so a lazy regex would
    # stop at the first inner ``}``.
    start = hlo_text.find("input_output_alias={")
    if start < 0:
        return []
    i = hlo_text.index("{", start)
    depth, j = 0, i
    for j in range(i, min(len(hlo_text), i + 1_000_000)):
        if hlo_text[j] == "{":
            depth += 1
        elif hlo_text[j] == "}":
            depth -= 1
            if depth == 0:
                break
    blob = hlo_text[i + 1:j]
    out = []
    for om, pnum, pidx in _ALIAS_ENTRY_RE.findall(blob):
        out.append({
            "output_index": [int(x) for x in om.split(",") if x.strip()],
            "param_number": int(pnum),
            "param_index": [int(x) for x in pidx.split(",")
                            if x.strip()],
        })
    return out


def entry_param_shapes(hlo_text: str) -> list[str]:
    """The entry computation's parameter shapes, in order."""
    m = _ENTRY_LAYOUT_RE.search(hlo_text)
    if not m:
        return []
    return _SHAPE_RE.findall(m.group(1))


def audit_donation(hlo_text: str, donated_params: Iterable[int],
                   label: str = "train_step") -> list[Finding]:
    """Donation actually taken: every parameter index in
    ``donated_params`` must appear in the compiled module's
    ``input_output_alias`` map.  A missing entry means XLA inserted a
    copy of the donated operand — the buffer is NOT reused, peak memory
    holds two copies of the state, and on real checkpoint-sized params
    that is the difference between fitting and OOM."""
    aliased = {e["param_number"] for e in parse_input_output_alias(hlo_text)}
    shapes = entry_param_shapes(hlo_text)
    findings = []
    for p in donated_params:
        if p in aliased:
            continue
        shape = shapes[p] if p < len(shapes) else "?"
        findings.append(Finding(
            rule=RULE_DONATION, file=label, line=0,
            message=(
                f"donated operand {p} ({shape}) is not aliased to any "
                "output in the compiled module — XLA copied it instead "
                "of reusing the buffer (dtype/shape mismatch or a live "
                "second use); donation is silently not taken"
            ),
            snippet=f"param {p}: {shape}", severity="error", layer=2,
        ))
    return findings


def audit_critical_path_collectives(
    hlo_text: str, kinds: Sequence[str] = ("all-gather",),
    label: str = "train_step", severity: str = "error",
) -> list[Finding]:
    """No sync collective of the given kinds on the critical path.

    A collective compiled WITHOUT the ``-start``/``-done`` split cannot
    overlap anything — it serializes the step at exactly the point the
    sharded weight update was supposed to be free (2004.13336).  An
    async pair whose window contains no compute is flagged the same
    way: in-flight but hiding nothing.  Severity defaults to ``error``
    since the overlap-aware weight update landed (ISSUE 9); pass
    ``severity="advisory"`` for programs still carrying documented
    debt."""
    findings = []
    for rec in sync_collectives_from_hlo(hlo_text, kinds=kinds):
        where = ("feeds the step output directly"
                 if rec["feeds_root"] else "mid-step")
        findings.append(Finding(
            rule=RULE_CRITICAL_PATH, file=label, line=0,
            message=(
                f"sync {rec['kind']} ({rec['shape']}) on the critical "
                f"path ({where}) — compiled without -start/-done, so "
                "nothing overlaps it; the weight-update gather belongs "
                "under the next step's backward (arxiv 2004.13336)"
            ),
            snippet=f"{rec['name']} = {rec['shape']} {rec['kind']}(...)",
            severity=severity, layer=2,
        ))
    try:
        sched = audit_schedule(hlo_text)
    except ValueError:
        sched = None
    if sched is not None:
        # Per-KIND emptiness: permute windows full of compute must not
        # mask an all-gather window that hides nothing.
        empty = any(
            sched["async_pairs_by_kind"].get(k, 0) > 0
            and sched["pairs_with_compute_by_kind"].get(k, 0) == 0
            for k in kinds)
        if empty:
            findings.append(Finding(
                rule=RULE_CRITICAL_PATH, file=label, line=0,
                message=(
                    "async collective windows contain no compute — the "
                    "DMA is in flight but hides nothing; effectively "
                    "still on the critical path"
                ),
                severity=severity, layer=2,
            ))
    return findings


def audit_ring_wire_accounting(
    mesh, length: int, schemes: Sequence[str] = ("none", "int8"),
    bucket_bytes: int = 8192, topk_frac: float = 0.125,
    label: str = "ring_all_reduce", topology: str | None = None,
    codec_impl: str = "xla",
) -> tuple[list[Finding], dict]:
    """Compiled collective-permute bytes == static ``ring_wire_bytes``
    accounting, per wire scheme — the telemetry counter's number and
    the executable's number must be the same number (ISSUE 7's CI
    assertion, generalized to every scheme).  Returns
    ``(findings, {scheme: {"hlo_bytes", "static_bytes", "permutes"}})``.

    ``topology`` ("INNERxOUTER", round 11): audit the hierarchical
    build instead, PER AXIS — each permute's compiled
    ``source_target_pairs`` routing is attributed to the inner or
    outer axis and must equal the static per-axis accounting
    (``ring_wire_bytes_by_axis``); the known XLA:CPU bf16-widening
    signature stays an advisory, carried per axis.  Additionally the
    exact hierarchical build's OUTER-axis (inter-node) bytes must be
    ≤ (1/inner + 5%) of the exact FLAT ring's total — the DynamiQ
    multi-hop reduction, proven on the compiled artifact."""
    from distributed_machine_learning_tpu.ops.ring import (
        get_wire_scheme,
        ring_wire_bytes,
        ring_wire_bytes_by_axis,
    )

    n = mesh.shape[mesh.axis_names[0]]
    findings = []
    table: dict = {}
    topo = None
    if topology is not None:
        from distributed_machine_learning_tpu.ops.topology import (
            Topology,
            parse_topology,
        )

        t_inner, t_outer = parse_topology(topology)
        flat_exact = ring_wire_bytes(length, n, bucket_bytes=bucket_bytes)
    for scheme_name in schemes:
        if topology is not None:
            topo = Topology(t_inner, t_outer, outer_scheme=scheme_name,
                            topk_frac=topk_frac, hd_max_bytes=0,
                            codec_impl=codec_impl)
            hlo = compile_ring_hlo(mesh, length, compress=scheme_name,
                                   topk_frac=topk_frac,
                                   bucket_bytes=bucket_bytes,
                                   topology=topology, hd_max_bytes=0,
                                   codec_impl=codec_impl)
            got = wire_bytes_from_hlo(hlo, inner=t_inner)
            want_axes = ring_wire_bytes_by_axis(
                length, n, bucket_bytes=bucket_bytes, topology=topo)
            full_width = ring_wire_bytes_by_axis(
                length, n, bucket_bytes=bucket_bytes,
                topology=Topology(t_inner, t_outer, hd_max_bytes=0))
            table[scheme_name] = {"hlo_bytes": got["total_bytes"],
                                  "hlo_by_axis": got["by_axis"],
                                  "static_by_axis": want_axes,
                                  "permutes": got["count"]}
            for axis in ("inner", "outer"):
                got_ax = got["by_axis"][axis]
                want_ax = want_axes[axis]
                if got_ax == want_ax:
                    continue
                widened = got_ax == full_width[axis]
                findings.append(Finding(
                    rule=RULE_WIRE_ACCOUNTING, file=label, line=0,
                    message=(
                        f"wire scheme {scheme_name!r} ({topology}): "
                        f"compiled program moves {got_ax} "
                        f"collective-permute bytes on the {axis} axis "
                        f"but the static per-axis accounting says "
                        f"{want_ax}"
                        + (" — the backend widened the sub-32-bit "
                           "payload to full 32-bit words (known "
                           "XLA:CPU behavior); validate the reduction "
                           "on the TPU target" if widened else
                           " — the per-axis ring_wire_bytes telemetry "
                           "counter is lying about the executable")
                    ),
                    snippet=f"{scheme_name}@{axis}: hlo={got_ax} "
                            f"static={want_ax}",
                    severity="advisory" if widened else "error", layer=2,
                ))
            if scheme_name == "none":
                bound = (1.0 / t_inner + 0.05) * flat_exact
                if t_inner > 1 and got["by_axis"]["outer"] > bound:
                    findings.append(Finding(
                        rule=RULE_WIRE_ACCOUNTING, file=label, line=0,
                        message=(
                            f"hierarchical {topology} exact build moves "
                            f"{got['by_axis']['outer']} outer-axis "
                            f"(inter-node) bytes — more than "
                            f"(1/{t_inner} + 5%) of the flat ring's "
                            f"{flat_exact}-byte total; the multi-hop "
                            "inter-node reduction has regressed"
                        ),
                        snippet=(f"outer={got['by_axis']['outer']} "
                                 f"flat_total={flat_exact}"),
                        severity="error", layer=2,
                    ))
            continue
        hlo = compile_ring_hlo(mesh, length, compress=scheme_name,
                               topk_frac=topk_frac,
                               bucket_bytes=bucket_bytes,
                               codec_impl=codec_impl)
        got = wire_bytes_from_hlo(hlo)
        scheme = (None if scheme_name == "none"
                  else get_wire_scheme(scheme_name, topk_frac=topk_frac,
                                       codec_impl=codec_impl))
        want = ring_wire_bytes(length, n, bucket_bytes=bucket_bytes,
                               scheme=scheme)
        full_width = ring_wire_bytes(length, n, bucket_bytes=bucket_bytes)
        table[scheme_name] = {"hlo_bytes": got["total_bytes"],
                              "static_bytes": want,
                              "permutes": got["count"]}
        if got["total_bytes"] != want:
            # The one known benign shape: XLA:CPU widens sub-32-bit
            # collective payloads back to 32-bit words (bf16 wire
            # compiles to f32 permutes; s8 stays narrow), so on the CI
            # backend a 16-bit scheme's savings do not materialize.
            # That is a true statement about THIS executable — reported
            # — but it is a backend property, not a codec bug, so it is
            # advisory here and an error on targets that can carry the
            # narrow dtype (the TPU AOT audit).
            widened = got["total_bytes"] == full_width
            findings.append(Finding(
                rule=RULE_WIRE_ACCOUNTING, file=label, line=0,
                message=(
                    f"wire scheme {scheme_name!r}: compiled program "
                    f"moves {got['total_bytes']} collective-permute "
                    f"bytes but the static ring_wire_bytes accounting "
                    f"says {want}"
                    + (" — the backend widened the sub-32-bit payload "
                       "to full 32-bit words (known XLA:CPU behavior); "
                       "validate the reduction on the TPU target"
                       if widened else
                       " — the ring_wire_bytes telemetry counter is "
                       "lying about the executable")
                ),
                snippet=f"{scheme_name}: hlo={got['total_bytes']} "
                        f"static={want}",
                severity="advisory" if widened else "error", layer=2,
            ))
    return findings, table


# ``debug_print`` is the Pallas-kernel spelling (``pl.debug_print``):
# under the interpreter it is a host round-trip per grid step, and on
# TPU a trace-slowing scalar dump — same class of leak as the XLA
# callbacks, visible now that the walker descends kernel jaxprs.
_CALLBACK_PRIMITIVES = ("pure_callback", "io_callback", "debug_callback",
                        "debug_print")


def audit_step_host_callbacks(fn, *args, label: str = "train_step",
                              allowed: Sequence[str] = ()) -> list[Finding]:
    """Jaxpr pass: no host-callback primitives inside a compiled step.

    ``jax.debug.print`` / ``pure_callback`` inside a train step round-
    trips device→host EVERY step — the program-level version of Layer
    1's DML004 (which can only see syncs the loop spells out).  ``fn``
    is traced (not compiled) with ``jax.make_jaxpr`` over ``args``
    (shape structs are fine); nested jaxprs (pjit/scan/cond bodies,
    shard_map, AND ``pallas_call`` kernel bodies — a Pallas kernel's
    params carry an *open* Jaxpr, not a ClosedJaxpr, so the walker
    descends both spellings and the audit sees through the round-13
    fused-kernel boundary) are walked recursively."""
    import jax

    jaxpr = jax.make_jaxpr(fn)(*args)
    hits: list[str] = []

    def _sub(v):
        # ClosedJaxpr carries .jaxpr; an open Jaxpr (pallas_call's
        # kernel param) IS the walkable object itself.
        inner = getattr(v, "jaxpr", None)
        if inner is not None:
            return inner
        return v if hasattr(v, "eqns") else None

    def walk(jx):
        for eqn in jx.eqns:
            name = eqn.primitive.name
            if name in _CALLBACK_PRIMITIVES and name not in allowed:
                hits.append(name)
            for v in eqn.params.values():
                sub = _sub(v)
                if sub is not None:
                    walk(sub)
                elif isinstance(v, (list, tuple)):
                    for item in v:
                        s = _sub(item)
                        if s is not None:
                            walk(s)

    walk(jaxpr.jaxpr)
    return [Finding(
        rule=RULE_HOST_CALLBACK, file=label, line=0,
        message=(
            f"host callback primitive {name!r} inside the compiled "
            "step — a device→host round-trip on every step; move it "
            "behind a profiling guard in the driver loop"
        ),
        snippet=name, severity="error", layer=2,
    ) for name in hits]


# ---------------------------------------------------------------------------
# Whole-program entry points (what tools/dmlcheck.py --layer2 runs)
# ---------------------------------------------------------------------------

def _vggtest_setup():
    """(model, init_fn, state_shape) for the audits' canonical tiny
    model — VGGTest keeps the compiles tier-affordable while every
    structural property under audit is model-size-independent."""
    import jax
    import jax.numpy as jnp

    from distributed_machine_learning_tpu.models.vgg import VGGTest
    from distributed_machine_learning_tpu.train.state import TrainState

    model = VGGTest()

    def init():
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 32, 32, 3)))
        return TrainState.create(params=variables["params"],
                                 rng=jax.random.PRNGKey(1))

    return model, init, jax.eval_shape(init)


def _audit_ring_strategy(mesh, strategy, label: str,
                         global_batch: int = 16) -> list[Finding]:
    """Shared body of the ring-step audits: compile the part3 train
    step under ``strategy`` and run the donation, critical-path
    (permute-only) and host-callback passes.  Stateful strategies
    (error-feedback codecs) lower the inner 4-ary program so donation
    covers the threaded residual too."""
    import jax
    import jax.numpy as jnp

    from distributed_machine_learning_tpu.train.step import make_train_step

    model, _, state_shape = _vggtest_setup()
    step = make_train_step(model, strategy, mesh=mesh, augment=False)
    x = jax.ShapeDtypeStruct((global_batch, 32, 32, 3), jnp.float32)
    y = jax.ShapeDtypeStruct((global_batch,), jnp.int32)
    n_leaves = len(jax.tree_util.tree_leaves(state_shape))
    if getattr(strategy, "stateful", False):
        res = jax.eval_shape(
            lambda: step.fresh_sync_state(state_shape.params))
        hlo = step.inner.lower(state_shape, x, y, res).compile().as_text()
        n_res = len(jax.tree_util.tree_leaves(res))
        # Flat entry params: state leaves, then x, y, then the residual
        # (a copied residual would double the EF memory exactly where
        # it is per-device by design).
        donated = list(range(n_leaves)) + list(
            range(n_leaves + 2, n_leaves + 2 + n_res))
        cb_args = (step.inner, state_shape, x, y, res)
    else:
        hlo = step.lower(state_shape, x, y).compile().as_text()
        donated = list(range(n_leaves))
        cb_args = (step, state_shape, x, y)
    findings = audit_donation(hlo, donated, label=label)
    findings += audit_critical_path_collectives(
        hlo, kinds=("all-gather",), label=label, severity="error")
    findings += audit_step_host_callbacks(*cb_args, label=label)
    return findings


def audit_ring_step(mesh, global_batch: int = 16,
                    codec_impl: str | None = None) -> list[Finding]:
    """Compile the part3 ring train step for ``mesh``; run the donation
    audit (every state leaf is donated via donate_argnums=(0,)), the
    critical-path all-gather pass (the ring must have NONE — it is
    permute-only), and the jaxpr host-callback pass.

    ``codec_impl`` (round 13): audit the COMPRESSED ring instead —
    int8 + error feedback with the given codec implementation.  With
    ``"pallas"`` this is the fused-kernel build: the audits must see
    through the ``pallas_call`` boundary and prove the fused step is
    still permute-only and fully donated (EF residual included), with
    zero new baseline entries."""
    from distributed_machine_learning_tpu.parallel.strategies import (
        get_strategy,
    )

    if codec_impl is None:
        return _audit_ring_strategy(
            mesh, get_strategy("ring"), "ring_step",
            global_batch=global_batch)
    return _audit_ring_strategy(
        mesh,
        get_strategy("ring", compress="int8", codec_impl=codec_impl),
        f"ring_step_int8_{codec_impl}", global_batch=global_batch)


def audit_hier_ring_step(mesh, global_batch: int = 16,
                         topology: str | None = None,
                         codec_impl: str = "xla") -> list[Finding]:
    """Round 11: compile the part3 train step under the TOPOLOGY-aware
    hierarchical ring (int8 outer codec + error feedback — the
    stateful build, so donation covers the threaded residual too) and
    hold it to the flat ring's program invariants:

    - donation taken on every state leaf AND the EF residual pytree
      (the residual is donated argnum 3 — a copied residual would
      double the EF memory exactly where it is per-device by design);
    - permute-only: the hierarchical phases (inner reduce-scatter,
      outer compressed ring, inner all-gather, halving-doubling) must
      all lower to collective-permutes — an ``all-gather`` appearing on
      the critical path means phase 3 re-serialized into the monolithic
      collective the explicit ring exists to replace;
    - no host callbacks in the jaxpr.

    ``codec_impl="pallas"`` (round 13) audits the fused-kernel build of
    the same program — the knob must not change any invariant.
    """
    from distributed_machine_learning_tpu.parallel.strategies import (
        get_strategy,
    )

    n = mesh.shape[mesh.axis_names[0]]
    if topology is None:
        topology = f"2x{n // 2}" if n % 2 == 0 else f"1x{n}"
    label = ("hier_ring_step" if codec_impl == "xla"
             else f"hier_ring_step_{codec_impl}")
    return _audit_ring_strategy(
        mesh,
        get_strategy("ring", compress="int8", topology=topology,
                     codec_impl=codec_impl),
        label, global_batch=global_batch)


def audit_zero1_step(mesh, global_batch: int = 16,
                     fused_update: bool = False) -> list[Finding]:
    """Compile the OVERLAP-AWARE zero1 train step (the default build
    this audit gates since ISSUE 9) — both phases:

    - the **update program** must contain no all-gather at all (the
      2004.13336 anti-pattern is structurally impossible: the program
      ends at the updated shard) — checked at ERROR severity, so a
      change that re-serializes the gather into the step fails CI;
    - the **consume program** (bucketed ring gather) must be
      permute-only — an all-gather reappearing there is the same
      regression wearing the other program's clothes;
    - donation on the update program: the momentum buffers (the only
      donated operands — param_flat cannot alias the sharded output,
      and step/rng are wrapper-carried) must actually alias.

    ``fused_update`` (round 13): audit the AdamW build with the fused
    one-pass update kernel (``AdamWConfig(fused=True)``) — the update
    program the overlap work can least afford to bloat.  The same
    invariants must hold THROUGH the ``pallas_call`` boundary: the
    fused moments still alias (the kernel's ``input_output_aliases``
    must not break the jit-level donation), and the update program
    stays gather-free.

    The legacy sync build (``overlap=False``) still exists for parity
    testing and the bench baseline; it is not audited here because its
    critical-path gather is now a *documented baseline*, not the
    shipped default."""
    import jax
    import jax.numpy as jnp

    from distributed_machine_learning_tpu.parallel.zero1 import (
        make_zero1_train_step,
        shard_zero1_state,
    )

    model, init_state, _ = _vggtest_setup()
    state = init_state()
    if fused_update:
        from distributed_machine_learning_tpu.train.adamw import (
            AdamWConfig,
            adamw_init,
        )

        cfg = AdamWConfig(fused=True)
        state = state.replace(config=cfg,
                              momentum=adamw_init(state.params))
    z1, unravel, n_elems = shard_zero1_state(state, mesh)
    step = make_zero1_train_step(model, mesh, unravel, n_elems,
                                 augment=False, overlap=True)
    zshape = jax.eval_shape(lambda: z1)
    x = jax.ShapeDtypeStruct((global_batch, 32, 32, 3), jnp.float32)
    y = jax.ShapeDtypeStruct((global_batch,), jnp.int32)
    upd_hlo = step.update_for(z1.config).lower(
        zshape.param_flat, zshape.momentum_shards, zshape.batch_stats,
        zshape.step, zshape.rng, x, y,
    ).compile().as_text()
    gather_hlo = step.gather_inner.lower(
        zshape.param_flat
    ).compile().as_text()

    # Donated operands of the update program: momentum (+ BN stats when
    # present) — flat entry params 1..1+len(mom)+len(stats).
    n_donated = len(jax.tree_util.tree_leaves(
        (zshape.momentum_shards, zshape.batch_stats)
    ))
    suffix = "_fused" if fused_update else ""
    findings = audit_donation(
        upd_hlo, range(1, 1 + n_donated), label=f"zero1_update{suffix}")
    findings += audit_critical_path_collectives(
        upd_hlo, kinds=("all-gather",), label=f"zero1_update{suffix}",
        severity="error")
    findings += audit_critical_path_collectives(
        gather_hlo, kinds=("all-gather",), label=f"zero1_gather{suffix}",
        severity="error")
    return findings


def audit_fsdp_perlayer_step(mesh, batch: int = 8, seq: int = 16
                             ) -> list[Finding]:
    """Compile the per-layer (GSPMD) FSDP LM step and verify the
    overlap-aware structure it claims: one all-gather per parameter AT
    ITS USE SITE — so there must be SEVERAL gathers (per-leaf, not one
    monolithic prelude) and NONE of them may feed ROOT (the updated
    params leave the program in their SHARDED layout; a gather feeding
    ROOT would mean the update's output was re-gathered onto the
    critical path — the 2004.13336 anti-pattern in GSPMD clothing)."""
    import jax
    import jax.numpy as jnp

    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )
    from distributed_machine_learning_tpu.parallel.fsdp_perlayer import (
        make_fsdp_pl_lm_train_step,
        shard_fsdp_pl_state,
    )
    from distributed_machine_learning_tpu.train.adamw import AdamWConfig
    from distributed_machine_learning_tpu.train.lm_step import init_lm_state

    model = TransformerLM(vocab_size=64, d_model=32, n_layers=2,
                          n_heads=4, attn_impl="dense")
    state = shard_fsdp_pl_state(
        init_lm_state(model, seed=0, config=AdamWConfig()), mesh
    )
    step = make_fsdp_pl_lm_train_step(model, mesh)
    sshape = jax.eval_shape(lambda: state)
    x = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    y = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    hlo = step.lower(sshape, x, y).compile().as_text()

    findings = []
    gathers = sync_collectives_from_hlo(hlo, kinds=("all-gather",))
    rooted = [g for g in gathers if g["feeds_root"]]
    for g in rooted:
        findings.append(Finding(
            rule=RULE_CRITICAL_PATH, file="fsdp_perlayer_step", line=0,
            message=(
                f"per-layer FSDP all-gather {g['name']} ({g['shape']}) "
                "feeds ROOT — the updated params must leave the program "
                "sharded (gathers belong at the NEXT use site, where "
                "the scheduler overlaps them with the previous layer's "
                "compute); a root-feeding gather puts the weight update "
                "back on the critical path (arxiv 2004.13336)"
            ),
            snippet=f"{g['name']} = {g['shape']} all-gather(...)",
            severity="error", layer=2,
        ))
    # Structural sanity: per-layer means SEVERAL gathers (use-site, one
    # per sharded leaf neighborhood), not one monolithic prelude.
    if len(gathers) < 2:
        findings.append(Finding(
            rule=RULE_CRITICAL_PATH, file="fsdp_perlayer_step", line=0,
            message=(
                f"per-layer FSDP step compiled with {len(gathers)} "
                "all-gather(s) — the per-leaf use-site gathers the "
                "scheme is named for have collapsed into a monolithic "
                "(or absent) gather; overlap with the consuming forward "
                "is no longer possible"
            ),
            severity="error", layer=2,
        ))
    return findings


def run_layer2(mesh=None) -> list[Finding]:
    """The full Layer-2 sweep ``tools/dmlcheck.py --layer2`` runs:
    ring-step donation/collective/jaxpr audits (flat, the round-11
    topology-aware hierarchical build, AND the round-13 fused-codec
    build), the overlap-aware zero1 two-program audit (DML102 at ERROR
    severity since ISSUE 9; reference and fused-AdamW builds), the
    per-layer-FSDP use-site-gather audit, and the wire-byte accounting
    for every wire scheme — whole-ring, per-axis, and through the
    fused int8 kernels (the fusion must never change the wire)."""
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh

    if mesh is None:
        mesh = make_mesh(8)
    findings = audit_ring_step(mesh)
    findings += audit_ring_step(mesh, codec_impl="pallas")
    findings += audit_hier_ring_step(mesh)
    findings += audit_zero1_step(mesh)
    findings += audit_zero1_step(mesh, fused_update=True)
    findings += audit_fsdp_perlayer_step(mesh)
    wire_findings, _ = audit_ring_wire_accounting(
        mesh, 4096, schemes=("none", "bf16", "int8", "topk"))
    findings += wire_findings
    pallas_findings, _ = audit_ring_wire_accounting(
        mesh, 4096, schemes=("int8",), codec_impl="pallas",
        label="ring_all_reduce_pallas")
    findings += pallas_findings
    n = mesh.shape[mesh.axis_names[0]]
    hier_findings, _ = audit_ring_wire_accounting(
        mesh, 4096, schemes=("none", "bf16", "int8", "topk"),
        topology=f"2x{n // 2}" if n % 2 == 0 else f"1x{n}",
        label="hier_ring_all_reduce")
    findings += hier_findings
    return findings
