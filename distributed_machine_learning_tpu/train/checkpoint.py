"""Checkpoint / resume via orbax.

The reference has no checkpointing at all — no ``state_dict``/save/load
anywhere in its 908 LoC (SURVEY.md §5: runs are 40 iterations, results
transcribed by hand).  This subsystem goes beyond parity: save the full
:class:`TrainState` (params, momentum buffers, BN running stats, step
counter, augmentation PRNG key) plus the SGD hyperparameters, and resume
bit-exactly.

TPU-native notes: orbax's OCDBT-backed PyTree checkpointing writes each
host's addressable shards, so the same API covers single-chip and
multi-host pod saves; ``restore`` takes an ``abstract_state`` template so
arrays come back with the correct shardings placed onto the mesh (or as
host arrays when no template is given).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
import zlib
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp

from distributed_machine_learning_tpu.runtime.mesh import (
    BATCH_AXIS,
    ShardSpec,
    padded_len,
    repad_flat,
)
from distributed_machine_learning_tpu.train.state import TrainState

_CONFIG_FILE = "sgd_config.json"
_STATE_DIR = "state"
_MANIFEST_FILE = "manifest.json"
_INVALID_MARKER = ".invalid"

# Absolute checkpoint paths this process has already fully hashed clean
# during GC — complete checkpoints are immutable, so GC (which runs on
# the training thread after every save) trusts one full hash per path
# and falls back to cheap marker/completeness checks afterwards.  A
# re-save over the same step discards the entry, as do quarantining and
# the fault injector's byte-flipper (``forget_validated``).  Content
# that rots on disk after its one hash — outside those doors — is still
# caught where it matters: at restore time, and by any fresh process's
# first full check.
_GC_VALIDATED: set[str] = set()


def forget_validated(path: str | os.PathLike) -> None:
    """Drop ``path`` from the in-process GC validation memo — called by
    anything that mutates a committed checkpoint's bytes (re-saves,
    quarantine verdicts, the chaos injector's bit-flipper), so GC can
    never anchor the keep window on data known to have changed since
    its one full hash."""
    _GC_VALIDATED.discard(os.path.abspath(os.fspath(path)))


class CheckpointVerifyError(RuntimeError):
    """A checkpoint failed end-to-end content verification (manifest
    missing a file, byte-size drift, digest mismatch, or a quarantine
    marker left by an earlier failure).  Raised instead of silently
    materializing garbage into a TrainState."""


def _bump(name: str, events=None) -> None:
    """Increment the named telemetry counter (``ckpt_verify_failures`` /
    ``ckpt_fallbacks``) and, when given, the matching FaultEvents field —
    every verification event must be observable (PR 2's contract)."""
    from distributed_machine_learning_tpu.telemetry import get_telemetry

    tel = get_telemetry()
    if tel is not None:
        tel.registry.counter(name).inc()
    if events is not None and hasattr(events, name):
        setattr(events, name, getattr(events, name) + 1)


# -- manifest: per-leaf + per-file content digests -------------------------
def _file_digest(path: str) -> tuple[str, int]:
    """(sha256 hexdigest, byte size) of a file, streamed."""
    h = hashlib.sha256()
    n = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            n += len(chunk)
            h.update(chunk)
    return h.hexdigest(), n


def _state_files(path: str) -> list[str]:
    """Every file under the orbax state dir, as paths relative to the
    checkpoint root — the on-disk surface the manifest covers."""
    state_dir = os.path.join(path, _STATE_DIR)
    out = []
    for root, _, files in os.walk(state_dir):
        for name in files:
            out.append(os.path.relpath(os.path.join(root, name), path))
    return sorted(out)


def _keystr(keypath) -> str:
    """``(DictKey('params'), DictKey('kernel'))`` → ``params/kernel`` —
    stable, human-readable leaf names for the manifest."""
    parts = []
    for k in keypath:
        parts.append(str(getattr(k, "key", getattr(k, "idx", k))))
    return "/".join(parts)


def _leaf_readable(leaf) -> bool:
    if isinstance(leaf, np.ndarray):
        return True
    if isinstance(leaf, jax.Array):
        return leaf.is_fully_addressable or leaf.is_fully_replicated
    return False


# Leaf names (prefixes) that hold flat world-padded vectors under the
# zero1/fsdp layouts — the leaves whose manifest digests must cover the
# LOGICAL array (the unpadded prefix) so verification survives a
# reshard onto a different world size.
_FLAT_LEAF_PREFIXES = ("param_flat", "param_shards", "momentum_shards")


def _logical_elems(name: str, leaf, spec: ShardSpec | None) -> int | None:
    """The unpadded logical length of ``leaf`` under ``spec``, or None
    for leaves that carry no world-dependent padding (every dp leaf,
    and the replicated stats/step/rng of the flat-shard layouts)."""
    if (spec is None or spec.layout == "dp" or spec.n_elems is None
            or getattr(leaf, "ndim", None) != 1):
        return None
    if not any(name == p or name.startswith(p + "/")
               for p in _FLAT_LEAF_PREFIXES):
        return None
    if leaf.shape[0] != padded_len(spec.n_elems, spec.world):
        return None
    return spec.n_elems


def _leaf_entries(tree, spec: ShardSpec | None = None) -> dict:
    """Per-leaf content digests of an in-memory state pytree: crc32,
    sha256, byte size, dtype, shape.  Computed from the arrays
    themselves (not the files) so verification is end to end — a flip
    anywhere between save and restore is caught at restore time.  Leaves
    not readable from this process (multi-host shards that are neither
    addressable nor replicated) are recorded unverified rather than
    skipped silently.

    Under a flat-shard ``spec`` (zero1/fsdp), the digests of the padded
    flat leaves cover the LOGICAL prefix (``arr[:n_elems]``), recorded
    with a ``logical_elems`` field — a checkpoint restored onto a
    different world size re-pads those leaves, and only the logical
    content is invariant across worlds.  The file-level manifest half
    still covers the physical bytes as written."""
    entries = {}
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    for keypath, leaf in leaves:
        name = _keystr(keypath)
        if not _leaf_readable(leaf):
            entries[name] = {"unverified": "not addressable from the "
                                           "manifest-writing process"}
            continue
        arr = np.ascontiguousarray(np.asarray(jax.device_get(leaf)))
        logical = _logical_elems(name, arr, spec)
        digest_arr = arr if logical is None else arr[:logical]
        raw = digest_arr.tobytes()
        entries[name] = {
            "sha256": hashlib.sha256(raw).hexdigest(),
            "crc32": zlib.crc32(raw) & 0xFFFFFFFF,
            "bytes": len(raw),
            "dtype": str(arr.dtype),
            "shape": list(arr.shape),
        }
        if logical is not None:
            entries[name]["logical_elems"] = logical
    return entries


def write_checkpoint_manifest(path: str | os.PathLike, tree=None,
                              leaf_entries: dict | None = None,
                              shard_spec: ShardSpec | None = None) -> dict:
    """Hash every file under ``path/state`` (and, when ``tree`` or
    precomputed ``leaf_entries`` are given, every array leaf) into
    ``path/manifest.json`` (atomic replace).  Returns the manifest.

    Written between the state dir and the config file, so a complete
    checkpoint (``_is_complete``) always carries its manifest — and a
    kill before the manifest leaves the checkpoint incomplete, never
    complete-but-unverifiable.

    ``shard_spec``: the layout/world the state was saved under —
    recorded in the manifest (and mirrored in the config payload) so
    offline tools and reshard restores know how to recompute partition
    boundaries, and flat-leaf digests cover the logical arrays.
    """
    path = os.path.abspath(os.fspath(path))
    files = {}
    for rel in _state_files(path):
        sha, nbytes = _file_digest(os.path.join(path, rel))
        files[rel] = {"sha256": sha, "bytes": nbytes}
    manifest = {
        "version": 1,
        "files": files,
        "leaves": (leaf_entries if leaf_entries is not None
                   else _leaf_entries(tree, shard_spec)
                   if tree is not None else {}),
    }
    if shard_spec is not None:
        manifest["shard_spec"] = shard_spec.as_dict()
    tmp = os.path.join(path, _MANIFEST_FILE + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
    os.replace(tmp, os.path.join(path, _MANIFEST_FILE))
    return manifest


def checkpoint_manifest(path: str | os.PathLike) -> dict | None:
    """The manifest a checkpoint was saved with, or None for pre-manifest
    (legacy) checkpoints."""
    try:
        with open(os.path.join(os.fspath(path), _MANIFEST_FILE)) as f:
            return json.load(f)
    except FileNotFoundError:
        return None


# -- quarantine: known-bad checkpoints are marked, not re-probed ----------
def quarantine_reason(path: str | os.PathLike) -> str | None:
    """The reason a checkpoint was quarantined (``.invalid`` marker), or
    None for an unmarked one."""
    try:
        with open(os.path.join(os.fspath(path), _INVALID_MARKER)) as f:
            payload = json.load(f)
        return str(payload.get("reason", "unknown"))
    except FileNotFoundError:
        return None
    except (OSError, json.JSONDecodeError):
        return "unreadable quarantine marker"


def quarantine_checkpoint(path: str | os.PathLike, reason: str) -> None:
    """Mark a checkpoint dir known-bad (``.invalid`` marker with the
    reason).  The fallback chain and every reader skip marked dirs
    without re-reading their data; GC may delete them once a newer valid
    checkpoint exists.  Idempotent and race-safe (atomic replace — on a
    shared filesystem every rank writes the same verdict)."""
    path = os.fspath(path)
    forget_validated(path)
    tmp = os.path.join(path, _INVALID_MARKER + ".tmp")
    with open(tmp, "w") as f:
        json.dump({"reason": reason, "time": time.time()}, f)
    os.replace(tmp, os.path.join(path, _INVALID_MARKER))


def _verify_manifest_files(path: str, manifest: dict) -> list[str]:
    """Problems found checking the on-disk files against ``manifest``
    (empty list = all files present, sized, and digest-identical)."""
    problems = []
    for rel, entry in manifest.get("files", {}).items():
        fp = os.path.join(path, rel)
        if not os.path.isfile(fp):
            problems.append(f"missing file {rel}")
            continue
        size = os.path.getsize(fp)
        if size != entry["bytes"]:
            problems.append(
                f"size mismatch {rel}: {size} != {entry['bytes']}"
            )
            continue
        sha, _ = _file_digest(fp)
        if sha != entry["sha256"]:
            problems.append(f"digest mismatch {rel}")
    return problems


def _verify_restored_leaves(tree, leaf_manifest: dict) -> list[str]:
    """Problems comparing restored array leaves to the manifest's
    per-leaf digests (empty = every verifiable leaf matches byte for
    byte).  Leaves recorded unverified, restored with a different dtype
    (a deliberate cast template), or not readable from this process
    (sharded multi-host restores) are skipped — content verification
    covers exactly the leaves whose saved bytes this process can see
    again."""
    restored = {
        _keystr(kp): leaf
        for kp, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]
    }
    problems = []
    for name, entry in leaf_manifest.items():
        if "sha256" not in entry:
            continue  # recorded unverified at save time
        leaf = restored.get(name)
        if leaf is None or not _leaf_readable(leaf):
            continue
        arr = np.ascontiguousarray(np.asarray(jax.device_get(leaf)))
        if str(arr.dtype) != entry["dtype"]:
            continue  # cast restore: saved bytes are not comparable
        logical = entry.get("logical_elems")
        if logical is not None:
            # Flat-padded leaf: the digest covers the logical prefix,
            # which is what survives a reshard onto a different world
            # size (the restored padding may be longer or shorter).
            if arr.ndim != 1 or arr.shape[0] < logical:
                problems.append(
                    f"leaf {name}: shape {arr.shape} cannot hold "
                    f"{logical} logical elements"
                )
                continue
            arr = np.ascontiguousarray(arr[:logical])
        raw = arr.tobytes()
        if len(raw) != entry["bytes"]:
            problems.append(
                f"leaf {name}: {len(raw)} bytes != {entry['bytes']}"
            )
        elif (zlib.crc32(raw) & 0xFFFFFFFF) != entry["crc32"] or (
                hashlib.sha256(raw).hexdigest() != entry["sha256"]):
            problems.append(f"leaf {name}: content digest mismatch")
    return problems


def validate_checkpoint(path: str | os.PathLike) -> list[str]:
    """Why this checkpoint cannot be restored — empty list means valid.

    The single validity predicate shared by the fallback chain
    (``latest_checkpoint``), GC (``gc_checkpoints``), the gang
    supervisor's restore-point election, and ``tools/ckpt_verify.py``:
    quarantine marker, completeness (state dir + config), and manifest
    file digests.  Pre-manifest checkpoints validate on completeness
    alone (legacy compatibility)."""
    path = os.path.abspath(os.fspath(path))
    reason = quarantine_reason(path)
    if reason is not None:
        return [f"quarantined: {reason}"]
    if not _is_complete(path):
        return ["incomplete: state dir or config file missing"]
    try:
        manifest = checkpoint_manifest(path)
    except (OSError, json.JSONDecodeError) as e:
        return [f"manifest unreadable: {e}"]
    if manifest is None:
        return []  # legacy checkpoint: complete == valid
    return _verify_manifest_files(path, manifest)


def _tree_bytes(tree) -> int:
    """Total array payload of a pytree — the telemetry "bytes" figure
    for save/restore spans (shard-local on multi-host runs: each host
    writes its own addressable shards)."""
    return sum(
        int(getattr(leaf, "nbytes", 0) or 0)
        for leaf in jax.tree_util.tree_leaves(tree)
    )


def _record_ckpt_io(tel, kind: str, start_s: float, end_s: float,
                    step: int, nbytes: int) -> None:
    """Span + registry entries for one checkpoint save/restore.  Callers
    guard on ``get_telemetry()`` BEFORE computing ``step``/``nbytes`` —
    both cost a host sync / pytree walk that the telemetry-off default
    must not pay."""
    dur = end_s - start_s
    tel.tracer.complete(f"checkpoint_{kind}", start_s, end_s, step=step,
                        bytes=nbytes)
    tel.registry.histogram(f"checkpoint_{kind}_seconds").observe(dur)
    tel.registry.counter(f"checkpoint_{kind}_bytes_total").inc(nbytes)
    tel.registry.counter(f"checkpoint_{kind}s_total").inc()
    if dur > 0:
        tel.registry.gauge(f"checkpoint_{kind}_mb_per_s").set(
            nbytes / dur / 1e6
        )


@jax.jit
def _copy_arrays(arrays: list) -> list:
    """Identity copy through XLA — every output is a jit-owned buffer.

    Non-donating by construction, so the inputs are left intact.
    """
    import jax.numpy as jnp

    return [jnp.asarray(a).copy() for a in arrays]


def fresh_buffers(tree):
    """Re-materialize every array leaf of ``tree`` into an XLA-owned
    buffer (via a non-donating jitted copy); non-array leaves pass
    through untouched.

    The ONE sanctioned conversion before handing arrays to a
    ``donate_argnums`` step.  Arrays from orbax/tensorstore restores, or
    zero-copied host numpy (the CPU backend aliases any 64-byte-aligned
    numpy buffer), are backed by memory XLA does not own; donating them
    frees that memory with XLA's allocator — heap corruption that
    segfaults at some LATER free.  Jit outputs are the same ownership
    class init states come from, which donation handles correctly.
    Uncommitted inputs stay uncommitted (the dp/ring shard_map paths
    rely on this).  Used by :func:`restore_checkpoint`, the
    supervisor's init-state copy, and the LM CLI's commitment fix-up.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = list(leaves)
    idx = [i for i, x in enumerate(leaves)
           if isinstance(x, (jax.Array, np.ndarray))]
    if idx:
        copied = _copy_arrays([leaves[i] for i in idx])
        for i, c in zip(idx, copied):
            out[i] = c
    return jax.tree_util.tree_unflatten(treedef, out)


def _state_pytree(state) -> dict:
    """The array-valued part of a training state (the config dataclass
    is static metadata).  Handles the three checkpointable layouts by
    their leaf fields (duck-typed — the Zero1State/FSDPState dataclasses
    live in ``parallel/`` and must not be imported here): a replicated
    ``TrainState`` (dp), ``Zero1State`` (``param_flat``), and
    ``FSDPState`` (``param_shards``).  The flat-shard trees keep their
    own leaf names so a restore can never silently unflatten the wrong
    layout."""
    if hasattr(state, "param_shards"):  # FSDPState
        return {
            "param_shards": state.param_shards,
            "momentum_shards": state.momentum_shards,
            "batch_stats": state.batch_stats,
            "step": state.step,
            "rng": state.rng,
        }
    if hasattr(state, "param_flat"):  # Zero1State
        return {
            "param_flat": state.param_flat,
            "momentum_shards": state.momentum_shards,
            "batch_stats": state.batch_stats,
            "step": state.step,
            "rng": state.rng,
        }
    return {
        "params": state.params,
        "momentum": state.momentum,
        "batch_stats": state.batch_stats,
        "step": state.step,
        "rng": state.rng,
    }


def state_layout(state) -> str:
    """The :data:`~..runtime.mesh.SHARD_LAYOUTS` name of a state's
    type — the layout half of the ShardSpec a save should carry."""
    if hasattr(state, "param_shards"):
        return "fsdp"
    if hasattr(state, "param_flat"):
        return "zero1"
    return "dp"


def _check_shard_spec(state, shard_spec: ShardSpec | None) -> None:
    """A flat-shard state saved without (or with a mismatched) spec is
    unrestorable-by-construction — fail the save, not the restore."""
    layout = state_layout(state)
    if shard_spec is None:
        if layout != "dp":
            raise ValueError(
                f"saving a {layout} state requires a shard_spec "
                "(world size + unpadded flat length); without it the "
                "padded vectors cannot be resharded or verified"
            )
        return
    if shard_spec.layout != layout:
        raise ValueError(
            f"shard_spec.layout={shard_spec.layout!r} does not match "
            f"the state's layout {layout!r}"
        )
    if layout == "dp":
        return
    # The spec's (world, n_elems) must describe THIS state's padded
    # vectors exactly: a mismatch would record no logical digests (or
    # wrong ones), and a later reshard would silently truncate real
    # parameter values to the claimed n_elems.
    flat = (state.param_shards if layout == "fsdp" else state.param_flat)
    expect = padded_len(shard_spec.n_elems, shard_spec.world)
    if getattr(flat, "ndim", None) != 1 or flat.shape[0] != expect:
        raise ValueError(
            f"shard_spec {shard_spec} expects a flat vector of "
            f"{expect} elements (padded_len({shard_spec.n_elems}, "
            f"{shard_spec.world})), but the state's is "
            f"{getattr(flat, 'shape', None)} — wrong world or n_elems "
            "would silently drop parameter data on reshard"
        )


def save_checkpoint(directory: str | os.PathLike, state,
                    layout: str | None = None, cursor: int | None = None,
                    mid_save_hook=None, keep_last_n: int | None = None,
                    post_save_hook=None,
                    shard_spec: ShardSpec | None = None,
                    extra_payload: dict | None = None) -> str:
    """Write `state` under `directory/step_<n>/`; returns the path written.

    ``state`` may be a replicated :class:`TrainState` (dp) or one of the
    flat-shard states (``parallel/zero1.py::Zero1State``,
    ``parallel/fsdp.py::FSDPState``); the latter REQUIRE a matching
    ``shard_spec`` — their padded flat vectors are meaningless without
    the world size and unpadded length that produced them.

    Only process 0's metadata file is written once; array shards are saved
    by every host (orbax handles the multi-host coordination).

    ``layout``: optional tag naming the PARAMETER layout (e.g. the
    pipeline schedules' block-stacking orders, which share one tree
    structure but permute the layers) — recorded so a resume under a
    different layout can be rejected instead of silently loading
    permuted weights (``checkpoint_layout``).

    ``cursor``: optional data-stream position (batches consumed).  The
    step counter alone under-counts it once the non-finite-gradient
    guard has skipped a batch, so the supervisor records the true
    position for exact replay (``checkpoint_cursor``).  Stored in the
    config payload — written last — so a checkpoint is never complete
    with a missing cursor.

    ``mid_save_hook``: test/chaos hook called between the state write
    and the config write — the crash window ``_is_complete`` guards
    (``runtime/faults.py`` kills here to prove resume falls back).

    ``keep_last_n``: if set, garbage-collect older checkpoints after
    this save completes (``gc_checkpoints``) so supervised long runs
    don't fill the disk.

    ``post_save_hook``: test/chaos hook called with the written path
    after the checkpoint is fully committed (state + manifest + config)
    — the bit-rot window ``runtime/faults.py``'s ``corrupt_ckpt`` fault
    flips bytes in, proving the verification chain catches it.

    ``shard_spec``: the layout/world the state is laid out for
    (``runtime/mesh.py::ShardSpec``) — recorded in the manifest and the
    config payload so the checkpoint can be restored onto a DIFFERENT
    world size (``reshard_restore``) with its flat-leaf digests
    verified against the logical arrays.

    ``extra_payload``: optional JSON-serializable dict of caller
    metadata riding the config payload (under ``__extra__``, so it can
    never collide with a config field) — e.g. the elastic gang
    worker's cumulative example cursor, whose meaning only the caller
    knows.  Read back with :func:`checkpoint_extra`.

    Verification: before the config file (the completeness marker)
    lands, a ``manifest.json`` records a sha256 + byte size for every
    file under the state dir and a crc32/sha256/size/dtype/shape for
    every array leaf — ``restore_checkpoint`` verifies both ends, and
    ``latest_checkpoint`` falls back past checkpoints that no longer
    match.
    """
    directory = os.path.abspath(os.fspath(directory))
    _check_shard_spec(state, shard_spec)
    step = int(jax.device_get(state.step))
    path = os.path.join(directory, f"step_{step}")
    _GC_VALIDATED.discard(path)  # a re-save invalidates the GC memo
    t0 = time.perf_counter()
    tree = _state_pytree(state)
    with ocp.PyTreeCheckpointer() as ckptr:
        # force=True: re-saving the same step (e.g. rerunning a crashed job
        # into the same --ckpt-dir) overwrites instead of raising.
        ckptr.save(os.path.join(path, _STATE_DIR), tree, force=True)
    if mid_save_hook is not None:
        mid_save_hook()
    if jax.process_index() == 0:
        # A re-save over a quarantined dir is a fresh checkpoint: the
        # old verdict must not outlive the data it judged.
        try:
            os.remove(os.path.join(path, _INVALID_MARKER))
        except FileNotFoundError:
            pass
        write_checkpoint_manifest(path, tree, shard_spec=shard_spec)
        with open(os.path.join(path, _CONFIG_FILE), "w") as f:
            # Record the config class so restore rebuilds the right
            # optimizer config (LARSConfig carries extra fields that
            # SGDConfig(**...) would reject).
            payload = {"__class__": type(state.config).__name__,
                       **dataclasses.asdict(state.config)}
            if layout is not None:
                payload["__layout__"] = layout
            if cursor is not None:
                payload["__cursor__"] = int(cursor)
            if shard_spec is not None:
                payload["__shard_spec__"] = shard_spec.as_dict()
            if extra_payload:
                payload["__extra__"] = dict(extra_payload)
            json.dump(payload, f)
        # The manifest was just computed from these very bytes: the GC
        # below (and every later pass) must not immediately re-hash
        # them on the training thread.
        _GC_VALIDATED.add(path)
        if keep_last_n is not None:
            gc_checkpoints(directory, keep_last_n)
        if post_save_hook is not None:
            post_save_hook(path)
    # A save that died above (e.g. the injected kill) records no span —
    # the torn attempt is visible as the fault instant + missing save.
    from distributed_machine_learning_tpu.telemetry import get_telemetry

    tel = get_telemetry()
    if tel is not None:
        _record_ckpt_io(tel, "save", t0, time.perf_counter(), step,
                        _tree_bytes(tree))
    return path


def gc_checkpoints(directory: str | os.PathLike, keep_last_n: int
                   ) -> list[str]:
    """Delete old checkpoints, keeping the newest ``keep_last_n``
    *valid* ones; returns the paths removed.

    Validity is the fallback chain's check (``validate_checkpoint``):
    complete, unquarantined, manifest digests intact.  The newest valid
    checkpoint is never deleted (it is the resume anchor — losing it
    turns every later fault into a from-scratch restart), and a corrupt
    NEWEST dir therefore cannot trick GC into retaining only garbage:
    the corrupt dir doesn't count, so the newest intact one stays
    protected.  Non-valid directories (crash leftovers, quarantined
    dirs) are removed only when a valid checkpoint with a HIGHER step
    exists: an older one is garbage, but a newer one may be an in-flight
    async save that simply hasn't committed yet — or the only copy of
    anything, corrupt or not.
    """
    import shutil

    if keep_last_n < 1:
        raise ValueError(f"keep_last_n must be >= 1, got {keep_last_n}")
    directory = os.path.abspath(os.fspath(directory))
    if not os.path.isdir(directory):
        return []
    steps = []
    for name in os.listdir(directory):
        if name.startswith("step_") and name[5:].isdigit():
            steps.append(int(name[5:]))
    # Walk newest-first, validating only until the keep window is full:
    # everything older gets deleted (valid or not) once keep_last_n
    # valid checkpoints exist above it, so hashing it would be wasted
    # I/O — this runs on the training thread after every save.  A
    # complete dir that fails its digests is quarantined ON DISCOVERY
    # (same as the fallback chain), so later GC passes short-circuit on
    # the marker instead of re-hashing known-bad data forever; a dir
    # this process already hashed clean is trusted on cheap checks
    # alone (``_GC_VALIDATED`` — checkpoints are immutable once
    # complete, and restore-time verification remains the authoritative
    # content check for anything that rots after its one full hash).
    keep: set[int] = set()
    newest_valid: int | None = None
    validated_bad: set[int] = set()
    for s in sorted(steps, reverse=True):
        if len(keep) >= keep_last_n:
            break
        path = os.path.join(directory, f"step_{s}")
        if (path in _GC_VALIDATED and _is_complete(path)
                and quarantine_reason(path) is None):
            problems: list[str] = []
        else:
            problems = validate_checkpoint(path)
        if not problems:
            _GC_VALIDATED.add(path)
            keep.add(s)
            if newest_valid is None:
                newest_valid = s
            continue
        validated_bad.add(s)
        if (_is_complete(path) and quarantine_reason(path) is None):
            quarantine_checkpoint(path, "; ".join(problems))
            _bump("ckpt_verify_failures")
    removed = []
    for s in steps:
        if s in keep:
            continue
        if s in validated_bad and (newest_valid is None
                                   or s >= newest_valid):
            continue  # possibly an in-flight save — leave it alone
        path = os.path.join(directory, f"step_{s}")
        shutil.rmtree(path, ignore_errors=True)
        removed.append(path)
    return removed


class AsyncCheckpointWriter:
    """Non-blocking checkpoint saves — training continues while orbax
    serializes in a background thread.

    At LM scale a synchronous save stalls every step for seconds; the
    async writer hides that behind compute (the standard production
    setup).  Layout and completeness semantics are identical to
    :func:`save_checkpoint`: orbax writes the state dir to a temp name
    and renames atomically on finish, and the config file alone does not
    satisfy ``_is_complete`` — so an in-flight or crashed async save is
    invisible to ``latest_checkpoint`` until it actually lands.

    Call :meth:`wait` before process exit (or rely on ``close``); a new
    ``save`` transparently waits for the previous one (orbax serializes
    saves on one thread).

    Write-order invariant: the config file is deferred until
    ``wait_until_finished`` of ITS OWN save has returned (flushed at the
    next ``save``/``wait``/``close``).  Writing it eagerly would break
    the ``_is_complete`` contract — a crash after the config landed but
    before orbax committed the state dir... cannot happen (orbax renames
    atomically), but the converse ordering CAN: an eager config plus a
    crashed orbax *rename race* would present a complete-looking
    checkpoint with no state.  More concretely: ``_is_complete``
    documents "config written after the state dir", and the async path
    must honor the same ordering the sync path does.  The cost is that
    an async checkpoint becomes visible to ``latest_checkpoint`` only at
    the next sync point — which is exactly when the caller can first
    rely on it anyway.
    """

    def __init__(self):
        self._ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
        self._pending: tuple | None = None
        # (start_s, step, nbytes) of the in-flight save, when telemetry
        # is on — recorded as a checkpoint_save span at the flush that
        # commits it (the span covers dispatch → durable-on-disk, the
        # honest window for an async save).
        self._inflight_telemetry: tuple[float, int, int] | None = None

    def save(self, directory: str | os.PathLike, state,
             cursor: int | None = None,
             keep_last_n: int | None = None,
             shard_spec: ShardSpec | None = None) -> str:
        directory = os.path.abspath(os.fspath(directory))
        _check_shard_spec(state, shard_spec)
        step = int(jax.device_get(state.step))
        path = os.path.join(directory, f"step_{step}")
        # Flush the PREVIOUS save's config first: this also orders saves
        # (orbax would serialize them anyway) and guarantees at most one
        # pending config at a time.
        self._flush_pending()
        from distributed_machine_learning_tpu.telemetry import (
            get_telemetry,
        )

        if get_telemetry() is not None:
            self._inflight_telemetry = (
                time.perf_counter(), step,
                _tree_bytes(_state_pytree(state)),
            )
        _GC_VALIDATED.discard(path)  # a re-save invalidates the GC memo
        tree = _state_pytree(state)
        self._ckptr.save(
            os.path.join(path, _STATE_DIR), tree, force=True
        )
        if jax.process_index() == 0:
            payload = {"__class__": type(state.config).__name__,
                       **dataclasses.asdict(state.config)}
            if cursor is not None:
                payload["__cursor__"] = int(cursor)
            if shard_spec is not None:
                payload["__shard_spec__"] = shard_spec.as_dict()
            # Per-leaf digests are computed NOW, while the caller's
            # arrays are still alive (the next train step may donate
            # them); the per-FILE half of the manifest can only be
            # hashed at flush time, once orbax has committed the state
            # dir.
            self._pending = (path, payload, directory, keep_last_n,
                             _leaf_entries(tree, shard_spec), shard_spec)
        return path

    def _flush_pending(self) -> None:
        self._ckptr.wait_until_finished()
        if self._inflight_telemetry is not None:
            from distributed_machine_learning_tpu.telemetry import (
                get_telemetry,
            )

            t0, step, nbytes = self._inflight_telemetry
            self._inflight_telemetry = None
            tel = get_telemetry()
            if tel is not None:
                _record_ckpt_io(tel, "save", t0, time.perf_counter(),
                                step, nbytes)
        if self._pending is not None:
            (path, payload, directory, keep_last_n, leaf_entries,
             shard_spec) = self._pending
            os.makedirs(path, exist_ok=True)
            try:
                os.remove(os.path.join(path, _INVALID_MARKER))
            except FileNotFoundError:
                pass
            # Same write order as the sync path: manifest before the
            # config file, so complete always implies verifiable.
            write_checkpoint_manifest(path, leaf_entries=leaf_entries,
                                      shard_spec=shard_spec)
            with open(os.path.join(path, _CONFIG_FILE), "w") as f:
                json.dump(payload, f)
            _GC_VALIDATED.add(path)  # manifest just hashed these bytes
            self._pending = None
            # GC only after the save is complete: the just-flushed
            # checkpoint is now the newest complete one and therefore
            # protected, same as the sync path.
            if keep_last_n is not None:
                gc_checkpoints(directory, keep_last_n)

    def wait(self) -> None:
        """Block until the in-flight save (if any) is fully on disk AND
        its config file (completeness marker) is written."""
        self._flush_pending()

    def close(self) -> None:
        self.wait()
        self._ckptr.close()

    def __enter__(self) -> "AsyncCheckpointWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _is_complete(path: str) -> bool:
    """A checkpoint is complete iff both halves landed: the orbax state dir
    (orbax writes to a tmp dir and renames atomically, so a crashed save
    never leaves a final-named `state/`) and the config file written after
    it.  An interrupted save therefore fails this check."""
    return os.path.isdir(os.path.join(path, _STATE_DIR)) and os.path.isfile(
        os.path.join(path, _CONFIG_FILE)
    )


def latest_checkpoint(directory: str | os.PathLike,
                      events=None) -> str | None:
    """Highest-step *valid* `step_<n>` subdirectory of `directory`, or
    None — a fallback CHAIN, not a single probe.

    Walking down from the newest step: incomplete checkpoints (crash
    mid-save, in-flight async save) are skipped silently as before;
    already-quarantined dirs are skipped without touching their data;
    and a complete checkpoint whose manifest digests no longer match
    (bit flip, truncation, torn shard) is quarantined with an
    ``.invalid`` marker and skipped — each such discovery counts one
    ``ckpt_verify_failures`` and one ``ckpt_fallbacks`` — so resume
    lands on the newest checkpoint that is actually restorable instead
    of crashing on (or silently restoring) garbage."""
    directory = os.fspath(directory)
    if not os.path.isdir(directory):
        return None
    steps = []
    for name in os.listdir(directory):
        if name.startswith("step_") and name[5:].isdigit():
            steps.append(int(name[5:]))
    for step in sorted(steps, reverse=True):
        path = os.path.join(directory, f"step_{step}")
        if quarantine_reason(path) is not None:
            continue  # known bad: counted when first quarantined
        if not _is_complete(path):
            continue  # crash leftover or in-flight save — never marked
        problems = validate_checkpoint(path)
        if problems:
            quarantine_checkpoint(path, "; ".join(problems))
            _bump("ckpt_verify_failures", events)
            _bump("ckpt_fallbacks", events)
            from distributed_machine_learning_tpu.utils.logging import (
                rank0_print,
            )

            rank0_print(
                f"[checkpoint] {path} failed verification "
                f"({problems[0]}{' …' if len(problems) > 1 else ''}); "
                "quarantined, falling back to the previous valid "
                "checkpoint"
            )
            continue
        return path
    return None


def checkpoint_config(path: str | os.PathLike):
    """The optimizer config instance a checkpoint was saved with — lets a
    resume build its abstract template with the *saved* momentum layout
    (AdamW's moment dict vs SGD's buffer tree) before restoring.

    Quarantined checkpoints raise :class:`CheckpointVerifyError` without
    opening any data file: resume-time probing must never read
    known-bad checkpoints."""
    reason = quarantine_reason(path)
    if reason is not None:
        raise CheckpointVerifyError(
            f"checkpoint {os.fspath(path)} is quarantined ({reason}); "
            "refusing to read its config"
        )
    with open(os.path.join(os.fspath(path), _CONFIG_FILE)) as f:
        payload = json.load(f)
    from distributed_machine_learning_tpu.train.optimizers import (
        config_class_by_name,
    )

    # "SGDConfig" default: checkpoints written before the class tag existed.
    payload.pop("__layout__", None)  # layout tag is checkpoint_layout's
    payload.pop("__cursor__", None)  # data cursor is checkpoint_cursor's
    payload.pop("__shard_spec__", None)  # spec is checkpoint_shard_spec's
    payload.pop("__extra__", None)  # caller metadata is checkpoint_extra's
    return config_class_by_name(payload.pop("__class__", "SGDConfig"))(
        **payload
    )


def checkpoint_shard_spec(path: str | os.PathLike) -> ShardSpec | None:
    """The :class:`~..runtime.mesh.ShardSpec` a checkpoint was saved
    under, or None for spec-less checkpoints (legacy saves, and plain
    dp saves that never recorded one — both restore as replicated dp).
    Quarantined and torn checkpoints read as None: known-bad data is
    never probed for metadata."""
    if quarantine_reason(path) is not None:
        return None
    try:
        with open(os.path.join(os.fspath(path), _CONFIG_FILE)) as f:
            payload = json.load(f).get("__shard_spec__")
    except (OSError, json.JSONDecodeError):
        return None
    if payload is None:
        return None
    try:
        return ShardSpec.from_dict(payload)
    except (KeyError, TypeError, ValueError):
        return None


def checkpoint_cursor(path: str | os.PathLike) -> int | None:
    """The data-stream position (batches consumed) a checkpoint was saved
    at, or None for checkpoints saved without one.  Diverges from the
    step counter once the non-finite-gradient guard has skipped a batch;
    the supervisor replays from the cursor so the post-restart stream is
    exactly the pre-crash one.

    Quarantined (``.invalid``-marked) and torn checkpoints read as None
    rather than raising or returning garbage — callers fall back to the
    state's own step counter, which is always safe (it merely replays a
    few extra batches)."""
    if quarantine_reason(path) is not None:
        return None
    try:
        with open(os.path.join(os.fspath(path), _CONFIG_FILE)) as f:
            cursor = json.load(f).get("__cursor__")
    except (OSError, json.JSONDecodeError):
        return None
    return None if cursor is None else int(cursor)


def checkpoint_extra(path: str | os.PathLike) -> dict:
    """The caller-metadata dict a checkpoint was saved with
    (``save_checkpoint(extra_payload=...)``); empty for checkpoints
    without one, and for quarantined/torn dirs (same known-bad-data
    rule as :func:`checkpoint_cursor`)."""
    if quarantine_reason(path) is not None:
        return {}
    try:
        with open(os.path.join(os.fspath(path), _CONFIG_FILE)) as f:
            extra = json.load(f).get("__extra__")
    except (OSError, json.JSONDecodeError):
        return {}
    return extra if isinstance(extra, dict) else {}


def checkpoint_layout(path: str | os.PathLike) -> str | None:
    """The parameter-layout tag a checkpoint was saved with (see
    ``save_checkpoint``); None for plain layouts, pre-tag checkpoints,
    and quarantined dirs (whose data must not be probed)."""
    if quarantine_reason(path) is not None:
        return None
    with open(os.path.join(os.fspath(path), _CONFIG_FILE)) as f:
        return json.load(f).get("__layout__")


def checkpoint_array_shapes(path: str | os.PathLike) -> dict:
    """Shapes of the arrays a checkpoint holds — a pure metadata read
    (no array IO).  For callers that must pick a restore template by the
    SAVED layout (e.g. ``--unsync-bn``'s stacked ``[world, C]`` BN stats
    vs a pre-quirk checkpoint's plain ``[C]``) instead of fishing
    structure mismatches out of a blanket except."""
    path = os.path.abspath(os.fspath(path))
    with ocp.PyTreeCheckpointer() as ckptr:
        meta = ckptr.metadata(os.path.join(path, _STATE_DIR))
    tree = meta.item_metadata
    tree = tree.tree if hasattr(tree, "tree") else tree
    return jax.tree_util.tree_map(lambda m: tuple(m.shape), tree)


def restore_checkpoint(
    path: str | os.PathLike, abstract_state: TrainState | None = None,
    *, files_verified: bool = False,
) -> TrainState:
    """Load the TrainState saved at `path` (a `step_<n>` directory).

    `abstract_state` (e.g. the freshly initialized state, possibly with
    sharded arrays) restores each leaf with matching dtype/sharding; without
    it, arrays land unsharded on the default device.

    Verification is end to end: the on-disk files are checked against
    the manifest BEFORE orbax touches them, and every restored leaf's
    content digest is checked against the manifest's per-leaf
    crc32/sha256 BEFORE the state is materialized for training — a
    mismatch quarantines the checkpoint and raises
    :class:`CheckpointVerifyError` instead of silently training on
    garbage.  Pre-manifest checkpoints restore unverified (legacy).

    ``files_verified=True`` skips the pre-restore file sweep: for
    callers that just received ``path`` from ``latest_checkpoint`` (the
    chain ran the identical sha256 pass moments ago) the second sweep
    would double resume-time read I/O for nothing — gang restart
    latency rides directly against the peers' stall window.  The
    post-restore per-leaf content check still runs either way.
    """
    path = os.path.abspath(os.fspath(path))
    reason = quarantine_reason(path)
    if reason is not None:
        raise CheckpointVerifyError(
            f"checkpoint {path} is quarantined ({reason})"
        )
    manifest = checkpoint_manifest(path)
    if manifest is not None and not files_verified:
        problems = _verify_manifest_files(path, manifest)
        if problems:
            quarantine_checkpoint(path, "; ".join(problems))
            _bump("ckpt_verify_failures")
            raise CheckpointVerifyError(
                f"checkpoint {path} failed file verification: "
                + "; ".join(problems[:3])
            )
    t0 = time.perf_counter()
    restore_args: Any = None
    if abstract_state is not None:
        template = jax.tree_util.tree_map(
            ocp.utils.to_shape_dtype_struct, _state_pytree(abstract_state)
        )
        restore_args = ocp.args.PyTreeRestore(
            item=template,
            restore_args=ocp.checkpoint_utils.construct_restore_args(template),
        )
    with ocp.PyTreeCheckpointer() as ckptr:
        if restore_args is not None:
            tree = ckptr.restore(os.path.join(path, _STATE_DIR), args=restore_args)
        else:
            tree = ckptr.restore(os.path.join(path, _STATE_DIR))
    if manifest is not None and manifest.get("leaves"):
        problems = _verify_restored_leaves(tree, manifest["leaves"])
        if problems:
            quarantine_checkpoint(path, "; ".join(problems))
            _bump("ckpt_verify_failures")
            raise CheckpointVerifyError(
                f"checkpoint {path} failed content verification after "
                "restore: " + "; ".join(problems[:3])
            )
    # Re-materialize every leaf into an XLA-owned buffer (see
    # fresh_buffers: restored tensorstore/zero-copy-aliased leaves fed
    # to a donating step are a deferred heap corruption — this
    # reproducibly segfaulted resume paths on CPU).  Host-side
    # round-trips (np.array + device_put / jnp.asarray) do NOT work:
    # they re-enter the zero-copy path whenever malloc hands back a
    # 64-byte-aligned block, which is why the failure was flaky.  One
    # copy of the state per restore is noise next to training; losing a
    # run to a heap corruption after a restart is the exact failure the
    # resilience layer exists to prevent.
    tree = fresh_buffers(tree)
    config = checkpoint_config(path)
    from distributed_machine_learning_tpu.telemetry import get_telemetry

    tel = get_telemetry()
    if tel is not None:
        _record_ckpt_io(
            tel, "restore", t0, time.perf_counter(),
            int(jax.device_get(tree["step"])), _tree_bytes(tree),
        )
    return TrainState(
        params=tree["params"],
        momentum=tree["momentum"],
        batch_stats=tree.get("batch_stats") or {},
        step=tree["step"],
        rng=tree["rng"],
        config=config,
    )


# -- elastic restore: reshard a checkpoint onto a different world ----------
def _host_state_tree(path: str) -> dict:
    """The saved state tree as host numpy arrays, restored at the SAVED
    shapes regardless of this process's device topology — the neutral
    form a reshard slices and re-pads.  (A plain orbax restore would
    re-apply the saved sharding, which need not exist on the restoring
    host: the elastic case is precisely a different topology.)"""
    state_dir = os.path.join(path, _STATE_DIR)
    with ocp.PyTreeCheckpointer() as ckptr:
        meta = ckptr.metadata(state_dir)
        tree = getattr(meta, "item_metadata", meta)
        tree = tree.tree if hasattr(tree, "tree") else tree
        restore_args = jax.tree_util.tree_map(
            lambda m: ocp.RestoreArgs(restore_type=np.ndarray), tree
        )
        template = jax.tree_util.tree_map(
            lambda m: jax.ShapeDtypeStruct(tuple(m.shape), m.dtype), tree
        )
        return ckptr.restore(
            state_dir,
            args=ocp.args.PyTreeRestore(item=template,
                                        restore_args=restore_args),
        )


def reshard_restore(
    path: str | os.PathLike, *, world: int | None = None, mesh=None,
    axis_name: str = BATCH_AXIS, events=None, files_verified: bool = False,
):
    """Restore the checkpoint at ``path`` onto a (possibly different)
    world size — the elastic half of the restore surface.

    The checkpoint carries the :class:`~..runtime.mesh.ShardSpec` it was
    saved under (``checkpoint_shard_spec``); this restores the LOGICAL
    state and re-lays it out for the target world:

    - ``dp``: leaves carry no world-dependent padding — a plain restore,
      replicated onto ``mesh`` when given;
    - ``zero1``/``fsdp``: the flat padded vectors are restored at their
      saved shapes (host-side, topology-independent), verified against
      the manifest's LOGICAL leaf digests, then sliced to ``n_elems``
      and re-padded for the target world — partition boundaries are
      recomputed, content is preserved bit for bit.

    Target selection: ``mesh`` (its ``axis_name`` size wins), else
    ``world``, else the saved world (a plain same-layout restore).
    Layout conversion is NOT attempted: a zero1 checkpoint restores as a
    ``Zero1State``, fsdp as ``FSDPState``, dp as ``TrainState`` (the
    flat layouts don't record the unravel needed to rebuild a params
    tree).  Returns ``(state, spec)`` with ``spec`` re-aimed at the
    target world.  A restore whose target differs from the saved world
    counts one ``reshard_restores`` (telemetry + FaultEvents).

    Spec-less (legacy / plain dp) checkpoints restore as dp at any
    target — they were never padded, so every world size fits.
    """
    path = os.path.abspath(os.fspath(path))
    reason = quarantine_reason(path)
    if reason is not None:
        raise CheckpointVerifyError(
            f"checkpoint {path} is quarantined ({reason})"
        )
    manifest = checkpoint_manifest(path)
    if manifest is not None and not files_verified:
        problems = _verify_manifest_files(path, manifest)
        if problems:
            quarantine_checkpoint(path, "; ".join(problems))
            _bump("ckpt_verify_failures", events)
            raise CheckpointVerifyError(
                f"checkpoint {path} failed file verification: "
                + "; ".join(problems[:3])
            )
    spec = checkpoint_shard_spec(path)
    saved_spec = spec if spec is not None else ShardSpec("dp", world=1)
    if mesh is not None:
        target_world = int(mesh.shape[axis_name])
    elif world is not None:
        target_world = int(world)
    else:
        target_world = saved_spec.world
    # Spec-less checkpoints were never world-padded: nothing to reshard.
    resharding = spec is not None and target_world != saved_spec.world
    t0 = time.perf_counter()

    if saved_spec.layout == "dp":
        state = restore_checkpoint(path, files_verified=True)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            state = jax.device_put(
                state, NamedSharding(mesh, PartitionSpec())
            )
    else:
        tree = _host_state_tree(path)
        if manifest is not None and manifest.get("leaves"):
            problems = _verify_restored_leaves(tree, manifest["leaves"])
            if problems:
                quarantine_checkpoint(path, "; ".join(problems))
                _bump("ckpt_verify_failures", events)
                raise CheckpointVerifyError(
                    f"checkpoint {path} failed content verification "
                    "after restore: " + "; ".join(problems[:3])
                )
        n_elems = saved_spec.n_elems
        config = checkpoint_config(path)

        def _repad(a):
            return repad_flat(a, n_elems, target_world)

        flat_key = ("param_shards" if saved_spec.layout == "fsdp"
                    else "param_flat")
        param_vec = _repad(tree[flat_key])
        momentum = jax.tree_util.tree_map(_repad, tree["momentum_shards"])
        batch_stats = tree.get("batch_stats") or {}
        step, rng = tree["step"], tree["rng"]
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            sharded = NamedSharding(mesh, P(axis_name))
            replicated = NamedSharding(mesh, P())
            # zero1 keeps params replicated; fsdp shards them too.
            param_vec = jax.device_put(
                param_vec,
                sharded if saved_spec.layout == "fsdp" else replicated,
            )
            momentum = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, sharded), momentum
            )
            batch_stats = jax.device_put(batch_stats, replicated)
            step = jax.device_put(step, replicated)
            rng = jax.device_put(rng, replicated)
        else:
            # Host numpy leaves must still become XLA-owned buffers —
            # the flat-shard steps donate their input state (see
            # fresh_buffers).
            param_vec, momentum, batch_stats, step, rng = fresh_buffers(
                (param_vec, momentum, batch_stats, step, rng)
            )
        if saved_spec.layout == "fsdp":
            from distributed_machine_learning_tpu.parallel.fsdp import (
                FSDPState,
            )

            state = FSDPState(
                param_shards=param_vec, momentum_shards=momentum,
                batch_stats=batch_stats, step=step, rng=rng, config=config,
            )
        else:
            from distributed_machine_learning_tpu.parallel.zero1 import (
                Zero1State,
            )

            state = Zero1State(
                param_flat=param_vec, momentum_shards=momentum,
                batch_stats=batch_stats, step=step, rng=rng, config=config,
            )

    if resharding:
        _bump("reshard_restores", events)
        from distributed_machine_learning_tpu.utils.logging import (
            rank0_print,
        )

        rank0_print(
            f"[checkpoint] resharded {path} ({saved_spec.layout}) from "
            f"world {saved_spec.world} onto world {target_world}"
        )
    from distributed_machine_learning_tpu.telemetry import get_telemetry

    tel = get_telemetry()
    if tel is not None:
        # The dp branch delegated to restore_checkpoint, which already
        # recorded this restore's span/bytes/counter — recording again
        # here would double every dp restore in the I/O accounting.
        if saved_spec.layout != "dp":
            _record_ckpt_io(
                tel, "restore", t0, time.perf_counter(),
                int(jax.device_get(state.step)),
                _tree_bytes(_state_pytree(state)),
            )
        if resharding:
            tel.tracer.instant(
                "reshard_restore", layout=saved_spec.layout,
                from_world=saved_spec.world, to_world=target_world,
            )
    return state, saved_spec.with_world(target_world)


# -- fallback-chain diagnostics -------------------------------------------
class NoRestorableCheckpointError(CheckpointVerifyError):
    """Every candidate in the fallback chain is unusable (quarantined,
    incomplete, or digest-mismatched).  The message lists each candidate
    with its verdict — the 3am operator must see WHY resume is
    impossible, not a bare "no checkpoint found"."""


def checkpoint_chain_report(directory: str | os.PathLike
                            ) -> list[tuple[str, str]]:
    """(path, verdict) for every ``step_<n>`` candidate under
    ``directory``, newest first — ``"valid"``, ``"quarantined: <why>"``,
    ``"incomplete: ..."``, or the first digest problem.  The diagnostic
    behind :class:`NoRestorableCheckpointError`; also useful on its own
    for status tooling."""
    directory = os.fspath(directory)
    out: list[tuple[str, str]] = []
    if not os.path.isdir(directory):
        return out
    steps = []
    for name in os.listdir(directory):
        if name.startswith("step_") and name[5:].isdigit():
            steps.append(int(name[5:]))
    for step in sorted(steps, reverse=True):
        path = os.path.join(directory, f"step_{step}")
        reason = quarantine_reason(path)
        if reason is not None:
            verdict = f"quarantined: {reason}"
        else:
            problems = validate_checkpoint(path)
            verdict = "valid" if not problems else problems[0]
        out.append((path, verdict))
    return out


def require_latest_checkpoint(directory: str | os.PathLike,
                              events=None) -> str:
    """``latest_checkpoint`` for callers that cannot proceed without
    one: returns the newest valid checkpoint path, or raises
    :class:`NoRestorableCheckpointError` whose message reports every
    candidate directory with its quarantine/validity verdict."""
    latest = latest_checkpoint(directory, events=events)
    if latest is not None:
        return latest
    report = checkpoint_chain_report(directory)
    if not report:
        raise NoRestorableCheckpointError(
            f"no checkpoint under {os.fspath(directory)} (no step_<n> "
            "directories exist)"
        )
    lines = "\n".join(f"  {p}: {v}" for p, v in report)
    raise NoRestorableCheckpointError(
        f"no restorable checkpoint under {os.fspath(directory)} — every "
        f"candidate in the fallback chain is unusable:\n{lines}"
    )
