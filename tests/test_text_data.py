"""Byte-level text corpus + LM perplexity eval (data/text.py,
train/lm_step.py::make_lm_eval_step): determinism, sharding union, and
eval math."""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.data.text import (
    BOS,
    VOCAB_SIZE,
    TextWindowLoader,
    eval_windows,
    load_corpus,
    split_corpus,
)


def test_split_corpus_holds_out_tail():
    corpus = np.arange(100, dtype=np.uint16)
    train, ev = split_corpus(corpus, eval_frac=0.1)
    assert len(train) == 90 and len(ev) == 10
    np.testing.assert_array_equal(np.concatenate([train, ev]), corpus)
    # min_eval_tokens bumps a too-small slice up to a usable window.
    train2, ev2 = split_corpus(corpus, eval_frac=0.1, min_eval_tokens=33)
    assert len(ev2) == 33 and len(train2) == 67
    # Degenerate corpus: degrade to (all, all) rather than error.
    tiny = np.arange(4, dtype=np.uint16)
    t3, e3 = split_corpus(tiny, eval_frac=0.1, min_eval_tokens=9)
    assert len(t3) == len(tiny) and len(e3) == len(tiny)
    # Train slice must sustain a window too: 300 tokens at seq 256 can
    # train but not split — degrade, don't leave a 43-token train slice.
    mid = np.arange(300, dtype=np.uint16)
    t4, e4 = split_corpus(mid, eval_frac=0.1, min_eval_tokens=257)
    assert len(t4) == 300 and len(e4) == 300
    with pytest.raises(ValueError):
        split_corpus(corpus, eval_frac=1.5)


def _write_corpus(tmp_path):
    (tmp_path / "a.txt").write_text("hello world")
    (tmp_path / "b.md").write_text("byte level")
    sub = tmp_path / "sub"
    sub.mkdir()
    (sub / "c.py").write_text("print(1)")
    (tmp_path / "skip.bin").write_bytes(b"\x00\x01")  # not a text ext
    return tmp_path


def test_load_corpus_sorted_with_bos(tmp_path):
    corpus = load_corpus(_write_corpus(tmp_path))
    # Leading BOS + one BOS after each of the 3 text files; .bin skipped.
    assert (corpus == BOS).sum() == 4
    text = bytes(t for t in corpus.tolist() if t != BOS).decode()
    assert text == "hello worldbyte levelprint(1)"
    assert corpus.max() <= BOS and VOCAB_SIZE == 257


def test_load_corpus_errors(tmp_path):
    with pytest.raises(FileNotFoundError):
        load_corpus(tmp_path / "empty_dir_that_has_no_files")


def test_loader_deterministic_and_shaped(tmp_path):
    corpus = load_corpus(_write_corpus(tmp_path))
    a = iter(TextWindowLoader(corpus, batch=3, seq_len=8, seed=7))
    b = iter(TextWindowLoader(corpus, batch=3, seq_len=8, seed=7))
    xa, ya = next(a)
    xb, yb = next(b)
    assert xa.shape == (3, 8) and ya.shape == (3, 8)
    np.testing.assert_array_equal(xa, xb)
    np.testing.assert_array_equal(ya[:, :-1], xa[:, 1:])  # shifted targets


def test_rank_sharding_union_matches_single_stream(tmp_path):
    # Rank-strided windows: the union over ranks == the world-size-1
    # stream drawn with batch B*world (DistributedSampler semantics).
    corpus = load_corpus(_write_corpus(tmp_path))
    world = 4
    full = next(iter(TextWindowLoader(corpus, batch=8, seq_len=4, seed=3)))[0]
    shards = [
        next(iter(TextWindowLoader(corpus, batch=2, seq_len=4, seed=3,
                                   rank=r, world=world)))[0]
        for r in range(world)
    ]
    recombined = np.empty_like(full)
    for r in range(world):
        recombined[r::world] = shards[r]
    np.testing.assert_array_equal(recombined, full)


def test_loader_validation(tmp_path):
    corpus = load_corpus(_write_corpus(tmp_path))
    with pytest.raises(ValueError, match="corpus"):
        TextWindowLoader(corpus, batch=1, seq_len=10_000)
    with pytest.raises(ValueError, match="rank"):
        TextWindowLoader(corpus, batch=1, seq_len=4, rank=2, world=2)
    with pytest.raises(ValueError, match="batch"):
        TextWindowLoader(corpus, batch=0, seq_len=4)


def test_eval_windows_fixed(tmp_path):
    corpus = load_corpus(_write_corpus(tmp_path))
    a = list(eval_windows(corpus, batch=2, seq_len=4, num_batches=3))
    b = list(eval_windows(corpus, batch=2, seq_len=4, num_batches=3))
    assert len(a) == 3
    for (xa, _), (xb, _) in zip(a, b):
        np.testing.assert_array_equal(xa, xb)


def test_lm_eval_perplexity_math(rng):
    # Pooled NLL over unequal batches must equal the exact corpus mean;
    # cross-check perplexity against the per-token loss definition.
    from distributed_machine_learning_tpu.models.transformer import TransformerLM
    from distributed_machine_learning_tpu.train.lm_step import (
        init_lm_state,
        make_lm_eval_step,
    )
    from distributed_machine_learning_tpu.train.loop import evaluate_lm
    from distributed_machine_learning_tpu.train.losses import lm_cross_entropy

    model = TransformerLM(vocab_size=32, d_model=16, n_layers=1, n_heads=2)
    state = init_lm_state(model)
    step = make_lm_eval_step(model)
    b1 = rng.integers(0, 32, (2, 9)).astype(np.int32)
    b2 = rng.integers(0, 32, (1, 9)).astype(np.int32)  # unequal batch
    batches = [(b[:, :-1], b[:, 1:]) for b in (b1, b2)]
    mean_nll, ppl = evaluate_lm(step, state.params, batches)

    tot, cnt = 0.0, 0
    for x, y in batches:
        logits = model.apply({"params": state.params}, jnp.asarray(x),
                             train=False)
        tot += float(lm_cross_entropy(logits, jnp.asarray(y))) * y.size
        cnt += y.size
    assert mean_nll == pytest.approx(tot / cnt, rel=1e-6)
    assert ppl == pytest.approx(math.exp(tot / cnt), rel=1e-6)


def test_eval_step_uses_dense_for_ring_model(rng):
    from distributed_machine_learning_tpu.models.transformer import TransformerLM
    from distributed_machine_learning_tpu.train.lm_step import (
        init_lm_state,
        make_lm_eval_step,
    )

    model = TransformerLM(vocab_size=32, d_model=16, n_layers=1, n_heads=2,
                          attn_impl="ring")
    state = init_lm_state(model)
    step = make_lm_eval_step(model)  # clones to dense: runs without a mesh
    b = rng.integers(0, 32, (2, 9)).astype(np.int32)
    nll, count = step(state.params, b[:, :-1], b[:, 1:])
    assert np.isfinite(float(nll)) and int(count) == 16


def test_smallest_legal_corpus_and_last_window_reachable():
    # len == seq_len+1 must yield the single valid window (regression:
    # the start bound was off by one and crashed exactly this case).
    corpus = np.arange(9, dtype=np.uint16)
    x, y = next(iter(TextWindowLoader(corpus, batch=2, seq_len=8)))
    np.testing.assert_array_equal(x, np.tile(np.arange(8), (2, 1)))
    np.testing.assert_array_equal(y, np.tile(np.arange(1, 9), (2, 1)))
    ex, ey = next(iter(eval_windows(corpus, 1, 8, 1)))
    np.testing.assert_array_equal(ex[0], np.arange(8))

    # Larger corpus: the final start (len - L - 1) must be drawable.
    corpus = np.arange(12, dtype=np.uint16)
    seen_last = False
    loader = iter(TextWindowLoader(corpus, batch=16, seq_len=4, seed=0))
    for _ in range(50):
        x, _ = next(loader)
        if (x[:, 0] == 7).any():  # start 7 == 12 - 4 - 1
            seen_last = True
            break
    assert seen_last


def test_eval_windows_validates_short_corpus():
    with pytest.raises(ValueError, match="corpus"):
        next(eval_windows(np.arange(4, dtype=np.uint16), 1, 8, 1))
