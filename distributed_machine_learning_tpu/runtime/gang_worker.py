"""One rank of a coordinated local gang — the end-to-end chaos harness.

Run as a subprocess by ``gang_supervise`` (``cli/gang.py`` launches it;
``tests/test_gang.py`` asserts on it): each of N OS processes trains
lock-step SGD steps with real verified checkpoints
(``train/checkpoint.py``) in a PER-RANK checkpoint directory
(``<ckpt-root>/rank<r>`` — the per-host-shards layout of a pod run,
which is what makes the restore-point election load-bearing: validity
is each rank's own view), and wires the gang coordinator
(``runtime/coordinator.py``) around the loop: heartbeats per step,
suspensions around compile/saves, a restore-point record after every
verified save.

Lock-step is enforced by ``GangCoordinator.wait_for_peers`` — a barrier
over the beat directory — rather than a cross-process XLA collective:
the CI host's CPU backend cannot run multi-process XLA computations
(the same env drift that fails ``tests/test_multihost.py`` here), and
the barrier reproduces the exact failure semantics this subsystem
exists for: when a peer dies or stalls, the survivors BLOCK, and only
the peer-failure detector's coordinated abort frees them.  On real TPU
pods the blocking collective is the psum itself and the identical
coordinator sits around it (``cli/common.py``'s ``--gang-dir`` path).

The chaos contract this worker proves (ISSUE 3's acceptance bar): with
``--faults kill_rank@1:7`` on a 4-worker gang, rank 1 dies hard at step
7, the survivors block at the next barrier, their peer detectors abort
the gang, ``gang_supervise`` relaunches everyone from the elected
restore point, and the final parameters are **bit-identical** to a
fault-free run on every rank — the per-step batch is keyed on the
absolute step index, so a resumed gang replays exactly the stream the
dead gang would have seen.
"""

from __future__ import annotations

import argparse
import hashlib
import os
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _data_for_step(step: int) -> "object":
    """The batch for an absolute step index — deterministic in ``step``
    alone, so every rank (and every restart attempt) agrees on it."""
    import numpy as np

    rng = np.random.default_rng(10_000 + step)
    return rng.standard_normal((4, 8)).astype(np.float32)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--world", type=int, required=True)
    ap.add_argument("--gang-dir", required=True)
    ap.add_argument("--ckpt-dir", required=True,
                    help="checkpoint ROOT; this rank writes under "
                         "<ckpt-dir>/rank<r> (per-host shard layout)")
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--save-every", type=int, default=5)
    ap.add_argument("--faults", default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--heartbeat-interval", type=float, default=0.25)
    ap.add_argument("--peer-timeout", type=float, default=15.0)
    ap.add_argument("--step-sleep", type=float, default=0.02)
    ap.add_argument("--telemetry-dir", default=None)
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_machine_learning_tpu.runtime.coordinator import (
        GangCoordinator,
    )
    from distributed_machine_learning_tpu.runtime.faults import (
        FaultEvents,
        FaultInjector,
    )
    from distributed_machine_learning_tpu.train.checkpoint import (
        checkpoint_cursor,
        latest_checkpoint,
        restore_checkpoint,
        save_checkpoint,
    )
    from distributed_machine_learning_tpu.train.state import TrainState
    from distributed_machine_learning_tpu.utils.summary import (
        resilience_summary,
    )

    telemetry = None
    if args.telemetry_dir:
        from distributed_machine_learning_tpu.telemetry import (
            Telemetry,
            set_telemetry,
        )

        telemetry = Telemetry(args.telemetry_dir)
        set_telemetry(telemetry)

    ckpt_dir = os.path.join(args.ckpt_dir, f"rank{args.rank}")
    events = FaultEvents()
    injector = FaultInjector.from_flags(
        args.faults, seed=args.seed, horizon=max(args.steps, 2),
        rank=args.rank,
    )
    if injector is not None:
        from distributed_machine_learning_tpu.runtime.faults import (
            FAULT_LEDGER_FILE,
        )

        os.makedirs(args.gang_dir, exist_ok=True)
        # The exactly-once latch must survive the relaunch this very
        # fault will cause — without the ledger every attempt re-fires
        # the same kill and the gang can never finish.
        injector.attach_ledger(
            os.path.join(args.gang_dir, FAULT_LEDGER_FILE)
        )
    coord = GangCoordinator(
        args.gang_dir, rank=args.rank, world=args.world,
        heartbeat_interval_s=args.heartbeat_interval,
        peer_timeout_s=args.peer_timeout, events=events,
    ).start()

    with coord.suspend():
        state = TrainState.create(
            params={"w": jnp.zeros((8,), jnp.float32)}
        )
        start = 0
        latest = latest_checkpoint(ckpt_dir, events=events)
        if latest is not None:
            state = restore_checkpoint(latest, abstract_state=state,
                                       files_verified=True)
            restored_step = int(jax.device_get(state.step))
            cursor = checkpoint_cursor(latest)
            start = cursor if cursor is not None else restored_step
            # The restore is this rank's proof the checkpoint is whole —
            # record it so the next election can agree on it even if no
            # further save ever lands.
            coord.record_valid_step(restored_step)
            print(f"resumed {latest} step {restored_step}", flush=True)

        @jax.jit
        def step_fn(state, xs):
            # Every rank computes the same mean-gradient update from the
            # same step-keyed batch — the value a psum over the gang
            # would produce, so replicated params stay bit-identical
            # across ranks (asserted by digest below).
            g = xs.mean(0)
            w = state.params["w"] - 0.1 * (g + 0.01 * state.params["w"])
            return state.replace(params={"w": w}, step=state.step + 1)

        # AOT-compile inside the suspension: the first step's compile
        # must not read as a stall under short chaos-test timeouts.
        compiled = step_fn.lower(state, _data_for_step(start)).compile()
        # Publish the resumed position BEFORE the first barrier: peers
        # wait for our published step, and a gang resuming at step k
        # would otherwise deadlock at barrier k with everyone still
        # publishing step 0.
        coord.beat(step=start)

    print(f"ready rank={args.rank} start={start}", flush=True)
    post_save = injector.post_save_hook(events) if injector else None
    batches = range(start, args.steps)
    if injector is not None:
        batches = injector.wrap_batches(batches, events, start=start)

    for idx in batches:
        # The lock-step barrier: the stand-in for the synchronous
        # collective — blocks until every peer has published step idx
        # (a dead peer blocks us here until the detector aborts the
        # gang, exactly like a hung psum).
        if not coord.wait_for_peers(idx):
            break  # test mode only; production aborts the process
        state = compiled(state, _data_for_step(idx))
        jax.block_until_ready(state.params["w"])
        coord.beat(step=idx + 1)
        if args.rank == 0:
            print(f"step {idx}", flush=True)
        if (idx + 1) % args.save_every == 0 or idx + 1 == args.steps:
            # Saves are liveness, not progress: suspend the stall clock
            # exactly as the watchdog path does.
            with coord.suspend():
                save_checkpoint(
                    ckpt_dir, state, cursor=idx + 1,
                    post_save_hook=post_save,
                )
            coord.record_valid_step(int(jax.device_get(state.step)))
        if args.step_sleep:
            time.sleep(args.step_sleep)

    digest = hashlib.sha256(
        np.ascontiguousarray(np.asarray(state.params["w"])).tobytes()
    ).hexdigest()[:16]
    print(f"final_step {int(jax.device_get(state.step))}", flush=True)
    print(f"final {digest}", flush=True)
    if events.total():
        print(resilience_summary(events), flush=True)
    coord.finish()
    if telemetry is not None:
        telemetry.close()


if __name__ == "__main__":
    main()
