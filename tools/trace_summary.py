#!/usr/bin/env python3
"""Summarize a telemetry directory — no third-party imports, jax-free.

Reads the artifacts a run's ``--telemetry-dir`` produced
(``distributed_machine_learning_tpu/telemetry/``) and prints:

- per-phase time shares from the Chrome trace's complete events
  (data_wait / place_batch / step_dispatch / device_block /
  checkpoint_save / eval / ...), the first diagnosis dimension for
  stragglers and sync overhead — trace *instants* (fault markers,
  gang_shrink, restarts) are counted in the same table: a fault that
  fired during a phase is the context that phase's duration needs;
- the top-5 slowest steps from the metrics JSONL (attempt-tagged), with
  their phase breakdown;
- attempt/restart structure when the run was supervised.

Tolerates the artifacts of a crash: a torn final JSONL line and an
unterminated trace array are both read to the last complete record —
this tool's main job is diagnosing runs that died.

Usage:  python tools/trace_summary.py <telemetry-dir> [--top N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# One source of truth for the tolerant readers: the modules that WRITE
# the artifacts also own the readers that decode them (so the formats
# cannot drift apart).  These imports are jax-free by construction (jax
# only loads lazily inside the sinks' write paths) — this tool stays
# runnable on a bare host; the path bootstrap makes it runnable from
# anywhere, not just the repo root.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))
from distributed_machine_learning_tpu.telemetry.sink import (  # noqa: E402
    read_jsonl,
)
from distributed_machine_learning_tpu.telemetry.tracer import (  # noqa: E402
    read_trace,
)
from distributed_machine_learning_tpu.utils.timing import (  # noqa: E402
    percentile,
)

METRICS_FILE = "metrics.jsonl"
TRACE_FILE = "trace.json"
REGISTRY_FILE = "registry.json"

# The per-step driver phases, in pipeline order (other spans —
# checkpoint_save, eval, restart_attempt — are reported after these).
STEP_PHASES = ("data_wait", "place_batch", "step_dispatch", "device_block")
# Spans that run CONCURRENTLY with the pipeline phases (the
# overlap-aware sharded update's consume-phase gather runs behind
# data_wait): shown in the phase table for visibility, but excluded
# from the pipeline total — counting an overlapped span into the
# denominator would misstate every share.
OVERLAY_PHASES = ("param_gather",)


def summarize(telemetry_dir: str, top: int = 5) -> str:
    lines: list[str] = []
    trace_path = os.path.join(telemetry_dir, TRACE_FILE)
    metrics_path = os.path.join(telemetry_dir, METRICS_FILE)

    # -- per-phase shares from the trace --------------------------------
    if os.path.isfile(trace_path):
        all_events = [e for e in read_trace(trace_path)
                      if isinstance(e, dict)]
        events = [e for e in all_events if e.get("ph") == "X"]
        # Instants (ph "i") are the zero-duration markers — injected
        # faults, gang aborts/shrinks, worker starts.  They were
        # silently dropped before this fix; a phase table that omits
        # the fault fired mid-phase misreads the run it summarizes.
        instants: dict[str, int] = {}
        for e in all_events:
            if e.get("ph") == "i":
                name = str(e.get("name", "?"))
                instants[name] = instants.get(name, 0) + 1
        by_name: dict[str, dict] = {}
        for e in events:
            d = by_name.setdefault(e.get("name", "?"),
                                   {"dur": 0.0, "count": 0})
            d["dur"] += float(e.get("dur", 0.0))
            d["count"] += 1
        phase_total = sum(
            by_name.get(p, {"dur": 0.0})["dur"] for p in STEP_PHASES
        )
        lines.append(f"== Phase time shares ({trace_path}) ==")
        if phase_total > 0:
            for p in STEP_PHASES:
                d = by_name.get(p)
                if d is None:
                    continue
                share = 100.0 * d["dur"] / phase_total
                lines.append(
                    f"  {p:<14} {share:5.1f}%  "
                    f"({d['dur'] / 1e6:.3f}s over {d['count']} spans)"
                )
            for p in OVERLAY_PHASES:
                d = by_name.get(p)
                if d is None:
                    continue
                # Reported against the same pipeline total so "how much
                # of a step the gather spans" reads directly, but
                # flagged: this time runs UNDER the phases above
                # (overlap-aware update), not in addition to them.
                share = 100.0 * d["dur"] / phase_total
                lines.append(
                    f"  {p:<14} {share:5.1f}%  "
                    f"({d['dur'] / 1e6:.3f}s over {d['count']} spans, "
                    "overlapped — runs under data_wait/dispatch)"
                )
        other = sorted(
            (n for n in by_name
             if n not in STEP_PHASES and n not in OVERLAY_PHASES),
            key=lambda n: -by_name[n]["dur"],
        )
        for n in other:
            d = by_name[n]
            lines.append(
                f"  {n:<14} ------  "
                f"({d['dur'] / 1e6:.3f}s over {d['count']} spans)"
            )
        for n in sorted(instants, key=lambda n: (-instants[n], n)):
            lines.append(f"  {n:<14} ------  ({instants[n]} instant(s))")
        if not by_name and not instants:
            lines.append("  (no complete events)")
    else:
        lines.append(f"== No trace at {trace_path} ==")

    # -- slowest steps from the metrics stream --------------------------
    if os.path.isfile(metrics_path):
        all_rows = [r for r in read_jsonl(metrics_path)
                    if isinstance(r, dict) and "iter_s" in r]
        # Warm-up iterations (XLA compile; timer-excluded, row-tagged)
        # would otherwise head every "slowest" list and own the tail.
        rows = [r for r in all_rows if not r.get("warmup")]
        n_warm = len(all_rows) - len(rows)
        lines.append(f"== Steps ({metrics_path}) ==")
        if rows:
            iters = [float(r["iter_s"]) for r in rows]
            attempts = sorted({int(r.get("attempt", 0)) for r in all_rows})
            lines.append(
                f"  {len(rows)} step rows over attempt(s) "
                f"{','.join(map(str, attempts))}"
                + (f" (+{n_warm} warm-up rows excluded)" if n_warm else "")
                + f"; iter_s "
                f"p50 {percentile(iters, 0.5):.6f}  "
                f"p95 {percentile(iters, 0.95):.6f}  "
                f"p99 {percentile(iters, 0.99):.6f}  "
                f"max {max(iters):.6f}"
            )
            lines.append(f"  top-{top} slowest steps:")
            slowest = sorted(rows, key=lambda r: -float(r["iter_s"]))[:top]
            for r in slowest:
                phases = "  ".join(
                    f"{k}={float(r[k]):.6f}"
                    for k in ("data_wait_s", "place_s", "dispatch_s",
                              "block_s", "param_gather_s")
                    if k in r
                )
                lines.append(
                    f"    step {r.get('step', '?'):>6}  attempt "
                    f"{r.get('attempt', 0)}  iter_s "
                    f"{float(r['iter_s']):.6f}  {phases}"
                )
        else:
            lines.append("  (no step rows)")
    else:
        lines.append(f"== No metrics at {metrics_path} ==")

    # -- fault counters, if the registry snapshot landed ----------------
    reg_path = os.path.join(telemetry_dir, REGISTRY_FILE)
    if os.path.isfile(reg_path):
        with open(reg_path) as f:
            snap = json.load(f)
        faults = [c for c in snap.get("counters", [])
                  if c.get("name") == "fault_events"]
        if faults:
            lines.append(f"== Fault events ({reg_path}) ==")
            for c in sorted(faults, key=lambda c: c["labels"].get("kind", "")):
                lines.append(
                    f"  {c['labels'].get('kind', '?'):<18} {c['value']}"
                )
        # -- ring wire compression, if the run synced through the ring --
        wire = [c for c in snap.get("counters", [])
                if c.get("name") == "ring_wire_bytes"]
        ratio = [g for g in snap.get("gauges", [])
                 if g.get("name") == "ring_compression_ratio"]
        if wire:
            total = sum(c.get("value", 0) for c in wire)
            r = ratio[0].get("value") if ratio else None
            lines.append("== Ring wire compression ==")
            lines.append(f"  wire bytes (whole run)   {total:,.0f}")
            # Per-axis split (round 11): a --ring-topology run labels
            # the counter {axis=inner|outer}; the outer (inter-node)
            # share is the link the hierarchy exists to relieve.  Flat
            # runs carry {axis=flat} and skip the breakdown.
            by_axis = {}
            for c in wire:
                ax = (c.get("labels") or {}).get("axis", "flat")
                by_axis[ax] = by_axis.get(ax, 0) + c.get("value", 0)
            if set(by_axis) - {"flat"} and total:
                for ax in ("inner", "outer", "flat"):
                    if ax in by_axis:
                        lines.append(
                            f"    axis={ax:<6} {by_axis[ax]:>14,.0f}  "
                            f"({100 * by_axis[ax] / total:.0f}%)"
                        )
            if r:
                lines.append(f"  compression ratio        {r:.2f}x "
                             f"(exact/compressed)")
                if r > 1:
                    saved = total * (r - 1)
                    lines.append(
                        f"  bytes saved vs exact     {saved:,.0f}"
                    )
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("telemetry_dir", help="directory a run's "
                                              "--telemetry-dir pointed at")
    parser.add_argument("--top", default=5, type=int,
                        help="how many slowest steps to list (default 5)")
    args = parser.parse_args(argv)
    if not os.path.isdir(args.telemetry_dir):
        print(f"not a directory: {args.telemetry_dir}", file=sys.stderr)
        return 2
    try:
        print(summarize(args.telemetry_dir, top=args.top))
    except BrokenPipeError:  # `| head` closed the pipe — not an error
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
    return 0


if __name__ == "__main__":
    sys.exit(main())
