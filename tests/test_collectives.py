"""psum / gather-scatter collective wrappers on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

from distributed_machine_learning_tpu.ops.collectives import (
    all_reduce_mean,
    all_reduce_sum,
    gather_scatter_sum,
)


def _per_device(fn):
    def inner(tree):
        local = jax.tree_util.tree_map(lambda x: x[0], tree)
        out = fn(local)
        return jax.tree_util.tree_map(lambda x: x[None], out)

    return inner


def _run(mesh, fn, data):
    wrapped = shard_map(
        _per_device(fn), mesh=mesh, in_specs=P("batch"), out_specs=P("batch"),
        check_vma=False,
    )
    return jax.jit(wrapped)(jax.tree_util.tree_map(jnp.asarray, data))


def test_all_reduce_sum_semantics(mesh8, rng):
    # 2b parity: SUM, never divided by world size (SURVEY.md §2.4).
    data = {"g": rng.standard_normal((8, 5, 3)).astype(np.float32)}
    out = _run(mesh8, lambda t: all_reduce_sum(t, "batch"), data)
    expected = data["g"].sum(axis=0)
    for d in range(8):
        np.testing.assert_allclose(np.asarray(out["g"][d]), expected, rtol=1e-5)


def test_all_reduce_mean_semantics(mesh8, rng):
    data = {"g": rng.standard_normal((8, 4)).astype(np.float32)}
    out = _run(mesh8, lambda t: all_reduce_mean(t, "batch"), data)
    expected = data["g"].mean(axis=0)
    for d in range(8):
        np.testing.assert_allclose(np.asarray(out["g"][d]), expected, rtol=1e-5)


def test_gather_scatter_matches_manual_rank_order_sum(mesh4, rng):
    # 2a postcondition: every rank ends with the rank-ordered sum
    # (part2/2a/main.py:104-116).
    data = {"g": rng.standard_normal((4, 11)).astype(np.float32)}
    out = _run(mesh4, lambda t: gather_scatter_sum(t, "batch"), data)
    expected = data["g"][0] + data["g"][1] + data["g"][2] + data["g"][3]
    for d in range(4):
        np.testing.assert_allclose(np.asarray(out["g"][d]), expected, rtol=1e-5)


def test_cross_replica_equality_invariant(mesh8, rng):
    """The reference's de facto distributed-correctness assertion —
    identical results on every rank (group25.pdf p.5) — as a bitwise test."""
    data = {"g": rng.standard_normal((8, 257)).astype(np.float32)}
    out = _run(mesh8, lambda t: all_reduce_sum(t, "batch"), data)
    base = np.asarray(out["g"][0])
    for d in range(1, 8):
        assert (np.asarray(out["g"][d]) == base).all()
