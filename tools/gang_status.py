#!/usr/bin/env python3
"""Live / post-mortem gang status — no third-party imports, jax-free.

Renders one gang's health from its coordination directory (the files
``runtime/coordinator.py`` and ``gang_supervise`` write) plus the
telemetry plane the workers stream (default ``<gang-dir>/telemetry``):

- the per-rank table: last published step, progress age, rolling step
  time, skew vs the gang median, and state (ok / SUSPENDED / DONE /
  STRAGGLER / STALE?) — plus one row per WARM SPARE (state ``spare``,
  the checkpoint step it has prefetched) and any pending (non-spare)
  join announcements from the ``join_rank<r>.json`` channel;
- the advisory history from ``gang_health.jsonl``: straggler verdicts,
  coordinated restarts, shrinks, grows, spare promotions/demotions, and
  planned boundaries — plus fired faults from ``faults_fired.jsonl``
  and the abort latch, if present — and the run's world-size
  trajectory (e.g. ``4 -> 3 -> 5``), also under ``world_trajectory``
  in ``--json``;
- the cross-rank rollup from the per-rank metrics streams
  (``telemetry/aggregator.py``): per-rank throughput, whole-run
  p95/max step-time skew, offline straggler verdicts;
- the serving view (ISSUE 16): replica states (role / serving epoch /
  drain latch / queue depth / committed+staging weight version) from
  the transport snapshot, the router's final SLO summary record, and
  the promotion / eviction / drain / weight-swap / canary
  promote-rollback history from the health ledger (ISSUE 18).

Live mode (``--watch N``) re-renders every N seconds; everything
tolerates the artifacts of a crash (torn lines, frozen beat files) —
diagnosing dead runs is this tool's main job.

Usage:  python tools/gang_status.py <gang-dir> [--telemetry DIR]
                                    [--watch N] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Reader-side package modules only (telemetry/, utils/timing, and —
# since ISSUE 12 — runtime/transport, all stdlib-importable by
# construction; the jax-heavy submodules are never touched) — same
# bootstrap as tools/trace_summary.py.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))
from distributed_machine_learning_tpu.runtime.transport import (  # noqa: E402,E501
    FileTransport,
)
from distributed_machine_learning_tpu.telemetry.aggregator import (  # noqa: E402,E501
    aggregate_gang_metrics,
    median,
)


def _world_trajectory(health: list[dict], fallback: int) -> list[int]:
    """The run's world sizes in order, derived from the health ledger's
    reshape events (shrink/grow/replace carry from_world/to_world;
    restart/boundary lines confirm the standing world).  A run with no
    events at all reports just the observed world."""
    traj: list[int] = []
    for e in health:
        kind = e.get("kind")
        if kind in ("shrink", "grow", "replace"):
            fw, tw = e.get("from_world"), e.get("to_world")
            if not traj and isinstance(fw, int):
                traj.append(fw)
            if isinstance(tw, int) and (not traj or traj[-1] != tw):
                traj.append(tw)
        elif not traj and isinstance(e.get("world"), int):
            traj.append(e["world"])
    return traj or ([fallback] if fallback else [])


def collect(gang_dir: str, telemetry_dir: str) -> dict:
    """Everything the renderers need, as one JSON-ready dict.

    Reads through the ``GangTransport`` snapshot API (ISSUE 12) — the
    file backend here, since a status tool points at a directory; an
    in-proc/tcp campaign mirrors its durable ledgers into the same
    layout, so dead campaigns render identically."""
    snap = FileTransport(gang_dir).snapshot()
    beats = snap["beats"]
    # Staleness basis (dmlcheck DML001): NEVER this process's wall
    # clock vs timestamps other hosts wrote — on the shared mounts pods
    # use, reader-vs-writer clock skew of a minute is routine and would
    # read as mass death.  Ages are PEER-RELATIVE instead: how much
    # older each rank's beat is than the freshest beat in the gang,
    # plus the rank's own self-published progress age — the quantity
    # the straggler story actually needs, with the reader's clock out
    # of the comparison entirely.
    beat_times = [float(p["time"]) for p in beats.values()
                  if isinstance(p.get("time"), (int, float))]
    newest_beat = max(beat_times, default=None)
    # The ONE deliberate reader-clock delta (dmlcheck-baselined): with
    # every rank dead at once, all beats freeze together and the
    # peer-relative ages read ~0 forever — only the reader's own clock
    # can say "nothing has beaten for 20 minutes".  It is a single
    # gang-LEVEL line, labeled approximate, never folded into the
    # per-rank comparisons.
    reader_lag = (max(time.time() - newest_beat, 0.0)
                  if newest_beat is not None else None)
    health = snap["health"]
    # The live table's STRAGGLER column must match the beat files'
    # CURRENT rank numbering (a shrink renumbers survivors, while
    # verdict `rank` fields carry the original identity) and only the
    # LATEST attempt's verdicts — a rank that stalled two attempts ago
    # is history, not current state.  The History section below still
    # shows every verdict under its original-rank id.
    latest_attempt = max(
        (e["attempt"] for e in health
         if isinstance(e.get("attempt"), int)), default=0,
    )
    flagged = {
        e.get("cur_rank", e.get("rank")) for e in health
        if e.get("kind") == "straggler"
        and e.get("attempt", 0) == latest_attempt
    }
    rank_rows = []
    step_times = {}
    for rank, p in sorted(beats.items()):
        metrics = p.get("metrics") if isinstance(p.get("metrics"), dict) \
            else {}
        stime = metrics.get("step_time_s")
        if isinstance(stime, (int, float)):
            step_times[rank] = float(stime)
        # Post-mortem age: self-published progress age plus how much
        # the rank's beat lags the gang's freshest beat (writer-clock
        # timestamps compared among themselves; a frozen file reads as
        # ever-older as its peers keep beating).
        wall_age = (max(newest_beat - float(p["time"]), 0.0)
                    if newest_beat is not None
                    and isinstance(p.get("time"), (int, float)) else 0.0)
        rank_rows.append({
            "rank": rank,
            "step": int(p.get("step", 0)),
            "age_s": float(p.get("beat_age", 0.0)) + wall_age,
            "step_time_s": stime,
            "phases": metrics.get("phases") or {},
            "suspended": bool(p.get("suspended")),
            "done": bool(p.get("done")),
            "straggler": rank in flagged,
        })
    med = median(step_times.values())
    for row in rank_rows:
        st = row["step_time_s"]
        row["skew"] = (st / med) if (st and med > 0) else None
    # The join channel: warm spares (their own table rows) and pending
    # non-spare joins (a recovered host waiting for the next boundary).
    # Ages are writer-clock vs the gang's freshest beat — peer-relative,
    # same rule as the rank rows; the reader's clock stays out of it.
    spare_rows, pending_joins = [], []
    for rank, p in sorted(snap["joins"].items()):
        lag = (max(newest_beat - float(p["time"]), 0.0)
               if newest_beat is not None
               and isinstance(p.get("time"), (int, float)) else None)
        row = {"rank": rank, "announced_lag_s": lag}
        if p.get("spare"):
            row["prefetched_step"] = p.get("prefetched_step")
            spare_rows.append(row)
        else:
            row["at_step"] = p.get("at_step")
            pending_joins.append(row)
    # The latest transport-health record the supervisor appended
    # (backend + op/retry/timeout totals) — the lossy-transport
    # post-mortem line.
    transport_health = None
    for e in health:
        if e.get("kind") == "transport":
            transport_health = e
    # The serving view (ISSUE 16): the router's final summary record,
    # its lifecycle history (promotions / evictions / drains), and the
    # live serving-plane state from the transport snapshot (replica
    # roles/epochs/drain latches and queue depths — non-empty while a
    # fleet is up or when a file-backend fleet died mid-flight).
    serving_summary = None
    serving_history = []
    for e in health:
        kind = e.get("kind")
        if kind == "serving":
            serving_summary = e
        elif kind in ("serve_promote", "serve_evict", "serve_drain",
                      "serve_demote", "weight_swap", "deploy_canary",
                      "deploy_promote", "deploy_rollback",
                      "deploy_verify_failed"):
            serving_history.append(e)
    # The modeled-network view (ISSUE 20): replay the gray-link ledger
    # (``link_degraded`` / ``link_restored``) in order — the surviving
    # rows are the links the digital twin is CURRENTLY pricing
    # off-baseline, each carrying its effective modeled parameters and
    # the fault spec that put it there.
    live_links: dict = {}
    for e in health:
        kind = e.get("kind")
        if kind == "link_degraded":
            live_links[(e.get("src"), e.get("dst"))] = e
        elif kind == "link_restored":
            live_links.pop((e.get("src"), e.get("dst")), None)
    out = {
        "gang_dir": gang_dir,
        "world": len(rank_rows),
        "world_trajectory": _world_trajectory(health, len(rank_rows)),
        "abort": snap["abort"],
        "freshest_beat_lag_s": reader_lag,
        "ranks": rank_rows,
        "spares": spare_rows,
        "pending_joins": pending_joins,
        "health": health,
        "faults_fired": snap["faults_fired"],
        "degraded_links": list(live_links.values()),
        "transport": transport_health,
        "serving": serving_summary,
        "serving_history": serving_history,
        "serving_state": snap.get("serving"),
    }
    if os.path.isdir(telemetry_dir):
        rollup = aggregate_gang_metrics(telemetry_dir)
        if rollup.ranks:
            out["rollup"] = rollup.as_dict()
    return out


def render(status: dict) -> str:
    lines = [f"== Gang {status['gang_dir']} — "
             f"{status['world']} rank(s) heartbeating =="]
    if status["abort"]:
        a = status["abort"]
        lines.append(f"  ABORT latched by rank {a.get('by_rank')}: "
                     f"{a.get('reason')}")
    lag = status.get("freshest_beat_lag_s")
    if lag is not None:
        lines.append(f"  freshest beat: {lag:.1f}s ago by this "
                     "reader's clock (approximate across hosts; "
                     "per-rank ages below are peer-relative)")
    th = status.get("transport")
    if th:
        lines.append(
            f"  transport: {th.get('backend', '?')} — "
            f"{th.get('ops_total', 0)} op(s), "
            f"{th.get('retries', 0)} retr{'y' if th.get('retries') == 1 else 'ies'}, "
            f"{th.get('timeouts', 0)} timeout(s)")
    if status["ranks"]:
        lines.append(f"  {'rank':>4}  {'step':>6}  {'age':>8}  "
                     f"{'step_time':>10}  {'skew':>6}  state")
        for r in status["ranks"]:
            st = (f"{r['step_time_s']:.4f}s"
                  if r["step_time_s"] is not None else "-")
            skew = f"{r['skew']:.2f}x" if r["skew"] is not None else "-"
            state = ("DONE" if r["done"]
                     else "SUSPENDED" if r["suspended"]
                     else "STRAGGLER" if r["straggler"] else "ok")
            lines.append(f"  {r['rank']:>4}  {r['step']:>6}  "
                         f"{r['age_s']:>7.1f}s  {st:>10}  {skew:>6}  "
                         f"{state}")
    else:
        lines.append("  (no heartbeat files)")
    for r in status.get("spares", ()):
        pre = (f"prefetched step {r['prefetched_step']}"
               if r.get("prefetched_step") is not None
               else "nothing prefetched yet")
        lag = (f", announced {r['announced_lag_s']:.1f}s behind the "
               "freshest beat" if r.get("announced_lag_s") is not None
               else "")
        lines.append(f"  {r['rank']:>4}  {'-':>6}  {'-':>8}  "
                     f"{'-':>10}  {'-':>6}  spare ({pre}{lag})")
    for r in status.get("pending_joins", ()):
        at = (f" at step {r['at_step']}"
              if r.get("at_step") is not None else "")
        lines.append(f"  pending join: rank {r['rank']} announced"
                     f"{at} — admitted at the next boundary")
    traj = status.get("world_trajectory") or []
    if len(traj) > 1:
        lines.append("  world trajectory: "
                     + " -> ".join(str(w) for w in traj))

    history = [e for e in status["health"]
               if e.get("kind") in ("restart", "boundary", "shrink",
                                    "grow", "replace", "promote",
                                    "demote", "straggler")]
    if history or status["faults_fired"]:
        lines.append("== History ==")
    for e in history:
        kind = e.get("kind")
        if kind in ("restart", "boundary"):
            label = ("planned boundary" if kind == "boundary"
                     else "restart")
            lines.append(f"  {label} #{e.get('attempt')}: world "
                         f"{e.get('world')} — {e.get('why', '?')}")
        elif kind == "shrink":
            lines.append(f"  shrink @attempt {e.get('attempt')}: "
                         f"{e.get('from_world')} -> {e.get('to_world')} "
                         f"(lost rank(s) {e.get('lost')}, restore step "
                         f"{e.get('restore_step')})")
        elif kind in ("grow", "replace"):
            detail = []
            if e.get("joined"):
                detail.append(f"joined {e['joined']}")
            if e.get("promoted"):
                detail.append(f"promoted spare(s) {e['promoted']}")
            if e.get("demoted"):
                detail.append(f"demoted {e['demoted']}")
            lines.append(f"  {kind} @attempt {e.get('attempt')}: "
                         f"{e.get('from_world')} -> {e.get('to_world')} "
                         f"({', '.join(detail) or '?'}; restore step "
                         f"{e.get('restore_step')})")
        elif kind == "promote":
            lines.append(f"  promote @attempt {e.get('attempt')}: spare "
                         f"{e.get('rank')} -> live (restore step "
                         f"{e.get('restore_step')})")
        elif kind == "demote":
            lines.append(f"  demote @attempt {e.get('attempt')}: rank "
                         f"{e.get('rank')} -> spare "
                         f"({e.get('why', '?')})")
        else:
            lines.append(f"  straggler: rank {e.get('rank')} at step "
                         f"{e.get('step')} — {e.get('ratio')}x the gang "
                         f"median (attempt {e.get('attempt')})")
    for e in status["faults_fired"]:
        tgt = (f" (target rank {e.get('target')})"
               if e.get("target") is not None
               and e.get("target") != e.get("rank") else "")
        lines.append(f"  fault fired: {e.get('kind')} rank "
                     f"{e.get('rank')} at {e.get('at')}{tgt}")

    dl = status.get("degraded_links") or []
    if dl:
        lines.append("== Modeled network: degraded links ==")
        lines.append(f"  {'link':>11}  {'axis':>5}  {'latency':>10}  "
                     f"{'bandwidth':>10}  {'loss':>5}  fault")
        for e in dl:
            lat = (f"{e['latency_s'] * 1e6:.1f}µs"
                   if isinstance(e.get("latency_s"), (int, float))
                   else "-")
            bw = (f"{e['bytes_per_s'] / 1e9:.1f}GB/s"
                  if isinstance(e.get("bytes_per_s"), (int, float))
                  else "-")
            loss = (f"{e['flaky_p']:.2f}"
                    if isinstance(e.get("flaky_p"), (int, float))
                    and e["flaky_p"] else "-")
            lines.append(
                f"  {e.get('src', '?'):>4} -> {e.get('dst', '?'):>4}  "
                f"{e.get('axis', '?'):>5}  {lat:>10}  {bw:>10}  "
                f"{loss:>5}  {e.get('source', '?')}")

    sv = status.get("serving")
    sv_hist = status.get("serving_history") or []
    sv_state = status.get("serving_state") or {}
    sv_replicas = sv_state.get("replicas") or {}
    if sv or sv_hist or sv_replicas:
        lines.append("== Serving fleet ==")
    if sv:
        lines.append(
            f"  fleet: {sv.get('replicas', '?')} live replica(s), "
            f"queue depth {sv.get('queue_depth', '?')} — "
            f"{sv.get('completed', 0)}/{sv.get('admitted', 0)} "
            f"completed, {sv.get('rejected', 0)} rejected, "
            f"{sv.get('duplicates_discarded', 0)} duplicate(s) "
            "discarded")
        lines.append(
            f"  events: {sv.get('promotions', 0)} promotion(s), "
            f"{sv.get('evictions', 0)} eviction(s), "
            f"{sv.get('drains', 0)} drain(s); exactly-once: "
            f"{'PASS' if sv.get('exactly_once') else 'FAIL'}")
        if sv.get("p99") is not None:
            lines.append(
                f"  latency: p50 {sv.get('p50', 0) * 1e3:.1f} ms  "
                f"p95 {sv.get('p95', 0) * 1e3:.1f} ms  "
                f"p99 {sv['p99'] * 1e3:.1f} ms")
    for rank_s, rec in sorted(sv_replicas.items(),
                              key=lambda kv: int(kv[0])):
        state = ("draining" if rec.get("drain")
                 else rec.get("role", "?"))
        w = rec.get("weights") or {}
        wtxt = f", weights v{w.get('version', 0)}"
        if w.get("pending") is not None:
            wtxt += f" (staging v{w['pending']})"
        lines.append(f"  replica {rank_s}: {state}, epoch "
                     f"{rec.get('epoch', 0)}, "
                     f"{rec.get('queued', 0)} queued request(s)"
                     f"{wtxt}")
    for e in sv_hist:
        kind = e.get("kind")
        if kind == "serve_promote":
            lines.append(f"  promote: spare {e.get('rank')} -> live "
                         f"replica (serving epoch {e.get('epoch')})")
        elif kind == "serve_evict":
            lines.append(f"  evict: replica {e.get('rank')} — "
                         f"{e.get('why', '?')} "
                         f"({e.get('requeued', 0)} request(s) "
                         "re-dispatched)")
        elif kind == "serve_drain":
            lines.append(f"  drain: replica {e.get('rank')} stopped "
                         "admitting, finishing in-flight")
        elif kind == "weight_swap":
            lines.append(f"  swap: replica {e.get('rank')} -> "
                         f"v{e.get('version', '?')} "
                         f"(step {e.get('step', '?')}, "
                         f"{e.get('why', '?')})")
        elif kind == "deploy_canary":
            lines.append(f"  canary: v{e.get('version', '?')} on "
                         f"replica(s) {e.get('ranks', '?')}, every "
                         f"{e.get('every_n', '?')}th dispatch")
        elif kind == "deploy_promote":
            lines.append(f"  promote deploy: v{e.get('version', '?')} "
                         "fleet-wide (clean canary window)")
        elif kind == "deploy_rollback":
            lines.append(f"  rollback: v{e.get('version', '?')} -> "
                         f"v{e.get('to_version', '?')} — "
                         f"{e.get('reason', '?')}")
        elif kind == "deploy_verify_failed":
            lines.append(f"  deploy blocked: step {e.get('step', '?')} "
                         "failed verification before any bytes moved")
        else:  # serve_demote
            lines.append(f"  demote: replica {e.get('rank')} -> spare "
                         f"({e.get('why', '?')})")

    rollup = status.get("rollup")
    if rollup:
        lines.append("== Cross-rank rollup ==")
        skew = rollup["skew"]
        lines.append(f"  step-time skew (slowest/median): p95 "
                     f"{skew['p95']:.2f}x  max {skew['max']:.2f}x over "
                     f"{len(rollup['steps'])} step(s)")
        for rank_s, pr in sorted(rollup["per_rank"].items(),
                                 key=lambda kv: int(kv[0])):
            eps = (f"{pr['examples_per_s_mean']:.1f} ex/s"
                   if pr["examples_per_s_mean"] is not None else "-")
            lines.append(f"  rank {rank_s}: {pr['rows']} step row(s), "
                         f"mean {pr['iter_s_mean']:.4f}s, {eps}, "
                         f"attempt(s) "
                         f"{','.join(map(str, pr['attempts']))}, last "
                         f"step {pr['last_step']}")
        for v in rollup["stragglers"]:
            lines.append(f"  straggler (offline): rank {v['rank']} at "
                         f"step {v['step']} ({v['ratio']:.1f}x median)")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("gang_dir", help="the gang coordination dir "
                                         "(--gang-dir of the run)")
    parser.add_argument("--telemetry", default=None,
                        help="gang telemetry plane (default: "
                             "<gang-dir>/telemetry)")
    parser.add_argument("--watch", type=float, default=None,
                        help="re-render every N seconds (live mode)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable dump instead of the table")
    args = parser.parse_args(argv)
    if not os.path.isdir(args.gang_dir):
        print(f"not a directory: {args.gang_dir}", file=sys.stderr)
        return 2
    telemetry_dir = args.telemetry or os.path.join(args.gang_dir,
                                                   "telemetry")
    try:
        while True:
            status = collect(args.gang_dir, telemetry_dir)
            if args.json:
                print(json.dumps(status, indent=1))
            else:
                print(render(status))
            if args.watch is None:
                return 0
            time.sleep(args.watch)
            print()
    except KeyboardInterrupt:
        return 0
    except BrokenPipeError:  # `| head` closed the pipe — not an error
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0


if __name__ == "__main__":
    sys.exit(main())
