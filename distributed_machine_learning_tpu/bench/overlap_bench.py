"""Overlap-aware sharded weight update bench: sync vs overlapped tails.

Measures the ISSUE-9 tentpole (``parallel/overlap.py`` two-phase
update; arxiv 2004.13336) the way the train loop experiences it: the
per-iteration wall clock brackets ``block_until_ready(loss)`` (the
measurement protocol), and a configurable host-side data wait (a
``time.sleep`` standing in for the input pipeline) separates steps.

- **sync build**: the parameter gather is inside the step program and
  feeds ROOT, so the loss block waits it out — the gather is ON the
  measured critical path and the data wait hides nothing.
- **overlap build**: the loss block returns at the end of the update
  program; the separately-dispatched bucketed-ring gather executes
  during the data wait (the sleep releases the GIL, so even this
  one-core CI host genuinely runs the gather under it — on a pod the
  DMAs ride ICI while the host feeds data).  ``param_gather_s`` (the
  span from gather dispatch to observed readiness) is reported
  alongside, showing where the gather went.

Schemes: zero1 and fsdp (CNN steps, fixed-seed synthetic batches,
loss parity asserted bit-identical), the GPipe pipeline with the
pipe-sharded boundary update, and — on jax versions with
partial-manual shard_map — zero1×3-D (annotated-dependency grad
constraint vs its compile only; this host's jax lacks manual_axes, in
which case the row records the skip reason instead of numbers).

Run:  python -m distributed_machine_learning_tpu.bench.overlap_bench \
          [--iters 24] [--data-wait-ms 10] [--json out.json]
"""

from __future__ import annotations

import argparse
import json
import time


def _row(name, build, iters, gathers, loss, extra=None):
    from distributed_machine_learning_tpu.utils.timing import (
        percentile_stats,
    )

    stats = percentile_stats(iters)
    row = {
        "scheme": name,
        "build": build,
        "iters_timed": len(iters),
        "iter_p50_s": stats["p50"],
        "iter_p95_s": stats["p95"],
        "final_loss": loss,
    }
    if gathers:
        g = percentile_stats(gathers)
        row["param_gather_p50_s"] = g["p50"]
        row["param_gather_p95_s"] = g["p95"]
    if extra:
        row.update(extra)
    return row


def _gather_spans(make_step, shard, model, batches, data_wait_s):
    """Short telemetry-on pass: collect the param_gather span durations
    (dispatch → observed ready) the main timed pass cannot see."""
    import tempfile

    from distributed_machine_learning_tpu.cli.common import (
        init_model_and_state,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh
    from distributed_machine_learning_tpu.telemetry import (
        Telemetry,
        set_telemetry,
    )

    mesh = make_mesh(8)
    state, unravel, n_elems = shard(init_model_and_state(model), mesh)
    step = make_step(state, unravel, n_elems)
    gathers = []
    with tempfile.TemporaryDirectory() as td:
        tel = Telemetry(td, flush_every=10**6)
        prev = set_telemetry(tel)
        try:
            for i, (x, y) in enumerate(batches):
                if data_wait_s:
                    time.sleep(data_wait_s)
                state, loss = step(state, x, y)
                g = step.pop_gather_seconds()
                if g is not None and i > 1:
                    gathers.append(g)
        finally:
            set_telemetry(prev)
            tel.close()
    return gathers


def bench_overlap(iters: int = 24, data_wait_ms: float = 10.0,
                  per_device_batch: int = 16) -> list[dict]:
    import jax
    import numpy as np

    from distributed_machine_learning_tpu.cli.common import (
        init_model_and_state,
    )
    from distributed_machine_learning_tpu.models.vgg import VGGTest
    from distributed_machine_learning_tpu.parallel.fsdp import (
        make_fsdp_train_step,
        shard_fsdp_state,
    )
    from distributed_machine_learning_tpu.parallel.zero1 import (
        make_zero1_train_step,
        shard_zero1_state,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh
    from distributed_machine_learning_tpu.train.step import shard_batch

    data_wait_s = data_wait_ms / 1e3
    mesh = make_mesh(8)
    n = 8
    model = VGGTest(use_bn=False)
    rng = np.random.default_rng(20250804)
    global_batch = per_device_batch * n
    host_batches = [
        (rng.integers(0, 256, (global_batch, 32, 32, 3), dtype=np.uint8),
         rng.integers(0, 10, global_batch).astype(np.int32))
        for _ in range(iters)
    ]
    batches = [shard_batch(mesh, x, y) for x, y in host_batches]

    rows = []

    for scheme, make in (
        ("zero1", lambda ov: (
            lambda s, u, ne: make_zero1_train_step(
                model, mesh, u, ne, augment=False, overlap=ov),
            shard_zero1_state,
        )),
        ("fsdp", lambda ov: (
            lambda s, u, ne: make_fsdp_train_step(
                model, mesh, u, ne, augment=False, overlap=ov),
            shard_fsdp_state,
        )),
    ):
        # A/B INTERLEAVED: both builds advance through the same batch
        # stream alternately, one iteration apiece, so slow host drift
        # (the dominant noise on a 1-core box whose conv timings wander
        # by several percent) hits both series equally instead of
        # whichever build ran second.
        runs = {}
        for build, overlap in (("sync", False), ("overlap", True)):
            make_step, shard = make(overlap)
            state, unravel, n_elems = shard(
                init_model_and_state(model), mesh)
            runs[build] = {
                "step": make_step(state, unravel, n_elems),
                "state": state, "iters": [], "loss": None,
            }
        for i, b in enumerate(batches):
            for build in ("sync", "overlap"):
                r = runs[build]
                if data_wait_s:
                    time.sleep(data_wait_s)
                t0 = time.perf_counter()
                r["state"], loss = r["step"](r["state"], b[0], b[1])
                r["loss"] = float(jax.block_until_ready(loss))
                if i > 0:
                    r["iters"].append(time.perf_counter() - t0)
        make_step, shard = make(True)
        gathers = _gather_spans(make_step, shard, model, batches[:8],
                                data_wait_s)
        for build in ("sync", "overlap"):
            r = runs[build]
            rows.append(_row(scheme, build, r["iters"],
                             gathers if build == "overlap" else [],
                             r["loss"]))
        assert runs["sync"]["loss"] == runs["overlap"]["loss"], (
            f"{scheme}: overlapped final loss != sync "
            "(the builds must be bit-identical)")

    rows += _bench_fsdp_lm(iters, data_wait_s)
    rows += _bench_pipeline(iters, data_wait_s)
    rows += _bench_3d_zero1(iters, data_wait_s)
    return rows


def _bench_fsdp_lm(iters: int, data_wait_s: float) -> list[dict]:
    """The params-heavy configuration (embedding+head dominate): the
    sync build's up-front all-gather is a real ~10% prelude on this
    host, so taking it off the critical path shows up directly in the
    loss-ready p50 — the one scheme whose gather latency the CPU host
    can render (the CNN rows' gathers are sub-noise memcpys).
    Interleaved A/B like the CNN rows."""
    import jax
    import numpy as np

    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )
    from distributed_machine_learning_tpu.parallel.fsdp import (
        make_fsdp_lm_train_step,
        shard_fsdp_state,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh
    from distributed_machine_learning_tpu.train.adamw import AdamWConfig
    from distributed_machine_learning_tpu.train.lm_step import init_lm_state
    from distributed_machine_learning_tpu.train.step import shard_batch

    model = TransformerLM(vocab_size=1024, d_model=128, n_layers=2,
                          n_heads=4, attn_impl="dense")
    mesh = make_mesh(8)
    rng = np.random.default_rng(3)
    toks = rng.integers(0, 1024, (16, 33))
    mx, my = shard_batch(mesh, toks[:, :-1].astype(np.int32),
                         toks[:, 1:].astype(np.int32))

    runs = {}
    for build, ov in (("sync", False), ("overlap", True)):
        st, unravel, n = shard_fsdp_state(
            init_lm_state(model, seed=0, config=AdamWConfig()), mesh)
        runs[build] = {
            "step": make_fsdp_lm_train_step(model, mesh, unravel, n,
                                            overlap=ov),
            "state": st, "iters": [], "loss": None,
        }
    for i in range(iters):
        for build in ("sync", "overlap"):
            r = runs[build]
            if data_wait_s:
                time.sleep(data_wait_s)
            t0 = time.perf_counter()
            r["state"], loss = r["step"](r["state"], mx, my)
            r["loss"] = float(jax.block_until_ready(loss))
            if i > 1:
                r["iters"].append(time.perf_counter() - t0)
    assert runs["sync"]["loss"] == runs["overlap"]["loss"]
    return [
        _row("fsdp_lm", build, runs[build]["iters"], [],
             runs[build]["loss"])
        for build in ("sync", "overlap")
    ]


def _bench_pipeline(iters: int, data_wait_s: float) -> list[dict]:
    import numpy as np

    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )
    from distributed_machine_learning_tpu.parallel.pipeline import (
        init_pipeline_state,
        make_pp_lm_train_step,
        microbatch,
        shard_pp_state,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh
    from distributed_machine_learning_tpu.train.adamw import AdamWConfig

    model = TransformerLM(vocab_size=256, d_model=64, n_layers=4,
                          n_heads=4)
    mesh = make_mesh(4, axis_names=("pipe",))
    rng = np.random.default_rng(7)
    toks = [rng.integers(0, 256, (8, 65)) for _ in range(iters)]
    batches = [
        microbatch(t[:, :-1].astype(np.int32), t[:, 1:].astype(np.int32),
                   2)
        for t in toks
    ]
    import jax

    # Interleaved A/B like the CNN rows: both builds alternate through
    # the same batch stream so host drift cancels.
    runs = {}
    for build, overlap in (("sync", False), ("overlap", True)):
        runs[build] = {
            "step": make_pp_lm_train_step(model, mesh, 2,
                                          overlap_update=overlap),
            "state": shard_pp_state(
                init_pipeline_state(model, config=AdamWConfig()), mesh),
            "iters": [], "loss": None,
        }
    for i, (x, y) in enumerate(batches):
        for build in ("sync", "overlap"):
            r = runs[build]
            if data_wait_s:
                time.sleep(data_wait_s)
            t0 = time.perf_counter()
            r["state"], loss = r["step"](r["state"], x, y)
            r["loss"] = float(jax.block_until_ready(loss))
            if i > 0:
                r["iters"].append(time.perf_counter() - t0)
    return [
        _row("pp_gpipe", build, runs[build]["iters"], [],
             runs[build]["loss"])
        for build in ("sync", "overlap")
    ]


def _bench_3d_zero1(iters: int, data_wait_s: float) -> list[dict]:
    """zero1×3-D with the annotated-dependency grad constraint —
    requires partial-manual shard_map; records the skip reason on jax
    versions without it (this CI host)."""
    import numpy as np

    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )
    from distributed_machine_learning_tpu.train.adamw import AdamWConfig

    try:
        import jax

        from distributed_machine_learning_tpu.parallel.parallel3d import (
            init_pipeline_state,
            make_3d_lm_train_step,
            make_3d_mesh,
            microbatch,
            shard_3d_batch,
            shard_3d_state,
        )

        model = TransformerLM(vocab_size=64, d_model=32, n_layers=4,
                              n_heads=4)
        mesh = make_3d_mesh(2, 2, 2)
        rng = np.random.default_rng(7)
        rows = []
        for build, z1 in (("plain", False), ("zero1_dp", True)):
            state = shard_3d_state(
                init_pipeline_state(model, config=AdamWConfig()), mesh,
                zero1_dp=z1)
            step = make_3d_lm_train_step(model, mesh, 2, zero1_dp=z1)
            it = []
            loss = None
            for i in range(iters):
                t = rng.integers(0, 64, (8, 17))
                mx, my = shard_3d_batch(
                    mesh, *microbatch(t[:, :-1].astype(np.int32),
                                      t[:, 1:].astype(np.int32), 2))
                if data_wait_s:
                    time.sleep(data_wait_s)
                t0 = time.perf_counter()
                state, loss = step(state, mx, my)
                loss = jax.block_until_ready(loss)
                it.append(time.perf_counter() - t0)
            rows.append(_row("3d_zero1", build, it[1:], [], float(loss)))
        return rows
    except RuntimeError as e:
        if "manual_axes" not in str(e) and "check_rep" not in str(e):
            raise
        return [{
            "scheme": "3d_zero1", "build": "skipped",
            "reason": (
                "partial-manual shard_map unavailable on this jax "
                f"({e}); the annotated-dependency constraint is "
                "compile-covered by tests/test_parallel3d.py on capable "
                "versions"
            ),
        }]


def main(argv=None) -> None:
    from distributed_machine_learning_tpu.runtime.mesh import (
        ensure_host_devices,
    )

    ensure_host_devices(8)  # before the CPU client spins up
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--iters", default=24, type=int)
    parser.add_argument("--data-wait-ms", dest="data_wait_ms",
                        default=10.0, type=float)
    parser.add_argument("--per-device-batch", dest="per_device_batch",
                        default=16, type=int)
    parser.add_argument("--json", default=None,
                        help="write the rows to this path")
    args = parser.parse_args(argv)
    rows = bench_overlap(args.iters, args.data_wait_ms,
                         args.per_device_batch)
    out = {
        "metric": "overlap_weight_update",
        "iters": args.iters,
        "data_wait_ms": args.data_wait_ms,
        "rows": rows,
    }
    print(json.dumps(out, indent=2))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=2)


if __name__ == "__main__":
    main()
