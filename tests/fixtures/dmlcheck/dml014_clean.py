# dmlcheck-virtual-path: distributed_machine_learning_tpu/runtime/transport.py
"""DML014 clean case: the sanctioned reservation idiom — membership
check and insert in ONE critical section, so a duplicate either sees
the reservation or loses the race to make it; reads with no mutation
anywhere in the function are also fine."""
import threading


class TcpGangServer:
    def __init__(self):
        self._seen = {}
        self._seen_lock = threading.Lock()

    def dispatch(self, op_id, result):
        with self._seen_lock:
            if op_id in self._seen:
                return self._seen[op_id]
            self._seen[op_id] = result
        return result

    def peek(self, op_id):
        with self._seen_lock:
            return self._seen.get(op_id)
