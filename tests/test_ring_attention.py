"""Ring attention correctness: the sequence-sharded ppermute ring must
reproduce single-device dense causal attention exactly (up to fp32
reduction-order tolerance) — the same property-test discipline as the
gradient ring (tests/test_ring.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
try:  # jax>=0.6 exposes shard_map at top level
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from distributed_machine_learning_tpu.ops.ring_attention import (
    dense_self_attention,
    ring_self_attention,
)
from distributed_machine_learning_tpu.runtime.mesh import make_mesh

B, L, H, D = 2, 32, 4, 8


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(69143)
    shape = (B, L, H, D)
    return tuple(
        jnp.asarray(rng.standard_normal(shape, dtype=np.float32)) for _ in range(3)
    )


def _naive_causal(q, k, v):
    """O(L²) reference computed with plain softmax per query row."""
    out = np.zeros_like(np.asarray(q))
    qn, kn, vn = (np.asarray(a) for a in (q, k, v))
    scale = 1.0 / np.sqrt(D)
    for b in range(B):
        for h in range(H):
            s = qn[b, :, h] @ kn[b, :, h].T * scale  # [L, L]
            for i in range(L):
                w = np.exp(s[i, : i + 1] - s[i, : i + 1].max())
                w = w / w.sum()
                out[b, i, h] = w @ vn[b, : i + 1, h]
    return out


def test_dense_matches_naive(qkv):
    q, k, v = qkv
    np.testing.assert_allclose(
        np.asarray(dense_self_attention(q, k, v)),
        _naive_causal(q, k, v),
        rtol=1e-5,
        atol=1e-6,
    )


@pytest.mark.parametrize(
    "n_shards",
    [2,
     pytest.param(4, marks=pytest.mark.slow),
     pytest.param(8, marks=pytest.mark.slow)],
)
def test_ring_matches_dense(qkv, n_shards):
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh

    q, k, v = qkv
    mesh = make_mesh(n_shards, axis_names=("seq",))
    ring = shard_map(
        lambda a, b, c: ring_self_attention(a, b, c, "seq", n_shards),
        mesh=mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
    )
    out = jax.jit(ring)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(dense_self_attention(q, k, v)),
        rtol=1e-5,
        atol=1e-6,
    )


def test_ring_bf16_stays_finite(qkv):
    """bf16 QKV with fp32 accumulators: no inf/nan from the NEG_INF mask."""
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh

    q, k, v = (a.astype(jnp.bfloat16) for a in qkv)
    mesh = make_mesh(4, axis_names=("seq",))
    ring = shard_map(
        lambda a, b, c: ring_self_attention(a, b, c, "seq", 4),
        mesh=mesh,
        in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq"),
    )
    out = np.asarray(jax.jit(ring)(q, k, v), dtype=np.float32)
    assert np.isfinite(out).all()
    assert out.dtype == np.float32 and np.abs(out).max() < 10.0


def test_ring_gqa_narrow_rotation_matches_dense():
    """GQA through the einsum ring: narrow K/V chunks rotate (widened
    only at the local block math) and the result equals unsharded dense
    attention with widened heads."""
    rng = np.random.default_rng(21)
    B, L, H, Hkv, D, n = 2, 32, 8, 2, 8, 4
    rep = H // Hkv
    q = jnp.asarray(rng.standard_normal((B, L, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, L, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, L, Hkv, D)), jnp.float32)
    ref = dense_self_attention(
        q, jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2)
    )
    mesh = make_mesh(n, axis_names=("seq",))
    fn = shard_map(
        lambda q, k, v: ring_self_attention(q, k, v, "seq", n),
        mesh=mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
    )
    out = fn(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )
    with pytest.raises(ValueError, match="multiple of K/V"):
        ring_self_attention(q, k[:, :, :1].repeat(3, axis=2)[:, :, :3], v,
                            "seq", 1)
