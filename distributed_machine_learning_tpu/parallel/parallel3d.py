"""Composed 3-D parallelism: data × pipeline × tensor on one mesh.

The reference composes nothing — its one strategy axis is data-parallel
gradient sync (SURVEY.md §2.3).  This module runs all three major
parallelism dimensions simultaneously over a ``("batch", "pipe",
"model")`` mesh, the way a real TPU pod is carved up:

- **pipe** (pipeline): *manual* — the GPipe-style ppermute tick loop of
  ``parallel/pipeline.py``, reused verbatim: transformer blocks stacked
  on a leading layer axis and sharded over the pipe axis, activations
  rotating one hop per tick.
- **model** (tensor): *automatic* — block params additionally carry the
  Megatron column/row splits of ``parallel/tensor_parallel.py`` on their
  trailing dims; XLA's SPMD partitioner derives every activation sharding
  and inserts the per-block all-reduces.
- **batch** (data): *automatic* — each microbatch's batch dim is sharded
  over the data axis; the partitioner emits the gradient all-reduce.

The composition mechanism is partial-manual ``shard_map`` (jax's
``axis_names``): only ``pipe`` is manual inside the body — giving us
``lax.axis_index``/``ppermute`` for the schedule — while ``batch`` and
``model`` stay under GSPMD propagation, seeded by the state's
``NamedSharding``s at the jit boundary.  One compiled program carries the
pipeline collectives, the Megatron all-reduces, and the data-parallel
gradient reduction, and XLA is free to overlap all three.
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_machine_learning_tpu.models.transformer import TransformerLM
from distributed_machine_learning_tpu.parallel.pipeline import (
    _pp_step_impl,
    _state_specs,
    init_pipeline_state,
    microbatch,
)
from distributed_machine_learning_tpu.parallel.tensor_parallel import tp_spec_for
from distributed_machine_learning_tpu.runtime.mesh import (
    make_mesh,
    shard_map_no_check as _shard_map,
)
from distributed_machine_learning_tpu.train.state import TrainState

DATA_AXIS = "batch"
PIPE_AXIS = "pipe"
MODEL_AXIS = "model"
MESH_AXES = (DATA_AXIS, PIPE_AXIS, MODEL_AXIS)

__all__ = [
    "MESH_AXES",
    "make_3d_mesh",
    "p3_param_spec",
    "p3_zero1_moment_spec",
    "p3_zero1_grad_spec",
    "shard_3d_state",
    "make_3d_lm_train_step",
    "shard_3d_batch",
    "init_pipeline_state",
    "microbatch",
]


def make_3d_mesh(dp: int, pp: int, tp: int, devices=None) -> Mesh:
    """(dp, pp, tp)-shaped mesh over dp·pp·tp devices.

    Axis order puts ``model`` innermost (fastest-varying chips): on real
    hardware the Megatron all-reduces are the latency-critical
    collectives, so they get the shortest ICI hops; the per-tick pipe
    hop is next; the once-per-step data-parallel reduce rides whatever
    is left (DCN across hosts).
    """
    return make_mesh(
        dp * pp * tp, axis_names=MESH_AXES, axis_shape=(dp, pp, tp),
        devices=devices,
    )


def p3_param_spec(
    path: tuple[str, ...],
    ndim: int,
    pipe_axis: str = PIPE_AXIS,
    model_axis: str = MODEL_AXIS,
) -> P:
    """PartitionSpec for one pipeline-layout parameter under 3-D layout.

    ``blocks/...`` leaves have a leading stacked-layer dim sharded over
    the pipe axis and Megatron splits (``tp_spec_for``) on the rest;
    stage-boundary params (embed / ln_f / lm_head) replicate over pipe
    and keep their plain TP spec — except the embedding table, which
    replicates over model too: partitioning the token-gather's operand
    dim trips an XLA SPMD-partitioner CHECK under partial-manual
    shard_map (observed in XLA's PartitionGatherTrivialSlicedOperand-
    Dimensions), and an O(V·E) table is small next to the block stack.
    """
    if path and path[0] == "blocks":
        inner = tuple(tp_spec_for(path[1:], ndim - 1, model_axis))
        return P(pipe_axis, *inner)
    if path and path[0] == "embed":
        return P(*(None,) * ndim)
    return tp_spec_for(path, ndim, model_axis)


def _path_keys(path) -> tuple:
    """KeyPath → plain string keys — the ONE normalization the param,
    moment, and grad spec builders all share."""
    return tuple(k.key if hasattr(k, "key") else str(k) for k in path)


def p3_zero1_moment_spec(
    path: tuple[str, ...],
    shape: tuple[int, ...],
    dp: int,
    data_axis: str = DATA_AXIS,
) -> P:
    """Optimizer-moment PartitionSpec under ZeRO-1 × 3-D: the param's
    3-D spec (``p3_param_spec``) PLUS the data axis on the largest
    dp-divisible still-unsharded dim — the moments are the state the dp
    axis otherwise replicates dp-fold for nothing (a real pod LM run
    wants ZeRO-1 on its data axis; VERDICT r4 item 8).  Leaves with no
    divisible free dim replicate over dp, the O(d) minority (same
    degrade rule as ``fsdp_perlayer.fsdp_pl_spec_for``).  Params are
    NOT touched: every dp rank needs them whole each forward, and the
    update's shard→replicated transition is exactly the all-gather
    GSPMD inserts."""
    if path and path[0] == "embed":
        # Same exclusion (and reason) as p3_param_spec's embed rule: a
        # dp-sharded embedding MOMENT forces the partitioner to push the
        # vocab sharding up through the scatter-add gradient into the
        # token gather, tripping the same SPMD-partitioner CHECK under
        # partial-manual shard_map (observed from the cli.lm 3d
        # --zero1-dp program).  O(V·E) — noise next to the block stack.
        return P(*(None,) * len(shape))
    base = tuple(p3_param_spec(path, len(shape)))
    axes = list(base) + [None] * (len(shape) - len(base))
    best = None
    for i, d in enumerate(shape):
        if axes[i] is None and d % dp == 0 and d >= dp and (
            best is None or d > shape[best]
        ):
            best = i
    if best is not None:
        axes[best] = data_axis
    return P(*axes)


def p3_zero1_grad_spec(
    path: tuple[str, ...],
    shape: tuple[int, ...],
    dp: int,
    data_axis: str = DATA_AXIS,
    pipe_axis: str = PIPE_AXIS,
) -> P:
    """Gradient PartitionSpec at the zero1_dp backward→update boundary:
    the MOMENT's dp-sharded layout (``p3_zero1_moment_spec``) with the
    pipe axis dropped (pipe is manual inside the step's shard_map region
    — stacked-layer grads are already per-stage slices).  This is the
    annotation that lets GSPMD propagate the dp-sharded update end to
    end: the grads arrive at the update already in their consumer's
    layout, one planned reshard per leaf, instead of the old PARAM-spec
    barrier's dp-replicated pin (which forced a full-grad
    materialization and left the dp transition implicit)."""
    full = tuple(p3_zero1_moment_spec(path, shape, dp, data_axis))
    axes = [None if a == pipe_axis else a for a in full]
    return P(*(axes + [None] * (len(shape) - len(axes))))


def _state_shardings_3d(
    state: TrainState, mesh: Mesh, zero1_dp: bool = False
) -> TrainState:
    """NamedSharding pytree: params per ``p3_param_spec``; momentum the
    same, or additionally dp-sharded (``p3_zero1_moment_spec``) when
    ``zero1_dp``; scalar fields replicated."""

    def spec(path, leaf):
        return NamedSharding(mesh, p3_param_spec(_path_keys(path), leaf.ndim))

    def z1_spec(path, leaf):
        keys = _path_keys(path)
        return NamedSharding(
            mesh,
            p3_zero1_moment_spec(keys, leaf.shape, mesh.shape[DATA_AXIS]),
        )

    from distributed_machine_learning_tpu.train.optimizers import (
        moment_layout as _moment_layout,
    )

    param_shardings = jax.tree_util.tree_map_with_path(spec, state.params)
    moment_base = (
        jax.tree_util.tree_map_with_path(z1_spec, state.params)
        if zero1_dp else param_shardings
    )
    replicated = NamedSharding(mesh, P())
    return TrainState(
        params=param_shardings,
        momentum=_moment_layout(moment_base, state.params, state.momentum),
        batch_stats=jax.tree_util.tree_map(lambda _: replicated, state.batch_stats),
        step=replicated,
        rng=replicated,
        config=state.config,
    )


def shard_3d_state(
    state: TrainState, mesh: Mesh, zero1_dp: bool = False
) -> TrainState:
    """Place a pipeline-layout TrainState (``init_pipeline_state``) into
    the 3-D layout.  ``zero1_dp=True`` additionally shards the optimizer
    moments 1/dp over the data axis (pass the same flag to
    ``make_3d_lm_train_step``)."""
    return jax.tree_util.tree_map(
        jax.device_put, state, _state_shardings_3d(state, mesh, zero1_dp)
    )


def shard_3d_batch(mesh: Mesh, tokens_mb, targets_mb):
    """[M, mb, L] microbatch stacks with the batch dim sharded over the
    data axis (microbatch and sequence dims stay whole)."""
    import jax.numpy as jnp

    dp = mesh.shape[DATA_AXIS]
    mb = np.shape(tokens_mb)[1]
    if mb % dp:
        raise ValueError(
            f"microbatch size {mb} must be divisible by the {dp}-device "
            f"data axis (global batch = microbatches × mb; pick a batch "
            "divisible by microbatches × dp)"
        )
    sharding = NamedSharding(mesh, P(None, DATA_AXIS, None))
    return (
        jax.device_put(jnp.asarray(tokens_mb), sharding),
        jax.device_put(jnp.asarray(targets_mb), sharding),
    )


def make_3d_lm_train_step(
    model: TransformerLM, mesh: Mesh, num_microbatches: int,
    zero1_dp: bool = False,
):
    """Build ``step(state, tokens_mb, targets_mb) -> (state, loss)``.

    ``state`` from ``init_pipeline_state`` + ``shard_3d_state``; inputs
    from ``microbatch`` + ``shard_3d_batch``.  Requires ``n_layers``
    divisible by the pipe-axis size and ``n_heads`` by the model-axis
    size.  Reuses the pipeline step implementation unchanged — only the
    shard_map becomes partial-manual and the jit shardings add the
    batch/model dimensions.

    ``zero1_dp=True`` (ZeRO-1 × 3-D, the 4th axis): the optimizer
    moments live dp-sharded (``p3_zero1_moment_spec``; state placed
    with the same flag).  The MANUAL pipe region is untouched — the
    extra sharding enters purely through the jit in/out_shardings, so
    GSPMD partitions the elementwise update to the moment shards and
    inserts the dp all-gather where the updated params go back to
    replicated; the update stays elementwise-exact, so the trajectory
    equals plain 3-D (tested)."""
    if model.attn_impl in ("flash", "auto"):
        if model.flash_mesh is not None:
            raise ValueError(
                "make_3d_lm_train_step configures the model's flash "
                "shard_map wrap itself (it must match this step's mesh "
                "and axes); pass a model with flash_mesh unset"
            )
        # Flash inside the 3-D step: the outer shard_map is manual over
        # PIPE only, so the model's wrap manualizes the REMAINING
        # (batch, model) axes — a nested partial-manual shard_map whose
        # union covers the whole mesh, leaving the Mosaic custom call
        # fully local (batch sharded over dp, heads over tp, and the
        # pipe axis already manual in the enclosing region).
        model = model.clone(
            flash_mesh=mesh,
            flash_batch_axis=DATA_AXIS,
            flash_head_axis=MODEL_AXIS,
            flash_manual_axes=(DATA_AXIS, MODEL_AXIS),
        )
    elif model.attn_impl != "dense":
        raise ValueError(
            "3-D step supports attn_impl dense/flash/auto (sequence-"
            "sharded impls have no axis here)"
        )
    missing = [a for a in MESH_AXES if a not in mesh.axis_names]
    if missing:
        raise ValueError(f"3-D mesh is missing axes {missing}: {mesh.axis_names}")
    pp = mesh.shape[PIPE_AXIS]
    tp = mesh.shape[MODEL_AXIS]
    if model.n_layers % pp:
        raise ValueError(
            f"n_layers={model.n_layers} must divide into {pp} pipeline stages"
        )
    if model.n_heads % tp:
        raise ValueError(
            f"n_heads={model.n_heads} must be divisible by the model-axis "
            f"size {tp}"
        )
    if num_microbatches < 1:
        raise ValueError("num_microbatches must be >= 1")

    grad_constraint = None
    if zero1_dp:
        dp = mesh.shape[DATA_AXIS]

        def grad_constraint(grads):
            # Two sharding-annotated dependencies between backward and
            # update (replacing the old single PARAM-spec barrier whose
            # dp-replicated pin was the END of layout propagation — the
            # update's dp-sharded reshard was left implicit, wherever
            # GSPMD happened to put it):
            #
            # 1. pin the backward's output to the param sharding (pipe
            #    is manual inside the region — dropped from the spec),
            #    so the dp-sharded moment layout cannot walk up into
            #    the stacked-layer backward scatter (the historical XLA
            #    SPMD-partitioner CHECK; regression-covered at the
            #    microbatch-rows > 1 shape);
            # 2. immediately annotate the grads with their MOMENT's
            #    dp-sharded layout (``p3_zero1_moment_spec``), making
            #    the shard transition ONE explicit planned reshard per
            #    leaf through which GSPMD propagates into the update —
            #    the elementwise update then runs on dp shards end to
            #    end and the partitioner inserts the dp all-gather
            #    exactly where updated params return to replicated
            #    (arxiv 2004.13336's shard-the-update placement).
            def param_spec(path, leaf):
                full = tuple(p3_param_spec(_path_keys(path), leaf.ndim))
                axes = [None if a == PIPE_AXIS else a for a in full]
                return P(*(axes + [None] * (leaf.ndim - len(axes))))

            def moment_spec(path, leaf):
                return p3_zero1_grad_spec(
                    _path_keys(path), leaf.shape, dp
                )

            grads = jax.lax.with_sharding_constraint(
                grads, jax.tree_util.tree_map_with_path(param_spec, grads)
            )
            return jax.lax.with_sharding_constraint(
                grads, jax.tree_util.tree_map_with_path(moment_spec, grads)
            )

    impl = partial(_pp_step_impl, model, pipe_axis=PIPE_AXIS, num_stages=pp,
                   grad_constraint=grad_constraint)
    batch_sharding = NamedSharding(mesh, P(None, DATA_AXIS, None))
    jitted: dict = {}

    def step(state: TrainState, tokens_mb, targets_mb):
        if tokens_mb.shape[0] != num_microbatches:
            raise ValueError(
                f"expected {num_microbatches} microbatches, got input shaped "
                f"{tokens_mb.shape}"
            )
        key = jax.tree_util.tree_structure(state)
        fn = jitted.get(key)
        if fn is None:
            # in_specs constrain the MANUAL axis only (blocks stacked dim
            # over pipe — pipeline.py's specs, reused); batch/model
            # shardings enter through in_shardings and propagate via GSPMD.
            pipe_spec = _state_specs(PIPE_AXIS, state.params,
                                     state.momentum)
            pipe_spec = pipe_spec.replace(config=state.config)
            shardings = _state_shardings_3d(state, mesh, zero1_dp)
            fn = jitted[key] = jax.jit(
                _shard_map(
                    impl,
                    mesh=mesh,
                    in_specs=(pipe_spec, P(), P()),
                    out_specs=(pipe_spec, P()),
                    manual_axes={PIPE_AXIS},
                ),
                in_shardings=(shardings, batch_sharding, batch_sharding),
                out_shardings=(shardings, NamedSharding(mesh, P())),
                donate_argnums=(0,),
            )
        return fn(state, tokens_mb, targets_mb)

    return step
