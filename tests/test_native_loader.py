"""C++ native loader vs the pure-Python loaders: identical batch streams,
clean mid-epoch abandonment (the 40-iteration cap), and graceful fallback
reporting."""

import numpy as np
import pytest

from distributed_machine_learning_tpu.data.cifar10 import Dataset
from distributed_machine_learning_tpu.data.distributed_loader import (
    DistributedBatchLoader,
)
from distributed_machine_learning_tpu.data.loader import BatchLoader
from distributed_machine_learning_tpu.data.native_loader import (
    NativeBatchLoader,
    NativeDistributedBatchLoader,
    native_available,
    native_unavailable_reason,
)

pytestmark = pytest.mark.skipif(
    not native_available(),
    reason=f"native loader unavailable: {native_unavailable_reason()}",
)


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(69143)
    images = rng.integers(0, 256, (103, 32, 32, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, 103).astype(np.int64)
    return Dataset(images=images, labels=labels, synthetic=True)


def _streams_equal(a, b):
    a, b = list(a), list(b)
    assert len(a) == len(b)
    for (ia, la), (ib, lb) in zip(a, b):
        np.testing.assert_array_equal(ia, ib)
        np.testing.assert_array_equal(la, lb)


def test_native_matches_python_loader(dataset):
    _streams_equal(
        NativeBatchLoader(dataset, 16), BatchLoader(dataset, 16, prefetch=0)
    )


def test_native_matches_python_loader_custom_indices(dataset):
    idx = np.arange(len(dataset))[::-1].copy()
    _streams_equal(
        NativeBatchLoader(dataset, 10, indices=idx),
        BatchLoader(dataset, 10, indices=idx, prefetch=0),
    )


def test_native_distributed_matches_python(dataset):
    _streams_equal(
        NativeDistributedBatchLoader(dataset, 8, 4),
        DistributedBatchLoader(dataset, 8, 4),
    )


def test_native_loader_early_abandon(dataset):
    """Breaking mid-epoch (reference's 40-iter cap) must not hang or leak."""
    loader = NativeBatchLoader(dataset, 4, prefetch=2)
    for _ in range(3):
        it = iter(loader)
        next(it)
        next(it)
        it.close()  # generator close → dl_destroy while worker mid-queue


def test_native_loader_reiterable(dataset):
    first = [l.copy() for _, l in NativeBatchLoader(dataset, 16)]
    second = [l.copy() for _, l in NativeBatchLoader(dataset, 16)]
    for a, b in zip(first, second):
        np.testing.assert_array_equal(a, b)


def test_native_rejects_bad_batch(dataset):
    with pytest.raises(ValueError):
        NativeBatchLoader(dataset, 0)
    with pytest.raises(ValueError):
        NativeDistributedBatchLoader(dataset, -1, 4)
