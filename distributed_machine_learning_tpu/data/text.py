"""Byte-level text dataset for LM training — real data, zero deps.

The CNN side has a real dataset pipeline (``data/cifar10.py`` replacing
torchvision — SURVEY.md §1 "data pipeline"); the LM side until now
trained on synthetic random tokens.  This module gives it real text with
the same design rules as the CIFAR pipeline:

- **no external deps**: any directory of text files (code, markdown,
  logs) becomes a corpus; bytes are the tokens (vocab 256 + BOS=256 →
  257), so there is no tokenizer artifact to ship or download;
- **deterministic**: files are read in sorted order, windows are drawn
  by a seeded PRNG — every host computes the identical stream;
- **sharded like DistributedSampler(shuffle=False)**: rank r takes
  windows r, r+R, r+2R… of the global window sequence
  (``part2/2a/main.py:158-159`` semantics, applied to windows).

Batches are ``[B, L+1]`` int32 blocks; ``[:, :-1]`` feeds the model and
``[:, 1:]`` are the shifted targets (the shift happens on the host —
under sequence sharding it must cross chunk boundaries, see
``train/lm_step.py``).
"""

from __future__ import annotations

import os

import numpy as np

BOS = 256
VOCAB_SIZE = 257  # 256 byte values + BOS

_TEXT_EXTS = (".txt", ".md", ".py", ".cc", ".h", ".json", ".rst", ".toml",
              ".yaml", ".yml", ".cfg", ".sh")


def load_corpus(root: str | os.PathLike, max_bytes: int | None = None,
                exts: tuple[str, ...] = _TEXT_EXTS) -> np.ndarray:
    """Concatenate every text file under ``root`` (sorted walk, BOS
    between documents) into one uint16 token array."""
    root = os.fspath(root)
    if os.path.isfile(root):
        paths = [root]
    else:
        paths = sorted(
            os.path.join(dirpath, f)
            for dirpath, _, files in os.walk(root)
            for f in files
            if f.endswith(exts)
        )
    if not paths:
        raise FileNotFoundError(
            f"no text files ({'/'.join(e.lstrip('.') for e in exts)}) "
            f"under {root!r}"
        )
    parts = [np.array([BOS], np.uint16)]
    total = 1
    for p in paths:
        with open(p, "rb") as f:
            raw = f.read()
        parts.append(np.frombuffer(raw, np.uint8).astype(np.uint16))
        parts.append(np.array([BOS], np.uint16))
        total += len(raw) + 1
        if max_bytes is not None and total >= max_bytes:
            break
    corpus = np.concatenate(parts)
    if max_bytes is not None:
        corpus = corpus[:max_bytes]
    return corpus


def split_corpus(
    corpus: np.ndarray, eval_frac: float = 0.1, min_eval_tokens: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    """(train, eval) split: the final ``eval_frac`` of tokens is reserved
    for evaluation, so eval windows are genuinely held out from training
    (the byte-stream analogue of CIFAR's fixed train/test file split).

    ``min_eval_tokens`` (e.g. ``seq_len + 1``) bumps the eval slice up to
    a usable size on tiny corpora; if the corpus cannot sustain both
    slices the split degrades to (everything, everything) rather than
    erroring — matching the loaders' own too-small-corpus behavior.
    """
    if not (0.0 < eval_frac < 1.0):
        raise ValueError(f"eval_frac must be in (0, 1), got {eval_frac}")
    n_eval = max(int(len(corpus) * eval_frac), min_eval_tokens)
    n_train = len(corpus) - n_eval
    # The TRAIN slice must also sustain a window (the loaders require
    # min_eval_tokens = seq_len + 1 tokens) — otherwise enabling eval
    # would make training crash on a corpus that trains fine without it.
    if n_train < max(min_eval_tokens, 1) or n_eval <= 0:
        return corpus, corpus
    return corpus[:n_train], corpus[n_train:]


def _gather_windows(corpus: np.ndarray, starts: np.ndarray,
                    seq_len: int) -> np.ndarray:
    return np.stack(
        [corpus[s : s + seq_len + 1] for s in starts]
    ).astype(np.int32)


def _draw_windows(corpus: np.ndarray, rng: np.random.Generator,
                  batch: int, seq_len: int) -> np.ndarray:
    """[batch, seq_len+1] int32 windows — the single window-drawing
    implementation shared by the training loader and ``eval_windows``."""
    starts = rng.integers(0, len(corpus) - seq_len, batch)
    return _gather_windows(corpus, starts, seq_len)


class TextWindowLoader:
    """Seeded random-window batches over a token array.

    Yields ``[B, seq_len+1]`` int32 blocks forever (the training driver
    owns the iteration cap — ``train/loop.py``).  ``rank``/``world``
    shard the window sequence rank-strided, so the union over ranks is
    the same window stream a single process would draw — the exact
    sharding contract of the CNN's ``DistributedBatchLoader``.
    """

    def __init__(self, corpus: np.ndarray, batch: int, seq_len: int,
                 seed: int = 69143, rank: int = 0, world: int = 1):
        if len(corpus) < seq_len + 1:
            raise ValueError(
                f"corpus has {len(corpus)} tokens, need >= {seq_len + 1}"
            )
        if not (0 <= rank < world):
            raise ValueError(f"rank {rank} outside world {world}")
        if batch < 1 or seq_len < 1:
            raise ValueError(
                f"batch and seq_len must be >= 1, got {batch}, {seq_len}"
            )
        self.corpus = corpus
        self.batch = batch
        self.seq_len = seq_len
        self.rank = rank
        self.world = world
        self._rng = np.random.default_rng(seed)

    def __iter__(self):
        L = self.seq_len
        while True:
            # One global START draw; every rank computes it identically
            # (deterministic cross-host agreement with zero communication
            # — seeds replace gloo's rendezvous) but gathers only its own
            # stride's windows: 1/world of the copy cost.
            starts = self._rng.integers(
                0, len(self.corpus) - L, self.batch * self.world
            )
            block = _gather_windows(
                self.corpus, starts[self.rank :: self.world], L
            )
            yield block[:, :-1], block[:, 1:]


def eval_windows(corpus: np.ndarray, batch: int, seq_len: int,
                 num_batches: int, seed: int = 69143 + 1):
    """A fixed, finite eval set: ``num_batches`` deterministic windows
    drawn from ``corpus``.  For genuinely held-out perplexity, pass the
    eval slice from ``split_corpus`` (the CLI does — ``cli/lm.py``);
    windows drawn from the training slice measure in-distribution
    training-set perplexity."""
    if len(corpus) < seq_len + 1:
        raise ValueError(
            f"corpus has {len(corpus)} tokens, need >= {seq_len + 1}"
        )
    rng = np.random.default_rng(seed)
    for _ in range(num_batches):
        block = _draw_windows(corpus, rng, batch, seq_len)
        yield block[:, :-1], block[:, 1:]
