from distributed_machine_learning_tpu.models.registry import get_model, list_models
from distributed_machine_learning_tpu.models.resnet import (
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
)
from distributed_machine_learning_tpu.models.vgg import VGG, VGG11, VGG13, VGG16, VGG19

__all__ = [
    "VGG", "VGG11", "VGG13", "VGG16", "VGG19",
    "ResNet", "ResNet18", "ResNet34", "ResNet50",
    "get_model", "list_models",
]
