# dmlcheck-virtual-path: tests/test_fixture.py
"""DML006 firing case: unmarked tests spawning a gang (directly and via
a module-level helper) and building an oversized mesh."""
import subprocess
import sys


def _run_gang(root):
    return subprocess.run(
        [sys.executable, "-m", "distributed_machine_learning_tpu.cli.gang",
         "--workers", "4", "--gang-dir", root],
        capture_output=True, timeout=120,
    )


def test_gang_finishes(tmp_path):          # unmarked, spawns via helper
    assert _run_gang(str(tmp_path)).returncode == 0


def test_wide_mesh(make_mesh):             # unmarked, >8 devices
    mesh = make_mesh(16)
    assert mesh is not None
