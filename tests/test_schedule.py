"""LR schedules and gradient transforms (train/schedule.py), standalone
and integrated into the jitted train step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.train.schedule import (
    clip_by_global_norm,
    constant,
    global_norm,
    step_decay,
    warmup_cosine,
)


def test_constant():
    s = constant(0.1)
    assert float(s(0)) == pytest.approx(0.1)
    assert float(s(1000)) == pytest.approx(0.1)


def test_warmup_cosine_shape():
    s = warmup_cosine(peak_lr=1.0, warmup_steps=10, total_steps=110, end_lr=0.1)
    assert float(s(0)) == pytest.approx(0.0)
    assert float(s(5)) == pytest.approx(0.5)
    assert float(s(10)) == pytest.approx(1.0)
    # halfway through decay: mean of peak and end
    assert float(s(60)) == pytest.approx(0.55, abs=1e-6)
    assert float(s(110)) == pytest.approx(0.1, abs=1e-6)
    # past the end: stays at end_lr
    assert float(s(500)) == pytest.approx(0.1, abs=1e-6)


def test_warmup_cosine_validates():
    with pytest.raises(ValueError, match="exceed"):
        warmup_cosine(1.0, warmup_steps=100, total_steps=100)


def test_step_decay():
    s = step_decay(0.1, boundaries=(30, 60), gamma=0.1)
    assert float(s(0)) == pytest.approx(0.1)
    assert float(s(29)) == pytest.approx(0.1)
    assert float(s(30)) == pytest.approx(0.01)
    assert float(s(60)) == pytest.approx(0.001)


def test_schedule_is_jittable():
    s = warmup_cosine(0.1, 5, 50)
    lrs = jax.jit(jax.vmap(s))(jnp.arange(50))
    assert lrs.shape == (50,) and np.isfinite(np.asarray(lrs)).all()


def test_clip_by_global_norm():
    grads = {"a": jnp.full((4,), 3.0), "b": jnp.full((4,), 4.0)}
    # global norm = sqrt(4*9 + 4*16) = 10
    assert float(global_norm(grads)) == pytest.approx(10.0)
    clipped = clip_by_global_norm(grads, 5.0)
    assert float(global_norm(clipped)) == pytest.approx(5.0, rel=1e-6)
    # ratios preserved
    np.testing.assert_allclose(np.asarray(clipped["a"]), 1.5, rtol=1e-6)
    # under the limit: untouched
    same = clip_by_global_norm(grads, 100.0)
    np.testing.assert_allclose(np.asarray(same["b"]), 4.0, rtol=1e-6)


def test_clip_preserves_dtype():
    g = {"w": jnp.ones((8,), jnp.bfloat16) * 100}
    out = clip_by_global_norm(g, 1.0)
    assert out["w"].dtype == jnp.bfloat16


def test_cli_schedule_resume_offset():
    """A resumed run's schedule must cover ITS OWN horizon, not return
    end_lr=0 because the restored step counter is past total_steps."""
    import argparse

    from distributed_machine_learning_tpu.cli.common import make_schedule

    args = argparse.Namespace(
        lr_schedule="cosine", warmup_steps=0, max_iters=40, epochs=1
    )
    fresh = make_schedule(args, 0.1, start_step=0)
    resumed = make_schedule(args, 0.1, start_step=40)
    # Step 40 of the resumed run == step 0 of a fresh run, and is NOT the
    # decayed-to-zero tail.
    assert float(resumed(40)) == pytest.approx(float(fresh(0)))
    assert float(resumed(60)) == pytest.approx(float(fresh(20)))
    assert float(resumed(40)) > 0.09
    # constant stays None (reference parity: no schedule object at all)
    args.lr_schedule = "constant"
    assert make_schedule(args, 0.1, start_step=40) is None


def test_cli_flag_validation():
    """Bad schedule/clip flag values fail at parse time, not mid-run."""
    from distributed_machine_learning_tpu.cli.common import (
        make_flag_parser,
        parse_flags,
    )

    parser = make_flag_parser("t")
    with pytest.raises(SystemExit):
        parse_flags(parser, ["--clip-norm", "0"])
    with pytest.raises(SystemExit):
        parse_flags(parser, ["--clip-norm", "-1"])
    with pytest.raises(SystemExit):
        parse_flags(parser, ["--warmup-steps", "-1"])
    with pytest.raises(SystemExit):
        parse_flags(
            parser, ["--lr-schedule", "cosine", "--warmup-steps", "40"]
        )  # default horizon is 40 steps
    # valid combinations parse
    args = parse_flags(
        parser,
        ["--lr-schedule", "cosine", "--warmup-steps", "5", "--clip-norm", "1.0"],
    )
    assert args.clip_norm == 1.0


def test_train_step_with_schedule_and_clip():
    """Integration: a scheduled step at lr=0 must not move params; clipping
    must bound the first-step update magnitude at clip_norm * lr."""
    from distributed_machine_learning_tpu.cli.common import init_model_and_state
    from distributed_machine_learning_tpu.models.vgg import VGGTest
    from distributed_machine_learning_tpu.train.step import make_train_step

    model = VGGTest()
    rng = np.random.default_rng(0)
    x = rng.integers(0, 256, (4, 32, 32, 3), dtype=np.uint8)
    y = rng.integers(0, 10, 4).astype(np.int32)

    # Schedule that is 0 at step 0: params must be unchanged after step 1
    # (momentum=0 initially, wd scaled by lr=0 too... wd enters the grad,
    # but the param delta is lr * buf = 0).
    state0 = init_model_and_state(model)
    step = make_train_step(model, schedule=constant(0.0), augment=False)
    state1, _ = step(state0, x, y)
    ref = init_model_and_state(model)
    for a, b in zip(
        jax.tree_util.tree_leaves(ref.params),
        jax.tree_util.tree_leaves(state1.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # Clipped step: ||param delta|| <= lr * clip_norm on the first step
    # (buf == clipped grad + wd*param; wd=1e-4 adds a tiny slack).
    state0 = init_model_and_state(model)
    clip = 0.5
    lr = state0.config.learning_rate
    stepc = make_train_step(model, clip_norm=clip, augment=False)
    state2, _ = stepc(state0, x, y)
    delta = jax.tree_util.tree_map(
        lambda a, b: a - b, state2.params, init_model_and_state(model).params
    )
    from distributed_machine_learning_tpu.train.schedule import global_norm as gn

    param_norm = float(gn(init_model_and_state(model).params))
    bound = lr * (clip + 1e-4 * param_norm) * 1.01
    assert float(gn(delta)) <= bound
