"""Pod-scale digital twin: the modeled network under the in-proc gang
(round 20).

PR 12 proved 64–128 thread ranks run in seconds; what kept the in-proc
transport from being a pod simulator was a *network model*.  This
module is that model:

- :class:`VirtualClock` — the twin's ONLY time source.  Campaign time
  is virtual: a 512-rank gang whose modeled steps cost tens of
  milliseconds each runs in wall-clock seconds because nothing here
  ever sleeps or reads a real clock (``dmlcheck`` DML016 makes that a
  static error in this file, not a convention).
- :class:`NetModel` — per-link latency/bandwidth over the topology
  descriptor's axes: ranks are inner-major (node ``o`` owns ranks
  ``[o·inner, (o+1)·inner)``, exactly :class:`ops.topology.Topology`'s
  convention), an intra-node link rides the fast ICI-class parameters
  and an inter-node link the slow DCN-class ones
  (:class:`ops.topology.LinkModel` — the SAME cost model that drives
  ``Topology.select``, so the twin and the selector can never price
  the wire differently).  Gray failures mutate the link table:

  * ``degrade_link(src, dst, k)`` — latency ×k on one directed link;
  * ``flaky_link(src, dst, p)`` — loss probability ``p`` modeled as
    its DETERMINISTIC expected retransmission factor ``1/(1−p)``
    (no RNG: the same campaign seed reproduces the same trajectory
    bit-for-bit, the acceptance criterion);
  * ``bw_collapse(node, k)`` — bandwidth ÷k on every link touching a
    node;
  * ``restore_link(src, dst)`` — clear both gray states on a link.

- :meth:`NetModel.step_time` — the per-rank modeled training-step
  seconds the in-proc worker reports through ``observe_step`` instead
  of its measured CPU time: modeled compute plus this rank's send
  schedule of the flat data-parallel ring — ``2·(world−1)`` chunks of
  ``ceil(step_bytes/world)`` to the right neighbor, the identical
  per-device accounting ``ops.ring.ring_wire_bytes`` pins (and DML103
  asserts against compiled HLO).  A gray-degraded rank's modeled step
  inflates while healthy ranks stay at baseline, which is precisely
  the signature the straggler detector flags — the 512-rank campaigns
  in ``tests/test_pod_twin.py`` close that loop end to end.

The model state lives on the ``InProcHub`` (``hub.netmodel``), NOT on
a transport or an attempt: a supervisor relaunch clears beats and
aborts but a degraded physical link stays degraded — while the fault
LEDGER (replayed by ``FaultInjector.attach_ledger``) guarantees the
*injection* itself never re-fires on the relaunched attempt.
"""

from __future__ import annotations

import threading


class VirtualClock:
    """Monotone virtual seconds — the clock seam of the digital twin.

    Contract: ``now()`` returns accumulated VIRTUAL seconds; the only
    way time passes is an explicit ``advance``/``advance_to`` by the
    simulation's owner (the gang's rank-0 step hook, a DES loop).  No
    method reads a real clock or sleeps; campaigns therefore cost wall
    time proportional to the *work simulated*, never the time modeled.
    Thread-safe: thread ranks observe and advance it concurrently.
    """

    def __init__(self, start: float = 0.0):
        self._now = float(start)
        self._lock = threading.Lock()

    def now(self) -> float:
        with self._lock:
            return self._now

    def advance(self, dt: float) -> float:
        """Move time forward by ``dt >= 0``; returns the new now."""
        if dt < 0:
            raise ValueError(f"virtual time cannot rewind (dt={dt})")
        with self._lock:
            self._now += dt
            return self._now

    def advance_to(self, t: float) -> float:
        """Monotone jump: ``now = max(now, t)``; returns the new now."""
        with self._lock:
            if t > self._now:
                self._now = t
            return self._now


class NetModel:
    """Per-link latency/bandwidth model over an inner×outer rank
    grouping, with mutable gray-failure state.

    ``world`` ranks in nodes of ``inner`` (inner-major).  ``link`` is
    an :class:`ops.topology.LinkModel` (imported lazily so this module
    stays stdlib-cheap for the tools layer); ``compute_s`` is the
    modeled per-step compute, ``step_bytes`` the per-step gradient
    payload of the data-parallel ring.  All mutation and reads are
    lock-protected — thread ranks and the fault injector touch one
    shared instance.
    """

    def __init__(self, world: int, inner: int = 1, *, link=None,
                 compute_s: float = 0.005,
                 step_bytes: int = 4 << 20,
                 clock: VirtualClock | None = None):
        if world < 1 or inner < 1 or world % inner:
            raise ValueError(
                f"world {world} must be a positive multiple of inner "
                f"{inner}")
        if link is None:
            from distributed_machine_learning_tpu.ops.topology import (
                DEFAULT_LINK_MODEL,
            )
            link = DEFAULT_LINK_MODEL
        self.world = world
        self.inner = inner
        self.link = link
        self.compute_s = float(compute_s)
        self.step_bytes = int(step_bytes)
        self.clock = clock if clock is not None else VirtualClock()
        self._lock = threading.Lock()
        self._latency_mult: dict[tuple[int, int], float] = {}
        self._flaky_p: dict[tuple[int, int], float] = {}
        self._bw_div: dict[int, float] = {}

    # -- topology arithmetic -------------------------------------------

    def node_of(self, rank: int) -> int:
        return rank // self.inner

    def link_axis(self, src: int, dst: int) -> str:
        return ("inner" if self.node_of(src) == self.node_of(dst)
                else "outer")

    # -- gray-failure state (the fault kinds' mutation surface) --------

    def degrade_link(self, src: int, dst: int, k: float) -> None:
        if k < 1.0:
            raise ValueError(f"latency multiplier must be >= 1, got {k}")
        with self._lock:
            self._latency_mult[(src, dst)] = float(k)

    def flaky_link(self, src: int, dst: int, p: float) -> None:
        if not 0.0 <= p <= 0.99:
            raise ValueError(f"loss probability must be in [0, 0.99], "
                             f"got {p}")
        with self._lock:
            self._flaky_p[(src, dst)] = float(p)

    def bw_collapse(self, node: int, k: float) -> None:
        if k < 1.0:
            raise ValueError(f"bandwidth divisor must be >= 1, got {k}")
        with self._lock:
            self._bw_div[node] = float(k)

    def restore_link(self, src: int, dst: int) -> None:
        with self._lock:
            self._latency_mult.pop((src, dst), None)
            self._flaky_p.pop((src, dst), None)

    def link_params(self, src: int, dst: int) -> dict:
        """Effective parameters of one directed link — what the
        ``link_degraded`` health event records and
        ``tools/gang_status.py`` renders."""
        axis = self.link_axis(src, dst)
        base_lat = (self.link.inner_overhead_s if axis == "inner"
                    else self.link.outer_overhead_s)
        base_bw = (self.link.inner_bytes_per_s if axis == "inner"
                   else self.link.outer_bytes_per_s)
        with self._lock:
            mult = self._latency_mult.get((src, dst), 1.0)
            p = self._flaky_p.get((src, dst), 0.0)
            div = max(self._bw_div.get(self.node_of(src), 1.0),
                      self._bw_div.get(self.node_of(dst), 1.0))
        return {
            "src": src, "dst": dst, "axis": axis,
            "latency_mult": mult, "flaky_p": p, "bw_div": div,
            "latency_s": base_lat * mult,
            "bytes_per_s": base_bw / div,
        }

    def degraded_links(self) -> list[dict]:
        """Every link/node with non-baseline gray state, as
        ``link_params`` rows (bw-collapsed nodes contribute their
        outgoing ring link as the representative row)."""
        with self._lock:
            keys = set(self._latency_mult) | set(self._flaky_p)
            nodes = list(self._bw_div)
        for node in nodes:
            src = node * self.inner
            keys.add((src, (src + 1) % self.world))
        return [self.link_params(s, d) for s, d in sorted(keys)]

    # -- the cost queries ----------------------------------------------

    def link_time(self, src: int, dst: int, nbytes: int) -> float:
        """Modeled seconds to move ``nbytes`` over one directed link,
        with every gray effect applied: latency ×mult, bandwidth ÷div,
        and the whole transfer ×1/(1−p) expected retransmissions."""
        p = self.link_params(src, dst)
        once = p["latency_s"] + nbytes / p["bytes_per_s"]
        return once / (1.0 - p["flaky_p"])

    def step_time(self, rank: int) -> float:
        """Modeled seconds of one training step as RANK experiences it:
        compute plus the rank's send schedule of the flat data-parallel
        ring — ``2·(world−1)`` hops of ``ceil(step_bytes/world)`` on
        the (rank → rank+1) link.  Per-device accounting, so only the
        ranks incident to a gray link inflate — the straggler
        detector's input signal."""
        if self.world == 1:
            return self.compute_s
        dst = (rank + 1) % self.world
        chunk = -(-self.step_bytes // self.world)
        return (self.compute_s
                + 2 * (self.world - 1) * self.link_time(rank, dst, chunk))
